"""SRL data provider (ref: demo/semantic_role_labeling/dataprovider.py).

Seven aligned integer sequences per sample: word ids, the predicate
broadcast to sentence length, three context-window features, the 0/1
predicate mark, and the target labels.

Real mode: when the config passes src_dict/tgt_dict paths (written by
prepare_data.py), file-list entries are 7-field feature lines
('sentence \t verb \t ctx_n1 \t ctx_0 \t ctx_p1 \t mark \t labels') and
words map through the dicts with <unk>=0 — the reference provider's
contract. Default: deterministic synthetic sentences from common.py.
"""

import os

from paddle.trainer.PyDataProvider2 import *

import common

UNK_IDX = 0


def dict_dims(src_dict="", tgt_dict=""):
    """Layer dims for db_lstm.py: converter dict sizes in real mode, the
    synthetic vocab otherwise — one definition shared with the provider
    hook so config dims can never diverge from the mapping."""
    class _Bag:  # throwaway attribute bag; _load_dicts sets dict attrs
        pass

    return _load_dicts(_Bag(), src_dict, tgt_dict)


def _load_dicts(settings, src_dict, tgt_dict):
    if bool(src_dict) != bool(tgt_dict):
        raise ValueError(
            "real mode needs BOTH src_dict and tgt_dict "
            f"(got src_dict={src_dict!r}, tgt_dict={tgt_dict!r})"
        )
    if src_dict and tgt_dict:
        from paddle_tpu.data import datasets

        settings.word_dict = datasets.load_dict(src_dict)
        settings.label_dict = datasets.load_dict(tgt_dict)
        return len(settings.word_dict), len(settings.label_dict)
    settings.word_dict = settings.label_dict = None
    return len(common.WORDS), len(common.LABELS)


def hook(settings, src_dict=None, tgt_dict=None, **kwargs):
    words, labels = _load_dicts(settings, src_dict, tgt_dict)
    settings.input_types = [
        integer_value_sequence(words),
        integer_value_sequence(words),
        integer_value_sequence(words),
        integer_value_sequence(words),
        integer_value_sequence(words),
        integer_value_sequence(2),
        integer_value_sequence(labels),
    ]


def _real_samples(settings, file_name):
    wd, ld = settings.word_dict, settings.label_dict
    with open(file_name) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) != 7:
                continue
            sentence, verb, ctx_n1, ctx_0, ctx_p1, mark, labels = parts
            words = sentence.split()
            n = len(words)
            try:
                # gold labels must be in-dict — mapping an unseen tag to
                # id 0 would silently score against wrong labels
                label_ids = [ld[l] for l in labels.split()]
            except KeyError as e:
                raise KeyError(
                    f"{file_name}: label {e.args[0]!r} not in tgt.dict — "
                    "regenerate dicts with prepare_data.py over this split"
                ) from None
            yield (
                [wd.get(w, UNK_IDX) for w in words],
                [wd.get(verb, UNK_IDX)] * n,
                [wd.get(ctx_n1, UNK_IDX)] * n,
                [wd.get(ctx_0, UNK_IDX)] * n,
                [wd.get(ctx_p1, UNK_IDX)] * n,
                [int(m) for m in mark.split()],
                label_ids,
            )


@provider(init_hook=hook)
def process(settings, file_name):
    if settings.word_dict is not None:
        if not os.path.exists(file_name):
            # real mode was requested: never fall back to synthetic silently
            raise FileNotFoundError(f"feature file not found: {file_name}")
        yield from _real_samples(settings, file_name)
        return
    for words, verb, labels in common.synth_sentences(file_name):
        n = len(words)
        verb_id = words[verb]
        ctx_n1 = words[verb - 1] if verb > 0 else 0
        ctx_p1 = words[verb + 1] if verb < n - 1 else 0
        yield (
            words,
            [verb_id] * n,
            [ctx_n1] * n,
            [words[verb]] * n,
            [ctx_p1] * n,
            [1 if i == verb else 0 for i in range(n)],
            labels,
        )
