"""Convert raw CoNLL-2005-style SRL data into provider feature lines.

Role analog of the reference's demo/semantic_role_labeling/data pipeline
(get_data.sh fetch + extract_pairs.py + extract_dict_feature.py), minus
the network fetch — point --words / --props at already-downloaded
conll05st-release files:

  words file: one token per line, blank line between sentences;
  props file: per-token rows, column 0 = predicate lemma (or '-'),
              one bracketed-span label column per predicate
              ('(A0*', '*', '*)', '(V*)'), blank line between sentences.

Span columns become B-/I-/O tags (the reference's transform_labels walk),
then each (sentence, predicate) pair becomes one feature line:

  sentence \t verb \t ctx_n1 \t ctx_0 \t ctx_p1 \t mark \t labels

— the exact format demo dataprovider.py reads in real mode. Outputs under
--out (default data/srl-out): train.txt (+ test.txt when --test_words /
--test_props given), src.dict / tgt.dict (word id 0 = <unk>),
train.list / test.list.

Then train with
  --config_args=src_dict=data/srl-out/src.dict,tgt_dict=data/srl-out/tgt.dict
and the file lists pointing at the written lists.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from paddle_tpu.data import datasets


def _read_blocks(path):
    """Yield lists of non-empty lines, split on blank lines."""
    block = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                if block:
                    yield block
                block = []
            else:
                block.append(line)
    if block:
        yield block


def _span_to_tags(col):
    """One predicate's bracketed-span column -> B-/I-/O tag sequence
    (reference transform_labels semantics)."""
    tags, current, inside = [], "O", False
    for ll in col:
        if ll == "*":
            tags.append("I-" + current if inside else "O")
        elif ll == "*)":
            if not inside:
                raise ValueError("span close '*)' with no open span")
            tags.append("I-" + current)
            inside = False
        elif "(" in ll and ")" in ll:
            current = ll[1 : ll.find("*")]
            tags.append("B-" + current)
            inside = False
        elif "(" in ll:
            current = ll[1 : ll.find("*")]
            tags.append("B-" + current)
            inside = True
        else:
            raise ValueError(f"unparseable span token {ll!r}")
    return tags


def _feature_lines(words_path, props_path):
    """Yield the 7-field feature lines for every (sentence, predicate).

    Context/mark semantics mirror the reference's extract_dict_feature.py
    bit-exactly, INCLUDING its boundary quirk: a predicate at the
    second-to-last position gets ctx_p1='eos' and no +1 mark (the
    reference tests `verb_index < len - 2`, not `len - 1`)."""
    import itertools

    sent_no = 0
    for words_block, props_block in itertools.zip_longest(
        _read_blocks(words_path), _read_blocks(props_path)
    ):
        sent_no += 1
        if words_block is None or props_block is None:
            raise ValueError(
                f"words/props sentence counts differ at sentence {sent_no} "
                f"({words_path} vs {props_path})"
            )
        if len(words_block) != len(props_block):
            raise ValueError(
                f"sentence {sent_no}: {len(words_block)} words but "
                f"{len(props_block)} prop rows"
            )
        sentence = [w.lower() for w in words_block]
        rows = [p.split() for p in props_block]
        n_cols = {len(r) for r in rows}
        if len(n_cols) != 1:
            raise ValueError(
                f"sentence {sent_no}: ragged props rows (column counts {sorted(n_cols)})"
            )
        n_preds = len(rows[0]) - 1
        for j in range(n_preds):
            tags = _span_to_tags([r[j + 1] for r in rows])
            if "B-V" not in tags:
                continue
            verb_index = tags.index("B-V")
            verb = sentence[verb_index]
            mark = ["0"] * len(sentence)
            mark[verb_index] = "1"
            if verb_index > 0:
                mark[verb_index - 1] = "1"
                ctx_n1 = sentence[verb_index - 1]
            else:
                ctx_n1 = "bos"
            if verb_index < len(sentence) - 2:
                mark[verb_index + 1] = "1"
                ctx_p1 = sentence[verb_index + 1]
            else:
                ctx_p1 = "eos"
            yield (
                " ".join(sentence), verb, ctx_n1, verb, ctx_p1,
                " ".join(mark), " ".join(tags),
            )


def convert(words_path, props_path, out_dir, test_words=None, test_props=None,
            max_dict: int = 30000):
    """Returns (n_train, n_test, src_dict_size, tgt_dict_size)."""
    os.makedirs(out_dir, exist_ok=True)
    train = list(_feature_lines(words_path, props_path))
    test = list(_feature_lines(test_words, test_props)) if test_words and test_props else []
    if not train:
        raise ValueError(f"no (sentence, predicate) pairs found in {words_path}")

    src_words = datasets.build_dict(
        (line[0].split() + [line[1], line[2], line[4]] for line in train),
        max_size=max_dict, reserved=("<unk>",))
    # label tags are a closed set: build the dict over BOTH splits so a
    # test-only tag can never fall outside it
    tgt_words = datasets.build_dict((line[6].split() for line in train + test))
    datasets.save_dict(src_words, os.path.join(out_dir, "src.dict"))
    datasets.save_dict(tgt_words, os.path.join(out_dir, "tgt.dict"))

    for name, rows in (("train", train), ("test", test)):
        if not rows and name == "test":
            continue
        with open(os.path.join(out_dir, f"{name}.txt"), "w") as f:
            for row in rows:
                f.write("\t".join(row) + "\n")
        with open(os.path.join(out_dir, f"{name}.list"), "w") as f:
            f.write(os.path.abspath(os.path.join(out_dir, f"{name}.txt")) + "\n")
    return len(train), len(test), len(src_words), len(tgt_words)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--words", required=True, help="conll05 words file")
    ap.add_argument("--props", required=True, help="conll05 props file")
    ap.add_argument("--test_words")
    ap.add_argument("--test_props")
    ap.add_argument("--out", default="data/srl-out")
    args = ap.parse_args()
    nt, ns, ds, dt = convert(args.words, args.props, args.out,
                             args.test_words, args.test_props)
    print(f"wrote {nt} train / {ns} test pairs, dicts src={ds} tgt={dt} under {args.out}")


if __name__ == "__main__":
    main()
