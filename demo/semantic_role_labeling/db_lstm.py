#edit-mode: -*- python -*-
"""Semantic role labeling: deep bidirectional LSTM tagger
(ref: demo/semantic_role_labeling/db_lstm.py).

Six parallel id-sequence features (word, predicate, three context windows,
predicate mark) are embedded — the word-family features share one embedding
table — fused by a mixed_layer of full-matrix projections, then run through
a `depth`-deep stack of alternating-direction LSTMs with direct fc edges;
per-token softmax + classification cost over the padded sequence.
"""

from paddle.trainer_config_helpers import *

import common

is_test = get_config_arg("is_test", bool, False)
is_predict = get_config_arg("is_predict", bool, False)
depth = get_config_arg("depth", int, 8)
# per-parameter lr multipliers (tutorial values; raise for small synthetic runs)
lr_mult = get_config_arg("lr_mult", float, 1e-2)
drop_rate = get_config_arg("drop_rate", float, 0.5)
hidden_dim = get_config_arg("hidden_dim", int, 128)

# real-corpus mode (--config_args=src_dict=...,tgt_dict=...): dims come
# from the converter-written dicts (prepare_data.py)
src_dict = get_config_arg("src_dict", str, "")
tgt_dict = get_config_arg("tgt_dict", str, "")
import dataprovider as _dp
word_dict_len, label_dict_len = _dp.dict_dims(src_dict, tgt_dict)
mark_dict_len = 2
word_dim = 32
mark_dim = 5

if not is_predict:
    define_py_data_sources2(
        train_list=None if is_test else "train.list",
        test_list="test.list",
        module="dataprovider",
        obj="process",
        args={"src_dict": src_dict, "tgt_dict": tgt_dict},
    )

settings(
    batch_size=150,
    learning_method=AdamOptimizer(),
    learning_rate=1e-3,
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25,
)

word = data_layer(name="word_data", size=word_dict_len)
predicate = data_layer(name="verb_data", size=word_dict_len)
ctx_n1 = data_layer(name="ctx_n1_data", size=word_dict_len)
ctx_0 = data_layer(name="ctx_0_data", size=word_dict_len)
ctx_p1 = data_layer(name="ctx_p1_data", size=word_dict_len)
mark = data_layer(name="mark_data", size=mark_dict_len)

if not is_predict:
    target = data_layer(name="target", size=label_dict_len)

src_emb = ParameterAttribute(name="src_emb", learning_rate=lr_mult)
layer_attr = ExtraLayerAttribute(drop_rate=drop_rate)
fc_para_attr = ParameterAttribute(learning_rate=lr_mult)
lstm_para_attr = ParameterAttribute(initial_std=0.0, learning_rate=2 * lr_mult)
para_attr = [fc_para_attr, lstm_para_attr]

embs = [
    embedding_layer(size=word_dim, input=word, param_attr=src_emb),
    embedding_layer(size=word_dim, input=predicate, param_attr=src_emb),
    embedding_layer(size=word_dim, input=ctx_n1, param_attr=src_emb),
    embedding_layer(size=word_dim, input=ctx_0, param_attr=src_emb),
    embedding_layer(size=word_dim, input=ctx_p1, param_attr=src_emb),
    embedding_layer(size=mark_dim, input=mark),
]

hidden_0 = mixed_layer(
    size=hidden_dim,
    input=[full_matrix_projection(input=e) for e in embs],
)

lstm_0 = lstmemory(input=hidden_0, layer_attr=layer_attr)

# stack L-LSTM and R-LSTM with direct edges
input_tmp = [hidden_0, lstm_0]
for i in range(1, depth):
    fc = fc_layer(input=input_tmp, size=hidden_dim, param_attr=para_attr)
    lstm = lstmemory(
        input=fc,
        act=ReluActivation(),
        reverse=(i % 2) == 1,
        layer_attr=layer_attr,
    )
    input_tmp = [fc, lstm]

prob = fc_layer(
    input=input_tmp,
    size=label_dict_len,
    act=SoftmaxActivation(),
    param_attr=para_attr,
)

if not is_predict:
    outputs(classification_cost(input=prob, label=target))
else:
    outputs(prob)
