"""Synthetic IMDB-style review corpus for the sentiment demo.

The reference demo preprocesses the aclImdb dataset
(ref: demo/sentiment/preprocess.py); here reviews are synthesized with a
planted sentiment signal (longer documents than quick_start, 20-80 words)
so training runs with no downloads.
"""

import random

NUM_CLASSES = 2

POSITIVE = ["brilliant", "moving", "masterpiece", "superb", "delight",
            "captivating", "flawless", "charming", "gripping", "stunning"]
NEGATIVE = ["dull", "mess", "waste", "boring", "cliched", "shallow",
            "tedious", "incoherent", "forgettable", "lifeless"]
NEUTRAL = ["the", "movie", "film", "plot", "actor", "scene", "story", "it",
           "was", "with", "and", "a", "of", "in", "that", "this", "his",
           "her", "they", "screen", "director", "script", "music", "ending",
           "character", "moment", "minute", "hour", "watch", "see", "felt",
           "seemed", "looked", "went", "came", "thought", "knew", "made"]

VOCAB = POSITIVE + NEGATIVE + NEUTRAL


def synth_reviews(seed, n=800):
    """Yield (label, words) movie reviews with planted sentiment."""
    rng = random.Random(seed)
    for _ in range(n):
        label = rng.randint(0, NUM_CLASSES - 1)
        strong = POSITIVE if label else NEGATIVE
        weak = NEGATIVE if label else POSITIVE
        words = []
        for _ in range(rng.randint(20, 80)):
            r = rng.random()
            if r < 0.15:
                words.append(rng.choice(strong))
            elif r < 0.18:
                words.append(rng.choice(weak))
            else:
                words.append(rng.choice(NEUTRAL))
        yield label, words

def samples(file_name, n=800):
    """Real '<label>\\t<text>' corpus when the file-list entry exists
    (prepare_data.py output), else the synthetic generator."""
    from paddle_tpu.data import datasets

    yield from datasets.labeled_samples_or_synth(file_name, synth_reviews, n)


def resolve_dict(dict_path=""):
    """Converter dict file when given, else the synthetic vocabulary."""
    from paddle_tpu.data import datasets

    return datasets.resolve_word_dict(dict_path, VOCAB)
