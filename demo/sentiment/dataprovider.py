"""Data provider for the sentiment demo (ref: demo/sentiment/dataprovider.py)."""

from paddle.trainer.PyDataProvider2 import *

import common

UNK_IDX = 0


def hook(settings, dictionary, **kwargs):
    settings.word_dict = dictionary
    settings.input_types = [
        integer_value_sequence(len(dictionary)),
        integer_value(common.NUM_CLASSES),
    ]


# sort_by_length: reviews vary 5..30+ tokens — length-sorted bucketing
# (a paddle_tpu extension, doc/divergences.md) cuts padded-token waste
# substantially with batch order still shuffled
@provider(init_hook=hook, sort_by_length=True)
def process(settings, file_name):
    for label, words in common.samples(file_name):
        yield [settings.word_dict.get(w, UNK_IDX) for w in words], label
