#!/bin/bash
# Train the stacked-LSTM sentiment model (ref: demo/sentiment/train.sh).
set -e
cd "$(dirname "$0")"
echo train-seed-1 > train.list
echo test-seed-1 > test.list
paddle train \
  --config=trainer_config.py \
  --save_dir=./model_output \
  --num_passes=10 \
  --log_period=5
