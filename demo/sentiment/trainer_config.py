#edit-mode: -*- python -*-
"""Sentiment demo driver config (ref: demo/sentiment/trainer_config.py)."""

from paddle.trainer_config_helpers import *

from sentiment_net import *

is_test = get_config_arg("is_test", bool, False)
is_predict = get_config_arg("is_predict", bool, False)
# shrunk sizes for smoke runs: stacked_num=3 hid_dim=512 is the tutorial shape
hid_dim = get_config_arg("hid_dim", int, 512)
stacked_num = get_config_arg("stacked_num", int, 3)

dict_dim, class_dim = sentiment_data(is_test, is_predict,
                                     dict_path=get_config_arg("dict", str, ""))

settings(
    batch_size=128,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25,
)

stacked_lstm_net(dict_dim, class_dim=class_dim, hid_dim=hid_dim,
                 stacked_num=stacked_num, is_predict=is_predict)
# bidirectional_lstm_net(dict_dim, class_dim=class_dim, is_predict=is_predict)
