"""Convert the raw IMDB sentiment dataset (aclImdb) into corpus files.

Role analog of the reference's demo/sentiment/data/get_imdb.sh +
preprocess.py pipeline, minus the network fetch (no egress here — point
--imdb at an already-extracted aclImdb directory with
train/{pos,neg}/*.txt and test/{pos,neg}/*.txt).

Outputs under --out (default data/imdb-out):
  train.txt / test.txt   '<label>\t<tokenized text>' lines, shuffled
                         (label 1 = pos, 0 = neg)
  dict.txt               frequency-ordered vocabulary from the train split
  train.list / test.list one corpus path per line

Then train with
  --config_args=dict=data/imdb-out/dict.txt
and train.list/test.list pointing at the written lists.
"""

from __future__ import annotations

import argparse
import glob
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from paddle_tpu.data import datasets


def _read_split(imdb_dir: str, split: str):
    samples = []
    for label, sub in ((1, "pos"), (0, "neg")):
        for path in sorted(glob.glob(os.path.join(imdb_dir, split, sub, "*.txt"))):
            with open(path, encoding="utf-8", errors="replace") as f:
                words = datasets.tokenize(f.read())
            if words:
                samples.append((label, words))
    return samples


def convert(imdb_dir: str, out_dir: str, seed: int = 42, max_dict: int = 30000,
            cutoff: int = 2):
    """Returns (n_train, n_test, dict_size). Deterministic under seed."""
    os.makedirs(out_dir, exist_ok=True)
    train = _read_split(imdb_dir, "train")
    test = _read_split(imdb_dir, "test")
    if not train or not test:
        raise FileNotFoundError(f"no aclImdb train/test review files under {imdb_dir}")
    rng = random.Random(seed)
    rng.shuffle(train)
    rng.shuffle(test)

    words = datasets.build_dict((w for _, w in train), max_size=max_dict, cutoff=cutoff)
    datasets.save_dict(words, os.path.join(out_dir, "dict.txt"))
    datasets.write_labeled_lines(train, os.path.join(out_dir, "train.txt"))
    datasets.write_labeled_lines(test, os.path.join(out_dir, "test.txt"))
    for name in ("train", "test"):
        with open(os.path.join(out_dir, f"{name}.list"), "w") as f:
            f.write(os.path.abspath(os.path.join(out_dir, f"{name}.txt")) + "\n")
    return len(train), len(test), len(words)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--imdb", required=True, help="extracted aclImdb directory")
    ap.add_argument("--out", default="data/imdb-out")
    ap.add_argument("--max_dict", type=int, default=30000)
    args = ap.parse_args()
    n_train, n_test, d = convert(args.imdb, args.out, max_dict=args.max_dict)
    print(f"wrote {n_train} train / {n_test} test samples, dict={d} words under {args.out}")


if __name__ == "__main__":
    main()
