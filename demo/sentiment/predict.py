"""Predict sentiment from a trained model via the embedding API
(ref: demo/sentiment/predict.py, which drives the SWIG binding).

Usage:
    python predict.py --model_dir=./model_output [--data_file=f]
Reads one review per line (whitespace-tokenized) from data_file or the
synthetic corpus when absent, prints the predicted label per line.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from py_paddle import swig_paddle
from paddle.trainer.config_parser import parse_config
from paddle.trainer.PyDataProvider2 import integer_value_sequence

import common


class SentimentPrediction:
    def __init__(self, train_conf, model_dir, config_args="is_predict=1"):
        self.word_dict = {w: i for i, w in enumerate(common.VOCAB)}
        conf = parse_config(train_conf, config_args)
        self.network = swig_paddle.GradientMachine.createFromConfigProto(
            conf.model_config
        )
        self.network.loadParameters(model_dir)
        self.converter = swig_paddle.DataProviderConverter(
            [integer_value_sequence(len(self.word_dict))],
            self.network.input_layer_names(),
        )

    def predict_line(self, line):
        words = [self.word_dict.get(w, 0) for w in line.strip().split()]
        if not words:
            return None
        out = self.network.forwardTest(self.converter([[words]]))
        prob = out[0]["value"][0]
        return int(np.argmax(prob)), prob


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train_conf", default="trainer_config.py")
    p.add_argument("--config_args", default="is_predict=1,hid_dim=32")
    p.add_argument("--model_dir", required=True)
    p.add_argument("--data_file", default="")
    args = p.parse_args()

    predictor = SentimentPrediction(args.train_conf, args.model_dir, args.config_args)
    if args.data_file:
        lines = open(args.data_file)
    else:  # demo mode: a few synthetic reviews
        lines = [" ".join(ws) for _, ws in common.synth_reviews("demo", n=5)]
    for line in lines:
        res = predictor.predict_line(line)
        if res is not None:
            label, prob = res
            print(f"{label}\t{prob[label]:.4f}\t{line.strip()[:60]}")


if __name__ == "__main__":
    main()
