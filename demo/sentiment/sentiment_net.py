"""Sentiment-analysis networks (ref: demo/sentiment/sentiment_net.py).

Two interchangeable nets over a word-id sequence:
- stacked_lstm_net: alternating-direction stack of lstmemory layers with
  direct fc edges (the tutorial's headline model, 3 stacked layers), max
  pooled over time.
- bidirectional_lstm_net: single fwd+bwd LSTM pair with dropout.
"""

from paddle.trainer_config_helpers import *

import common


def sentiment_data(is_test=False, is_predict=False,
                   train_list="train.list", test_list="test.list",
                   dict_path=""):
    """Declare the sentiment data sources; returns (dict_dim, class_dim).
    dict_path (--config_args=dict=...) switches to a converter-written
    vocabulary, and file lists pointing at prepare_data.py output feed the
    real corpus through the same provider."""
    word_dict = common.resolve_dict(dict_path)
    if is_predict:
        return len(word_dict), common.NUM_CLASSES
    define_py_data_sources2(
        train_list=None if is_test else train_list,
        test_list=test_list,
        module="dataprovider",
        obj="process",
        args={"dictionary": word_dict},
    )
    return len(word_dict), common.NUM_CLASSES


def bidirectional_lstm_net(input_dim, class_dim=2, emb_dim=128, lstm_dim=128,
                           is_predict=False):
    data = data_layer("word", input_dim)
    emb = embedding_layer(input=data, size=emb_dim)
    bi_lstm = bidirectional_lstm(input=emb, size=lstm_dim)
    dropout = dropout_layer(input=bi_lstm, dropout_rate=0.5)
    output = fc_layer(input=dropout, size=class_dim, act=SoftmaxActivation())
    if is_predict:
        outputs(output)
    else:
        outputs(classification_cost(input=output, label=data_layer("label", 1)))


def stacked_lstm_net(input_dim, class_dim=2, emb_dim=128, hid_dim=512,
                     stacked_num=3, is_predict=False):
    """Alternating-direction stacked LSTM (fewer-layer variant of the
    architecture in aclweb.org/anthology/P15-1109)."""
    assert stacked_num % 2 == 1

    layer_attr = ExtraLayerAttribute(drop_rate=0.5)
    fc_para_attr = ParameterAttribute(learning_rate=1e-3)
    lstm_para_attr = ParameterAttribute(initial_std=0.0, learning_rate=1.0)
    para_attr = [fc_para_attr, lstm_para_attr]
    bias_attr = ParameterAttribute(initial_std=0.0, l2_rate=0.0)

    data = data_layer("word", input_dim)
    emb = embedding_layer(input=data, size=emb_dim)

    fc1 = fc_layer(input=emb, size=hid_dim, act=LinearActivation(),
                   bias_attr=bias_attr)
    lstm1 = lstmemory(input=fc1, act=ReluActivation(), bias_attr=bias_attr,
                      layer_attr=layer_attr)

    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fc_layer(input=inputs, size=hid_dim, act=LinearActivation(),
                      param_attr=para_attr, bias_attr=bias_attr)
        lstm = lstmemory(input=fc, reverse=(i % 2) == 0, act=ReluActivation(),
                         bias_attr=bias_attr, layer_attr=layer_attr)
        inputs = [fc, lstm]

    fc_last = pooling_layer(input=inputs[0], pooling_type=MaxPooling())
    lstm_last = pooling_layer(input=inputs[1], pooling_type=MaxPooling())
    output = fc_layer(input=[fc_last, lstm_last], size=class_dim,
                      act=SoftmaxActivation(),
                      bias_attr=bias_attr, param_attr=para_attr)

    if is_predict:
        outputs(output)
    else:
        outputs(classification_cost(input=output, label=data_layer("label", 1)))
