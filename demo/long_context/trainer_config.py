"""Long-context causal language model trained with SEQUENCE PARALLELISM.

The user-facing long-context recipe this framework treats as first-class
(doc/distributed.md "Sequence parallelism"): a transformer-style causal
LM whose attention context is sharded over the mesh's `seq` axis — ring
attention rotates K/V blocks over `ppermute` with an online softmax, so
the per-device memory is O(T / seq_axis) while the math equals full
attention exactly.

Defaults train single-device for a laptop-scale smoke; pass
--config_args=mesh_data=2,mesh_seq=4,seq_len=2048 to shard 2048-token
contexts over 4 devices (the reference framework has no analog — its
only attention is simple_attention inside recurrent groups).
"""

from paddle.trainer_config_helpers import *

VOCAB = get_config_arg("vocab", int, 500)
SEQ_LEN = get_config_arg("seq_len", int, 256)
DIM = get_config_arg("dim", int, 64)
HEADS = get_config_arg("heads", int, 4)
BLOCKS = get_config_arg("blocks", int, 2)
MESH_DATA = get_config_arg("mesh_data", int, 0)
MESH_SEQ = get_config_arg("mesh_seq", int, 0)
MESH = ""
if MESH_DATA or MESH_SEQ:
    axes = []
    if MESH_DATA:
        axes.append(f"data={MESH_DATA}")
    if MESH_SEQ:
        axes.append(f"seq={MESH_SEQ}")
    MESH = ",".join(axes)

define_py_data_sources2(
    train_list="train.list", test_list="test.list",
    module="dataprovider", obj="process",
    args={"vocab": VOCAB, "seq_len": SEQ_LEN},
)

settings(
    batch_size=get_config_arg("batch_size", int, 8),
    learning_rate=1e-3,
    learning_method=AdamOptimizer(),
    mesh_shape=MESH or None,
    dtype=get_config_arg("dtype", str, "float32"),
)

words = data_layer(name="words", size=VOCAB)
x = embedding_layer(input=words, size=DIM, param_attr=ParamAttr(name="tok_emb"))

for i in range(BLOCKS):
    # norm-free transformer-style block: ring-attention + position-wise
    # FFN with residual connections via addto_layer (small depth keeps
    # training stable without normalization)
    att = multi_head_attention_layer(
        input=x, num_heads=HEADS, causal=True,
        seq_parallel="ring" if "seq=" in MESH else "",
        name=f"block{i}_att",
    )
    x = addto_layer(input=[x, att], name=f"block{i}_res1", bias_attr=False)
    ffn = fc_layer(input=x, size=4 * DIM, act=ReluActivation(),
                   name=f"block{i}_ffn1")
    ffn = fc_layer(input=ffn, size=DIM, act=LinearActivation(),
                   name=f"block{i}_ffn2")
    x = addto_layer(input=[x, ffn], name=f"block{i}_res2", bias_attr=False)

logits = fc_layer(input=x, size=VOCAB, act=SoftmaxActivation(), name="lm_head")
next_words = data_layer(name="next_words", size=VOCAB)
outputs(classification_cost(input=logits, label=next_words))
