#!/bin/bash
# Single-device smoke:
#   ./train.sh
# Long-context sharded (2048 tokens over a data=2,seq=4 mesh — on real
# hardware the mesh maps to chips; for a CPU dry run export
# JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8):
#   ./train.sh --config_args=mesh_data=2,mesh_seq=4,seq_len=2048
set -e
echo seed-1 > train.list
echo seed-2 > test.list
paddle train \
  --config=trainer_config.py \
  --save_dir=./output \
  --num_passes=4 \
  --log_period=4 \
  "$@"
