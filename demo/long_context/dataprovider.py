"""Synthetic long-context corpus: a planted bigram language (each token
deterministically constrains its successor to a small set), so the LM's
next-token cross-entropy has real signal at any context length, with no
dataset on disk. Swap for a reader of real tokenized lines to use a
corpus."""

import random

from paddle.trainer.PyDataProvider2 import *


def hook(settings, vocab=500, seq_len=256, **kwargs):
    settings.vocab = vocab
    settings.seq_len = seq_len
    settings.input_types = {
        "words": integer_value_sequence(vocab),
        "next_words": integer_value_sequence(vocab),
    }


@provider(init_hook=hook, sort_by_length=False)
def process(settings, file_name):
    V, T = settings.vocab, settings.seq_len
    rng = random.Random(file_name)
    for _ in range(64):
        toks = [rng.randrange(V)]
        for _ in range(T):
            # planted structure: successor lives in a 8-token window
            # determined by the current token
            base = (toks[-1] * 7) % V
            toks.append((base + rng.randrange(8)) % V)
        yield {"words": toks[:-1], "next_words": toks[1:]}
