#edit-mode: -*- python -*-
"""quick_start: LSTM text classifier
(ref: demo/quick_start/trainer_config.lstm.py).

embedding → fc (dropout) → lstmemory (dropout) → max-pool over time →
softmax. Exercises the recurrent stack on variable-length sequences.
"""

from paddle.trainer_config_helpers import *

import common

word_dict = common.resolve_dict(get_config_arg("dict", str, ""))

is_predict = get_config_arg("is_predict", bool, False)
define_py_data_sources2(train_list="train.list" if not is_predict else None,
                        test_list="test.list" if not is_predict else "pred.list",
                        module="dataprovider_emb",
                        obj="process" if not is_predict else "process_predict",
                        args={"dictionary": word_dict})

settings(batch_size=128 if not is_predict else 1,
         learning_rate=2e-3,
         learning_method=AdamOptimizer(),
         regularization=L2Regularization(8e-4),
         gradient_clipping_threshold=25)

bias_attr = ParamAttr(initial_std=0.0, l2_rate=0.0)

data = data_layer(name="word", size=len(word_dict))
emb = embedding_layer(input=data, size=32)
fc = fc_layer(input=emb, size=64, act=LinearActivation(), bias_attr=bias_attr,
              layer_attr=ExtraAttr(drop_rate=0.1))
lstm = lstmemory(input=fc, act=TanhActivation(), bias_attr=bias_attr,
                 layer_attr=ExtraAttr(drop_rate=0.25))
lstm_last = pooling_layer(input=lstm, pooling_type=MaxPooling())
output = fc_layer(input=lstm_last, size=2, act=SoftmaxActivation())

if not is_predict:
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
else:
    outputs(maxid_layer(output))
