"""Bag-of-words provider for quick_start (ref: demo/quick_start/dataprovider_bow.py).

Each sample is the text as a 0/1 sparse vector over the dictionary plus the
integer label. The dictionary is passed from the trainer config through
`define_py_data_sources2(args=...)` into `init_hook`.
"""

from paddle.trainer.PyDataProvider2 import *

import common

UNK_IDX = 0


def initializer(settings, dictionary, **kwargs):
    settings.word_dict = dictionary
    settings.input_types = [
        sparse_binary_vector(len(dictionary)),
        integer_value(2),
    ]


@provider(init_hook=initializer, cache=CacheType.CACHE_PASS_IN_MEM)
def process(settings, file_name):
    for label, words in common.samples(file_name):
        ids = sorted({settings.word_dict.get(w, UNK_IDX) for w in words})
        yield ids, label


def predict_initializer(settings, dictionary, **kwargs):
    settings.word_dict = dictionary
    settings.input_types = [sparse_binary_vector(len(dictionary))]


@provider(init_hook=predict_initializer, should_shuffle=False)
def process_predict(settings, file_name):
    for _, words in common.samples(file_name, n=100):
        yield sorted({settings.word_dict.get(w, UNK_IDX) for w in words})
