"""Shared synthetic sentiment corpus for the quick_start demo.

The reference demo (ref: demo/quick_start/preprocess.sh) downloads Amazon
product reviews; here a deterministic generator plants the same kind of
signal — each sentence mixes sentiment-bearing words with neutral filler,
and the label is decided by which sentiment vocabulary dominates — so every
config trains out-of-the-box with no downloads. Swap `synth_samples` for a
reader of real `label\ttext` lines to use a real corpus.
"""

import random

POSITIVE = ["good", "great", "love", "excellent", "best", "happy", "wonderful",
            "perfect", "amazing", "recommend"]
NEGATIVE = ["bad", "poor", "hate", "terrible", "worst", "sad", "awful",
            "broken", "refund", "disappointing"]
NEUTRAL = ["the", "a", "it", "this", "product", "item", "was", "is", "i",
           "we", "they", "box", "time", "day", "use", "one", "very", "really",
           "quite", "somewhat", "arrived", "ordered", "bought", "tried",
           "works", "looks", "feels", "seems", "still", "again"]

VOCAB = POSITIVE + NEGATIVE + NEUTRAL


def write_dict(path):
    with open(path, "w") as f:
        for w in VOCAB:
            f.write(w + "\n")


def synth_samples(seed, n=1000):
    """Yield (label, words) pairs with planted sentiment signal."""
    rng = random.Random(seed)
    for _ in range(n):
        label = rng.randint(0, 1)
        strong = POSITIVE if label else NEGATIVE
        weak = NEGATIVE if label else POSITIVE
        length = rng.randint(5, 30)
        words = []
        for _ in range(length):
            r = rng.random()
            if r < 0.25:
                words.append(rng.choice(strong))
            elif r < 0.30:
                words.append(rng.choice(weak))  # noise
            else:
                words.append(rng.choice(NEUTRAL))
        yield label, words

def samples(file_name, n=1000):
    """Real '<label>\\t<text>' corpus when the file-list entry exists
    (prepare_data.py output), else the synthetic generator."""
    from paddle_tpu.data import datasets

    yield from datasets.labeled_samples_or_synth(file_name, synth_samples, n)


def resolve_dict(dict_path=""):
    """Converter dict file when given (--config_args=dict=...), else the
    synthetic vocabulary."""
    from paddle_tpu.data import datasets

    return datasets.resolve_word_dict(dict_path, VOCAB)
