#!/bin/bash
# Train any of the quick_start configs (ref: demo/quick_start/train.sh).
# Usage: ./train.sh [lr|emb|cnn|lstm]
set -e
cd "$(dirname "$0")"
cfg=${1:-lr}
echo train-seed-1 > train.list
echo test-seed-1 > test.list
paddle train \
  --config=trainer_config.${cfg}.py \
  --save_dir=./output_${cfg} \
  --num_passes=5 \
  --log_period=5
