"""Word-id sequence provider for quick_start (ref: demo/quick_start/dataprovider_emb.py).

Used by the embedding / CNN / LSTM configs: each sample is the sentence as
an integer-id sequence plus the label.
"""

from paddle.trainer.PyDataProvider2 import *

import common

UNK_IDX = 0


def initializer(settings, dictionary, **kwargs):
    settings.word_dict = dictionary
    settings.input_types = [
        integer_value_sequence(len(dictionary)),
        integer_value(2),
    ]


@provider(init_hook=initializer)
def process(settings, file_name):
    for label, words in common.samples(file_name):
        yield [settings.word_dict.get(w, UNK_IDX) for w in words], label


def predict_initializer(settings, dictionary, **kwargs):
    settings.word_dict = dictionary
    settings.input_types = [integer_value_sequence(len(dictionary))]


@provider(init_hook=predict_initializer, should_shuffle=False)
def process_predict(settings, file_name):
    for _, words in common.samples(file_name, n=100):
        yield [settings.word_dict.get(w, UNK_IDX) for w in words]
