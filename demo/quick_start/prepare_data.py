"""Convert raw Amazon product-review JSON into quick_start corpus files.

Role analog of the reference's demo/quick_start/data/get_data.sh +
preprocess.py pipeline (minus the network fetch — no egress here; point
--reviews at an already-downloaded reviews_Electronics_5.json.gz). Label
semantics match the reference: rating 5 is positive (label 1 here),
ratings 1-2 negative (label 0), 3-4 discarded. Tokenization is the
simple lowercase tokenizer in paddle_tpu.data.datasets (mosesdecoder
divergence documented in doc/divergences.md).

Outputs under --out (default data/amazon-out):
  train.txt / test.txt   '<label>\t<tokenized text>' lines, shuffled
  dict.txt               frequency-ordered vocabulary, id = line number
  train.list / test.list one corpus path per line

Then train with
  --config_args=dict=data/amazon-out/dict.txt
and train.list/test.list pointing at the written lists.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from paddle_tpu.data import datasets


def convert(reviews_path: str, out_dir: str, test_ratio: float = 0.1,
            seed: int = 42, max_dict: int = 30000):
    """Returns (n_train, n_test, dict_size). Deterministic under seed."""
    os.makedirs(out_dir, exist_ok=True)
    samples = []
    with datasets.open_maybe_gz(reviews_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            rating = float(d.get("overall", 0))
            words = datasets.tokenize(d.get("reviewText", ""))
            if not words:
                continue
            if rating >= 5:
                samples.append((1, words))
            elif rating <= 2:
                samples.append((0, words))
    rng = random.Random(seed)
    rng.shuffle(samples)
    n_test = max(1, int(len(samples) * test_ratio))
    test, train = samples[:n_test], samples[n_test:]

    words = datasets.build_dict((w for _, w in train), max_size=max_dict)
    datasets.save_dict(words, os.path.join(out_dir, "dict.txt"))
    datasets.write_labeled_lines(train, os.path.join(out_dir, "train.txt"))
    datasets.write_labeled_lines(test, os.path.join(out_dir, "test.txt"))
    for name in ("train", "test"):
        with open(os.path.join(out_dir, f"{name}.list"), "w") as f:
            f.write(os.path.abspath(os.path.join(out_dir, f"{name}.txt")) + "\n")
    return len(train), len(test), len(words)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--reviews", required=True,
                    help="reviews_*_5.json[.gz] (one JSON object per line)")
    ap.add_argument("--out", default="data/amazon-out")
    ap.add_argument("--test_ratio", type=float, default=0.1)
    args = ap.parse_args()
    n_train, n_test, d = convert(args.reviews, args.out, args.test_ratio)
    print(f"wrote {n_train} train / {n_test} test samples, dict={d} words under {args.out}")


if __name__ == "__main__":
    main()
