#edit-mode: -*- python -*-
"""quick_start: logistic regression over bag-of-words
(ref: demo/quick_start/trainer_config.lr.py).

The minimum end-to-end model — one fc+softmax over a sparse binary text
vector. SURVEY.md Milestone A.
"""

from paddle.trainer_config_helpers import *

import common

word_dict = common.resolve_dict(get_config_arg("dict", str, ""))

is_predict = get_config_arg("is_predict", bool, False)
trn = "train.list" if not is_predict else None
tst = "test.list" if not is_predict else "pred.list"
process = "process" if not is_predict else "process_predict"
define_py_data_sources2(train_list=trn,
                        test_list=tst,
                        module="dataprovider_bow",
                        obj=process,
                        args={"dictionary": word_dict})

settings(batch_size=128 if not is_predict else 1,
         learning_rate=2e-3,
         learning_method=AdamOptimizer(),
         regularization=L2Regularization(8e-4),
         gradient_clipping_threshold=25)

data = data_layer(name="word", size=len(word_dict))
output = fc_layer(input=data, size=2, act=SoftmaxActivation())

if not is_predict:
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
else:
    outputs(maxid_layer(output))
