#!/bin/bash
# Run prediction with a trained quick_start model
# (ref: demo/quick_start/predict.sh).
set -e
cd "$(dirname "$0")"
cfg=${1:-lr}
echo pred-seed-1 > pred.list
paddle test \
  --config=trainer_config.${cfg}.py \
  --config_args=is_predict=1 \
  --init_model_path=./output_${cfg}/pass-00004
