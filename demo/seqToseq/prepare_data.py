"""Convert raw parallel corpus files into seqToseq dicts + sbeos shards.

Role analog of the reference's demo/seqToseq/data/wmt14_data.sh +
preprocess pipeline, minus the network fetch — point --train_src /
--train_trg (and optionally --test_src / --test_trg) at already-downloaded
plain-text parallel files, one sentence per line, line i of src aligned
with line i of trg.

Outputs under --out (default data/wmt-out), the reference's corpus layout:
  src.dict / trg.dict    one word per line; <s>/<e>/<unk> are ids 0/1/2
  train/part-000...      '<src sentence>\t<trg sentence>' shard files
  test/part-000...       same for the held-out split
  train.list / test.list one shard path per line

Then train with
  --config_args=src_dict=data/wmt-out/src.dict,trg_dict=data/wmt-out/trg.dict
and train.list/test.list pointing at the written lists.
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from paddle_tpu.data import datasets

LINES_PER_SHARD = 50000


def _pairs(src_path, trg_path):
    with datasets.open_maybe_gz(src_path) as fs, datasets.open_maybe_gz(trg_path) as ft:
        for s, t in zip(fs, ft):
            s_toks, t_toks = s.split(), t.split()
            if s_toks and t_toks:
                yield s_toks, t_toks


def _write_shards(pairs, out_dir, lines_per_shard):
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    it = iter(pairs)
    for shard_idx in itertools.count():
        chunk = list(itertools.islice(it, lines_per_shard))
        if not chunk:
            break
        path = os.path.join(out_dir, f"part-{shard_idx:03d}")
        with open(path, "w") as f:
            for s_toks, t_toks in chunk:
                f.write(f"{' '.join(s_toks)}\t{' '.join(t_toks)}\n")
        paths.append(path)
    return paths


def convert(train_src, train_trg, out_dir, test_src=None, test_trg=None,
            max_dict: int = 30000, lines_per_shard: int = LINES_PER_SHARD):
    """Returns (n_train_shards, n_test_shards, src_dict_size, trg_dict_size)."""
    os.makedirs(out_dir, exist_ok=True)
    # two passes: dict building, then sharding (corpora can exceed memory)
    src_words = datasets.build_dict(
        (s for s, _ in _pairs(train_src, train_trg)),
        max_size=max_dict, reserved=datasets.SEQ_RESERVED)
    trg_words = datasets.build_dict(
        (t for _, t in _pairs(train_src, train_trg)),
        max_size=max_dict, reserved=datasets.SEQ_RESERVED)
    datasets.save_dict(src_words, os.path.join(out_dir, "src.dict"))
    datasets.save_dict(trg_words, os.path.join(out_dir, "trg.dict"))

    train_paths = _write_shards(_pairs(train_src, train_trg),
                                os.path.join(out_dir, "train"), lines_per_shard)
    test_paths = []
    if test_src and test_trg:
        test_paths = _write_shards(_pairs(test_src, test_trg),
                                   os.path.join(out_dir, "test"), lines_per_shard)
    for name, paths in (("train.list", train_paths), ("test.list", test_paths)):
        if paths:
            with open(os.path.join(out_dir, name), "w") as f:
                f.write("\n".join(os.path.abspath(p) for p in paths) + "\n")
    return len(train_paths), len(test_paths), len(src_words), len(trg_words)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--train_src", required=True)
    ap.add_argument("--train_trg", required=True)
    ap.add_argument("--test_src")
    ap.add_argument("--test_trg")
    ap.add_argument("--out", default="data/wmt-out")
    ap.add_argument("--max_dict", type=int, default=30000)
    args = ap.parse_args()
    nt, ns, ds, dt = convert(args.train_src, args.train_trg, args.out,
                             args.test_src, args.test_trg, args.max_dict)
    print(f"wrote {nt} train / {ns} test shards, dicts src={ds} trg={dt} under {args.out}")


if __name__ == "__main__":
    main()
