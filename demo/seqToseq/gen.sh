#!/bin/bash
# Beam-search generation from the trained model
# (ref: demo/seqToseq/translation/gen.sh drives paddle train --job=test).
set -e
cd "$(dirname "$0")"
echo seed2 > test.list
paddle gen \
  --config=gen.conf \
  --init_model_path=./model/pass-00007 \
  --gen_result=gen_result.txt
head -20 gen_result.txt
