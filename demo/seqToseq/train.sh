#!/bin/bash
# Train the attention NMT model (ref: demo/seqToseq/translation/train.sh).
set -e
cd "$(dirname "$0")"
echo seed1 > train.list
echo seed2 > test.list
paddle train \
  --config=train.conf \
  --save_dir=./model \
  --num_passes=8 \
  --log_period=10
