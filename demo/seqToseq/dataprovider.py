"""Translation data provider for the seqToseq demo
(ref: demo/seqToseq/dataprovider.py).

Two modes, one yield contract:
- real: when the config passes src_dict/trg_dict paths (written by
  prepare_data.py), file-list entries are corpus shards of
  '<src sentence>\t<trg sentence>' lines; words map through the dicts
  with <s>/<e>/<unk> at ids 0/1/2 (the reference's sbeos convention) and
  teacher forcing frames the target with <s>.../...<e>.
- synthetic (default): a deterministic toy "translation" — the target is
  the source reversed over a small shared vocabulary — so the demo runs
  with no dataset on disk.
"""

import os
import random

from paddle.trainer.PyDataProvider2 import *

VOCAB = 20          # ids 0..VOCAB-1; 0 = <s>, 1 = <e>, 2 = <unk>
MIN_LEN, MAX_LEN = 3, 8
NUM_SAMPLES = 300
START, END, UNK = 0, 1, 2


def dict_dims(src_dict_path="", trg_dict_path=""):
    """Layer dims for train.conf/gen.conf: converter dict sizes in real
    mode, the synthetic VOCAB otherwise. One definition so config-declared
    dims can never diverge from the provider's mapping."""
    if bool(src_dict_path) != bool(trg_dict_path):
        raise ValueError("real mode needs BOTH src_dict and trg_dict config args")
    if src_dict_path and trg_dict_path:
        from paddle_tpu.data import datasets

        return (len(datasets.load_dict(src_dict_path)),
                len(datasets.load_dict(trg_dict_path)))
    return VOCAB, VOCAB


def _load_dicts(settings, src_dict_path, trg_dict_path):
    if bool(src_dict_path) != bool(trg_dict_path):
        raise ValueError(
            "real mode needs BOTH src_dict and trg_dict "
            f"(got src_dict={src_dict_path!r}, trg_dict={trg_dict_path!r})"
        )
    if src_dict_path and trg_dict_path:
        from paddle_tpu.data import datasets

        settings.src_dict = datasets.load_dict(src_dict_path)
        settings.trg_dict = datasets.load_dict(trg_dict_path)
        return len(settings.src_dict), len(settings.trg_dict)
    settings.src_dict = settings.trg_dict = None
    return VOCAB, VOCAB


def hook(settings, src_dict=None, trg_dict=None, **kwargs):
    src_dim, trg_dim = _load_dicts(settings, src_dict, trg_dict)
    settings.input_types = {
        "source_language_word": integer_value_sequence(src_dim),
        "target_language_word": integer_value_sequence(trg_dim),
        "target_language_next_word": integer_value_sequence(trg_dim),
    }


def gen_hook(settings, src_dict=None, trg_dict=None, **kwargs):
    src_dim, _ = _load_dicts(settings, src_dict, trg_dict)
    settings.input_types = {"source_language_word": integer_value_sequence(src_dim)}


def _pairs(seed):
    rng = random.Random(seed)
    for _ in range(NUM_SAMPLES):
        n = rng.randint(MIN_LEN, MAX_LEN)
        src = [rng.randint(3, VOCAB - 1) for _ in range(n)]
        trg = list(reversed(src))
        yield src, trg


def _real_pairs(settings, file_name):
    from paddle_tpu.data import datasets

    for s_toks, t_toks in datasets.read_parallel_lines(file_name):
        src = [settings.src_dict.get(w, UNK) for w in s_toks]
        trg = [settings.trg_dict.get(w, UNK) for w in t_toks]
        yield src, trg


def _stream(settings, file_name):
    if getattr(settings, "src_dict", None) is not None:
        # real-corpus mode was requested (dicts passed): a missing shard is
        # an error — silently training on the synthetic toy corpus while
        # the user believes it's their data would be far worse
        if not os.path.exists(file_name):
            raise FileNotFoundError(f"corpus shard not found: {file_name}")
        yield from _real_pairs(settings, file_name)
    else:
        yield from _pairs(file_name)


@provider(init_hook=hook)
def process(settings, file_name):
    # decoder input = <s> + target; label = target + <e>  (teacher forcing)
    for src, trg in _stream(settings, file_name):
        yield {
            "source_language_word": src,
            "target_language_word": [START] + trg,
            "target_language_next_word": trg + [END],
        }


@provider(init_hook=gen_hook)
def gen_process(settings, file_name):
    for src, _ in _stream(settings, file_name):
        yield {"source_language_word": src}
