"""Synthetic translation data for the seqToseq demo.

The reference demo feeds WMT-14 fr→en corpus files
(/root/reference/demo/seqToseq/dataprovider.py); to keep this demo
self-contained it synthesizes a deterministic toy "translation": the target
sentence is the source sentence reversed, over a small shared vocabulary.
Swap `process` for a corpus reader (same yield contract) to train on real
data. Token ids 0/1 are reserved for <s>/<e> like the reference's dicts.
"""

import random

from paddle.trainer.PyDataProvider2 import *

VOCAB = 20          # ids 0..VOCAB-1; 0 = <s>, 1 = <e>
MIN_LEN, MAX_LEN = 3, 8
NUM_SAMPLES = 300


def _pairs(seed):
    rng = random.Random(seed)
    for _ in range(NUM_SAMPLES):
        n = rng.randint(MIN_LEN, MAX_LEN)
        src = [rng.randint(2, VOCAB - 1) for _ in range(n)]
        trg = list(reversed(src))
        yield src, trg


@provider(
    input_types={
        "source_language_word": integer_value_sequence(VOCAB),
        "target_language_word": integer_value_sequence(VOCAB),
        "target_language_next_word": integer_value_sequence(VOCAB),
    }
)
def process(settings, file_name):
    # decoder input = <s> + target; label = target + <e>  (teacher forcing)
    for src, trg in _pairs(file_name):
        yield {
            "source_language_word": src,
            "target_language_word": [0] + trg,
            "target_language_next_word": trg + [1],
        }


@provider(input_types={"source_language_word": integer_value_sequence(VOCAB)})
def gen_process(settings, file_name):
    for src, _ in _pairs(file_name):
        yield {"source_language_word": src}
