"""Attention GRU encoder-decoder network (NMT).

Same model family and config API as the reference demo
(/root/reference/demo/seqToseq/seqToseq_net.py:65-181): bidirectional GRU
encoder, Bahdanau attention, GRU decoder driven by a recurrent_group in
training and beam search in generation. Written against the TPU-native
DSL — the recurrent group compiles to lax.scan / static-shape beam search.
"""

from paddle.trainer_config_helpers import *


def gru_encoder_decoder(
    source_dict_dim,
    target_dict_dim,
    is_generating,
    word_vector_dim=512,
    encoder_size=512,
    decoder_size=512,
    beam_size=3,
    max_length=250,
    bos_id=0,
    eos_id=1,
    gen_result="gen_result.txt",
    gen_dict=None,
):
    src_word_id = data_layer(name="source_language_word", size=source_dict_dim)
    src_embedding = embedding_layer(
        input=src_word_id,
        size=word_vector_dim,
        param_attr=ParamAttr(name="_source_language_embedding"),
    )
    src_forward = simple_gru(input=src_embedding, size=encoder_size)
    src_backward = simple_gru(input=src_embedding, size=encoder_size, reverse=True)
    encoded_vector = concat_layer(input=[src_forward, src_backward])

    with mixed_layer(size=decoder_size) as encoded_proj:
        encoded_proj += full_matrix_projection(encoded_vector)

    backward_first = first_seq(input=src_backward)
    with mixed_layer(size=decoder_size, act=TanhActivation()) as decoder_boot:
        decoder_boot += full_matrix_projection(backward_first)

    def gru_decoder_with_attention(enc_vec, enc_proj, current_word):
        decoder_mem = memory(name="gru_decoder", size=decoder_size, boot_layer=decoder_boot)
        context = simple_attention(
            encoded_sequence=enc_vec, encoded_proj=enc_proj, decoder_state=decoder_mem
        )
        with mixed_layer(size=decoder_size * 3) as decoder_inputs:
            decoder_inputs += full_matrix_projection(context)
            decoder_inputs += full_matrix_projection(current_word)
        gru_step = gru_step_layer(
            name="gru_decoder", input=decoder_inputs, output_mem=decoder_mem, size=decoder_size
        )
        with mixed_layer(size=target_dict_dim, bias_attr=True, act=SoftmaxActivation()) as out:
            out += full_matrix_projection(input=gru_step)
        return out

    decoder_group_name = "decoder_group"
    if not is_generating:
        trg_embedding = embedding_layer(
            input=data_layer(name="target_language_word", size=target_dict_dim),
            size=word_vector_dim,
            param_attr=ParamAttr(name="_target_language_embedding"),
        )
        decoder = recurrent_group(
            name=decoder_group_name,
            step=gru_decoder_with_attention,
            input=[
                StaticInput(input=encoded_vector, is_seq=True),
                StaticInput(input=encoded_proj, is_seq=True),
                trg_embedding,
            ],
        )
        lbl = data_layer(name="target_language_next_word", size=target_dict_dim)
        cost = classification_cost(input=decoder, label=lbl)
        outputs(cost)
    else:
        trg_embedding = GeneratedInput(
            size=target_dict_dim,
            embedding_name="_target_language_embedding",
            embedding_size=word_vector_dim,
        )
        beam_gen = beam_search(
            name=decoder_group_name,
            step=gru_decoder_with_attention,
            input=[
                StaticInput(input=encoded_vector, is_seq=True),
                StaticInput(input=encoded_proj, is_seq=True),
                trg_embedding,
            ],
            bos_id=bos_id,
            eos_id=eos_id,
            beam_size=beam_size,
            max_length=max_length,
            dict_file=gen_dict,
            result_file=gen_result,
        )
        outputs(beam_gen)
