#edit-mode: -*- python -*-
"""CIFAR image classification with a small VGG (ref: demo/image_classification/vgg_16_cifar.py).

`--config_args=is_predict=1` builds the inference graph (no label/cost).
`--config_args=small=1` shrinks the net for CI smoke runs.
"""

from paddle.trainer_config_helpers import *

is_predict = get_config_arg("is_predict", bool, False)
small = get_config_arg("small", bool, False)

if not is_predict:
    # src_size > img_size gives train-time random cropping real freedom;
    # --config_args=meta=data/cifar-out/batches.meta,src_size=32 switches
    # to the real dataset written by prepare_data.py
    define_py_data_sources2(
        train_list="train.list",
        test_list="test.list",
        module="image_provider",
        obj="process",
        args={
            "img_size": 32,
            "src_size": get_config_arg("src_size", int, 36),
            "num_classes": 10,
            "meta": get_config_arg("meta", str, ""),
        },
    )

settings(
    batch_size=32 if small else 128,
    learning_rate=0.1 / 128.0,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * 128),
)

datadim = 3 * 32 * 32
img = data_layer(name="image", size=datadim)

if small:
    # two tiny conv blocks — same topology family, CI-sized
    tmp = img_conv_group(
        input=img, num_channels=3, conv_num_filter=[16], conv_filter_size=3,
        conv_padding=1, conv_act=ReluActivation(), pool_size=2, pool_stride=2,
        pool_type=MaxPooling(),
    )
    tmp = img_conv_group(
        input=tmp, conv_num_filter=[32], conv_filter_size=3, conv_padding=1,
        conv_act=ReluActivation(), pool_size=2, pool_stride=2,
        pool_type=MaxPooling(),
    )
    out = fc_layer(input=tmp, size=10, act=SoftmaxActivation(), name="output")
else:
    out = small_vgg(input_image=img, num_channels=3, num_classes=10)

if not is_predict:
    lbl = data_layer(name="label", size=10)
    outputs(classification_cost(input=out, label=lbl))
else:
    outputs(out)
