"""Convert raw CIFAR python batches into provider batch files + mean meta.

Role analog of the reference's demo/image_classification/data/download_cifar.sh
+ python/paddle/utils/preprocess_img.py pipeline: raw dataset -> shuffled
batch files + a ``batches.meta`` holding the training-set mean image that
image_provider.py subtracts from every sample. No network access is
assumed — point --cifar at an already-downloaded cifar-10-batches-py
directory (the standard python pickle release with data_batch_1..5 and
test_batch).

Outputs under --out (default data/cifar-out):
  batches/train_batch_NNN, batches/test_batch_NNN   pickled
      {"images": float32 (N,3,32,32) in [0,1], "labels": int list}
  batches.meta      np.savez with data_mean (3*32*32 float32, train mean)
  train.list / test.list   one batch path per line

Usage:
  python prepare_data.py --cifar data/cifar-10-batches-py [--out data/cifar-out]
Then train with
  --config_args=meta=data/cifar-out/batches.meta,src_size=32
and train.list/test.list pointing at the written lists.
"""

from __future__ import annotations

import argparse
import os
import pickle

import numpy as np

SAMPLES_PER_OUT_BATCH = 1024


def _load_raw_batch(path):
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    # standard CIFAR python pickles key by bytes; tolerate str too
    data = d.get(b"data", d.get("data"))
    labels = d.get(b"labels", d.get("labels", d.get(b"fine_labels")))
    images = np.asarray(data, np.float32).reshape(-1, 3, 32, 32) / 255.0
    return images, [int(x) for x in labels]


def convert(cifar_dir: str, out_dir: str, samples_per_batch: int = SAMPLES_PER_OUT_BATCH):
    """Returns (n_train, n_test). Deterministic: fixed shuffle seed."""
    batches_dir = os.path.join(out_dir, "batches")
    os.makedirs(batches_dir, exist_ok=True)

    def gather(names):
        imgs, labs = [], []
        for name in names:
            p = os.path.join(cifar_dir, name)
            if not os.path.exists(p):
                continue
            i, l = _load_raw_batch(p)
            imgs.append(i)
            labs.extend(l)
        if not imgs:
            raise FileNotFoundError(f"no CIFAR batches among {names} in {cifar_dir}")
        return np.concatenate(imgs), labs

    train_imgs, train_labs = gather([f"data_batch_{i}" for i in range(1, 6)])
    test_imgs, test_labs = gather(["test_batch"])

    rng = np.random.RandomState(0)
    order = rng.permutation(len(train_imgs))
    train_imgs, train_labs = train_imgs[order], [train_labs[i] for i in order]

    def write_split(imgs, labs, prefix):
        paths = []
        for b in range(0, len(imgs), samples_per_batch):
            path = os.path.join(batches_dir, f"{prefix}_batch_{b // samples_per_batch:03d}")
            with open(path, "wb") as f:
                pickle.dump(
                    {"images": imgs[b : b + samples_per_batch],
                     "labels": labs[b : b + samples_per_batch]},
                    f, protocol=pickle.HIGHEST_PROTOCOL,
                )
            paths.append(path)
        return paths

    train_paths = write_split(train_imgs, train_labs, "train")
    test_paths = write_split(test_imgs, test_labs, "test")

    # training-set mean image, flattened like the reference's batches.meta
    # (write through a handle — np.savez would append .npz to a bare path)
    with open(os.path.join(out_dir, "batches.meta"), "wb") as f:
        np.savez(f, data_mean=train_imgs.mean(axis=0).ravel().astype(np.float32))
    for name, paths in (("train.list", train_paths), ("test.list", test_paths)):
        with open(os.path.join(out_dir, name), "w") as f:
            f.write("\n".join(os.path.abspath(p) for p in paths) + "\n")
    return len(train_imgs), len(test_imgs)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cifar", required=True, help="cifar-10-batches-py directory")
    ap.add_argument("--out", default="data/cifar-out")
    ap.add_argument("--samples_per_batch", type=int, default=SAMPLES_PER_OUT_BATCH)
    args = ap.parse_args()
    n_train, n_test = convert(args.cifar, args.out, args.samples_per_batch)
    print(f"wrote {n_train} train / {n_test} test samples under {args.out}")


if __name__ == "__main__":
    main()
