"""CIFAR-style image provider with train-time augmentation
(ref: demo/image_classification/image_provider.py).

Pipeline per sample (paddle_tpu.utils.image_util): random crop from the
src_size source image + 50% horizontal flip when training, center crop at
test time, then dataset-mean subtraction — the reference's
preprocess_img, with an explicit per-file-seeded rng so every pass is
reproducible.

Data source: deterministic synthetic generator (each class plants a
distinct low-frequency color template at src_size; samples are template +
noise). To train on the real dataset instead, run
demo/image_classification/prepare_data.py over the CIFAR python batches —
it writes batch files + a meta mean this provider picks up via the
``meta`` arg (same yield contract, same config).
"""

import os
import pickle
import zlib

import numpy as np

from paddle.trainer.PyDataProvider2 import *
from paddle_tpu.utils import image_util

CHANNELS = 3
SAMPLES_PER_FILE = 256


def _class_template(label, size):
    rng = np.random.RandomState(1000 + label)
    # low-frequency pattern upsampled to full resolution, per channel
    coarse = rng.uniform(-1.0, 1.0, (CHANNELS, 4, 4))
    return np.kron(coarse, np.ones((size // 4, size // 4)))


_TEMPLATES = {}


def _templates(classes, size):
    key = (classes, size)
    if key not in _TEMPLATES:
        _TEMPLATES[key] = [_class_template(c, size) for c in range(classes)]
    return _TEMPLATES[key]


def hook(settings, img_size=32, src_size=36, num_classes=10, meta=None,
         is_train=True, **kwargs):
    """Provider init: declares slot types and resolves the mean image.

    img_size: crop fed to the net; src_size: generated source images
    (src_size > img_size makes train-time random cropping non-trivial);
    meta: optional path to a mean file (written by prepare_data.py) —
    absent, the mean of the class templates stands in.
    """
    settings.img_size = img_size
    settings.src_size = src_size
    settings.num_classes = num_classes
    settings.is_train = is_train
    if meta:
        # an explicit meta arg that can't be loaded is an error — silently
        # training on synthetic data while the user believes it's real
        # CIFAR would be far worse than failing here
        if not os.path.exists(meta):
            raise FileNotFoundError(f"meta file not found: {meta}")
        settings.img_mean = image_util.load_meta(meta, src_size, img_size)
        settings.real_batches = True
    else:
        tmpl = np.stack(_templates(num_classes, src_size))
        border = (src_size - img_size) // 2
        settings.img_mean = tmpl.mean(axis=0)[
            :, border : border + img_size, border : border + img_size
        ].astype(np.float32)
        settings.real_batches = False
    settings.input_types = {
        "image": dense_vector(img_size * img_size * CHANNELS),
        "label": integer_value(num_classes),
    }


@provider(init_hook=hook)
def process(settings, file_name):
    seed = zlib.crc32(file_name.encode()) % (2**31)
    rng = np.random.RandomState(seed)
    if settings.real_batches:
        with open(file_name, "rb") as f:
            data = pickle.load(f)
        images, labels = data["images"], data["labels"]
        order = rng.permutation(len(images)) if settings.is_train else range(len(images))
        for i in order:
            feat = image_util.preprocess_img(
                images[i], settings.img_mean, settings.img_size,
                settings.is_train, rng=rng,
            )
            yield {"image": feat.astype(np.float32).tolist(), "label": int(labels[i])}
        return
    tmpl = _templates(settings.num_classes, settings.src_size)
    for _ in range(SAMPLES_PER_FILE):
        label = int(rng.randint(settings.num_classes))
        img = tmpl[label] + rng.normal(0.0, 0.6, tmpl[label].shape)
        feat = image_util.preprocess_img(
            img, settings.img_mean, settings.img_size, settings.is_train, rng=rng
        )
        yield {"image": feat.astype(np.float32).tolist(), "label": label}
