"""Synthetic CIFAR-style image provider (ref: demo/image_classification/image_provider.py).

Deterministic generator: each class plants a distinct low-frequency color
template; samples are template + noise, so the net has real signal to
learn. Swap `process` for a reader of the preprocessed CIFAR batches
(same yield contract) to train on the real dataset.
"""

import zlib

import numpy as np

from paddle.trainer.PyDataProvider2 import *

IMG_SIZE = 32
CHANNELS = 3
CLASSES = 10
SAMPLES_PER_FILE = 256


def _class_template(label):
    rng = np.random.RandomState(1000 + label)
    # low-frequency pattern upsampled to full resolution, per channel
    coarse = rng.uniform(-1.0, 1.0, (CHANNELS, 4, 4))
    return np.kron(coarse, np.ones((IMG_SIZE // 4, IMG_SIZE // 4)))


_TEMPLATES = None


def _templates():
    global _TEMPLATES
    if _TEMPLATES is None:
        _TEMPLATES = [_class_template(c) for c in range(CLASSES)]
    return _TEMPLATES


@provider(
    input_types={
        "image": dense_vector(IMG_SIZE * IMG_SIZE * CHANNELS),
        "label": integer_value(CLASSES),
    }
)
def process(settings, file_name):
    seed = zlib.crc32(file_name.encode()) % (2**31)
    rng = np.random.RandomState(seed)
    tmpl = _templates()
    for _ in range(SAMPLES_PER_FILE):
        label = int(rng.randint(CLASSES))
        img = tmpl[label] + rng.normal(0.0, 0.6, tmpl[label].shape)
        yield {"image": img.astype(np.float32).ravel().tolist(), "label": label}
