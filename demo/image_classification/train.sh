#!/bin/bash
# Train the CIFAR VGG (ref: demo/image_classification/train.sh)
set -e
cd "$(dirname "$0")"
paddle train \
  --config=vgg_16_cifar.py \
  --save_dir=./cifar_vgg_model \
  --num_passes=300 \
  --log_period=100 \
  --use_tpu=1 \
  2>&1 | tee train.log
