#edit-mode: -*- python -*-
"""ctr: click-through-rate prediction over row-sharded sparse embeddings.

The millions-of-users workload (doc/sparse.md, ROADMAP item 5): two id
features — user and ad — each feed a ``sparse_embedding`` whose table
trains on the row-sparse path (per-row gradients, per-row optimizer
slots, ``row_range``-stamped durable shards). The user table is sized
by ``num_users`` so a chaos drill can make it exceed a simulated
per-host row budget (``--sparse_row_budget``) — the table then only
fits SHARDED across hosts, which is exactly the elastic machinery the
drill kills a host out from under.

Train with::

    paddle train --config=demo/ctr/trainer_config.py \
        --save_dir=output --num_passes=3
"""

from paddle.trainer_config_helpers import *

num_users = get_config_arg("num_users", int, 120)
num_ads = get_config_arg("num_ads", int, 48)
emb_dim = get_config_arg("emb_dim", int, 16)

define_py_data_sources2(
    train_list="train.list",
    test_list=None,
    module="dataprovider",
    obj="process",
    args={"num_users": num_users, "num_ads": num_ads},
)

settings(
    batch_size=32,
    learning_rate=0.1,
    learning_method=AdaGradOptimizer(),
)

user = data_layer(name="user_id", size=num_users)
ad = data_layer(name="ad_id", size=num_ads)
user_emb = sparse_embedding(input=user, size=emb_dim, name="user",
                            param_attr=ParamAttr(name="_user_emb"))
ad_emb = sparse_embedding(input=ad, size=emb_dim, name="ad",
                          param_attr=ParamAttr(name="_ad_emb"))
hidden = fc_layer(input=[user_emb, ad_emb], size=32, act=ReluActivation())
prediction = fc_layer(input=hidden, size=2, act=SoftmaxActivation())
label = data_layer(name="click", size=2)
outputs(classification_cost(input=prediction, label=label))
