"""Seeded synthetic CTR impressions for demo/ctr.

Each user belongs to an interest cluster and each ad to a topic; a
click is likely when they match. Fully deterministic per (file seed,
sample index) — the chaos drill replays the stream bit-exactly across
a crash/relaunch, so recovered training is comparable to uninterrupted
training. The id distribution is hot-set-skewed (a few heavy users
dominate impressions, the CTR-shaped access pattern), which keeps the
touched-row rate per batch well under the table size — the property
the sparse gather/scatter path exists for.
"""

import random

from paddle.trainer.PyDataProvider2 import *

N_CLUSTERS = 4


def initializer(settings, num_users, num_ads, **kwargs):
    settings.num_users = num_users
    settings.num_ads = num_ads
    settings.input_types = [
        integer_value(num_users),
        integer_value(num_ads),
        integer_value(2),
    ]


@provider(init_hook=initializer)
def process(settings, file_name):
    # file_name carries the seed ("impressions-seed-N"), mirroring the
    # model_zoo/embedding corpus convention
    seed = int(file_name.rsplit("-", 1)[-1])
    rng = random.Random(seed)
    for _ in range(1024):
        if rng.random() < 0.8:
            # hot set: 10% of users produce 80% of impressions
            user = rng.randrange(max(settings.num_users // 10, 1))
        else:
            user = rng.randrange(settings.num_users)
        ad = rng.randrange(settings.num_ads)
        match = user % N_CLUSTERS == ad % N_CLUSTERS
        click = 1 if rng.random() < (0.8 if match else 0.1) else 0
        yield user, ad, click
