"""Shared dataset dimensions for the recommendation demo — DSL-free so the
dataprovider can import it without executing the trainer config."""

MOVIE_IDS = 1000
USER_IDS = 800
TITLE_WORDS = 500
GENRES = 18
GENDERS = 2
AGES = 7
JOBS = 21


def load_meta(path):
    """meta.pkl written by prepare_data.py (dims + movie/user tables)."""
    import pickle

    with open(path, "rb") as f:
        return pickle.load(f)
