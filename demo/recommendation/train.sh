#!/bin/bash
# Train the CTR model (ref: demo/recommendation/run.sh).
set -e
cd "$(dirname "$0")"
echo seed1 > train.list
echo seed2 > test.list
paddle train --config=trainer_config.py --save_dir=./output --num_passes=6 --log_period=10
