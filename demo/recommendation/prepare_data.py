"""Convert raw MovieLens ml-1m files into provider meta + rating splits.

Role analog of the reference's demo/recommendation/data pipeline
(ml_data.sh fetch + meta_generator.py + split.py), minus the network
fetch — point --ml at an extracted ml-1m directory containing
movies.dat / users.dat / ratings.dat ('::'-separated, latin-1).

Outputs under --out (default data/ml-out):
  meta.pkl      {"dims": {...}, "movies": {mid: {"title": [word ids],
                "genres": [idx]}}, "users": {uid: {"gender": i,
                "age": i, "job": i}}} — the meta_generator.py role
  train.txt / test.txt   'uid::mid::rating' lines, split per user
                         (last `test_per_user` ratings of each user held
                         out — the split.py role)
  train.list / test.list one path per line

Then train with
  --config_args=meta=data/ml-out/meta.pkl
and train.list/test.list pointing at the written lists.
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from paddle_tpu.data import datasets

ML_AGES = [1, 18, 25, 35, 45, 50, 56]  # ml-1m age buckets, index = feature


def _read_dat(path):
    with open(path, encoding="latin-1") as f:
        for line in f:
            line = line.strip()
            if line:
                yield line.split("::")


def convert(ml_dir: str, out_dir: str, test_per_user: int = 1, max_title_dict: int = 5000):
    """Returns (n_train, n_test, dims). Deterministic (no RNG: the split
    holds out each user's most recent `test_per_user` ratings)."""
    os.makedirs(out_dir, exist_ok=True)

    # movies: id -> title word ids + genre indices
    raw_movies = list(_read_dat(os.path.join(ml_dir, "movies.dat")))
    title_tokens = [datasets.tokenize(title) for _, title, _ in raw_movies]
    title_words = datasets.build_dict(title_tokens, max_size=max_title_dict,
                                      reserved=("<unk>",))
    title_ids = {w: i for i, w in enumerate(title_words)}
    genre_names = sorted({g for _, _, gs in raw_movies for g in gs.split("|")})
    genre_ids = {g: i for i, g in enumerate(genre_names)}
    movies = {}
    for (mid, title, gs), toks in zip(raw_movies, title_tokens):
        movies[int(mid)] = {
            "title": [title_ids.get(t, 0) for t in toks] or [0],
            "genres": sorted(genre_ids[g] for g in gs.split("|")),
        }

    # users: id -> categorical features
    users = {}
    for uid, gender, age, job, _zip in _read_dat(os.path.join(ml_dir, "users.dat")):
        users[int(uid)] = {
            "gender": 0 if gender.upper() == "M" else 1,
            "age": ML_AGES.index(int(age)) if int(age) in ML_AGES else 0,
            "job": int(job),
        }

    # ratings: per-user split, most recent test_per_user held out
    by_user = defaultdict(list)
    for uid, mid, rating, ts in _read_dat(os.path.join(ml_dir, "ratings.dat")):
        by_user[int(uid)].append((int(ts), int(mid), float(rating)))
    train, test = [], []
    for uid in sorted(by_user):
        rs = sorted(by_user[uid])
        for i, (_, mid, r) in enumerate(rs):
            (test if i >= len(rs) - test_per_user else train).append((uid, mid, r))

    dims = {
        "movie_ids": max(movies) + 1,
        "user_ids": max(users) + 1,
        "title_words": len(title_words),
        "genres": len(genre_names),
        "genders": 2,
        "ages": len(ML_AGES),
        "jobs": max(u["job"] for u in users.values()) + 1,
    }
    with open(os.path.join(out_dir, "meta.pkl"), "wb") as f:
        pickle.dump({"dims": dims, "movies": movies, "users": users}, f,
                    protocol=pickle.HIGHEST_PROTOCOL)
    for name, rows in (("train", train), ("test", test)):
        with open(os.path.join(out_dir, f"{name}.txt"), "w") as f:
            for uid, mid, r in rows:
                f.write(f"{uid}::{mid}::{r}\n")
        with open(os.path.join(out_dir, f"{name}.list"), "w") as f:
            f.write(os.path.abspath(os.path.join(out_dir, f"{name}.txt")) + "\n")
    return len(train), len(test), dims


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ml", required=True, help="extracted ml-1m directory")
    ap.add_argument("--out", default="data/ml-out")
    ap.add_argument("--test_per_user", type=int, default=1)
    args = ap.parse_args()
    n_train, n_test, dims = convert(args.ml, args.out, args.test_per_user)
    print(f"wrote {n_train} train / {n_test} test ratings, dims={dims} under {args.out}")


if __name__ == "__main__":
    main()
