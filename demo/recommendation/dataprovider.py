"""Synthetic MovieLens-style data (ref: demo/recommendation/dataprovider.py).

Deterministic generator: each (movie, user) pair gets a rating from a
planted low-rank structure so the model has signal to learn. Replace
`process` with a reader of the real ml-1m files (same yield contract) to
train on MovieLens.
"""

import random

from paddle.trainer.PyDataProvider2 import *

import common as C


@provider(
    input_types={
        "movie_id": integer_value(C.MOVIE_IDS),
        "movie_title": integer_value_sequence(C.TITLE_WORDS),
        "movie_genre": sparse_binary_vector(C.GENRES),
        "user_id": integer_value(C.USER_IDS),
        "user_gender": integer_value(C.GENDERS),
        "user_age": integer_value(C.AGES),
        "user_job": integer_value(C.JOBS),
        "rating": dense_vector(1),
    }
)
def process(settings, file_name):
    rng = random.Random(file_name)
    for _ in range(2000):
        mid = rng.randrange(C.MOVIE_IDS)
        uid = rng.randrange(C.USER_IDS)
        title = [rng.randrange(C.TITLE_WORDS) for _ in range(rng.randint(2, 6))]
        genres = sorted(rng.sample(range(C.GENRES), rng.randint(1, 3)))
        gender = uid % C.GENDERS
        age = uid % C.AGES
        job = uid % C.JOBS
        # planted preference: users like movies whose id shares low bits
        rating = 1.0 if (mid % 8) == (uid % 8) else -1.0
        yield {
            "movie_id": mid,
            "movie_title": title,
            "movie_genre": genres,
            "user_id": uid,
            "user_gender": gender,
            "user_age": age,
            "user_job": job,
            "rating": [rating],
        }
