"""MovieLens-style data provider (ref: demo/recommendation/dataprovider.py).

Two modes sharing one yield contract:
- real: when the config passes ``meta`` (meta.pkl from prepare_data.py)
  and the file-list entries are rating files ('uid::mid::rating' lines),
  features are joined from the meta tables; ratings 1..5 are mapped to
  [-1, 1] to match the cos_sim output range.
- synthetic (default): deterministic generator — each (movie, user) pair
  gets a rating from a planted low-rank structure so the model has signal
  to learn with no dataset on disk.
"""

import os
import random

from paddle.trainer.PyDataProvider2 import *

import common as C


def hook(settings, meta=None, **kwargs):
    if meta:
        settings.meta = C.load_meta(meta)
        d = settings.meta["dims"]
    else:
        settings.meta = None
        d = {"movie_ids": C.MOVIE_IDS, "user_ids": C.USER_IDS,
             "title_words": C.TITLE_WORDS, "genres": C.GENRES,
             "genders": C.GENDERS, "ages": C.AGES, "jobs": C.JOBS}
    settings.input_types = {
        "movie_id": integer_value(d["movie_ids"]),
        "movie_title": integer_value_sequence(d["title_words"]),
        "movie_genre": sparse_binary_vector(d["genres"]),
        "user_id": integer_value(d["user_ids"]),
        "user_gender": integer_value(d["genders"]),
        "user_age": integer_value(d["ages"]),
        "user_job": integer_value(d["jobs"]),
        "rating": dense_vector(1),
    }


@provider(init_hook=hook)
def process(settings, file_name):
    if settings.meta is not None:
        # real mode was requested: a missing ratings file is an error, and
        # the synthetic generator's C.* id ranges may not even fit the
        # meta-declared dims — never fall back silently
        if not os.path.exists(file_name):
            raise FileNotFoundError(f"ratings file not found: {file_name}")
        movies, users = settings.meta["movies"], settings.meta["users"]
        with open(file_name) as f:
            for line in f:
                parts = line.strip().split("::")
                if len(parts) < 3:
                    continue
                uid, mid, rating = int(parts[0]), int(parts[1]), float(parts[2])
                m, u = movies.get(mid), users.get(uid)
                if m is None or u is None:
                    continue
                yield {
                    "movie_id": mid,
                    "movie_title": m["title"],
                    "movie_genre": m["genres"],
                    "user_id": uid,
                    "user_gender": u["gender"],
                    "user_age": u["age"],
                    "user_job": u["job"],
                    "rating": [(rating - 3.0) / 2.0],
                }
        return
    rng = random.Random(file_name)
    for _ in range(2000):
        mid = rng.randrange(C.MOVIE_IDS)
        uid = rng.randrange(C.USER_IDS)
        title = [rng.randrange(C.TITLE_WORDS) for _ in range(rng.randint(2, 6))]
        genres = sorted(rng.sample(range(C.GENRES), rng.randint(1, 3)))
        gender = uid % C.GENDERS
        age = uid % C.AGES
        job = uid % C.JOBS
        # planted preference: users like movies whose id shares low bits
        rating = 1.0 if (mid % 8) == (uid % 8) else -1.0
        yield {
            "movie_id": mid,
            "movie_title": title,
            "movie_genre": genres,
            "user_id": uid,
            "user_gender": gender,
            "user_age": age,
            "user_job": job,
            "rating": [rating],
        }
