#edit-mode: -*- python -*-
"""MovieLens-style CTR regression (ref: demo/recommendation/trainer_config.py).

Two-tower model: movie features (id embedding + title word sequence via
conv-pool + genre one-hot) and user features (id/gender/age/job embeddings)
each fused by fc layers; rating predicted from the towers' cosine
similarity. Embedding tables are marked sparse_update — only the rows a
batch touches advance, the TPU replacement for the reference's sparse
remote parameter updates.
"""

from paddle.trainer_config_helpers import *

# synthetic dataset dimensions shared with dataprovider.py; a real
# MovieLens meta.pkl (--config_args=meta=...) overrides them
from common import AGES, GENDERS, GENRES, JOBS, MOVIE_IDS, TITLE_WORDS, USER_IDS, load_meta

is_predict = get_config_arg("is_predict", bool, False)
meta_path = get_config_arg("meta", str, "")
if meta_path:
    _dims = load_meta(meta_path)["dims"]
    MOVIE_IDS, USER_IDS = _dims["movie_ids"], _dims["user_ids"]
    TITLE_WORDS, GENRES = _dims["title_words"], _dims["genres"]
    GENDERS, AGES, JOBS = _dims["genders"], _dims["ages"], _dims["jobs"]

settings(batch_size=64, learning_rate=1e-3, learning_method=RMSPropOptimizer())


def embed_fc(name, size, emb_dim=64, out=64):
    emb = embedding_layer(input=data_layer(name, size=size), size=emb_dim,
                          param_attr=ParamAttr(name=f"_{name}_emb", sparse_update=True))
    return fc_layer(input=emb, size=out)


def construct_movie():
    fusion = [embed_fc("movie_id", MOVIE_IDS)]
    title_emb = embedding_layer(input=data_layer("movie_title", size=TITLE_WORDS),
                                size=64,
                                param_attr=ParamAttr(name="_title_emb", sparse_update=True))
    fusion.append(sequence_conv_pool(input=title_emb, context_len=3, hidden_size=64))
    genre = data_layer("movie_genre", size=GENRES)
    fusion.append(fc_layer(input=fc_layer(input=genre, size=64), size=64))
    return fc_layer(name="movie_fusion", input=fusion, size=64)


def construct_user():
    fusion = [
        embed_fc("user_id", USER_IDS),
        embed_fc("user_gender", GENDERS, emb_dim=8),
        embed_fc("user_age", AGES, emb_dim=8),
        embed_fc("user_job", JOBS, emb_dim=8),
    ]
    return fc_layer(name="user_fusion", input=fusion, size=64)


similarity = cos_sim(a=construct_movie(), b=construct_user())
if not is_predict:
    outputs(regression_cost(input=similarity, label=data_layer("rating", size=1)))
    define_py_data_sources2("train.list", "test.list",
                            module="dataprovider", obj="process",
                            args={"meta": meta_path} if meta_path else None)
else:
    outputs(similarity)
