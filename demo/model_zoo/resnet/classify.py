"""Image classification / feature extraction with a trained ResNet
(ref: demo/model_zoo/resnet/classify.py, which drives the SWIG binding).

Usage:
    python classify.py --model_dir=./output/pass-00009 \
        [--layer_num=50] [--img_size=32] [--num_classes=16] [--n=8]
Feeds synthetic images (or .npy files listed via --data_file, one
flattened CHW float row per line) and prints top-1 class + probability.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from py_paddle import swig_paddle
from paddle.trainer.config_parser import parse_config
from paddle.trainer.PyDataProvider2 import dense_vector


class ImageClassifier:
    def __init__(self, conf_file, model_dir, config_args):
        conf = parse_config(conf_file, config_args)
        self.network = swig_paddle.GradientMachine.createFromConfigProto(
            conf.model_config
        )
        self.network.loadParameters(model_dir)
        dim = conf.model_config.layers[0].size
        self.converter = swig_paddle.DataProviderConverter(
            [dense_vector(dim)], self.network.input_layer_names()
        )
        self.dim = dim

    def classify(self, rows):
        out = self.network.forwardTest(self.converter([[r] for r in rows]))
        prob = out[0]["value"]
        top = np.argmax(prob, axis=-1)
        return top, prob[np.arange(len(top)), top]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--conf", default="resnet.py")
    p.add_argument("--model_dir", required=True)
    p.add_argument("--layer_num", type=int, default=50)
    p.add_argument("--img_size", type=int, default=32)
    p.add_argument("--num_classes", type=int, default=16)
    p.add_argument("--data_file", default="")
    p.add_argument("--n", type=int, default=4)
    args = p.parse_args()

    cfg_args = (
        f"is_predict=1,layer_num={args.layer_num},"
        f"img_size={args.img_size},num_classes={args.num_classes}"
    )
    clf = ImageClassifier(args.conf, args.model_dir, cfg_args)
    if args.data_file:
        rows = [np.load(line.strip()).ravel().tolist() for line in open(args.data_file)]
    else:
        rng = np.random.RandomState(0)
        rows = [rng.rand(clf.dim).astype(np.float32).tolist() for _ in range(args.n)]
    labels, probs = clf.classify(rows)
    for i, (l, pr) in enumerate(zip(labels, probs)):
        print(f"sample {i}: class={int(l)} prob={pr:.4f}")


if __name__ == "__main__":
    main()
