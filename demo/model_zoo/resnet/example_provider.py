"""Synthetic ImageNet-shaped provider for the ResNet config — planted
class templates + noise (same scheme as demo/image_classification).
Replace with a real ImageNet reader keeping the yield contract."""

import zlib

import numpy as np

from paddle.trainer.PyDataProvider2 import *

_TEMPLATES = {}


def _template(label, img_size):
    key = (label, img_size)
    if key not in _TEMPLATES:
        rng = np.random.RandomState(7000 + label)
        coarse = rng.uniform(-1.0, 1.0, (3, 4, 4))
        _TEMPLATES[key] = np.kron(coarse, np.ones((img_size // 4, img_size // 4)))
    return _TEMPLATES[key]


def _init(settings, img_size=224, num_classes=1000, **kwargs):
    settings.img_size = img_size
    settings.num_classes = num_classes
    settings.input_types = {
        "input": dense_vector(3 * img_size * img_size),
        "label": integer_value(num_classes),
    }


@provider(init_hook=_init)
def process(settings, file_name):
    seed = zlib.crc32(file_name.encode()) % (2**31)
    rng = np.random.RandomState(seed)
    n_classes = min(settings.num_classes, 16)
    for _ in range(64):
        label = int(rng.randint(n_classes))
        img = _template(label, settings.img_size) + rng.normal(
            0.0, 0.5, (3, settings.img_size, settings.img_size)
        )
        yield {"input": img.astype(np.float32).ravel().tolist(), "label": label}
