#!/bin/bash
# Train ResNet (ref: demo/model_zoo/resnet — reference ships pretrained
# models + feature extraction; we ship the training entry too)
set -e
cd "$(dirname "$0")"
paddle train \
  --config=resnet.py \
  --config_args=layer_num=50 \
  --save_dir=./resnet_model \
  --num_passes=90 \
  --log_period=100 \
  --use_tpu=1 \
  2>&1 | tee train.log
