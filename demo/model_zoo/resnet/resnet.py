#edit-mode: -*- python -*-
"""ResNet-50/101/152 ImageNet configs (ref: demo/model_zoo/resnet/resnet.py:160-242).

config_args:
  layer_num   50 | 101 | 152 (default 50)
  img_size    input resolution (default 224; use 32 for CIFAR-scale smoke runs)
  num_classes default 1000
  is_predict  build inference graph (no label/cost)
"""

from paddle.trainer_config_helpers import *

layer_num = get_config_arg("layer_num", int, 50)
img_size = get_config_arg("img_size", int, 224)
num_classes = get_config_arg("num_classes", int, 1000)
is_predict = get_config_arg("is_predict", bool, False)

STAGE_BLOCKS = {
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}[layer_num]

if not is_predict:
    define_py_data_sources2(
        train_list="train.list",
        test_list="test.list",
        module="example_provider",
        obj="process",
        args={"img_size": img_size, "num_classes": num_classes},
    )

settings(
    batch_size=64,
    learning_rate=0.01 / 64.0,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0001 * 64),
)


def conv_bn(name, input, filter_size, num_filters, stride, padding,
            channels=None, act=None):
    """conv (no bias) + batch-norm; linear unless act given."""
    conv = img_conv_layer(
        name=name,
        input=input,
        filter_size=filter_size,
        num_filters=num_filters,
        num_channels=channels,
        stride=stride,
        padding=padding,
        act=LinearActivation(),
        bias_attr=False,
    )
    return batch_norm_layer(name=name + "_bn", input=conv,
                            act=act or ReluActivation())


def bottleneck(name, input, mid_filters, out_filters, stride=1, project=False):
    """1x1 → 3x3 → 1x1 bottleneck with identity or projection shortcut."""
    if project:
        shortcut = conv_bn(name + "_proj", input, 1, out_filters, stride, 0,
                           act=LinearActivation())
    else:
        shortcut = input
    path = conv_bn(name + "_a", input, 1, mid_filters, stride, 0)
    path = conv_bn(name + "_b", path, 3, mid_filters, 1, 1)
    path = conv_bn(name + "_c", path, 1, out_filters, 1, 0,
                   act=LinearActivation())
    return addto_layer(name=name + "_sum", input=[shortcut, path],
                       act=ReluActivation())


def stage(name, input, blocks, mid_filters, out_filters, first_stride):
    tmp = bottleneck(name + "_1", input, mid_filters, out_filters,
                     stride=first_stride, project=True)
    for i in range(2, blocks + 1):
        tmp = bottleneck(f"{name}_{i}", tmp, mid_filters, out_filters)
    return tmp


img = data_layer(name="input", size=img_size * img_size * 3)
tmp = conv_bn("conv1", img, 7, 64, 2, 3, channels=3)
tmp = img_pool_layer(name="pool1", input=tmp, pool_size=3, stride=2,
                     padding=1, pool_type=MaxPooling())

widths = [(64, 256), (128, 512), (256, 1024), (512, 2048)]
for s, ((mid, out_w), blocks) in enumerate(zip(widths, STAGE_BLOCKS), start=2):
    tmp = stage(f"res{s}", tmp, blocks, mid, out_w,
                first_stride=1 if s == 2 else 2)

tmp = img_pool_layer(name="global_pool", input=tmp, pool_size=tmp.img_size,
                     stride=1, pool_type=AvgPooling())
output = fc_layer(name="output", input=tmp, size=num_classes,
                  act=SoftmaxActivation())

if not is_predict:
    lbl = data_layer(name="label", size=num_classes)
    outputs(classification_cost(input=output, label=lbl))
else:
    outputs(output)
