"""Skip-gram pair provider for embedding training."""

from paddle.trainer.PyDataProvider2 import *

import common


@provider(
    input_types={
        "word": integer_value(common.VOCAB_SIZE),
        "context": integer_value(common.VOCAB_SIZE),
    }
)
def process(settings, file_name):
    for center, ctx_word in common.synth_pairs(file_name):
        yield {"word": center, "context": ctx_word}
