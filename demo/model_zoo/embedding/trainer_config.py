#edit-mode: -*- python -*-
"""Skip-gram word embeddings with hierarchical-sigmoid output
(the training counterpart of ref demo/model_zoo/embedding's pretrained
vectors; hsigmoid keeps the output cost O(log V) like word2vec).
"""

from paddle.trainer_config_helpers import *

import common

emb_dim = get_config_arg("dim", int, 32)

define_py_data_sources2("train.list", "test.list",
                        module="dataprovider", obj="process")

settings(batch_size=256, learning_rate=1e-2, learning_method=AdamOptimizer())

word = data_layer(name="word", size=common.VOCAB_SIZE)
emb = embedding_layer(input=word, size=emb_dim,
                      param_attr=ParamAttr(name="_emb"))
hidden = fc_layer(input=emb, size=emb_dim, act=TanhActivation())
context = data_layer(name="context", size=common.VOCAB_SIZE)
cost = hsigmoid(input=hidden, label=context, num_classes=common.VOCAB_SIZE)
outputs(cost)
