#!/bin/bash
# Train skip-gram embeddings, then extract a user-dict subset.
set -e
cd "$(dirname "$0")"
echo corpus-seed-1 > train.list
echo corpus-seed-2 > test.list
paddle train --config=trainer_config.py --save_dir=./output --num_passes=5 --log_period=10
python - <<'PY'
import common
open("pre.dict", "w").write("\n".join(common.word_list()) + "\n")
open("usr.dict", "w").write("\n".join(common.word_list()[:10]) + "\n")
PY
python extract_para.py --model_dir=./output/pass-00004 \
    --pre_dict=pre.dict --usr_dict=usr.dict --out=usr_emb.npz
