"""Synthetic corpus for word-embedding training.

The reference model_zoo ships pretrained Chinese vectors
(ref: demo/model_zoo/embedding/pre_DictAndModel.sh); here embeddings are
*trained*: words are grouped into topic clusters and sentences draw from
one cluster, so skip-gram context prediction has real structure and
within-cluster vectors end up closer than across clusters.
"""

import random

NUM_CLUSTERS = 8
WORDS_PER_CLUSTER = 25
VOCAB_SIZE = NUM_CLUSTERS * WORDS_PER_CLUSTER


def word_list():
    return [f"w{c}_{i}" for c in range(NUM_CLUSTERS) for i in range(WORDS_PER_CLUSTER)]


def cluster_of(word_id: int) -> int:
    return word_id // WORDS_PER_CLUSTER


def synth_pairs(seed, n=6000, window=2):
    """Yield (center, context) skip-gram id pairs."""
    rng = random.Random(seed)
    for _ in range(n // 8):
        c = rng.randrange(NUM_CLUSTERS)
        sent = [c * WORDS_PER_CLUSTER + rng.randrange(WORDS_PER_CLUSTER)
                for _ in range(10)]
        for i, center in enumerate(sent):
            for off in range(-window, window + 1):
                j = i + off
                if off != 0 and 0 <= j < len(sent):
                    yield center, sent[j]
