"""Extract embedding rows for a user dictionary from a trained model
(ref: demo/model_zoo/embedding/extract_para.py — same job against the
binary parameter format; here checkpoints are npz).

Usage:
    python extract_para.py --model_dir=./output/pass-00004 \
        --param=_emb --pre_dict=pre.dict --usr_dict=usr.dict \
        --out=usr_emb.npz
Writes an npz with `words` (the user dict) and `vectors` [len(usr), dim].
"""

import argparse
import os

import numpy as np


def load_dict(path):
    with open(path) as f:
        return [line.strip().split("\t")[0] for line in f if line.strip()]


def extract(model_dir, param, pre_dict, usr_dict):
    with np.load(os.path.join(model_dir, "params.npz")) as z:
        table = z[param]
    index = {w: i for i, w in enumerate(pre_dict)}
    missing = [w for w in usr_dict if w not in index]
    assert not missing, f"words not in pretrained dict: {missing[:5]}..."
    rows = np.stack([table[index[w]] for w in usr_dict])
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_dir", required=True)
    p.add_argument("--param", default="_emb")
    p.add_argument("--pre_dict", required=True)
    p.add_argument("--usr_dict", required=True)
    p.add_argument("--out", required=True)
    args = p.parse_args()
    pre = load_dict(args.pre_dict)
    usr = load_dict(args.usr_dict)
    rows = extract(args.model_dir, args.param, pre, usr)
    np.savez(args.out, words=np.asarray(usr), vectors=rows)
    print(f"wrote {args.out}: {rows.shape[0]} words × {rows.shape[1]} dims")


if __name__ == "__main__":
    main()
