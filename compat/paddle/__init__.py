"""`paddle` import-compatibility shim.

Lets reference-era user configs (`from paddle.trainer_config_helpers import
*`, `from paddle.trainer.PyDataProvider2 import *`) run unmodified against
paddle_tpu. Added to sys.path by parse_config and the CLI.
"""
