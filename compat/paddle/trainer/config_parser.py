from paddle_tpu.config.config_parser import (  # noqa: F401
    get_config_arg,
    parse_config,
    parse_config_and_serialize,
)
