"""Legacy PyDataProviderWrapper slot declarations (compat).

Only the slot classes survive here — used by reference-style predictors
with DataProviderWrapperConverter (ref: /root/reference/python/paddle/
trainer/PyDataProviderWrapper.py). The legacy pickled-slot provider
protocol itself is superseded by PyDataProvider2.
"""


class _Slot:
    def __init__(self, dim):
        self.dim = dim


class DenseSlot(_Slot):
    pass


class IndexSlot(_Slot):
    pass


class SparseNonValueSlot(_Slot):
    pass


class SparseValueSlot(_Slot):
    pass


class StringSlot(_Slot):
    pass
