from paddle_tpu.data.provider import *  # noqa: F401,F403
from paddle_tpu.data.provider import __all__  # noqa: F401
