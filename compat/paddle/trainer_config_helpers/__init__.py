from paddle_tpu.trainer_config_helpers import *  # noqa: F401,F403
from paddle_tpu.config.config_parser import get_config_arg  # noqa: F401
