"""swig_paddle compatibility module → paddle_tpu.api.

Exposes the names reference predictors import
(ref: /root/reference/paddle/api/PaddleAPI.h:92-799 via Paddle.swig).
"""

from paddle_tpu.api import (  # noqa: F401
    DataProviderConverter,
    GradientMachine,
    SequenceGenerator,
    initPaddle,
)
