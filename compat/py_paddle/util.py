"""py_paddle.util compatibility — converter with the wrapper-slot API.

The reference's DataProviderWrapperConverter (py_paddle/util.py:83-213)
takes legacy PyDataProviderWrapper slot objects (IndexSlot/DenseSlot/...)
plus an is_sequence flag; translate those into PyDataProvider2 input
types and reuse the modern converter.
"""

from typing import Sequence

from paddle_tpu.api import DataProviderConverter
from paddle_tpu.data.provider import (
    DataType,
    SequenceType,
    InputType,
)


def _as_input_type(slot, is_sequence: bool) -> InputType:
    seq = SequenceType.SEQUENCE if is_sequence else SequenceType.NO_SEQUENCE
    if isinstance(slot, InputType):
        return InputType(slot.dim, seq, slot.type) if is_sequence else slot
    # legacy wrapper slots expose .dim and a class name ending in "Slot"
    dim = getattr(slot, "dim")
    name = type(slot).__name__
    mapping = {
        "IndexSlot": DataType.Index,
        "DenseSlot": DataType.Dense,
        "SparseNonValueSlot": DataType.SparseNonValue,
        "SparseValueSlot": DataType.SparseValue,
    }
    assert name in mapping, f"unsupported slot type {name}"
    return InputType(dim, seq, mapping[name])


class DataProviderWrapperConverter:
    def __init__(self, is_sequence: bool, slots: Sequence, slot_names=None):
        self.input_types = [_as_input_type(s, is_sequence) for s in slots]
        self.slot_names = list(slot_names) if slot_names else [
            str(i) for i in range(len(self.input_types))
        ]
        self._conv = DataProviderConverter(self.input_types, self.slot_names)

    def __call__(self, samples):
        return self._conv(list(samples))
