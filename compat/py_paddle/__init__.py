"""py_paddle compatibility package.

Lets reference-style user programs (`from py_paddle import swig_paddle,
util, DataProviderWrapperConverter`) run against paddle_tpu.api — the
SWIG module's roles without SWIG (ref: /root/reference/paddle/py_paddle/).
"""

from py_paddle import swig_paddle, util  # noqa: F401
from py_paddle.util import DataProviderWrapperConverter  # noqa: F401
