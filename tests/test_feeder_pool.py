"""Parallel batch packing (doc/performance.md "Zero-stall host"):
the --data_packer_threads pool must preserve batch order and shuffle
semantics exactly, keep the stall watchdog / fault-site / bad-sample
budget contracts of the single-thread prefetch path, respect the
--prefetch_depth bound, and publish the pack_threads_busy telemetry.
Also covers the bench.py feeder microbenchmark leg's shape."""

import os
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.data.feeder import DataProvider, MultiDataProvider
from paddle_tpu.data.provider import (
    dense_vector,
    dense_vector_sequence,
    integer_value,
    provider,
)
from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import BadSampleError, DataStallError, faultinject
from paddle_tpu.utils.retry import RetryPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    obs.registry().reset()
    yield
    faultinject.configure("")


def _dense_provider(n=64, bad_every=0, shuffle=None):
    @provider(input_types=[dense_vector(4), integer_value(2)],
              should_shuffle=shuffle)
    def process(settings, file_name):
        for i in range(n):
            if bad_every and i % bad_every == 3:
                yield ["not", "a", "float", "!"], 0
            else:
                yield [float(i)] * 4, i % 2

    return process


def _mk_dp(p, **kw):
    kw.setdefault("stall_timeout", 0)
    kw.setdefault("max_bad_samples", 0)
    kw.setdefault(
        "retry",
        RetryPolicy(max_attempts=3, base_delay=0.01, max_delay=0.02, jitter=0.0),
    )
    return DataProvider(p, ["f1"], 8, ["x", "y"], **kw)


def _values(batches):
    return [
        [float(v) for v in np.asarray(b["x"].value)[:, 0]] for b in batches
    ]


def test_pool_matches_sync_order_and_content_exactly():
    """Same seed, same provider: the 2-thread pool must deliver the
    SAME batches in the SAME order as the synchronous path — the
    sequential shuffle half runs on one dispatcher regardless of the
    packer count, and the queue is order-preserving."""
    ref = _values(_mk_dp(_dense_provider(n=64), async_prefetch=False,
                         seed=7).batches())
    pooled = _values(_mk_dp(_dense_provider(n=64), packer_threads=2,
                            seed=7).batches())
    assert pooled == ref
    four = _values(_mk_dp(_dense_provider(n=64), packer_threads=4,
                          seed=7, prefetch_depth=2).batches())
    assert four == ref


def test_single_thread_path_also_matches_sync():
    ref = _values(_mk_dp(_dense_provider(n=40), async_prefetch=False,
                         seed=3).batches())
    one = _values(_mk_dp(_dense_provider(n=40), packer_threads=1,
                         seed=3).batches())
    assert one == ref


@pytest.mark.chaos
def test_pool_stall_watchdog_fires():
    faultinject.configure("provider.stall=sleep:20@2")
    dp = _mk_dp(_dense_provider(), stall_timeout=1.0, packer_threads=2)
    t0 = time.monotonic()
    with pytest.raises(DataStallError) as ei:
        list(dp.batches())
    assert time.monotonic() - t0 < 10
    msg = str(ei.value)
    assert "data_stall_timeout" in msg and "alive" in msg, msg


def test_pool_propagates_provider_error():
    @provider(input_types=[dense_vector(4), integer_value(2)])
    def boom(settings, file_name):
        for i in range(20):
            yield [float(i)] * 4, i % 2
        raise ValueError("provider exploded")

    dp = _mk_dp(boom, packer_threads=2,
                retry=RetryPolicy(max_attempts=1, base_delay=0.0, jitter=0.0))
    with pytest.raises(ValueError, match="provider exploded"):
        list(dp.batches())


def test_pool_bad_sample_budget_semantics():
    dp = _mk_dp(_dense_provider(n=40, bad_every=10), max_bad_samples=5,
                packer_threads=2)
    total = sum(len(np.asarray(b["y"].ids)) for b in dp.batches())
    assert total == 36  # 4 malformed samples skipped, all others kept
    dp2 = _mk_dp(_dense_provider(n=40, bad_every=10), max_bad_samples=3,
                 packer_threads=2)
    with pytest.raises(BadSampleError, match="max_bad_samples"):
        list(dp2.batches())


def test_prefetch_depth_bounds_runahead():
    """With the consumer paused, the dispatcher may run at most
    prefetch_depth queued + packer_threads executing + 1 blocked-in-put
    batches ahead — the bounded queue is the backpressure."""
    produced = []

    @provider(input_types=[dense_vector(4), integer_value(2)],
              should_shuffle=False, pool_size=8)
    def counted(settings, file_name):
        for i in range(400):
            produced.append(i)
            yield [float(i)] * 4, i % 2

    dp = _mk_dp(counted, packer_threads=2, prefetch_depth=2)
    it = dp.batches()
    next(it)
    time.sleep(0.5)  # dispatcher free-runs against the bound
    # batches of 8 from a pool of 8: consumed 1 batch; bound =
    # depth(2) + threads(2) + 1 in-put + 1 delivered (+ pool slack of
    # one 8-sample refill in flight)
    assert len(produced) <= 8 * 8, len(produced)
    it.close()


def test_pool_busy_histogram_published():
    list(_mk_dp(_dense_provider(n=64), packer_threads=2).batches())
    snap = obs.registry().snapshot().get("data.pack_threads_busy")
    assert snap and snap["count"] > 0 and 1.0 <= snap["max"] <= 2.0, snap


def test_multi_provider_rides_the_pool():
    from paddle_tpu.proto import DataConfig

    subs = [_mk_dp(_dense_provider(n=32), async_prefetch=False, seed=i)
            for i in range(2)]
    mp = MultiDataProvider(subs, [1, 1], async_prefetch=True)
    total = sum(len(np.asarray(b["y"].ids)) for b in mp.batches())
    assert total == 64


def test_sort_by_length_unchanged_through_pool():
    @provider(input_types={"x": dense_vector_sequence(4),
                           "y": integer_value(2)},
              pool_size=32, should_shuffle=True)
    def seqs(settings, file_name):
        rng = np.random.RandomState(0)
        for i in range(64):
            t = int(rng.randint(1, 30))
            yield {"x": [[float(i)] * 4] * t, "y": i % 2}

    seqs.sort_by_length = True  # the @provider extension flag

    ref = _values_seq(_mk_dp_seq(seqs, async_prefetch=False, seed=5).batches())
    pooled = _values_seq(_mk_dp_seq(seqs, packer_threads=3, seed=5).batches())
    assert pooled == ref


def _mk_dp_seq(p, **kw):
    kw.setdefault("stall_timeout", 0)
    kw.setdefault("max_bad_samples", 0)
    return DataProvider(p, ["f1"], 8, ["x", "y"], **kw)


def _values_seq(batches):
    return [np.asarray(b["x"].seq_lengths).tolist() for b in batches]


# ------------------------------------------------------- bench feeder leg


def test_bench_feeder_leg_small():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    rate, extras = bench.bench_feeder(B=16, dim=32, n_batches=6, repeats=1)
    assert rate > 0
    assert extras["packer_threads"] == 2
    assert extras["samples_per_sec_1thread"] > 0
    assert extras["bytes_per_sec"] > 0
    assert "speedup_vs_1thread" in extras
