"""Nested (sub-sequence) in-links in GENERATION: each generated step s
consumes the s-th whole subsequence of a [B, S, T, D] in-link — training's
outer-scan-over-subsequences (createInFrameInfo hasSubseq, reference
RecurrentGradientMachine.cpp:564) running under generateSequence. The
reference forbids ALL in-links in generation (RecurrentGradientMachine.cpp
:374-377); this extends the framework's flat generation in-links upgrade
to nested conditioning. Verified against a numpy rollout (methodology of
tests/test_gen_seq_memory.py).
"""

import os
import tempfile
import textwrap

import jax.numpy as jnp
import numpy as np

from paddle_tpu.graph import Argument, GradientMachine


def parse_str(src: str):
    from paddle_tpu.config import parse_config

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(src))
        path = f.name
    try:
        return parse_config(path)
    finally:
        os.unlink(path)


E, V = 5, 8
BOS, EOS = 0, 7
MAXLEN = 6

GEN_NESTED = f"""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
ctxt = data_layer(name="ctxt", size={E})
def gen_step(prev_emb, sub_ctx):
    pooled = pooling_layer(input=sub_ctx, pooling_type=AvgPooling())
    comb = addto_layer(input=[pooled, prev_emb], act=LinearActivation(),
                       bias_attr=False)
    return fc_layer(input=comb, size={V}, act=SoftmaxActivation(), name="scorer")
out = beam_search(step=gen_step,
                  input=[GeneratedInput(size={V}, embedding_name="Tgen",
                                        embedding_size={E}),
                         SubsequenceInput(ctxt)],
                  bos_id={BOS}, eos_id={EOS}, beam_size=1, max_length={MAXLEN},
                  name="gen")
"""


def _softmax(x):
    e = np.exp(x - x.max())
    return e / e.sum()


def test_generation_nested_inlink_matches_numpy_rollout():
    B, S, T = 3, 4, 3
    rng = np.random.RandomState(11)
    x = rng.randn(B, S, T, E).astype(np.float32) * 2.0
    n_subs = np.array([4, 2, 3], np.int32)
    sub_lens = np.array([[3, 1, 2, 3], [2, 3, 0, 0], [1, 1, 2, 0]], np.int32)

    tc = parse_str(GEN_NESTED)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=4)
    batch = {
        "ctxt": Argument(
            value=jnp.asarray(x),
            seq_lengths=jnp.asarray(n_subs),
            sub_seq_lengths=jnp.asarray(sub_lens),
        )
    }
    out, _ = gm.forward(params, batch, "gen")
    got_ids = np.asarray(out["gen"].ids)
    got_lens = np.asarray(out["gen"].seq_lengths)

    Tgen = np.asarray(params["Tgen"])
    W = np.asarray(params["_scorer.w0"])
    bias = np.asarray(params["_scorer.wbias"]).reshape(-1)
    for b in range(B):
        prev = BOS
        toks = []
        for s in range(min(MAXLEN, int(n_subs[b]))):
            pooled = x[b, s, : sub_lens[b, s]].mean(axis=0)  # one subsequence
            comb = pooled + Tgen[prev]
            tok = int(np.argmax(_softmax(comb @ W + bias)))
            toks.append(tok)
            if tok == EOS:
                break
            prev = tok
        assert int(got_lens[b]) == len(toks), (b, got_lens[b], toks)
        np.testing.assert_array_equal(got_ids[b, : len(toks)], toks, err_msg=str(b))
