"""Structural pins for the scan-hoisting optimizations (jaxpr-level).

The parity tests (test_recurrent_group, test_fused_ce) prove hoisting
preserves numerics, and prove the PLANNER finds candidates — but a
regression that ignores the plan at apply time would pass both. These
tests walk the actual train-step jaxpr and assert the big matmuls live
where the optimization puts them:

- NMT decoder: the [.., vocab] output projection (epilogue hoisting) and
  the target-word input projection (prologue hoisting) must appear
  OUTSIDE every scan body; the per-step dots remaining inside the
  decoder scan are pinned by count, so a new per-step matmul sneaking
  into the hot loop fails the suite.
- LSTM classifier: the x-projection ([.., 4H] mixed input) is built
  outside the recurrence by construction; only the [H, 4H] recurrent dot
  may appear inside a scan.
"""

import jax

from paddle_tpu.flagship import (
    example_batch,
    flagship_config,
    nmt_batch,
    nmt_config,
)
from paddle_tpu.graph import GradientMachine


def _dots(jaxpr, in_scan=False, out=None):
    """Collect (in_scan, lhs_shape, rhs_shape, out_shape) for every
    dot_general, recursing like ops/kernel_flops.jaxpr_flops does."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    if out is None:
        out = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            out.append((in_scan, tuple(eqn.invars[0].aval.shape),
                        tuple(eqn.invars[1].aval.shape),
                        tuple(eqn.outvars[0].aval.shape)))
        elif name == "scan":
            _dots(eqn.params["jaxpr"], True, out)
        elif name == "while":
            _dots(eqn.params["body_jaxpr"], True, out)
        elif name == "cond":
            for b in eqn.params["branches"]:
                _dots(b, in_scan, out)
        else:
            for v in eqn.params.values():
                if hasattr(v, "jaxpr") or hasattr(v, "eqns"):
                    _dots(v, in_scan, out)
    return out


def _train_step_dots(tc, batch):
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=1)
    grad_fn = gm.grad_fn()
    jx = jax.make_jaxpr(lambda p, b: grad_fn(p, b, None)[0])(params, batch)
    return _dots(jx)


VOCAB = 300  # distinct from every hidden dim so vocab dots are identifiable


def test_nmt_vocab_and_word_projections_hoisted_out_of_scans():
    tc = nmt_config(vocab=VOCAB, dim=32, batch_size=4)
    dots = _train_step_dots(tc, nmt_batch(vocab=VOCAB, B=4, T=6))
    vocab_dots = [d for d in dots if VOCAB in d[2] or VOCAB in d[1]]
    assert vocab_dots, "expected vocab-projection dots in the step"
    in_scan_vocab = [d for d in vocab_dots if d[0]]
    assert not in_scan_vocab, (
        f"vocab-sized dot(s) inside a scan body — epilogue hoisting "
        f"regressed: {in_scan_vocab}"
    )
    # pin the per-step matmul count across ALL scans (encoder fwd+bwd
    # GRUs and the decoder group, forward + transpose passes): attention
    # (transform, scores) + context/input projections + gru_step +
    # recurrences. A new in-scan dot is a perf regression the parity
    # tests cannot see. Measured 27 at pinning time (round 5).
    in_scan = [d for d in dots if d[0]]
    assert len(in_scan) <= 27, (
        f"{len(in_scan)} dots inside scan bodies (was 27 at pinning "
        f"time; fwd+bwd): {in_scan}"
    )


def test_lstm_classifier_x_projection_outside_scan():
    H = 64
    tc = flagship_config(dict_dim=200, emb_dim=48, hidden=H, classes=2)
    dots = _train_step_dots(tc, example_batch(dict_dim=200, B=4, T=6))
    in_scan = [d for d in dots if d[0]]
    assert in_scan, "expected the recurrent dot inside the scan"
    for _, lhs, rhs, _o in in_scan:
        # only the [H, 4H] recurrent dot (fwd) and its transposes (bwd)
        # may live in the scan; the x-projection (emb -> 4H) must not
        assert 48 not in lhs and 48 not in rhs, (
            f"x-projection dot inside the scan: {lhs} x {rhs}"
        )
