"""Ops tooling: plotcurve parsing, model diagram, cluster launch dry run."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_plotcurve_parses_and_plots():
    from paddle_tpu.utils.plotcurve import ascii_plot, parse_log

    log = [
        "[x I paddle_tpu] Pass 0 done: samples=100 AvgCost=0.9 CurrentCost=0.9  e.classification_error: classification_error=0.5  (10 samples/s)",
        "[x I paddle_tpu] Pass 1 done: samples=100 AvgCost=0.7 CurrentCost=0.6  e.classification_error: classification_error=0.3  (10 samples/s)",
        "noise line",
        "[x I paddle_tpu] Pass 2 done: samples=100 AvgCost=0.5 CurrentCost=0.4  e.classification_error: classification_error=0.2  (10 samples/s)",
    ]
    series = parse_log(log)
    assert series["AvgCost"] == [0.9, 0.7, 0.5]
    assert series["classification_error"] == [0.5, 0.3, 0.2]
    art = ascii_plot(series["AvgCost"])
    assert "*" in art and "0.9" in art


def test_make_model_diagram(tmp_path):
    from paddle_tpu.config import parse_config
    from paddle_tpu.utils.make_model_diagram import make_diagram

    cfg_file = tmp_path / "conf.py"
    cfg_file.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=4, learning_rate=0.1)\n"
        "d = data_layer('x', size=4)\n"
        "o = fc_layer(input=d, size=2, act=SoftmaxActivation(), name='out')\n"
        "outputs(classification_cost(input=o, label=data_layer('label', size=2)))\n"
    )
    cfg = parse_config(str(cfg_file))
    dot = make_diagram(cfg.model_config)
    assert dot.startswith("digraph") and '"x" -> "out"' in dot


def test_cluster_launch_dry_run(tmp_path):
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h0', 'u@h1']\n")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job", "--dry_run",
         "--", "--config=train.conf", "--mesh_shape=data=16"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "PYTHONPATH": f"{REPO}:{REPO}/compat"},
    )
    assert out.returncode == 0, out.stderr
    assert "--process_id=0" in out.stdout and "--process_id=1" in out.stdout
    assert "--coordinator_address=h0:8476" in out.stdout
    assert "u@h1" in out.stdout


def _write_fake_ssh(bin_dir, body):
    """A stub `ssh` on PATH: argv is [-o, BatchMode=yes, host, remote] —
    $3 is the host, $4 the remote command (cluster_launch's call shape)."""
    ssh = bin_dir / "ssh"
    ssh.write_text("#!/bin/sh\nhost=$3\nremote=$4\n" + body)
    ssh.chmod(0o755)
    return {**os.environ, "PATH": f"{bin_dir}:{os.environ['PATH']}",
            "PYTHONPATH": f"{REPO}:{REPO}/compat"}


def test_cluster_launch_tears_down_on_first_host_failure(tmp_path):
    """One dead host must fail the whole launch promptly (and kill the
    surviving hosts) instead of leaving the launcher blocked in a serial
    wait while the others hang in collectives."""
    import time

    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_fail', 'u@h_hang']\n")
    env = _write_fake_ssh(tmp_path, (
        "case \"$host\" in\n"
        "  *fail*) sleep 0.3; exit 3;;\n"
        "  *) sleep 120;;\n"
        "esac\n"
    ))
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--", "--config=train.conf"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=60,
    )
    elapsed = time.monotonic() - t0
    assert out.returncode == 3, (out.returncode, out.stderr)
    assert elapsed < 30, elapsed  # did not wait out the 120s survivor
    # the failing rank is named in the exit message
    assert "rank 0" in out.stderr and "u@h_fail" in out.stderr


def test_cluster_launch_relaunches_with_auto_resume(tmp_path):
    """--max_restarts: after a host failure the whole job relaunches
    with --init_model_path=auto appended (resume from the newest
    verified checkpoint), and a clean second round exits 0."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_once', 'u@h_ok']\n")
    calls = tmp_path / "calls.log"
    marker = tmp_path / "round2"
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$remote\" >> {calls}\n"
        "case \"$host\" in\n"
        f"  *once*) if [ ! -f {marker} ]; then touch {marker}; exit 2; fi;"
        " exit 0;;\n"
        "  *) exit 0;;\n"
        "esac\n"
    ))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--max_restarts", "1", "--restart_delay", "0.1",
         "--", "--config=train.conf"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=60,
    )
    assert out.returncode == 0, (out.returncode, out.stderr)
    assert "relaunching" in out.stderr
    lines = calls.read_text().splitlines()
    assert len(lines) == 4  # 2 hosts x 2 rounds
    assert all("--init_model_path=auto" not in l for l in lines[:2])
    assert all("--init_model_path=auto" in l for l in lines[2:])


def test_cluster_launch_names_signal_deaths(tmp_path):
    """Satellite (doc/resilience.md): a host killed by a signal is
    reported by signal NAME (rc=-15 → SIGTERM), and the launcher's own
    exit status follows the 128+signum shell convention."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_sig', 'u@h_ok']\n")
    env = _write_fake_ssh(tmp_path, (
        "case \"$host\" in\n"
        "  *sig*) sleep 0.3; kill -TERM $$;;\n"
        "  *) sleep 120;;\n"
        "esac\n"
    ))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--", "--config=train.conf"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=60,
    )
    assert out.returncode == 143, (out.returncode, out.stderr)
    assert "SIGTERM" in out.stderr and "rc=-15" in out.stderr


def test_cluster_launch_preemption_exit_is_budget_free(tmp_path):
    """A host exiting EXIT_PREEMPTED (18 — clean preemption save) must
    trigger an auto-resume relaunch that consumes NO restart budget:
    even --max_restarts=0 (fail fast) relaunches."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_pre', 'u@h_ok']\n")
    calls = tmp_path / "calls.log"
    marker = tmp_path / "round2"
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$remote\" >> {calls}\n"
        "case \"$host\" in\n"
        f"  *pre*) if [ ! -f {marker} ]; then touch {marker}; exit 18; fi;"
        " exit 0;;\n"
        "  *) exit 0;;\n"
        "esac\n"
    ))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--max_restarts", "0", "--restart_delay", "0.1",
         "--", "--config=train.conf"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=60,
    )
    assert out.returncode == 0, (out.returncode, out.stderr)
    assert "preempt" in out.stderr
    assert "no restart budget" in out.stderr
    lines = calls.read_text().splitlines()
    assert len(lines) == 4  # 2 hosts x 2 rounds despite max_restarts=0
    assert all("--init_model_path=auto" in l for l in lines[2:])


def test_cluster_launch_elastic_drops_repeat_offender(tmp_path):
    """--elastic_min_hosts: a host that caused two job failures is
    dropped from the next relaunch; the survivors get recomputed ranks
    and --num_processes, and the job completes without it."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_bad', 'u@h_ok']\n")
    calls = tmp_path / "calls.log"
    # h_ok hangs while h_bad is around (it would be torn down anyway)
    # and exits 0 once it is the only host (--num_processes=1): round 3
    # — after the drop — is the clean single-host completion
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$host $remote\" >> {calls}\n"
        "case \"$host\" in\n"
        "  *bad*) sleep 0.2; exit 2;;\n"
        "  *) case \"$remote\" in\n"
        "       *--num_processes=1*) exit 0;;\n"
        "       *) sleep 120;;\n"
        "     esac;;\n"
        "esac\n"
    ))
    # budget of ONE: round 1 consumes it; round 2's failure triggers the
    # drop, whose relaunch must be budget-free (the drop IS the fix) —
    # with budget accounting on the drop round the job would give up here
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--max_restarts", "1", "--restart_delay", "0.1",
         "--elastic_min_hosts", "1",
         "--", "--config=train.conf"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=60,
    )
    assert out.returncode == 0, (out.returncode, out.stderr)
    assert "dropping host u@h_bad" in out.stderr, out.stderr
    assert "no restart budget consumed" in out.stderr
    lines = calls.read_text().splitlines()
    rounds3 = [l for l in lines if "--num_processes=1" in l]
    assert rounds3 and all("h_ok" in l.split()[0] for l in rounds3)
    assert all("--process_id=0" in l for l in rounds3)


def test_cluster_launch_heartbeat_staleness_names_wedged_rank(tmp_path):
    """Tentpole: a wedged-but-alive rank (process running, heartbeat
    stale) is detected by the launcher's staleness poll, named, and the
    job torn down with the hang exit code — the failure process
    liveness alone can never see."""
    import time

    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_beat', 'u@h_wedge']\n")
    hb_dir = tmp_path / "hb"
    hb_dir.mkdir()
    # the stub hosts write the heartbeat files themselves: h_beat renews
    # every 0.2s, h_wedge writes ONE beat then goes silent while staying
    # alive — exactly a wedged collective
    env = _write_fake_ssh(tmp_path, (
        "case \"$host\" in\n"
        "  *beat*)\n"
        "    i=0\n"
        "    while [ $i -lt 300 ]; do\n"
        f"      echo '{{\"host\": 0, \"t\": '$(date +%s)'}}' > {hb_dir}/host-0.json\n"
        "      sleep 0.2; i=$((i+1))\n"
        "    done;;\n"
        "  *wedge*)\n"
        f"    echo '{{\"host\": 1, \"t\": '$(date +%s)'}}' > {hb_dir}/host-1.json\n"
        "    sleep 120;;\n"
        "esac\n"
    ))
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--heartbeat_startup_grace", "0",  # stubs beat instantly
         "--", "--config=train.conf",
         "--heartbeat_interval=0.2", "--heartbeat_stale_after=3",
         f"--heartbeat_dir={hb_dir}"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    elapsed = time.monotonic() - t0
    from paddle_tpu.resilience import EXIT_HANG

    assert out.returncode == EXIT_HANG, (out.returncode, out.stderr)
    assert elapsed < 60, elapsed  # did not wait out the 120s wedge
    assert "rank 1" in out.stderr and "heartbeat stale" in out.stderr
    assert "wedged" in out.stderr


def test_cluster_launch_relative_heartbeat_dir_disables_monitoring(capsys):
    """A relative heartbeat dir resolves differently on the launcher
    and the hosts — monitoring must refuse it loudly instead of watching
    an empty local directory and tearing down healthy jobs."""
    from paddle_tpu.utils.cluster_launch import _heartbeat_config

    assert _heartbeat_config(
        ["--heartbeat_interval=5", "--save_dir=ckpts"]
    ) is None
    assert "relative" in capsys.readouterr().err
    dir_, stale = _heartbeat_config(
        ["--heartbeat_interval=5", "--heartbeat_dir=/shared/hb"]
    )
    assert dir_ == "/shared/hb" and stale == 15.0  # 3x interval default
    assert _heartbeat_config(["--config=c.py"]) is None  # hb off


def test_teardown_escalates_on_one_shared_deadline(monkeypatch):
    """Satellite: _teardown must not serially wait ≥0.1s per
    already-expired host — once the shared grace deadline has passed,
    the remaining hosts skip straight to SIGKILL."""
    import signal as _signal
    import time

    from paddle_tpu.utils import cluster_launch as cl

    class FakeProc:
        """A host that ignores SIGTERM for the whole grace window."""

        def __init__(self):
            self.signals = []
            self.wait_timeouts = []

        def got(self, sig):
            self.signals.append(sig)

        def poll(self):
            return None

        def wait(self, timeout=None):
            if timeout is None:
                return -9  # SIGKILL always lands
            self.wait_timeouts.append(timeout)
            time.sleep(timeout)  # stubborn: rides out the full grace
            raise subprocess.TimeoutExpired("ssh", timeout)

    monkeypatch.setattr(cl, "_signal_group", lambda p, sig: p.got(sig))
    procs = [FakeProc() for _ in range(20)]
    t0 = time.monotonic()
    cl._teardown(procs, grace_s=0.2)
    elapsed = time.monotonic() - t0
    # old behavior: 19 extra clamped 0.1s waits ≈ 2.1s total
    assert elapsed < 1.0, elapsed
    # only the host(s) inside the grace window got a timed wait; the
    # rest were killed outright
    assert sum(len(p.wait_timeouts) for p in procs) == 1
    for p in procs:
        assert p.signals == [_signal.SIGTERM, _signal.SIGKILL]


def test_cmd_arguments_doc_flags_exist():
    """Every `--flag` referenced in a doc/cmd_arguments.md table row must
    exist in utils/flags.py, so the flag reference can't silently rot —
    and (the reverse direction) every flag the code defines must appear
    in the doc, so a new flag can't land undocumented."""
    import dataclasses
    import re

    from paddle_tpu.utils.flags import _Flags

    known = {f.name for f in dataclasses.fields(_Flags)}
    doc = open(os.path.join(REPO, "doc", "cmd_arguments.md")).read()
    referenced = set()
    for line in doc.splitlines():
        if line.lstrip().startswith("|"):
            referenced.update(re.findall(r"`--([A-Za-z0-9_]+)", line))
    assert len(referenced) > 20, "doc table parsing broke"
    missing = referenced - known
    assert not missing, (
        f"doc/cmd_arguments.md references flags missing from "
        f"utils/flags.py: {sorted(missing)}"
    )
    # anywhere in the doc counts for the reverse check (a few flags are
    # described in prose rather than a table row)
    documented = set(re.findall(r"`--([A-Za-z0-9_]+)", doc))
    undocumented = known - documented
    assert not undocumented, (
        f"utils/flags.py defines flags doc/cmd_arguments.md never "
        f"mentions: {sorted(undocumented)}"
    )


def test_supervise_dry_run_prints_plan_without_launching(tmp_path):
    """`paddle supervise --dry_run` prints the child command and restart
    policy, launches nothing, and needs no jax/accelerator."""
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "supervise",
         "--dry_run=1", "--config=cfg.py", "--restart_budget=2",
         f"--supervise_dir={tmp_path / 'sup'}"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
        env={**os.environ, "PYTHONPATH": f"{REPO}:{REPO}/compat",
             "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    assert "--config=cfg.py" in out.stdout
    assert "--init_model_path=auto" in out.stdout
    assert "restart_budget=2" in out.stdout
    assert not (tmp_path / "sup").exists()


def test_trace_summary_reads_cpu_trace(tmp_path):
    """benchmarks/trace_summary.py parses a jax.profiler xplane trace and
    surfaces the dominant op (the HLO dot — SSA instances like "dot.4"
    folded onto their opcode; older jax exposed the framework name
    "dot_general", also accepted) for a matmul-heavy step."""
    import io
    import re
    import sys as _sys
    from contextlib import redirect_stdout

    import jax
    import jax.numpy as jnp

    _sys.path.insert(0, str(REPO))
    try:
        from benchmarks.trace_summary import print_summary
    finally:
        _sys.path.remove(str(REPO))

    f = jax.jit(lambda a, b: jnp.tanh(a @ b).sum())
    a = jnp.ones((256, 256))
    f(a, a).block_until_ready()
    with jax.profiler.trace(str(tmp_path)):
        for _ in range(3):
            f(a, a).block_until_ready()
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = print_summary(str(tmp_path), 10)
    out = buf.getvalue()
    assert rc == 0
    assert re.search(r"^dot(_general)?\b", out, re.M), out
    assert "matmul/conv" in out and "%" in out


def test_mfu_flops_accounting_matches_known_matmul():
    """benchmarks/mfu.py counts FLOPs via XLA cost analysis of the
    compiled step — pin it against a matmul whose FLOPs are known
    (2*M*N*K), so the bench's MFU denominator can't silently drift."""
    import jax
    import jax.numpy as jnp

    _sys = __import__("sys")
    _sys.path.insert(0, str(REPO))
    try:
        from benchmarks.mfu import flops_of_compiled, mfu, peak_tflops
    finally:
        _sys.path.remove(str(REPO))

    M = N = K = 256
    f = jax.jit(lambda a, b: a @ b)
    compiled = f.lower(
        jax.ShapeDtypeStruct((M, K), jnp.float32),
        jax.ShapeDtypeStruct((K, N), jnp.float32),
    ).compile()
    flops = flops_of_compiled(compiled)
    expected = 2 * M * N * K
    assert flops is not None
    assert 0.9 * expected <= flops <= 1.2 * expected, (flops, expected)
    # mfu: known device kinds produce a ratio, unknown produce None
    got = mfu(flops, step_time_s=1e-3, device_kind="TPU v5e")
    assert got is not None and 0 < got < 1e-3
    assert mfu(flops, 1e-3, "mystery-chip") is None
    assert peak_tflops("TPU v4") == 275.0


def test_bench_ladder_steps_down_only_on_oom():
    """bench._try_ladder must step down a rung ONLY for OOM-class errors
    (RESOURCE_EXHAUSTED / out-of-memory), re-raise anything else at the
    failing rung, and record every skipped rung + reason in the winning
    rung's extras so the emitted JSON can't hide a silent downgrade."""
    sys.path.insert(0, REPO)  # bench.py pins REPO on sys.path itself anyway
    from bench import _try_ladder

    # OOM at 256 steps down; 128 wins and reports the skipped rung
    def run_oom(b, r):
        if b == 256:
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating ...")
        return 100.0 * b, {"batch": b}

    v, extras = _try_ladder([(256, "none"), (128, "none")], run_oom)
    assert v == 12800.0
    assert extras["skipped_rungs"][0]["rung"] == [256, "none"]
    assert "RESOURCE_EXHAUSTED" in extras["skipped_rungs"][0]["error"]

    # a non-OOM failure (shape bug) re-raises immediately — no downgrade
    def run_bug(b, r):
        if b == 256:
            raise ValueError("dot_general shape mismatch")
        return 100.0 * b, {}

    try:
        _try_ladder([(256, "none"), (128, "none")], run_bug)
    except ValueError:
        pass
    else:
        raise AssertionError("non-OOM error must fail the leg loudly")

    # OOM on the LAST rung re-raises too (nothing left to step to)
    def run_all_oom(b, r):
        raise RuntimeError("RESOURCE_EXHAUSTED")

    try:
        _try_ladder([(64, "none")], run_all_oom)
    except RuntimeError:
        pass
    else:
        raise AssertionError("exhausted ladder must raise")


def test_show_pb_inspects_shard_and_checkpoint(tmp_path, capsys):
    """show_pb analog (ref python/paddle/utils/show_pb.py): dumps binary
    shards, checkpoint trees, and merged models."""
    import numpy as np

    from paddle_tpu.data.binary import write_shard
    from paddle_tpu.data.provider import dense_vector, integer_value
    from paddle_tpu.utils import show_pb

    shard = tmp_path / "shard.npz"
    write_shard(str(shard), [[[0.5, 1.0], 1], [[2.0, 3.0], 0]],
                [dense_vector(2), integer_value(2)])
    assert show_pb.show(str(shard)) == 0
    out = capsys.readouterr().out
    assert "samples: 2" in out and "dense" in out and "index" in out

    from paddle_tpu.trainer.checkpoint import save_checkpoint

    save_checkpoint(str(tmp_path / "model"), 0,
                    {"_fc.w0": np.ones((3, 2), np.float32)})
    assert show_pb.show(str(tmp_path / "model" / "pass-00000")) == 0
    out = capsys.readouterr().out
    assert "_fc.w0" in out and "(3, 2)" in out and "total parameters: 6" in out


def test_torch2paddle_converts_and_trains(tmp_path):
    """torch2paddle analog (ref python/paddle/utils/torch2paddle.py):
    torch Linear weights convert (transposed) into a checkpoint that
    initializes our fc layers and reproduces torch's forward."""
    import subprocess

    import numpy as np
    import torch

    from paddle_tpu.utils.torch2paddle import convert, convert_tensor

    # layout rules
    w = np.arange(6, dtype=np.float32).reshape(2, 3)  # torch [out=2, in=3]
    assert convert_tensor("x", w).shape == (3, 2)
    c = np.zeros((4, 3, 2, 2), np.float32)  # conv OIHW
    assert convert_tensor("c", c).shape == (4, 12)

    lin = torch.nn.Linear(4, 2)
    sd = lin.state_dict()
    model_path = tmp_path / "m.pth"
    torch.save(sd, str(model_path))
    layers = tmp_path / "layers.txt"
    layers.write_text("out\n")

    env = {**os.environ, "PYTHONPATH": f"{REPO}:{REPO}/compat",
           "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.torch2paddle",
         "-i", str(model_path), "-l", str(layers), "-o", str(tmp_path / "ckpt")],
        capture_output=True, text=True, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "ckpt" / "pass-00000" / "params.npz").exists()

    # the converted fc reproduces torch's forward: x @ w0 + wbias
    with np.load(tmp_path / "ckpt" / "pass-00000" / "params.npz") as z:
        w0, wb = z["_out.w0"], z["_out.wbias"]
    x = np.random.RandomState(0).rand(5, 4).astype(np.float32)
    ours = x @ w0 + wb
    theirs = lin(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)


def test_append_results_formats_tpu_session(tmp_path):
    """benchmarks/append_results.py: session JSON lines -> append-only
    RESULTS.md rows (last cumulative line wins, CPU smoke excluded,
    failed legs and skipped rungs surfaced)."""
    sys.path.insert(0, REPO)
    from benchmarks.append_results import fmt_row, parse_session

    raw = tmp_path / "raw.txt"
    raw.write_text(
        '=== TPU session\n'
        '{"metric": "m1", "value": 1.0, "unit": "x/s", "backend": "axon"}\n'
        '{"metric": "m1", "value": 1.0, "unit": "x/s", "backend": "axon",'
        ' "legs": {"l1": {"value": 2.0, "unit": "t/s"},'
        ' "l2": {"error": "E: boom"}}}\n'
        '--- f32 A/B\n'
        '{"metric": "m1", "value": 0.5, "unit": "x/s", "backend": "cpu"}\n'
        '{"metric": "bench_failed", "value": 0, "unit": "none", "error": "x"}\n'
    )
    sections = parse_session(str(raw))
    assert [ctx for ctx, _ in sections] == ["headline", "f32 A/B"]
    # cumulative: the headline's LAST line (with legs) won
    assert "legs" in sections[0][1]
    rows = [r for ctx, rec in sections for r in fmt_row("now", ctx, rec)]
    joined = "\n".join(rows)
    assert "**1.0 x/s**" in joined and "**2.0 t/s**" in joined
    assert "leg failed" in joined and "E: boom" in joined
    # the CPU line produced no row
    assert "0.5" not in joined


def test_append_results_sanitizes_and_sections(tmp_path, monkeypatch):
    """Multi-line / pipe-bearing error text must not break the markdown
    table, rows land in a headed section (header written once), and a
    second session appends without duplicating the header."""
    sys.path.insert(0, REPO)
    from benchmarks import append_results as ar

    import json as _json

    raw = tmp_path / "raw.txt"
    rec = {"metric": "m", "value": 1.0, "unit": "x", "backend": "axon",
           "legs": {"l": {"error": "UNAVAILABLE: line1\nline2 | pipe"}}}
    raw.write_text(_json.dumps(rec) + "\n")
    results = tmp_path / "RESULTS.md"
    results.write_text("# log\n\nprose tail\n")
    monkeypatch.setattr(ar, "HERE", str(tmp_path))
    assert ar.main([str(raw)]) == 0
    text = results.read_text()
    # every appended line is a well-formed single-line table row
    tail = text.split("prose tail\n", 1)[1]
    row_lines = [l for l in tail.splitlines() if l.startswith("|")]
    assert all(l.endswith("|") for l in row_lines), row_lines
    assert "line1 line2 \\| pipe" in text
    assert text.count("auto-appended") == 1
    # second session: rows appended, header not duplicated
    assert ar.main([str(raw)]) == 0
    assert results.read_text().count("auto-appended") == 1
    assert results.read_text().count("leg failed") == 2


def test_refresh_measured_json_headline_precedence(tmp_path, monkeypatch):
    """measured_tpu.json refresh: the production ("headline") row must win
    over later A/B contexts for the same metric, newest wins within a
    precedence class, legs are flattened, and a prior embedded
    last_measured key can never feed back into the file."""
    sys.path.insert(0, REPO)
    from benchmarks import append_results as ar

    import json as _json

    raw = tmp_path / "raw.txt"
    raw.write_text(
        '=== TPU session\n'
        '{"metric": "m_res", "value": 2400.0, "unit": "i/s", "backend": "axon",'
        ' "dtype": "bfloat16", "last_measured": {"old": 1},'
        ' "legs": {"m_lstm": {"value": 6.0, "unit": "t/s"}}}\n'
        '--- f32 A/B\n'
        '{"metric": "m_res", "value": 1300.0, "unit": "i/s", "backend": "axon",'
        ' "dtype": "float32"}\n'
        '--- pallas nmt\n'
        '{"metric": "m_nmt", "value": 400.0, "unit": "t/s", "backend": "axon"}\n'
        '--- pallas nmt retry\n'
        '{"metric": "m_nmt", "value": 410.0, "unit": "t/s", "backend": "axon"}\n'
        '--- cpu smoke\n'
        '{"metric": "m_cpu", "value": 1.0, "unit": "i/s", "backend": "cpu"}\n'
    )
    monkeypatch.setattr(ar, "HERE", str(tmp_path))
    n = ar.refresh_measured_json(ar.parse_session(str(raw)), "2026-07-31 16:00Z")
    assert n == 3
    doc = _json.loads((tmp_path / "measured_tpu.json").read_text())
    rows = doc["rows"]
    # headline beat the later f32 A/B for the same metric
    assert rows["m_res"]["value"] == 2400.0 and rows["m_res"]["dtype"] == "bfloat16"
    assert "session_leg" not in rows["m_res"]
    # headline legs are flattened with the session backend
    assert rows["m_lstm"]["value"] == 6.0 and rows["m_lstm"]["backend"] == "axon"
    # newest non-headline wins when the headline lacks the metric
    assert rows["m_nmt"]["value"] == 410.0
    assert rows["m_nmt"]["session_leg"] == "pallas nmt retry"
    # CPU smoke never lands; embedded last_measured never feeds back
    assert "m_cpu" not in rows
    assert "last_measured" not in rows["m_res"]

    # a malformed file must not abort main()'s RESULTS.md append
    (tmp_path / "measured_tpu.json").write_text('{"rows": "oops"}')
    (tmp_path / "RESULTS.md").write_text("# log\n")
    assert ar.main([str(raw)]) == 0
    assert "m_res" in (tmp_path / "RESULTS.md").read_text()


def test_pallas_fallback_decorator(monkeypatch):
    """A leg failing with PADDLE_TPU_BENCH_PALLAS_RNN=1 reruns on the
    scan path with an honest JSON tag; without the env it fails loudly;
    the env value is restored either way."""
    sys.path.insert(0, REPO)
    from bench import _pallas_fallback

    calls = []

    @_pallas_fallback
    def leg(**kw):
        calls.append(os.environ.get("PADDLE_TPU_BENCH_PALLAS_RNN"))
        if os.environ.get("PADDLE_TPU_BENCH_PALLAS_RNN") == "1":
            raise RuntimeError("Mosaic lowering failed: vmem exceeded")
        return 42.0, {"mfu": 0.1}

    monkeypatch.setenv("PADDLE_TPU_BENCH_PALLAS_RNN", "1")
    v, extras = leg()
    assert v == 42.0 and calls == ["1", "0"]
    assert "FELL BACK" in extras["pallas_rnn"] and "Mosaic" in extras["pallas_rnn"]
    assert os.environ["PADDLE_TPU_BENCH_PALLAS_RNN"] == "1"

    # knob off: failures propagate (no silent downgrade)
    monkeypatch.setenv("PADDLE_TPU_BENCH_PALLAS_RNN", "0")

    @_pallas_fallback
    def bad(**kw):
        raise ValueError("real bug")

    import pytest

    with pytest.raises(ValueError):
        bad()


def test_pallas_fallback_double_failure(monkeypatch):
    """When the scan-path rerun ALSO fails, the raised error must carry
    the original pallas diagnosis, and the env flag must still be
    restored for later legs."""
    sys.path.insert(0, REPO)
    import pytest

    from bench import _pallas_fallback

    @_pallas_fallback
    def leg(**kw):
        if os.environ.get("PADDLE_TPU_BENCH_PALLAS_RNN") == "1":
            raise RuntimeError("Mosaic lowering failed")
        raise ValueError("scan path oom")

    monkeypatch.setenv("PADDLE_TPU_BENCH_PALLAS_RNN", "1")
    with pytest.raises(RuntimeError) as ei:
        leg()
    msg = str(ei.value)
    assert "scan path oom" in msg and "Mosaic lowering failed" in msg
    assert os.environ["PADDLE_TPU_BENCH_PALLAS_RNN"] == "1"


def test_run_abandoning_salvages_without_signaling():
    """run_abandoning: on timeout the child is left running (no signal —
    a signaled mid-claim TPU client wedges the tunnel) but its output so
    far is returned; on normal exit behaves like run()."""
    import time as _time

    from paddle_tpu.utils.backend_guard import run_abandoning

    # normal exit
    rc, out, err = run_abandoning(
        [sys.executable, "-c", "print('fast'); import sys; sys.exit(3)"],
        timeout_s=30)
    assert rc == 3 and out.strip() == "fast"

    # timeout: partial stdout salvaged, child NOT killed. Margins sized
    # for a loaded machine (observed flake: under a concurrent full-suite
    # run, interpreter startup alone exceeded a 2s window, so 'headline'
    # was printed only after the salvage) — the child sleeps far longer
    # than the timeout, and the timeout is generous vs startup cost.
    code = ("import sys, time\n"
            "print('headline', flush=True)\n"
            "time.sleep(60)\n"
            "print('late', flush=True)\n")
    t0 = _time.monotonic()
    rc, out, err = run_abandoning([sys.executable, "-c", code], timeout_s=8)
    assert _time.monotonic() - t0 < 30  # returned at the timeout, not after
    assert rc is None
    assert out.strip() == "headline"  # salvage of pre-hang output


def test_bench_gen_leg_micro():
    """bench.py's generation leg wiring: builds the beam-search graph,
    runs it, and reports best-beam tokens/s with the beam knobs tagged."""
    sys.path.insert(0, REPO)
    import bench

    v, extras = bench.bench_nmt_gen(B=2, T=4, vocab=60, dim=32, beam_size=2,
                                    max_length=5, steps=2, warmup=1,
                                    dtype="float32")
    assert v > 0
    assert extras["beam_size"] == 2 and extras["max_length"] == 5
    assert extras["tokens"] == "best-beam generated"


def test_resnet_ladder_order_plain_before_remat(monkeypatch):
    """All plain-batch rungs must precede any remat rung: if 512/none
    OOMs, the known-good 256/none wins the headline — never a 512/full
    whose +33% recompute would swap mfu for hw_flops_util."""
    sys.path.insert(0, REPO)
    import bench

    seen = []

    def fake_try_ladder(configs, run_one):
        seen.extend(configs)
        return 1.0, {}

    monkeypatch.setattr(bench, "_try_ladder", fake_try_ladder)
    monkeypatch.setattr(bench, "_jit_train_step",
                        lambda *a, **k: (_ for _ in ()).throw(AssertionError))
    bench.bench_resnet50()
    kinds = [r for _, r in seen]
    assert kinds == ["none"] * 4 + ["full"] * 4, seen
    # 256 leads: measured 2026-08-01 batch A/B (2201 imgs/s at 256 vs
    # 2082 at 512, 1957 at 768)
    assert [b for b, _ in seen][:4] == [256, 512, 128, 64], seen


def test_session_script_legs_are_valid_bench_args():
    """Every `python bench.py <leg>` in tpu_session.sh must name a leg
    main() accepts — a typo would silently burn that leg's slice of a
    rare tunnel window on a usage error."""
    import re

    sh = open(os.path.join(REPO, "benchmarks", "tpu_session.sh")).read()
    legs = re.findall(r"python bench\.py(?:\s+(\w+))?\s*>>", sh)
    assert legs, "no bench invocations found in tpu_session.sh"
    accepted = {"", "all", "resnet", "lstm", "nmt", "gen"}
    bad = [l for l in legs if l not in accepted]
    assert not bad, bad
