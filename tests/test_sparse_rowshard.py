"""Row-sharded sparse-parameter training (doc/sparse.md): the row
math, the ``row_range``-stamped durable shard records, the threaded
reshard loader, row-coverage verification end to end through `paddle
check-checkpoint`, the launcher/trainer row-budget refusals, and the
kind=sparse telemetry surface.

The chaos/e2e half (host killed between row-shard write and commit,
reshard-and-resume, the CTR demo drill) lives in
tests/test_sparse_chaos.py; the no-lost/duplicate-row schedule sweep
lives in tests/race_specs/spec_sparse_reshard.py under the `paddle
race` repo-wide gate.
"""

import json
import os

import numpy as np
import pytest

from paddle_tpu.sparse import ckpt as sparse_ckpt
from paddle_tpu.sparse import rowshard
from paddle_tpu.sparse import runtime as sparse_rt
from paddle_tpu.sparse.reshard import ReshardError, ReshardLoader
from paddle_tpu.trainer import checkpoint as ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.sparse


@pytest.fixture(autouse=True)
def _fresh_registry():
    sparse_rt.clear_tables()
    yield
    sparse_rt.clear_tables()


# ------------------------------------------------------------- row math


def test_partition_rows_tiles_exactly_and_balances():
    for nrows, n in [(10, 3), (7, 7), (3, 4), (0, 2), (1000, 16)]:
        ranges = rowshard.partition_rows(nrows, n)
        assert len(ranges) == n
        assert ranges[0][0] == 0 and ranges[-1][1] == nrows
        for (a, b), (c, d) in zip(ranges, ranges[1:]):
            assert b == c and a <= b and c <= d  # contiguous, ordered
        sizes = [b - a for a, b in ranges]
        assert max(sizes) - min(sizes) <= 1
        assert max(sizes) == rowshard.rows_per_host(nrows, n)


def test_partition_rows_rejects_bad_inputs():
    with pytest.raises(ValueError):
        rowshard.partition_rows(-1, 2)
    with pytest.raises(ValueError):
        rowshard.partition_rows(10, 0)


def test_row_budget_error_names_table_hosts_and_need():
    # fits: 100 rows over 2 hosts needs 50/host
    assert rowshard.row_budget_error({"emb": 100}, 2, 50) is None
    # budget <= 0 is unlimited (the flag default)
    assert rowshard.row_budget_error({"emb": 10**9}, 1, 0) is None
    err = rowshard.row_budget_error({"emb": 100}, 2, 49)
    assert err == (
        "sparse table 'emb' of 100 rows does not fit 2 host(s) within "
        "--sparse_row_budget=49 rows/host (needs 50)"
    )
    # the launcher's anonymous form (--sparse_total_rows) has no name
    err = rowshard.row_budget_error({"": 100}, 1, 10)
    assert err.startswith("sparse table of 100 rows")
    assert rowshard.row_budget_error({"emb": 1}, 0, 5) is not None


def test_reshard_plan_tiles_every_new_range():
    old = rowshard.partition_rows(100, 3)
    new = rowshard.partition_rows(100, 2)
    plan = rowshard.reshard_plan(old, new)
    assert len(plan) == 2
    for (nlo, nhi), parts in zip(new, plan):
        assert parts[0][1] == nlo and parts[-1][2] == nhi
        for (_, _, b), (_, c, _) in zip(parts, parts[1:]):
            assert b == c  # contiguous tiling in row order
    # the 3->2 shrink splits the middle host's block across both
    srcs = [{s for s, _, _ in parts} for parts in plan]
    assert 1 in srcs[0] and 1 in srcs[1]


def test_coverage_problems_names_holes_overlaps_and_bounds():
    assert rowshard.coverage_problems(10, [(0, 4, 0), (4, 10, 1)]) == []
    probs = rowshard.coverage_problems(10, [(0, 4, 0), (6, 10, 1)])
    assert probs == [
        "rows [4, 6) of 10 uncovered (no host's shard record claims them)"
    ]
    probs = rowshard.coverage_problems(10, [(0, 6, 0), (4, 10, 1)])
    assert len(probs) == 1 and "covered more than once" in probs[0]
    assert "host 1 overlaps host(s) 0" in probs[0]
    probs = rowshard.coverage_problems(10, [(0, 12, 0)])
    assert any("outside table" in p for p in probs)
    # a lost trailing host is an uncovered TAIL, named
    probs = rowshard.coverage_problems(10, [(0, 5, 0)])
    assert probs == [
        "rows [5, 10) of 10 uncovered (no host's shard record claims them)"
    ]


# ------------------------------------------------------- reshard loader


def _recs(ranges, table):
    return [
        {"file": f"params.shard{i:05d}.npz", "key": f"t::{i}",
         "row_range": [lo, hi]}
        for i, (lo, hi) in enumerate(ranges)
    ], (lambda rec: table[rec["row_range"][0]:rec["row_range"][1]])


def test_reshard_loader_assembles_any_slice_exactly_once():
    table = np.arange(40, dtype=np.float32).reshape(10, 4)
    records, read_fn = _recs([(0, 3), (3, 6), (6, 10)], table)
    reads = []
    loader = ReshardLoader(
        records, lambda r: (reads.append(r["key"]), read_fn(r))[1],
        workers=3)
    np.testing.assert_array_equal(loader.load(2, 9), table[2:9])
    # only OVERLAPPING shards were read (record 0,1,2 all overlap [2,9))
    assert sorted(reads) == ["t::0", "t::1", "t::2"]
    reads.clear()
    np.testing.assert_array_equal(loader.load(4, 6), table[4:6])
    assert reads == ["t::1"]  # the others were never touched
    assert loader.load(5, 5).shape[0] == 0


def test_reshard_loader_names_missing_and_doubled_rows():
    table = np.zeros((10, 2), np.float32)
    records, read_fn = _recs([(0, 4), (6, 10)], table)
    with pytest.raises(ReshardError, match=r"rows \[4, 6\) missing"):
        ReshardLoader(records, read_fn).load(0, 10)
    records, read_fn = _recs([(0, 6), (4, 10)], table)
    with pytest.raises(ReshardError, match=r"rows \[4, 6\) written more"):
        ReshardLoader(records, read_fn).load(0, 10)


def test_reshard_loader_rejects_a_lying_shard():
    records, _ = _recs([(0, 10)], np.zeros((10, 2), np.float32))
    short = lambda rec: np.zeros((3, 2), np.float32)  # claims 10 rows
    with pytest.raises(ReshardError, match="claims rows"):
        ReshardLoader(records, short).load(0, 10)


# ----------------------------------------- durable row-shard records


def _sparse_snapshot(pid, ranges, table, pass_id=0):
    """Handcrafted (pieces, partial) for host pid owning ranges[pid] of
    a row-sharded table — the shape ``snapshot_owned_trees`` emits."""
    lo, hi = ranges[pid]
    shard_file = f"params.shard{pid:05d}.npz"
    return {"params": (
        {f"emb::{pid}": table[lo:hi] + 100.0 * pass_id},
        {"emb": {"shape": list(table.shape), "dtype": "float32",
                 "shards": [{"file": shard_file, "key": f"emb::{pid}",
                             "start": [lo, 0],
                             "shape": [hi - lo, table.shape[1]],
                             "row_range": [lo, hi]}]}},
    )}


def _commit_sparse_pass(save_dir, table, ranges, pass_id=0):
    for pid in range(len(ranges)):
        ckpt.write_sharded_host_trees(
            save_dir, pass_id, _sparse_snapshot(pid, ranges, table, pass_id),
            pid)
    return ckpt.finalize_sharded_pass(
        save_dir, pass_id, ["params"],
        {"pass_id": pass_id, "format_version": 2},
        expected_pids=range(len(ranges)))


def test_snapshot_owned_trees_stamps_row_range_for_registered_tables():
    import jax.numpy as jnp

    sparse_rt.register_tables({"emb": 10})
    flat = {"emb": jnp.arange(80, dtype=jnp.float32).reshape(10, 8),
            "dense_w": jnp.zeros((10, 8), jnp.float32)}
    _, partial = ckpt.snapshot_owned_trees({"params": flat}, 0)["params"]
    assert partial["emb"]["shards"][0]["row_range"] == [0, 10]
    # a same-shaped param NOT registered as a sparse table is untouched
    assert "row_range" not in partial["dense_w"]["shards"][0]


def test_verify_sharded_shards_proves_row_coverage(tmp_path):
    table = np.arange(80, dtype=np.float32).reshape(10, 8)
    ranges = rowshard.partition_rows(10, 2)
    path = _commit_sparse_pass(str(tmp_path), table, ranges)
    assert ckpt.verify_sharded_shards(path) == []
    # regression: a hand-torn merged index (host 1's claim shrunk) is a
    # NAMED row hole even though every byte still CRC-verifies
    idx_path = os.path.join(path, "params.index.json")
    with open(idx_path) as f:
        index = json.load(f)
    index["emb"]["shards"][1]["row_range"] = [5, 8]
    index["emb"]["shards"][1]["shape"] = [3, 8]
    with open(idx_path, "w") as f:
        json.dump(index, f)
    probs = ckpt.verify_sharded_shards(path)
    assert any("row coverage:" in p and "rows [8, 10)" in p
               for p in probs), probs


def test_load_table_rows_roundtrips_and_accepts_derived_ranges(tmp_path):
    table = np.arange(80, dtype=np.float32).reshape(10, 8)
    path = _commit_sparse_pass(
        str(tmp_path), table, rowshard.partition_rows(10, 2))
    np.testing.assert_array_equal(
        sparse_ckpt.load_table_rows(path, "emb", 3, 9), table[3:9])
    # pre-sparse records (no explicit row_range) derive theirs from
    # start/shape — old checkpoints stay row-loadable
    idx_path = os.path.join(path, "params.index.json")
    with open(idx_path) as f:
        index = json.load(f)
    for rec in index["emb"]["shards"]:
        del rec["row_range"]
    with open(idx_path, "w") as f:
        json.dump(index, f)
    np.testing.assert_array_equal(
        sparse_ckpt.load_table_rows(path, "emb", 0, 10), table)
    with pytest.raises(KeyError):
        sparse_ckpt.load_table_rows(path, "nope", 0, 1)


def test_reshard_from_committed_pass_survives_host_count_change(tmp_path):
    """The relaunch round's actual read pattern: a 3-host checkpoint
    reassembled onto 2 hosts' new ranges, every row bit-exact."""
    table = np.arange(33 * 4, dtype=np.float32).reshape(33, 4)
    path = _commit_sparse_pass(
        str(tmp_path), table, rowshard.partition_rows(33, 3))
    for lo, hi in rowshard.partition_rows(33, 2):
        np.testing.assert_array_equal(
            sparse_ckpt.load_table_rows(path, "emb", lo, hi),
            table[lo:hi])


def test_check_checkpoint_partial_on_committed_row_hole(tmp_path, capsys):
    """Satellite 3: a committed dir whose only problems are row-coverage
    gaps classifies PARTIAL (exit 1) and names interval + host(s)."""
    from paddle_tpu import cli
    from paddle_tpu.resilience import manifest as mf

    save_dir = str(tmp_path)
    table = np.arange(80, dtype=np.float32).reshape(10, 8)
    path = _commit_sparse_pass(
        save_dir, table, rowshard.partition_rows(10, 2))
    idx_path = os.path.join(path, "params.index.json")
    with open(idx_path) as f:
        index = json.load(f)
    # host 1's row CLAIM shrinks while its bytes/extent stay intact —
    # the bad-merge shape only the row check can see
    index["emb"]["shards"][1]["row_range"] = [5, 8]
    with open(idx_path, "w") as f:
        json.dump(index, f)
    # keep the byte-level manifest TRUE so the row check is the only
    # failing one (the scenario: a bad merge, not disk corruption)
    m = mf.read_manifest(path)
    m["files"]["params.index.json"] = mf.file_digest(idx_path)
    mf.write_manifest(path, m)
    assert ckpt.verify_checkpoint(path) == []
    assert cli.main(["check-checkpoint", save_dir]) == 1
    out = capsys.readouterr().out
    assert "PARTIAL" in out and "CORRUPT" not in out
    assert "rows [8, 10)" in out, out


def test_check_checkpoint_names_row_holes_in_torn_tmp(tmp_path, capsys):
    """A torn pass tmp dir (one host's shards never landed) reports the
    missing row interval from the survivors' partial indexes."""
    from paddle_tpu import cli

    save_dir = str(tmp_path)
    table = np.arange(80, dtype=np.float32).reshape(10, 8)
    ranges = rowshard.partition_rows(10, 2)
    _commit_sparse_pass(save_dir, table, ranges, pass_id=0)
    # pass 1: only host 0 writes; host 1 died first
    ckpt.write_sharded_host_trees(
        save_dir, 1, _sparse_snapshot(0, ranges, table, 1), 0)
    tmp = os.path.join(save_dir, ckpt.PASS_FMT % 1) + ckpt.TMP_SUFFIX
    holes = sparse_ckpt.partial_row_holes(tmp)
    assert len(holes) == 1
    assert "params/emb" in holes[0] and "rows [5, 10)" in holes[0]
    assert "host(s) 0" in holes[0]  # who DID land theirs
    assert cli.main(["check-checkpoint", save_dir]) == 1
    out = capsys.readouterr().out
    assert "PARTIAL" in out and "rows [5, 10)" in out, out


def test_partial_row_holes_ignores_column_sharded_dense_params(tmp_path):
    """Derived start/shape ranges must NOT feed the torn-dir row check:
    a column-sharded dense param (both hosts claim all rows) would read
    as a phantom overlap."""
    tmp = str(tmp_path)
    for pid in range(2):
        partial = {"w": {"shape": [4, 8], "dtype": "float32",
                         "shards": [{"file": f"params.shard{pid:05d}.npz",
                                     "key": f"w::{pid}",
                                     "start": [0, pid * 4],
                                     "shape": [4, 4]}]}}
        with open(os.path.join(tmp, f"params.index.{pid:05d}.json"),
                  "w") as f:
            json.dump(partial, f)
    assert sparse_ckpt.partial_row_holes(tmp) == []


# ----------------------------------------------- refusals and flags


def test_cluster_launch_refuses_a_shrink_over_row_budget():
    from paddle_tpu.utils.cluster_launch import _reshard_error

    args = ["--config=c.py", "--sparse_row_budget=50",
            "--sparse_total_rows=120"]
    # 3 hosts hold 120 rows at 40/host; 2 hosts would need 60 > 50
    assert _reshard_error(args, 3, 3) is None or True  # not called at same n
    err = _reshard_error(args, 3, 2)
    assert err and "--sparse_row_budget=50" in err and "needs 60" in err
    assert _reshard_error(["--config=c.py"], 3, 2) is None
    # malformed numbers degrade to "no check", never crash the launcher
    assert _reshard_error(
        ["--sparse_row_budget=x", "--sparse_total_rows=y"], 3, 2) is None


def test_sparse_flags_exist_with_unlimited_defaults():
    from paddle_tpu.utils.flags import _Flags

    f = _Flags(config="c")
    assert f.sparse_row_budget == 0 and f.sparse_total_rows == 0


def test_trainer_refuses_table_over_row_budget(tmp_path, monkeypatch):
    import shutil

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    demo = os.path.join(REPO, "demo", "ctr")
    for fn in os.listdir(demo):
        if fn.endswith(".py"):
            shutil.copy(os.path.join(demo, fn), tmp_path)
    (tmp_path / "train.list").write_text("impressions-seed-1\n")
    monkeypatch.chdir(tmp_path)
    cfg = parse_config("trainer_config.py", "")
    flags = _Flags(config="trainer_config.py", num_passes=1, use_tpu=False,
                   save_dir=str(tmp_path / "out"), sparse_row_budget=100)
    with pytest.raises(ValueError) as ei:
        Trainer(cfg, flags)  # _user_emb has 120 rows > 100/host on 1 host
    assert "_user_emb" in str(ei.value)
    assert "--sparse_row_budget=100" in str(ei.value)
    # the refusal left no tables registered (nothing half-constructed)
    assert sparse_rt.registered_tables() == {}


# ------------------------------------------------- telemetry surface


def test_sparse_stats_accounting_and_pass_record():
    class _Arg:
        def __init__(self, ids):
            self.ids = np.asarray(ids, dtype=np.int32)

    stats = sparse_rt.SparseStats({"emb": 64})  # 16 cols * f32
    plan = [("emb", "ids_layer")]
    stats.note_batch(plan, {"ids_layer": _Arg([1, 1, 2, 3])})
    stats.note_batch(plan, {"ids_layer": _Arg([3, 4])})
    stats.note_batch(plan, {"other": _Arg([9])})  # not in the plan
    rec = stats.pass_record(duration_s=2.0)
    assert rec["rows_touched"] == 6
    assert rec["unique_rows"] == 4  # {1, 2, 3, 4} across the pass
    assert rec["gather_bytes"] == 6 * 64
    assert rec["scatter_bytes"] == (3 + 2) * 64  # per-batch dedupe
    assert rec["sparse_rows_per_sec"] == pytest.approx(3.0)
    assert rec["reshard_events"] == 0
    # pass_record resets per-pass counters; reshard events persist
    stats.note_reshard(2, 1)
    rec = stats.pass_record(duration_s=1.0)
    assert rec["rows_touched"] == 0 and rec["reshard_events"] == 1


def test_sparse_kind_is_schema_required_and_documented():
    from paddle_tpu.observability import metrics as obs

    assert obs.KIND_REQUIRED["sparse"] == ("rows_touched",)
    assert "sparse" in obs.FLUSH_KINDS
    doc = open(os.path.join(REPO, "doc", "observability.md")).read()
    assert "| `sparse` |" in doc  # PTL007's documentation half


def test_analyzer_shows_rows_per_sec_column(tmp_path):
    from paddle_tpu.observability import metrics as obs
    from paddle_tpu.observability.analyze import (
        analyze, load_run, _fmt_table)

    w = obs.MetricsWriter(str(tmp_path), host=0)
    w.emit("pass_end", pass_id=0, step=8, samples=64, AvgCost=0.5,
           pass_time_s=1.0)
    w.emit("sparse", pass_id=0, step=8, rows_touched=4096, unique_rows=100,
           unique_row_rate=0.02, gather_bytes=1, scatter_bytes=1,
           reshard_events=1, sparse_rows_per_sec=4096.0)
    w.flush()
    doc = analyze(load_run(str(tmp_path)))
    row = doc["passes"][0]
    assert row["sparse_rows_per_sec"] == pytest.approx(4096.0)
    assert row["reshard_events"] == 1
    table = _fmt_table(doc)
    assert "rows/s" in table and "4.1e+03" in table.replace("4.10e+03", "4.1e+03")


def test_compare_directions_for_sparse_metrics():
    from paddle_tpu.observability.compare import _higher_is_better

    assert _higher_is_better("sparse_rows_per_sec") is True
    assert _higher_is_better("sparse_gather_share") is False


def test_gather_dominated_step_classifies_memory_bound():
    """Satellite 1's roofline claim: a row gather does ~0 FLOPs/byte,
    far below any known chip's ridge point."""
    from paddle_tpu.observability import costs

    assert costs.classify(0.05, "TPU v4") == "memory-bound"


# --------------------------------------------------- config + fault sites


def test_sparse_embedding_helper_forces_sparse_update(tmp_path, monkeypatch):
    import shutil

    from paddle_tpu.config import parse_config

    demo = os.path.join(REPO, "demo", "ctr")
    for fn in os.listdir(demo):
        if fn.endswith(".py"):
            shutil.copy(os.path.join(demo, fn), tmp_path)
    (tmp_path / "train.list").write_text("impressions-seed-1\n")
    monkeypatch.chdir(tmp_path)
    cfg = parse_config("trainer_config.py", "")
    sparse = {p.name: p.sparse_update
              for p in cfg.model_config.parameters
              if p.name in ("_user_emb", "_ad_emb")}
    assert sparse == {"_user_emb": True, "_ad_emb": True}


def test_sparse_fault_sites_are_documented():
    from paddle_tpu.resilience.faultinject import SITE_DOCS

    for site in ("sparse.gather_fault", "sparse.row_corrupt",
                 "sparse.shard_lost"):
        assert site in SITE_DOCS


def test_shard_lost_fault_leaves_a_named_row_hole(tmp_path):
    """sparse.shard_lost at the write boundary: this host's shards never
    land, and the torn tmp dir names the missing interval."""
    from paddle_tpu.resilience import faultinject

    save_dir = str(tmp_path)
    table = np.arange(80, dtype=np.float32).reshape(10, 8)
    ranges = rowshard.partition_rows(10, 2)
    ckpt.write_sharded_host_trees(
        save_dir, 0, _sparse_snapshot(0, ranges, table), 0)
    faultinject.configure("sparse.shard_lost=raise", 0)
    try:
        with pytest.raises(faultinject.FaultInjected):
            ckpt.write_sharded_host_trees(
                save_dir, 0, _sparse_snapshot(1, ranges, table), 1)
    finally:
        faultinject.configure("", 0)
    tmp = os.path.join(save_dir, ckpt.PASS_FMT % 0) + ckpt.TMP_SUFFIX
    assert not os.path.exists(os.path.join(tmp, "params.shard00001.npz"))
    holes = sparse_ckpt.partial_row_holes(tmp)
    assert holes and "rows [5, 10)" in holes[0], holes


def test_row_corrupt_fault_is_caught_by_the_manifest_verify(tmp_path):
    """sparse.row_corrupt flips a byte AFTER the partial manifest
    digested the healthy shard — the commit's CRC verify must fail."""
    from paddle_tpu.resilience import faultinject

    save_dir = str(tmp_path)
    table = np.arange(80, dtype=np.float32).reshape(10, 8)
    ranges = rowshard.partition_rows(10, 2)
    ckpt.write_sharded_host_trees(
        save_dir, 0, _sparse_snapshot(0, ranges, table), 0)
    faultinject.configure("sparse.row_corrupt=raise", 0)
    try:
        ckpt.write_sharded_host_trees(
            save_dir, 0, _sparse_snapshot(1, ranges, table), 1)
    finally:
        faultinject.configure("", 0)
    path = ckpt.finalize_sharded_pass(
        save_dir, 0, ["params"], {"pass_id": 0, "format_version": 2},
        expected_pids=range(2))
    probs = ckpt.verify_checkpoint(path)
    assert any("crc32" in p and "shard00001" in p for p in probs), probs


# ----------------------------------------------------- dense-path golden


def test_dense_training_unchanged_without_sparse_layers(tmp_path):
    """Acceptance: with no sparse layer configured the dense path emits
    no sparse telemetry, registers no tables, and stays bit-for-bit
    deterministic (two same-seed runs produce identical params)."""
    from demo_utils import setup_demo, train_demo

    setup_demo(tmp_path, "quick_start", ["train-seed-1"], ["test-seed-1"])
    finals = []
    for run in ("a", "b"):
        mdir = str(tmp_path / run)
        trainer, _ = train_demo(
            tmp_path, "trainer_config.lr.py", num_passes=1,
            log_period=1000, metrics_path=mdir)
        assert trainer._sparse_plan == []
        assert trainer._sparse_stats is None
        assert sparse_rt.registered_tables() == {}
        recs = [json.loads(l)
                for l in open(os.path.join(mdir, "metrics.jsonl"))]
        assert not [r for r in recs if r.get("kind") == "sparse"]
        finals.append({k: np.asarray(v)
                       for k, v in trainer.params.items()})
    assert sorted(finals[0]) == sorted(finals[1])
    for k in finals[0]:
        np.testing.assert_array_equal(finals[0][k], finals[1][k], err_msg=k)
