"""CRF / CTC / LambdaRank / selective_fc correctness tests.

Methodology mirrors the reference's test_LinearChainCRF.cpp and
test_LayerGrad.cpp: compare the scan-based implementations against
brute-force enumeration on tiny problems, and analytic gradients against
finite differences.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.graph.argument import Argument, make_seq
from paddle_tpu.layers.base import LayerContext, forward_layer
from paddle_tpu.layers.structured import crf_decode, crf_log_likelihood, ctc_loss
from paddle_tpu.proto import LayerConfig, LayerInputConfig, ModelConfig


def _crf_brute_nll(x, labels, length, param):
    """Enumerate all label sequences of `length` to compute -log P(gold)."""
    C = x.shape[-1]
    a, b, w = param[0], param[1], param[2:]

    def score(seq):
        s = a[seq[0]] + b[seq[length - 1]]
        for t in range(length):
            s += x[t, seq[t]]
        for t in range(1, length):
            s += w[seq[t - 1], seq[t]]
        return s

    log_z = np.logaddexp.reduce(
        [score(seq) for seq in itertools.product(range(C), repeat=length)]
    )
    return log_z - score(tuple(labels[:length]))


def test_crf_nll_matches_bruteforce():
    rng = np.random.RandomState(0)
    B, T, C = 3, 4, 3
    x = rng.randn(B, T, C).astype(np.float32)
    labels = rng.randint(0, C, (B, T)).astype(np.int32)
    lengths = np.array([4, 2, 3], dtype=np.int32)
    param = (0.5 * rng.randn(C + 2, C)).astype(np.float32)

    got = np.asarray(crf_log_likelihood(jnp.asarray(x), jnp.asarray(labels),
                                        jnp.asarray(lengths), jnp.asarray(param)))
    for i in range(B):
        want = _crf_brute_nll(x[i], labels[i], int(lengths[i]), param)
        np.testing.assert_allclose(got[i], want, rtol=1e-4, atol=1e-4)


def test_crf_grad_finite_diff():
    rng = np.random.RandomState(1)
    B, T, C = 2, 3, 3
    x = jnp.asarray(rng.randn(B, T, C).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, C, (B, T)).astype(np.int32))
    lengths = jnp.asarray(np.array([3, 2], dtype=np.int32))
    param = jnp.asarray((0.3 * rng.randn(C + 2, C)).astype(np.float32))

    def loss(param, x):
        return jnp.sum(crf_log_likelihood(x, labels, lengths, param))

    for argnum, arg in ((0, param), (1, x)):
        g = jax.grad(loss, argnums=argnum)(param, x)
        flat = np.asarray(arg).ravel()
        gflat = np.asarray(g).ravel()
        eps = 1e-3
        for k in rng.choice(flat.size, 6, replace=False):
            pert = flat.copy(); pert[k] += eps
            hi = loss(*( (jnp.asarray(pert.reshape(arg.shape)), x) if argnum == 0
                         else (param, jnp.asarray(pert.reshape(arg.shape))) ))
            pert[k] -= 2 * eps
            lo = loss(*( (jnp.asarray(pert.reshape(arg.shape)), x) if argnum == 0
                         else (param, jnp.asarray(pert.reshape(arg.shape))) ))
            fd = (float(hi) - float(lo)) / (2 * eps)
            np.testing.assert_allclose(gflat[k], fd, rtol=2e-2, atol=2e-3)


def test_crf_decode_matches_bruteforce():
    rng = np.random.RandomState(2)
    B, T, C = 3, 4, 3
    x = rng.randn(B, T, C).astype(np.float32)
    lengths = np.array([4, 3, 2], dtype=np.int32)
    param = (0.5 * rng.randn(C + 2, C)).astype(np.float32)
    a, b, w = param[0], param[1], param[2:]

    path = np.asarray(crf_decode(jnp.asarray(x), jnp.asarray(lengths), jnp.asarray(param)))
    for i in range(B):
        L = int(lengths[i])
        best, best_s = None, -np.inf
        for seq in itertools.product(range(C), repeat=L):
            s = a[seq[0]] + b[seq[L - 1]] + sum(x[i, t, seq[t]] for t in range(L))
            s += sum(w[seq[t - 1], seq[t]] for t in range(1, L))
            if s > best_s:
                best, best_s = seq, s
        assert tuple(path[i, :L]) == best, f"seq {i}: {path[i, :L]} != {best}"
        assert (path[i, L:] == 0).all()


def _ctc_brute(log_p, T, labels, blank):
    """-log sum over all alignments collapsing to `labels`."""
    C = log_p.shape[1]

    def collapse(path):
        out, prev = [], None
        for s in path:
            if s != prev and s != blank:
                out.append(s)
            prev = s
        return tuple(out)

    tot = -np.inf
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == tuple(labels):
            tot = np.logaddexp(tot, sum(log_p[t, path[t]] for t in range(T)))
    return -tot


def test_ctc_matches_bruteforce():
    rng = np.random.RandomState(3)
    B, T, C, S = 3, 4, 3, 2  # blank = 2
    logits = rng.randn(B, T, C).astype(np.float32)
    log_p = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    in_lengths = np.array([4, 3, 4], dtype=np.int32)
    labels = np.array([[0, 1], [1, 0], [0, 0]], dtype=np.int32)
    label_lengths = np.array([2, 1, 2], dtype=np.int32)

    got = np.asarray(ctc_loss(jnp.asarray(log_p), jnp.asarray(in_lengths),
                              jnp.asarray(labels), jnp.asarray(label_lengths), blank=C - 1))
    for i in range(B):
        want = _ctc_brute(log_p[i], int(in_lengths[i]),
                          labels[i, : int(label_lengths[i])], C - 1)
        np.testing.assert_allclose(got[i], want, rtol=1e-4, atol=1e-4)


def test_ctc_grad_finite_diff():
    rng = np.random.RandomState(4)
    B, T, C = 2, 4, 3
    logits = jnp.asarray(rng.randn(B, T, C).astype(np.float32))
    in_lengths = jnp.asarray(np.array([4, 3], dtype=np.int32))
    labels = jnp.asarray(np.array([[0, 1], [1, 1]], dtype=np.int32))
    label_lengths = jnp.asarray(np.array([2, 1], dtype=np.int32))

    def loss(logits):
        lp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.sum(ctc_loss(lp, in_lengths, labels, label_lengths, blank=C - 1))

    g = np.asarray(jax.grad(loss)(logits)).ravel()
    flat = np.asarray(logits).ravel()
    eps = 1e-3
    for k in rng.choice(flat.size, 6, replace=False):
        pert = flat.copy(); pert[k] += eps
        hi = float(loss(jnp.asarray(pert.reshape(logits.shape))))
        pert[k] -= 2 * eps
        lo = float(loss(jnp.asarray(pert.reshape(logits.shape))))
        np.testing.assert_allclose(g[k], (hi - lo) / (2 * eps), rtol=2e-2, atol=2e-3)


def _ctx(params, model=None):
    return LayerContext(params=params, model=model or ModelConfig(), pass_type="train",
                        rng=jax.random.PRNGKey(0))


def test_crf_layer_registered_and_runs():
    rng = np.random.RandomState(5)
    B, T, C = 2, 4, 3
    feats = make_seq(rng.randn(B, T, C).astype(np.float32),
                     np.array([4, 2], dtype=np.int32))
    label = make_seq(None, np.array([4, 2], dtype=np.int32),
                     ids=rng.randint(0, C, (B, T)).astype(np.int32))
    cfg = LayerConfig(name="crf", type="crf", size=C,
                      inputs=[LayerInputConfig(input_layer_name="f", input_parameter_name="crf.w"),
                              LayerInputConfig(input_layer_name="l")])
    params = {"crf.w": jnp.asarray(0.3 * rng.randn(C + 2, C).astype(np.float32))}
    out = forward_layer(cfg, [feats, label], _ctx(params))
    assert out.value.shape == (B, 1)
    assert np.isfinite(np.asarray(out.value)).all()

    dcfg = LayerConfig(name="dec", type="crf_decoding", size=C,
                       inputs=[LayerInputConfig(input_layer_name="f", input_parameter_name="crf.w"),
                               LayerInputConfig(input_layer_name="l")])
    dout = forward_layer(dcfg, [feats, label], _ctx(params))
    assert dout.ids.shape == (B, T)
    assert dout.value.shape == (B, T, 1)


def test_lambda_cost_forward_is_neg_ndcg_and_grad_direction():
    # two lists; scores aligned vs anti-aligned with relevance
    s = np.array([[3.0, 2.0, 1.0, 0.0], [0.0, 1.0, 2.0, 3.0]], dtype=np.float32)
    r = np.array([[3.0, 2.0, 1.0, 0.0], [3.0, 2.0, 1.0, 0.0]], dtype=np.float32)
    lengths = np.array([4, 4], dtype=np.int32)
    sc = make_seq(s[..., None], lengths)
    rel = make_seq(r[..., None], lengths)
    cfg = LayerConfig(name="lc", type="lambda_cost", size=1, NDCG_num=4,
                      inputs=[LayerInputConfig(input_layer_name="s"),
                              LayerInputConfig(input_layer_name="r")])
    out = forward_layer(cfg, [sc, rel], _ctx({}))
    vals = np.asarray(out.value)[:, 0]
    np.testing.assert_allclose(vals[0], -1.0, atol=1e-5)  # perfect ranking
    assert vals[1] > vals[0]  # worse ranking → higher cost

    def loss(sv):
        o = forward_layer(cfg, [make_seq(sv, lengths), rel], _ctx({}))
        return jnp.sum(o.value)

    g = np.asarray(jax.grad(loss)(jnp.asarray(s[..., None])))[1, :, 0]
    # anti-aligned list: gradient must push the most relevant item's score up
    assert g[0] < 0 and g[3] > 0


def test_selective_fc_matches_fc_and_masks():
    rng = np.random.RandomState(6)
    B, D, O = 3, 4, 6
    x = Argument(value=jnp.asarray(rng.randn(B, D).astype(np.float32)))
    w = jnp.asarray(rng.randn(D, O).astype(np.float32))
    b = jnp.asarray(rng.randn(O).astype(np.float32))
    params = {"sfc.w": w, "sfc.b": b}
    base = LayerConfig(name="sfc", type="selective_fc", size=O, active_type="",
                       bias_parameter_name="sfc.b",
                       inputs=[LayerInputConfig(input_layer_name="x", input_parameter_name="sfc.w")])
    out = forward_layer(base, [x], _ctx(params))
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(x.value @ w + b), rtol=1e-5)

    sel_ids = jnp.asarray(np.array([[0, 1], [2, 3], [4, 5]], dtype=np.int32))
    cfg2 = LayerConfig(name="sfc", type="selective_fc", size=O, active_type="softmax",
                       bias_parameter_name="sfc.b",
                       inputs=[LayerInputConfig(input_layer_name="x", input_parameter_name="sfc.w"),
                               LayerInputConfig(input_layer_name="sel")])
    out2 = forward_layer(cfg2, [x, Argument(ids=sel_ids)], _ctx(params))
    v = np.asarray(out2.value)
    for i in range(B):
        sel = set(np.asarray(sel_ids)[i].tolist())
        for j in range(O):
            if j in sel:
                assert v[i, j] > 0
            else:
                assert v[i, j] == 0
        np.testing.assert_allclose(v[i].sum(), 1.0, rtol=1e-5)


def test_selective_fc_padded_selection_excludes_column0():
    rng = np.random.RandomState(7)
    B, D, O = 2, 4, 6
    x = Argument(value=jnp.asarray(rng.randn(B, D).astype(np.float32)))
    params = {"sfc.w": jnp.asarray(rng.randn(D, O).astype(np.float32))}
    cfg = LayerConfig(name="sfc", type="selective_fc", size=O, active_type="softmax",
                      inputs=[LayerInputConfig(input_layer_name="x", input_parameter_name="sfc.w"),
                              LayerInputConfig(input_layer_name="sel")])
    # row 0 selects {2,3} (padded with 0s); row 1 selects {0,1,4,5}
    sel = Argument(ids=jnp.asarray(np.array([[2, 3, 0, 0], [0, 1, 4, 5]], np.int32)),
                   seq_lengths=jnp.asarray(np.array([2, 4], np.int32)))
    out = forward_layer(cfg, [x, sel], _ctx(params))
    v = np.asarray(out.value)
    assert v[0, 0] == 0.0 and v[0, 1] == 0.0  # padding must NOT select col 0
    assert v[0, 2] > 0 and v[0, 3] > 0
    assert v[1, 0] > 0  # genuine col-0 selection still works
    np.testing.assert_allclose(v.sum(axis=1), 1.0, rtol=1e-5)


def test_block_expand_extracts_patches_as_sequence(tmp_path):
    """blockexpand (ref BlockExpandLayer.cpp): sliding blocks become a
    sequence of flattened patches; pinned against hand-sliced numpy,
    including when the input arrives via the conv family's NHWC view."""
    import textwrap

    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.argument import Argument

    cfg_file = tmp_path / "conf.py"
    cfg_file.write_text(textwrap.dedent("""
    from paddle.trainer_config_helpers import *
    settings(batch_size=2, learning_rate=0.1)
    img = data_layer('image', size=2*4*4)
    seq = block_expand_layer(input=img, channel=2, block_x=2, block_y=2,
                             stride_x=2, stride_y=2, name='blocks')
    outputs(seq)
    """))
    cfg = parse_config(str(cfg_file))
    gm = GradientMachine(cfg.model_config)
    params = gm.init_params(seed=1)
    rng = np.random.RandomState(0)
    img = rng.rand(2, 2, 4, 4).astype(np.float32)  # [B, C, H, W]
    outputs, _ = gm.forward(
        params, {"image": Argument(value=jnp.asarray(img.reshape(2, -1)))}
    )
    out = outputs["blocks"]
    got = np.asarray(out.value)
    assert got.shape == (2, 4, 2 * 2 * 2)  # 2x2 grid of blocks, C*by*bx wide
    assert np.asarray(out.seq_lengths).tolist() == [4, 4]
    # block (0,0) of sample 0 = channels-major flatten of img[0,:,0:2,0:2]
    np.testing.assert_allclose(got[0, 0], img[0, :, 0:2, 0:2].reshape(-1), rtol=1e-6)
    # block (1,1) = img[:, 2:4, 2:4]
    np.testing.assert_allclose(got[0, 3], img[0, :, 2:4, 2:4].reshape(-1), rtol=1e-6)

    # NHWC-view path: a conv producer publishes into ctx.nhwc; blockexpand
    # over the conv output must equal hand-sliced patches of the conv's
    # own flat output
    cfg_file2 = tmp_path / "conf2.py"
    cfg_file2.write_text(textwrap.dedent("""
    from paddle.trainer_config_helpers import *
    settings(batch_size=2, learning_rate=0.1)
    img = data_layer('image', size=2*4*4)
    c = img_conv_layer(input=img, num_channels=2, num_filters=3, filter_size=3,
                       padding=1, act=ReluActivation(), name='c1')
    seq = block_expand_layer(input=c, channel=3, block_x=2, block_y=2,
                             stride_x=2, stride_y=2, name='blocks')
    outputs(seq)
    """))
    cfg2 = parse_config(str(cfg_file2))
    gm2 = GradientMachine(cfg2.model_config)
    params2 = gm2.init_params(seed=2)
    outputs2, _ = gm2.forward(
        params2, {"image": Argument(value=jnp.asarray(img.reshape(2, -1)))}
    )
    conv_out = np.asarray(outputs2["c1"].value).reshape(2, 3, 4, 4)
    blocks2 = np.asarray(outputs2["blocks"].value)
    assert blocks2.shape == (2, 4, 3 * 2 * 2)
    np.testing.assert_allclose(
        blocks2[1, 0], conv_out[1, :, 0:2, 0:2].reshape(-1), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        blocks2[1, 3], conv_out[1, :, 2:4, 2:4].reshape(-1), rtol=1e-5, atol=1e-6)
