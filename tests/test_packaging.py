"""Packaging surface (pyproject.toml + setup.py + Dockerfile + CI — the
reference's cmake/docker/deb/travis roles, SURVEY §2.11).

A full wheel build is exercised out-of-band (CI `package` job; verified
manually: the wheel carries paddle_tpu, the compat shims under their
reference import names, and the prebuilt native datapath). Here: cheap
invariants that catch drift without paying a build per suite run.
"""

import ast
import os

import pytest

try:  # stdlib from 3.11; the package supports 3.10 (CI matrix)
    import tomllib
except ModuleNotFoundError:  # pragma: no cover - 3.10 only
    tomllib = None

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pyproject():
    if tomllib is None:
        pytest.skip("tomllib unavailable (python < 3.11)")
    with open(os.path.join(ROOT, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)


def test_metadata_and_entry_point():
    meta = _pyproject()
    assert meta["project"]["name"] == "paddle-tpu"
    # console script must point at an importable callable
    target = meta["project"]["scripts"]["paddle"]
    mod, attr = target.split(":")
    m = __import__(mod, fromlist=[attr])
    assert callable(getattr(m, attr))
    # version comes from the single source of truth
    assert meta["tool"]["setuptools"]["dynamic"]["version"]["attr"] == (
        "paddle_tpu.version.__version__"
    )


def test_compat_shim_mapping_matches_tree():
    """setup.py's explicit shim packages must match the compat/ tree —
    a new shim subpackage that isn't listed would silently drop out of
    the wheel."""
    src = open(os.path.join(ROOT, "setup.py")).read()
    listed = {
        n.value
        for n in ast.walk(ast.parse(src))
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
        and (n.value in ("paddle", "py_paddle") or n.value.startswith("paddle."))
    }
    on_disk = set()
    for base, import_name in (("compat/paddle", "paddle"),
                              ("compat/py_paddle", "py_paddle")):
        for dirpath, _dirs, files in os.walk(os.path.join(ROOT, base)):
            if "__init__.py" in files:
                rel = os.path.relpath(dirpath, os.path.join(ROOT, base))
                name = import_name if rel == "." else (
                    import_name + "." + rel.replace(os.sep, ".")
                )
                on_disk.add(name)
    missing = on_disk - listed
    assert not missing, f"compat packages not listed in setup.py: {missing}"


def test_dockerfile_and_ci_reference_real_commands():
    docker = open(os.path.join(ROOT, "Dockerfile")).read()
    assert "pip install" in docker and "ENTRYPOINT" in docker
    ci = open(os.path.join(ROOT, ".github", "workflows", "ci.yml")).read()
    assert "pytest tests/" in ci
    # CLI subcommand used as the container smoke must exist
    from paddle_tpu.cli import main  # noqa: F401
    from paddle_tpu import version
    assert version.__version__
