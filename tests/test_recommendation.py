"""Recommendation demo (sparse embedding CTR) end-to-end smoke test."""

import os
import shutil

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "demo", "recommendation")


def test_recommendation_trains(tmp_path):
    for f in os.listdir(DEMO):
        if f.endswith(".py"):
            shutil.copy(os.path.join(DEMO, f), tmp_path)
    (tmp_path / "train.list").write_text("seed1\n")
    (tmp_path / "test.list").write_text("seed2\n")

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config(str(tmp_path / "trainer_config.py"))
        # embeddings must have been marked sparse_update by the config
        sparse = [p.name for p in cfg.model_config.parameters if p.sparse_update]
        assert "_movie_id_emb" in sparse and "_title_emb" in sparse
        flags = _Flags(config="trainer_config.py", num_passes=3,
                       log_period=100, use_tpu=False)
        trainer = Trainer(cfg, flags)
        trainer.train()
        # planted structure is learnable: train cost must drop well below
        # the 1.0 baseline (squared error of predicting 0)
        from paddle_tpu.data.feeder import create_data_provider
        provider = trainer._provider(for_test=False)
        import numpy as np
        costs = []
        for batch in provider.batches():
            outputs = trainer.test_fwd(trainer.params, batch)
            costs.append(float(trainer.gm.total_cost(outputs)))
        assert np.mean(costs) < 0.5, f"CTR model did not learn: {np.mean(costs)}"
    finally:
        os.chdir(cwd)
