"""Recommendation demo (sparse embedding CTR) end-to-end smoke test."""

import os

import numpy as np

from demo_utils import setup_demo, train_demo


def test_recommendation_trains(tmp_path):
    setup_demo(tmp_path, "recommendation", ["seed1"], ["seed2"])
    trainer, _ = train_demo(tmp_path, "trainer_config.py", num_passes=3,
                            log_period=100)
    # embeddings must have been marked sparse_update by the config
    sparse = [p.name for p in trainer.config.model_config.parameters
              if p.sparse_update]
    assert "_movie_id_emb" in sparse and "_title_emb" in sparse
    # planted structure is learnable: train cost must drop well below
    # the 1.0 baseline (squared error of predicting 0)
    cwd = os.getcwd()
    os.chdir(tmp_path)  # provider reads the list files relatively
    try:
        provider = trainer._provider(for_test=False)
        costs = []
        for batch in provider.batches():
            outputs = trainer.test_fwd(trainer.params, batch)
            costs.append(float(trainer.gm.total_cost(outputs)))
    finally:
        os.chdir(cwd)
    assert np.mean(costs) < 0.5, f"CTR model did not learn: {np.mean(costs)}"
