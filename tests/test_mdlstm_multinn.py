"""MDLstm (2-D multi-dimensional LSTM) and multi_nn sub-networks.

MDLstm mirrors the reference's test_LayerGrad MDLstmLayer test
(/root/reference/paddle/gserver/tests/test_LayerGrad.cpp:962): all four
direction combinations checked against an independent numpy
re-implementation of the CoordIterator math (MDLstmLayer.cpp:81-473).
multi_nn mirrors MultiNetwork (gradientmachines/MultiNetwork.h:25):
independent sub-networks trained jointly.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.graph import GradientMachine, make_dense, make_ids
from paddle_tpu.graph.argument import Argument


def parse_str(src: str):
    import os
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(src))
        path = f.name
    try:
        return parse_config(path)
    finally:
        os.unlink(path)


def np_mdlstm(x, w, bias, dirs, nb):
    """Pure-numpy 2-D MDLSTM following MDLstmLayer.cpp exactly:
    shared recurrent weight, summed predecessor contributions, per-dim
    forget gates, peepholes [checkIg | checkFg x2 | checkOg]."""
    B, H, W_, _ = x.shape
    gb = bias[: 5 * nb]
    cig = bias[5 * nb : 6 * nb]
    cfg = bias[6 * nb : 8 * nb].reshape(2, nb)
    cog = bias[8 * nb : 9 * nb]
    out = np.zeros((B, H, W_, nb))
    st = np.zeros((B, H, W_, nb))
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    rows = range(H) if dirs[0] else range(H - 1, -1, -1)
    cols = range(W_) if dirs[1] else range(W_ - 1, -1, -1)
    for i in rows:
        for j in cols:
            pi = i - (1 if dirs[0] else -1)
            pj = j - (1 if dirs[1] else -1)
            top_o = out[:, pi, j] if 0 <= pi < H else np.zeros((B, nb))
            top_s = st[:, pi, j] if 0 <= pi < H else np.zeros((B, nb))
            left_o = out[:, i, pj] if 0 <= pj < W_ else np.zeros((B, nb))
            left_s = st[:, i, pj] if 0 <= pj < W_ else np.zeros((B, nb))
            g = x[:, i, j] + gb + (top_o + left_o) @ w
            inn, ig, fg, og = (
                g[:, :nb],
                g[:, nb : 2 * nb],
                g[:, 2 * nb : 4 * nb],
                g[:, 4 * nb :],
            )
            iga = sig(ig + (top_s + left_s) * cig)
            fga = sig(fg + np.concatenate([top_s * cfg[0], left_s * cfg[1]], -1))
            s = fga[:, :nb] * top_s + fga[:, nb:] * left_s + np.tanh(inn) * iga
            oga = sig(og + s * cog)
            out[:, i, j] = oga * sig(s)
            st[:, i, j] = s
    return out


MDLSTM_CFG = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=20)
out = mdlstm_layer(input=x, size=4, directions={dirs}, name="md",
                   param_attr=ParamAttr(name="w_md"),
                   bias_attr=ParamAttr(name="b_md"))
outputs(out)
"""


@pytest.mark.parametrize("dirs", [(True, True), (True, False), (False, True), (False, False)])
def test_mdlstm_matches_numpy(dirs):
    B, H, W_, nb = 2, 3, 4, 4
    rng = np.random.RandomState(0)
    x = rng.randn(B, H, W_, 5 * nb).astype(np.float32) * 0.5
    tc = parse_str(MDLSTM_CFG.format(dirs=list(dirs)))
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=2)
    batch = {
        "x": Argument(
            value=jnp.asarray(x),
            seq_lengths=jnp.full((B,), H, jnp.int32),
            sub_seq_lengths=jnp.full((B, H), W_, jnp.int32),
        )
    }
    out, _ = gm.forward(params, batch, "test")
    got = np.asarray(out["md"].value)
    w = np.asarray(params["w_md"]).reshape(nb, 5 * nb)
    b = np.asarray(params["b_md"]).reshape(-1)
    want = np_mdlstm(x.astype(np.float64), w, b, dirs, nb)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_mdlstm_gradients_flow():
    B, H, W_, nb = 2, 3, 3, 4
    rng = np.random.RandomState(1)
    x = rng.randn(B, H, W_, 5 * nb).astype(np.float32) * 0.5
    tc = parse_str(MDLSTM_CFG.format(dirs=[True, True]))
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=3)
    batch = {
        "x": Argument(
            value=jnp.asarray(x),
            seq_lengths=jnp.full((B,), H, jnp.int32),
            sub_seq_lengths=jnp.full((B, H), W_, jnp.int32),
        )
    }

    def loss(p):
        outs, _ = gm.forward(p, batch, "train")
        return jnp.sum(outs["md"].value ** 2)

    grads = jax.grad(loss)(params)
    for k in ("w_md", "b_md"):
        g = np.asarray(grads[k])
        assert np.isfinite(g).all() and np.abs(g).sum() > 0, k


MULTI_NN = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=8, learning_rate=0.1)
with sub_network("task_a"):
    a = data_layer(name="a_in", size=10)
    a_out = fc_layer(input=a, size=2, act=SoftmaxActivation(), name="a_out")
    a_lab = data_layer(name="a_lab", size=2)
    outputs(classification_cost(input=a_out, label=a_lab, name="a_cost"))
with sub_network("task_b"):
    b = data_layer(name="b_in", size=6)
    b_out = fc_layer(input=b, size=1, act=LinearActivation(), name="b_out")
    b_lab = data_layer(name="b_lab", size=1)
    outputs(regression_cost(input=b_out, label=b_lab, name="b_cost"))
"""


def test_multi_nn_trains_both_subnets():
    tc = parse_str(MULTI_NN)
    assert tc.model_config.type == "multi_nn"
    subs = {s.name for s in tc.model_config.sub_models}
    assert {"root", "task_a", "task_b"} <= subs
    for slot in ("a_in", "a_lab", "b_in", "b_lab"):
        assert slot in tc.model_config.input_layer_names

    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=4)
    rng = np.random.RandomState(5)
    B = 8
    batch = {
        "a_in": make_dense(rng.randn(B, 10).astype(np.float32)),
        "a_lab": make_ids(rng.randint(0, 2, (B,)).astype(np.int32)),
        "b_in": make_dense(rng.randn(B, 6).astype(np.float32)),
        "b_lab": make_dense(rng.randn(B, 1).astype(np.float32)),
    }
    loss, grads, outputs, _ = jax.jit(gm.grad_fn())(params, batch, None)
    assert np.isfinite(float(loss))
    # the joint loss is the sum of both tasks' costs
    ce = float(jnp.mean(outputs["a_cost"].value[:, 0]))
    mse = float(jnp.mean(outputs["b_cost"].value[:, 0]))
    np.testing.assert_allclose(float(loss), ce + mse, rtol=1e-6)
    # both sub-networks receive gradients
    for pname in ("_a_out.w0", "_b_out.w0"):
        g = np.asarray(grads[pname])
        assert np.isfinite(g).all() and np.abs(g).sum() > 0, pname


def test_mdlstm_ragged_grid_matches_per_sample():
    """Per-sample grid sizes: padded cells act as out-of-grid (zeros), so a
    ragged sample matches running its exact-size grid alone — in every
    direction combination (flips must not move padding into the scan
    path)."""
    nb = 4
    rng = np.random.RandomState(7)
    H, W_ = 4, 5
    h1, w1 = 2, 3  # sample 1's real grid
    x = rng.randn(2, H, W_, 5 * nb).astype(np.float32) * 0.5
    sub_lens = np.array([[W_] * H, [w1, w1, 0, 0]], np.int32)
    for dirs in [(True, True), (False, True), (True, False), (False, False)]:
        tc = parse_str(MDLSTM_CFG.format(dirs=list(dirs)))
        gm = GradientMachine(tc.model_config)
        params = gm.init_params(seed=2)
        batch = {
            "x": Argument(
                value=jnp.asarray(x),
                seq_lengths=jnp.asarray([H, h1], np.int32),
                sub_seq_lengths=jnp.asarray(sub_lens),
            )
        }
        out, _ = gm.forward(params, batch, "test")
        got = np.asarray(out["md"].value)
        w = np.asarray(params["w_md"]).reshape(nb, 5 * nb)
        b = np.asarray(params["b_md"]).reshape(-1)
        # sample 1 computed alone on its exact h1 x w1 grid
        want1 = np_mdlstm(
            x[1:2, :h1, :w1].astype(np.float64), w, b, dirs, nb
        )
        np.testing.assert_allclose(
            got[1, :h1, :w1], want1[0], rtol=1e-4, atol=1e-5,
            err_msg=f"dirs={dirs}",
        )
