"""quick_start demo end-to-end: all four configs parse and train.

Mirrors the reference's first tutorial workload
(/root/reference/demo/quick_start/) — the SURVEY.md Milestone A slice —
on the synthetic sentiment corpus. The LR config additionally asserts the
planted signal is learned (cross-entropy well below chance).
"""

import os

import numpy as np
import pytest

from demo_utils import setup_demo, train_demo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "demo", "quick_start")


def _setup(tmp_path):
    setup_demo(tmp_path, "quick_start", ["train-seed-1"], ["test-seed-1"])


def _train(tmp_path, cfg_name, num_passes=3, dtype=None):
    return train_demo(tmp_path, cfg_name, num_passes=num_passes, dtype=dtype,
                      log_period=100, run_final_test=True)


def test_lr_learns(tmp_path):
    _setup(tmp_path)
    trainer, results = _train(tmp_path, "trainer_config.lr.py", num_passes=12)
    # cross-entropy well below ln(2)≈0.693 chance level on held-out data
    assert results["cost"] < 0.4, f"LR did not learn: {results}"


@pytest.mark.parametrize("cfg", ["trainer_config.emb.py",
                                 "trainer_config.cnn.py",
                                 "trainer_config.lstm.py"])
def test_configs_train(tmp_path, cfg):
    _setup(tmp_path)
    trainer, results = _train(tmp_path, cfg, num_passes=1)
    assert np.isfinite(results["cost"])


def test_lr_bf16_parity(tmp_path):
    """quick_start trains under bfloat16 mixed precision with held-out
    cost tracking the f32 run (the VERDICT bf16 done-criterion names
    quick_start explicitly)."""
    _setup(tmp_path)
    _, r32 = _train(tmp_path, "trainer_config.lr.py", num_passes=12)
    _, r16 = _train(tmp_path, "trainer_config.lr.py", num_passes=12,
                    dtype="bfloat16")
    assert r16["cost"] < 0.4, f"bf16 LR did not learn: {r16}"
    # measured: 0.39185 (bf16) vs 0.39172 (f32) — near-exact tracking
    np.testing.assert_allclose(r16["cost"], r32["cost"], rtol=0.05)


def test_predict_config_parses(tmp_path):
    _setup(tmp_path)
    from paddle_tpu.config import parse_config

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("trainer_config.lr.py", "is_predict=1")
        assert any(l.type == "maxid" for l in cfg.model_config.layers)
    finally:
        os.chdir(cwd)
