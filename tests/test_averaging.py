"""Sliding-window parameter averaging + the non-finite-loss trap.

Reference: AverageOptimizer (/root/reference/paddle/parameter/
AverageOptimizer.h:24,99) keeps a bounded window — average = (SUM1+SUM2+
SUM3)/(numAccumulates+oldNumAccumulates), shifting the window once it
holds min(max_average_window, numUpdates*average_window) batches. The
FP trap mirrors TrainerMain.cpp:96 (feenableexcept): NaN/Inf aborts.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.optimizer import Updater
from paddle_tpu.proto import ModelConfig, OptimizationConfig, ParameterConfig


def _updater(average_window=1.0, max_average_window=3):
    m = ModelConfig()
    m.parameters.append(ParameterConfig(name="w", size=4, dims=[4]))
    opt = OptimizationConfig(
        learning_rate=0.1, learning_method="sgd",
        learning_rate_schedule="constant", batch_size=2,
        average_window=average_window, max_average_window=max_average_window,
    )
    return Updater(opt, m)


def test_window_average_matches_reference_semantics():
    upd = _updater(average_window=1.0, max_average_window=3)
    params = {"w": jnp.zeros((4,), jnp.float32)}
    state = upd.init_state(params)
    history = []
    for i in range(5):
        g = jnp.full((4,), float(i + 1), jnp.float32)
        params, state = upd(params, {"w": g}, state, 2.0)
        history.append(np.asarray(params["w"]).copy())
    # steps 1..3 fill the window (limit = min(3, t*1.0) with min_window=3),
    # so at t=3 it shifts: old = w1+w2+w3, count 3; t=4,5 accumulate anew.
    want = (history[0] + history[1] + history[2] + history[3] + history[4]) / 5.0
    got = np.asarray(upd.averaged_params(params, state)["w"])
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert float(state.avg_old_count) == 3.0
    assert float(state.avg_count) == 2.0
    np.testing.assert_allclose(
        np.asarray(state.avg_old_sum["w"]),
        history[0] + history[1] + history[2],
        rtol=1e-6,
    )


def test_cumulative_before_first_shift():
    """Until the window first closes, the average is the plain cumulative
    mean (old bucket empty)."""
    upd = _updater(average_window=1.0, max_average_window=100)
    params = {"w": jnp.zeros((4,), jnp.float32)}
    state = upd.init_state(params)
    history = []
    for i in range(4):
        params, state = upd(params, {"w": jnp.ones((4,), jnp.float32)}, state, 2.0)
        history.append(np.asarray(params["w"]).copy())
    got = np.asarray(upd.averaged_params(params, state)["w"])
    np.testing.assert_allclose(got, np.mean(history, axis=0), rtol=1e-6)


def test_nan_loss_aborts_training(tmp_path, monkeypatch):
    import os
    import sys
    import textwrap

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    provider_dir = os.path.join(os.path.dirname(__file__), "providers")
    sys.path.insert(0, provider_dir)
    monkeypatch.setattr(FLAGS, "save_dir", "")
    monkeypatch.setattr(FLAGS, "mesh_shape", "")
    try:
        train_list = tmp_path / "train.list"
        train_list.write_text("1\n")
        src = textwrap.dedent(f"""
        from paddle_tpu.trainer_config_helpers import *
        define_py_data_sources2(train_list={str(train_list)!r}, test_list=None,
                                module="synthetic_bow", obj="process")
        settings(batch_size=32, learning_rate=0.05)
        data = data_layer(name="word", size=100)
        output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=2)
        outputs(classification_cost(input=output, label=label))
        """)
        cfg_path = tmp_path / "cfg.py"
        cfg_path.write_text(src)
        trainer = Trainer(parse_config(str(cfg_path)))
        # force a poisoned step: the trap must abort, not train through it
        trainer._train_step_fn = lambda p, o, b, r, n: (p, o, jnp.nan, {})
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            trainer.train(num_passes=1)
    finally:
        sys.path.remove(provider_dir)
