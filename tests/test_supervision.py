"""Run supervision (doc/resilience.md "Supervision & divergence
recovery"): the crash-loop-aware auto-restart supervisor behind `paddle
supervise`, the trainer's --nonfinite_policy divergence recovery
(skip/rollback), the unified NonFiniteLossError type, and the barrier
skew-summary guard the supervisor's crash report consumes.

The chaos tests are fast and deterministic (seeded injection at the new
``trainer.crash`` / ``trainer.nonfinite`` sites), so they ride along
with tier-1 under the ``chaos`` marker.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle_tpu.resilience import NonFiniteLossError, faultinject
from paddle_tpu.resilience.supervisor import (
    CRASH_REPORT,
    EXIT_CRASH_LOOP,
    Supervisor,
    probe_restorable,
)
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.utils.flags import _Flags

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

SUBPROC_ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    PALLAS_AXON_POOL_IPS="",
    PYTHONPATH=f"{REPO}:{os.path.join(REPO, 'compat')}:{PROVIDERS}",
)


@pytest.fixture(autouse=True)
def _clear_faults():
    """Fault plans are process-global; never leak one across tests."""
    yield
    faultinject.configure("")


def _no_sleep(_s):
    pass


# ---------------------------------------------------------- supervisor


def _stub_supervisor(tmp_path, script, flags=None, **kw):
    flags = flags or _Flags(
        supervise_dir=str(tmp_path / "sup"),
        restart_budget=5,
        crash_loop_threshold=3,
    )
    return Supervisor(
        ["--config=unused.py"], flags,
        child_cmd=[sys.executable, "-c", script, str(tmp_path / "counter")],
        sleep=_no_sleep, **kw,
    )


def test_supervisor_restarts_with_backoff_until_success(tmp_path):
    # child fails twice, then succeeds — the supervisor must restart it
    # (bounded) and report overall success
    script = textwrap.dedent("""
        import os, sys
        c = sys.argv[1]
        n = int(open(c).read()) if os.path.exists(c) else 0
        open(c, "w").write(str(n + 1))
        print("attempt", n)
        sys.exit(0 if n >= 2 else 1)
    """)
    sup = _stub_supervisor(tmp_path, script)
    assert sup.run() == 0
    assert [a["exit_code"] for a in sup.attempts] == [1, 1, 0]
    # per-attempt child output was captured
    for a in sup.attempts:
        assert os.path.exists(a["log"])
    assert "attempt 0" in open(sup.attempts[0]["log"]).read()
    # no crash report on a run that eventually succeeded
    assert not os.path.exists(os.path.join(sup.dir, CRASH_REPORT))


def test_supervisor_crash_loop_stops_with_report(tmp_path):
    # a child that dies identically every launch with zero checkpoint
    # progress is poison: restarting replays it, so the supervisor must
    # stop at the threshold and write a diagnosable JSON crash report
    script = (
        "import sys\n"
        "print('BarrierStat: step mean/host=[...] slowest=host1')\n"
        "print('boom: poisoned batch')\n"
        "sys.exit(5)\n"
    )
    sup = _stub_supervisor(tmp_path, script)
    assert sup.run() == EXIT_CRASH_LOOP
    assert len(sup.attempts) == 3  # crash_loop_threshold
    report_path = os.path.join(sup.dir, CRASH_REPORT)
    report = json.load(open(report_path))
    assert report["reason"] == "crash_loop"
    assert [a["exit_code"] for a in report["attempts"]] == [5, 5, 5]
    assert "boom: poisoned batch" in report["log_tail"]
    # slowest-host attribution (utils/barrier skew line) is surfaced
    assert "slowest=host1" in report["step_time_skew"]


def test_supervisor_budget_exhausted_when_progressing(tmp_path):
    # the child keeps making checkpoint progress (the probe sees a new
    # restorable pass each launch) so it is NOT a crash loop — but the
    # restart budget still bounds the supervisor
    progress = iter(range(100))
    script = "import sys; sys.exit(4)"
    flags = _Flags(
        supervise_dir=str(tmp_path / "sup"),
        restart_budget=2,
        crash_loop_threshold=3,
    )
    sup = _stub_supervisor(
        tmp_path, script, flags=flags,
        probe=lambda: f"pass-{next(progress):05d}",
    )
    assert sup.run() == 4
    assert len(sup.attempts) == 3  # initial + 2 restarts
    report = json.load(open(os.path.join(sup.dir, CRASH_REPORT)))
    assert report["reason"] == "restart_budget_exhausted"


def test_supervisor_forwards_sigterm_and_stops(tmp_path):
    # preemption: SIGTERM to the supervisor reaches the child and the
    # supervisor does NOT restart it
    script = "import time; time.sleep(60)"
    sup = _stub_supervisor(tmp_path, script)
    threading.Timer(
        1.0, lambda: os.kill(os.getpid(), signal.SIGTERM)
    ).start()
    t0 = time.monotonic()
    rc = sup.run()
    assert time.monotonic() - t0 < 30  # child died at the signal, not 60s
    assert rc != 0
    assert len(sup.attempts) == 1  # no restart after a forwarded SIGTERM


def test_supervisor_dry_run_prints_plan(tmp_path, capsys):
    flags = _Flags(dry_run=True, restart_budget=2,
                   supervise_dir=str(tmp_path / "sup"))
    sup = Supervisor(["--config=cfg.py", "--save_dir=out"], flags)
    assert sup.run() == 0
    out = capsys.readouterr().out
    assert "--init_model_path=auto" in out       # the restart injection
    assert "restart_budget=2" in out
    assert CRASH_REPORT in out
    assert not os.path.exists(sup.dir)           # nothing was launched
    assert sup.attempts == []


def test_restart_cmd_replaces_user_init_model_path():
    sup = Supervisor(
        ["--config=c.py", "--init_model_path=/pretrained", "--seed=7"],
        _Flags(),
    )
    first = sup.child_cmd(restart=False)
    again = sup.child_cmd(restart=True)
    assert "--init_model_path=/pretrained" in first
    assert "--init_model_path=/pretrained" not in again
    assert again[-1] == "--init_model_path=auto"
    assert "--seed=7" in again
    # space-separated value form is stripped as a pair
    sup2 = Supervisor(["--init_model_path", "/x", "--seed=7"], _Flags())
    again2 = sup2.child_cmd(restart=True)
    assert "/x" not in again2 and "--seed=7" in again2


def test_supervisor_import_is_jax_free():
    """The supervisor must stay usable when the accelerator runtime is
    exactly what keeps crashing the child — importing it (and the probe
    it uses) may never pull in jax."""
    code = (
        "import sys\n"
        "from paddle_tpu.resilience.supervisor import probe_restorable\n"
        "sys.exit(1 if 'jax' in sys.modules else 0)\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], env=SUBPROC_ENV, capture_output=True,
        text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr


def test_probe_restorable_is_manifest_aware(tmp_path):
    import jax.numpy as jnp

    d = str(tmp_path)
    assert probe_restorable(d) is None
    params = {"w": jnp.ones((2, 2))}
    ckpt.save_checkpoint(d, 0, params)
    ckpt.save_checkpoint(d, 1, params)
    assert probe_restorable(d) == os.path.join(d, "pass-00001")
    # a torn newest checkpoint must not count as progress
    data = open(os.path.join(d, "pass-00001", "params.npz"), "rb").read()
    open(os.path.join(d, "pass-00001", "params.npz"), "wb").write(data[:10])
    assert probe_restorable(d) == os.path.join(d, "pass-00000")
    # a bare tmp dir is not restorable state
    os.makedirs(os.path.join(d, "pass-00009.tmp"))
    assert probe_restorable(d) == os.path.join(d, "pass-00000")


# --------------------------------------------------- barrier skew guard


def test_summarize_host_stats_guards_idle_hosts():
    """A host with no recorded steps joins the allgather with NaN
    sentinels; attribution must exclude it (not let zeros fake the
    fastest host) while still calling it out."""
    from paddle_tpu.utils.barrier import summarize_host_stats

    stats = np.array([[0.010, 0.012], [np.nan, np.nan], [0.030, 0.040]])
    line = summarize_host_stats(stats)
    assert "slowest=host2" in line
    assert "skew=20.0ms" in line
    assert "no steps recorded on host(s) 1" in line
    assert summarize_host_stats(np.full((3, 2), np.nan)) is None


def test_skew_summary_single_process_returns_none():
    from paddle_tpu.utils.barrier import step_time_skew_summary

    assert step_time_skew_summary([]) is None
    assert step_time_skew_summary([0.01, 0.02]) is None


# -------------------------------------------- divergence policy (unit)


@pytest.fixture
def bow_cfg(tmp_path):
    """Fresh parsed config per call (rollback mutates opt_config)."""
    sys.path.insert(0, PROVIDERS)
    (tmp_path / "train.list").write_text("1\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={str(tmp_path / 'train.list')!r},
                            test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02,
             learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    (tmp_path / "cfg.py").write_text(src)

    def make():
        from paddle_tpu.config import parse_config

        return parse_config(str(tmp_path / "cfg.py"))

    yield make
    sys.path.remove(PROVIDERS)


@pytest.mark.chaos
def test_nonfinite_skip_finishes_where_abort_dies(tmp_path, bow_cfg):
    """The acceptance scenario: the same injected divergence kills an
    abort run and is survived by --nonfinite_policy=skip."""
    from paddle_tpu.trainer import Trainer

    faultinject.configure("trainer.nonfinite=raise@3")
    t = Trainer(bow_cfg(), _Flags(log_period=0))
    with pytest.raises(NonFiniteLossError) as ei:
        t.train(num_passes=1)
    assert isinstance(ei.value, FloatingPointError)  # back-compat contract
    assert ei.value.pass_id == 0 and ei.value.batch_id == 2

    faultinject.configure("trainer.nonfinite=raise@3")
    t2 = Trainer(
        bow_cfg(),
        _Flags(log_period=0, nonfinite_policy="skip", max_nonfinite_steps=2),
    )
    t2.train(num_passes=1)  # completes
    assert t2._nf_count == 1
    # 400 samples / batch 64 = 7 batches; the poisoned one was discarded
    assert int(t2.opt_state.step) == 6


@pytest.mark.chaos
def test_nonfinite_skip_budget_exhausts_loudly(tmp_path, bow_cfg):
    from paddle_tpu.trainer import Trainer

    faultinject.configure("trainer.nonfinite=raise@3+")  # every batch >= 3
    t = Trainer(
        bow_cfg(),
        _Flags(log_period=0, nonfinite_policy="skip", max_nonfinite_steps=2),
    )
    with pytest.raises(NonFiniteLossError, match="max_nonfinite_steps"):
        t.train(num_passes=1)
    assert t._nf_count == 3  # two discarded, the third raised


@pytest.mark.chaos
def test_nonfinite_rollback_restores_and_tempers_lr(tmp_path, bow_cfg):
    """rollback: restore the newest verified checkpoint, scale the lr,
    fast-forward the re-run pass past the poison region, finish."""
    from paddle_tpu.trainer import Trainer

    save_dir = str(tmp_path / "out_rb")
    cfg = bow_cfg()
    # hit 10 = pass 1, batch 2 (7 batches per pass)
    faultinject.configure("trainer.nonfinite=raise@10")
    t = Trainer(
        cfg,
        _Flags(save_dir=save_dir, log_period=0,
               nonfinite_policy="rollback", rollback_lr_scale=0.5),
    )
    t.train(num_passes=2)
    assert t.rollbacks == 1
    assert cfg.opt_config.learning_rate == pytest.approx(0.02 * 0.5)
    assert ckpt.latest_pass(save_dir) == 1
    # pass 0: 7 steps; pass 1 diverged at batch 2 (2 steps, then rolled
    # back to the pass-0 state); re-run pass 1 fast-forwarded past
    # batches 0..2 and trained the remaining 4
    assert int(t.opt_state.step) == 7 + 4


@pytest.mark.chaos
def test_rollback_without_checkpoint_raises_typed(tmp_path, bow_cfg):
    from paddle_tpu.trainer import Trainer

    faultinject.configure("trainer.nonfinite=raise@2")
    t = Trainer(
        bow_cfg(),
        _Flags(save_dir=str(tmp_path / "empty_rb"), log_period=0,
               nonfinite_policy="rollback"),
    )
    with pytest.raises(NonFiniteLossError, match="no restorable checkpoint"):
        t.train(num_passes=1)


def test_whole_data_cost_raises_same_type(tmp_path, bow_cfg, monkeypatch):
    """Satellite: the whole-data cost check and the per-step check raise
    the SAME typed error, so supervisors classify divergence uniformly."""
    from paddle_tpu.trainer import Trainer

    cfg = bow_cfg()
    cfg.opt_config.algorithm = "owlqn"
    cfg.opt_config.learning_method = "lbfgs"
    t = Trainer(cfg, _Flags(log_period=0))
    monkeypatch.setattr(
        t, "_full_data_sweep", lambda *a, **k: (float("nan"), {}, 1)
    )
    with pytest.raises(NonFiniteLossError, match="whole-data"):
        t.train(num_passes=1)


def test_bad_policy_value_rejected(tmp_path, bow_cfg):
    from paddle_tpu.trainer import Trainer

    with pytest.raises(ValueError, match="nonfinite_policy"):
        Trainer(bow_cfg(), _Flags(nonfinite_policy="explode"))


# --------------------------------------------- end-to-end (subprocess)


def _write_train_cfg(tmp_path):
    (tmp_path / "train.list").write_text("1\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={str(tmp_path / 'train.list')!r},
                            test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02,
             learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg = tmp_path / "cfg.py"
    cfg.write_text(src)
    return str(cfg)


@pytest.mark.chaos
def test_supervise_e2e_restart_resumes_and_completes(tmp_path):
    """The acceptance scenario end-to-end with REAL process deaths:
    `paddle supervise` survives an injected `trainer.crash` (os._exit
    mid-pass-2), restarts with backoff, resumes from the PR 1
    manifest-verified checkpoint, and the run completes."""
    cfg = _write_train_cfg(tmp_path)
    save_dir = str(tmp_path / "out")
    sup_dir = str(tmp_path / "sup")
    # 7 batches/pass: hit 18 = pass 2, batch 3. Run 1 saves passes 0-1
    # then dies; run 2 resumes at pass 2 (hits restart at 1) and finishes.
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "supervise",
         f"--config={cfg}", f"--save_dir={save_dir}",
         f"--supervise_dir={sup_dir}", "--num_passes=3", "--log_period=0",
         "--restart_base_delay=0.01",
         "--fault_spec=trainer.crash=exit:9@18"],
        capture_output=True, text=True, timeout=420, env=SUBPROC_ENV,
        cwd=str(tmp_path),
    )
    assert r.returncode == 0, (r.returncode, r.stderr[-3000:])
    # the run got all the way to the end across the restart
    assert os.path.isdir(os.path.join(save_dir, "pass-00002"))
    logs = sorted(
        n for n in os.listdir(sup_dir) if n.startswith("attempt-")
    )
    assert logs == ["attempt-000.log", "attempt-001.log"]
    # the restart actually resumed from the verified checkpoint
    assert "resumed pass 1" in open(os.path.join(sup_dir, logs[1])).read()
    assert not os.path.exists(os.path.join(sup_dir, CRASH_REPORT))


@pytest.mark.chaos
def test_supervise_e2e_crash_loop_report(tmp_path):
    """Deterministic crash loop: the child dies at batch 3 of pass 0
    every launch, never checkpointing — the supervisor must stop within
    the threshold and emit the JSON crash report."""
    cfg = _write_train_cfg(tmp_path)
    save_dir = str(tmp_path / "out")
    sup_dir = str(tmp_path / "sup")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "supervise",
         f"--config={cfg}", f"--save_dir={save_dir}",
         f"--supervise_dir={sup_dir}", "--num_passes=3", "--log_period=0",
         "--restart_base_delay=0.01", "--crash_loop_threshold=2",
         "--fault_spec=trainer.crash=exit:9@3"],
        capture_output=True, text=True, timeout=420, env=SUBPROC_ENV,
        cwd=str(tmp_path),
    )
    assert r.returncode == EXIT_CRASH_LOOP, (r.returncode, r.stderr[-3000:])
    report = json.load(open(os.path.join(sup_dir, CRASH_REPORT)))
    assert report["reason"] == "crash_loop"
    assert [a["exit_code"] for a in report["attempts"]] == [9, 9]
    assert all(a["restored_from"] is None for a in report["attempts"])
    assert report["log_tail"]  # the child log tail is attached
    # the report carries the child's structured telemetry tail (last N
    # metrics.jsonl records per host), not just grepped log text: the
    # trainer wrote run_start into <save_dir>/metrics.jsonl and the
    # fault-injection layer flushed its own firing before os._exit
    tail = report["metrics_tail"]["0"]
    kinds = [rec["kind"] for rec in tail]
    assert "run_start" in kinds and "fault" in kinds, kinds
    fault = next(rec for rec in tail if rec["kind"] == "fault")
    assert fault["site"] == "trainer.crash" and fault["action"] == "exit"
