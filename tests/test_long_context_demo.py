"""demo/long_context: causal LM with ring attention over a data x seq
mesh — the user-facing long-context recipe (doc/distributed.md). Trains
on the planted-bigram synthetic corpus; the sharded run must compile,
train, and beat chance (the structure bounds the best next-token error
at 7/8 = 0.875)."""

import numpy as np

from demo_utils import setup_demo, train_demo


def test_single_device_trains(tmp_path):
    setup_demo(tmp_path, "long_context", ["seed-1"], ["seed-2"])
    trainer, _ = train_demo(
        tmp_path, "trainer_config.py", num_passes=6,
        config_arg_str="seq_len=128,vocab=200,batch_size=16")
    # planted bigram structure: successors live in an 8-token window, so
    # the best achievable next-token error is 7/8 = 0.875 (measured run:
    # err 0.877 by pass 9). Six passes must show clear learning: held-out
    # cost strictly decreasing and error well off the ~0.995 of chance.
    costs = [r["cost"] for _, r in trainer.test_history]
    assert all(a > b for a, b in zip(costs, costs[1:])), costs
    err = trainer.test_history[-1][1][
        "__cost_0__.classification_error.classification_error"]
    assert err < 0.94, (err, costs)


def test_seq_parallel_mesh_trains(tmp_path):
    """512-token contexts sharded over seq=4 (ring attention) x data=2 —
    compiles and trains on the virtual 8-device CPU mesh."""
    setup_demo(tmp_path, "long_context", ["seed-1"], ["seed-2"])
    trainer, results = train_demo(
        tmp_path, "trainer_config.py", num_passes=1, run_final_test=True,
        config_arg_str="mesh_data=2,mesh_seq=4,seq_len=512,"
                       "batch_size=4,vocab=200")
    assert trainer.config.opt_config.mesh_shape == "data=2,seq=4"
    # one sharded pass: the ring-attention graph compiled, executed and
    # produced a sane (finite, near-start) held-out cost for T=512
    assert np.isfinite(results["cost"])
    assert results["cost"] / 512 < 16, results
