"""Elastic sharded checkpointing (doc/resilience.md "Elastic sharded
checkpointing"): per-host async shard saves with a pass-end commit
agreement, reshard-on-relaunch, and host rejoin.

Four layers of coverage:

- unit: the mesh rescale rule, the launcher's reshard/heartbeat helpers,
  and the ShardedAsyncCheckpointer's ordering/commit/failure contracts
  driven through a fake agreement (gates, not wall-clock).
- structural: ``verify_sharded_shards`` catches missing/corrupt/lost
  host shards that the byte-level manifest check cannot see, and
  `paddle check-checkpoint` reports uncommitted partial passes.
- two-process (mp_harness): the REAL pass-end agreement over the jax
  distributed runtime's KV store — these need NO cross-process device
  computations (the protocol is host-side by design), so they run even
  on the CPU backend that skips the two-process TRAINING tests.
- launcher e2e (fake ssh): elastic drop reshards the forwarded
  --mesh_shape, an unreshardable mesh refuses the drop, a recovered
  host rejoins, stale heartbeats are swept — and the per-host chaos
  drill: one host hard-killed between its shard write and the rename
  relaunches and auto-resumes from the last fully-merged pass.
"""

import json
import os
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

import mp_harness
from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import CheckpointError
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.trainer.async_ckpt import ShardedAsyncCheckpointer
from paddle_tpu.parallel.mesh import rescale_mesh_spec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")


# ------------------------------------------------------- mesh rescale rule


def test_rescale_mesh_spec_scales_only_the_data_axis():
    assert rescale_mesh_spec("data=4,model=2", 2, 1) == "data=2,model=2"
    assert rescale_mesh_spec("data=2", 2, 4) == "data=4"
    # a bare extent is the data axis (MeshSpec.parse's shorthand)
    assert rescale_mesh_spec("8", 4, 2) == "data=4"
    # identity: unchanged host count returns the spec as-is
    assert rescale_mesh_spec("data=4,model=2", 2, 2) == "data=4,model=2"
    # empty spec is identity too: the trainer auto-sizes the mesh from
    # jax.devices(), which already follows the surviving host set
    assert rescale_mesh_spec("", 2, 1) == ""


def test_rescale_mesh_spec_refuses_what_cannot_reshard():
    with pytest.raises(ValueError, match="no data axis"):
        rescale_mesh_spec("model=4", 2, 1)
    with pytest.raises(ValueError, match="integrally"):
        rescale_mesh_spec("data=3", 2, 1)
    with pytest.raises(ValueError):
        rescale_mesh_spec("data=2", 0, 1)


def test_rescaled_train_args_rewrites_the_forwarded_flag():
    from paddle_tpu.utils.cluster_launch import _rescaled_train_args

    args = ["--config=c.py", "--mesh_shape=data=4,model=2", "--seed=1"]
    out = _rescaled_train_args(args, 2, 1)
    assert "--mesh_shape=data=2,model=2" in out
    assert not any("data=4" in a for a in out)
    assert "--config=c.py" in out and "--seed=1" in out
    # unchanged host count: the args pass through untouched
    assert _rescaled_train_args(args, 2, 2) is args


def test_clear_heartbeats_sweeps_only_beat_files(tmp_path):
    from paddle_tpu.utils.cluster_launch import _clear_heartbeats

    (tmp_path / "host-0.json").write_text("{}")
    (tmp_path / "host-7.json").write_text("{}")
    (tmp_path / "notes.txt").write_text("keep me")
    assert _clear_heartbeats(str(tmp_path)) == 2
    assert sorted(os.listdir(tmp_path)) == ["notes.txt"]
    assert _clear_heartbeats(str(tmp_path / "missing")) == 0
    assert _clear_heartbeats(None) == 0


# ------------------------------------ sharded async checkpointer contracts


class _FakeAgreement:
    """Deterministic agreement seam: records what THIS process publishes
    and injects the peers' replies. ``peers`` maps a local payload dict
    to a list of reply dicts (or is a static list). The local payload is
    always first (this fake plays process 0, whose reply heads the
    pid-ordered list)."""

    def __init__(self, peers=None):
        self.sent = []
        self.peers = peers

    def agree(self, payload: str):
        d = json.loads(payload)
        self.sent.append(d)
        peers = self.peers(d) if callable(self.peers) else (self.peers or [])
        return [payload] + [json.dumps(p) for p in peers]


class _GatedShardWriter:
    """write_fn(save_dir, pass_id, snapshot, pid) whose writes block
    until released — the event-ordering seam (no wall-clock races)."""

    def __init__(self):
        self.events = []
        self.gates = {}
        self.written = []

    def gate(self, pass_id):
        self.gates[pass_id] = threading.Event()
        return self.gates[pass_id]

    def __call__(self, save_dir, pass_id, snapshot, pid):
        self.events.append(("write_start", pass_id))
        g = self.gates.get(pass_id)
        if g is not None:
            g.wait(20.0)
        self.written.append(pass_id)
        self.events.append(("write_done", pass_id))


def _params(offset=0.0):
    return {"w": jnp.arange(12.0).reshape(3, 4) + offset,
            "b": jnp.ones((4,)) + offset}


@pytest.mark.perf
def test_sharded_save_never_blocks_on_shard_write(tmp_path):
    """Acceptance (event-ordering, mirroring tests/test_async_ckpt.py):
    the step loop side of a SHARDED async save returns before the
    background shard serialize/fsync even runs — proven by a gate."""
    w = _GatedShardWriter()
    gate = w.gate(0)
    ac = ShardedAsyncCheckpointer(
        str(tmp_path), inflight_limit=2, process_index=0, process_count=2,
        agreement=_FakeAgreement(), write_fn=w,
    )
    ac.save(0, _params())
    w.events.append(("save_returned", 0))
    ac.save(1, _params(1.0))
    w.events.append(("save_returned", 1))
    gate.set()
    order = w.events
    deadline = time.monotonic() + 5
    while len(w.written) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert order.index(("save_returned", 0)) < order.index(("write_done", 0)), order
    assert order.index(("save_returned", 1)) < order.index(("write_done", 0)), order
    assert w.written == [0, 1], w.written


def test_commit_is_the_intersection_of_locally_durable_passes(tmp_path):
    """Writer speeds differ per host (drop-oldest can drop DIFFERENT
    passes), so a pass commits only where EVERY host's shards landed —
    the agreed set is the intersection."""
    peer = {"pid": 1, "ok": True, "passes": [0], "error": ""}
    ac = ShardedAsyncCheckpointer(
        str(tmp_path), inflight_limit=2, process_index=0, process_count=1,
        agreement=_FakeAgreement(peers=[peer]),
    )
    durables = []
    ac.save(0, _params(), on_durable=lambda p, path: durables.append(p))
    ac.save(1, _params(1.0), on_durable=lambda p, path: durables.append(p))
    ac.drain()
    # pass 0: in both hosts' durable sets -> committed and renamed
    assert os.path.isdir(os.path.join(str(tmp_path), ckpt.PASS_FMT % 0))
    assert durables == [0]
    # pass 1: the peer never landed it -> NOT committed, and (since it
    # can never commit — its snapshot was consumed) the post-commit
    # rotation sweeps its tmp so the uncommittable attempt is not litter
    assert not os.path.isdir(os.path.join(str(tmp_path), ckpt.PASS_FMT % 1))
    assert ckpt.partial_pass_report(str(tmp_path)) == []


def test_peer_writer_failure_propagates_to_every_host(tmp_path):
    """A failed background write on ANY host surfaces as CheckpointError
    from drain() on ALL hosts (the agreement carries the error) — the
    job tears down together instead of one rank dying in a barrier."""
    peer = {"pid": 1, "ok": False, "passes": [],
            "error": "OSError: disk on fire"}
    fake = _FakeAgreement(peers=[peer])
    ac = ShardedAsyncCheckpointer(
        str(tmp_path), process_index=0, process_count=2, agreement=fake,
    )
    ac.save(0, _params())
    with pytest.raises(CheckpointError, match="host 1.*disk on fire"):
        ac.drain()
    # nothing from the round was committed, and no commit round ran
    # (every process raises at the same point: rounds stay aligned)
    assert not os.path.isdir(os.path.join(str(tmp_path), ckpt.PASS_FMT % 0))
    assert len(fake.sent) == 1


def test_local_writer_failure_travels_via_the_agreement(tmp_path):
    """The sharded save() must NOT re-raise a pending local error early
    (it would desync the collective call sites) — the failure is
    published in the agreement payload and raised at drain on everyone."""

    def doomed(save_dir, pass_id, snapshot, pid):
        raise OSError("shard disk on fire")

    fake = _FakeAgreement()
    ac = ShardedAsyncCheckpointer(
        str(tmp_path), inflight_limit=2, process_index=0, process_count=1,
        agreement=fake, write_fn=doomed,
    )
    ac.save(0, _params())
    deadline = time.monotonic() + 5
    while ac.inflight() and time.monotonic() < deadline:
        time.sleep(0.01)
    ac.save(1, _params(1.0))  # does not raise: symmetric failure contract
    with pytest.raises(CheckpointError, match="shard disk on fire"):
        ac.drain()
    assert fake.sent and fake.sent[0]["ok"] is False
    assert "shard disk on fire" in fake.sent[0]["error"]


def test_commit_failure_on_host0_raises_everywhere_with_rounds_aligned(
        tmp_path, monkeypatch):
    """A finalize failure on process 0 (disk error during merge/rename)
    must surface as CheckpointError — never a raw escape that skips the
    commit round and leaves the agreement counters desynced across the
    pod. The commit-verdict round still runs (rounds aligned), and it
    carries the failure to every host."""
    from paddle_tpu.trainer import async_ckpt as ac_mod

    def doomed_finalize(*a, **kw):
        raise OSError("rename target vanished")

    monkeypatch.setattr(ac_mod.ckpt, "finalize_sharded_pass", doomed_finalize)
    fake = _FakeAgreement(peers=[{"pid": 1, "ok": True, "passes": [0],
                                  "error": ""}])
    ac = ShardedAsyncCheckpointer(
        str(tmp_path), process_index=0, process_count=2, agreement=fake,
    )
    durables = []
    ac.save(0, _params(), on_durable=lambda p, path: durables.append(p))
    with pytest.raises(CheckpointError, match="commit failed on host 0"):
        ac.drain()
    # BOTH rounds ran: the pass agreement and the commit verdict — a
    # peer reading verdicts[0] sees committed=False and raises too
    assert len(fake.sent) == 2, fake.sent
    assert fake.sent[1] == {"pid": 0, "committed": False}
    assert durables == []


def test_sharded_async_round_trip_single_process(tmp_path):
    """Real write path end-to-end (degenerate one-process agreement):
    the committed pass verifies byte-level AND structurally, loads back
    bit-exact, and nothing partial is left behind."""
    ac = ShardedAsyncCheckpointer(str(tmp_path), agree_timeout=30)
    durables = []
    ac.save(0, _params(), extra_meta={"batch_id": 7},
            on_durable=lambda p, path: durables.append((p, path)))
    ac.drain()
    ac.drain()  # nothing new enqueued: the agreement round is skipped
    path = os.path.join(str(tmp_path), ckpt.PASS_FMT % 0)
    assert ckpt.verify_checkpoint(path) == []
    assert ckpt.verify_sharded_shards(path) == []
    params, _, meta = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(
        np.asarray(params["w"]), np.asarray(_params()["w"]))
    assert meta["batch_id"] == 7 and meta["format_version"] == 2
    assert durables == [(0, path)]
    assert ckpt.partial_pass_report(str(tmp_path)) == []
    # the split accounting exists: snapshot cost + background write cost
    assert obs.registry().counter("ckpt.write_s").value > 0.0


# -------------------------------------------- structural shard verification


def _host_snapshot(pid, pass_id=0, rows=4, cols=2):
    """One handcrafted host's half of a (rows x cols) table: host pid
    owns the contiguous row block [pid*rows/2, (pid+1)*rows/2)."""
    table = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
    table = table + 100.0 * pass_id
    half = rows // 2
    lo = pid * half
    piece = table[lo:lo + half]
    shard_file = f"params.shard{pid:05d}.npz"
    return {"params": (
        {f"w::{pid}": piece},
        {"w": {"shape": [rows, cols], "dtype": "float32",
               "shards": [{"file": shard_file, "key": f"w::{pid}",
                           "start": [lo, 0], "shape": [half, cols]}]}},
    )}


def _commit_two_host_pass(save_dir, pass_id=0):
    for pid in range(2):
        ckpt.write_sharded_host_trees(
            save_dir, pass_id, _host_snapshot(pid, pass_id), pid)
    return ckpt.finalize_sharded_pass(
        save_dir, pass_id, ["params"],
        {"pass_id": pass_id, "format_version": 2}, expected_pids=range(2),
    )


def test_two_host_shard_files_assemble_on_restore(tmp_path):
    path = _commit_two_host_pass(str(tmp_path))
    files = sorted(os.listdir(path))
    assert "params.shard00000.npz" in files and "params.shard00001.npz" in files
    assert "params.index.json" in files and "MANIFEST.json" in files
    assert not any(f.startswith("params.index.0") for f in files)  # merged
    assert ckpt.verify_checkpoint(path) == []
    assert ckpt.verify_sharded_shards(path) == []
    params, _, _ = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(
        np.asarray(params["w"]),
        np.arange(8, dtype=np.float32).reshape(4, 2))


def test_verify_sharded_shards_names_the_losing_host(tmp_path):
    path = _commit_two_host_pass(str(tmp_path))
    os.remove(os.path.join(path, "params.shard00001.npz"))
    problems = ckpt.verify_sharded_shards(path)
    assert problems and any(
        "missing" in p and "host 1" in p for p in problems), problems
    # host 0's file is fine: no problem names it
    assert not any("host 0" in p for p in problems), problems


def test_verify_sharded_shards_catches_a_coverage_hole(tmp_path):
    """A bad merge that silently LOST one host's records leaves files
    the manifest still fully verifies — only the structural coverage
    check can see the hole."""
    path = _commit_two_host_pass(str(tmp_path))
    idx_path = os.path.join(path, "params.index.json")
    with open(idx_path) as f:
        index = json.load(f)
    index["w"]["shards"] = index["w"]["shards"][:1]  # drop host 1's record
    with open(idx_path, "w") as f:
        json.dump(index, f)
    problems = ckpt.verify_sharded_shards(path)
    assert any("cover" in p and "4 of 8" in p for p in problems), problems


def test_verify_sharded_shards_catches_a_wrong_npz_key(tmp_path):
    path = _commit_two_host_pass(str(tmp_path))
    shard = os.path.join(path, "params.shard00001.npz")
    np.savez(shard, **{"not::the::key": np.zeros((2, 2), np.float32)})
    problems = ckpt.verify_sharded_shards(path)
    assert any("absent from" in p and "host 1" in p for p in problems), problems


def test_check_checkpoint_cli_reports_partial_passes(tmp_path, capsys):
    """Satellite: `paddle check-checkpoint` exits nonzero on a partial
    pass and says which one, per host count of partial manifests."""
    from paddle_tpu import cli

    save_dir = str(tmp_path)
    _commit_two_host_pass(save_dir, pass_id=0)
    # pass 1: both hosts' shards land but the commit never happens
    for pid in range(2):
        ckpt.write_sharded_host_trees(
            save_dir, 1, _host_snapshot(pid, 1), pid)
    report = ckpt.partial_pass_report(save_dir)
    assert len(report) == 1 and report[0][1] == 2
    assert cli.main(["check-checkpoint", save_dir]) == 1
    out = capsys.readouterr().out
    assert "OK " in out and "PARTIAL" in out and "pass-00001.tmp" in out
    # a torn sharded pass dir directly: nonzero with per-host problems
    os.remove(os.path.join(save_dir, "pass-00000", "params.shard00001.npz"))
    assert cli.main(["check-checkpoint",
                     os.path.join(save_dir, "pass-00000")]) == 1
    assert "host 1" in capsys.readouterr().out


# ----------------------------- two-process protocol (real KV agreement)
# These run the REAL jax distributed runtime across two OS processes but
# need no cross-process device computations — the checkpoint protocol is
# host-side (KV store + host barriers) by design, so they run even where
# the two-process TRAINING tests skip.

_SAVE2_WORKER = mp_harness.WORKER_PREAMBLE + """
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from paddle_tpu.trainer.async_ckpt import ShardedAsyncCheckpointer
from paddle_tpu.trainer import checkpoint as ckpt

mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
rows, cols = 64, 4
exp = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
table = jax.make_array_from_callback(
    (rows, cols), NamedSharding(mesh, P("data", None)),
    lambda idx: exp[idx])
bias = jax.make_array_from_callback(
    (cols,), NamedSharding(mesh, P()),
    lambda idx: np.ones((cols,), np.float32))

save_dir = os.path.join(ws, "model")
ac = ShardedAsyncCheckpointer(save_dir, inflight_limit=2, agree_timeout=120)
ac.save(0, {{"table": table, "bias": bias}}, extra_meta={{"batch_id": 3}})
ac.save(1, {{"table": table, "bias": bias}})
ac.drain()   # ONE agreement commits both passes
assert os.path.isdir(os.path.join(save_dir, ckpt.PASS_FMT % 1))
print("WORKER_OK", pid, flush=True)
"""


def test_two_process_async_sharded_save_restores_on_one(tmp_path):
    """Mesh-shape round trip N=2 -> M=1: per-host async shard saves with
    the real pass-end KV agreement; the committed checkpoint assembles
    whole on a single process."""
    mp_harness.run_two_workers(
        _SAVE2_WORKER.format(repo=REPO, providers=PROVIDERS), str(tmp_path))
    save_dir = os.path.join(str(tmp_path), "model")
    best = ckpt.find_restorable_checkpoint(save_dir)
    assert best is not None and best.endswith(ckpt.PASS_FMT % 1), best
    for p in (0, 1):
        path = os.path.join(save_dir, ckpt.PASS_FMT % p)
        files = sorted(os.listdir(path))
        # BOTH hosts' shard files are in the committed pass
        assert "params.shard00000.npz" in files, files
        assert "params.shard00001.npz" in files, files
        assert ckpt.verify_checkpoint(path) == []
        assert ckpt.verify_sharded_shards(path) == []
    params, _, meta = ckpt.load_checkpoint(os.path.join(
        save_dir, ckpt.PASS_FMT % 1))
    np.testing.assert_array_equal(
        np.asarray(params["table"]),
        np.arange(64 * 4, dtype=np.float32).reshape(64, 4))
    np.testing.assert_array_equal(
        np.asarray(params["bias"]), np.ones((4,), np.float32))
    assert meta["format_version"] == 2
    assert ckpt.partial_pass_report(save_dir) == []


_LOAD2_WORKER = mp_harness.WORKER_PREAMBLE + """
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from paddle_tpu.trainer import checkpoint as ckpt

mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
sh = NamedSharding(mesh, P("data", None))
path = os.path.join(ws, "model", ckpt.PASS_FMT % 0)
params, _, meta = ckpt.load_checkpoint(
    path, sharding_for=lambda base, key, shape: sh)
t = params["table"]
exp = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)
for s in t.addressable_shards:
    np.testing.assert_array_equal(np.asarray(s.data), exp[s.index])
print("WORKER_OK", pid, flush=True)
"""


def test_single_process_save_restores_sharded_on_two(tmp_path):
    """Mesh-shape round trip M=1 -> N=2: a single-process checkpoint
    reshards onto a two-process mesh through load_checkpoint's
    sharding_for path — every process checks its own device slices."""
    save_dir = os.path.join(str(tmp_path), "model")
    table = jnp.asarray(np.arange(64 * 4, dtype=np.float32).reshape(64, 4))
    ckpt.save_checkpoint(save_dir, 0, {"table": table})
    mp_harness.run_two_workers(
        _LOAD2_WORKER.format(repo=REPO, providers=PROVIDERS), str(tmp_path))


# --------------------------------------- launcher e2e: reshard and rejoin


def _write_fake_ssh(bin_dir, body):
    """A stub `ssh` on PATH (cluster_launch's call shape: $3 the host,
    $4 the remote command — both the launch and the rejoin probe)."""
    ssh = bin_dir / "ssh"
    ssh.write_text("#!/bin/sh\nhost=$3\nremote=$4\n" + body)
    ssh.chmod(0o755)
    return {**os.environ, "PATH": f"{bin_dir}:{os.environ['PATH']}",
            "PYTHONPATH": f"{REPO}:{REPO}/compat"}


def _launch_cluster(conf, env, *extra, timeout=120,
                    train=("--config=train.conf",)):
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--restart_delay", "0.1", *extra, "--", *train],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout,
    )


def test_cluster_launch_reshards_mesh_on_elastic_drop(tmp_path):
    """Tentpole: the drop round's survivors get a RESCALED --mesh_shape
    (data axis follows the host count; global batch is the config's and
    never changes), not just a smaller --num_processes."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_bad', 'u@h_ok']\n")
    calls = tmp_path / "calls.log"
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$host $remote\" >> {calls}\n"
        "case \"$host\" in\n"
        "  *bad*) sleep 0.2; exit 2;;\n"
        "  *) case \"$remote\" in\n"
        "       *--num_processes=1*) exit 0;;\n"
        "       *) sleep 120;;\n"
        "     esac;;\n"
        "esac\n"
    ))
    out = _launch_cluster(
        conf, env, "--max_restarts", "1", "--elastic_min_hosts", "1",
        "--rejoin_probe_timeout", "0",
        train=("--config=train.conf", "--mesh_shape=data=4"),
    )
    assert out.returncode == 0, (out.returncode, out.stderr)
    assert "the mesh reshards to the survivors" in out.stderr
    lines = calls.read_text().splitlines()
    solo = [l for l in lines if "--num_processes=1" in l]
    assert solo and all("--mesh_shape=data=2" in l for l in solo), lines
    # full-set rounds kept the original spec
    assert all("--mesh_shape=data=4" in l
               for l in lines if "--num_processes=2" in l), lines


def test_cluster_launch_refuses_drop_when_mesh_cannot_reshard(tmp_path):
    """A drop the mesh cannot follow (data=3 does not halve) must be
    refused: the host is kept and the relaunch spends budget instead of
    launching a job whose mesh no longer matches its devices."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_bad', 'u@h_ok']\n")
    calls = tmp_path / "calls.log"
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$host $remote\" >> {calls}\n"
        "case \"$host\" in\n"
        "  *bad*) sleep 0.2; exit 2;;\n"
        "  *) sleep 120;;\n"
        "esac\n"
    ))
    out = _launch_cluster(
        conf, env, "--max_restarts", "2", "--elastic_min_hosts", "1",
        "--rejoin_probe_timeout", "0",
        train=("--config=train.conf", "--mesh_shape=data=3"),
    )
    assert out.returncode == 2, (out.returncode, out.stderr)
    assert "cannot drop host u@h_bad" in out.stderr, out.stderr
    assert "does not reshard" in out.stderr
    # no round ever launched the un-reshardable single-host mesh
    assert "--num_processes=1" not in calls.read_text()


def test_cluster_launch_rejoin_and_heartbeat_sweep(tmp_path):
    """Satellites + tentpole: a dropped host REJOINS the mesh once the
    reachability probe answers (recovery is not permanent capacity
    loss), and every relaunch round first sweeps stale heartbeat files
    so a previous mesh's beats can't condemn the new ranks.

    The probe is gated to rounds LATER than the drop round: the flapping
    host's sshd stays healthy throughout, so probing in the drop round
    itself would reinstate it immediately — the drop would never take
    effect and the budget-free drop/rejoin cycle would relaunch forever.
    The solo round actually running (--num_processes=1 below) is the
    regression assertion for that."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_flap', 'u@h_ok']\n")
    calls = tmp_path / "calls.log"
    flap_runs = tmp_path / "flap_runs"
    hb_dir = tmp_path / "hb"
    hb_dir.mkdir()
    # stale beats from a "previous mesh" — must be swept, not believed
    (hb_dir / "host-0.json").write_text('{"host": 0, "t": 1}')
    (hb_dir / "host-1.json").write_text('{"host": 1, "t": 1}')
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$host $remote\" >> {calls}\n"
        "[ \"$remote\" = true ] && exit 0\n"  # rejoin probe: reachable
        "case \"$host\" in\n"
        f"  *flap*) echo run >> {flap_runs}\n"
        f"    if [ $(wc -l < {flap_runs}) -lt 3 ]; then sleep 0.2; exit 2; fi\n"
        "    exit 0;;\n"
        "  *) case \"$remote\" in\n"
        "       *--num_processes=1*) sleep 0.2; exit 5;;\n"
        "       *) exit 0;;\n"
        "     esac;;\n"
        "esac\n"
    ))
    out = _launch_cluster(
        conf, env, "--max_restarts", "3", "--elastic_min_hosts", "1",
        "--rejoin_probe_timeout", "5",
        train=("--config=train.conf", "--mesh_shape=data=2",
               "--heartbeat_interval=5", f"--heartbeat_dir={hb_dir}"),
        timeout=180,
    )
    # round 1: flap fails (budget). round 2: flap fails again -> dropped.
    # round 3: SOLO on the survivor (probe gated out of the drop round),
    # mesh resharded to data=1; the survivor fails (budget). round 4:
    # the probe answers -> flap rejoins at its ORIGINAL rank, mesh back
    # to data=2, both exit 0.
    assert out.returncode == 0, (out.returncode, out.stderr)
    assert "dropping host u@h_flap" in out.stderr
    assert "rejoining the mesh at rank 0" in out.stderr, out.stderr
    assert "cleared 2 heartbeat file(s)" in out.stderr, out.stderr
    assert not list(hb_dir.glob("host-*.json"))
    lines = calls.read_text().splitlines()
    # the drop TOOK EFFECT: a resharded solo round ran without flap,
    # before the rejoin round
    solo = [l for l in lines
            if "--num_processes=1" in l and "--mesh_shape=data=1" in l]
    assert solo and all(l.startswith("u@h_ok") for l in solo), lines
    assert lines.index(solo[-1]) < len(lines) - 2, lines
    last_round = lines[-2:]
    assert all("--num_processes=2" in l and "--mesh_shape=data=2" in l
               for l in last_round), lines
    # the rejoined host came back as rank 0 (original order preserved)
    assert any(l.startswith("u@h_flap") and "--process_id=0" in l
               for l in last_round), lines


# ------------------------------------------------- per-host chaos drill

_STUB_TRAINER = '''#!/usr/bin/env python3
"""Fake `paddle train` for the per-host chaos drill: drives the REAL
shard-write/commit functions, then dies in the window the drill needs."""
import os, sys, time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from paddle_tpu.trainer import checkpoint as ckpt

args = sys.argv[2:]  # after the "train" verb


def flagval(name, default=""):
    for a in args:
        if a.startswith("--" + name + "="):
            return a.split("=", 1)[1]
    return default


pid = int(flagval("process_id", "0"))
n = int(flagval("num_processes", "1"))
save_dir = flagval("save_dir")
resume = flagval("init_model_path") == "auto"


def snapshot(pass_id):
    rows = np.arange(8.0, dtype=np.float32).reshape(4, 2) + 100.0 * pass_id
    lo = pid * 2
    return {{"params": (
        {{"w::%d" % pid: rows[lo:lo + 2]}},
        {{"w": {{"shape": [4, 2], "dtype": "float32",
               "shards": [{{"file": "params.shard%05d.npz" % pid,
                           "key": "w::%d" % pid, "start": [lo, 0],
                           "shape": [2, 2]}}]}}}},
    )}}


def wait_for(path, timeout=60):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if os.path.exists(path):
            return True
        time.sleep(0.05)
    return False


def save_pass(p):
    ckpt.write_sharded_host_trees(save_dir, p, snapshot(p), pid)
    tmp = os.path.join(save_dir, ckpt.PASS_FMT % p) + ckpt.TMP_SUFFIX
    final = os.path.join(save_dir, ckpt.PASS_FMT % p)
    if pid == 0:
        # the drill's stand-in for the pass-end agreement: wait for every
        # host's partial manifest (written AFTER its shards are durable),
        # then merge + rename
        for q in range(n):
            assert wait_for(os.path.join(
                tmp, "MANIFEST.partial.%05d.json" % q)), "peer never wrote"
        ckpt.finalize_sharded_pass(
            save_dir, p, ["params"], {{"pass_id": p, "format_version": 2}},
            expected_pids=range(n))
    else:
        assert wait_for(final), "commit never landed"


if not resume:
    save_pass(0)  # pass 0 fully commits on every host
    # pass 1: shards land, then host 1 dies BETWEEN its shard write and
    # the rename; host 0 never sees the commit agreement complete
    ckpt.write_sharded_host_trees(save_dir, 1, snapshot(1), pid)
    if pid == 1:
        os._exit(3)  # hard kill in the window
    time.sleep(120)  # host 0 blocks "in the agreement" until torn down
else:
    best = ckpt.find_restorable_checkpoint(save_dir)
    assert best and best.endswith(ckpt.PASS_FMT % 0), best
    sys.exit(0)
'''


@pytest.mark.chaos
def test_one_host_killed_between_shard_write_and_rename(tmp_path):
    """Acceptance chaos e2e: a 2-host launch loses one host in the
    shard-write/rename window; the relaunch auto-resumes from the last
    FULLY-merged pass (pass 0), the torn pass stays visibly partial, and
    the checkpoint assembles whole on this (M=1) process."""
    from paddle_tpu import cli

    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h0', 'u@h1']\n")
    save_dir = tmp_path / "model"
    stub = tmp_path / "paddle_stub"
    stub.write_text(_STUB_TRAINER.format(repo=REPO))
    stub.chmod(0o755)
    calls = tmp_path / "calls.log"
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$host $remote\" >> {calls}\n"
        "exec sh -c \"$remote\"\n"
    ))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", str(tmp_path),
         "--paddle", str(stub),
         "--poll_interval", "0.1", "--grace", "2",
         "--max_restarts", "1", "--restart_delay", "0.1",
         "--", "--config=train.conf", "--mesh_shape=data=2",
         f"--save_dir={save_dir}"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300,
    )
    assert out.returncode == 0, (out.returncode, out.stderr[-3000:])
    assert "relaunching" in out.stderr
    # round 2 resumed every host from the newest verified checkpoint
    resumed = [l for l in calls.read_text().splitlines()
               if "--init_model_path=auto" in l]
    assert len(resumed) == 2, calls.read_text()
    # pass 0 survived the chaos: both checks clean, assembles whole here
    p0 = os.path.join(str(save_dir), ckpt.PASS_FMT % 0)
    assert ckpt.verify_checkpoint(p0) == []
    assert ckpt.verify_sharded_shards(p0) == []
    params, _, _ = ckpt.load_checkpoint(p0)
    np.testing.assert_array_equal(
        np.asarray(params["w"]),
        np.arange(8, dtype=np.float32).reshape(4, 2))
    # the torn pass 1 is a reported partial, and the CLI flags it
    report = ckpt.partial_pass_report(str(save_dir))
    assert len(report) == 1 and report[0][0].endswith("pass-00001.tmp")
    assert cli.main(["check-checkpoint", str(save_dir)]) == 1
