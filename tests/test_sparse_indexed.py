"""Row-sparse (indexed) embedding gradients — the sparse-at-scale path.

Reference contracts mirrored: prefetch-from-input-ids
(/root/reference/paddle/trainer/TrainerInternal.cpp:91-95), sparse-row
gradients (paddle/math/SparseRowMatrix.h:31), per-row pserver updates
(paddle/pserver/ParameterServer2.cpp:352,572). The TPU design computes a
RowSparseGrad (ids + occurrence rows, static shapes) by differentiating
w.r.t. prefetched rows — never a dense [V, D] gradient — and must match
the dense-gradient row-scan path bit-for-bit on small vocabularies.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.config.builder import fresh_context
from paddle_tpu.graph import GradientMachine, make_ids, make_seq
from paddle_tpu.optimizer import Updater
from paddle_tpu.optimizer.sparse import RowSparseGrad, dedupe
from paddle_tpu.proto import ModelConfig, OptimizationConfig, ParameterConfig
from paddle_tpu.trainer_config_helpers import (
    MaxPooling,
    ParamAttr,
    SoftmaxActivation,
    classification_cost,
    data_layer,
    embedding_layer,
    fc_layer,
    outputs,
    pooling_layer,
    settings,
)


def _updater(method="adagrad", decay=0.0, V=8, D=3):
    m = ModelConfig()
    m.parameters.append(
        ParameterConfig(name="emb", size=V * D, dims=[V, D],
                        decay_rate=decay, sparse_update=True)
    )
    opt = OptimizationConfig(learning_rate=0.1, learning_method=method,
                             learning_rate_schedule="constant", batch_size=2)
    return Updater(opt, m)


def test_dedupe_sums_duplicates():
    ids = jnp.asarray([3, 1, 3, 5, 1, 3], jnp.int32)
    rows = jnp.arange(18, dtype=jnp.float32).reshape(6, 3)
    uid, g_rows, valid = dedupe(ids, rows, nrows=8)
    uid, g_rows, valid = np.asarray(uid), np.asarray(g_rows), np.asarray(valid)
    assert valid.sum() == 3
    got = {int(uid[i]): g_rows[i] for i in range(3)}
    want = np.zeros((8, 3), np.float32)
    for i, r in enumerate(np.asarray(ids)):
        want[r] += np.asarray(rows)[i]
    for rid, grow in got.items():
        np.testing.assert_allclose(grow, want[rid], rtol=1e-6)
    assert (uid[3:] == 8).all()  # sentinel = nrows, dropped at scatter


def test_indexed_matches_dense_row_scan():
    """RowSparseGrad updates == dense-gradient sparse-row updates, incl.
    lazy L2 catch-up, over several steps with idle rows and duplicates."""
    V, D = 8, 3
    rng = np.random.RandomState(0)
    w0 = jnp.asarray(rng.randn(V, D).astype(np.float32))
    for method, decay in [("adagrad", 0.0), ("sgd", 0.5), ("adam", 0.25)]:
        upd_a = _updater(method, decay, V, D)
        upd_b = _updater(method, decay, V, D)
        pa, pb = {"emb": w0}, {"emb": w0}
        sa, sb = upd_a.init_state(pa), upd_b.init_state(pb)
        step_ids = [[1, 3, 1], [5, 5, 0], [1, 7, 2]]  # dups + idle rows
        for ids in step_ids:
            ids_j = jnp.asarray(ids, jnp.int32)
            rows = jnp.asarray(rng.randn(len(ids), D).astype(np.float32))
            sg = RowSparseGrad(ids=ids_j, rows=rows, nrows=V)
            pa, sa = jax.jit(upd_a)(pa, {"emb": sg}, sa, 2.0)
            pb, sb = jax.jit(upd_b)(pb, {"emb": sg.to_dense()}, sb, 2.0)
            np.testing.assert_allclose(
                np.asarray(pa["emb"]), np.asarray(pb["emb"]), rtol=1e-5, atol=1e-6,
                err_msg=f"{method} decay={decay}",
            )
        for k in sa.slots["emb"]:
            np.testing.assert_allclose(
                np.asarray(sa.slots["emb"][k]), np.asarray(sb.slots["emb"][k]),
                rtol=1e-5, atol=1e-6, err_msg=f"{method} slot {k}",
            )


def test_zero_aggregate_rows_stay_frozen():
    """Ids whose summed gradient is exactly zero (padded positions) must
    not advance the row or its optimizer state — matching the dense
    path's any(g != 0) touched-row detection."""
    V, D = 8, 3
    upd = _updater("adam", 0.0, V, D)
    w0 = jnp.asarray(np.random.RandomState(3).randn(V, D).astype(np.float32))
    params = {"emb": w0}
    state = upd.init_state(params)
    # row 2: two occurrences that cancel exactly; row 5: zero rows only
    ids = jnp.asarray([2, 5, 2, 1], jnp.int32)
    rows = jnp.asarray(
        [[1.0, 2.0, 3.0], [0.0, 0.0, 0.0], [-1.0, -2.0, -3.0], [0.5, 0.5, 0.5]],
        jnp.float32,
    )
    sg = RowSparseGrad(ids=ids, rows=rows, nrows=V)
    params, state = jax.jit(upd)(params, {"emb": sg}, state, 2.0)
    w = np.asarray(params["emb"])
    np.testing.assert_array_equal(w[2], np.asarray(w0)[2])
    np.testing.assert_array_equal(w[5], np.asarray(w0)[5])
    assert not np.allclose(w[1], np.asarray(w0)[1])
    t_last = np.asarray(state.slots["emb"]["t_last"])
    np.testing.assert_array_equal(t_last, [0, 1, 0, 0, 0, 0, 0, 0])
    m = np.asarray(state.slots["emb"]["m"])
    assert (m[[2, 5]] == 0).all() and (m[1] != 0).any()


def _emb_model(V, D, classes=3, sparse=True):
    with fresh_context() as ctx:
        settings(batch_size=4, learning_rate=0.05)
        words = data_layer(name="words", size=V)
        emb = embedding_layer(
            input=words, size=D,
            param_attr=ParamAttr(name="emb", sparse_update=sparse),
        )
        pool = pooling_layer(input=emb, pooling_type=MaxPooling())
        out = fc_layer(input=pool, size=classes, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=classes)
        outputs(classification_cost(input=out, label=label))
        return ctx.finalize()


def _batch(V, B=4, T=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, V, (B, T)).astype(np.int32)
    lengths = rng.randint(T // 2, T + 1, (B,)).astype(np.int32)
    labels = rng.randint(0, classes, (B,)).astype(np.int32)
    return {"words": make_seq(None, lengths, ids=ids), "label": make_ids(labels)}


def test_grad_fn_returns_row_sparse():
    V, D = 50, 4
    tc = _emb_model(V, D)
    gm = GradientMachine(tc.model_config)
    assert gm.sparse_prefetch_plan() == [("emb", "words")]
    params = gm.init_params(seed=1)
    batch = _batch(V)
    loss, grads, _, _ = jax.jit(gm.grad_fn())(params, batch, None)
    g = grads["emb"]
    assert isinstance(g, RowSparseGrad)
    assert g.ids.shape == (4 * 6,) and g.rows.shape == (24, D)
    # sparse-path loss and gradient must match the plain dense autodiff
    loss_d, grads_d = jax.value_and_grad(
        lambda p: gm.loss_fn(p, batch, None)[0]
    )(params)
    np.testing.assert_allclose(float(loss), float(loss_d), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(g.to_dense()), np.asarray(grads_d["emb"]), rtol=1e-5, atol=1e-7
    )


def test_unresolvable_sparse_falls_back_dense():
    """A sparse table used outside a data-fed table projection keeps the
    dense path (the reference prefetch has the same reach)."""
    V, D = 20, 4
    with fresh_context() as ctx:
        settings(batch_size=4, learning_rate=0.05)
        words = data_layer(name="words", size=V)
        emb = embedding_layer(
            input=words, size=D,
            param_attr=ParamAttr(name="emb", sparse_update=True),
        )
        emb2 = embedding_layer(  # same table fed from a NON-data layer
            input=fc_layer(input=emb, size=V, name="idsrc"), size=D,
            param_attr=ParamAttr(name="emb", sparse_update=True),
        )
        del emb2
        pool = pooling_layer(input=emb, pooling_type=MaxPooling())
        out = fc_layer(input=pool, size=3, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=3)
        outputs(classification_cost(input=out, label=label))
        tc = ctx.finalize()
    gm = GradientMachine(tc.model_config)
    assert gm.sparse_prefetch_plan() == []


def test_million_row_table_trains_sharded():
    """>=1M-row sparse table trains one SPMD step on the CPU mesh with the
    table sharded over 'model' — without a dense [V, D] gradient (grad is
    RowSparseGrad by construction; a dense f32 grad at this size would be
    32MB per step per buffer)."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.spmd import shard_train_step

    V, D = 1_000_000, 8
    tc = _emb_model(V, D)
    for p in tc.model_config.parameters:
        if p.name == "emb":
            p.sharding = ["model", None]
    gm = GradientMachine(tc.model_config)
    assert gm.sparse_prefetch_plan() == [("emb", "words")]
    updater = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=1)
    opt_state = updater.init_state(params)
    mesh = make_mesh("data=4,model=2")
    grad_fn = gm.grad_fn()

    def step(params, opt_state, batch, rng, bs):
        loss, grads, _, _ = grad_fn(params, batch, rng)
        new_params, new_opt = updater(params, grads, opt_state, bs)
        return new_params, new_opt, loss, loss

    sharded = shard_train_step(step, mesh, gm)
    batch = _batch(V, B=8, T=6)
    w_before = np.asarray(params["emb"][:100])
    params, opt_state, loss, _ = sharded(
        params, opt_state, batch, jax.random.PRNGKey(0), jnp.asarray(8.0)
    )
    assert np.isfinite(float(loss))
    # only touched rows moved
    touched = set(np.asarray(batch["words"].ids).ravel().tolist())
    w_after = np.asarray(params["emb"][:100])
    for r in range(100):
        if r not in touched:
            np.testing.assert_array_equal(w_after[r], w_before[r])
