"""Multi-process distributed training — the loopback-pserver analog.

The reference tests distribution without a cluster by spinning loopback
pservers in-process and asserting the remote updater matches local
training (/root/reference/paddle/trainer/tests/test_TrainerOnePass.cpp:
120-296). Here: two OS processes join a jax.distributed coordination
service over localhost, form one 8-device CPU mesh (4 virtual devices
each), train the same config, and the result must match the
single-process 8-device run. Also asserts the BarrierStat-style per-host
step-time skew summary appears in the pass log.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "").replace("--xla_force_host_platform_device_count=8", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
sys.path.insert(0, {repo!r})
sys.path.insert(0, {providers!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as _xb
for _n in list(_xb._backend_factories):
    if _n not in ("cpu", "tpu"):
        del _xb._backend_factories[_n]

pid = int(sys.argv[1])
jax.distributed.initialize(coordinator_address="localhost:" + sys.argv[2],
                           num_processes=2, process_id=pid)
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

FLAGS.save_dir = ""
FLAGS.mesh_shape = "data=8"
FLAGS.log_period = 0
FLAGS.seed = 7
ws = sys.argv[3]
trainer = Trainer(parse_config(os.path.join(ws, "cfg.py")))
trainer.train(num_passes=1)

# distributeEval analog, sufficient-statistics form: evaluators
# accumulate over LOCAL row blocks and merge small state vectors at read
# time — no per-batch activation gather (asserted: gather_outputs never
# fires for this all-mergeable chain). Results must be identical across
# processes and match the single-process run.
import json
from paddle_tpu.parallel import spmd
from paddle_tpu.parallel.spmd import globalize_batch
from paddle_tpu.trainer.evaluators import EvaluatorChain

gather_calls = [0]
_orig_gather = spmd.gather_outputs
def _counting_gather(*a, **k):
    gather_calls[0] += 1
    return _orig_gather(*a, **k)
spmd.gather_outputs = _counting_gather

chain = EvaluatorChain(trainer.config.model_config)
chain.start()
provider = trainer._provider(for_test=False)
for batch in provider.batches():
    b = globalize_batch(batch, trainer._mesh)
    if b is None:
        continue
    outputs = trainer.test_fwd(trainer.params, b)
    trainer._eval_outputs(chain, outputs)
res = chain.results()
res["_gather_calls"] = gather_calls[0]
spmd.gather_outputs = _orig_gather
with open(os.path.join(ws, "eval_p%d.json" % pid), "w") as f:
    json.dump(res, f)

if jax.process_index() == 0:
    import numpy as np
    np.savez(os.path.join(ws, "mp_params.npz"),
             **{{k: np.asarray(v) for k, v in trainer.params.items()}})
print("WORKER_OK", pid, flush=True)
"""


def _write_config(ws):
    train_list = os.path.join(ws, "train.list")
    with open(train_list, "w") as f:
        f.write("1\n2\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={train_list!r}, test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.05)
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    path = os.path.join(ws, "cfg.py")
    with open(path, "w") as f:
        f.write(src)
    return path


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_training_matches_single(tmp_path):
    ws = str(tmp_path)
    cfg_path = _write_config(ws)
    sys.path.insert(0, PROVIDERS)

    # single-process reference on the same 8-device mesh (this pytest
    # process already has 8 virtual devices via conftest)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    FLAGS.save_dir = ""
    FLAGS.mesh_shape = "data=8"
    FLAGS.log_period = 0
    FLAGS.seed = 7
    try:
        ref = Trainer(parse_config(cfg_path))
        ref.train(num_passes=1)
    finally:
        FLAGS.mesh_shape = ""
        sys.path.remove(PROVIDERS)

    port = _free_port()
    worker_py = os.path.join(ws, "worker.py")
    with open(worker_py, "w") as f:
        f.write(WORKER.format(repo=REPO, providers=PROVIDERS))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker_py, str(i), str(port), ws],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "WORKER_OK" in out, (out, err[-2000:])
    # BarrierStat skew line logged at pass end on every host
    assert any("BarrierStat" in err for _, _, err in outs), outs[0][2][-2000:]

    with np.load(os.path.join(ws, "mp_params.npz")) as z:
        mp_params = {k: z[k] for k in z.files}
    for name, ref_v in ref.params.items():
        np.testing.assert_allclose(
            np.asarray(ref_v), mp_params[name], rtol=2e-4, atol=1e-5,
            err_msg=name,
        )

    # merged evaluator metrics: identical on every process, and the
    # classification error matches the single-process run over the same
    # data with the (numerically near-identical) final parameters
    import json
    from paddle_tpu.trainer.evaluators import EvaluatorChain

    with open(os.path.join(ws, "eval_p0.json")) as f:
        eval_p0 = json.load(f)
    with open(os.path.join(ws, "eval_p1.json")) as f:
        eval_p1 = json.load(f)
    assert eval_p0 == eval_p1, (eval_p0, eval_p1)
    assert eval_p0, "no evaluator results produced"
    # the chain is all-mergeable (classification_error): local rows +
    # state merge, never a per-batch activation gather
    assert eval_p0.pop("_gather_calls") == 0
    eval_p1.pop("_gather_calls")

    sys.path.insert(0, PROVIDERS)
    try:
        chain = EvaluatorChain(ref.config.model_config)
        chain.start()
        provider = ref._provider(for_test=False)
        for batch in provider.batches():
            chain.eval_batch(ref.test_fwd(ref.params, batch))
        ref_results = chain.results()
    finally:
        sys.path.remove(PROVIDERS)
    for k, v in ref_results.items():
        assert abs(eval_p0[k] - v) <= 5e-3, (k, eval_p0[k], v)
