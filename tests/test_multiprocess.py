"""Multi-process distributed training — the loopback-pserver analog.

The reference tests distribution without a cluster by spinning loopback
pservers in-process and asserting the remote updater matches local
training (/root/reference/paddle/trainer/tests/test_TrainerOnePass.cpp:
120-296). Here: two OS processes join a jax.distributed coordination
service over localhost, form one 8-device CPU mesh (4 virtual devices
each), train the same config, and the result must match the
single-process 8-device run. Also asserts the BarrierStat-style per-host
step-time skew summary appears in the pass log.
"""

import os
import sys
import textwrap

import mp_harness

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

WORKER = mp_harness.WORKER_PREAMBLE + """

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

FLAGS.save_dir = ""
FLAGS.mesh_shape = "data=8"
FLAGS.log_period = 0
FLAGS.seed = 7
trainer = Trainer(parse_config(os.path.join(ws, "cfg.py")))
trainer.train(num_passes=1)

# distributeEval analog, sufficient-statistics form: evaluators
# accumulate over LOCAL row blocks and merge small state vectors at read
# time — no per-batch activation gather (asserted: gather_outputs never
# fires for this all-mergeable chain). Results must be identical across
# processes and match the single-process run.
import json
from paddle_tpu.parallel import spmd
from paddle_tpu.parallel.spmd import globalize_batch
from paddle_tpu.trainer.evaluators import EvaluatorChain

gather_calls = [0]
_orig_gather = spmd.gather_outputs
def _counting_gather(*a, **k):
    gather_calls[0] += 1
    return _orig_gather(*a, **k)
spmd.gather_outputs = _counting_gather

chain = EvaluatorChain(trainer.config.model_config)
chain.start()
provider = trainer._provider(for_test=False)
for batch in provider.batches():
    b = globalize_batch(batch, trainer._mesh)
    if b is None:
        continue
    outputs = trainer.test_fwd(trainer.params, b)
    trainer._eval_outputs(chain, outputs)
res = chain.results()
res["_gather_calls"] = gather_calls[0]
spmd.gather_outputs = _orig_gather
with open(os.path.join(ws, "eval_p%d.json" % pid), "w") as f:
    json.dump(res, f)

if jax.process_index() == 0:
    import numpy as np
    np.savez(os.path.join(ws, "mp_params.npz"),
             **{{k: np.asarray(v) for k, v in trainer.params.items()}})
print("WORKER_OK", pid, flush=True)
"""


def _write_config(ws):
    train_list = os.path.join(ws, "train.list")
    with open(train_list, "w") as f:
        f.write("1\n2\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={train_list!r}, test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.05)
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    path = os.path.join(ws, "cfg.py")
    with open(path, "w") as f:
        f.write(src)
    return path


def test_two_process_training_matches_single(tmp_path):
    mp_harness.skip_unless_cross_process_computations()
    ws = str(tmp_path)
    cfg_path = _write_config(ws)
    sys.path.insert(0, PROVIDERS)

    # single-process reference on the same 8-device mesh (this pytest
    # process already has 8 virtual devices via conftest)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    FLAGS.save_dir = ""
    FLAGS.mesh_shape = "data=8"
    FLAGS.log_period = 0
    FLAGS.seed = 7
    try:
        ref = Trainer(parse_config(cfg_path))
        ref.train(num_passes=1)
    finally:
        FLAGS.mesh_shape = ""
        sys.path.remove(PROVIDERS)

    outs = mp_harness.run_two_workers(
        WORKER.format(repo=REPO, providers=PROVIDERS), ws)
    # BarrierStat skew line logged at pass end on every host
    assert any("BarrierStat" in err for _, _, err in outs), outs[0][2][-2000:]

    with np.load(os.path.join(ws, "mp_params.npz")) as z:
        mp_params = {k: z[k] for k in z.files}
    for name, ref_v in ref.params.items():
        np.testing.assert_allclose(
            np.asarray(ref_v), mp_params[name], rtol=2e-4, atol=1e-5,
            err_msg=name,
        )

    # merged evaluator metrics: identical on every process, and the
    # classification error matches the single-process run over the same
    # data with the (numerically near-identical) final parameters
    import json
    from paddle_tpu.trainer.evaluators import EvaluatorChain

    with open(os.path.join(ws, "eval_p0.json")) as f:
        eval_p0 = json.load(f)
    with open(os.path.join(ws, "eval_p1.json")) as f:
        eval_p1 = json.load(f)
    assert eval_p0 == eval_p1, (eval_p0, eval_p1)
    assert eval_p0, "no evaluator results produced"
    # the chain is all-mergeable (classification_error): local rows +
    # state merge, never a per-batch activation gather
    assert eval_p0.pop("_gather_calls") == 0
    eval_p1.pop("_gather_calls")

    sys.path.insert(0, PROVIDERS)
    try:
        chain = EvaluatorChain(ref.config.model_config)
        chain.start()
        provider = ref._provider(for_test=False)
        for batch in provider.batches():
            chain.eval_batch(ref.test_fwd(ref.params, batch))
        ref_results = chain.results()
    finally:
        sys.path.remove(PROVIDERS)
    for k, v in ref_results.items():
        assert abs(eval_p0[k] - v) <= 5e-3, (k, eval_p0[k], v)
