"""Synthetic bag-of-words text-classification provider (quick_start-shaped:
compare /root/reference/demo/quick_start/dataprovider_bow.py's contract).

"files" are seeds; samples are linearly separable bags of word ids so a
logistic regression must reach low error.
"""

import random

from paddle_tpu.data import (
    integer_value,
    integer_value_sequence,
    provider,
    sparse_binary_vector,
)

DICT_DIM = 100


@provider(input_types=[sparse_binary_vector(DICT_DIM), integer_value(2)])
def process(settings, filename):
    seed = int(filename)
    rng = random.Random(seed)
    for _ in range(400):
        label = rng.randint(0, 1)
        # class-dependent vocabulary halves with a little noise
        lo, hi = (0, DICT_DIM // 2) if label == 0 else (DICT_DIM // 2, DICT_DIM)
        words = {rng.randrange(lo, hi) for _ in range(rng.randint(5, 15))}
        words |= {rng.randrange(0, DICT_DIM) for _ in range(2)}
        yield [sorted(words), label]


@provider(input_types=[integer_value_sequence(DICT_DIM), integer_value(2)])
def process_seq(settings, filename):
    seed = int(filename)
    rng = random.Random(seed)
    for _ in range(200):
        label = rng.randint(0, 1)
        lo, hi = (0, DICT_DIM // 2) if label == 0 else (DICT_DIM // 2, DICT_DIM)
        length = rng.randint(3, 20)
        seq = [rng.randrange(lo, hi) for _ in range(length)]
        yield [seq, label]
