"""Pass-end model-TFLOP/s + MFU logging (Trainer._count_model_flops).

The trainer accumulates analytic model matmul FLOPs per batch (jaxpr
walk, cached by shape signature) and appends 'model X TFLOP/s[, MFU Y]'
to the pass-done log line. MFU appears only when the device peak is
known (never on CPU), so here we assert the FLOP accounting itself and
the note formatting.
"""

from demo_utils import setup_demo, train_demo


def test_pass_flops_accumulate_and_note(tmp_path):
    setup_demo(tmp_path, "quick_start", ["train-seed-1"], ["test-seed-1"])
    trainer, _ = train_demo(tmp_path, "trainer_config.lr.py", num_passes=1)
    # one pass over 1000 samples, batch 64: flops counted for every batch
    assert trainer._pass_flops > 0
    # two cached signatures at most (full batches + the 40-sample tail)
    assert 1 <= len(trainer._flops_cache) <= 2, trainer._flops_cache
    per_batch = max(v for v in trainer._flops_cache.values())
    # LR model ~ dims known loosely: fwd+bwd of [64,1000-ish bow] x fc;
    # just require a sane magnitude and the full-batch > tail-batch order
    assert per_batch > 1e4
    # training time accumulated from the step windows only
    assert trainer._pass_train_s > 0
    # note formatting: TFLOP/s always, MFU absent on CPU (unknown peak)
    note = trainer._mfu_note()
    assert note.startswith(", model ") and "TFLOP/s" in note
    assert "MFU" not in note  # CPU device kind has no published peak


def test_mfu_note_empty_without_accounting(tmp_path):
    setup_demo(tmp_path, "quick_start", ["train-seed-1"], ["test-seed-1"])
    trainer, _ = train_demo(tmp_path, "trainer_config.lr.py", num_passes=1)
    trainer._pass_flops = 0.0
    assert trainer._mfu_note() == ""
    # a partially-failed accounting suppresses the note entirely
    trainer._pass_flops = 1e9
    trainer._pass_train_s = 1.0
    trainer._pass_flops_incomplete = True
    assert trainer._mfu_note() == ""
