"""Serving telemetry (doc/observability.md "Serving telemetry"):
request/serve_window schema + driver determinism, saturation behavior,
the `paddle serve-report` analyzer with its roofline join, `--follow`
on serve streams, `paddle compare` serve-artifact semantics, the
embedding API's request records, and the CPU `bench.py serve` e2e
smoke (the acceptance path: a run dir serve-report can render with
recompiles=0 after warmup)."""

import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import serving
from paddle_tpu.observability.analyze import analyze, follow, load_run

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")


def _fixed_launch(service_s=0.05, tokens=5):
    """Deterministic injected service time: the rung becomes a pure
    function of the seed (the determinism contract under test)."""

    def launch(requests):
        return [tokens] * len(requests), service_s

    return launch


def _validated_records(run_dir):
    recs = [r for recs in load_run(run_dir).values() for r in recs]
    assert recs, f"no records under {run_dir}"
    for rec in recs:
        assert not obs.validate_record(rec), (rec, obs.validate_record(rec))
    return recs


# ------------------------------------------------------------- schedule


def test_arrival_schedule_deterministic_and_rate_shaped():
    a = serving.arrival_offsets(500, 20.0, seed=3)
    b = serving.arrival_offsets(500, 20.0, seed=3)
    assert np.array_equal(a, b)
    assert not np.array_equal(a, serving.arrival_offsets(500, 20.0, seed=4))
    # offsets are cumulative (sorted) and the mean inter-arrival matches
    # the offered rate to sampling noise
    assert np.all(np.diff(a) >= 0)
    assert abs(np.diff(a, prepend=0.0).mean() - 1 / 20.0) < 0.01


def test_same_seed_same_cohort_assignment():
    def run():
        _, reqs = serving.run_rung(
            _fixed_launch(0.03), rate_rps=100.0, n_requests=60, seed=11,
            max_batch=4, timeout_s=10.0,
        )
        return [(r.rid, r.cohort, r.cohort_size, r.outcome,
                 round(r.t_enqueue, 9), round(r.t_admit, 9)) for r in reqs]

    first, second = run(), run()
    assert first == second
    # the load is high enough that cohorts actually batch (the test
    # would pass vacuously if every cohort had one request)
    assert any(c[2] > 1 for c in first)


def test_saturation_rejects_timeouts_and_queue_wait_dominate(tmp_path):
    obs.configure(str(tmp_path))
    summary, reqs = serving.run_rung(
        _fixed_launch(0.5), rate_rps=1000.0, n_requests=40, seed=5,
        max_batch=4, timeout_s=2.0, queue_cap=20, beam_size=2,
    )
    outcomes = {r.outcome for r in reqs}
    assert "rejected" in outcomes and "timeout" in outcomes
    assert summary["rejected"] > 0 and summary["timeouts"] > 0
    # offered load >> capacity: completed requests spent most of their
    # end-to-end time waiting in the queue
    assert summary["queue_wait_share"] > 0.5
    recs = _validated_records(str(tmp_path))
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    assert len(by_kind["request"]) == 40  # every arrival leaves evidence
    assert {r["outcome"] for r in by_kind["request"]} == {
        "ok", "rejected", "timeout"
    }
    ok = [r for r in by_kind["request"] if r["outcome"] == "ok"]
    for r in ok:
        assert r["ttft_s"] == pytest.approx(r["queue_wait_s"] + r["decode_s"])
        assert r["cohort_size"] >= 1 and r["beam_size"] == 2
    (w,) = by_kind["serve_window"]
    assert w["arrived"] == 40
    assert w["completed"] == len(ok)
    assert w["latency"]["count"] == len(ok)
    # admitted = joined a cohort: rejected/timed-out requests never were
    assert w["admitted"] == len(ok)
    assert w["admitted"] < w["arrived"]


def test_expired_queue_entries_free_capped_slots():
    """An entry that expired before a later arrival must not occupy a
    capped queue slot: queue_cap=1, timeout 2s, 10s launches — request B
    (t=1) expires at t=3, so D (t=8) gets B's slot instead of a
    spurious rejection."""
    arrivals = iter([0.0, 1.0, 8.0])

    def sched(n, rate, seed):
        return np.array([next(arrivals) for _ in range(n)])

    real = serving.arrival_offsets
    serving.arrival_offsets = sched
    try:
        summary, reqs = serving.run_rung(
            _fixed_launch(10.0), rate_rps=1.0, n_requests=3, seed=0,
            max_batch=1, timeout_s=2.0, queue_cap=1,
        )
    finally:
        serving.arrival_offsets = real
    by = {r.rid: r.outcome for r in reqs}
    assert by["r0-0"] == "ok"       # admitted immediately
    assert by["r0-1"] == "timeout"  # expired at t=3 waiting out launch 1
    assert by["r0-2"] == "ok"       # took the freed slot — NOT rejected
    assert summary["rejected"] == 0 and summary["timeouts"] == 1
    assert summary["admitted"] == 2


def test_request_and_serve_window_schema_registration():
    assert "request" in obs.FLUSH_KINDS and "serve_window" in obs.FLUSH_KINDS
    base = {"v": obs.SCHEMA_VERSION, "host": 0, "t": 0.0}
    assert obs.validate_record(dict(base, kind="request", id="r0", outcome="ok")) == []
    missing = obs.validate_record(dict(base, kind="request"))
    assert any("id" in p for p in missing) and any("outcome" in p for p in missing)
    assert obs.validate_record(
        dict(base, kind="serve_window", rung=0, offered_rps=1.0,
             engine="static")
    ) == []
    # the engine stamp became REQUIRED with the continuous engine: two
    # engines' rungs in one stream must never be mistaken for one ladder
    missing = obs.validate_record(
        dict(base, kind="serve_window", rung=0, offered_rps=1.0))
    assert any("engine" in p for p in missing)
    assert obs.validate_record(dict(base, kind="serve_window", rung=0))
    # a non-int rung is junk the analyzers must be able to SKIP (the
    # sort keys mix rungs across hosts), not crash on
    assert obs.validate_record(
        dict(base, kind="serve_window", rung="2", offered_rps=1.0)
    )


def test_saturation_knee_is_contiguous():
    """A rung that passes ABOVE a demonstrated failure (sampling luck)
    must not overstate capacity: the knee scan stops at the first
    saturated rung."""
    def rung(rate, completed, p99):
        return {"offered_rps": rate, "arrived": 100, "completed": completed,
                "latency": {"p99": p99}}

    assert serving.saturation_knee(
        [rung(10, 100, 0.01), rung(20, 100, 0.02), rung(40, 50, 0.5)]
    ) == 20
    # 20 req/s fails the completion bar; 40 passing cannot revive it
    assert serving.saturation_knee(
        [rung(10, 100, 0.01), rung(20, 98, 0.02), rung(40, 100, 0.02)]
    ) == 10
    assert serving.saturation_knee([rung(10, 50, 0.5)]) is None


# --------------------------------------------------------- serve-report


def _write_serve_fixture(run_dir, *, recompiles=0, host_share=0.1,
                         exec_per_launch=0.05):
    """A 3-rung serve run with compile/roofline joins; every record is
    validate_record-checked before it lands (the golden fixtures must
    obey the same schema the live driver does)."""
    w = obs.MetricsWriter(run_dir, host=0)
    real_emit = w.emit

    def emit(kind, **fields):
        real_emit(kind, **fields)
        rec = {"v": obs.SCHEMA_VERSION, "kind": kind, "host": 0, "t": 0.0,
               **fields}
        assert not obs.validate_record(rec), obs.validate_record(rec)

    emit("compile", group=serving.SERVE_GROUP, sig="cafe01",
         recompiles=recompiles, trace_s=0.1, compile_s=0.4,
         flops=8.0e6, bytes_accessed=1.0e5)
    for rung, (rate, p50, p99, wait_share, occ, goodput) in enumerate([
        (10.0, 0.010, 0.020, 0.05, 2.0, 900.0),
        (40.0, 0.020, 0.050, 0.30, 3.5, 3200.0),
        (160.0, 0.200, 0.800, 0.85, 4.0, 3900.0),
    ]):
        snap = lambda v: {"count": 30, "mean": v, "p50": p50, "p99": p99,
                          "max": p99}
        emit("serve_window", rung=rung, offered_rps=rate, engine="static",
             window_s=3.0,
             arrived=30, admitted=30 if rung < 2 else 24,
             completed=30 if rung < 2 else 24,
             rejected=0 if rung < 2 else 4, timeouts=0 if rung < 2 else 2,
             errors=0, launches=10, exec_s=exec_per_launch * 10,
             gen_tokens=int(goodput * 3), goodput_tok_s=goodput,
             completed_rps=10.0, queue_wait_share=wait_share,
             host_share=host_share, latency=snap(p50), ttft=snap(p50),
             queue_wait=snap(p50 * wait_share),
             queue_depth={"count": 10, "mean": 2.0, "p50": 2, "p99": 6,
                          "max": 8},
             occupancy={"count": 10, "mean": occ, "p50": occ, "p99": occ,
                        "max": occ})
        for i in range(3):  # a few request records per rung
            emit("request", id=f"r{rung}-{i}", rung=rung, outcome="ok",
                 cohort=i, cohort_size=4, beam_size=3, prompt_tokens=8,
                 gen_tokens=12, t_enqueue=0.0, t_admit=0.01,
                 t_first_token=0.02, t_finish=0.02, queue_wait_s=0.01,
                 ttft_s=0.02, decode_s=0.01, e2e_s=0.02)
    emit("roofline", group=serving.SERVE_GROUP, sig="cafe01", launches=30,
         batches=30, exec_s=exec_per_launch * 30, flops_per_launch=8.0e6,
         bytes_per_launch=1.0e5, device_kind="TPU v4")
    emit("run_end", status="completed")
    w.flush()


def test_serve_report_golden_table(tmp_path, capsys):
    _write_serve_fixture(str(tmp_path))
    assert serving.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    # >= 3 rungs with latency/ttft/queue-wait/occupancy/goodput columns
    for frag in ("rung", "offered r/s", "p50 ms", "p99 ms", "ttft p50",
                 "q-wait", "occ", "goodput tok/s", "bound"):
        assert frag in out
    rows = [ln for ln in out.splitlines()
            if ln.strip().startswith(("0 ", "1 ", "2 "))]
    assert len(rows) == 3
    assert "  10.00" in rows[0] and " 160.00" in rows[2]
    assert "85.0%" in rows[2]  # queue-wait share of the saturated rung
    assert "recompiles after warmup: 0" in out
    # TPU v4 intensity 80 FLOP/B < ridge -> memory-bound via the
    # roofline join (host_share low, launches above the dispatch floor)
    assert "memory-bound" in out
    # rung 2 drops completions and blows past 5x p99: knee is rung 1
    assert "saturation knee: 40.00 req/s" in out


def test_serve_report_flags_recompiles_and_bound_overrides(tmp_path, capsys):
    _write_serve_fixture(str(tmp_path), recompiles=2, host_share=0.9,
                         exec_per_launch=0.001)
    assert serving.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "recompiles after warmup: 2" in out
    assert "signature instability" in out
    assert "host-bound" in out  # host_share > 0.5 beats everything

    # dispatch floor: host share low, launches at ~1ms -> dispatch-bound
    assert serving.classify_rung(
        {"host_share": 0.1, "launches": 10, "exec_s": 0.01},
        {"intensity": 80.0, "device_kind": "TPU v4"},
    ) == "dispatch-bound"


def test_serve_report_rejects_non_serve_dir(tmp_path, capsys):
    w = obs.MetricsWriter(str(tmp_path), host=0)
    w.emit("pass_end", pass_id=0, samples=8)
    w.flush()
    assert serving.main([str(tmp_path)]) == 1
    assert "no serve_window records" in capsys.readouterr().err


def test_metrics_analyzer_recognizes_serve_runs(tmp_path):
    _write_serve_fixture(str(tmp_path))
    doc = analyze(load_run(str(tmp_path)))
    assert doc["serve"] == {"requests": 9, "windows": 3, "rungs": 3}
    assert len(doc["serve_windows"]) == 3
    # a rerun appending to the same run dir re-emits the same request
    # ids and rungs: counts stay latest-wins, never 2x
    _write_serve_fixture(str(tmp_path))
    doc = analyze(load_run(str(tmp_path)))
    assert doc["serve"] == {"requests": 9, "windows": 3, "rungs": 3}
    from paddle_tpu.observability.analyze import _fmt_table

    table = _fmt_table(doc)
    assert "serve telemetry: 9 request record(s)" in table
    assert "paddle serve-report" in table


def test_rerun_with_shorter_ladder_leaves_no_ghost_rungs(tmp_path):
    """A new run_start supersedes the host's earlier serve telemetry
    wholesale — a previous 3-rung sweep must not leak rung 2 into a
    later 1-rung sweep's report/knee/compare."""
    _write_serve_fixture(str(tmp_path))  # 3 rungs
    w = obs.MetricsWriter(str(tmp_path), host=0)  # new epoch: run_start
    w.emit("serve_window", rung=0, offered_rps=5.0, engine="static",
           window_s=1.0,
           arrived=4, admitted=4, completed=4, rejected=0, timeouts=0,
           errors=0, launches=2, exec_s=0.1, gen_tokens=40,
           goodput_tok_s=40.0,
           latency={"count": 4, "mean": 0.01, "p50": 0.01, "p99": 0.02,
                    "max": 0.02})
    w.emit("run_end", status="completed")
    w.flush()
    doc = analyze(load_run(str(tmp_path)))
    assert doc["serve"]["windows"] == 1 and doc["serve"]["rungs"] == 1
    assert doc["serve_windows"][0]["offered_rps"] == 5.0


def test_epoch_reset_covers_run_end_and_compile_joins(tmp_path):
    """The run_start epoch reset is wholesale: a crashed rerun is NOT
    reported completed on the strength of the previous epoch's run_end,
    and a previous sweep's recompile does not flag signature
    instability on a clean rerun."""
    _write_serve_fixture(str(tmp_path), recompiles=2)  # epoch 1: dirty
    w = obs.MetricsWriter(str(tmp_path), host=0)  # epoch 2 begins
    w.emit("compile", group=serving.SERVE_GROUP, sig="beef02",
           recompiles=0, trace_s=0.1, compile_s=0.2)
    w.emit("request", id="e2-0", rung=0, outcome="ok")
    w.flush()  # killed mid-rung: no serve_window, no run_end
    doc = analyze(load_run(str(tmp_path)))
    assert not doc["run_ended"]
    assert any("run_end" in warning for warning in doc["warnings"])
    sdoc = serving.serve_doc(load_run(str(tmp_path)))
    assert sdoc["compiles"] == 1 and sdoc["recompiles"] == 0
    # epoch 3 is oneshot-only (rung -1): the crashed epoch-2 driver is
    # superseded and this stream owes no run_end — no crash warning
    w3 = obs.MetricsWriter(str(tmp_path), host=0)
    w3.emit("request", id="e3-0", rung=-1, outcome="ok")
    w3.flush()
    doc = analyze(load_run(str(tmp_path)))
    assert not any("run_end" in warning for warning in doc["warnings"])


def test_failed_launch_leaves_error_records_and_partial_window(tmp_path):
    """A raising launch_fn must not take its cohort's evidence with it:
    terminal outcome=error records (with the failing launch's measured
    seconds) and the partial serve_window land before the re-raise."""
    obs.configure(str(tmp_path))
    calls = []

    def flaky(requests):
        calls.append(len(requests))
        if len(calls) >= 2:
            raise RuntimeError("device fell over")
        return [3] * len(requests), 0.01

    with pytest.raises(RuntimeError):
        serving.run_rung(flaky, rate_rps=500.0, n_requests=12, seed=2,
                         max_batch=4, timeout_s=10.0)
    recs = _validated_records(str(tmp_path))
    reqs = [r for r in recs if r["kind"] == "request"]
    errs = [r for r in reqs if r["outcome"] == "error"]
    assert errs and all(r["service_s"] >= 0 for r in errs)
    assert all("cohort" in r for r in errs)
    (w,) = [r for r in recs if r["kind"] == "serve_window"]
    assert w["errors"] == len(errs)
    assert w["completed"] == len(reqs) - len(errs)


# --------------------------------------------------------------- follow


def test_metrics_follow_tails_serve_stream_until_run_end(tmp_path):
    """Mirror of the PR-7 follow test for serve runs: request and
    serve_window records stream live, torn tails stay buffered, and the
    serve driver's run_end ends the tail."""
    run_dir = str(tmp_path)
    w = obs.MetricsWriter(run_dir, host=0)
    w.emit("request", id="r0-0", rung=0, outcome="ok")
    w.flush()
    path = os.path.join(run_dir, "metrics.jsonl")
    g = follow(run_dir, poll_s=0.01, max_polls=200)
    assert next(g)["kind"] == "run_start"
    rec = next(g)
    assert rec["kind"] == "request" and rec["id"] == "r0-0"
    # a complete serve_window plus a TORN request tail: the window is
    # yielded, the torn half stays buffered until its newline lands
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "serve_window", "host": 0, "t": 1.0, '
                '"rung": 0, "offered_rps": 8.0}\n'
                '{"v": 1, "kind": "requ')
    rec = next(g)
    assert rec["kind"] == "serve_window" and rec["offered_rps"] == 8.0
    with open(path, "a") as f:
        f.write('est", "host": 0, "t": 2.0, "id": "r0-1", "outcome": "ok"}\n'
                '{"v": 1, "kind": "run_end", "host": 0, "t": 3.0, '
                '"status": "completed"}\n')
    assert next(g)["id"] == "r0-1"
    assert next(g)["kind"] == "run_end"
    # the CLI stop rule: every observed host completed
    assert list(follow(run_dir, poll_s=0, max_polls=2))[-1]["kind"] == "run_end"


# -------------------------------------------------------------- compare


def test_compare_serve_artifacts_direction_aware(tmp_path):
    from paddle_tpu.observability.compare import compare, load_side

    a, b = tmp_path / "a", tmp_path / "b"
    _write_serve_fixture(str(a))
    _write_serve_fixture(str(b))
    # degrade B's rung-1 latency 3x and raise its goodput: latency is
    # lower-is-better (REGRESSION), goodput higher-is-better (IMPROVED)
    path = os.path.join(str(b), "metrics.jsonl")
    lines = open(path).read().splitlines()
    out = []
    for ln in lines:
        rec = json.loads(ln)
        if rec.get("kind") == "serve_window" and rec.get("rung") == 1:
            rec["latency"] = dict(rec["latency"], p50=0.060, p99=0.150)
            rec["ttft"] = dict(rec["ttft"], p50=0.060, p99=0.150)
            rec["goodput_tok_s"] = 4800.0
        out.append(json.dumps(rec))
    open(path, "w").write("\n".join(out) + "\n")
    doc = compare(load_side(str(a)), load_side(str(b)))
    by = {m["metric"]: m["verdict"] for m in doc["metrics"]}
    # rungs join on OFFERED LOAD (40 req/s), not index — two sweeps with
    # different auto-calibrated ladders must never cross-compare
    assert by["serve.40rps.p99_ms"] == "REGRESSION"
    assert by["serve.40rps.ttft_p99_ms"] == "REGRESSION"
    assert by["serve.40rps.goodput_tok_s"] == "IMPROVED"
    assert by["serve.10rps.p99_ms"] == "SAME"
    assert doc["verdict"] == "REGRESSION"  # exit-1 semantics upstream


def test_compare_mismatched_rate_ladders_never_cross_join(tmp_path):
    """Auto-calibrated sweeps on different machines land different
    ladders: the serve metrics must fall into only_a/only_b instead of
    judging rung k of one ladder against rung k of another."""
    from paddle_tpu.observability.compare import compare, load_side

    a, b = tmp_path / "a", tmp_path / "b"
    _write_serve_fixture(str(a))
    _write_serve_fixture(str(b))
    path = os.path.join(str(b), "metrics.jsonl")
    lines = open(path).read().splitlines()
    out = []
    for ln in lines:
        rec = json.loads(ln)
        if rec.get("kind") == "serve_window":
            rec["offered_rps"] = rec["offered_rps"] * 2  # other ladder
            rec["latency"] = dict(rec["latency"], p50=9.0, p99=9.0)
        out.append(json.dumps(rec))
    open(path, "w").write("\n".join(out) + "\n")
    doc = compare(load_side(str(a)), load_side(str(b)))
    assert not any(m["metric"].startswith("serve.") and "rps." in m["metric"]
                   for m in doc["metrics"])
    assert any(n.startswith("serve.10rps.") for n in doc["only_a"])
    assert any(n.startswith("serve.20rps.") for n in doc["only_b"])


def test_compare_serve_bench_artifacts(tmp_path):
    """The archived BENCH_*.json serve line is comparable on its own:
    per-rung latency/goodput + knee, offered-load-keyed like the
    run-dir side — a latency regression with a flat headline must not
    read NO CHANGE."""
    from paddle_tpu.observability.compare import compare, load_side

    def artifact(name, p99, knee):
        p = tmp_path / name
        p.write_text(json.dumps({
            "metric": "serve_cpu_smoke_goodput_tokens_per_sec",
            "value": 5000.0, "unit": "tokens/s", "vs_baseline": 1.0,
            "knee_rps": knee,
            "rungs": [{"offered_rps": 50.0, "p50_ms": 2.0, "p99_ms": p99,
                       "ttft_p50_ms": 2.0, "ttft_p99_ms": p99,
                       "goodput_tok_s": 5000.0, "queue_wait_share": 0.2}],
        }))
        return str(p)

    doc = compare(load_side(artifact("a.json", 4.0, 200.0)),
                  load_side(artifact("b.json", 12.0, 100.0)))
    by = {m["metric"]: m["verdict"] for m in doc["metrics"]}
    assert by["serve.50rps.p99_ms"] == "REGRESSION"
    assert by["serve_knee_rps"] == "REGRESSION"
    assert by["serve.50rps.goodput_tok_s"] == "SAME"
    assert doc["verdict"] == "REGRESSION"


def test_compare_pipeline_modes_never_cross_join(tmp_path):
    """The rung join is (engine, pipeline, offered load): one artifact
    carrying BOTH a blocking and a pipelined sweep of the same rate
    ladder keeps the modes apart (pipeline-qualified keys, never a
    blocking-vs-pipelined rung diffed against itself), and two such
    artifacts join mode-to-mode regardless of sweep order."""
    from paddle_tpu.observability.compare import compare, load_side

    def artifact(name, order):
        rungs = []
        for mode in order:
            rungs.append({
                "offered_rps": 50.0, "p50_ms": 2.0, "p99_ms": 5.0,
                "goodput_tok_s": 4000.0 if mode == "off" else 5000.0,
                "engine": "continuous", "pipeline": mode,
            })
        p = tmp_path / name
        p.write_text(json.dumps({
            "metric": "serve_cpu_smoke_goodput_tokens_per_sec",
            "value": 5000.0, "unit": "tokens/s", "vs_baseline": 1.0,
            "rungs": rungs,
        }))
        return str(p)

    # sweep order differs between the artifacts — the deterministic
    # (engine, pipeline)-sorted key assignment must still join
    # off-to-off and on-to-on
    doc = compare(load_side(artifact("a.json", ("off", "on"))),
                  load_side(artifact("b.json", ("on", "off"))))
    by = {m["metric"]: m["verdict"] for m in doc["metrics"]}
    joined = [k for k in by if k.startswith("serve.") and "rps." in k]
    assert len(joined) >= 4, by
    # identical values mode-to-mode: every joined rung metric is SAME —
    # a crosswise join would read the structural off-vs-on goodput gap
    # (4000 vs 5000, 25%) as a verdict
    assert all(by[k] == "SAME" for k in joined), by
    assert not doc["only_a"] and not doc["only_b"], doc


# ------------------------------------------------------- embedding API


def test_sequence_generator_emits_request_records(tmp_path):
    from paddle_tpu import api
    from paddle_tpu.flagship import nmt_gen_batch, nmt_gen_config

    obs.configure(str(tmp_path))
    tc = nmt_gen_config(vocab=50, dim=16, beam_size=2, max_length=4,
                        batch_size=2)
    machine = api.GradientMachine(tc.model_config)
    gen = machine.asSequenceGenerator()
    batch = nmt_gen_batch(vocab=50, B=2, T=4)
    results = gen.generate(batch)
    obs.flush()
    assert len(results) == 2
    reqs = [r for r in _validated_records(str(tmp_path))
            if r["kind"] == "request"]
    assert len(reqs) == 2
    for r in reqs:
        assert r["outcome"] == "ok"
        assert r["cohort_size"] == 2
        assert r["beam_size"] == 2
        assert r["prompt_tokens"] >= 1
        assert r["gen_tokens"] >= 1
        assert r["e2e_s"] > 0
        # the first call paid the jit trace+compile: flagged, so
        # aggregations can split compile cost from steady-state latency
        assert r["cold_start"] is True
    # both samples share the call's cohort; a second call gets a new one
    assert len({r["cohort"] for r in reqs}) == 1
    gen.generate(batch)
    obs.flush()
    reqs2 = [r for r in _validated_records(str(tmp_path))
             if r["kind"] == "request"]
    assert len({r["cohort"] for r in reqs2}) == 2
    warm = [r for r in reqs2 if r["id"] not in {x["id"] for x in reqs}]
    assert all("cold_start" not in r for r in warm)

    # a raising forward still leaves per-sample error evidence
    gen._fwd = lambda *a: (_ for _ in ()).throw(RuntimeError("boom"))
    with pytest.raises(RuntimeError):
        gen.generate(batch)
    obs.flush()
    errs = [r for r in _validated_records(str(tmp_path))
            if r["kind"] == "request" and r["outcome"] == "error"]
    assert len(errs) == 2

    # dense-only feeds (no seq_lengths) still emit: n sizes the cohort
    serving.log_oneshot([], [], 0.1, outcome="error", n=3)
    obs.flush()
    errs = [r for r in _validated_records(str(tmp_path))
            if r["kind"] == "request" and r["outcome"] == "error"]
    assert len(errs) == 5

    # an oneshot-only stream owes no run_end: `paddle metrics` must not
    # claim the run crashed nor point at serve-report (which would exit
    # 1 with zero serve_window records)
    doc = analyze(load_run(str(tmp_path)))
    assert not any("run_end" in w for w in doc["warnings"])
    from paddle_tpu.observability.analyze import _fmt_table

    table = _fmt_table(doc)
    assert "serve telemetry" in table
    assert "serve-report" not in table


# ------------------------------------------------------------ bench e2e


def test_bench_serve_e2e_cpu_acceptance(tmp_path, monkeypatch, capsys):
    """The acceptance path: `bench.py serve` on the CPU backend produces
    a run dir where serve-report renders >= 3 offered-load rungs, every
    record passes validate_record, and the serve launch group shows
    recompiles=0 after warmup (signature-stable padding)."""
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_REQUESTS", "10")
    monkeypatch.delenv("PADDLE_TPU_BENCH_METRICS_DIR", raising=False)
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    value, extras = bench.bench_serve(B=2, T=4, vocab=50, dim=16,
                                      beam_size=2, max_length=4,
                                      dtype="float32")
    # with no explicit mirror dir, bench.main() mirrors the headline
    # into the serve stream and THEN closes it — replay that here
    obs.emit("bench", metric="serve_cpu_smoke_goodput_tokens_per_sec",
             value=round(value, 1))
    obs.emit("run_end", status="completed")
    obs.flush()
    assert value > 0
    assert len(extras["rungs"]) >= 3
    assert extras["run_dir"] == str(tmp_path)

    recs = _validated_records(str(tmp_path))
    kinds = {r["kind"] for r in recs}
    assert {"request", "serve_window", "compile", "roofline",
            "run_end"} <= kinds
    compiles = [r for r in recs if r["kind"] == "compile"
                and r["group"] == serving.SERVE_GROUP]
    assert compiles and all(c["recompiles"] == 0 for c in compiles)
    assert len(compiles) == 1  # ONE signature across warmup + all rungs

    assert serving.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    rows = [ln for ln in out.splitlines()
            if ln.strip() and ln.strip().split()[0].isdigit()]
    assert len(rows) >= 3
    assert "recompiles after warmup: 0" in out
    assert "stream ends without run_end" not in out
