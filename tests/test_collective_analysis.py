"""Collective-volume analysis (benchmarks/collective_analysis.py).

The scaling story rests on these numbers being right: the HLO parser
must handle tuple-shaped (combined) all-reduces, async -start/-done
pairs (TPU post-optimization form), and in-while-body detection via the
computation graph (metadata op_name survives hoisting, so it cannot be
the signal); and the end-to-end dp=8 gradient all-reduce volume must
equal the model's parameter bytes to within the scalar loss reduction.
"""

from benchmarks.collective_analysis import (
    _shape_bytes,
    collective_bytes,
)


def test_shape_bytes_tuple_and_scalar():
    assert _shape_bytes("f32[512,128]{1,0}") == 512 * 128 * 4
    assert _shape_bytes("bf16[8]{0}") == 16
    assert _shape_bytes("(f32[4,4]{1,0}, bf16[2]{0}, f32[])") == 64 + 4 + 4
    assert _shape_bytes("f32[]") == 4  # scalar


_HLO = """HloModule jit_step

%fused_inner.7 (p0: f32[16]) -> f32[16] {
  %all-reduce.9 = f32[16]{0} all-reduce(%p0), channel_id=9
}

%region_body.1 (arg_tuple.1: (s32[], f32[512,128])) -> (s32[], f32[512,128]) {
  %all-reduce.43 = (f32[512,128]{1,0}, f32[512]{0}) all-reduce(%dot.23), channel_id=1, metadata={op_name="jit(step)/while/body"}
  %fusion.2 = f32[16]{0} fusion(%x), kind=kLoop, calls=%fused_inner.7
}

%region_cond.1 (arg: (s32[], f32[512,128])) -> pred[] {
  %lt = pred[] compare(%a, %b), direction=LT
}

ENTRY %main.24_spmd (p0: f32[2]) -> f32[2] {
  %while.1 = (s32[], f32[512,128]) while(%tuple.0), condition=%region_cond.1, body=%region_body.1
  %all-reduce.44 = f32[1000,64]{1,0} all-reduce(%scatter), channel_id=2, metadata={op_name="jit(step)/while/body/leftover_metadata"}
  %all-gather-start.1 = bf16[64,64]{1,0} all-gather-start(%p), channel_id=3
  %all-gather-done.1 = bf16[64,64]{1,0} all-gather-done(%all-gather-start.1)
  %dot.9 = f32[4,4]{1,0} dot(%a, %b)
}
"""


def test_collective_bytes_tuples_async_and_loop_context():
    cols = collective_bytes(_HLO)
    ar_count, ar_bytes, ar_loop = cols["all-reduce"]
    assert ar_count == 3
    tuple_bytes = (512 * 128 + 512) * 4
    assert ar_bytes == tuple_bytes + 1000 * 64 * 4 + 16 * 4
    # in-loop = computation-graph membership, transitively through the
    # fusion call; all-reduce.44 carries stale while/body METADATA but
    # lives in ENTRY — it must NOT be flagged (hoisted-op false positive)
    assert ar_loop == tuple_bytes + 16 * 4
    # async pair counted once, at -start
    ag_count, ag_bytes, ag_loop = cols["all-gather"]
    assert (ag_count, ag_bytes, ag_loop) == (1, 64 * 64 * 2, 0)
    assert "dot" not in cols


def test_dp8_allreduce_volume_equals_param_bytes():
    """End-to-end on the virtual 8-device mesh: pure data parallelism
    all-reduces each gradient exactly once, so total collective bytes ==
    sum of parameter sizes in f32 plus the scalar loss reduction."""
    from benchmarks.collective_analysis import _sharded_step_hlo

    from paddle_tpu.flagship import example_batch, flagship_config

    tc = flagship_config(dict_dim=500, emb_dim=32, hidden=128, classes=2,
                         mesh_shape="data=8")
    hlo = _sharded_step_hlo(tc, example_batch(dict_dim=500, B=16, T=8),
                            "data=8")
    cols = collective_bytes(hlo)
    total = sum(b for _, b, _lb in cols.values())
    pbytes = sum(p.size for p in tc.model_config.parameters) * 4
    assert pbytes <= total <= pbytes * 1.05 + 4096, (total, pbytes)
    # the recurrent dW all-reduce is inside the backward scan on CPU HLO
    # — the in-loop flag must catch it (this is the hoisting tripwire)
    assert cols["all-reduce"][2] > 0