"""Fused Pallas LSTM kernel parity vs the XLA scan path (interpret mode).

The kernel (ops/pallas_lstm.py) must reproduce layers/recurrent.py's
``lstm_cell_step`` + ``_scan_time`` semantics bit-for-tolerance: gate
order [candidate, input, forget, output], peephole bias layout, carry
masking of padded steps, reversed scans — forward values AND gradients
(the backward kernel is hand-derived, so the gradient check against
jax.grad of the scan path is the real test).
"""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.graph  # noqa: F401  (break the layers<->graph import cycle)
from paddle_tpu.layers.recurrent import _scan_time, lstm_cell_step
from paddle_tpu.ops import pallas_lstm as pk


def _cfg(reversed_=False, act="tanh", gate="sigmoid", state="sigmoid", size=128):
    return types.SimpleNamespace(
        size=size,
        reversed=reversed_,
        active_type=act,
        active_gate_type=gate,
        active_state_type=state,
    )


def _ref(cfg, x, mask, w, bias):
    """The production scan path, verbatim semantics of lstmemory_layer."""

    def cell(carry, x_t):
        h, c = carry
        h2, c2 = lstm_cell_step(cfg, x_t, h, c, w, bias)
        return (h2, c2), h2

    B = x.shape[1]
    init = (jnp.zeros((B, cfg.size), x.dtype), jnp.zeros((B, cfg.size), x.dtype))
    _, ys = _scan_time(cell, x, mask, init, cfg.reversed)
    return ys


def _rand(key, T=5, B=8, H=128, dtype=jnp.float32, with_bias=True):
    ks = jax.random.split(key, 4)
    x = jax.random.normal(ks[0], (T, B, 4 * H), dtype) * 0.5
    w = (jax.random.normal(ks[1], (H, 4 * H), dtype) * float(1.0 / np.sqrt(H))).astype(dtype)
    bias = (jax.random.normal(ks[2], (7 * H,), dtype) * 0.1) if with_bias else None
    lengths = jax.random.randint(ks[3], (B,), 1, T + 1)
    mask = (jnp.arange(T)[:, None] < lengths[None, :]).astype(dtype)
    return x, w, bias, mask


@pytest.mark.parametrize("reversed_", [False, True])
@pytest.mark.parametrize("with_bias", [True, False])
def test_forward_parity(reversed_, with_bias):
    cfg = _cfg(reversed_=reversed_)
    x, w, bias, mask = _rand(jax.random.PRNGKey(0), with_bias=with_bias)
    ref = _ref(cfg, x, mask, w, bias)
    got = pk.lstm_layer_forward(cfg, x, mask, w, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("reversed_", [False, True])
def test_gradient_parity(reversed_):
    cfg = _cfg(reversed_=reversed_)
    x, w, bias, mask = _rand(jax.random.PRNGKey(1))
    cot = jax.random.normal(jax.random.PRNGKey(2), (5, 8, 128))

    def loss_ref(x, w, bias):
        return jnp.sum(_ref(cfg, x, mask, w, bias) * cot)

    def loss_pk(x, w, bias):
        return jnp.sum(pk.lstm_layer_forward(cfg, x, mask, w, bias, interpret=True) * cot)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, bias)
    gp = jax.grad(loss_pk, argnums=(0, 1, 2))(x, w, bias)
    for r, p, name in zip(gr, gp, ("dx", "dw", "dbias")):
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(r), rtol=2e-4, atol=2e-4, err_msg=name
        )


def test_gradient_parity_no_bias_tanh_state():
    # the common DSL configuration: tanh state activation, no peepholes
    cfg = _cfg(state="tanh")
    x, w, _, mask = _rand(jax.random.PRNGKey(3), with_bias=False)
    cot = jax.random.normal(jax.random.PRNGKey(4), (5, 8, 128))

    gr = jax.grad(lambda x, w: jnp.sum(_ref(cfg, x, mask, w, None) * cot), (0, 1))(x, w)
    gp = jax.grad(
        lambda x, w: jnp.sum(
            pk.lstm_layer_forward(cfg, x, mask, w, None, interpret=True) * cot
        ),
        (0, 1),
    )(x, w)
    for r, p, name in zip(gr, gp, ("dx", "dw")):
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(r), rtol=2e-4, atol=2e-4, err_msg=name
        )


def test_bf16_forward_parity():
    cfg = _cfg()
    x, w, bias, mask = _rand(jax.random.PRNGKey(5), dtype=jnp.bfloat16)
    ref = _ref(cfg, x, mask, w, bias)
    got = pk.lstm_layer_forward(cfg, x, mask, w, bias, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), rtol=0.1, atol=0.05
    )


def test_machine_level_parity(monkeypatch):
    # whole-graph check: same params, same batch, pallas on vs off —
    # loss and every parameter gradient agree. The env var forces the
    # interpreted kernel on CPU (production non-TPU runs take the scan).
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.flagship import example_batch, flagship_config
    from paddle_tpu.graph import GradientMachine

    tc = flagship_config(dict_dim=200, emb_dim=32, hidden=128, classes=2)
    tc.opt_config.batch_size = 16
    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, pallas_rnn=True)
    params = gm_off.init_params(seed=3)
    batch = example_batch(dict_dim=200, B=16, T=12)

    calls = []
    orig = pk.lstm_layer_forward
    monkeypatch.setattr(
        pk, "lstm_layer_forward",
        lambda *a, **k: (calls.append(1), orig(*a, **k))[1],
    )
    l_off, g_off, _, _ = gm_off.grad_fn()(params, batch, None)
    assert not calls  # pallas off → scan path
    l_on, g_on, _, _ = gm_on.grad_fn()(params, batch, None)
    assert calls  # the kernel path actually engaged
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-5)
    for k in g_off:
        np.testing.assert_allclose(
            np.asarray(g_on[k]), np.asarray(g_off[k]), rtol=5e-4, atol=5e-5,
            err_msg=k,
        )


def test_remat_composes_with_kernel(monkeypatch):
    # remat="full" wraps the loss in jax.checkpoint: the custom_vjp kernel
    # must replay (forward-only primal) and still produce the same grads
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.flagship import example_batch, flagship_config
    from paddle_tpu.graph import GradientMachine

    tc = flagship_config(dict_dim=200, emb_dim=32, hidden=128, classes=2)
    gm = GradientMachine(tc.model_config, pallas_rnn=True)
    params = gm.init_params(seed=3)
    batch = example_batch(dict_dim=200, B=16, T=12)
    l0, g0, _, _ = gm.grad_fn(remat="none")(params, batch, None)
    l1, g1, _, _ = gm.grad_fn(remat="full")(params, batch, None)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    for k in g0:
        np.testing.assert_allclose(
            np.asarray(g1[k]), np.asarray(g0[k]), rtol=1e-5, atol=1e-6, err_msg=k
        )


def test_edge_lengths():
    # zero-length sequences (all steps masked), T=1, and full-length rows
    # in one batch — carry stays at init for masked steps, matching scan
    cfg = _cfg()
    T, B, H = 3, 8, 128
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    x = jax.random.normal(ks[0], (T, B, 4 * H)) * 0.5
    w = jax.random.normal(ks[1], (H, 4 * H)) * 0.05
    bias = jax.random.normal(ks[2], (7 * H,)) * 0.1
    lengths = jnp.asarray([0, 1, 3, 2, 0, 3, 1, 2], jnp.int32)
    mask = (jnp.arange(T)[:, None] < lengths[None, :]).astype(x.dtype)
    ref = _ref(cfg, x, mask, w, bias)
    got = pk.lstm_layer_forward(cfg, x, mask, w, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # zero-length rows emit exactly zeros
    np.testing.assert_array_equal(np.asarray(got)[:, 0], 0.0)
    np.testing.assert_array_equal(np.asarray(got)[:, 4], 0.0)

    # T=1
    ref1 = _ref(cfg, x[:1], mask[:1], w, bias)
    got1 = pk.lstm_layer_forward(cfg, x[:1], mask[:1], w, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(ref1), rtol=2e-5, atol=2e-5)


def test_unsupported_shapes_fall_back():
    # H not a lane multiple → usable() false; the layer silently uses scan
    assert not pk.usable(_cfg(size=96), jnp.zeros((4, 8, 384)))
    assert not pk.usable(_cfg(size=128), jnp.zeros((4, 6, 512)))  # B % 8
    assert pk.usable(_cfg(size=128), jnp.zeros((4, 8, 512)))
