"""Async SGD analog (algorithm='async_sgd' → local SGD over the data
mesh axis, paddle_tpu/parallel/local_sgd.py).

Semantics pinned here:
- merge period 1 with a linear-in-gradient method (momentum) reproduces
  sync SGD exactly (averaging after linear local updates == updating
  with the mean gradient);
- longer merge periods still converge on a separable problem;
- the drift gate (async_lagged_grad_discard_ratio analog of the
  reference's stale-gradient discard, TrainerConfig.proto.m4:124-129)
  excludes a diverged replica from the merge and reports it;
- the DSL surface (settings(is_async=True, ...)) reaches
  OptimizationConfig.
"""

import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS


PROVIDER = """
import numpy as np
from paddle_tpu.data import provider, dense_vector, integer_value

@provider(input_types=[dense_vector(20), integer_value(3)],
          should_shuffle=False)
def process(settings, filename):
    rng = np.random.RandomState(11)
    for _ in range(192):
        y = rng.randint(0, 3)
        x = (rng.randn(20) * 0.4 + y).astype(np.float32)
        yield x.tolist(), int(y)
"""


def _config(tmp_path, is_async, period=1, ratio=None):
    train_list = tmp_path / "train.list"
    train_list.write_text("a\n")
    extra = f", async_lagged_grad_discard_ratio={ratio}" if ratio is not None else ""
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list=None,
                            module="lsgdprov", obj="process")
    settings(batch_size=16, learning_rate=0.05,
             learning_method=MomentumOptimizer(momentum=0.9),
             is_async={is_async},
             num_batches_per_send_parameter={period}{extra})
    data = data_layer(name="x", size=20)
    h = fc_layer(input=data, size=8, act=TanhActivation(), name="h")
    output = fc_layer(input=h, size=3, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=3)
    outputs(classification_cost(input=output, label=label))
    """)
    p = tmp_path / f"cfg_async{int(is_async)}_{period}_{ratio}.py"
    p.write_text(src)
    return str(p)


@pytest.fixture()
def ws(tmp_path):
    (tmp_path / "lsgdprov.py").write_text(PROVIDER)
    sys.path.insert(0, str(tmp_path))
    yield tmp_path
    sys.path.remove(str(tmp_path))


def _train(tmp_path, is_async, period=1, ratio=None, passes=2, stats_period=0):
    FLAGS.save_dir = ""
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.mesh_shape = "data=8"
    prev_stats = FLAGS.show_parameter_stats_period
    FLAGS.show_parameter_stats_period = stats_period
    try:
        cfg = parse_config(_config(tmp_path, is_async, period, ratio))
        tr = Trainer(cfg)
        tr.train(num_passes=passes)
        return tr, {k: np.asarray(v) for k, v in tr.params.items()}
    finally:
        FLAGS.mesh_shape = ""
        FLAGS.show_parameter_stats_period = prev_stats


def test_async_period1_matches_sync_momentum(ws):
    """Merge period 1 + momentum == sync SGD: local updates are linear in
    the gradient, so post-update averaging equals the mean-gradient
    update, bit-for-bit up to float reassociation."""
    _, p_sync = _train(ws, is_async=False)
    tr, p_async = _train(ws, is_async=True, period=1)
    assert tr._async, "async mode should be active under data=8"
    # the staleness gate must NOT fire on healthy stochastic variation
    assert tr._lsgd_discarded == 0
    assert set(p_sync) == set(p_async)
    for k in p_sync:
        np.testing.assert_allclose(p_async[k], p_sync[k], rtol=2e-4,
                                   atol=2e-5, err_msg=k)


def test_async_period4_converges(ws):
    """Merge every 4 batches: replicas diverge between merges but the
    averaged model still learns the (separable) problem."""
    tr, p_async = _train(ws, is_async=True, period=4, passes=3)
    # canonical params materialized (flushed) after training
    for k, v in p_async.items():
        assert v.ndim <= 2, f"{k} left stacked: {v.shape}"
    # train cost on a fresh sweep must beat the ~log(3) random baseline
    provider = tr._provider(for_test=False)
    cost, _, _ = tr._full_data_sweep(tr.params, provider, want_grad=False)
    assert cost < 0.7, f"local SGD failed to learn: cost {cost}"


def test_observability_does_not_perturb_async_numerics(ws):
    """Mid-pass stats/test hooks read a PASSIVE merged view: turning on
    show_parameter_stats_period must reproduce the exact parameters of a
    run without it — a logging flag must not cut the merge period short
    (the reference pserver's test path read merged params without
    collapsing trainers' local progress)."""
    _, plain = _train(ws, is_async=True, period=4)
    _, with_stats = _train(ws, is_async=True, period=4, stats_period=3)
    for k in plain:
        np.testing.assert_array_equal(plain[k], with_stats[k], err_msg=k)


def test_drift_gate_discards_outlier():
    """One replica pushed far from the rest is excluded by the gate and
    counted; with the gate disabled (ratio<=0) it contaminates the mean."""
    from paddle_tpu.parallel.local_sgd import LocalSgd
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh("data=8")
    base = np.tile(np.arange(4, dtype=np.float32), (8, 1))  # identical
    noise = np.linspace(-0.01, 0.01, 8, dtype=np.float32)[:, None]
    stacked = base + noise
    outlier = stacked.copy()
    outlier[5] += 100.0

    gated = LocalSgd.__new__(LocalSgd)
    gated.mesh, gated.R, gated.ratio = mesh, 8, 1.5
    from jax.sharding import NamedSharding, PartitionSpec as P

    gated._stacked = NamedSharding(mesh, P("data"))
    gated._repl = NamedSharding(mesh, P())
    gated._merge_fn = None
    new_p, _, discarded = gated.merge({"w": outlier.copy()}, {})
    assert int(discarded) == 1
    merged = np.asarray(new_p["w"])
    # all replicas identical after merge, equal to the mean of the 7 kept
    expect = np.delete(outlier, 5, axis=0).mean(0)
    np.testing.assert_allclose(merged[0], expect, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(merged[5], expect, rtol=1e-5, atol=1e-6)

    ungated = LocalSgd.__new__(LocalSgd)
    ungated.mesh, ungated.R, ungated.ratio = mesh, 8, 0.0
    ungated._stacked = gated._stacked
    ungated._repl = gated._repl
    ungated._merge_fn = None
    new_p2, _, discarded2 = ungated.merge({"w": outlier.copy()}, {})
    assert int(discarded2) == 0
    np.testing.assert_allclose(
        np.asarray(new_p2["w"])[0], outlier.mean(0), rtol=1e-5, atol=1e-6
    )


def test_drift_gate_discards_nan_replica():
    """A replica with a non-finite parameter must be discarded and must
    NOT poison the merge (a plain-median anchor would turn every
    replica's drift NaN, reject everyone, and average the NaN in through
    the keep-everyone fallback)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel.local_sgd import LocalSgd
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh("data=8")
    base = np.tile(np.arange(4, dtype=np.float32), (8, 1))
    base += np.linspace(-0.01, 0.01, 8, dtype=np.float32)[:, None]
    poisoned = base.copy()
    poisoned[3, 2] = np.nan

    lsgd = LocalSgd.__new__(LocalSgd)
    lsgd.mesh, lsgd.R, lsgd.ratio = mesh, 8, 1.5
    lsgd._stacked = NamedSharding(mesh, P("data"))
    lsgd._repl = NamedSharding(mesh, P())
    lsgd._merge_fn = None
    new_p, _, discarded = lsgd.merge({"w": poisoned.copy()}, {})
    assert int(discarded) == 1
    merged = np.asarray(new_p["w"])
    assert np.isfinite(merged).all(), "NaN replica poisoned the merge"
    expect = np.delete(base, 3, axis=0).mean(0)
    np.testing.assert_allclose(merged[3], expect, rtol=1e-5, atol=1e-6)


def test_is_async_reaches_opt_config(ws):
    cfg = parse_config(_config(ws, is_async=True, period=3, ratio=2.0))
    assert cfg.opt_config.algorithm == "async_sgd"
    assert cfg.opt_config.num_batches_per_send_parameter == 3
    assert cfg.opt_config.async_lagged_grad_discard_ratio == 2.0


def test_async_merge_period_not_rejected_as_accumulation(ws):
    """In async mode num_batches_per_send_parameter is the merge period
    (its reference meaning), so combining it with batches_per_launch
    must not trip the accumulation/fuse conflict check — fuse is simply
    ignored (mesh + async are not single-chip dispatch paths)."""
    FLAGS.save_dir = ""
    FLAGS.mesh_shape = "data=8"
    try:
        cfg = parse_config(_config(ws, is_async=True, period=4))
        cfg.opt_config.batches_per_launch = 8
        tr = Trainer(cfg)
        assert tr._async and tr._sync_n == 4
        assert tr._accum_n == 1 and tr._fuse_k == 1
    finally:
        FLAGS.mesh_shape = ""
