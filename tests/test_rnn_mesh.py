"""RNN/attention models under a device mesh — the dryrun composition,
continuously tested.

Round 1's multichip gate exercised an LSTM over data×model and ring
attention over data×seq only from __graft_entry__; these tests keep the
same compositions in the suite AND assert sharded == unsharded numerics
(the loopback-pserver methodology of the reference,
/root/reference/paddle/trainer/tests/test_TrainerOnePass.cpp:120-296).
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.flagship import example_batch, flagship_config
from paddle_tpu.graph import GradientMachine
from paddle_tpu.optimizer import Updater
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.spmd import shard_train_step


def _step_fns(tc, seed=1):
    gm = GradientMachine(tc.model_config)
    updater = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=seed)
    opt_state = updater.init_state(params)
    grad_fn = gm.grad_fn()

    def step(params, opt_state, batch, rng, bs):
        loss, grads, outputs, state_updates = grad_fn(params, batch, rng)
        new_params, new_opt = updater(params, grads, opt_state, bs)
        for k, v in state_updates.items():
            new_params[k] = v
        return new_params, new_opt, loss, outputs["output"].value

    return gm, step, params, opt_state


def test_lstm_data_model_parallel_matches_single():
    """Flagship LSTM: sharded (data=4,model=2, emb+softmax over 'model')
    train step == unsharded train step."""
    B, T = 8, 16
    rng = jax.random.PRNGKey(0)
    batch = example_batch(B=B, T=T)

    tc = flagship_config()
    gm0, step0, params0, opt0 = _step_fns(tc)
    p_ref, _, loss_ref, out_ref = jax.jit(step0)(
        params0, opt0, batch, rng, jnp.asarray(float(B))
    )

    tc2 = flagship_config(mesh_shape="data=4,model=2")
    for p in tc2.model_config.parameters:
        if p.name == "emb":
            p.sharding = [None, "model"]
        if p.name == "_output.w0":
            p.sharding = ["model", None]
    gm2, step2, params2, opt2 = _step_fns(tc2)
    mesh = make_mesh("data=4,model=2")
    sharded = shard_train_step(step2, mesh, gm2)
    p_sh, _, loss_sh, out_sh = sharded(params2, opt2, batch, rng, jnp.asarray(float(B)))

    np.testing.assert_allclose(float(loss_ref), float(loss_sh), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(out_ref), np.asarray(out_sh), rtol=1e-3, atol=1e-5
    )
    for name in ("emb", "_output.w0"):
        np.testing.assert_allclose(
            np.asarray(p_ref[name]), np.asarray(p_sh[name]), rtol=1e-3, atol=1e-5,
            err_msg=name,
        )


def test_attention_data_seq_parallel_matches_single():
    """Ring-attention model on data=2,seq=4: loss matches the meshless
    full-attention run."""
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        MaxPooling,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        multi_head_attention_layer,
        outputs,
        pooling_layer,
        settings,
    )

    def build():
        with fresh_context() as ctx:
            settings(batch_size=8, learning_rate=1e-3)
            words = data_layer(name="words", size=500)
            emb = embedding_layer(input=words, size=32)
            att = multi_head_attention_layer(
                input=emb, num_heads=4, causal=True, seq_parallel="ring", name="att"
            )
            pool = pooling_layer(input=att, pooling_type=MaxPooling())
            out = fc_layer(input=pool, size=4, act=SoftmaxActivation(), name="output")
            label = data_layer(name="label", size=4)
            outputs(classification_cost(input=out, label=label))
            return ctx.finalize()

    T = 32  # divides seq=4
    batch = example_batch(dict_dim=500, B=8, T=T, classes=4, seed=1)

    losses = {}
    for mesh_shape in (None, "data=2,seq=4"):
        tc = build()
        gm, step, params, opt_state = _step_fns(tc, seed=2)
        if mesh_shape:
            gm.mesh = make_mesh(mesh_shape)
        _, _, loss, _ = jax.jit(step)(
            params, opt_state, batch, jax.random.PRNGKey(1), jnp.asarray(8.0)
        )
        losses[mesh_shape] = float(loss)
    assert np.isfinite(losses["data=2,seq=4"])
    np.testing.assert_allclose(losses[None], losses["data=2,seq=4"], rtol=1e-4)


def test_attention_seq_parallel_bf16_remat_composes():
    """The full long-context stack composes: bf16 mixed precision +
    remat="full" + ring attention over a data=2,seq=4 mesh; loss tracks
    the meshless f32 run within bf16 tolerance."""
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.graph.machine import compute_dtype_of
    from paddle_tpu.trainer_config_helpers import (
        MaxPooling,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        multi_head_attention_layer,
        outputs,
        pooling_layer,
        settings,
    )

    def build(dtype, remat):
        with fresh_context() as ctx:
            settings(batch_size=8, learning_rate=1e-3, dtype=dtype, remat=remat)
            words = data_layer(name="words", size=500)
            emb = embedding_layer(input=words, size=32)
            att = multi_head_attention_layer(
                input=emb, num_heads=4, causal=True, seq_parallel="ring", name="att"
            )
            pool = pooling_layer(input=att, pooling_type=MaxPooling())
            out = fc_layer(input=pool, size=4, act=SoftmaxActivation(), name="output")
            label = data_layer(name="label", size=4)
            outputs(classification_cost(input=out, label=label))
            return ctx.finalize()

    batch = example_batch(dict_dim=500, B=8, T=32, classes=4, seed=3)
    losses = {}
    for key, (dtype, remat, mesh_shape) in {
        "f32": ("float32", "none", None),
        "bf16+remat+mesh": ("bfloat16", "full", "data=2,seq=4"),
    }.items():
        tc = build(dtype, remat)
        gm = GradientMachine(tc.model_config, compute_dtype=compute_dtype_of(tc.opt_config))
        up = Updater(tc.opt_config, tc.model_config)
        params = gm.init_params(seed=4)
        opt_state = up.init_state(params)
        grad_fn = gm.grad_fn(remat=tc.opt_config.remat)

        def step(params, opt_state, batch, rng, bs):
            loss, grads, outputs, su = grad_fn(params, batch, rng)
            new_params, new_opt = up(params, grads, opt_state, bs)
            return new_params, new_opt, loss, outputs["att"].value

        if mesh_shape:
            gm.mesh = make_mesh(mesh_shape)
        _, _, loss, att = jax.jit(step)(
            params, opt_state, batch, jax.random.PRNGKey(1), jnp.asarray(8.0)
        )
        losses[key] = float(loss)
        if dtype == "bfloat16":
            assert att.dtype == jnp.bfloat16
    np.testing.assert_allclose(losses["f32"], losses["bf16+remat+mesh"], rtol=0.03, atol=0.02)
