"""Gradient-check sweep over the layer-type tail.

The reference's standing op test is test_LayerGrad.cpp: every layer type
gets a tiny net + finite-difference gradient check. The big families
(fc/conv/pool/bn/recurrent/costs/sequence ops) are covered throughout the
suite; this sweep closes the tail — layer types no demo or other test
constructs — with the same methodology via GradientMachine.check_gradient
(float64 finite differences, Trainer::checkGradient analog).

Forward-only types (samplers/selectors with no parameters or no
meaningful cotangent) get shape/finiteness assertions instead.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.graph import GradientMachine
from paddle_tpu.graph.argument import Argument

B = 4

HEAD = """
from paddle.trainer_config_helpers import *
settings(batch_size=4, learning_rate=0.1)
"""

# cases: (name, config body, feed builder). Bodies end with outputs(...)
# over a differentiable cost so check_gradient has a scalar loss.
TAIL = """
out = fc_layer(input=top, size=3, act=SoftmaxActivation(), name='out')
outputs(classification_cost(input=out, label=data_layer('label', size=3)))
"""


def _r(shape, seed=0, positive=False):
    v = np.random.RandomState(seed).rand(*shape).astype(np.float32)
    return jnp.asarray(v + 0.1 if positive else v - 0.5)


def _labels(n=3, seed=1):
    return jnp.asarray(np.random.RandomState(seed).randint(0, n, (B,)), jnp.int32)


CASES = {
    "interpolation": (
        "w = fc_layer(input=data_layer('win', size=4), size=1,"
        " act=SigmoidActivation(), name='w')\n"
        "a = fc_layer(input=data_layer('ain', size=8), size=8, name='a')\n"
        "b = fc_layer(input=data_layer('bin', size=8), size=8, name='b')\n"
        "top = interpolation_layer(input=[a, b], weight=w)\n" + TAIL,
        lambda: {"win": Argument(value=_r((B, 4), 0)),
                 "ain": Argument(value=_r((B, 8), 1)),
                 "bin": Argument(value=_r((B, 8), 2)),
                 "label": Argument(ids=_labels())},
    ),
    "power": (
        "w = data_layer('w', size=1)\n"
        "a = fc_layer(input=data_layer('ain', size=8), size=8,"
        " act=SigmoidActivation(), name='a')\n"
        "top = power_layer(input=a, weight=w)\n" + TAIL,
        lambda: {"w": Argument(value=_r((B, 1), 0, True)),
                 "ain": Argument(value=_r((B, 8), 1)),
                 "label": Argument(ids=_labels())},
    ),
    "sum_to_one_norm": (
        "a = fc_layer(input=data_layer('ain', size=8), size=8,"
        " act=SigmoidActivation(), name='a')\n"
        "top = sum_to_one_norm_layer(input=a)\n" + TAIL,
        lambda: {"ain": Argument(value=_r((B, 8), 1)),
                 "label": Argument(ids=_labels())},
    ),
    "slope_intercept": (
        "a = fc_layer(input=data_layer('ain', size=8), size=8, name='a')\n"
        "top = slope_intercept_layer(input=a, slope=2.0, intercept=0.5)\n" + TAIL,
        lambda: {"ain": Argument(value=_r((B, 8), 1)),
                 "label": Argument(ids=_labels())},
    ),
    "conv_shift": (
        "a = fc_layer(input=data_layer('ain', size=8), size=8, name='a')\n"
        "b = fc_layer(input=data_layer('bin', size=4), size=3, name='b')\n"
        "top = conv_shift_layer(input=[a, b])\n" + TAIL,
        lambda: {"ain": Argument(value=_r((B, 8), 1)),
                 "bin": Argument(value=_r((B, 4), 2)),
                 "label": Argument(ids=_labels())},
    ),
    "tensor": (
        "a = data_layer('a', size=5)\n"
        "b = data_layer('b', size=4)\n"
        "top = tensor_layer(input=[a, b], size=6)\n" + TAIL,
        lambda: {"a": Argument(value=_r((B, 5), 1)),
                 "b": Argument(value=_r((B, 4), 2)),
                 "label": Argument(ids=_labels())},
    ),
    "convex_comb": (
        "w = fc_layer(input=data_layer('win', size=4), size=2,"
        " act=SoftmaxActivation(), name='w')\n"
        "v = fc_layer(input=data_layer('vin', size=8), size=16, name='v')\n"
        "top = convex_comb_layer(input=[w, v], size=8)\n" + TAIL,
        lambda: {"win": Argument(value=_r((B, 4), 1)),
                 "vin": Argument(value=_r((B, 8), 2)),
                 "label": Argument(ids=_labels())},
    ),
    "multiplex": (
        "idx = data_layer('idx', size=2)\n"
        "i1 = fc_layer(input=data_layer('x1', size=8), size=6, name='i1')\n"
        "i2 = fc_layer(input=data_layer('x2', size=8), size=6, name='i2')\n"
        "top = multiplex_layer(input=[idx, i1, i2])\n" + TAIL,
        lambda: {"idx": Argument(ids=jnp.asarray([0, 1, 0, 1], jnp.int32)),
                 "x1": Argument(value=_r((B, 8), 1)),
                 "x2": Argument(value=_r((B, 8), 2)),
                 "label": Argument(ids=_labels())},
    ),
    "out_prod": (
        "a = fc_layer(input=data_layer('ain', size=8), size=4, name='a')\n"
        "b = fc_layer(input=data_layer('bin', size=8), size=3, name='b')\n"
        "top = out_prod_layer(a, b)\n" + TAIL,
        lambda: {"ain": Argument(value=_r((B, 8), 1)),
                 "bin": Argument(value=_r((B, 8), 2)),
                 "label": Argument(ids=_labels())},
    ),
    "rank-cost": (
        "left = fc_layer(input=data_layer('a', size=8), size=1, name='left')\n"
        "right = fc_layer(input=data_layer('b', size=8), size=1, name='right')\n"
        "lab = data_layer('rlabel', size=1)\n"
        "outputs(rank_cost(left=left, right=right, label=lab))\n",
        lambda: {"a": Argument(value=_r((B, 8), 1)),
                 "b": Argument(value=_r((B, 8), 2)),
                 "rlabel": Argument(value=jnp.asarray(
                     np.random.RandomState(3).randint(0, 2, (B, 1)).astype(np.float32)))},
    ),
    "huber": (
        "score = fc_layer(input=data_layer('a', size=8), size=1, name='score')\n"
        "outputs(huber_cost(input=score, label=data_layer('hlabel', size=2)))\n",
        lambda: {"a": Argument(value=_r((B, 8), 1)),
                 "hlabel": Argument(ids=_labels(2))},
    ),
    "multi_binary_label_cross_entropy": (
        "p = fc_layer(input=data_layer('a', size=8), size=6,"
        " act=SigmoidActivation(), name='p')\n"
        "outputs(multi_binary_label_cross_entropy(input=p,"
        " label=data_layer('mlabel', size=6)))\n",
        lambda: {"a": Argument(value=_r((B, 8), 1)),
                 "mlabel": Argument(value=jnp.asarray(
                     (np.random.RandomState(4).rand(B, 6) > 0.5).astype(np.float32)))},
    ),
    "multi_class_cross_entropy_with_selfnorm": (
        "p = fc_layer(input=data_layer('a', size=8), size=4,"
        " act=SoftmaxActivation(), name='p')\n"
        "outputs(cross_entropy_with_selfnorm(input=p,"
        " label=data_layer('label4', size=4)))\n",
        lambda: {"a": Argument(value=_r((B, 8), 1)),
                 "label4": Argument(ids=_labels(4))},
    ),
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_layer_grad(case, tmp_path):
    body, feed = CASES[case]
    cfg_file = tmp_path / "conf.py"
    cfg_file.write_text(HEAD + textwrap.dedent(body))
    cfg = parse_config(str(cfg_file))
    types = {l.type for l in cfg.model_config.layers}
    assert case in types, (case, types)
    gm = GradientMachine(cfg.model_config)
    params = gm.init_params(seed=7)
    batch = feed()
    outputs, _ = gm.forward(params, batch, pass_type="test")
    assert np.isfinite(float(gm.total_cost(outputs))), case
    report = gm.check_gradient(params, batch, epsilon=1e-4, max_entries=6)
    assert report, f"{case}: no parameters checked"
    for name, diff in report.items():
        assert diff < 5e-2, f"{case}: gradient mismatch for {name}: {diff}"


def test_ctc_layer_grad(tmp_path):
    """ctc cost over a dense sequence input (ref test_LayerGrad CTC case)."""
    cfg_file = tmp_path / "conf.py"
    cfg_file.write_text(HEAD + textwrap.dedent("""
    seq = data_layer('seq', size=8)
    h = fc_layer(input=seq, size=5, act=SoftmaxActivation(), name='h')
    outputs(ctc_layer(input=h, label=data_layer('clabel', size=5), size=5))
    """))
    cfg = parse_config(str(cfg_file))
    gm = GradientMachine(cfg.model_config)
    params = gm.init_params(seed=7)
    T, L = 6, 3
    rng = np.random.RandomState(0)
    batch = {
        "seq": Argument(value=jnp.asarray(rng.rand(B, T, 8), jnp.float32),
                        seq_lengths=jnp.full((B,), T, jnp.int32)),
        "clabel": Argument(ids=jnp.asarray(rng.randint(0, 4, (B, L)), jnp.int32),
                           seq_lengths=jnp.full((B,), L, jnp.int32)),
    }
    outputs, _ = gm.forward(params, batch, pass_type="test")
    assert np.isfinite(float(gm.total_cost(outputs)))
    report = gm.check_gradient(params, batch, epsilon=1e-4, max_entries=6)
    assert report, "ctc: no parameters checked"
    for name, diff in report.items():
        assert diff < 5e-2, f"ctc: gradient mismatch for {name}: {diff}"


def test_sampling_and_eos_forward(tmp_path):
    """Forward-only tail: sampling_id draws ids from row distributions and
    eos_id flags end-of-sequence hits over those ids."""
    cfg_file = tmp_path / "conf.py"
    cfg_file.write_text(HEAD + textwrap.dedent("""
    p = data_layer('p', size=5)
    sid = sampling_id_layer(input=p, name='sid')
    hit = eos_layer(input=sid, eos_id=2, name='hit')
    miss = eos_layer(input=sid, eos_id=3, name='miss')
    outputs(hit, miss)
    """))
    cfg = parse_config(str(cfg_file))
    gm = GradientMachine(cfg.model_config)
    params = gm.init_params(seed=1)
    probs = np.zeros((B, 5), np.float32)
    probs[:, 2] = 1.0  # degenerate distribution pins the sample
    outputs, _ = gm.forward(
        params, {"p": Argument(value=jnp.asarray(probs))},
        pass_type="gen", rng=jax.random.PRNGKey(0),
    )
    ids = np.asarray(outputs["sid"].ids)
    assert ids.shape == (B,) and (ids == 2).all(), ids
    assert np.asarray(outputs["hit"].value).ravel().tolist() == [1.0] * B
    assert np.asarray(outputs["miss"].value).ravel().tolist() == [0.0] * B


@pytest.mark.parametrize("causal", [False, True])
def test_multi_head_attention_grad(causal, tmp_path):
    """multi_head_attention (causal + plain) under the same
    finite-difference methodology as every other layer type — the
    seq-parallel parity tests check sharding, not the analytic grads."""
    cfg_file = tmp_path / f"mha_{causal}.py"
    cfg_file.write_text(HEAD + textwrap.dedent(f"""
    seqin = data_layer('seqin', size=8)
    att = multi_head_attention_layer(input=seqin, num_heads=2,
                                     causal={causal}, name='att')
    top = pooling_layer(input=att, pooling_type=MaxPooling())
    """) + TAIL)
    cfg = parse_config(str(cfg_file))
    gm = GradientMachine(cfg.model_config)
    params = gm.init_params(seed=9)
    T = 5
    rng = np.random.RandomState(3)
    batch = {
        "seqin": Argument(
            value=jnp.asarray(rng.rand(B, T, 8).astype(np.float32) - 0.5),
            seq_lengths=jnp.asarray([T, T - 1, T - 2, T], jnp.int32)),
        "label": Argument(ids=_labels()),
    }
    outputs, _ = gm.forward(params, batch, pass_type="test")
    assert np.isfinite(float(gm.total_cost(outputs)))
    report = gm.check_gradient(params, batch, epsilon=1e-4, max_entries=6)
    assert any(k.startswith("_att.") for k in report), report
    for name, diff in report.items():
        assert diff < 5e-2, f"causal={causal}: {name}: {diff}"
