"""Sentiment (stacked bi-LSTM) and SRL (deep bi-LSTM tagger) demos.

End-to-end over the demo configs — exercises alternating-direction
lstmemory stacks, shared embedding tables across inputs, mixed_layer
projection fusion, and per-token sequence classification cost.
"""

import os
import shutil

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_demo(tmp_path, demo, cfg_name, config_args="", num_passes=2):
    demo_dir = os.path.join(REPO, "demo", demo)
    for f in os.listdir(demo_dir):
        if f.endswith(".py"):
            shutil.copy(os.path.join(demo_dir, f), tmp_path)
    (tmp_path / "train.list").write_text("train-seed-1\n")
    (tmp_path / "test.list").write_text("test-seed-1\n")

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config(cfg_name, config_args)
        flags = _Flags(config=cfg_name, num_passes=num_passes,
                       log_period=100, use_tpu=False)
        trainer = Trainer(cfg, flags)
        trainer.train()
        return cfg, trainer.test()
    finally:
        os.chdir(cwd)


def test_sentiment_stacked_lstm(tmp_path):
    # shrunk stack for the smoke run; structure identical to the tutorial
    cfg, results = _run_demo(
        tmp_path, "sentiment", "trainer_config.py",
        config_args="hid_dim=32,stacked_num=3", num_passes=2,
    )
    types = [l.type for l in cfg.model_config.layers]
    assert types.count("lstmemory") == 3
    assert np.isfinite(results["cost"])


def test_srl_db_lstm_learns(tmp_path):
    cfg, results = _run_demo(
        tmp_path, "semantic_role_labeling", "db_lstm.py",
        config_args="depth=2,hidden_dim=32,lr_mult=1,drop_rate=0", num_passes=10,
    )
    # one forward + one reverse LSTM at depth=2
    lstms = [l for l in cfg.model_config.layers if l.type == "lstmemory"]
    assert len(lstms) == 2 and lstms[1].reversed and not lstms[0].reversed
    # per-sequence cost must beat the always-predict-marginal baseline
    # (label entropy ≈ 1.13/token × ~15 tokens ≈ 17); full position
    # decoding needs more steps than a smoke run, so just require clear
    # progress past the marginal solution
    assert results["cost"] < 15.0, f"SRL tagger did not learn: {results}"


def test_sentiment_bidirectional_net(tmp_path):
    demo_dir = os.path.join(REPO, "demo", "sentiment")
    for f in os.listdir(demo_dir):
        if f.endswith(".py"):
            shutil.copy(os.path.join(demo_dir, f), tmp_path)
    (tmp_path / "train.list").write_text("train-seed-1\n")
    (tmp_path / "test.list").write_text("test-seed-1\n")
    (tmp_path / "bi_config.py").write_text(
        "from paddle.trainer_config_helpers import *\n"
        "from sentiment_net import *\n"
        "dict_dim, class_dim = sentiment_data()\n"
        "settings(batch_size=64, learning_rate=2e-3,\n"
        "         learning_method=AdamOptimizer())\n"
        "bidirectional_lstm_net(dict_dim, class_dim, emb_dim=16, lstm_dim=16)\n"
    )

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("bi_config.py")
        flags = _Flags(config="bi_config.py", num_passes=1,
                       log_period=100, use_tpu=False)
        trainer = Trainer(cfg, flags)
        trainer.train()
        assert np.isfinite(trainer.test()["cost"])
    finally:
        os.chdir(cwd)
