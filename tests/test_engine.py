"""Continuous-batching engine (paddle_tpu/serving/, doc/serving.md):
scheduler unit tests on the injectable-clock / fake-decode seam (slot
reuse after EOS, FIFO admit fairness, cancel/timeout/drain), greedy
prefill+decode parity vs ``SequenceGenerator`` golden outputs on the
same params, the chaos e2e (injected decode fault mid-load), the
``attention_gru_step`` ops seam vs the fused kernel, the
``bench.py serve --engine={static,continuous}`` A/B (compare verdict
IMPROVED on goodput), and the ``paddle serve`` SIGTERM graceful-drain
subprocess e2e."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import serving as slog
from paddle_tpu.observability.analyze import load_run
from paddle_tpu.serving import (
    Engine,
    FakeBackend,
    parse_decode_blocks,
    pick_block,
)
from paddle_tpu.utils import concurrency as cc

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")


def _results(futs, timeout=60.0):
    return [f.result(timeout=timeout) for f in futs]


# ------------------------------------------------------- scheduler units


def test_fifo_admission_and_slot_reuse_after_finish():
    """More requests than slots: admission order is strict FIFO and
    freed slots (EOS/budget) are reused — the total admitted across
    waves exceeds the slot count."""
    be = FakeBackend(slots=2, max_length=8)
    eng = Engine(be, request_timeout_s=30.0).start()
    futs = [eng.submit([2], max_new_tokens=1 + (i % 3), rid=f"r{i}")
            for i in range(7)]
    res = _results(futs)
    assert all(r.outcome == "ok" for r in res), [r.outcome for r in res]
    for i, r in enumerate(res):
        assert len(r.tokens) == 1 + (i % 3), (i, r.tokens)
    admitted = [rid for wave in be.admits for rid in wave]
    assert admitted == [f"r{i}" for i in range(7)]  # FIFO, no reorder
    assert len(be.admits) > 1  # slots were reused, not one static cohort
    assert eng.drain(timeout=30.0)


def test_eos_frees_slot_midstream():
    """A scripted EOS ends the sequence before its budget and frees the
    slot; the EOS token itself is delivered (the static path's lens
    semantics)."""
    eos_at = {"r0": 2}  # r0 emits eos as its 3rd token

    def token_fn(rid, i):
        return 1 if i == eos_at.get(rid, -1) else 5 + i

    be = FakeBackend(slots=1, max_length=16, eos=1, token_fn=token_fn)
    eng = Engine(be, request_timeout_s=30.0).start()
    r0 = eng.submit([2], max_new_tokens=10, rid="r0").result(timeout=30.0)
    r1 = eng.submit([2], max_new_tokens=2, rid="r1").result(timeout=30.0)
    assert r0.outcome == "ok" and r0.tokens == [5, 6, 1]
    assert r1.outcome == "ok" and len(r1.tokens) == 2
    assert eng.drain(timeout=30.0)


def test_injectable_clock_wall_deadlines():
    """Queued-request timeout and in-flight timeout run on the
    injectable clock (wall time, not virtual): advancing the fake clock
    past the deadline frees the queue entry / the slot at the next
    iteration boundary with outcome=timeout."""
    now = [0.0]
    # a slow backend that parks the only slot long enough for the fake
    # clock to expire it (1 ms of real time per step, 1000-step budget)
    be = FakeBackend(slots=1, max_length=1000, step_delay_s=0.001)
    eng = Engine(be, request_timeout_s=5.0, clock=lambda: now[0],
                 idle_poll_s=0.005)
    eng.start()
    blocker = eng.submit([2], max_new_tokens=1000, rid="blocker")
    queued = eng.submit([2], max_new_tokens=1, rid="queued")
    time.sleep(0.05)  # let the loop admit the blocker
    now[0] = 6.0      # past both deadlines
    rq = queued.result(timeout=30.0)
    rb = blocker.result(timeout=30.0)
    assert rq.outcome == "timeout", rq
    assert rb.outcome == "timeout", rb
    # the engine is still serving after the sweep
    now[0] = 7.0
    ok = eng.submit([2], max_new_tokens=1, rid="after").result(timeout=30.0)
    assert ok.outcome == "ok"
    assert eng.drain(timeout=30.0)


def test_cancel_queued_and_inflight():
    be = FakeBackend(slots=1, max_length=64, step_delay_s=0.002)
    eng = Engine(be, request_timeout_s=30.0).start()
    f0 = eng.submit([2], max_new_tokens=64, rid="long")
    f1 = eng.submit([2], max_new_tokens=1, rid="queued")
    assert eng.cancel("queued") is True
    assert eng.cancel("long") is True
    assert eng.cancel("nope") is False
    r0, r1 = f0.result(timeout=30.0), f1.result(timeout=30.0)
    assert r1.outcome == "cancelled"
    assert r0.outcome in ("cancelled", "ok")  # may have finished first
    nxt = eng.submit([2], max_new_tokens=1, rid="next").result(timeout=30.0)
    assert nxt.outcome == "ok"  # the cancelled slot was reclaimed
    assert eng.drain(timeout=30.0)


def test_drain_finishes_inflight_rejects_queued_and_new():
    be = FakeBackend(slots=1, max_length=32, step_delay_s=0.002)
    eng = Engine(be, request_timeout_s=30.0).start()
    inflight = eng.submit([2], max_new_tokens=20, rid="inflight")
    queued = [eng.submit([2], rid=f"q{i}") for i in range(3)]
    time.sleep(0.03)  # let the loop admit `inflight`
    assert eng.drain(timeout=30.0)
    assert inflight.result(timeout=1.0).outcome == "ok"
    assert {f.result(timeout=1.0).outcome for f in queued} <= {
        "rejected", "ok"}
    assert any(f.result(timeout=1.0).outcome == "rejected" for f in queued)
    late = eng.submit([2], rid="late").result(timeout=1.0)
    assert late.outcome == "rejected"


def test_drain_rejection_counts_arrived_once():
    """A queued request rejected by the drain was already counted as
    arrived at enqueue — the window must not double-count it."""
    be = FakeBackend(slots=1, max_length=32, step_delay_s=0.002)
    eng = Engine(be, request_timeout_s=30.0).start()
    futs = [eng.submit([2], max_new_tokens=20, rid=f"r{i}") for i in range(4)]
    time.sleep(0.03)
    assert eng.drain(timeout=30.0)
    _results(futs, timeout=1.0)
    w = eng.window_roll(offered_rps=1.0, rung=0)
    assert w["arrived"] == 4, w
    assert w["completed"] + w["rejected"] + w["timeouts"] == 4, w


def test_zero_budget_is_a_legal_answer():
    """max_new_tokens=0 means THE EMPTY GENERATION (0 is not an unset
    sentinel): outcome=ok, zero tokens, no slot consumed."""
    be = FakeBackend(slots=1, max_length=8)
    eng = Engine(be, request_timeout_s=30.0).start()
    r = eng.submit([2, 3], max_new_tokens=0, rid="empty").result(timeout=30.0)
    assert r.outcome == "ok" and r.tokens == []
    # None still means "the graph's max_length"
    full = eng.submit([2], rid="full").result(timeout=30.0)
    assert full.outcome == "ok" and len(full.tokens) == 8
    assert eng.drain(timeout=30.0)


def test_queue_cap_rejects_at_submit():
    be = FakeBackend(slots=1, max_length=64, step_delay_s=0.005)
    eng = Engine(be, queue_cap=1, request_timeout_s=30.0).start()
    futs = [eng.submit([2], max_new_tokens=30, rid=f"r{i}") for i in range(5)]
    outcomes = [f.result(timeout=60.0).outcome for f in futs]
    assert "rejected" in outcomes, outcomes
    assert outcomes[0] == "ok"
    assert eng.drain(timeout=30.0)


def test_chaos_decode_fault_midload_engine_survives(tmp_path):
    """Injected decode fault mid-load: the in-flight cohort resolves
    outcome=error, the engine stays alive, later requests complete, and
    every emitted record passes validate_record."""
    obs.configure(str(tmp_path))
    be = FakeBackend(slots=2, max_length=8, fail_at_launch=2,
                     step_delay_s=0.001)
    eng = Engine(be, request_timeout_s=30.0).start()
    first = [eng.submit([2], max_new_tokens=4, rid=f"a{i}") for i in range(4)]
    outcomes = [f.result(timeout=60.0).outcome for f in first]
    assert "error" in outcomes, outcomes
    later = [eng.submit([2], max_new_tokens=2, rid=f"b{i}") for i in range(3)]
    assert all(f.result(timeout=60.0).outcome == "ok" for f in later)
    assert eng.drain(timeout=30.0)
    eng.window_roll(offered_rps=1.0, rung=0)
    obs.emit("run_end", status="completed")
    obs.flush()
    recs = [r for recs in load_run(str(tmp_path)).values() for r in recs]
    for rec in recs:
        assert not obs.validate_record(rec), (rec, obs.validate_record(rec))
    reqs = [r for r in recs if r["kind"] == "request"]
    assert {r["outcome"] for r in reqs} >= {"ok", "error"}
    assert all(r.get("engine") == "continuous" for r in reqs)
    errs = [r for r in reqs if r["outcome"] == "error"]
    assert errs and all("decode" in (r.get("error") or "").lower()
                        or "injected" in (r.get("error") or "").lower()
                        for r in errs)


def test_realtime_ttft_is_midstream():
    """TTFT comes from the first token's readback, mid-sequence — for a
    multi-token request t_first_token strictly precedes t_finish (the
    static path's first-token==finish degenerate case is gone)."""
    be = FakeBackend(slots=1, max_length=32, step_delay_s=0.002)
    eng = Engine(be, request_timeout_s=30.0)
    captured = []
    orig = eng._finish_locked

    def spy(req, outcome, now, error=None):
        captured.append(req)
        return orig(req, outcome, now, error=error)

    eng._finish_locked = spy
    eng.start()
    assert eng.submit([2], max_new_tokens=10,
                      rid="r1").result(timeout=30.0).outcome == "ok"
    assert eng.drain(timeout=30.0)
    (req,) = [r for r in captured if r.rid == "r1"]
    assert 0 <= req.t_first_token < req.t_finish


# ---------------------------------------------- pipelined loop semantics


def test_pick_block_policy_and_ladder_parse():
    """The adaptive decode-block policy (doc/serving.md): budget caps,
    queue/TTFT pressure picks the smallest rung that amortizes the
    measured overhead, quiet picks the top rung."""
    assert parse_decode_blocks("8,4,2,1,4") == (1, 2, 4, 8)
    assert parse_decode_blocks(6) == (6,)
    assert parse_decode_blocks(None) == (1,)
    assert pick_block((4,), 1, True, 1.0, 0.0) == 4      # one rung: no choice
    assert pick_block((1, 2, 4, 8), 100, False, 0.0, 0.0) == 8   # quiet: top
    assert pick_block((1, 2, 4, 8), 3, False, 0.0, 0.0) == 2     # budget cap
    # pressure + measurements: smallest rung keeping overhead under the
    # share; overhead-dominated steps to the top; unmeasured stays low
    assert pick_block((1, 2, 4, 8), 100, True, 0.001, 0.001) == 2
    assert pick_block((1, 2, 4, 8), 100, True, 0.01, 0.001) == 8
    assert pick_block((1, 2, 4, 8), 100, True, 0.0, 0.0) == 1


def _run_workload(pipeline, n=12, slots=3):
    """One seeded schedule_requests workload through a fresh engine;
    returns ({rid: (outcome, tokens)}, flattened admission order)."""
    be = FakeBackend(slots=slots, max_length=16)
    eng = Engine(be, request_timeout_s=60.0, pipeline=pipeline).start()
    reqs = slog.schedule_requests(
        50.0, n, 3, prompt_fn=lambda rng, i: [2, 3],
        budget_fn=lambda rng, i: 1 + int(rng.randint(0, 5)),
    )
    futs = {r.rid: eng.submit(r.prompt, max_new_tokens=r.max_new, rid=r.rid)
            for r in reqs}
    res = {rid: f.result(timeout=60.0) for rid, f in futs.items()}
    admits = [rid for wave in be.admits for rid in wave]
    assert eng.drain(timeout=30.0)
    return {rid: (r.outcome, r.tokens) for rid, r in res.items()}, admits


def test_golden_pipelined_equals_blocking_streams():
    """THE golden test: on the same seeded schedule_requests workload
    the pipelined engine emits the IDENTICAL per-request token streams
    and outcomes as the PR-12 blocking loop — and the same FIFO
    admission order."""
    got_p, admits_p = _run_workload(True)
    got_b, admits_b = _run_workload(False)
    assert got_p == got_b
    assert admits_p == admits_b
    assert all(o == "ok" for o, _ in got_p.values())


def test_golden_pipelined_equals_blocking_cancel_timeout_drain_fault():
    """The edge paths, both loops: cancel lands cancelled, the
    injectable clock expires queued AND in-flight requests, drain
    completes in-flight and rejects queued, and a faulted launch errors
    its cohort while the engine keeps serving — identical outcomes."""
    results = {}
    for pipeline in (True, False):
        out = {}
        # cancel: the queued request is cancelled before its admission
        be = FakeBackend(slots=1, max_length=64, step_delay_s=0.005)
        eng = Engine(be, request_timeout_s=30.0, pipeline=pipeline).start()
        blk = eng.submit([2], max_new_tokens=40, rid="blk")
        q1 = eng.submit([2], max_new_tokens=1, rid="q1")
        assert eng.cancel("q1") is True
        out["cancel"] = q1.result(timeout=30.0).outcome
        assert blk.result(timeout=30.0).outcome == "ok"
        assert eng.drain(timeout=30.0)
        # timeout: fake clock expires the in-flight slot and the queue
        now = [0.0]
        be = FakeBackend(slots=1, max_length=1000, step_delay_s=0.001)
        eng = Engine(be, request_timeout_s=5.0, clock=lambda: now[0],
                     idle_poll_s=0.005, pipeline=pipeline).start()
        b2 = eng.submit([2], max_new_tokens=1000, rid="b2")
        q2 = eng.submit([2], max_new_tokens=1, rid="q2")
        time.sleep(0.05)
        now[0] = 6.0
        out["timeout"] = (q2.result(timeout=30.0).outcome,
                          b2.result(timeout=30.0).outcome)
        assert eng.drain(timeout=30.0)
        # drain: in-flight finishes, queued rejected
        be = FakeBackend(slots=1, max_length=32, step_delay_s=0.002)
        eng = Engine(be, request_timeout_s=30.0, pipeline=pipeline).start()
        inflight = eng.submit([2], max_new_tokens=20, rid="in")
        queued = [eng.submit([2], rid=f"dq{i}") for i in range(3)]
        time.sleep(0.05)
        assert eng.drain(timeout=30.0)
        out["drain_inflight"] = inflight.result(timeout=1.0).outcome
        out["drain_rejected"] = sorted(
            f.result(timeout=1.0).outcome for f in queued)
        # fault: launch 3 faults with both requests in flight
        be = FakeBackend(slots=2, max_length=8, step_delay_s=0.02,
                         fail_at_launch=3)
        eng = Engine(be, request_timeout_s=30.0, pipeline=pipeline).start()
        f0 = eng.submit([2], max_new_tokens=6, rid="f0")
        f1 = eng.submit([2], max_new_tokens=6, rid="f1")
        out["fault"] = sorted((f0.result(timeout=30.0).outcome,
                               f1.result(timeout=30.0).outcome))
        ok = eng.submit([2], max_new_tokens=1, rid="after")
        out["fault_after"] = ok.result(timeout=30.0).outcome
        assert eng.drain(timeout=30.0)
        results[pipeline] = out
    assert results[True] == results[False], results
    assert results[True]["cancel"] == "cancelled"
    assert results[True]["timeout"] == ("timeout", "timeout")
    assert results[True]["drain_inflight"] == "ok"
    assert "rejected" in results[True]["drain_rejected"]
    assert results[True]["fault"] == ["error", "error"]
    assert results[True]["fault_after"] == "ok"


def test_adaptive_ladder_matches_single_block():
    """The decode-block ladder is a perf knob, not a semantics knob:
    the adaptive engine's outputs equal the single-block engine's."""
    outs = {}
    for spec in ("1", "1,2,4,8"):
        be = FakeBackend(slots=2, max_length=16, chunk=spec)
        eng = Engine(be, request_timeout_s=30.0).start()
        futs = [eng.submit([2], max_new_tokens=3 + i, rid=f"r{i}")
                for i in range(5)]
        outs[spec] = [f.result(timeout=30.0).tokens for f in futs]
        assert eng.drain(timeout=30.0)
    assert outs["1"] == outs["1,2,4,8"]


class AsyncDeviceBackend(FakeBackend):
    """A FakeBackend whose launches run on a WALL-CLOCK deadline — the
    model of a real accelerator on a small CI host: an in-flight launch
    occupies no host core (sleep), so host work genuinely overlaps it.
    ``host_cost_s`` burns real host time at collect (the readback /
    bookkeeping the pipelined loop hides behind the next launch)."""

    def __init__(self, *a, launch_s=0.003, host_cost_s=0.0015, **kw):
        super().__init__(*a, **kw)
        self.launch_s = float(launch_s)
        self.host_cost_s = float(host_cost_s)
        self._ready_at = []

    def dispatch(self, block=None):
        now = cc.monotonic()
        start = max(now, self._ready_at[-1] if self._ready_at else now)
        super().dispatch(block=block)
        self._ready_at.append(start + self.launch_s)

    def collect(self):
        ready = self._ready_at.pop(0)
        now = cc.monotonic()
        if now < ready:
            cc.sleep(ready - now)
        out = super().collect()
        t0 = cc.monotonic()
        while cc.monotonic() - t0 < self.host_cost_s:
            pass  # busy host work, deliberately un-sleepable
        return out

    def reset(self):
        super().reset()
        self._ready_at = []


def test_ab_pipelined_overlap_acceptance(tmp_path):
    """THE overlap A/B, device-modeled so it holds on a 1-core CI box
    (on the CPU backend "device" work shares the host's core, so real
    overlap is physically impossible there — doc/performance.md): the
    pipelined engine on the same seeded mixed-length overload ladder
    beats the blocking loop on goodput, its serve_window host_share
    (the device-waits-for-host share) drops, overlap_s is accounted,
    and `paddle compare` of the two run dirs lands verdict IMPROVED
    with exit 0."""
    from paddle_tpu.observability import compare

    budget_fn = lambda rng, i: 12 if rng.rand() < 0.2 else 2 + int(
        rng.randint(0, 4))
    windows = {}
    for mode, pipeline in (("off", False), ("on", True)):
        obs.registry().reset()
        obs.configure(str(tmp_path / mode))
        from paddle_tpu.serving import drive_rung

        be = AsyncDeviceBackend(slots=2, max_length=12)
        eng = Engine(be, request_timeout_s=60.0, pipeline=pipeline).start()
        ws = []
        for rung, rate in enumerate((200.0, 400.0)):
            reqs = slog.schedule_requests(rate, 16, 7 + rung, rung=rung,
                                          prompt_fn=lambda rng, i: [2, 3],
                                          budget_fn=budget_fn)
            ws.append(drive_rung(eng, reqs, rate_rps=rate, rung=rung))
        assert eng.drain(timeout=60.0)
        obs.emit("run_end", status="completed")
        obs.flush()
        windows[mode] = ws
    for w_off, w_on in zip(windows["off"], windows["on"]):
        assert w_on["goodput_tok_s"] > w_off["goodput_tok_s"], (w_off, w_on)
        assert w_on["pipeline"] == "on" and w_off["pipeline"] == "off"
        assert w_on.get("overlap_s", 0.0) > 0.0
    # host/dispatch share down in aggregate (per-rung shares are small
    # in this device-heavy model; the direction is the structural claim)
    mean = lambda ws: sum(w["host_share"] for w in ws) / len(ws)
    assert mean(windows["on"]) < mean(windows["off"]), windows
    doc = compare.compare(compare.load_side(str(tmp_path / "off")),
                          compare.load_side(str(tmp_path / "on")),
                          threshold=0.15)
    assert doc["verdict"] == "IMPROVED", doc
    assert any("goodput_tok_s" in m for m in doc["improvements"]), doc
    assert compare.main([str(tmp_path / "off"), str(tmp_path / "on"),
                         "--threshold", "0.15"]) == 0


# ----------------------------------------------------- jax decode parity


@pytest.fixture(scope="module")
def tiny_gen_machine():
    from paddle_tpu.flagship import nmt_gen_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.machine import compute_dtype_of

    tc = nmt_gen_config(vocab=50, dim=16, beam_size=1, max_length=8,
                        dtype="float32", batch_size=2)
    gm = GradientMachine(tc.model_config,
                         compute_dtype=compute_dtype_of(tc.opt_config))
    return tc, gm, gm.init_params(seed=1)


def test_plan_gates_and_reasons(tiny_gen_machine):
    from paddle_tpu.flagship import nmt_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.decode_step import plan_of

    _, gm, _ = tiny_gen_machine
    plan, reason = plan_of(gm)
    assert plan is not None and reason == ""
    assert plan.score_layer and plan.max_length == 8
    # a training graph has no generator: refused with the reason
    train_tc = nmt_config(vocab=50, dim=16, batch_size=2)
    plan2, reason2 = plan_of(GradientMachine(train_tc.model_config))
    assert plan2 is None and "generator" in reason2


def test_engine_matches_sequence_generator_golden(tiny_gen_machine):
    """Greedy slot decode == SequenceGenerator at beam_size=1, token
    for token, on the same params — the engine subsumes the embedding
    API for concurrent use (its documented adapter contract). Pinned
    across the pipelined loop, the blocking loop, AND the
    --serve_fused_step decoder: pipelined == blocking == fused ==
    SequenceGenerator greedy."""
    from paddle_tpu import api
    from paddle_tpu.graph import make_seq

    tc, gm, params = tiny_gen_machine
    am = api.GradientMachine(tc.model_config)
    am.params = params
    am._core = gm  # the EXACT same machine + params on both paths
    sg = am.asSequenceGenerator()
    rng = np.random.RandomState(7)
    prompts = [rng.randint(2, 50, size=rng.randint(1, 5)).astype(np.int32)
               for _ in range(4)]
    T = 4
    ids = np.zeros((4, T), np.int32)
    lens = np.zeros((4,), np.int32)
    for i, p in enumerate(prompts):
        ids[i, : len(p)] = p
        lens[i] = len(p)
    golden = [r[0]["ids"] for r in sg.generate(
        {"source_language_word": make_seq(None, lens, ids=ids)})]

    for pipeline, fused in ((True, False), (False, False), (True, True)):
        eng = am.asDecodeEngine(slots=3, prompt_tokens=T, pipeline=pipeline,
                                fused_step=fused).start()
        futs = [eng.submit(p.tolist(), rid=f"g{i}")
                for i, p in enumerate(prompts)]
        out = [f.result(timeout=120.0).tokens for f in futs]
        assert out == golden, (pipeline, fused)
        assert eng.drain(timeout=60.0)


def test_fused_step_refuses_off_template_models():
    """--serve_fused_step is an explicit request: a step graph outside
    the attention-GRU template refuses loudly with the reason instead
    of silently serving different math."""
    from paddle_tpu.flagship import nmt_gen_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.decode_step import plan_fused_step, plan_of
    from paddle_tpu.graph.machine import compute_dtype_of
    from paddle_tpu.serving.jax_backend import (
        JaxDecodeBackend, UnsupportedModelError,
    )

    tc = nmt_gen_config(vocab=50, dim=16, beam_size=1, max_length=8,
                        dtype="float32", batch_size=2)
    gm = GradientMachine(tc.model_config,
                        compute_dtype=compute_dtype_of(tc.opt_config))
    plan, _ = plan_of(gm)
    fp, why = plan_fused_step(gm, plan)
    assert fp is not None, why
    assert fp["D"] == 16 and fp["vocab"] == 50
    # reduced compute precision: the fused math is f32, so parity with
    # the bf16 graph walk cannot be guaranteed — refused with the reason
    import jax.numpy as jnp

    gm_bf16 = GradientMachine(tc.model_config, compute_dtype=jnp.bfloat16)
    plan_bf16, _ = plan_of(gm_bf16)
    fp_bf16, why_bf16 = plan_fused_step(gm_bf16, plan_bf16)
    assert fp_bf16 is None and "float32" in why_bf16
    # de-template the gru activation: the matcher must refuse with the
    # reason, and the backend must raise it under the explicit flag
    gm.network.layer_map[plan.memories[0].layer_name].active_type = "relu"
    fp2, why2 = plan_fused_step(gm, plan)
    assert fp2 is None and "activations" in why2
    with pytest.raises(UnsupportedModelError, match="serve_fused_step"):
        JaxDecodeBackend(gm, gm.init_params(seed=1), slots=2,
                         prompt_tokens=4, fused_step=True)


WARM_SERVE_SCRIPT = """
import json, sys
cache_dir, run_dir = sys.argv[1], sys.argv[2]
# the cache must be enabled BEFORE anything touches jax: this jax
# version freezes the use-the-cache decision at first compile — the
# same ordering paddle_tpu.serving.frontend.main uses for the flag
from paddle_tpu.observability.compile_log import enable_compile_cache
assert enable_compile_cache(cache_dir)
from paddle_tpu.observability import metrics as obs
obs.configure(run_dir)
import jax
from paddle_tpu.flagship import nmt_gen_config
from paddle_tpu.graph import GradientMachine
from paddle_tpu.graph.machine import compute_dtype_of
from paddle_tpu.observability.compile_log import CompileRegistry
from paddle_tpu.serving import Engine
from paddle_tpu.serving.jax_backend import JaxDecodeBackend
tc = nmt_gen_config(vocab=50, dim=16, beam_size=1, max_length=8,
                    dtype="float32", batch_size=2)
gm = GradientMachine(tc.model_config,
                     compute_dtype=compute_dtype_of(tc.opt_config))
params = gm.init_params(seed=1)
registry = CompileRegistry(device_kind=jax.devices()[0].device_kind)
be = JaxDecodeBackend(gm, params, slots=2, prompt_tokens=4,
                      decode_block="1,2", registry=registry)
eng = Engine(be, request_timeout_s=60.0).start()
assert eng.drain(timeout=60.0)
obs.emit("run_end", status="completed")
obs.flush()
print(json.dumps({"warmup_s": eng.warmup_s}))
"""


def test_serve_warmup_compile_cache_hits(tmp_path):
    """--compile_cache_dir through the engine warmup (ROADMAP item 5
    applied to serving): a warm RESTART's serve_prefill/serve_decode
    compiles land with cache_hit=true and time-to-first-token-ready
    (Engine.start()'s warmup) drops below cold. Two fresh processes
    sharing the cache dir — the restart the elastic machinery makes
    frequent."""
    script = tmp_path / "warm_serve.py"
    script.write_text(WARM_SERVE_SCRIPT)
    warmup_s = {}
    for phase in ("cold", "warm"):
        out = subprocess.run(
            [sys.executable, str(script), str(tmp_path / "cache"),
             str(tmp_path / phase)],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
            cwd=REPO,
        )
        assert out.returncode == 0, out.stderr
        warmup_s[phase] = json.loads(out.stdout.splitlines()[-1])["warmup_s"]
    sums = {}
    for phase in ("cold", "warm"):
        recs = [r for rs in load_run(str(tmp_path / phase)).values()
                for r in rs]
        compiles = [r for r in recs if r["kind"] == "compile"
                    and r["group"] in ("serve_prefill", "serve_decode")]
        assert {c["group"] for c in compiles} == {"serve_prefill",
                                                 "serve_decode"}
        assert all(c["recompiles"] == 0 for c in compiles), compiles
        hits = [c.get("cache_hit") for c in compiles]
        assert all(h is (phase == "warm") for h in hits), (phase, compiles)
        sums[phase] = sum(c.get("compile_s", 0.0) + c.get("trace_s", 0.0)
                          for c in compiles)
    assert warmup_s["warm"] < warmup_s["cold"], warmup_s
    assert sums["warm"] < sums["cold"], sums


def test_decode_block_and_budget_on_device(tiny_gen_machine):
    """decode_block>1 micro-steps per launch: budgets still land
    exactly (device-side steps/budget termination), and outputs match
    the block=1 engine."""
    from paddle_tpu.serving.jax_backend import JaxDecodeBackend

    _, gm, params = tiny_gen_machine
    outs = {}
    for block in (1, 3):
        be = JaxDecodeBackend(gm, params, slots=2, prompt_tokens=4,
                              decode_block=block)
        eng = Engine(be, request_timeout_s=60.0).start()
        futs = [eng.submit([5 + i, 9], max_new_tokens=1 + i, rid=f"r{i}")
                for i in range(4)]
        res = _results(futs, timeout=120.0)
        assert all(r.outcome == "ok" for r in res)
        for i, r in enumerate(res):
            assert len(r.tokens) == 1 + i
        outs[block] = [r.tokens for r in res]
        assert eng.drain(timeout=60.0)
    assert outs[1] == outs[3]


def test_unsupported_model_refused_with_reason():
    from paddle_tpu.flagship import nmt_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.serving.jax_backend import (
        JaxDecodeBackend, UnsupportedModelError,
    )

    tc = nmt_config(vocab=50, dim=16, batch_size=2)
    gm = GradientMachine(tc.model_config)
    with pytest.raises(UnsupportedModelError, match="generator"):
        JaxDecodeBackend(gm, gm.init_params(seed=1), slots=2,
                         prompt_tokens=4)


def test_attention_gru_step_matches_fused_kernel():
    """The ops seam: T sequential attention_gru_step calls reproduce
    the fused kernel's whole-loop output (interpret mode) — the
    per-step math a TPU serve_decode kernel must implement."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_attention_gru import (
        attention_gru_step, fused_attention_gru,
    )

    rng = np.random.RandomState(0)
    Te, Td, B, D, E = 5, 4, 3, 8, 16
    r = lambda *s: jnp.asarray(rng.randn(*s).astype(np.float32) * 0.3)
    ep, ev = r(Te, B, D), r(Te, B, E)
    em = jnp.asarray(
        (rng.rand(Te, B, 1) > 0.2).astype(np.float32)).at[0].set(1.0)
    xw, h0 = r(Td, B, 3 * D), r(B, D)
    wa, ba, v, wctx, wg = r(D, D), r(1, D), r(1, D), r(E, 3 * D), r(D, 3 * D)
    dmask = jnp.ones((Td, B, 1), jnp.float32)
    ys = fused_attention_gru(ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg,
                             ("tanh", "sigmoid"), True)
    h = h0
    for t in range(Td):
        h = attention_gru_step(h, ep, ev, em, xw[t], wa, ba, v, wctx, wg)
        np.testing.assert_allclose(np.asarray(ys[t], np.float32),
                                   np.asarray(h, np.float32),
                                   rtol=1e-5, atol=1e-5)


# -------------------------------------------------- bench A/B acceptance


def _bench(monkeypatch, tmp_path):
    monkeypatch.delenv("PADDLE_TPU_BENCH_METRICS_DIR", raising=False)
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_REQUESTS", "16")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_MIXED_LEN", "1")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_SEED", "0")
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    return bench


def test_bench_serve_continuous_e2e_acceptance(tmp_path, monkeypatch,
                                               capsys):
    """The acceptance path: `bench.py serve --engine=continuous` on the
    CPU backend completes >= 3 rungs, serve_decode (and serve_prefill)
    compile exactly ONCE with recompiles=0 after warmup, every record
    validates, and serve-report renders the run."""
    bench = _bench(monkeypatch, tmp_path)
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path))
    value, extras = bench.bench_serve(B=2, T=4, vocab=50, dim=16,
                                      beam_size=1, max_length=8,
                                      dtype="float32", engine="continuous")
    obs.emit("run_end", status="completed")
    obs.flush()
    assert value > 0
    assert len(extras["rungs"]) >= 3
    assert extras["engine"] == "continuous"
    assert all(r["engine"] == "continuous" for r in extras["rungs"])

    recs = [r for rs in load_run(str(tmp_path)).values() for r in rs]
    for rec in recs:
        assert not obs.validate_record(rec), (rec, obs.validate_record(rec))
    compiles = {}
    for r in recs:
        if r["kind"] == "compile" and r["group"] in ("serve_decode",
                                                     "serve_prefill"):
            compiles.setdefault(r["group"], []).append(r)
    assert set(compiles) == {"serve_decode", "serve_prefill"}
    for group, rows in compiles.items():
        assert len(rows) == 1, (group, rows)      # ONE signature each
        assert rows[0]["recompiles"] == 0, (group, rows)
    wins = [r for r in recs if r["kind"] == "serve_window"]
    assert wins and all(w["engine"] == "continuous" for w in wins)

    assert slog.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    rows = [ln for ln in out.splitlines()
            if ln.strip() and ln.strip().split()[0].isdigit()]
    assert len(rows) >= 3
    assert "serve_decode" in out and "recompiles after warmup: 0" in out


def test_ab_compare_continuous_beats_static_at_knee(tmp_path, monkeypatch):
    """THE A/B: both engines on the same seeded arrival schedule and
    mixed-length workload (pinned rates); `paddle compare` static ->
    continuous lands verdict IMPROVED with goodput_tok_s at the knee
    among the improvements and exit 0."""
    from paddle_tpu.observability import compare

    bench = _bench(monkeypatch, tmp_path)
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_BLOCK", "16")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_REQUESTS", "24")
    # this A/B pins the BATCHING-POLICY win (run-to-completion vs
    # iteration-level scheduling), so the engine runs the serial loop:
    # with budgets <= the decode block the no-waste guard disables
    # overlap anyway, and the pipelined loop would only add scheduler
    # jitter to a 24-sample p99. The overlap win has its own A/B
    # (test_ab_compare_pipelined_beats_blocking) in the multi-launch
    # regime where it actually engages.
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_PIPELINE", "off")
    kw = dict(B=4, T=8, vocab=1000, dim=128, beam_size=1, max_length=64,
              dtype="float32")
    # the A/B regime is OVERLOAD: rates pinned at 1.5/3/6x the static
    # engine's measured capacity (a quick calibration pass), where
    # run-to-completion's max_length-per-cohort waste is the bottleneck.
    # Below capacity both engines are arrival-bound — goodput ties and
    # tail latency is pure scheduler jitter, a coin-flip verdict.
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path / "cal"))
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_RATES", "1.0")
    _, cal = bench.bench_serve(engine="static", n_requests=1, **kw)
    cap = cal["capacity_rps"]
    # DEEP overload only (2.5/5/10x): at 1.5x the lightest rung sits on
    # the saturation boundary, where a 24-sample p99 is one descheduled
    # launch away from a phantom REGRESSION; past ~2x every latency is
    # queue-drain structural and the run-to-completion waste dominates
    rates = ",".join(str(round(f * cap, 4)) for f in (2.5, 5.0, 10.0))
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_RATES", rates)
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR",
                       str(tmp_path / "static"))
    vs, es = bench.bench_serve(engine="static", **kw)
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path / "cont"))
    vc, ec = bench.bench_serve(engine="continuous", **kw)
    obs.configure("")

    a = tmp_path / "A.json"
    b = tmp_path / "B.json"
    metric = "serve_cpu_smoke_goodput_tokens_per_sec"
    a.write_text(json.dumps(dict(metric=metric, value=round(vs, 1), **es)))
    b.write_text(json.dumps(dict(metric=metric, value=round(vc, 1), **ec)))
    # 20% noise threshold: latency tails at smoke scale jitter across
    # CI containers; the goodput win at the knee is far beyond it
    rc = compare.main([str(a), str(b), "--threshold", "0.2"])
    assert rc == 0, "cross-engine compare regressed"

    # the headline claim, asserted directly: goodput at the saturation
    # knee improves (same knee rung joined on offered load)
    assert es["knee_rps"] is not None
    knee_static = next(r for r in es["rungs"]
                       if r["offered_rps"] == es["knee_rps"])
    knee_cont = next(r for r in ec["rungs"]
                     if r["offered_rps"] == es["knee_rps"])
    assert knee_cont["goodput_tok_s"] > 1.2 * knee_static["goodput_tok_s"], (
        knee_static, knee_cont)
    # and the compare doc agrees: IMPROVED with a goodput key among the
    # improvements
    doc = compare.compare(compare.load_side(str(a)),
                          compare.load_side(str(b)), threshold=0.2)
    assert doc["verdict"] == "IMPROVED", doc["verdict"]
    assert any("goodput_tok_s" in m for m in doc["improvements"]), (
        doc["improvements"])


def test_bench_serve_pipeline_stamps_and_host_share(tmp_path, monkeypatch):
    """PADDLE_TPU_BENCH_SERVE_PIPELINE rides the headline and every
    rung record; the pipelined run's serve_window host_share (the
    device-waits-for-host share, union-of-spans accounting) drops vs
    blocking; overlap_s is accounted; and `paddle compare` joins the
    two artifacts' rungs on (engine, pipeline, offered load) — nothing
    lands in only_a/only_b. Goodput direction is deliberately NOT
    asserted here: on a 1-core CI box real overlap is impossible
    (doc/performance.md); the win is pinned by
    test_ab_pipelined_overlap_acceptance's device-modeled A/B."""
    from paddle_tpu.observability import compare

    bench = _bench(monkeypatch, tmp_path)
    # one deep-overload rung with full-length decodes: every arrival is
    # effectively immediate and the window is all work — an idle-heavy
    # rung would put the same idle share in both modes' host_share and
    # drown the dispatch-bubble signal this test pins
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_RATES", "2000.0")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_BLOCK", "1,2")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_REQUESTS", "32")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_MIXED_LEN", "0")
    kw = dict(B=2, T=4, vocab=50, dim=16, beam_size=1, max_length=8,
              dtype="float32")
    extras = {}
    for mode in ("off", "on"):
        monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_PIPELINE", mode)
        monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path / mode))
        _v, e = bench.bench_serve(engine="continuous", **kw)
        obs.emit("run_end", status="completed")
        obs.flush()
        assert e["pipeline"] == mode
        assert e["decode_blocks"] == "1,2"
        assert all(r.get("pipeline") == mode for r in e["rungs"]), e["rungs"]
        extras[mode] = e

    def windows(d):
        recs = [r for rs in load_run(str(d)).values() for r in rs]
        for rec in recs:
            assert not obs.validate_record(rec), rec
        return [r for r in recs if r["kind"] == "serve_window"
                and r.get("rung", -1) >= 0]
    w_off, w_on = windows(tmp_path / "off"), windows(tmp_path / "on")
    assert all(w["pipeline"] == "off" for w in w_off)
    assert all(w["pipeline"] == "on" for w in w_on)
    assert all(w.get("overlap_s", 0.0) > 0.0 for w in w_on)
    mean = lambda ws: sum(w.get("host_share", 0.0) for w in ws) / len(ws)
    assert mean(w_on) < mean(w_off), (w_off, w_on)
    doc = compare.compare(compare.load_side(str(tmp_path / "off")),
                          compare.load_side(str(tmp_path / "on")),
                          threshold=10.0)
    strays = [k for k in list(doc.get("only_a") or []) +
              list(doc.get("only_b") or []) if str(k).startswith("serve.")]
    assert not strays, strays


# ------------------------------------------------- paddle serve e2e


SERVE_CONFIG = """
import sys
sys.path.insert(0, {demo!r})
from paddle.trainer_config_helpers import *
from seqToseq_net import gru_encoder_decoder

settings(batch_size=2, learning_rate=1e-3, learning_method=AdamOptimizer())
gru_encoder_decoder(source_dict_dim=50, target_dict_dim=50,
                    is_generating=True, word_vector_dim=16,
                    encoder_size=16, decoder_size=16, beam_size=1,
                    max_length=6)
"""


def test_paddle_serve_eof_batch_answers_everything(tmp_path):
    """Plain stdin EOF is a BATCH, not an abort: `paddle serve <
    requests.jsonl` completes every accepted request and prints its
    result line before exiting 0 — EOF must not drain-reject the queue
    the client just piped (found driving the real CLI; only a signal
    rejects)."""
    cfg = tmp_path / "serve_conf.py"
    cfg.write_text(SERVE_CONFIG.format(
        demo=os.path.join(REPO, "demo", "seqToseq")))
    reqs = "\n".join(json.dumps(
        {"id": f"b{i}", "prompt": [4 + i, 7], "max_new_tokens": 2 + i}
    ) for i in range(5))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "serve",
         f"--config={cfg}", "--use_tpu=0", "--serve_slots=2",
         "--serve_prompt_tokens=4", "--serve_decode_block=1,2"],
        input=reqs + "\n", capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert out.returncode == 0, out.stderr
    lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
    by_id = {l["id"]: l for l in lines}
    assert set(by_id) == {f"b{i}" for i in range(5)}, by_id
    for i in range(5):
        assert by_id[f"b{i}"]["outcome"] == "ok", by_id
        assert len(by_id[f"b{i}"]["tokens"]) == 2 + i, by_id


def test_paddle_serve_sigterm_graceful_drain(tmp_path):
    """`paddle serve` drains gracefully on SIGTERM: in-flight requests
    complete (their result lines are printed), queued/new requests are
    rejected, the exit code is 0, and run_end status=completed is the
    stream's LAST record."""
    cfg = tmp_path / "serve_conf.py"
    cfg.write_text(SERVE_CONFIG.format(
        demo=os.path.join(REPO, "demo", "seqToseq")))
    run_dir = tmp_path / "run"
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.cli", "serve",
         f"--config={cfg}", "--use_tpu=0", "--serve_slots=2",
         "--serve_prompt_tokens=4", "--serve_decode_block=1,2",
         f"--compile_cache_dir={tmp_path / 'ccache'}",
         f"--metrics_path={run_dir}"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    try:
        for i in range(3):
            proc.stdin.write(json.dumps(
                {"id": f"s{i}", "prompt": [4 + i, 7], "max_new_tokens": 4}
            ) + "\n")
        proc.stdin.flush()
        # wait for the first completed result — the engine is live and
        # mid-load — then ask for the graceful drain. All stdout reads
        # go through the SAME buffered object: readline() may buffer
        # more than one line, and a later communicate() would read the
        # raw fd and silently drop that buffer.
        first = proc.stdout.readline()
        assert first.strip(), "no result line before SIGTERM"
        proc.send_signal(signal.SIGTERM)
        # watchdog: a wedged drain must fail THIS test, not eat the
        # suite budget behind a blocking read
        import threading

        killer = threading.Timer(120.0, proc.kill)
        killer.start()
        try:
            rest = proc.stdout.read()      # until EOF at process exit
            rc = proc.wait(timeout=30)
            err = proc.stderr.read()
        finally:
            killer.cancel()
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdin.close()
        proc.stdout.close()
        proc.stderr.close()
    lines = [json.loads(l) for l in ([first] + rest.splitlines()) if l.strip()]
    assert rc == 0, (rc, err)
    assert "drained" in err
    by_id = {l["id"]: l for l in lines}
    assert set(by_id) == {"s0", "s1", "s2"}, by_id
    assert by_id["s0"]["outcome"] == "ok" and len(by_id["s0"]["tokens"]) == 4
    assert all(l["outcome"] in ("ok", "rejected") for l in lines)
    # telemetry: run_end status=completed is the LAST record
    recs = [r for rs in load_run(str(run_dir)).values() for r in rs]
    assert recs, "no serve telemetry written"
    for rec in recs:
        assert not obs.validate_record(rec), (rec, obs.validate_record(rec))
    assert recs[-1]["kind"] == "run_end"
    assert recs[-1]["status"] == "completed"
    wins = [r for r in recs if r["kind"] == "serve_window"]
    assert wins and wins[-1]["engine"] == "continuous"
