"""Shared harness for the 2-process loopback-cluster tests (the
reference's loopback-pserver testing pattern, test_TrainerOnePass.cpp:
120-296): one worker preamble + one process-pair runner, so the
env/backend setup, the free-port helper, and the kill-on-timeout
subprocess loop live in exactly one place.

Usage (see test_multiprocess*.py):

    WORKER = mp_harness.WORKER_PREAMBLE + '''
    ... body using pid, ws, jax ...
    print("WORKER_OK", pid, flush=True)
    '''
    outs = mp_harness.run_two_workers(
        WORKER.format(repo=REPO, providers=PROVIDERS), ws)

The preamble leaves ``pid`` (process index), ``ws`` (workspace dir,
argv[3]) and an initialized 2-process jax runtime (8 devices, 4 local)
in scope; bodies must end with the WORKER_OK print.
"""

import os
import socket
import subprocess
import sys
import tempfile

WORKER_PREAMBLE = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "").replace("--xla_force_host_platform_device_count=8", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
sys.path.insert(0, {repo!r})
sys.path.insert(0, {providers!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as _xb
for _n in list(_xb._backend_factories):
    if _n not in ("cpu", "tpu"):
        del _xb._backend_factories[_n]

pid = int(sys.argv[1])
jax.distributed.initialize(coordinator_address="localhost:" + sys.argv[2],
                           num_processes=2, process_id=pid)
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4
ws = sys.argv[3]
"""


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_two_workers(worker_src: str, ws: str, timeout: int = 300,
                    check: bool = True):
    """Write ``worker_src`` to ws/worker.py, run it as processes 0 and 1
    joined over a fresh localhost coordinator port, and assert both exit
    0 after printing WORKER_OK (``check=False`` skips the asserts — the
    capability probe's mode). Returns [(rc, stdout, stderr), ...] for
    test-specific assertions on the logs."""
    port = free_port()
    worker_py = os.path.join(ws, "worker.py")
    with open(worker_py, "w") as f:
        f.write(worker_src)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker_py, str(i), str(port), ws],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    if check:
        for rc, out, err in outs:
            assert rc == 0, err[-3000:]
            assert "WORKER_OK" in out, (out, err[-2000:])
    return outs


# ---------------------------------------------------------------------------
# capability probe: can this container run cross-process DEVICE
# computations at all? The CPU backend in CI initializes the 2-process
# distributed runtime fine (KV store, host barriers — the sharded
# checkpoint protocol runs on those and is tested here) but refuses to
# COMPILE a computation spanning both processes ("Multiprocess
# computations aren't implemented on the CPU backend"). Tests that
# train across the pair probe once and skip with the evidence instead
# of failing forever in environments that can never pass them.

_PROBE_WORKER = WORKER_PREAMBLE + """
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
x = jax.make_array_from_callback(
    (8,), NamedSharding(mesh, P("data")),
    lambda idx: np.ones((1,), np.float32),
)
# the smallest cross-process device computation: a global sum whose
# replicated output forces an all-reduce across both processes
total = jax.jit(
    lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P())
)(x)
assert float(total) == 8.0, total
print("WORKER_OK", pid, flush=True)
"""

_probe_result = None  # (supported: bool, evidence: str), cached per session


def cross_process_computations_supported():
    """(supported, evidence) — probed once per pytest session."""
    global _probe_result
    if _probe_result is None:
        ws = tempfile.mkdtemp(prefix="mp_probe_")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        providers = os.path.join(repo, "tests", "providers")
        try:
            outs = run_two_workers(
                _PROBE_WORKER.format(repo=repo, providers=providers),
                ws, timeout=120, check=False,
            )
        except subprocess.TimeoutExpired:
            _probe_result = (False, "probe timed out")
        else:
            ok = all(rc == 0 and "WORKER_OK" in out for rc, out, _ in outs)
            tail = "" if ok else (outs[0][2] or outs[1][2])[-400:]
            _probe_result = (ok, tail)
    return _probe_result


def skip_unless_cross_process_computations():
    """pytest.skip (documented, with the backend's own error as
    evidence) when the container cannot run cross-process device
    computations — the capability the two-process TRAINING tests need.
    Protocol-only tests (host KV barriers, sharded file I/O) must NOT
    call this: those run fine on the CPU backend."""
    import pytest

    ok, evidence = cross_process_computations_supported()
    if not ok:
        pytest.skip(
            "cross-process device computations unsupported in this "
            f"environment (CPU backend): {evidence or 'probe failed'}"
        )
