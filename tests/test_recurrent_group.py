"""Recurrent-group executor tests.

Mirrors the reference's test_RecurrentGradientMachine methodology
(/root/reference/paddle/gserver/tests/): a recurrent_group built from step
layers must numerically match the monolithic fused recurrent layer, and
generation must terminate/shape correctly.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.graph import GradientMachine, make_dense, make_ids, make_seq


def parse_str(src: str):
    import os
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(src))
        path = f.name
    try:
        return parse_config(path)
    finally:
        os.unlink(path)


GRU_PAIR = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=12)
# monolithic fused GRU
m1 = mixed_layer(name="proj_a", size=18,
                 input=[full_matrix_projection(x, param_attr=ParamAttr(name="w_in"))],
                 bias_attr=False)
g1 = grumemory(input=m1, name="gru_fused",
               param_attr=ParamAttr(name="w_rec"),
               bias_attr=ParamAttr(name="b_rec"))
# recurrent_group built from gru_step (explicitly: gru_group itself now
# LOWERS to the fused layer at top level, so the group form under test
# must be constructed by hand)
m2 = mixed_layer(name="proj_b", size=18,
                 input=[full_matrix_projection(x, param_attr=ParamAttr(name="w_in"))],
                 bias_attr=False)
g2 = recurrent_group(
    name="gru_grouped_recurrent_group",
    step=lambda ipt: gru_unit(input=ipt, name="gru_grouped", size=6,
                              gru_bias_attr=ParamAttr(name="b_rec2")),
    input=m2)
outputs(g1)
outputs(g2)
"""


def test_gru_group_matches_fused():
    tc = parse_str(GRU_PAIR)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=5)
    # tie the recurrent weights/biases of both implementations
    grouped_w = [k for k in params if k.startswith("_gru_grouped.w")]
    assert len(grouped_w) == 1, sorted(params)
    params[grouped_w[0]] = params["w_rec"].reshape(params[grouped_w[0]].shape)
    params["b_rec2"] = params["b_rec"].reshape(params["b_rec2"].shape)
    rng = np.random.RandomState(0)
    B, T = 3, 7
    x = rng.randn(B, T, 12).astype(np.float32)
    lengths = np.array([7, 4, 1], np.int32)
    batch = {"x": make_seq(jnp.asarray(x), jnp.asarray(lengths))}
    out, _ = gm.forward(params, batch, "test")
    fused = np.asarray(out["gru_fused"].value)
    grouped = np.asarray(out["gru_grouped"].value)
    np.testing.assert_allclose(fused, grouped, rtol=2e-5, atol=1e-5)


def test_gru_group_inside_group_keeps_group_form():
    """gru_group called inside another recurrent_group's step must keep
    the group form (the lowering is top-level only: a gated_recurrent
    full-sequence layer cannot run inside a sub-scan), and its numerics
    must equal flat grumemory on each subsequence."""
    from paddle_tpu.graph import make_seq
    from paddle_tpu.graph.argument import Argument

    NESTED = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=12)
def outer_step(sub):
    m = mixed_layer(name="proj", size=18, bias_attr=False,
        input=[full_matrix_projection(sub, param_attr=ParamAttr(name="w_in"))])
    return gru_group(input=m, name="igru", size=6,
                     gru_bias_attr=ParamAttr(name="b_rec"))
out = recurrent_group(step=outer_step, input=SubsequenceInput(x), name="outer")
outputs(out)
"""
    FLAT = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=12)
m = mixed_layer(name="proj", size=18, bias_attr=False,
    input=[full_matrix_projection(x, param_attr=ParamAttr(name="w_in"))])
g = grumemory(input=m, name="gflat", param_attr=ParamAttr(name="w_rec2"),
              bias_attr=ParamAttr(name="b_rec"))
outputs(g)
"""
    tc_n = parse_str(NESTED)
    types = {l.name: l.type for l in tc_n.model_config.layers}
    assert types["igru"] == "gru_step"  # group form kept inside a submodel

    B, S, T = 2, 2, 4
    rng = np.random.RandomState(1)
    x_nest = rng.randn(B, S, T, 12).astype(np.float32)
    n_subs = np.array([2, 1], np.int32)
    sub_lens = np.array([[4, 2], [3, 0]], np.int32)
    gm_n = GradientMachine(tc_n.model_config)
    params = gm_n.init_params(seed=3)
    out_n, _ = gm_n.forward(params, {"x": Argument(
        value=jnp.asarray(x_nest),
        seq_lengths=jnp.asarray(n_subs),
        sub_seq_lengths=jnp.asarray(sub_lens),
    )}, "test")
    nested = np.asarray(out_n["outer"].value)          # [B, S, T, 6]

    pairs = [(b, s) for b in range(B) for s in range(n_subs[b])]
    x_flat = np.stack([x_nest[b, s] for b, s in pairs])
    l_flat = np.array([sub_lens[b, s] for b, s in pairs], np.int32)
    tc_f = parse_str(FLAT)
    gm_f = GradientMachine(tc_f.model_config)
    params_f = gm_f.init_params(seed=4)
    params_f["w_in"] = params["w_in"]
    params_f["b_rec"] = params["b_rec"]
    inner_w = [k for k in params if k.startswith("_igru.w")][0]
    params_f["w_rec2"] = params[inner_w].reshape(params_f["w_rec2"].shape)
    out_f, _ = gm_f.forward(
        params_f, {"x": make_seq(jnp.asarray(x_flat), jnp.asarray(l_flat))}, "test"
    )
    flat = np.asarray(out_f["gflat"].value)
    for i, (b, s) in enumerate(pairs):
        l = int(sub_lens[b, s])
        np.testing.assert_allclose(
            nested[b, s, :l], flat[i, :l], rtol=2e-5, atol=1e-6,
            err_msg=f"subseq {(b, s)}",
        )


def test_prologue_hoisting_parity_nmt(monkeypatch):
    """The NMT decoder's target-word projection (mixed: fc(context) +
    fc(current_word)) is prologue-hoisted out of the scan; loss and every
    gradient must match the unhoisted computation."""
    import paddle_tpu.graph.recurrent_group as rg
    from paddle_tpu.flagship import nmt_batch, nmt_config

    tc = nmt_config(vocab=120, dim=32)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=1)
    batch = nmt_batch(vocab=120, B=4, T=6)
    l_on, g_on, _, _ = gm.grad_fn()(params, batch, None)

    captured = {}
    orig = rg._plan_prologue

    def disabled(network, sub, skip):
        captured.update(orig(network, sub, skip))
        return {}

    monkeypatch.setattr(rg, "_plan_prologue", disabled)
    l_off, g_off, _, _ = gm.grad_fn()(params, batch, None)
    assert captured, "expected the decoder to have hoistable projections"
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-5)
    for k in g_off:
        np.testing.assert_allclose(
            np.asarray(g_on[k]), np.asarray(g_off[k]), rtol=2e-4, atol=1e-6,
            err_msg=k,
        )


def test_prologue_hoisting_reversed_group(monkeypatch):
    """Hoisted slices ride the scan xs, so reversed groups consume them in
    reverse exactly like the in-links themselves."""
    import paddle_tpu.graph.recurrent_group as rg

    SRC = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=5)
def rnn_step(y):
    mem = memory(name="rstep", size=6)
    return mixed_layer(name="rstep", size=6, act=TanhActivation(), bias_attr=False,
        input=[full_matrix_projection(y, param_attr=ParamAttr(name="w_x")),
               full_matrix_projection(mem, param_attr=ParamAttr(name="w_h"))])
out = recurrent_group(step=rnn_step, input=x, name="rev_rnn", reverse=True)
outputs(out)
"""
    tc = parse_str(SRC)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=2)
    rng = np.random.RandomState(3)
    batch = {
        "x": make_seq(
            jnp.asarray(rng.randn(3, 7, 5).astype(np.float32)),
            jnp.asarray(np.array([7, 4, 1], np.int32)),
        )
    }
    out_on, _ = gm.forward(params, batch, "test")
    captured = {}
    orig = rg._plan_prologue

    def disabled(network, sub, skip):
        captured.update(orig(network, sub, skip))
        return {}

    monkeypatch.setattr(rg, "_plan_prologue", disabled)
    out_off, _ = gm.forward(params, batch, "test")
    assert captured, "expected the reversed group's in-link fc to be hoisted"
    np.testing.assert_allclose(
        np.asarray(out_on["rev_rnn"].value), np.asarray(out_off["rev_rnn"].value),
        rtol=2e-5, atol=1e-6,
    )


def test_gru_group_lowers_to_fused_layer():
    # top-level gru_group emits ONE gated_recurrent layer (the reference
    # documents the two as computing the same thing; the fused form is
    # the fast one) with the group-era layer/parameter names preserved
    tc = parse_str("""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=12)
g = simple_gru(input=x, name="enc", size=4)
outputs(g)
""")
    types = {l.name: l.type for l in tc.model_config.layers}
    assert types["enc"] == "gated_recurrent"
    assert "enc_recurrent_group" not in types
    assert any(p.name == "_enc.w0" for p in tc.model_config.parameters)


def test_gru_group_force_group_keeps_group_form():
    # escape hatch (doc/divergences.md): force_group=True keeps the
    # reference's '<name>_recurrent_group' submodel + step-level memory
    # for configs that reference the step form
    tc = parse_str("""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=12)
g = gru_group(input=x, name="enc", size=4, force_group=True)
outputs(g)
""")
    types = {l.name: l.type for l in tc.model_config.layers}
    assert "gated_recurrent" not in types.values()
    assert any("enc_recurrent_group" in s.name for s in tc.model_config.sub_models)


LSTM_PAIR = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=10)
m1 = mixed_layer(name="proj_a", size=24,
                 input=[full_matrix_projection(x, param_attr=ParamAttr(name="w_in"))],
                 bias_attr=False)
l1 = lstmemory(input=m1, name="lstm_fused",
               param_attr=ParamAttr(name="w_rec"),
               bias_attr=ParamAttr(name="b_rec"))
m2 = mixed_layer(name="proj_b", size=24,
                 input=[full_matrix_projection(x, param_attr=ParamAttr(name="w_in"))],
                 bias_attr=False)
l2 = lstmemory_group(input=m2, name="lstm_grouped", size=6,
                     param_attr=ParamAttr(name="w_rec2"),
                     lstm_bias_attr=ParamAttr(name="b_rec2"))
outputs(l1)
outputs(l2)
"""


def test_lstm_group_matches_fused():
    tc = parse_str(LSTM_PAIR)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=7)
    params["w_rec2"] = params["w_rec"].reshape(params["w_rec2"].shape)
    params["b_rec2"] = params["b_rec"].reshape(params["b_rec2"].shape)
    rng = np.random.RandomState(1)
    B, T = 2, 5
    x = rng.randn(B, T, 10).astype(np.float32)
    lengths = np.array([5, 3], np.int32)
    batch = {"x": make_seq(jnp.asarray(x), jnp.asarray(lengths))}
    out, _ = gm.forward(params, batch, "test")
    fused = np.asarray(out["lstm_fused"].value)
    grouped = np.asarray(out["lstm_grouped"].value)
    np.testing.assert_allclose(fused, grouped, rtol=2e-5, atol=1e-5)


def test_recurrent_group_gradcheck():
    tc = parse_str("""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=9)
g = simple_gru(input=x, size=3, name="g")
pool = last_seq(input=g, name="pool")
label = data_layer(name="label", size=3)
out = fc_layer(input=pool, size=3, act=SoftmaxActivation(), name="out")
outputs(classification_cost(input=out, label=label))
""")
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=2)
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, 9).astype(np.float32)
    batch = {
        "x": make_seq(jnp.asarray(x), jnp.asarray(np.array([4, 2], np.int32))),
        "label": make_ids(np.array([0, 2], np.int32)),
    }
    report = gm.check_gradient(params, batch, max_entries=4)
    for name, diff in report.items():
        assert diff < 5e-2, f"{name}: {diff}"


def test_attention_seq2seq_with_static_input():
    """recurrent_group with StaticInput + simple_attention (the seqToseq
    decoder shape, ref demo/seqToseq/seqToseq_net.py)."""
    tc = parse_str("""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
src = data_layer(name="src", size=20)
src_emb = embedding_layer(input=src, size=8, param_attr=ParamAttr(name="src_emb"))
enc = simple_gru(input=src_emb, size=8, name="encoder")
enc_proj = mixed_layer(name="enc_proj", size=8,
                       input=[full_matrix_projection(enc)])
trg = data_layer(name="trg", size=20)
trg_emb = embedding_layer(input=trg, size=8, param_attr=ParamAttr(name="trg_emb"))

def decoder_step(enc_seq, enc_p, cur_emb):
    decoder_mem = memory(name="dec_state", size=8)
    context = simple_attention(encoded_sequence=enc_seq, encoded_proj=enc_p,
                               decoder_state=decoder_mem, name="att")
    inputs = mixed_layer(size=8*3, input=[full_matrix_projection(context),
                                          full_matrix_projection(cur_emb)])
    return gru_step_layer(input=inputs, output_mem=decoder_mem,
                          size=8, name="dec_state")

dec = recurrent_group(step=decoder_step,
                      input=[StaticInput(enc, is_seq=True),
                             StaticInput(enc_proj, is_seq=True),
                             trg_emb],
                      name="decoder_group")
out = fc_layer(input=dec, size=20, act=SoftmaxActivation(), name="out")
label = data_layer(name="label", size=20)
outputs(classification_cost(input=out, label=label))
""")
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=4)
    rng = np.random.RandomState(5)
    B, S, T = 2, 6, 5
    src_ids = rng.randint(0, 20, (B, S)).astype(np.int32)
    trg_ids = rng.randint(0, 20, (B, T)).astype(np.int32)
    lab_ids = rng.randint(0, 20, (B, T)).astype(np.int32)
    batch = {
        "src": make_seq(None, np.array([6, 3], np.int32), ids=src_ids),
        "trg": make_seq(None, np.array([5, 2], np.int32), ids=trg_ids),
        "label": make_seq(None, np.array([5, 2], np.int32), ids=lab_ids),
    }
    out, _ = gm.forward(params, batch, "test")
    assert out["out"].value.shape == (B, T, 20)
    loss = gm.total_cost(out)
    assert np.isfinite(float(loss))
    # jit the loss to ensure the whole scan traces
    f = jax.jit(lambda p: gm.loss_fn(p, batch, None)[0])
    assert np.isfinite(float(f(params)))


def test_beam_search_generation():
    tc = parse_str("""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
src = data_layer(name="src", size=10)
src_emb = embedding_layer(input=src, size=6, param_attr=ParamAttr(name="emb"))
enc = simple_gru(input=src_emb, size=6, name="encoder")
enc_last = last_seq(input=enc, name="enc_last")

def gen_step(enc_l, cur_emb):
    mem = memory(name="dec", size=6, boot_layer=enc_l)
    inp = mixed_layer(size=18, input=[full_matrix_projection(cur_emb)],
                      name="dec_in")
    step = gru_step_layer(input=inp, output_mem=mem, size=6, name="dec")
    return fc_layer(input=step, size=10, act=SoftmaxActivation(), name="scores")

gen = beam_search(step=gen_step,
                  input=[StaticInput(enc_last),
                         GeneratedInput(size=10, embedding_name="emb",
                                        embedding_size=6)],
                  bos_id=0, eos_id=1, beam_size=3, max_length=7,
                  name="generator")
""")
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=6)
    src_ids = np.array([[2, 3, 4, 0], [5, 6, 0, 0]], np.int32)
    batch = {"src": make_seq(None, np.array([3, 2], np.int32), ids=src_ids)}
    out, _ = gm.forward(params, batch, "gen")
    gen_out = out["generator"]
    assert gen_out.ids.shape == (2, 7)
    assert gen_out.seq_lengths.shape == (2,)
    assert np.all(np.asarray(gen_out.seq_lengths) <= 7)
    beams = out["generator@beams"]
    assert beams.ids.shape == (2, 3, 7)
    assert beams.value.shape == (2, 3)
    # scores sorted descending per sample
    sc = np.asarray(beams.value)
    assert np.all(np.diff(sc, axis=1) <= 1e-6)


def test_greedy_generation_matches_manual_rollout():
    """beam_size=1 must equal an argmax rollout computed step by step."""
    tc = parse_str("""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
boot = data_layer(name="boot", size=5)

def gen_step(b, cur_emb):
    mem = memory(name="dec", size=5, boot_layer=b)
    inp = mixed_layer(size=15, input=[full_matrix_projection(cur_emb, param_attr=ParamAttr(name="w_x"))],
                      name="dec_in", bias_attr=False)
    step = gru_step_layer(input=inp, output_mem=mem, size=5, name="dec",
                          param_attr=ParamAttr(name="w_g"), bias_attr=False)
    return fc_layer(input=step, size=8, act=SoftmaxActivation(), name="scores",
                    param_attr=ParamAttr(name="w_s"), bias_attr=False)

gen = beam_search(step=gen_step,
                  input=[StaticInput(boot),
                         GeneratedInput(size=8, embedding_name="gen_emb",
                                        embedding_size=6)],
                  bos_id=0, eos_id=1, beam_size=1, max_length=5,
                  name="generator")
""")
    # the generated-id embedding table parameter
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=9)
    B = 2
    boot = np.random.RandomState(1).randn(B, 5).astype(np.float32)
    batch = {"boot": make_dense(jnp.asarray(boot))}
    out, _ = gm.forward(params, batch, "gen")
    got = np.asarray(out["generator"].ids)

    # manual rollout in numpy
    emb_name = "gen_emb"
    emb = np.asarray(params[emb_name])
    w_x = np.asarray(params["w_x"]).reshape(6, 15)
    w_g = np.asarray(params["w_g"]).reshape(5, 15)
    w_s = np.asarray(params["w_s"]).reshape(5, 8)
    sigmoid = lambda v: 1 / (1 + np.exp(-v))
    h = boot.copy()
    tok = np.zeros((B,), np.int32)
    done = np.zeros((B,), bool)
    expect = []
    for t in range(5):
        e = emb[tok]
        x3 = e @ w_x
        g = x3[:, :10] + h @ w_g[:, :10]
        u, r = sigmoid(g[:, :5]), sigmoid(g[:, 5:10])
        cand = np.tanh(x3[:, 10:] + (r * h) @ w_g[:, 10:])
        h_new = u * h + (1 - u) * cand
        h = np.where(done[:, None], h, h_new)
        probs = _np_softmax(h @ w_s)
        nxt = np.argmax(probs, axis=1).astype(np.int32)
        nxt = np.where(done, 1, nxt)
        expect.append(nxt)
        done = done | (nxt == 1)
        tok = nxt
    expect = np.stack(expect, axis=1)
    # guard against a trivially-passing comparison: the rollout must run
    # several live steps so decoder-state advancement is actually tested
    live_steps = (expect != 1).sum(axis=1)
    assert live_steps.max() >= 3, f"rollout finished too early to be a real test: {expect}"
    np.testing.assert_array_equal(got, expect)


def _np_softmax(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def test_beam_scores_rescore_exactly():
    """Every returned beam's reported cumulative log-prob must equal a
    numpy re-scoring of its token sequence under the model — the
    bookkeeping check on beam reindexing/freezing (a memoryless step
    makes exact re-scoring trivial: scores depend only on prev token)."""
    V, E, EOS = 7, 4, 6
    tc = parse_str(f"""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
boot = data_layer(name="boot", size={E})

def gen_step(b, cur_emb):
    h = mixed_layer(size={E}, name="h", bias_attr=False,
                    input=[full_matrix_projection(cur_emb, param_attr=ParamAttr(name="wx"))])
    comb = addto_layer(input=[h, b], act=TanhActivation(), bias_attr=False)
    return fc_layer(input=comb, size={V}, act=SoftmaxActivation(), name="scores")

gen = beam_search(step=gen_step,
                  input=[StaticInput(boot),
                         GeneratedInput(size={V}, embedding_name="Tg",
                                        embedding_size={E})],
                  bos_id=0, eos_id={EOS}, beam_size=3, max_length=5,
                  name="generator")
""")
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=8)
    B = 2
    rng = np.random.RandomState(2)
    boot = rng.randn(B, E).astype(np.float32) * 1.5
    out, _ = gm.forward(params, {"boot": make_dense(boot)}, "gen")
    beams = out["generator@beams"]
    ids = np.asarray(beams.ids)            # [B, K, L]
    scores = np.asarray(beams.value)       # [B, K]
    lens = np.asarray(beams.sub_seq_lengths)  # [B, K]

    Tg = np.asarray(params["Tg"])
    Wx = np.asarray(params["wx"])
    W = np.asarray(params["_scores.w0"])
    bias = np.asarray(params["_scores.wbias"]).reshape(-1)

    def logp(b, prev, tok):
        comb = np.tanh(Tg[prev] @ Wx + boot[b])
        z = comb @ W + bias
        z = z - z.max()
        p = np.exp(z) / np.exp(z).sum()
        return np.log(max(p[tok], 1e-20))

    for b in range(B):
        for k in range(ids.shape[1]):
            L = int(lens[b, k])
            assert L > 0
            prev, total = 0, 0.0
            for t in range(L):
                tok = int(ids[b, k, t])
                total += logp(b, prev, tok)
                prev = tok
            np.testing.assert_allclose(total, scores[b, k], rtol=2e-4,
                                       atol=2e-4, err_msg=f"{b},{k}")


def test_epilogue_hoisting_equivalence(monkeypatch):
    """The hoisted-epilogue scan must produce bit-for-bit the loss and
    gradients of the everything-inside scan (hoisting is scheduling, not
    math): run the same attention decoder config with hoisting disabled
    and compare."""
    import paddle_tpu.graph.recurrent_group as rg
    from paddle_tpu.flagship import nmt_batch, nmt_config

    tc = nmt_config(vocab=120, dim=16)
    batch = nmt_batch(vocab=120, B=3, T=6, seed=1)

    plans = []
    real_plan = rg._plan_epilogue

    def spy(*a):
        p = real_plan(*a)
        plans.append(p)
        return p

    def run(disable):
        if disable:
            monkeypatch.setattr(rg, "_plan_epilogue", lambda *a: None)
        else:
            monkeypatch.setattr(rg, "_plan_epilogue", spy)
        gm = GradientMachine(tc.model_config)
        params = gm.init_params(seed=3)
        loss, grads = jax.value_and_grad(
            lambda p: gm.loss_fn(p, batch, None)[0]
        )(params)
        monkeypatch.undo()
        return float(loss), {k: np.asarray(v) for k, v in grads.items()}

    l_hoist, g_hoist = run(disable=False)
    # the hoisted path must actually have engaged for the decoder scorer
    assert any(p is not None and p[0] for p in plans), plans
    l_plain, g_plain = run(disable=True)
    np.testing.assert_allclose(l_hoist, l_plain, rtol=1e-6)
    for k in g_plain:
        np.testing.assert_allclose(g_hoist[k], g_plain[k], rtol=1e-5,
                                   atol=1e-7, err_msg=k)


def test_epilogue_hoists_static_reader(monkeypatch):
    """A hoisted layer reading a (non-sequence) StaticInput: the static is
    tiled outside the scan; results must match the unhoisted run."""
    import paddle_tpu.graph.recurrent_group as rg

    tc = parse_str("""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=0.1)
word = data_layer(name="word", size=30)
cond = data_layer(name="cond", size=6)
emb = embedding_layer(input=word, size=6)
def step(x_t, c):
    mem = memory(name="rnn", size=6)
    h = fc_layer(input=[x_t, mem], size=6, act=TanhActivation(), name="rnn")
    return addto_layer(input=[h, c], act=LinearActivation(), name="out",
                       bias_attr=False)
rg_out = recurrent_group(step=step, input=[emb, StaticInput(cond)], name="grp")
pool = pooling_layer(input=rg_out, pooling_type=AvgPooling())
o = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
label = data_layer(name="label", size=2)
outputs(classification_cost(input=o, label=label))
""")
    rngnp = np.random.RandomState(0)
    B, T = 3, 5
    batch = {
        "word": make_seq(None, np.array([5, 3, 4], np.int32),
                         ids=rngnp.randint(0, 30, (B, T)).astype(np.int32)),
        "cond": make_dense(rngnp.randn(B, 6).astype(np.float32)),
        "label": make_ids(rngnp.randint(0, 2, (B,)).astype(np.int32)),
    }

    plans = []
    real = rg._plan_epilogue

    def spy(*a):
        p = real(*a)
        plans.append(p)
        return p

    def run(disable):
        monkeypatch.setattr(rg, "_plan_epilogue",
                            (lambda *a: None) if disable else spy)
        gm = GradientMachine(tc.model_config)
        params = gm.init_params(seed=2)
        loss, grads = jax.value_and_grad(
            lambda p: gm.loss_fn(p, batch, None)[0]
        )(params)
        monkeypatch.undo()
        return float(loss), {k: np.asarray(v) for k, v in grads.items()}

    l_h, g_h = run(False)
    assert any(p is not None and "out" in p[0] for p in plans), plans
    l_p, g_p = run(True)
    np.testing.assert_allclose(l_h, l_p, rtol=1e-6)
    for k in g_p:
        np.testing.assert_allclose(g_h[k], g_p[k], rtol=1e-5, atol=1e-7,
                                   err_msg=k)
