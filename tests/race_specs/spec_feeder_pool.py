"""Race spec: the feeder packer pool's bounded future queue.

The feeder's ``_pool_packed`` (PR 5) runs a dispatcher thread that
submits pack jobs to a worker pool and hands ORDER-PRESERVING futures
to the consumer through a queue bounded at ``--prefetch_depth``; the
consumer double-waits (queue get, then future result) under the stall
watchdog. The production pool is ``concurrent.futures``'s executor,
whose internal threads the shim cannot gate — so this spec drives the
same discipline with shim-visible parts: a dispatcher thread, a
bounded ``cc.Queue`` of future-like cells, one packer worker, and a
consumer double-wait, all on the virtual scheduler.

What the exploration buys over tests/test_feeder_pool.py's wall-clock
runs: every put/get/wait interleaving of the backpressure edge (queue
full exactly when the dispatcher finishes; sentinel racing the last
future) is exercised, and a lost-wakeup in the handoff discipline —
e.g. a sentinel placed before the last future resolves, or a bounded
put that nothing ever drains — quiesces a non-daemon thread and
becomes a finding instead of a flaky timeout.

Invariants: the consumer receives every batch exactly once, in
submission order; both pipeline threads terminate.
"""

import queue as std_queue

from paddle_tpu.utils import concurrency as cc

NAME = "feeder_pool"

DEPTH = 2
BATCHES = 5


class _Future:
    """Order-preserving future cell: the packer sets the result, the
    consumer waits — the same two-wait shape as Future.result()."""

    def __init__(self):
        self.done = cc.Event()
        self.value = None

    def set(self, value):
        self.value = value
        self.done.set()

    def result(self, timeout=None):
        if not self.done.wait(timeout=timeout):
            raise TimeoutError()
        return self.value


def run(ctx):
    out_q = cc.Queue(maxsize=DEPTH)   # bounded future queue (backpressure)
    work_q = cc.Queue()               # dispatcher -> packer
    sentinel = object()
    received = []

    def packer():
        while True:
            item = work_q.get()
            if item is sentinel:
                return
            fut, batch = item
            fut.set(("packed", batch))

    def dispatcher():
        try:
            for batch in range(BATCHES):
                fut = _Future()
                work_q.put((fut, batch))
                # the bounded put IS the backpressure: at most DEPTH
                # packed/packing batches run ahead of the consumer
                out_q.put(fut)
        finally:
            out_q.put(sentinel)
            work_q.put(sentinel)

    tp = cc.Thread(target=packer, name="packer", daemon=False)
    td = cc.Thread(target=dispatcher, name="dispatcher", daemon=False)
    tp.start()
    td.start()

    # the consumer's double-wait (bounded, like _watched_get's polls)
    while True:
        try:
            fut = out_q.get(timeout=30.0)
        except std_queue.Empty:
            raise AssertionError("consumer starved: dispatcher stalled")
        if fut is sentinel:
            break
        received.append(fut.result(timeout=30.0))

    td.join()
    tp.join()
    assert received == [("packed", b) for b in range(BATCHES)], received
