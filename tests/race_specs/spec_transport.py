"""Race spec: socket transport — reconnect-vs-send and
hedge-vs-first-answer interleavings over the REAL
:class:`SocketTransport` / :class:`FleetRouter`, with the wire replaced
by an in-memory duplex pipe built from cc primitives (the transport
takes ``connect_fn`` exactly for this seam).

Phase 1 — reconnect vs send: a sender thread pushes frames while the
server end drops the connection mid-stream. The transport's contract:
``send()`` returning True means the frame reached the peer's buffer
(the fake wire drains buffered bytes before reporting EOF, so every
accepted frame decodes); a drop surfaces as send() == False plus a
reconnect, never a crash, a torn decode or a duplicate. After the drop
the state machine must come back UP and deliver a marker frame on the
new wire.

Phase 2 — hedge vs first answer: a two-replica fleet where one replica
answers slowly; ``hedge_after`` is tiny, so the router's hedge loop
races the owner's late answer. Whichever side wins, every submitted id
is emitted exactly once in order, the loser is absorbed into
``duplicate_answers``, and the hedge counters stay consistent
(``hedge_wins <= hedges``).

Invariants:
- no frame is lost after being accepted, none decodes twice;
- a dropped connection advances ``reconnects`` and ends UP, not CLOSED;
- fleet exactly-once holds under hedging (no double emission, order
  kept, ``run()`` terminates);
- ``hedge_wins <= hedges`` and duplicates are counted, never emitted.
"""

import logging

from paddle_tpu.serving import transport
from paddle_tpu.serving.fleet import FleetRouter
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.retry import RetryPolicy

NAME = "transport"


# ------------------------------------------------- in-memory duplex wire


class FakeWire:
    """One end of an in-memory duplex pipe speaking the socket subset
    the transport uses (sendall/recv/close/settimeout), built on cc
    primitives so `paddle race` can interleave it. Closing either end
    closes both; buffered bytes drain before EOF — like a real TCP
    FIN, which delivers what was already in flight."""

    def __init__(self):
        self._lock = cc.Lock()
        self._cv = cc.Condition(self._lock)
        self._buf = bytearray()
        self._closed = False
        self.peer = None  # wired by _pipe()

    def settimeout(self, t):
        pass

    def sendall(self, data):
        p = self.peer
        with p._lock:
            if p._closed:
                raise ConnectionResetError(104, "peer closed")
            p._buf += data
            p._cv.notify_all()

    def recv(self, n):
        with self._lock:
            while not self._buf and not self._closed:
                self._cv.wait(timeout=0.05)
            if self._buf:
                out = bytes(self._buf[:n])
                del self._buf[:n]
                return out
            return b""

    def close(self):
        for w in (self, self.peer):
            with w._lock:
                w._closed = True
                w._cv.notify_all()


def _pipe():
    a, b = FakeWire(), FakeWire()
    a.peer, b.peer = b, a
    return a, b


def run(ctx):
    # connection drops log warnings per explored schedule — keep the
    # analyzer report readable
    logger = logging.getLogger("paddle_tpu")
    prev = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        _phase_reconnect_vs_send(ctx)
        _phase_hedge_vs_first_answer(ctx)
    finally:
        logger.setLevel(prev)


# ------------------------------------------- phase 1: reconnect vs send


def _phase_reconnect_vs_send(ctx):
    decoded = []
    dlock = cc.Lock()
    conns = []
    clock_ = cc.Lock()

    def serve(wire):
        reader = transport.FrameReader()
        while True:
            data = wire.recv(65536)
            if not data:
                return
            for doc in reader.feed(data):
                with dlock:
                    decoded.append(doc)

    def connect(addr):
        a, b = _pipe()
        with clock_:
            conns.append(b)
        cc.Thread(target=serve, args=(b,), name="fake-server",
                  daemon=True).start()
        return a

    policy = RetryPolicy(max_attempts=1000, base_delay=0.001,
                         max_delay=0.005, jitter=0.0, name="net.connect")
    t = transport.SocketTransport("c0", "fake:0", on_frame=lambda d: None,
                                  policy=policy, connect_fn=connect)
    ctx.static_watch(t)
    t.start()

    sent = []
    slock = cc.Lock()

    def sender():
        for i in range(4):
            rid = f"s{i}"
            while not t.send({"id": rid}):
                if t.closed():
                    return
                cc.sleep(0.002)
            with slock:
                sent.append(rid)

    st = cc.Thread(target=sender, name="sender")
    st.start()
    # drop the FIRST connection while the sends race it
    deadline = cc.monotonic() + 60.0
    first = None
    while cc.monotonic() < deadline:
        with clock_:
            if conns:
                first = conns[0]
                break
        cc.sleep(0.001)
    assert first is not None, "transport never connected"
    first.close()
    st.join()
    # the state machine must come back UP and deliver on the new wire
    while not t.send({"id": "marker"}):
        assert not t.closed(), "transport gave up instead of reconnecting"
        cc.sleep(0.002)
    with slock:
        sent.append("marker")
    deadline = cc.monotonic() + 60.0
    while cc.monotonic() < deadline:
        with dlock:
            if any(d.get("id") == "marker" for d in decoded):
                break
        cc.sleep(0.002)
    t.close()
    assert t.join(timeout=30.0), "transport thread did not exit"
    ids = [d.get("id") for d in decoded]
    assert len(ids) == len(set(ids)), f"duplicate decode: {ids}"
    assert set(ids) <= set(sent), (ids, sent)
    assert "marker" in ids, "reconnected wire never delivered"
    assert t.reconnects >= 1, "drop did not advance reconnects"


# -------------------------------------- phase 2: hedge vs first answer


class HedgeReplica:
    """Minimal ProcReplica duck-type: a worker answers each routed doc
    after ``delay_s`` — slow enough on one replica that the router's
    hedge loop races the owner's own late answer."""

    def __init__(self, name, delay_s):
        self.name = name
        self.delay_s = delay_s
        self.deliver = None
        self._lock = cc.Lock()
        self._cv = cc.Condition(self._lock)
        self._queue = []
        self._alive = False
        self._draining = False
        self._exit = None
        self._worker = None

    def start(self):
        with self._lock:
            self._alive = True
            self._exit = None
        self._worker = cc.Thread(target=self._run,
                                 name=f"hedge-{self.name}", daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            with self._lock:
                while not self._queue and self._alive and not self._draining:
                    self._cv.wait(timeout=0.05)
                if not self._alive:
                    return
                if not self._queue:
                    self._alive = False
                    self._exit = 0
                    return
                doc = self._queue.pop(0)
            cc.sleep(self.delay_s)
            with self._lock:
                if not self._alive:
                    return
            self.deliver(self.name, {
                "id": str(doc.get("id")), "outcome": "ok",
                "tokens": [1] * int(doc.get("max_new_tokens") or 1),
            })

    def alive(self):
        with self._lock:
            return self._alive

    def poll_exit(self):
        with self._lock:
            return self._exit

    def send(self, doc):
        with self._lock:
            if not self._alive or self._draining:
                return False
            self._queue.append(dict(doc))
            self._cv.notify_all()
        return True

    def health(self, now):
        with self._lock:
            return {"state": "serving", "queue_depth": len(self._queue),
                    "occupancy": 0}

    def pending_requests(self):
        return []

    def begin_drain(self):
        with self._lock:
            self._draining = True
            self._cv.notify_all()

    def kill(self):
        with self._lock:
            self._alive = False
            self._exit = 9
            self._cv.notify_all()

    def join(self, timeout):
        w = self._worker
        if w is not None:
            w.join(timeout=timeout)
            return not w.is_alive()
        return True


def _phase_hedge_vs_first_answer(ctx):
    emitted = []
    elock = cc.Lock()

    def emit(doc):
        with elock:
            emitted.append(doc)

    reps = [HedgeReplica("replica-0", delay_s=0.2),
            HedgeReplica("replica-1", delay_s=0.01)]
    router = FleetRouter(reps, emit=emit, poll_s=0.005,
                         health_period_s=0.0, restart_base_delay=0.02,
                         hedge_after=0.005)
    for r in reps:
        r.deliver = router.deliver
    ctx.static_watch(router)
    router.start()
    box = {}

    def target():
        box["rc"] = router.run()

    t = cc.Thread(target=target, name="fleet-run", daemon=True)
    t.start()
    submitted = [f"h{i}" for i in range(4)]
    for rid in submitted:
        assert router.submit({"id": rid, "prompt": [2, 3],
                              "max_new_tokens": 1})
    router.note_eof()
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate (hedge phase)"
    assert box["rc"] == 0, box
    ids = [str(d.get("id")) for d in emitted]
    assert len(ids) == len(set(ids)), f"double-emitted: {ids}"
    assert set(ids) == set(submitted), (set(ids), set(submitted))
    with router._lock:
        order = list(router._order)
    assert ids == order, ("emission violated submission order", ids, order)
    for d in emitted:
        assert d.get("outcome") == "ok", d
    st = router.status()
    assert st["hedge_wins"] <= st["hedges"], st
    # a hedge's loser answers late: it must be absorbed, never emitted
    assert st["duplicate_answers"] <= st["hedges"], st
    router.shutdown(timeout=10.0)
