"""Race spec: ShardedAsyncCheckpointer writer + commit agreement.

Two REAL ShardedAsyncCheckpointer instances (pid 0 and pid 1) run in
two virtual "host" threads, each with its own background writer
thread — four threads total — over an IN-PROCESS fake of the jax
distributed runtime's KV store (publish / barrier / read-back built
on the virtualized lock + condition, so the rendezvous itself is
explored for lock-order and lost-wakeup hazards). The write, snapshot,
and finalize seams are jax-free fakes; everything else — the bounded
queue, the drain protocol, the two agreement rounds, the intersection
commit — is the production code of PR 6.

Invariants (any violating interleaving becomes a finding):

- both hosts leave drain() the same way (both return: the commit round
  aligned them; the asymmetric outcome is the desync PR 6's verdict
  round exists to prevent);
- the committed set is the INTERSECTION of both hosts' locally-durable
  passes, finalized in order by pid 0 only;
- per-host writer completion counts match their enqueue counts (host 1
  drops its oldest under a smaller queue bound — dropped + completed
  must still account for every save).
"""

import json

from paddle_tpu.trainer.async_ckpt import ShardedAsyncCheckpointer
from paddle_tpu.utils import concurrency as cc

NAME = "sharded_commit"


class _KvStore:
    """In-process twin of the distributed KV rendezvous: set + barrier
    + directory read, over virtualized primitives."""

    def __init__(self, count):
        self.count = count
        self.lock = cc.Lock()
        self.cv = cc.Condition(self.lock)
        self.store = {}
        self.arrived = {}

    def agree(self, round_no, pid, payload):
        with self.cv:
            self.store[(round_no, pid)] = payload
            self.arrived[round_no] = self.arrived.get(round_no, 0) + 1
            self.cv.notify_all()
            while self.arrived[round_no] < self.count:
                self.cv.wait(timeout=60.0)
        return [
            self.store[(round_no, p)] for p in range(self.count)
        ]


class _Client:
    """The per-process agreement seam (same surface as _KvAgreement)."""

    def __init__(self, kv, pid):
        self.kv = kv
        self.pid = pid
        self._round = 0

    def agree(self, payload):
        r = self._round
        self._round += 1
        return self.kv.agree(r, self.pid, payload)


def _host(pid, kv, finals, durables, errors):
    written = []

    def write_fn(save_dir, pass_id, snapshot, wpid):
        written.append(pass_id)

    def snapshot_fn(pass_id, params, opt_state, extra_meta):
        return {"params": (["w"], {"w": pass_id})}, {"pass": pass_id}

    def finalize_fn(pass_id, job, rotate):
        finals.append((pid, pass_id, rotate))
        return f"pass-{pass_id}"

    ac = ShardedAsyncCheckpointer(
        "", inflight_limit=2 if pid == 0 else 1,
        process_index=pid, process_count=2, agreement=_Client(kv, pid),
        write_fn=write_fn, snapshot_fn=snapshot_fn, finalize_fn=finalize_fn,
    )

    def body():
        try:
            ac.save(0, {"w": 0}, on_durable=(
                (lambda p, path: durables.append((pid, p)))
                if pid == 0 else None
            ))
            ac.save(1, {"w": 1}, on_durable=(
                (lambda p, path: durables.append((pid, p)))
                if pid == 0 else None
            ))
            ac.drain()
        except BaseException as e:  # recorded, judged by the invariants
            errors.append((pid, repr(e)))
            raise

    return ac, body, written


def run(ctx):
    import logging

    # drop-oldest warnings are the code under test, once per schedule
    # that drops — bottled up so the analyzer report stays readable
    logger = logging.getLogger("paddle_tpu")
    prev_level = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        _run(ctx)
    finally:
        logger.setLevel(prev_level)


def _run(ctx):
    kv = _KvStore(2)
    finals, durables, errors = [], [], []
    ac0, body0, written0 = _host(0, kv, finals, durables, errors)
    ac1, body1, written1 = _host(1, kv, finals, durables, errors)
    ctx.static_watch(ac0)
    ctx.static_watch(ac1)

    t1 = cc.Thread(target=body1, name="host1", daemon=False)
    t1.start()
    body0()  # host 0 runs on the spec main thread
    t1.join()

    # --- invariants ---
    assert errors == [], f"drain desync: {errors}"
    # per-host accounting: every save either wrote or was dropped
    assert len(written0) == ac0.completed and len(written1) == ac1.completed
    assert ac0.completed + ac0.dropped == 2, (ac0.completed, ac0.dropped)
    assert ac1.completed + ac1.dropped == 2, (ac1.completed, ac1.dropped)
    # the commit set is the intersection, finalized by pid 0, in order,
    # with exactly one rotation on the last commit
    commit = sorted(set(written0) & set(written1))
    assert [p for (_pid, p, _r) in finals] == commit, (finals, commit)
    assert all(f[0] == 0 for f in finals), f"non-pid0 finalize: {finals}"
    if finals:
        assert [r for (_pid, _p, r) in finals] == (
            [False] * (len(finals) - 1) + [True]
        ), f"rotation not exactly-once-at-end: {finals}"
    assert sorted(p for (_pid, p) in durables) == commit, (durables, commit)
    # the agreement rounds stayed aligned: both clients advanced in
    # lockstep (publish round + verdict round per drain that saw work)
    assert len({(r, p) for (r, p) in kv.store}) == len(kv.store)
    rounds = {r for (r, _p) in kv.store}
    for r in rounds:
        payloads = [json.loads(kv.store[(r, p)]) for p in range(2)]
        assert len(payloads) == 2
