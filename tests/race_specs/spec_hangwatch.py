"""Race spec: HangWatch ping / fire / backstop-timer.

Drives the REAL in-process watchdog (PR 4) on the virtual clock:

1. a progressing phase — the step loop pings faster than the timeout
   while the monitor thread polls; no schedule may fire;
2. a stall phase — pings stop, virtual time runs past the timeout, the
   monitor must fire EXACTLY once (the PR-9 ``_fired`` test-and-set is
   claimed under the lock; an unlocked reintroduction double-fires
   under some schedule and torn-reads under all of them) and the
   forensics backstop timer must be cancelled after a successful
   report (a leaked backstop would exit a healthy process later);
3. shutdown — stop() joins the monitor; no fire after stop.

The report is written into the spec tmpdir (real, tiny file I/O); the
exit_fn is a recorder, so "exactly one exit" is an assertable
invariant rather than a dead process.
"""

import contextlib
import io
import logging
import os

from paddle_tpu.resilience.hangwatch import HANG_REPORT, HangWatch
from paddle_tpu.utils import concurrency as cc

NAME = "hangwatch"


def run(ctx):
    # the fire path's forensics (faulthandler stderr dump, logger.error)
    # are the code under test and fire once per explored schedule —
    # bottle them up so the analyzer's own report stays readable
    logger = logging.getLogger("paddle_tpu")
    prev_level = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        with contextlib.redirect_stderr(io.StringIO()):
            _run(ctx)
    finally:
        logger.setLevel(prev_level)


def _run(ctx):
    exits = []
    hw = HangWatch(
        timeout_s=5.0, report_dir=ctx.tmpdir,
        exit_fn=lambda code: exits.append(code), poll_s=1.0,
    )
    ctx.static_watch(hw)
    hw.start()

    # phase 1: live progress — ping every virtual second for 8 ticks
    # (past the 5 s timeout, so only the pings keep it alive)
    for step in range(8):
        hw.ping(0, step)
        cc.sleep(1.0)
    assert exits == [], f"fired while progressing: {exits}"

    # phase 2: stall — no pings for 3x the timeout; the monitor's poll
    # loop must fire exactly once even though check() keeps running
    cc.sleep(15.0)
    assert exits == [19], (
        f"expected exactly one EXIT_HANG=19 fire, got {exits} "
        "(0 = missed stall, >1 = double report: the _fired claim tore)"
    )
    assert os.path.exists(os.path.join(ctx.tmpdir, HANG_REPORT))

    # phase 3: shutdown — no further fire, monitor joins
    hw.stop()
    cc.sleep(30.0)
    assert exits == [19], f"fired after stop(): {exits}"
