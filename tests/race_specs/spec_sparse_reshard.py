"""Race spec: ReshardLoader — no lost or duplicated row update.

Drives the REAL sparse reshard loader (doc/sparse.md) — the threaded
reassembly a relaunch survivor runs to load its post-reshard row
slice from ``row_range``-stamped shard records — under explored
interleavings of:

- its own worker pool racing the shared work queue / output buffer /
  fill counters (all through the ``utils/concurrency`` seam),
- two concurrent ``load`` calls on the SAME loader (two tables'
  restores share one relaunch window in the trainer), whose state
  must be fully independent,
- a read_fn whose completion order the scheduler permutes.

Invariants asserted (schedule-independent):

- every destination row is written exactly once: the assembled slices
  are bit-exact against the source table (a lost update leaves a
  zero-initialized row; a duplicate would double-fill and be caught
  by the loader's own fill counters — either way the assert or the
  loader's ReshardError names the schedule);
- each load reads only the shard records overlapping its range, and
  reads each at most once (no double dispatch off the work queue);
- a coverage hole still raises, naming the missing interval, on every
  schedule — the error path must not itself depend on timing.
"""

import numpy as np

from paddle_tpu.sparse.reshard import ReshardError, ReshardLoader
from paddle_tpu.utils import concurrency as cc

NAME = "sparse_reshard"

_ROWS, _COLS = 12, 3
_RANGES = [(0, 5), (5, 8), (8, 12)]


def run(ctx):
    table = np.arange(_ROWS * _COLS, dtype=np.float32).reshape(_ROWS, _COLS)
    records = [
        {"file": f"shard{i}", "row_range": [a, b]}
        for i, (a, b) in enumerate(_RANGES)
    ]
    reads = []
    rlock = cc.Lock()

    def read_fn(rec):
        a, b = rec["row_range"]
        with rlock:
            reads.append((a, b))
        return table[a:b]

    loader = ReshardLoader(records, read_fn, workers=3)
    ctx.static_watch(loader)

    out = [None, None]

    def load_b():
        out[1] = loader.load(6, 12)

    t = cc.Thread(target=load_b, name="loadB", daemon=False)
    t.start()
    out[0] = loader.load(0, 6)
    t.join()

    # exactly-once: bit-exact slices prove no row was lost (zero-init
    # shows through) and none doubled (the fill counters would raise)
    assert np.array_equal(out[0], table[0:6]), out[0]
    assert np.array_equal(out[1], table[6:12]), out[1]
    # only overlapping records were read, each at most once per load:
    # [0,6) needs shards 0+1, [6,12) needs shards 1+2
    assert sorted(reads) == [(0, 5), (5, 8), (5, 8), (8, 12)], reads

    # a hole raises on EVERY schedule, naming the interval
    torn = ReshardLoader([records[0], records[2]], read_fn, workers=2)
    try:
        torn.load(0, 12)
    except ReshardError as e:
        assert "rows [5, 8) missing" in str(e), e
    else:
        raise AssertionError("hole did not raise")
