"""Race spec: AsyncCheckpointer save / drain / drop-oldest.

Drives the REAL single-process async checkpoint writer (PR 5) through
its injectable seams — jax-free fakes for the snapshot and the durable
write — under explored interleavings of:

- the step-loop thread enqueueing saves (including a drop-oldest
  overflow while the writer is busy),
- a second saver thread racing the queue (the library contract: the
  bounded queue + cv protect the queue, whoever calls),
- the writer thread claiming/completing jobs,
- a drain barrier with a hangwatch attached (the drain progress-signal
  regression this PR fixed: a drop-oldest rearranging the queue is NOT
  writer progress and must not ping the watchdog for it).

Invariants asserted (schedule-independent, so any violating
interleaving surfaces as a ``spec_error`` finding):

- after drain: nothing in flight, and every enqueued save was either
  completed or dropped (no lost jobs, no double counts);
- completed writes arrive in enqueue order;
- the watchdog was never pinged by a drain that observed no writer
  progress (claim or completion) — pings during an idle-writer window
  would mask a wedged writer forever.

Watch list: the PTL005 static seed over trainer/async_ckpt.py
(`completed`, `_active`, `_error`, `_pending`, ...), so the three
PR-9 torn-write bugs, if ever reintroduced, fail here dynamically too.
"""

from paddle_tpu.trainer.async_ckpt import AsyncCheckpointer
from paddle_tpu.utils import concurrency as cc

NAME = "async_ckpt"


class _Writes:
    """Deterministic jax-free write_fn: records completions; the first
    write stalls on a virtual gate so the queue demonstrably backs up
    behind an ACTIVE writer (drop-oldest then has pending jobs to
    drop)."""

    def __init__(self, gate):
        self.gate = gate
        self.done = []

    def __call__(self, save_dir, pass_id, params, opt_state=None, **kw):
        if pass_id == 0:
            self.gate.wait()
        self.done.append(pass_id)
        return f"pass-{pass_id}"


class _PingLog:
    """Fake hangwatch: records the full writer state at each DRAIN-side
    ping — (pass_id, completed, active job seq). The fixed progress
    signal pings at most once per distinct state, so a duplicate triple
    proves drain credited something else (drop-oldest queue motion,
    id() reuse) as writer progress — the wedged-writer-masking bug."""

    def __init__(self):
        self.drain_pings = []
        self.ac = None

    def ping(self, pass_id=None, step=None):
        import threading

        if "writer" in threading.current_thread().name:
            return  # writer-side start/end pings are unconditional
        active = self.ac._active
        self.drain_pings.append(
            (pass_id, self.ac.completed, active.seq if active else None)
        )


def run(ctx):
    import logging

    # drop-oldest warnings are the code under test, once per schedule
    # that drops — bottled up so the analyzer report stays readable
    logger = logging.getLogger("paddle_tpu")
    prev_level = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        _run(ctx)
    finally:
        logger.setLevel(prev_level)


def _run(ctx):
    gate = cc.Event()
    writes = _Writes(gate)
    hw = _PingLog()
    ac = AsyncCheckpointer(
        "", inflight_limit=1, hangwatch=hw,
        write_fn=writes, snapshot_fn=lambda tree: tree,
    )
    hw.ac = ac
    ctx.static_watch(ac)

    def second_saver():
        # races the main thread's saves against the same bounded queue
        ac.save(2, {"w": 2})
        gate.set()  # un-wedge the writer once the queue has backed up

    t = cc.Thread(target=second_saver, name="saver2", daemon=False)
    ac.save(0, {"w": 0})
    t.start()
    ac.save(1, {"w": 1})
    t.join()
    ac.drain()

    # --- invariants (any schedule that breaks one becomes a finding) ---
    assert ac.inflight() == 0, "drain returned with work in flight"
    saves = 3
    assert ac.completed + ac.dropped == saves, (
        f"lost/duplicated jobs: completed={ac.completed} "
        f"dropped={ac.dropped} of {saves} saves"
    )
    assert len(writes.done) == ac.completed, (writes.done, ac.completed)
    assert writes.done == sorted(writes.done), (
        f"writes out of enqueue order: {writes.done}"
    )
    # drain progress-signal contract: at most ONE ping per distinct
    # (completed, active-seq) writer state — a duplicate means drain
    # credited drop-oldest queue motion or id() reuse as writer
    # progress, which would keep a wedged writer from ever tripping
    # the watchdog (the bug this PR fixed; see _wait_idle)
    states = [(c, s) for (_p, c, s) in hw.drain_pings]
    assert len(states) == len(set(states)), (
        f"drain pinged twice for one writer state: {hw.drain_pings}"
    )
