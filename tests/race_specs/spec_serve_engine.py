"""Race spec: serve-engine submit / cancel / evict / drain — explored
over BOTH scheduler loops (pipelined dispatch/collect and the serial
baseline).

Drives the REAL continuous-batching engine (paddle_tpu/serving/engine)
over the deterministic FakeBackend under explored interleavings:

1. two client threads submit concurrently while the scheduler thread
   admits/steps/evicts, and one request is cancelled mid-flight (the
   cancel may land before or after completion — both orders are legal,
   and the invariant is exactly-once resolution either way);
2. drain() while work is still queued — it must TERMINATE, finish or
   reject everything, and leave no future unresolved;
3. a second engine whose first decode launch faults — the in-flight
   cohort resolves ``outcome=error``, the engine stays alive, later
   requests complete, drain terminates. Pipelined, the fault surfaces
   at COLLECT (jax async-dispatch semantics, modeled by FakeBackend)
   and must also error every other in-flight snapshot exactly once.

The pipelined loop adds a new shared hand-off: each dispatched launch
carries a SNAPSHOT of its slot cohort, applied at collect while
``submit``/``cancel``/``drain`` callers mutate the same request objects
— the schedules explore cancels and drains landing between a dispatch
and its collect (the snapshot must skip ``done`` requests, never
double-resolve, never lose one).

Invariants (the no-lost / no-double-completed contract):
- every submitted request's future resolves EXACTLY once (a second
  ``_resolve`` would return False and is asserted against),
- every outcome is terminal and legal,
- an ``ok`` result carries exactly its budgeted token count,
- every drain returns within the schedule.
"""

import logging

from paddle_tpu.serving.backend import FakeBackend
from paddle_tpu.serving.engine import OUTCOMES, Engine
from paddle_tpu.utils import concurrency as cc

NAME = "serve_engine"


def run(ctx):
    # phase 3's injected decode fault logs an error per explored
    # schedule — bottle it up so the analyzer's report stays readable
    logger = logging.getLogger("paddle_tpu")
    prev_level = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        # both scheduler loops under the same schedules: the pipelined
        # one exercises the in-flight-cohort snapshot hand-off, the
        # blocking one pins the PR-12 baseline unchanged
        _run(ctx, pipeline=True)
        _run(ctx, pipeline=False)
    finally:
        logger.setLevel(prev_level)


def _watchful_futures(ctx, engine):
    """Track double-resolution: wrap each future's _resolve so a second
    call (lost exactly-once claim) is an assertable event."""
    doubles = []
    orig_submit = engine.submit

    def submit(*a, **kw):
        fut = orig_submit(*a, **kw)
        orig = fut._resolve

        def resolve(result):
            if not orig(result):
                doubles.append(result.rid)
            return True

        fut._resolve = resolve
        return fut

    engine.submit = submit
    return doubles


def _check_all(futs, doubles):
    for rid, (fut, budget) in futs.items():
        assert fut.done(), f"lost request {rid} (future never resolved)"
        res = fut.result(timeout=1.0)
        assert res.outcome in OUTCOMES, (rid, res.outcome)
        if res.outcome == "ok":
            assert len(res.tokens) == budget, (
                f"{rid}: ok with {len(res.tokens)} tokens, budget {budget}"
            )
    assert not doubles, f"double-completed requests: {doubles}"


def _run(ctx, pipeline=True):
    # --- phase 1+2: concurrent submit/cancel, then drain-under-load
    backend = FakeBackend(slots=2, max_length=4, step_delay_s=0.05)
    engine = Engine(backend, queue_cap=0, request_timeout_s=30.0,
                    idle_poll_s=0.2, pipeline=pipeline)
    ctx.static_watch(engine)
    doubles = _watchful_futures(ctx, engine)
    engine.start()

    futs = {}
    flock = cc.Lock()

    def client(tag, n):
        for i in range(n):
            rid = f"{tag}{i}"
            fut = engine.submit([2, 3, 4], max_new_tokens=2, rid=rid)
            with flock:
                futs[rid] = (fut, 2)

    t_a = cc.Thread(target=client, args=("a", 2))
    t_b = cc.Thread(target=client, args=("b", 2))
    t_a.start()
    t_b.start()
    engine.cancel("a1")  # races the a-client and the scheduler: either
    # "not found yet" (False), cancelled, or already-completed is legal
    t_a.join()
    t_b.join()
    assert engine.drain(timeout=120.0), "drain did not terminate"
    _check_all(futs, doubles)
    # a1 specifically: cancelled or completed, never lost
    a1 = futs["a1"][0].result(timeout=1.0)
    assert a1.outcome in ("ok", "cancelled", "rejected"), a1.outcome

    # --- phase 3: decode fault mid-load — error the cohort, survive
    backend2 = FakeBackend(slots=2, max_length=4, fail_at_launch=1)
    engine2 = Engine(backend2, request_timeout_s=30.0, idle_poll_s=0.2,
                     pipeline=pipeline)
    ctx.static_watch(engine2)
    doubles2 = _watchful_futures(ctx, engine2)
    engine2.start()
    futs2 = {}
    for i in range(2):
        futs2[f"x{i}"] = (engine2.submit([5], max_new_tokens=1,
                                         rid=f"x{i}"), 1)
    # wait out the poisoned launch, then prove the engine still serves
    for rid in ("x0", "x1"):
        futs2[rid][0].result(timeout=120.0)
    for i in range(2):
        futs2[f"y{i}"] = (engine2.submit([6], max_new_tokens=1,
                                         rid=f"y{i}"), 1)
    # wait BEFORE draining (a drain racing a queued request may
    # legitimately reject it — that is drain's contract, not a bug)
    outcomes = {rid: futs2[rid][0].result(timeout=120.0).outcome
                for rid in futs2}
    assert engine2.drain(timeout=120.0), "post-fault drain did not terminate"
    _check_all(futs2, doubles2)
    # the y-requests arrived after the fault and were awaited before the
    # drain: the engine must have completed them (alive after a failed
    # launch)
    assert outcomes["y0"] == "ok" and outcomes["y1"] == "ok", outcomes
