"""Race spec: serve-engine submit / cancel / evict / drain / shed /
breaker — explored over BOTH scheduler loops (pipelined dispatch/
collect and the serial baseline).

Drives the REAL continuous-batching engine (paddle_tpu/serving/engine)
over the deterministic FakeBackend under explored interleavings:

1. two client threads submit concurrently while the scheduler thread
   admits/steps/evicts, and one request is cancelled mid-flight (the
   cancel may land before or after completion — both orders are legal,
   and the invariant is exactly-once resolution either way);
2. drain() while work is still queued — it must TERMINATE, finish or
   reject everything, and leave no future unresolved;
3. a second engine whose first decode launch faults — the in-flight
   cohort resolves ``outcome=error``, the engine stays alive, later
   requests complete, drain terminates. Pipelined, the fault surfaces
   at COLLECT (jax async-dispatch semantics, modeled by FakeBackend)
   and must also error every other in-flight snapshot exactly once;
4. breaker-trip interleavings (PR-15): an engine with a one-fault
   circuit breaker takes a collect fault while clients submit
   concurrently — submits racing the open/half-open/closed transitions
   may legally resolve ok, error, or shed (with a retry-after hint),
   but never twice and never not at all, and the post-fault drain
   terminates;
5. shed-under-drain (PR-15): a brownout-primed engine sheds arrivals
   while a concurrent drain rejects them — the shed/reject decision
   races the draining flag, and whichever wins, each future resolves
   exactly once with a legal terminal outcome. The frontend's journal
   discipline rides along: every submitted rid has its journal accept
   line appended (flushed + fsynced) BEFORE the submit — read back and
   asserted after the drain;
6. speculative decode (PR-20): a spec-enabled engine proposes drafts
   from its DraftTable and feeds acceptance back into the per-engine /
   per-request EMAs while client threads submit and cancel
   concurrently — the table's observe (at collect, under the lock)
   races later proposes, and a cancel landing between a verify
   dispatch and its collect must drop that slot's draft outcome
   cleanly. Invariants: exact greedy parity (token streams equal the
   spec-off reference), accepted <= proposed, EMAs stay in [0, 1].

The pipelined loop adds a new shared hand-off: each dispatched launch
carries a SNAPSHOT of its slot cohort, applied at collect while
``submit``/``cancel``/``drain`` callers mutate the same request objects
— the schedules explore cancels and drains landing between a dispatch
and its collect (the snapshot must skip ``done`` requests, never
double-resolve, never lose one).

Invariants (the no-lost / no-double-completed contract):
- every submitted request's future resolves EXACTLY once (a second
  ``_resolve`` would return False and is asserted against),
- every outcome is terminal and legal,
- an ``ok`` result carries exactly its budgeted token count,
- every drain returns within the schedule.
"""

import json
import logging
import os
import tempfile

from paddle_tpu.serving.backend import FakeBackend
from paddle_tpu.serving.engine import OUTCOMES, Engine
from paddle_tpu.serving.resilience import CircuitBreaker, RequestJournal
from paddle_tpu.utils import concurrency as cc

NAME = "serve_engine"


def run(ctx):
    # phase 3's injected decode fault logs an error per explored
    # schedule — bottle it up so the analyzer's report stays readable
    logger = logging.getLogger("paddle_tpu")
    prev_level = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        # both scheduler loops under the same schedules: the pipelined
        # one exercises the in-flight-cohort snapshot hand-off, the
        # blocking one pins the PR-12 baseline unchanged
        _run(ctx, pipeline=True)
        _run(ctx, pipeline=False)
    finally:
        logger.setLevel(prev_level)


def _watchful_futures(ctx, engine):
    """Track double-resolution: wrap each future's _resolve so a second
    call (lost exactly-once claim) is an assertable event."""
    doubles = []
    orig_submit = engine.submit

    def submit(*a, **kw):
        fut = orig_submit(*a, **kw)
        orig = fut._resolve

        def resolve(result):
            if not orig(result):
                doubles.append(result.rid)
            return True

        fut._resolve = resolve
        return fut

    engine.submit = submit
    return doubles


def _check_all(futs, doubles):
    for rid, (fut, budget) in futs.items():
        assert fut.done(), f"lost request {rid} (future never resolved)"
        res = fut.result(timeout=1.0)
        assert res.outcome in OUTCOMES, (rid, res.outcome)
        if res.outcome == "ok":
            assert len(res.tokens) == budget, (
                f"{rid}: ok with {len(res.tokens)} tokens, budget {budget}"
            )
    assert not doubles, f"double-completed requests: {doubles}"


def _run(ctx, pipeline=True):
    # --- phase 1+2: concurrent submit/cancel, then drain-under-load
    backend = FakeBackend(slots=2, max_length=4, step_delay_s=0.05)
    engine = Engine(backend, queue_cap=0, request_timeout_s=30.0,
                    idle_poll_s=0.2, pipeline=pipeline)
    ctx.static_watch(engine)
    doubles = _watchful_futures(ctx, engine)
    engine.start()

    futs = {}
    flock = cc.Lock()

    def client(tag, n):
        for i in range(n):
            rid = f"{tag}{i}"
            fut = engine.submit([2, 3, 4], max_new_tokens=2, rid=rid)
            with flock:
                futs[rid] = (fut, 2)

    t_a = cc.Thread(target=client, args=("a", 2))
    t_b = cc.Thread(target=client, args=("b", 2))
    t_a.start()
    t_b.start()
    engine.cancel("a1")  # races the a-client and the scheduler: either
    # "not found yet" (False), cancelled, or already-completed is legal
    t_a.join()
    t_b.join()
    assert engine.drain(timeout=120.0), "drain did not terminate"
    _check_all(futs, doubles)
    # a1 specifically: cancelled or completed, never lost
    a1 = futs["a1"][0].result(timeout=1.0)
    assert a1.outcome in ("ok", "cancelled", "rejected"), a1.outcome

    # --- phase 3: decode fault mid-load — error the cohort, survive
    backend2 = FakeBackend(slots=2, max_length=4, fail_at_launch=1)
    engine2 = Engine(backend2, request_timeout_s=30.0, idle_poll_s=0.2,
                     pipeline=pipeline)
    ctx.static_watch(engine2)
    doubles2 = _watchful_futures(ctx, engine2)
    engine2.start()
    futs2 = {}
    for i in range(2):
        futs2[f"x{i}"] = (engine2.submit([5], max_new_tokens=1,
                                         rid=f"x{i}"), 1)
    # wait out the poisoned launch, then prove the engine still serves
    for rid in ("x0", "x1"):
        futs2[rid][0].result(timeout=120.0)
    for i in range(2):
        futs2[f"y{i}"] = (engine2.submit([6], max_new_tokens=1,
                                         rid=f"y{i}"), 1)
    # wait BEFORE draining (a drain racing a queued request may
    # legitimately reject it — that is drain's contract, not a bug)
    outcomes = {rid: futs2[rid][0].result(timeout=120.0).outcome
                for rid in futs2}
    assert engine2.drain(timeout=120.0), "post-fault drain did not terminate"
    _check_all(futs2, doubles2)
    # the y-requests arrived after the fault and were awaited before the
    # drain: the engine must have completed them (alive after a failed
    # launch)
    assert outcomes["y0"] == "ok" and outcomes["y1"] == "ok", outcomes

    # --- phase 4: breaker trip — submits race open/half-open/closed
    backend3 = FakeBackend(slots=1, max_length=4, fail_at_launch=1)
    engine3 = Engine(backend3, request_timeout_s=30.0, idle_poll_s=0.2,
                     pipeline=pipeline,
                     breaker=CircuitBreaker(1, 0.05))
    ctx.static_watch(engine3)
    doubles3 = _watchful_futures(ctx, engine3)
    engine3.start()
    futs3 = {}

    def breaker_client(tag, n):
        for i in range(n):
            rid = f"{tag}{i}"
            fut = engine3.submit([7], max_new_tokens=1, rid=rid)
            with flock:
                futs3[rid] = (fut, 1)
            cc.sleep(0.02)  # spread submits across the breaker states

    t_c = cc.Thread(target=breaker_client, args=("p", 2))
    t_d = cc.Thread(target=breaker_client, args=("q", 2))
    t_c.start()
    t_d.start()
    t_c.join()
    t_d.join()
    # every outcome is legal whatever the interleaving: the faulted
    # cohort errors, open-window submits shed (with a retry hint),
    # half-open/closed ones complete
    for rid, (fut, _budget) in list(futs3.items()):
        res = fut.result(timeout=120.0)
        assert res.outcome in OUTCOMES, (rid, res.outcome)
        if res.outcome == "shed":
            assert res.retry_after_s is None or res.retry_after_s >= 0.0
    assert engine3.drain(timeout=120.0), "breaker drain did not terminate"
    _check_all(futs3, doubles3)

    # --- phase 5: shed-under-drain + the journal accept ordering
    backend4 = FakeBackend(slots=1, max_length=4, step_delay_s=0.01)
    engine4 = Engine(backend4, request_timeout_s=30.0, idle_poll_s=0.2,
                     pipeline=pipeline, shed_policy="brownout")
    ctx.static_watch(engine4)
    doubles4 = _watchful_futures(ctx, engine4)
    # prime the brownout (the EMA would need sustained boundaries the
    # schedule budget can't afford): arrivals past one slot wave now
    # shed — racing the drain's draining flag below
    with engine4._lock:
        engine4._brownout = True
        engine4._pressure_ema = 5.0
    engine4.start()
    futs4 = {}
    jpath = os.path.join(tempfile.mkdtemp(prefix="race-journal-"), "j.jsonl")
    journal = RequestJournal(jpath)

    def shed_client(tag, n):
        for i in range(n):
            rid = f"{tag}{i}"
            # the frontend's discipline, modeled: durable accept line
            # BEFORE the submit (crash-ordered ahead of any effect)
            journal.accept({"id": rid, "prompt": [8],
                            "max_new_tokens": 1})
            fut = engine4.submit([8], max_new_tokens=1, rid=rid)
            with flock:
                futs4[rid] = (fut, 1)

    t_e = cc.Thread(target=shed_client, args=("s", 3))
    t_e.start()
    engine4.drain(timeout=120.0)  # races the submits: shed vs reject
    t_e.join()
    assert engine4.drain(timeout=120.0), "shed drain did not terminate"
    _check_all(futs4, doubles4)
    journal.close()
    # the accept line for EVERY submitted rid is durably on disk —
    # whatever the interleaving, no request was submitted unjournaled
    with open(jpath) as f:
        accepted = {json.loads(l)["id"] for l in f if l.strip()
                    and json.loads(l).get("op") == "accept"}
    assert set(futs4) <= accepted, (set(futs4), accepted)

    # --- phase 6: speculative decode — draft-table updates (observe at
    # collect) race proposes and the acceptance EMAs, while a cancel
    # lands between a verify dispatch and its collect
    def periodic(rid, i):
        return (11, 12, 13)[i % 3]  # repetitive: drafts DO get accepted

    ref_be = FakeBackend(slots=2, max_length=8, token_fn=periodic)
    ref_eng = Engine(ref_be, request_timeout_s=30.0, idle_poll_s=0.2,
                     pipeline=pipeline)
    ref_eng.start()
    ref = ref_eng.submit([2, 3], max_new_tokens=4, rid="ref").result(
        timeout=120.0)
    assert ref_eng.drain(timeout=120.0)

    backend5 = FakeBackend(slots=2, max_length=8, token_fn=periodic,
                           step_delay_s=0.01, spec_tokens="2")
    engine5 = Engine(backend5, request_timeout_s=30.0, idle_poll_s=0.2,
                     pipeline=pipeline)
    ctx.static_watch(engine5)
    doubles5 = _watchful_futures(ctx, engine5)
    engine5.start()
    futs5 = {}

    def spec_client(tag, n):
        for i in range(n):
            rid = f"{tag}{i}"
            fut = engine5.submit([2, 3], max_new_tokens=4, rid=rid)
            with flock:
                futs5[rid] = (fut, 4)

    t_f = cc.Thread(target=spec_client, args=("u", 2))
    t_g = cc.Thread(target=spec_client, args=("v", 2))
    t_f.start()
    t_g.start()
    engine5.cancel("u1")  # may land mid-verify: drop the draft outcome
    t_f.join()
    t_g.join()
    assert engine5.drain(timeout=120.0), "spec drain did not terminate"
    for rid, (fut, _budget) in futs5.items():
        res = fut.result(timeout=1.0)
        if res.outcome == "ok" and rid != "u1":
            # exact greedy parity under every interleaving: speculation
            # must never change WHAT was generated
            assert res.tokens == ref.tokens, (rid, res.tokens, ref.tokens)
    _check_all(futs5, doubles5)
    # acceptance accounting stayed consistent under the races
    assert 0.0 <= engine5._spec_ema <= 1.0, engine5._spec_ema
    for snap in backend5.spec_drafts:
        assert all(len(d) <= 2 for d in snap.values()), snap
