"""Race spec: serve-fleet router — route / death / re-offer / drain
interleavings over the REAL :class:`FleetRouter` and in-process fake
replica handles (the duck-typed protocol ProcReplica implements).

The router's contract is the fleet-level exactly-once claim: whatever
the interleaving of the stdin submitter, the per-replica answer
threads, a replica death (journal re-offer to survivors + restart
replay) and a drain, every submitted request id is emitted EXACTLY
once, in submission order, with a legal terminal outcome. The fakes
keep the at-least-once hazard real: a restarted replica replays its
accepted-but-unanswered journal, so the same id can be answered by the
re-offer target AND the replayer — the router must emit the first and
count the duplicate.

Phases:

1. two client threads submit concurrently while the router loop routes
   across two live replicas — EOF batch completes, all answers in
   submission order;
2. death-mid-load: replica-0 is killed (exit 17, the budgeted class)
   after accepting work; its journal pending re-offers to replica-1
   while its restart replays the same entries — no lost id, no double
   emission, ``deaths``/``reoffers`` observed;
3. drain racing a submitting client: whichever side of the draining
   flag each submit lands on, the outcome is ok (in-flight completed),
   rejected (queued/new at drain) or error (owed by a child that
   exited mid-drain) — and the drain TERMINATES with every child down;
4. budget exhaustion: a one-replica fleet with ``restart_budget=0``
   takes a kill — the router answers everything ``outcome=error``
   instead of hanging the client, and ``run()`` returns 1.

Invariants (the no-lost / no-double-answered contract):
- every submitted id appears in the emit stream exactly once,
- emission respects submission order,
- ``run()`` terminates within the schedule,
- duplicate replica answers are absorbed (counted, never re-emitted).
"""

import collections
import logging

from paddle_tpu.serving.fleet import FleetRouter
from paddle_tpu.utils import concurrency as cc

NAME = "serve_fleet"

LEGAL = ("ok", "rejected", "error")


class FakeReplica:
    """In-process replica handle: a worker thread answers each routed
    request after a short delay, an in-memory journal records
    accept/done with the frontend's ordering (done only after the
    answer is delivered), and ``start()`` replays accepted-but-undone
    entries — the single server's at-least-once restart recovery."""

    def __init__(self, name, delay_s=0.01):
        self.name = name
        self.delay_s = delay_s
        self.deliver = None  # wired to router.deliver by the harness
        self._lock = cc.Lock()
        self._cv = cc.Condition(self._lock)
        self._queue = collections.deque()
        self._accepted = {}  # rid -> doc, acceptance order
        self._done = set()
        self._exit = None
        self._alive = False
        self._draining = False
        self._worker = None
        self.incarnations = 0

    # -------------------------------------------------- handle protocol

    def start(self):
        with self._lock:
            self._exit = None
            self._alive = True
            self._draining = False
            self.incarnations += 1
            # journal replay — the at-least-once hazard the router's
            # dedupe must absorb
            for rid, doc in self._accepted.items():
                if rid not in self._done:
                    self._queue.append(dict(doc))
            self._cv.notify_all()
        self._worker = cc.Thread(target=self._run,
                                 name=f"fake-{self.name}", daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            with self._lock:
                while not self._queue and self._alive and not self._draining:
                    self._cv.wait(timeout=0.05)
                if not self._alive:
                    return
                if not self._queue:
                    # draining and empty: graceful exit 0
                    self._alive = False
                    self._exit = 0
                    return
                doc = self._queue.popleft()
            cc.sleep(self.delay_s)
            with self._lock:
                if not self._alive:
                    return  # killed mid-request: stays journal-pending
            rid = str(doc.get("id"))
            self.deliver(self.name, {
                "id": rid, "outcome": "ok",
                "tokens": [1] * int(doc.get("max_new_tokens") or 1),
            })
            with self._lock:
                self._done.add(rid)

    def alive(self):
        with self._lock:
            return self._alive

    def poll_exit(self):
        with self._lock:
            return self._exit

    def send(self, doc):
        with self._lock:
            if not self._alive or self._draining:
                return False
            rid = str(doc.get("id"))
            self._accepted.setdefault(rid, dict(doc))  # journal accept
            self._queue.append(dict(doc))
            self._cv.notify_all()
        return True

    def health(self, now):
        with self._lock:
            return {"state": "serving", "queue_depth": len(self._queue),
                    "occupancy": 0}

    def pending_requests(self):
        with self._lock:
            return [dict(d) for rid, d in self._accepted.items()
                    if rid not in self._done]

    def begin_drain(self):
        with self._lock:
            self._draining = True
            self._cv.notify_all()

    def die(self, rc):
        with self._lock:
            if not self._alive:
                return
            self._alive = False
            self._exit = rc
            self._cv.notify_all()

    def kill(self):
        self.die(9)

    def join(self, timeout):
        w = self._worker
        if w is not None:
            w.join(timeout=timeout)
            return not w.is_alive()
        return True

    # ------------------------------------------------------ spec hooks

    def accepted_count(self):
        with self._lock:
            return len(self._accepted)


def _fleet(ctx, n, **kw):
    emitted = []
    elock = cc.Lock()

    def emit(doc):
        with elock:
            emitted.append(doc)

    reps = [FakeReplica(f"replica-{i}") for i in range(n)]
    kw.setdefault("poll_s", 0.01)
    kw.setdefault("health_period_s", 0.0)
    kw.setdefault("restart_base_delay", 0.02)
    router = FleetRouter(reps, emit=emit, **kw)
    for r in reps:
        r.deliver = router.deliver
    ctx.static_watch(router)
    return router, reps, emitted


def _check_exactly_once(router, emitted, submitted):
    ids = [str(d.get("id")) for d in emitted]
    assert len(ids) == len(set(ids)), f"double-emitted: {ids}"
    assert set(ids) == set(submitted), (set(ids), set(submitted))
    with router._lock:
        order = list(router._order)
    assert ids == order, ("emission violated submission order",
                          ids, order)
    for d in emitted:
        assert d.get("outcome") in LEGAL, d


def _run_router(router):
    box = {}

    def target():
        box["rc"] = router.run()

    t = cc.Thread(target=target, name="fleet-run", daemon=True)
    t.start()
    return t, box


def run(ctx):
    # replica deaths and budget exhaustion log warnings/errors per
    # explored schedule — keep the analyzer report readable
    logger = logging.getLogger("paddle_tpu")
    prev = logger.level
    logger.setLevel(logging.CRITICAL)
    try:
        _phase_route(ctx)
        _phase_death_reoffer(ctx)
        _phase_drain_race(ctx)
        _phase_budget_exhausted(ctx)
    finally:
        logger.setLevel(prev)


def _phase_route(ctx):
    router, reps, emitted = _fleet(ctx, 2)
    router.start()
    t, box = _run_router(router)
    submitted = []
    slock = cc.Lock()

    def client(tag, n):
        for i in range(n):
            rid = f"{tag}{i}"
            assert router.submit({"id": rid, "prompt": [2, 3],
                                  "max_new_tokens": 1})
            with slock:
                submitted.append(rid)

    t_a = cc.Thread(target=client, args=("a", 2))
    t_b = cc.Thread(target=client, args=("b", 2))
    t_a.start()
    t_b.start()
    t_a.join()
    t_b.join()
    # duplicate id at the front door is refused, not double-answered
    assert router.submit({"id": "a0", "prompt": [2]}) is False
    router.note_eof()
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate (route phase)"
    assert box["rc"] == 0, box
    _check_exactly_once(router, emitted, submitted)
    router.shutdown(timeout=10.0)


def _phase_death_reoffer(ctx):
    router, reps, emitted = _fleet(ctx, 2, restart_budget=3)
    router.start()
    t, box = _run_router(router)
    submitted = [f"r{i}" for i in range(4)]
    for rid in submitted:
        assert router.submit({"id": rid, "prompt": [5],
                              "max_new_tokens": 1})
    # wait until replica-0 has journaled at least one accept, then kill
    # it with the budgeted exit class — the re-offer races its restart's
    # journal replay
    deadline = cc.monotonic() + 60.0
    while reps[0].accepted_count() == 0 and cc.monotonic() < deadline:
        cc.sleep(0.005)
    reps[0].die(17)
    router.note_eof()
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate (death phase)"
    assert box["rc"] == 0, box
    _check_exactly_once(router, emitted, submitted)
    # every answer in this phase is a completion — nothing was draining
    for d in emitted:
        assert d.get("outcome") == "ok", d
    st = router.status()
    assert st["deaths"] >= 1, st
    router.shutdown(timeout=10.0)


def _phase_drain_race(ctx):
    router, reps, emitted = _fleet(ctx, 2)
    router.start()
    t, box = _run_router(router)
    submitted = []
    slock = cc.Lock()

    def client():
        for i in range(3):
            rid = f"d{i}"
            if router.submit({"id": rid, "prompt": [7],
                              "max_new_tokens": 1}):
                with slock:
                    submitted.append(rid)

    t_c = cc.Thread(target=client)
    t_c.start()
    router.request_drain()  # races the submits: in-flight complete,
    # queued/new reject — either side of the flag is legal
    t_c.join()
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate (drain phase)"
    assert box["rc"] == 0, box
    _check_exactly_once(router, emitted, submitted)
    # the drain's terminal fleet state: every child exited
    st = router.status()
    assert st["draining"] is True, st
    assert all(not r["up"] for r in st["replicas"].values()), st
    router.shutdown(timeout=10.0)


def _phase_budget_exhausted(ctx):
    router, reps, emitted = _fleet(ctx, 1, restart_budget=0)
    router.start()
    t, box = _run_router(router)
    submitted = ["z0", "z1"]
    for rid in submitted:
        assert router.submit({"id": rid, "prompt": [9],
                              "max_new_tokens": 1})
    reps[0].die(20)  # EXIT_OOM: budgeted class, budget is zero
    router.note_eof()
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate (budget phase)"
    _check_exactly_once(router, emitted, submitted)
    # the fleet failed — but it failed HONESTLY: if any request got an
    # error answer the exit code says so; racing answers may legally
    # complete everything first (died-after-answering), which is rc 0
    errs = [d for d in emitted if d.get("outcome") == "error"]
    assert box["rc"] == (1 if errs else 0), (box, emitted)
    router.shutdown(timeout=10.0)
