"""Race spec: HeartbeatWriter beat / renew / stop.

Drives the REAL cluster-heartbeat writer (PR 4) with its injectable
clock on the virtual timeline. The contract under test is the one
monitors rely on: the per-host ``seq`` is strictly increasing and
every published beat file is well-formed — even when ``stop()``'s
final synchronous beat overlaps an in-flight daemon-thread renewal
(the exact overlap PR 9's ``_seq_lock`` exists for; an unlocked
``_seq += 1`` reintroduction torn-reads here under every schedule and
loses a seq under some).

Beats land in the spec tmpdir as real (tiny) heartbeat files; the spec
re-reads the final file like a monitor would.
"""

import paddle_tpu.resilience.heartbeat as hb_mod
from paddle_tpu.resilience.heartbeat import HeartbeatWriter, read_beats
from paddle_tpu.utils import concurrency as cc

NAME = "heartbeat"


def run(ctx):
    # record every seq at the moment it is WRITTEN — inside beat()'s
    # lock, so the recording carries the exact published values (an
    # instance-side recorder would itself race the counter)
    seen = []
    orig_write = hb_mod.write_beat

    def recording_write(dir_, host, *, seq=0, clock=None, extra=None):
        seen.append(seq)
        # a slow shared-fs write, on the virtual clock: this is the
        # overlap window the bounded _seq_lock acquire exists for —
        # stop()'s final beat must either serialize behind it or skip
        # (never tear the counter)
        cc.sleep(1.5)
        return orig_write(dir_, host, seq=seq,
                          clock=clock or (lambda: 0.0), extra=extra)

    hb_mod.write_beat = recording_write
    try:
        hb = HeartbeatWriter(ctx.tmpdir, host=0, interval_s=1.0,
                             clock=lambda: 1e9 + cc.monotonic())
        ctx.static_watch(hb)

        hb.start()       # synchronous first beat + daemon renewal thread
        cc.sleep(3.5)    # ~3 renewals on the virtual clock
        hb.stop()        # final stopped=True beat can overlap a renewal
    finally:
        hb_mod.write_beat = orig_write

    beats = read_beats(ctx.tmpdir)
    assert 0 in beats, "no readable heartbeat published"
    final = beats[0]
    # no seq published twice: a torn `_seq += 1` loses an increment
    # and two beats share a number — the monitor's strictly-increasing
    # contract breaks
    assert len(seen) == len(set(seen)), f"duplicate seq published: {seen}"
    # consecutive from 1: no increment skipped or double-applied
    assert sorted(seen) == list(range(1, len(seen) + 1)), seen
    # file writes are serialized under the SAME lock as the increment,
    # so the beat on disk is the highest seq (a stale in-flight renewal
    # can never overwrite a newer beat)
    assert final["seq"] == max(seen), (final, seen)
