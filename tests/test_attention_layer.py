"""multi_head_attention layer: DSL → trained model, seq-parallel modes.

Covers: config building (params, heads), numerical match between the
unsharded layer and a manual computation, end-to-end training through the
layer, and sharded execution on a data×seq mesh matching the unsharded
forward (the loopback-pserver pattern for distributed tests, SURVEY.md §4).
"""

import numpy as np
import pytest

from paddle_tpu.config.builder import fresh_context
from paddle_tpu.graph import GradientMachine
from paddle_tpu.graph.argument import make_ids, make_seq
from paddle_tpu.trainer_config_helpers import (
    classification_cost,
    data_layer,
    embedding_layer,
    fc_layer,
    MaxPooling,
    multi_head_attention_layer,
    outputs,
    pooling_layer,
    settings,
    SoftmaxActivation,
)


def _config(dict_dim=50, emb=16, heads=4, classes=2, seq_parallel="", causal=False):
    with fresh_context() as ctx:
        settings(batch_size=8, learning_rate=1e-2)
        words = data_layer(name="words", size=dict_dim)
        e = embedding_layer(input=words, size=emb)
        att = multi_head_attention_layer(
            input=e, num_heads=heads, causal=causal, seq_parallel=seq_parallel,
            name="att",
        )
        pool = pooling_layer(input=att, pooling_type=MaxPooling())
        out = fc_layer(input=pool, size=classes, act=SoftmaxActivation(), name="out")
        label = data_layer(name="label", size=classes)
        outputs(classification_cost(input=out, label=label))
        return ctx.finalize()


def _batch(dict_dim=50, B=8, T=16, classes=2, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, dict_dim, (B, T)).astype(np.int32)
    lengths = rng.randint(T // 2, T + 1, (B,)).astype(np.int32)
    return {
        "words": make_seq(None, lengths, ids=ids),
        "label": make_ids(rng.randint(0, classes, (B,)).astype(np.int32)),
    }


def test_config_declares_params_and_heads():
    tc = _config()
    att = next(l for l in tc.model_config.layers if l.type == "multi_head_attention")
    assert att.num_heads == 4
    pnames = {p.name for p in tc.model_config.parameters}
    assert "_att.wqkv" in pnames and "_att.wo" in pnames


def test_trains_and_grads_flow():
    tc = _config(causal=True)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=3)
    batch = _batch()
    loss, grads, _, _ = gm.grad_fn()(params, batch, None)
    assert np.isfinite(float(loss))
    for name in ("_att.wqkv", "_att.wo"):
        assert float(np.abs(np.asarray(grads[name])).max()) > 0, name


@pytest.mark.parametrize("mode", ["ring", "alltoall"])
def test_seq_parallel_matches_unsharded(mode):
    from paddle_tpu.parallel.mesh import make_mesh

    tc = _config(seq_parallel=mode)
    gm_plain = GradientMachine(tc.model_config)
    params = gm_plain.init_params(seed=5)
    batch = _batch()
    ref, _ = gm_plain.forward(params, batch, pass_type="test")

    gm_mesh = GradientMachine(tc.model_config)
    gm_mesh.mesh = make_mesh("data=2,seq=4")
    out, _ = gm_mesh.forward(params, batch, pass_type="test")
    np.testing.assert_allclose(
        np.asarray(out["att"].value), np.asarray(ref["att"].value), atol=2e-5
    )
