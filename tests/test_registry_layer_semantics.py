"""Semantics of registry-only layer types (no DSL wrapper in the v0
config surface — the reference constructs these straight from
config_parser; here they're exercised at the forward_layer level).

Covers: seqconcat, seqreshape, subseq, seqfirstins, resize,
featmap_expand, data_norm, prelu, trans — each pinned against
hand-computed numpy. Reference impls:
SequenceConcatLayer/SequenceReshapeLayer/SubSequenceLayer/
SequenceLastInstanceLayer (gserver/layers), ResizeLayer,
FeatureMapExpandLayer, DataNormLayer, ParameterReluLayer, TransLayer.
"""

import jax.numpy as jnp
import numpy as np

from paddle_tpu.graph.argument import Argument
from paddle_tpu.layers.base import LayerContext, layer_registry
from paddle_tpu.proto import LayerConfig, LayerInputConfig, ModelConfig


def _ctx(params=None):
    return LayerContext(params=params or {}, model=ModelConfig(), pass_type="test")


def _run(type_name, cfg, inputs, params=None):
    return layer_registry.get(type_name)(cfg, inputs, _ctx(params))


def test_seqconcat_places_b_after_a():
    a = Argument(value=jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 2, 3)),
                 seq_lengths=jnp.asarray([2, 1], jnp.int32))
    b = Argument(value=jnp.asarray(100 + np.arange(12, dtype=np.float32).reshape(2, 2, 3)),
                 seq_lengths=jnp.asarray([1, 2], jnp.int32))
    out = _run("seqconcat", LayerConfig(name="sc", type="seqconcat", size=3), [a, b])
    assert np.asarray(out.seq_lengths).tolist() == [3, 3]
    v = np.asarray(out.value)
    # sample 0: a[0,0], a[0,1], b[0,0]
    np.testing.assert_array_equal(v[0, 0], [0, 1, 2])
    np.testing.assert_array_equal(v[0, 1], [3, 4, 5])
    np.testing.assert_array_equal(v[0, 2], [100, 101, 102])
    # sample 1: a[1,0], b[1,0], b[1,1]
    np.testing.assert_array_equal(v[1, 0], [6, 7, 8])
    np.testing.assert_array_equal(v[1, 1], [106, 107, 108])
    np.testing.assert_array_equal(v[1, 2], [109, 110, 111])


def test_seqreshape_reinterprets_width():
    a = Argument(value=jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 2, 6)),
                 seq_lengths=jnp.asarray([2, 1], jnp.int32))
    out = _run("seqreshape", LayerConfig(name="sr", type="seqreshape", size=3), [a])
    v = np.asarray(out.value)
    assert v.shape == (2, 4, 3)
    np.testing.assert_array_equal(v[0, 0], [0, 1, 2])
    np.testing.assert_array_equal(v[0, 1], [3, 4, 5])
    # lengths scale by D/size = 2
    assert np.asarray(out.seq_lengths).tolist() == [4, 2]


def test_subseq_slices_offset_size():
    a = Argument(value=jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 4, 3)),
                 seq_lengths=jnp.asarray([4, 4], jnp.int32))
    offs = Argument(ids=jnp.asarray([1, 0], jnp.int32))
    sizes = Argument(ids=jnp.asarray([2, 3], jnp.int32))
    out = _run("subseq", LayerConfig(name="ss", type="subseq", size=3), [a, offs, sizes])
    v = np.asarray(out.value)
    assert np.asarray(out.seq_lengths).tolist() == [2, 3]
    np.testing.assert_array_equal(v[0, 0], [3, 4, 5])   # offset 1
    np.testing.assert_array_equal(v[0, 1], [6, 7, 8])
    np.testing.assert_array_equal(v[0, 2], [0, 0, 0])   # beyond size: zeroed
    np.testing.assert_array_equal(v[1, 2], [18, 19, 20])


def test_seqfirstins_takes_first_valid_frame():
    a = Argument(value=jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 2, 3)),
                 seq_lengths=jnp.asarray([2, 1], jnp.int32))
    out = _run("seqfirstins", LayerConfig(name="fi", type="seqfirstins", size=3), [a])
    v = np.asarray(out.value)
    np.testing.assert_array_equal(v[0], [0, 1, 2])
    np.testing.assert_array_equal(v[1], [6, 7, 8])


def test_resize_reinterprets_rows():
    a = Argument(value=jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 6)))
    out = _run("resize", LayerConfig(name="rz", type="resize", size=3), [a])
    v = np.asarray(out.value)
    assert v.shape == (4, 3)
    np.testing.assert_array_equal(v[1], [3, 4, 5])


def test_featmap_expand_tiles_features():
    a = Argument(value=jnp.asarray(np.arange(6, dtype=np.float32).reshape(1, 2, 3)),
                 seq_lengths=jnp.asarray([2], jnp.int32))
    out = _run("featmap_expand",
               LayerConfig(name="fe", type="featmap_expand", size=6, num_filters=2), [a])
    v = np.asarray(out.value)
    assert v.shape == (1, 2, 6)
    np.testing.assert_array_equal(v[0, 0], [0, 1, 2, 0, 1, 2])


def test_data_norm_zscore_from_stats_param():
    cfg = LayerConfig(name="dn", type="data_norm", size=2,
                      data_norm_strategy="z-score")
    cfg.inputs.append(LayerInputConfig(input_layer_name="x",
                                       input_parameter_name="dn.stats"))
    # stats rows: min, max, sum, sum_sq, count over 4 observations
    xs = np.array([[1.0, 10.0], [2.0, 20.0], [3.0, 30.0], [4.0, 40.0]], np.float32)
    stats = np.stack([
        xs.min(0), xs.max(0), xs.sum(0), (xs ** 2).sum(0),
        np.full(2, 4.0, np.float32),
    ])
    a = Argument(value=jnp.asarray(xs))
    out = _run("data_norm", cfg, [a], params={"dn.stats": jnp.asarray(stats)})
    mean, std = xs.mean(0), xs.std(0)
    np.testing.assert_allclose(np.asarray(out.value), (xs - mean) / std, rtol=1e-5)


def test_prelu_per_partition_slopes():
    cfg = LayerConfig(name="pr", type="prelu", size=4, partial_sum=2)
    cfg.inputs.append(LayerInputConfig(input_layer_name="x",
                                       input_parameter_name="pr.w"))
    x = np.array([[1.0, -1.0, 2.0, -2.0]], np.float32)
    w = np.array([0.1, 0.5], np.float32)  # two partitions of width 2
    out = _run("prelu", cfg, [Argument(value=jnp.asarray(x))],
               params={"pr.w": jnp.asarray(w)})
    np.testing.assert_allclose(
        np.asarray(out.value), [[1.0, -0.1, 2.0, -1.0]], rtol=1e-6)


def test_trans_transposes_batch_matrix():
    a = Argument(value=jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3)))
    out = _run("trans", LayerConfig(name="tr", type="trans", size=3), [a])
    np.testing.assert_array_equal(np.asarray(out.value),
                                  np.arange(6).reshape(2, 3).T)
