"""Vision stack tests: conv/pool/bn numerics vs torch, gradient checks,
and the two vision demos end-to-end.

Analog of the reference's gserver/tests/test_LayerGrad.cpp conv/pool/norm
cases plus the image_classification demo as the integration fixture; the
CPU↔GPU equivalence harness (test_matrixCompare.cpp) becomes ours-vs-torch
cross-checks.
"""

import os
import shutil
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build(config_fn, config_args=""):
    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine

    cfg = parse_config(config_fn, config_args)
    return cfg, GradientMachine(cfg.model_config)


def conv_config(B=2, C=3, H=8, F=4, fs=3, stride=2, padding=1):
    def cfg():
        from paddle_tpu.trainer_config_helpers import (
            LinearActivation,
            data_layer,
            img_conv_layer,
            outputs,
            settings,
        )

        settings(batch_size=B, learning_rate=0.1)
        img = data_layer(name="image", size=C * H * H)
        conv = img_conv_layer(
            input=img, filter_size=fs, num_filters=F, num_channels=C,
            stride=stride, padding=padding, act=LinearActivation(), name="conv",
        )
        outputs(conv)

    return cfg


def test_conv_matches_torch():
    import torch
    import torch.nn.functional as TF

    from paddle_tpu.graph import make_dense

    B, C, H, F, fs, stride, padding = 2, 3, 8, 4, 3, 2, 1
    cfg, gm = _build(conv_config(B, C, H, F, fs, stride, padding))
    params = gm.init_params(seed=3)
    rng = np.random.RandomState(0)
    x = rng.randn(B, C * H * H).astype(np.float32)
    out, _ = gm.forward(params, {"image": make_dense(x)}, pass_type="test")
    ours = np.asarray(out["conv"].value)

    w = np.asarray(params["_conv.w0"]).reshape(F, C, fs, fs)
    bias = np.asarray(params["_conv.wbias"]).ravel()
    t = TF.conv2d(
        torch.from_numpy(x.reshape(B, C, H, H)),
        torch.from_numpy(w),
        bias=torch.from_numpy(bias),
        stride=stride,
        padding=padding,
    )
    theirs = t.numpy().reshape(B, -1)
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)


def test_pool_matches_torch():
    import torch
    import torch.nn.functional as TF

    from paddle_tpu.graph import make_dense

    B, C, H = 2, 3, 8

    def cfg():
        from paddle_tpu.trainer_config_helpers import (
            AvgPooling,
            MaxPooling,
            data_layer,
            img_pool_layer,
            outputs,
            settings,
        )

        settings(batch_size=B, learning_rate=0.1)
        img = data_layer(name="image", size=C * H * H)
        mx = img_pool_layer(input=img, num_channels=C, pool_size=2, stride=2,
                            pool_type=MaxPooling(), name="maxp")
        av = img_pool_layer(input=img, num_channels=C, pool_size=2, stride=2,
                            pool_type=AvgPooling(), name="avgp")
        outputs(mx, av)

    cfg_obj, gm = _build(cfg)
    params = gm.init_params(seed=1)
    rng = np.random.RandomState(1)
    x = rng.randn(B, C * H * H).astype(np.float32)
    out, _ = gm.forward(params, {"image": make_dense(x)}, pass_type="test")
    xt = torch.from_numpy(x.reshape(B, C, H, H))
    np.testing.assert_allclose(
        np.asarray(out["maxp"].value),
        TF.max_pool2d(xt, 2, 2).numpy().reshape(B, -1), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out["avgp"].value),
        TF.avg_pool2d(xt, 2, 2).numpy().reshape(B, -1), rtol=1e-5, atol=1e-5)


def test_avg_pool_ceil_mode_matches_torch():
    """Odd input → ceil-mode output; edge windows divide by in-image area."""
    import torch
    import torch.nn.functional as TF

    from paddle_tpu.graph import make_dense

    B, C, H = 2, 3, 7

    def cfg():
        from paddle_tpu.trainer_config_helpers import (
            AvgPooling,
            data_layer,
            img_pool_layer,
            outputs,
            settings,
        )

        settings(batch_size=B, learning_rate=0.1)
        img = data_layer(name="image", size=C * H * H)
        outputs(img_pool_layer(input=img, num_channels=C, pool_size=2, stride=2,
                               pool_type=AvgPooling(), name="avgp"))

    cfg_obj, gm = _build(cfg)
    params = gm.init_params(seed=1)
    rng = np.random.RandomState(4)
    x = rng.randn(B, C * H * H).astype(np.float32)
    out, _ = gm.forward(params, {"image": make_dense(x)}, pass_type="test")
    xt = torch.from_numpy(x.reshape(B, C, H, H))
    want = TF.avg_pool2d(xt, 2, 2, ceil_mode=True, count_include_pad=False)
    np.testing.assert_allclose(np.asarray(out["avgp"].value),
                               want.numpy().reshape(B, -1), rtol=1e-5, atol=1e-5)


def test_conv_bn_pool_gradient_check():
    from paddle_tpu.graph import make_dense, make_ids

    B, C, H = 3, 2, 6

    def cfg():
        from paddle_tpu.trainer_config_helpers import (
            MaxPooling,
            ReluActivation,
            SoftmaxActivation,
            batch_norm_layer,
            classification_cost,
            data_layer,
            fc_layer,
            img_conv_layer,
            img_pool_layer,
            outputs,
            settings,
        )

        settings(batch_size=B, learning_rate=0.1)
        img = data_layer(name="image", size=C * H * H)
        conv = img_conv_layer(input=img, filter_size=3, num_filters=4,
                              num_channels=C, stride=1, padding=1)
        bn = batch_norm_layer(input=conv, act=ReluActivation())
        pool = img_pool_layer(input=bn, pool_size=2, stride=2, pool_type=MaxPooling())
        outp = fc_layer(input=pool, size=3, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=3)
        outputs(classification_cost(input=outp, label=label))

    cfg_obj, gm = _build(cfg)
    params = gm.init_params(seed=2)
    rng = np.random.RandomState(2)
    batch = {
        "image": make_dense(rng.randn(B, C * H * H).astype(np.float32)),
        "label": make_ids(rng.randint(0, 3, (B,))),
    }
    report = gm.check_gradient(params, batch, epsilon=1e-3, max_entries=6)
    for name, diff in report.items():
        assert diff < 5e-2, f"gradient mismatch for {name}: {diff}"


@pytest.fixture()
def demo_workspace(tmp_path):
    def _copy(demo_rel):
        src = os.path.join(REPO, "demo", demo_rel)
        ws = tmp_path / os.path.basename(demo_rel)
        shutil.copytree(src, ws)
        return ws

    return _copy


def _train(ws, config, num_passes, config_args="", **flag_kw):
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(ws)
    try:
        cfg = parse_config(str(ws / config), config_args)
        flags = _Flags(config=config, save_dir=str(ws / "model"),
                       num_passes=num_passes, log_period=0, use_tpu=False,
                       config_args=config_args, **flag_kw)
        trainer = Trainer(cfg, flags)
        trainer.train()
        return trainer.test()
    finally:
        os.chdir(cwd)


def test_vgg_cifar_demo_trains(demo_workspace):
    ws = demo_workspace("image_classification")
    metrics = _train(ws, "vgg_16_cifar.py", num_passes=3, config_args="small=1")
    assert metrics["cost"] < 1.5, metrics
    err = metrics.get("classification_error_evaluator", metrics.get("error"))
    if err is not None:
        assert err < 0.5, metrics


def test_resnet50_trains_smoke(demo_workspace):
    ws = demo_workspace(os.path.join("model_zoo", "resnet"))
    metrics = _train(ws, "resnet.py", num_passes=1,
                     config_args="img_size=32,num_classes=16")
    assert np.isfinite(metrics["cost"]), metrics


def test_resnet_predict_graph_builds():
    from paddle_tpu.config import parse_config_at

    cfg = parse_config_at(
        os.path.join(REPO, "demo", "model_zoo", "resnet", "resnet.py"),
        "is_predict=1,layer_num=101",
    )
    names = {l.name for l in cfg.model_config.layers}
    assert "output" in names and "label" not in names
    assert len([n for n in names if n.endswith("_sum")]) == sum((3, 4, 23, 3))


def test_nhwc_chain_avoids_layout_roundtrips(tmp_path):
    """The conv family publishes NHWC views between layers
    (LayerContext.nhwc), so a conv->conv->pool chain must not pay a
    flat->NCHW->NHWC round-trip per layer. Pinned on the compiled HLO's
    transpose count: before the side-table this graph compiled to ~2x
    more transposes (they do NOT all cancel in XLA)."""
    import re
    import textwrap

    import jax
    import jax.numpy as jnp

    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.argument import Argument

    cfg_file = tmp_path / "conf.py"
    cfg_file.write_text(textwrap.dedent("""
    from paddle.trainer_config_helpers import *
    settings(batch_size=4, learning_rate=0.1)
    img = data_layer('image', size=3*16*16)
    t = img_conv_group(input=img, num_channels=3, conv_num_filter=[8, 8],
                       conv_filter_size=3, conv_padding=1,
                       conv_act=ReluActivation(), pool_size=2, pool_stride=2,
                       pool_type=MaxPooling())
    out = fc_layer(input=t, size=4, act=SoftmaxActivation(), name='out')
    outputs(classification_cost(input=out, label=data_layer('label', size=4)))
    """))
    cfg = parse_config(str(cfg_file))
    gm = GradientMachine(cfg.model_config)
    params = gm.init_params(seed=1)
    grad_fn = gm.grad_fn()
    batch = {"image": Argument(value=jnp.ones((4, 3 * 16 * 16), jnp.float32)),
             "label": Argument(ids=jnp.zeros((4,), jnp.int32))}
    f = jax.jit(lambda p, b: grad_fn(p, b, None)[:2])
    hlo = f.lower(params, batch).compile().as_text()
    n_transpose = len(re.findall(r"= \S+? transpose\(", hlo))
    # measured 13 with the side-table (was ~25 without); headroom for
    # compiler-version drift without letting the round-trips back in
    assert n_transpose <= 18, f"layout round-trips are back: {n_transpose} transposes"


def test_error_clipping_survives_nhwc_fast_path(tmp_path):
    """error_clipping_threshold wraps only the flat output; the published
    NHWC view must be dropped for such layers or consumers would bypass
    the clip (and XLA would DCE the clipped branch entirely)."""
    import textwrap

    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.argument import Argument

    def grads_for(threshold):
        cfg_file = tmp_path / f"conf_{threshold}.py"
        extra = (f", layer_attr=ExtraAttr(error_clipping_threshold={threshold})"
                 if threshold else "")
        cfg_file.write_text(textwrap.dedent(f"""
        from paddle.trainer_config_helpers import *
        settings(batch_size=4, learning_rate=0.1)
        img = data_layer('image', size=3*8*8)
        c1 = img_conv_layer(input=img, num_channels=3, num_filters=4,
                            filter_size=3, padding=1, act=ReluActivation(),
                            name='c1'{extra})
        c2 = img_conv_layer(input=c1, num_channels=4, num_filters=4,
                            filter_size=3, padding=1, act=ReluActivation(),
                            name='c2')
        out = fc_layer(input=c2, size=2, act=SoftmaxActivation(), name='out')
        outputs(classification_cost(input=out, label=data_layer('label', size=2)))
        """))
        cfg = parse_config(str(cfg_file))
        gm = GradientMachine(cfg.model_config)
        params = gm.init_params(seed=3)
        grad_fn = gm.grad_fn()
        batch = {
            "image": Argument(value=jnp.asarray(
                np.random.RandomState(0).rand(4, 3 * 8 * 8), jnp.float32)),
            "label": Argument(ids=jnp.zeros((4,), jnp.int32)),
        }
        _, grads, _, _ = grad_fn(params, batch, None)
        return float(jnp.abs(grads["_c1.w0"]).max())

    unclipped = grads_for(0)
    clipped = grads_for(1e-9)
    assert unclipped > 1e-6, unclipped
    # a 1e-9 cotangent clip on c1's output must crush c1's weight grads
    assert clipped < unclipped * 1e-2, (clipped, unclipped)
