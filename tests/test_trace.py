"""Distributed tracing (doc/observability.md "Distributed tracing"):
the `kind=span` schema, `paddle trace` stream reconstruction — segment-
wise wall-clock anchoring, causality-bounded skew alignment, torn-tail
tolerance — the attribution sweep's one-instant-one-bucket precedence,
fleet stream discovery, and the writer-timebase `rel_time` helper the
emitters depend on. All jax-free."""

import json
import os

import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability.tracing import (
    BUCKETS,
    _selftest,
    _sweep,
    align_streams,
    analyze_trace,
    load_stream,
    main as trace_main,
    p99_shares_by_rate,
)
from paddle_tpu.utils import concurrency as cc

pytestmark = pytest.mark.trace


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")


def _write(d, recs, torn=False):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "metrics.jsonl"), "w", encoding="utf-8") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
        if torn:
            # a crash mid-append: no newline, unparseable — every
            # reader must skip it
            f.write('{"v": 1, "kind": "span", "name": "eng')


def _span(t, name, t0, dur, **fields):
    return {"v": 1, "kind": "span", "host": 0, "t": t,
            "name": name, "t0": t0, "dur_s": dur, **fields}


def _start(wall, t=0.0):
    return {"v": 1, "kind": "run_start", "host": 0, "t": t,
            "wall_time": wall}


# ------------------------------------------------------------ schema


def test_span_record_is_schema_clean(tmp_path):
    obs.configure(str(tmp_path))
    obs.emit("span", name="engine.prefill", t0=0.5, dur_s=0.1,
             trace="t1", rid="r1")
    obs.flush()
    recs = obs.read_records(obs.metrics_files(str(tmp_path))[0])
    spans = [r for r in recs if r.get("kind") == "span"]
    assert len(spans) == 1
    assert not obs.validate_record(spans[0]), spans[0]
    # the required triple is enforced: a nameless span is invalid
    assert obs.validate_record({"v": 1, "kind": "span", "host": 0,
                                "t": 0.0, "t0": 0.1, "dur_s": 0.0})


def test_rel_time_maps_monotonic_onto_stream_timebase(tmp_path):
    obs.configure(str(tmp_path))
    r = obs.rel_time(cc.monotonic())
    # "now" in the writer's timebase: a small non-negative offset from
    # its run_start
    assert 0.0 <= r < 60.0, r
    # no writer: identity fallback keeps callers harmless
    obs.configure("")
    assert obs.rel_time(5.25) == 5.25


def test_fleet_stream_dirs_discovery(tmp_path):
    run = tmp_path / "run"
    _write(str(run), [_start(10.0)])
    _write(str(run / "replica-0"), [_start(10.0)])
    _write(str(run / "fleet_status" / "replica-1"), [_start(10.0)])
    (run / "replica-9").mkdir()  # no metrics file: not a stream
    dirs = obs.fleet_stream_dirs(str(run))
    names = [os.path.basename(os.path.normpath(d)) for d in dirs]
    assert names[0] == "run"
    assert "replica-0" in names and "replica-1" in names
    assert "replica-9" not in names
    # a plain single-stream dir stays itself
    assert obs.fleet_stream_dirs(str(run / "replica-0")) == [
        str(run / "replica-0")]


# ------------------------------------------------- anchoring + skew


def test_load_stream_segmentwise_anchoring_and_torn_tail(tmp_path):
    """A killed-and-restarted replica APPENDS a fresh run_start (new t
    base) to the same file; spans after it must anchor on the new
    wall_time, and records before any anchor are dropped, counted."""
    d = str(tmp_path / "replica-0")
    _write(d, [
        _span(0.0, "engine.prefill", 0.0, 0.1, trace="pre"),  # unanchored
        _start(100.0),
        _span(1.0, "engine.prefill", 1.0, 0.1, trace="a"),
        # restart: same file, new incarnation 50s later, t rebased to 0
        _start(150.0, t=0.0),
        _span(2.0, "engine.prefill", 2.0, 0.1, trace="b"),
    ], torn=True)
    st = load_stream(d)
    assert st["segments"] == 2 and st["dropped"] == 1
    by = {s["trace"]: s for s in st["spans"]}
    assert by["a"]["t0"] == pytest.approx(101.0)
    assert by["b"]["t0"] == pytest.approx(152.0)  # the NEW anchor
    assert "pre" not in by  # unplaceable, not guessed


def test_align_streams_recovers_planted_wall_clock_skew(tmp_path):
    """The replica's wall clock runs 0.30s behind the router's: hop
    causality (route-send <= first replica event; last replica event <=
    answer) must bound and correct the shift."""
    router = str(tmp_path / "run")
    replica = str(tmp_path / "run" / "replica-0")
    _write(router, [
        _start(1000.0),
        _span(0.1, "router.enqueue", 0.10, 0.0, trace="x", rid="x"),
        _span(0.2, "router.wait", 0.10, 0.10, trace="x",
              replica="replica-0", attempt=1),
        _span(2.0, "router.answer", 2.00, 0.0, trace="x",
              replica="replica-0"),
    ])
    _write(replica, [
        _start(999.70),  # 0.30s behind
        _span(0.0, "engine.queue_wait", 0.00, 0.20, trace="x"),
        _span(1.5, "engine.decode_window", 0.20, 1.30, traces=["x"]),
    ])
    streams = [load_stream(router), load_stream(replica)]
    reports = align_streams(streams)
    assert len(reports) == 1
    rep = reports[0]
    assert rep["stream"] == "replica-0" and rep["feasible"]
    # route-send at router-abs 1000.20; replica's first raw-anchored
    # event at 999.70 => shift >= 0.50... no: anchor 999.70 + 0.0 =
    # 999.70, route end = 1000.0 + 0.20 = 1000.20 -> lo = 0.50? The
    # planted skew is 0.30 plus the 0.20s pipe wait; causality can only
    # give a BOUND, and it must cover the truth without violating it:
    answer = 1000.0 + 2.00
    last = max(s["t0"] + s["dur_s"] for s in streams[1]["spans"])
    assert last <= answer + 1e-9  # hi-constraint honored post-shift
    assert rep["shift_s"] >= 0.30 - 1e-9  # at least the planted skew


def test_infeasible_alignment_is_flagged_not_hidden(tmp_path):
    """A replica event AFTER the router's answer with no shift that can
    fix both ends: reported feasible=False, never silently clamped."""
    router = str(tmp_path / "r")
    replica = str(tmp_path / "r" / "replica-0")
    _write(router, [
        _start(100.0),
        _span(0.1, "router.enqueue", 0.1, 0.0, trace="y"),
        _span(0.2, "router.wait", 0.1, 0.1, trace="y",
              replica="replica-0"),
        _span(0.3, "router.answer", 0.3, 0.0, trace="y",
              replica="replica-0"),
    ])
    # the replica claims 5s of decode inside a 0.1s route->answer hole
    _write(replica, [
        _start(100.0),
        _span(0.2, "engine.decode_window", 0.2, 5.0, traces=["y"]),
    ])
    streams = [load_stream(router), load_stream(replica)]
    reports = align_streams(streams)
    assert reports and reports[0]["feasible"] is False


# ------------------------------------------------- attribution sweep


def test_sweep_counts_each_instant_once_with_precedence():
    # decode window [0, 10] brackets its readback [8, 10]; queue_wait
    # [0, 2] overlaps decode too — precedence: readback > decode >
    # queue_wait, each instant exactly once
    buckets, union = _sweep([
        (0.0, 2.0, "queue_wait"),
        (0.0, 10.0, "decode"),
        (8.0, 10.0, "readback"),
    ], 0.0, 12.0)
    assert union == pytest.approx(10.0)
    assert buckets["decode"] == pytest.approx(8.0)  # 10 - readback's 2
    assert buckets["readback"] == pytest.approx(2.0)
    assert "queue_wait" not in buckets  # fully shadowed by decode
    assert buckets["uncovered"] == pytest.approx(2.0)
    assert sum(buckets.values()) == pytest.approx(12.0)  # e2e, exactly


def test_sweep_clips_to_request_window():
    buckets, union = _sweep([(-5.0, 50.0, "decode")], 0.0, 1.0)
    assert union == pytest.approx(1.0)
    assert buckets == {"decode": pytest.approx(1.0)}


def test_reoffer_outranks_every_other_bucket():
    assert BUCKETS[0] == "reoffer"
    buckets, _ = _sweep([
        (0.0, 4.0, "reoffer"), (0.0, 4.0, "decode"),
        (0.0, 4.0, "queue_wait"),
    ], 0.0, 4.0)
    assert buckets == {"reoffer": pytest.approx(4.0)}


# ------------------------------------------------------- end to end


def test_analyze_trace_reconstructs_and_flags_gaps(tmp_path):
    """Two requests, one fully covered and one with a deliberate 40%
    hole: the covered one passes, the holey one is flagged with its
    gap, and both count as reconstructed."""
    router = str(tmp_path / "run")
    replica = str(tmp_path / "run" / "replica-0")
    _write(router, [
        _start(0.0),
        _span(0.0, "router.enqueue", 0.0, 0.0, trace="ok", rid="ok"),
        _span(0.0, "router.wait", 0.0, 0.2, trace="ok",
              replica="replica-0"),
        _span(1.0, "router.answer", 1.0, 0.0, trace="ok",
              replica="replica-0"),
        _span(0.0, "router.enqueue", 0.0, 0.0, trace="gap", rid="gap"),
        _span(0.0, "router.wait", 0.0, 0.2, trace="gap",
              replica="replica-0"),
        _span(1.0, "router.answer", 1.0, 0.0, trace="gap",
              replica="replica-0"),
    ])
    _write(replica, [
        _start(0.0),
        _span(0.9, "engine.decode_window", 0.2, 0.8, traces=["ok"]),
        # "gap" is only covered 0.2..0.6: a 0.4s hole before the answer
        _span(0.6, "engine.decode_window", 0.2, 0.4, traces=["gap"]),
    ])
    doc = analyze_trace([router])
    assert doc["n_requests"] == 2 == doc["n_reconstructed"]
    assert doc["requests"]["ok"]["covered_ok"]
    assert doc["requests"]["ok"]["coverage"] == pytest.approx(1.0)
    assert doc["flagged"] == ["gap"]
    g = doc["requests"]["gap"]
    assert not g["covered_ok"]
    assert g["gap_s"] == pytest.approx(0.4, abs=1e-6)
    # rung table exists and its shares include the uncovered column
    assert doc["rungs"] and "uncovered" in doc["rungs"][0]["shares"]


def test_unanswered_request_rides_along_unflagged(tmp_path):
    d = str(tmp_path / "run")
    _write(d, [
        _start(0.0),
        _span(0.0, "router.enqueue", 0.0, 0.0, trace="lost"),
        _span(0.0, "router.wait", 0.0, 0.1, trace="lost",
              replica="replica-0"),
    ])
    doc = analyze_trace([d])
    tl = doc["requests"]["lost"]
    assert tl["answered"] is False and "e2e_s" not in tl
    assert doc["n_answered"] == 0 and doc["n_flagged"] == 0


def test_p99_shares_empty_for_pretracing_artifacts(tmp_path):
    """The compare join surface must be {} (=> zero-filled keys) for a
    run dir with no span records — and for garbage paths."""
    d = str(tmp_path / "old")
    _write(d, [_start(0.0),
               {"v": 1, "kind": "serve_window", "host": 0, "t": 1.0,
                "rung": 0, "offered_rps": 2.0, "engine": "continuous"}])
    assert p99_shares_by_rate(d) == {}
    assert p99_shares_by_rate(str(tmp_path / "nope")) == {}


def test_selftest_golden_fixture():
    assert _selftest() == 0
    assert trace_main(["--selftest"]) == 0


# --------------------------------------------- fleet-aware analyzers


def test_load_run_merges_fleet_streams_without_cross_wipe(tmp_path):
    """analyze.load_run on a fleet dir: every stream keyed separately
    (one replica's run_start must never supersede another stream's
    records), replica labels stamped onto its windows."""
    from paddle_tpu.observability.analyze import analyze, load_run

    run = tmp_path / "run"
    win = {"v": 1, "kind": "serve_window", "host": 0, "t": 1.0,
           "rung": 0, "offered_rps": 2.0, "engine": "continuous",
           "window_s": 1.0, "arrived": 2, "admitted": 2, "completed": 2,
           "rejected": 0, "timeouts": 0, "cancelled": 0, "errors": 0,
           "launches": 2, "exec_s": 0.5, "gen_tokens": 20,
           "goodput_tok_s": 20.0}
    _write(str(run), [_start(100.0), dict(win, replicas=2)])
    _write(str(run / "replica-0"), [_start(100.1), win])
    _write(str(run / "replica-1"), [_start(100.2), win])
    streams = load_run(str(run))
    assert sorted(streams) == ["replica-0/0", "replica-1/0", "router/0"]
    doc = analyze(streams)
    serve = doc.get("serve") or {}
    assert serve.get("replicas") == ["replica-0", "replica-1"], serve
    # all three windows survived the merge (no run_start cross-wipe)
    assert len(doc.get("serve_windows") or []) == 3
    # single-stream dirs keep the exact legacy int-keyed shape
    solo = load_run(str(run / "replica-0"))
    assert list(solo) == [0]


def test_follow_with_stream_labels_and_fleet_stop(tmp_path):
    from paddle_tpu.observability.analyze import follow

    run = tmp_path / "run"
    _write(str(run), [_start(100.0),
                      {"v": 1, "kind": "run_end", "host": 0, "t": 9.0,
                       "status": "completed"}])
    _write(str(run / "replica-0"), [_start(100.0)])
    got = []
    for item in follow(str(run), max_polls=1, poll_boundaries=True,
                       with_stream=True):
        if item is None:
            break
        got.append(item)
    labels = {lab for lab, _rec in got}
    assert labels == {"", "replica-0"}
    kinds = {(lab, rec["kind"]) for lab, rec in got}
    assert ("", "run_end") in kinds
