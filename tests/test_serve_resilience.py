"""Serving resilience (doc/resilience.md "Serving resilience"): the
launch-failure circuit breaker (open/half-open/close on the injectable
clock), deadline-aware admission shedding and brownout degradation, the
durable at-least-once request journal, the --status_path health probe +
`paddle serve-status`, `paddle supervise --supervise_job=serve`, the
shed/breaker telemetry + `paddle compare` rates, and the serve.* chaos
e2e drills: an injected `serve.stall` under supervision produces
serve_hang_report.json + exit 19, the server restarts, and every
journaled request is answered (deduped by id, zero stranded futures);
an injected `serve.oom` dies with oom_report.json + exit 20."""

import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability.analyze import load_run
from paddle_tpu.resilience import EXIT_HANG, EXIT_OOM, faultinject
from paddle_tpu.resilience.supervisor import CRASH_REPORT, Supervisor
from paddle_tpu.serving import Engine, FakeBackend
from paddle_tpu.serving.resilience import (
    SERVE_HANG_REPORT,
    CircuitBreaker,
    RequestJournal,
    StatusWriter,
    journal_progress,
    status_main,
)
from paddle_tpu.utils.flags import _Flags

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")
    faultinject.configure("")


def _validated(run_dir):
    recs = [r for rs in load_run(run_dir).values() for r in rs]
    for rec in recs:
        assert not obs.validate_record(rec), rec
    return recs


# ------------------------------------------------------ circuit breaker


def test_breaker_open_half_open_close_on_injectable_clock():
    """The full state machine, deterministically: threshold faults open
    the breaker, the cooldown's expiry reads half_open (one probe may
    launch), success closes, a half-open fault reopens with a FRESH
    cooldown."""
    t = [100.0]
    br = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=lambda: t[0])
    assert br.state == "closed"
    assert br.allow_submit() and br.allow_launch()
    assert br.retry_after_s() == 0.0

    assert br.record_fault() is False          # 1 of 2: still closed
    assert br.state == "closed"
    assert br.record_fault() is True           # 2nd consecutive: OPENS
    assert br.state == "open"
    assert not br.allow_submit() and not br.allow_launch()
    assert br.opened_total == 1
    t[0] += 4.0
    assert abs(br.retry_after_s() - 6.0) < 1e-9

    t[0] += 6.0                                # cooldown elapsed
    assert br.state == "half_open"
    assert br.allow_launch() and br.allow_submit()  # the probe window
    br.note_probe()                            # engine launched the probe
    # EXACTLY one probe cohort: until its collect resolves the state,
    # further boundaries must not burn cohorts against the device (the
    # pipelined loop runs boundaries faster than collects resolve)
    assert not br.allow_launch()
    assert br.allow_submit()                   # arrivals queue behind it
    assert br.record_fault() is True           # probe faulted: REOPENS
    assert br.state == "open" and br.opened_total == 2
    assert abs(br.retry_after_s() - 10.0) < 1e-9   # fresh cooldown

    t[0] += 10.0
    assert br.state == "half_open"
    br.note_probe()
    br.record_success()                        # probe succeeded: CLOSES
    assert br.state == "closed" and br.retry_after_s() == 0.0
    assert br.allow_launch()                   # the probe latch cleared
    # and the consecutive count reset with it: one fault stays closed
    assert br.record_fault() is False
    assert br.state == "closed"


def test_engine_sheds_fast_while_breaker_open(tmp_path):
    """A collect fault with threshold=1 opens the breaker; the next
    submit is answered outcome=shed with the cooldown remainder as its
    retry-after hint — within one boundary, no slot burned — and the
    breaker_open count lands in the serve_window."""
    obs.configure(str(tmp_path))
    be = FakeBackend(slots=1, max_length=4, fail_at_launch=1)
    eng = Engine(be, request_timeout_s=30.0, idle_poll_s=0.01,
                 breaker=CircuitBreaker(1, 600.0)).start()
    try:
        r0 = eng.submit([2], max_new_tokens=2, rid="f0").result(timeout=60.0)
        assert r0.outcome == "error", r0
        # the fault both errored the cohort AND opened the breaker (same
        # lock block) — this submit observes the open state
        r1 = eng.submit([2], max_new_tokens=2, rid="f1").result(timeout=60.0)
        assert r1.outcome == "shed", r1
        assert r1.retry_after_s is not None and 0.0 < r1.retry_after_s <= 600.0
        assert eng.status()["breaker"] == "open"
    finally:
        assert eng.drain(timeout=60.0)
    eng.window_roll()
    recs = _validated(str(tmp_path))
    (w,) = [r for r in recs if r["kind"] == "serve_window"]
    assert w["shed"] == 1 and w["breaker_open"] == 1, w
    (shed_rec,) = [r for r in recs if r["kind"] == "request"
                   and r["outcome"] == "shed"]
    assert shed_rec["id"] == "f1" and shed_rec["retry_after_s"] > 0.0


def test_engine_breaker_half_open_probe_recovers():
    """After the cooldown the half-open probe cohort goes through: the
    first non-faulting launch closes the breaker and service resumes."""
    be = FakeBackend(slots=1, max_length=4, fail_at_launch=1)
    eng = Engine(be, request_timeout_s=30.0, idle_poll_s=0.01,
                 breaker=CircuitBreaker(1, 0.05)).start()
    try:
        assert eng.submit([2], max_new_tokens=2,
                          rid="g0").result(timeout=60.0).outcome == "error"
        # sheds during the cooldown answer fast; once half-open, a probe
        # completes and closes the breaker — poll until service resumes
        import time as _time

        deadline = _time.time() + 60.0
        outcome, i = None, 0
        while _time.time() < deadline:
            i += 1
            outcome = eng.submit([2], max_new_tokens=1,
                                 rid=f"g{i}").result(timeout=60.0).outcome
            if outcome == "ok":
                break
            _time.sleep(0.01)
        assert outcome == "ok", outcome
        assert eng.status()["breaker"] == "closed"
    finally:
        assert eng.drain(timeout=60.0)


# ------------------------------------------------------ shed policies


def test_deadline_shed_at_admission_with_measured_etas():
    """shed_policy=deadline: a queued request whose remaining deadline
    the measured prefill+decode estimate can't cover is answered
    outcome=shed AT ADMISSION (no slot wasted, no retry hint — more
    time would not fit the budget either)."""
    be = FakeBackend(slots=2, max_length=16)
    eng = Engine(be, request_timeout_s=0.5, idle_poll_s=0.01,
                 shed_policy="deadline")
    # prime the EMAs as a warmed engine would have measured them:
    # 1s/micro-step makes an 8-token budget a provable 8s > 0.5s miss
    eng._step_ema = 1.0
    eng._prefill_ema = 0.0
    eng.start()
    try:
        res = eng.submit([2], max_new_tokens=8, rid="d0").result(timeout=60.0)
        assert res.outcome == "shed", res
        assert res.retry_after_s is None
        assert res.tokens == []
    finally:
        assert eng.drain(timeout=60.0)


def test_deadline_policy_never_guesses_unmeasured():
    """Before any launch has been measured (step EMA 0) the deadline
    policy must admit normally — shedding on a guess would refuse the
    very first requests of every run."""
    be = FakeBackend(slots=2, max_length=16)
    eng = Engine(be, request_timeout_s=0.5, idle_poll_s=0.01,
                 shed_policy="deadline").start()
    try:
        res = eng.submit([2], max_new_tokens=2, rid="u0").result(timeout=60.0)
        assert res.outcome == "ok", res
    finally:
        assert eng.drain(timeout=60.0)


def test_brownout_caps_budgets_and_sheds_excess_arrivals():
    """Engaged brownout degrades instead of dying: admissions get their
    token budget capped to the brownout share of max_length, and
    arrivals past one full slot wave are shed with a drain-ETA hint."""
    from paddle_tpu.serving.engine import BROWNOUT_BUDGET_SHARE

    be = FakeBackend(slots=1, max_length=8, step_delay_s=0.05)
    eng = Engine(be, request_timeout_s=30.0, idle_poll_s=0.01,
                 shed_policy="brownout")
    # engage the degraded mode directly (the EMA needs sustained
    # boundaries); give the drain-ETA estimator a measured rate
    eng._brownout = True
    eng._pressure_ema = 5.0
    eng._step_ema = 0.05
    eng.start()
    try:
        cap = max(1, int(8 * BROWNOUT_BUDGET_SHARE))
        f0 = eng.submit([2], max_new_tokens=8, rid="b0")   # occupies the slot
        # wait for b0's admission — a brownout shed is queue-depth-based,
        # so the next two submits must observe a settled queue
        import time as _time

        deadline = _time.time() + 30.0
        while eng.status().get("occupancy") != 1:
            assert _time.time() < deadline, eng.status()
            _time.sleep(0.005)
        f1 = eng.submit([2], max_new_tokens=8, rid="b1")   # fills the wave
        f2 = eng.submit([2], max_new_tokens=8, rid="b2")   # past it: shed
        r2 = f2.result(timeout=60.0)
        assert r2.outcome == "shed", r2
        assert r2.retry_after_s is not None and r2.retry_after_s > 0.0
        r0, r1 = f0.result(timeout=60.0), f1.result(timeout=60.0)
        # both admitted requests completed — with the capped budget, not
        # the 8 tokens they asked for (degrade, don't die)
        assert r0.outcome == "ok" and r1.outcome == "ok", (r0, r1)
        assert len(r0.tokens) <= cap and len(r1.tokens) <= cap, (r0, r1)
    finally:
        assert eng.drain(timeout=60.0)


def test_unmeasured_drain_eta_is_a_real_backoff():
    """A brownout shed BEFORE the first collect boundary (step EMA
    unmeasured) must hint a conservative retry-after, not echo the
    20 ms idle poll — a near-zero hint invites the burst right back."""
    from paddle_tpu.serving.engine import UNMEASURED_RETRY_S

    eng = Engine(FakeBackend(slots=1, max_length=8), idle_poll_s=0.02,
                 shed_policy="brownout")
    with eng._lock:
        assert eng._step_ema == 0.0
        assert eng._drain_eta_locked() == UNMEASURED_RETRY_S
        eng._step_ema = 0.05
        eng._prefill_ema = 0.1
        assert eng._drain_eta_locked() > eng.idle_poll_s


def test_journal_replay_bypasses_queue_cap():
    """queue_cap governs NEW arrivals. A restarted server's journal
    replay (submit(replay=True)) re-offers an already-accepted backlog
    that can legitimately exceed the cap (cap + in-flight at the
    crash); capping it would reject-and-done-mark the tail —
    permanently truncating the queue the journal exists to preserve."""
    be = FakeBackend(slots=1, max_length=8, step_delay_s=0.01)
    eng = Engine(be, queue_cap=2, request_timeout_s=30.0,
                 idle_poll_s=0.01)
    eng.start()
    try:
        futs = [eng.submit([2], max_new_tokens=1, rid=f"jr{i}",
                           replay=True)
                for i in range(5)]
        outs = [f.result(timeout=60.0).outcome for f in futs]
        assert outs == ["ok"] * 5, outs
        # the cap still binds fresh arrivals — flood past it
        fresh = [eng.submit([2], max_new_tokens=4, rid=f"nw{i}")
                 for i in range(8)]
        fresh_outs = [f.result(timeout=60.0).outcome for f in fresh]
        assert all(o in ("ok", "rejected") for o in fresh_outs), fresh_outs
        assert fresh_outs.count("rejected") >= 1, fresh_outs
    finally:
        assert eng.drain(timeout=60.0)


def test_unknown_shed_policy_refused_loudly():
    with pytest.raises(ValueError, match="shed policy"):
        Engine(FakeBackend(slots=1), shed_policy="sometimes")


# ------------------------------------------------------------- journal


def test_auto_request_ids_are_incarnation_salted():
    """Id-less stdin lines get pid-salted auto ids: the line counter
    restarts at 0 every incarnation, and a journaled `req-0` from a
    previous run must not make a FRESH id-less request look like a
    duplicate (silently dropped) after a supervised restart."""
    from paddle_tpu.serving.frontend import _parse_line

    doc, err, rid = _parse_line("[1, 2]", 0)
    assert err == "" and doc["id"] == rid == f"req-{os.getpid()}-0", doc
    doc2, _, rid2 = _parse_line('{"prompt": [3], "id": "mine"}', 1)
    assert doc2["id"] == rid2 == "mine"
    # a validation error still answers under the CLIENT's id when one
    # was parseable — a synthetic id is uncorrelatable
    doc3, err3, rid3 = _parse_line('{"prompt": "oops", "id": "bad1"}', 2)
    assert doc3 is None and err3 and rid3 == "bad1"
    doc4, err4, rid4 = _parse_line("{not json", 3)
    assert doc4 is None and err4 and rid4 == f"req-{os.getpid()}-3"


def test_request_journal_at_least_once_contract(tmp_path):
    """Accept is durable and deduping, done-marks clear pending, a
    reloaded journal re-offers exactly the accepted-but-unanswered set
    in acceptance order, and a torn tail line (the crash the journal
    exists for) is tolerated."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path)
    assert j.accept({"id": "a", "prompt": [1, 2], "max_new_tokens": 3})
    assert j.accept({"id": "b", "prompt": [4], "max_new_tokens": 1})
    assert not j.accept({"id": "a", "prompt": [9]})  # replayed stdin line
    j.answer("a", "ok")
    assert j.is_done("a") and not j.is_done("b")
    assert [d["id"] for d in j.pending()] == ["b"]
    j.close()

    # crash mid-append: the torn tail must not poison the reload
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"op": "acce')
    j2 = RequestJournal(path)
    assert [d["id"] for d in j2.pending()] == ["b"]
    assert j2.pending()[0]["prompt"] == [4]
    assert j2.pending()[0]["max_new_tokens"] == 1
    j2.close()

    # the supervisor's progress fingerprint moves with answered count —
    # and ONLY with it: fresh accepts must not disguise a crash loop
    # that answers nothing as progress
    fp1 = journal_progress(path)
    assert fp1 == "answered:1"
    j3 = RequestJournal(path)
    assert j3.accept({"id": "c", "prompt": [5], "max_new_tokens": 1})
    j3.close()
    assert journal_progress(path) == fp1
    j4 = RequestJournal(path)
    j4.answer("b", "ok")
    j4.close()
    assert journal_progress(path) != fp1
    assert journal_progress(str(tmp_path / "missing.jsonl")) is None


# ------------------------------------------------------- status probe


def test_status_writer_and_serve_status_renderer(tmp_path, capsys):
    """--status_path: the periodic snapshot is atomic and honest (queue
    depth, occupancy, totals, draining), the final stop() snapshot
    carries the draining flag, and `paddle serve-status` renders it
    jax-free (both table and --json)."""
    be = FakeBackend(slots=2, max_length=4)
    eng = Engine(be, request_timeout_s=30.0, idle_poll_s=0.01).start()
    path = str(tmp_path / "health" / "status.json")
    writer = StatusWriter(path, eng, interval_s=0.02)
    writer.write_now()
    doc = json.load(open(path))
    assert doc["started"] and not doc["draining"]
    assert doc["queue_depth"] == 0 and doc["slots"] == 2
    assert doc["breaker"] == "disabled" and doc["shed_policy"] == "off"
    assert eng.submit([2], max_new_tokens=1,
                      rid="s0").result(timeout=60.0).outcome == "ok"
    assert eng.drain(timeout=60.0)
    writer.stop()          # final snapshot after the drain
    doc = json.load(open(path))
    assert doc["draining"] is True
    assert doc["totals"]["ok"] == 1

    assert status_main([path]) == 0
    out = capsys.readouterr().out
    assert "draining" in out and "queue depth" in out
    assert status_main([path, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["totals"]["ok"] == 1
    assert status_main([str(tmp_path / "nope.json")]) == 1

    # degraded snapshots render LOUDLY, not as a blank 'not started'
    # table: stale = the engine's bounded-lock timeout fired (scheduler
    # busy or wedged), error = the probe itself failed
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({"stale": True, "detail": "lock busy"}))
    assert status_main([str(stale)]) == 0
    out = capsys.readouterr().out
    assert "STALE" in out and "lock busy" in out
    assert "not started" not in out
    broken = tmp_path / "broken.json"
    broken.write_text(json.dumps({"error": "probe exploded"}))
    assert status_main([str(broken)]) == 1
    assert "probe exploded" in capsys.readouterr().out


# ------------------------------------- supervise --supervise_job=serve


def _no_sleep(_s):
    pass


def test_supervisor_serve_child_cmd_keeps_args_verbatim():
    """A serve child's restart command is `paddle serve` with the user
    args kept verbatim: no --init_model_path=auto injection (the
    journal, not a checkpoint, is the resume state) and the
    supervisor-only --supervise_job stripped."""
    flags = _Flags(supervise_job="serve", serve_journal_path="/tmp/j.jsonl")
    sup = Supervisor(
        ["--config=c.py", "--supervise_job=serve",
         "--serve_journal_path=/tmp/j.jsonl"], flags,
    )
    first = sup.child_cmd(restart=False)
    again = sup.child_cmd(restart=True)
    assert first[-3:] == ["serve", "--config=c.py",
                          "--serve_journal_path=/tmp/j.jsonl"], first
    assert again == first, (first, again)
    assert not any("supervise_job" in a for a in first)
    assert not any("init_model_path" in a for a in again)


def test_supervisor_serve_probe_reads_journal_progress(tmp_path):
    """The serve child's crash-loop probe fingerprints the journal's
    answered count — None without a journal (every death then looks
    loop-like, which errs toward stopping)."""
    jpath = str(tmp_path / "j.jsonl")
    j = RequestJournal(jpath)
    j.accept({"id": "x", "prompt": [1], "max_new_tokens": 1})
    j.close()
    flags = _Flags(supervise_job="serve", serve_journal_path=jpath,
                   supervise_dir=str(tmp_path / "sup"))
    sup = Supervisor(["--config=c.py"], flags)
    assert sup.job == "serve"
    assert sup._probe() == "answered:0"
    flags2 = _Flags(supervise_job="serve",
                    supervise_dir=str(tmp_path / "sup2"))
    assert Supervisor(["--config=c.py"], flags2)._probe() is None


def test_supervisor_serve_exit20_consumes_budget_then_recovers(tmp_path):
    """An OOM death (exit 20) of a serve child is charged to the
    restart budget — never free — but within budget the child restarts
    and a clean second run ends the supervision with rc 0."""
    jpath = str(tmp_path / "j.jsonl")
    script = (
        "import json, os, sys\n"
        "counter, journal = sys.argv[1], sys.argv[2]\n"
        "n = int(open(counter).read()) if os.path.exists(counter) else 0\n"
        "open(counter, 'w').write(str(n + 1))\n"
        "with open(journal, 'a') as f:\n"
        "    f.write(json.dumps({'op': 'accept', 'id': f'r{n}'}) + '\\n')\n"
        "    f.write(json.dumps({'op': 'done', 'id': f'r{n}',\n"
        "                        'outcome': 'ok'}) + '\\n')\n"
        "sys.exit(20 if n == 0 else 0)\n"
    )
    flags = _Flags(supervise_job="serve", serve_journal_path=jpath,
                   supervise_dir=str(tmp_path / "sup"),
                   restart_budget=1, crash_loop_threshold=3)
    sup = Supervisor(
        ["--config=unused.py"], flags,
        child_cmd=[sys.executable, "-c", script,
                   str(tmp_path / "counter"), jpath],
        sleep=_no_sleep,
    )
    assert sup.run() == 0
    assert [a["exit_code"] for a in sup.attempts] == [EXIT_OOM, 0]
    assert not os.path.exists(os.path.join(str(tmp_path / "sup"),
                                           CRASH_REPORT))

    # the same death with ZERO budget is terminal: OOM never rides free
    flags0 = _Flags(supervise_job="serve",
                    supervise_dir=str(tmp_path / "sup0"),
                    restart_budget=0, crash_loop_threshold=3)
    sup0 = Supervisor(
        ["--config=unused.py"], flags0,
        child_cmd=[sys.executable, "-c", "import sys; sys.exit(20)"],
        sleep=_no_sleep,
    )
    assert sup0.run() == EXIT_OOM
    report = json.load(open(os.path.join(str(tmp_path / "sup0"),
                                         CRASH_REPORT)))
    assert report["reason"] == "restart_budget_exhausted"


# ----------------------------------------------------- compare rates


def test_compare_shed_and_error_rates_lower_is_better(tmp_path):
    """Per-rung shed_rate/error_rate growth is a serving REGRESSION —
    and an artifact that PREDATES the fields (no shed_rate key) still
    joins: the old side zero-fills, so 0 -> N growth is judged instead
    of landing invisibly in only_b."""
    from paddle_tpu.observability.compare import compare, load_side

    def artifact(name, rung_extra):
        p = tmp_path / name
        rung = {"offered_rps": 50.0, "p50_ms": 2.0, "p99_ms": 4.0,
                "goodput_tok_s": 5000.0}
        rung.update(rung_extra)
        p.write_text(json.dumps({
            "metric": "serve_cpu_smoke_goodput_tokens_per_sec",
            "value": 5000.0, "unit": "tokens/s", "vs_baseline": 1.0,
            "rungs": [rung],
        }))
        return str(p)

    old = artifact("old.json", {})                       # pre-PR-15 shape
    new = artifact("new.json", {"shed_rate": 0.25, "error_rate": 0.1})
    doc = compare(load_side(old), load_side(new))
    by = {m["metric"]: m["verdict"] for m in doc["metrics"]}
    assert by["serve.50rps.shed_rate"] == "REGRESSION", by
    assert by["serve.50rps.error_rate"] == "REGRESSION", by
    assert doc["verdict"] == "REGRESSION"
    strays = [k for k in list(doc.get("only_a") or []) +
              list(doc.get("only_b") or []) if "rate" in str(k)]
    assert not strays, strays
    # and shrinking rates read as improvement, not regression
    doc2 = compare(load_side(new), load_side(old))
    by2 = {m["metric"]: m["verdict"] for m in doc2["metrics"]}
    assert by2["serve.50rps.shed_rate"] == "IMPROVED", by2


# ------------------------------------------------------ overload A/B


def test_ab_overload_shed_on_vs_off(tmp_path, monkeypatch):
    """THE overload A/B (ISSUE 15 acceptance): the serve ladder at
    3x/6x measured capacity with a deadline that bites, shedding on vs
    off. The STABLE mechanical wins are asserted from the live run —
    deep-overload timeouts convert to sheds (a doomed request is
    answered outcome=shed well before its deadline instead of wasting
    a slot and timing out), the completed-request tail does not get
    worse, and the live artifacts' 0 -> N shed_rate growth is flagged
    by the like-for-like compare. The verdict-IMPROVED compare contract
    itself is pinned deterministically in
    test_compare_shed_ab_verdict_improved_with_abs_floor: at CPU smoke
    scale the sub-100ms percentiles jitter across containers by more
    than the policy's real latency win, so asserting the live verdict
    would pin a coin flip, not the contract."""
    from paddle_tpu.observability import compare

    monkeypatch.delenv("PADDLE_TPU_BENCH_METRICS_DIR", raising=False)
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_REQUESTS", "64")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_MIXED_LEN", "1")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_SEED", "0")
    # the serial loop, like the static-vs-continuous knee A/B: the
    # overload signal should measure the SHED POLICY, not pipelined
    # scheduler jitter in a 64-sample tail
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_PIPELINE", "off")
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    # the deadline must BITE at overload (it is what the off side burns
    # and the deadline policy defends) — 80ms against a ~4ms/req service
    kw = dict(B=4, T=8, vocab=1000, dim=128, beam_size=1, max_length=64,
              dtype="float32", timeout_s=0.08)
    # quick calibration pass, then pin the overload ladder off it
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path / "cal"))
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_RATES", "1.0")
    _, cal = bench.bench_serve(engine="continuous", n_requests=1, **kw)
    cap = cal["capacity_rps"]
    rates = ",".join(str(round(f * cap, 4)) for f in (3.0, 6.0))
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_RATES", rates)

    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path / "off"))
    v_off, e_off = bench.bench_serve(engine="continuous", **kw)
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_SHED", "deadline")
    monkeypatch.setenv("PADDLE_TPU_BENCH_SERVE_DIR", str(tmp_path / "on"))
    v_on, e_on = bench.bench_serve(engine="continuous", **kw)
    obs.configure("")

    assert e_on["shed_policy"] == "deadline"
    assert "shed_policy" not in e_off
    assert sum(r["shed"] for r in e_on["rungs"]) > 0, e_on["rungs"]
    assert all(r["shed"] == 0 for r in e_off["rungs"]), e_off["rungs"]

    # the conversion: at 6x the off side burns its deadline on doomed
    # requests; the deadline policy sheds them at admission instead
    off6, on6 = e_off["rungs"][-1], e_on["rungs"][-1]
    assert off6["timeouts"] > 0, off6
    assert on6["shed"] > 0, on6
    assert on6["timeouts"] <= off6["timeouts"] // 2, (off6, on6)
    # and the completed-request tail did not get worse for it
    assert on6["p99_ms"] <= off6["p99_ms"] * 1.25, (off6, on6)

    # every shed was ANSWERED well before the deadline it could not
    # have met — the client hears "shed, don't wait" instead of
    # burning its own 80ms
    recs = _validated(str(tmp_path / "on"))
    sheds = [r for r in recs if r["kind"] == "request"
             and r["outcome"] == "shed"]
    assert sheds
    assert all(r["t_shed"] - r["t_enqueue"] < 0.08 for r in sheds), sheds

    # the live artifacts join, and WITHOUT an abs-floor the deliberate
    # shed growth is flagged — the like-for-like guard (satellite:
    # growth => REGRESSION) bites on real sweeps
    a, b = tmp_path / "A.json", tmp_path / "B.json"
    metric = "serve_cpu_smoke_goodput_tokens_per_sec"
    a.write_text(json.dumps(dict(metric=metric, value=round(v_off, 1),
                                 **e_off)))
    b.write_text(json.dumps(dict(metric=metric, value=round(v_on, 1),
                                 **e_on)))
    doc = compare.compare(compare.load_side(str(a)),
                          compare.load_side(str(b)), threshold=0.2)
    assert any("shed_rate" in m for m in doc["regressions"]), doc
    strays = [k for k in list(doc["only_a"]) + list(doc["only_b"])
              if "shed_rate" in str(k) or "error_rate" in str(k)]
    assert not strays, strays


def test_compare_shed_ab_verdict_improved_with_abs_floor(tmp_path):
    """The compare half of the overload A/B contract, pinned
    deterministically: a shed-on sweep whose completed-request p99
    improved lands verdict IMPROVED when the deliberate 0 -> N
    shed_rate is absorbed via --abs-floor (which only applies to
    zero-baseline metrics — the latency rows are judged normally), and
    REGRESSION without the floor (the like-for-like guard)."""
    from paddle_tpu.observability import compare

    def artifact(name, p99, shed_rate):
        p = tmp_path / name
        p.write_text(json.dumps({
            "metric": "serve_cpu_smoke_goodput_tokens_per_sec",
            "value": 5000.0, "unit": "tokens/s", "vs_baseline": 1.0,
            "rungs": [{"offered_rps": 300.0, "p50_ms": 20.0, "p99_ms": p99,
                       "goodput_tok_s": 5000.0, "shed_rate": shed_rate,
                       "error_rate": 0.0}],
        }))
        return str(p)

    off = artifact("off.json", 120.0, 0.0)
    on = artifact("on.json", 40.0, 0.3)
    doc = compare.compare(compare.load_side(off), compare.load_side(on),
                          threshold=0.2, abs_floor=1.0)
    assert doc["verdict"] == "IMPROVED", doc
    assert "serve.300rps.p99_ms" in doc["improvements"], doc
    doc2 = compare.compare(compare.load_side(off), compare.load_side(on),
                           threshold=0.2)
    assert doc2["verdict"] == "REGRESSION", doc2
    assert "serve.300rps.shed_rate" in doc2["regressions"], doc2


# ------------------------------------------------------- chaos e2e


SERVE_CONFIG = """
import sys
sys.path.insert(0, {demo!r})
from paddle.trainer_config_helpers import *
from seqToseq_net import gru_encoder_decoder

settings(batch_size=2, learning_rate=1e-3, learning_method=AdamOptimizer())
gru_encoder_decoder(source_dict_dim=50, target_dict_dim=50,
                    is_generating=True, word_vector_dim=16,
                    encoder_size=16, decoder_size=16, beam_size=1,
                    max_length=6)
"""

SUBPROC_ENV = dict(
    os.environ, JAX_PLATFORMS="cpu",
    PYTHONPATH=f"{REPO}:{os.path.join(REPO, 'compat')}",
)


def _serve_cfg(tmp_path):
    cfg = tmp_path / "serve_conf.py"
    cfg.write_text(SERVE_CONFIG.format(
        demo=os.path.join(REPO, "demo", "seqToseq")))
    return cfg


@pytest.mark.chaos
def test_chaos_serve_oom_premortem_exit20(tmp_path):
    """An injected serve.oom (synthetic RESOURCE_EXHAUSTED at the 2nd
    collect boundary) gets the trainer's treatment: oom_report.json in
    the run dir and exit EXIT_OOM=20 — not a raw crash."""
    cfg = _serve_cfg(tmp_path)
    run_dir = tmp_path / "run"
    reqs = "\n".join(json.dumps(
        {"id": f"o{i}", "prompt": [4 + i, 7], "max_new_tokens": 4}
    ) for i in range(2))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "serve",
         f"--config={cfg}", "--use_tpu=0", "--serve_slots=2",
         "--serve_prompt_tokens=4", "--serve_decode_block=1",
         f"--metrics_path={run_dir}",
         "--fault_spec=serve.oom=raise@2"],
        input=reqs + "\n", capture_output=True, text=True, timeout=300,
        env=SUBPROC_ENV,
    )
    assert out.returncode == EXIT_OOM, (out.returncode, out.stderr[-3000:])
    report = json.load(open(run_dir / "oom_report.json"))
    assert "RESOURCE_EXHAUSTED" in report["error"], report["error"]


@pytest.mark.chaos
def test_chaos_serve_stall_hangwatch_exit19_with_forensics(tmp_path):
    """An injected serve.stall wedges the 2nd decode collect; the
    --serve_hang_timeout hangwatch dumps serve_hang_report.json — with
    thread stacks AND the in-flight cohort snapshot — and exits 19.
    The --status_path probe file exists and parses."""
    cfg = _serve_cfg(tmp_path)
    run_dir = tmp_path / "run"
    status = tmp_path / "status.json"
    reqs = "\n".join(json.dumps(
        {"id": f"h{i}", "prompt": [4 + i, 7], "max_new_tokens": 4}
    ) for i in range(2))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "serve",
         f"--config={cfg}", "--use_tpu=0", "--serve_slots=2",
         "--serve_prompt_tokens=4", "--serve_decode_block=1",
         f"--metrics_path={run_dir}", f"--status_path={status}",
         "--serve_hang_timeout=2",
         "--fault_spec=serve.stall=sleep:3600@2"],
        input=reqs + "\n", capture_output=True, text=True, timeout=300,
        env=SUBPROC_ENV,
    )
    assert out.returncode == EXIT_HANG, (out.returncode, out.stderr[-3000:])
    # the wedged cohort's outcome=error answers were FLUSHED to stdout
    # before the exit (the hangwatch's answer_flush hook) — without a
    # journal these lines are the only answer the client will ever get
    answers = {d["id"]: d for d in
               (json.loads(l) for l in out.stdout.splitlines()
                if l.strip().startswith("{")) if "outcome" in d}
    assert set(answers) == {"h0", "h1"}, (answers, out.stderr[-2000:])
    assert all(d["outcome"] == "error" and "hang" in d.get("error", "")
               for d in answers.values()), answers
    report = json.load(open(run_dir / SERVE_HANG_REPORT))
    assert report["reason"] == "serve_hang"
    assert report["threads"], "no thread stacks in the forensics"
    # the in-flight cohort snapshot: the wedged requests are NAMED
    inflight = report["inflight"]
    slotted = [s["rid"] for s in inflight["slots"] if s]
    assert slotted, inflight
    assert set(slotted) <= {"h0", "h1"}, inflight
    assert json.load(open(status))["started"] is True


@pytest.mark.chaos
def test_chaos_serve_stall_supervised_restart_answers_journal(tmp_path):
    """THE acceptance scenario (ISSUE 15): under an injected
    serve.stall, `paddle supervise --supervise_job=serve` sees the
    child's hangwatch produce serve_hang_report.json + exit 19,
    restarts the server, and the request journal re-offers every
    accepted-but-unanswered request — every request id is answered
    (at-least-once, deduped by id), none twice within an incarnation,
    zero stranded futures, and the supervision ends rc 0.

    8 requests x 2 slots x budget 2 = 8 collect boundaries in run 1;
    the stall at boundary 7 wedges the last cohort. Run 2 replays only
    the unanswered tail (at most one wave short of 7 boundaries even if
    every done-mark was lost), so the same fault spec never re-fires."""
    cfg = _serve_cfg(tmp_path)
    save_dir = tmp_path / "out"
    sup_dir = tmp_path / "sup"
    jpath = tmp_path / "journal.jsonl"
    ids = [f"j{i}" for i in range(8)]
    reqs = "\n".join(json.dumps(
        {"id": rid, "prompt": [4 + i, 7], "max_new_tokens": 2}
    ) for i, rid in enumerate(ids))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "supervise",
         "--supervise_job=serve",
         f"--config={cfg}", "--use_tpu=0", "--serve_slots=2",
         "--serve_prompt_tokens=4", "--serve_decode_block=1",
         f"--save_dir={save_dir}", f"--supervise_dir={sup_dir}",
         f"--serve_journal_path={jpath}",
         f"--compile_cache_dir={tmp_path / 'ccache'}",
         "--serve_hang_timeout=3", "--restart_base_delay=0.01",
         "--fault_spec=serve.stall=sleep:3600@7"],
        input=reqs + "\n", capture_output=True, text=True, timeout=600,
        env=SUBPROC_ENV, cwd=str(tmp_path),
    )
    assert out.returncode == 0, (out.returncode, out.stderr[-3000:])
    # the hang was diagnosed, not silent: forensics + exactly 2 attempts
    report = json.load(open(save_dir / SERVE_HANG_REPORT))
    assert report["reason"] == "serve_hang"
    logs = sorted(n for n in os.listdir(sup_dir)
                  if n.startswith("attempt-"))
    assert logs == ["attempt-000.log", "attempt-001.log"], logs

    def results(log_name):
        out = {}
        for line in open(sup_dir / log_name):
            line = line.strip()
            if not line.startswith("{"):
                continue
            doc = json.loads(line)
            if "outcome" in doc and doc.get("id") in ids:
                assert doc["id"] not in out, (
                    f"{doc['id']} answered twice in {log_name}")
                out[doc["id"]] = doc["outcome"]
        return out

    first, second = results(logs[0]), results(logs[1])
    # every journaled request is answered across the incarnations —
    # dedupe by id is the at-least-once contract; zero stranded futures
    assert set(first) | set(second) == set(ids), (first, second)
    # the wedged cohort either heard "the server hung" (outcome=error
    # answered by the hangwatch just before exit 19) or was re-offered
    # by the journal and answered ok by the restarted server; requests
    # the first incarnation never answered MUST all come back ok
    unanswered = set(ids) - set(first)
    assert unanswered <= set(second), (unanswered, second)
    assert all(second[rid] == "ok" for rid in unanswered), second
    assert all(o == "ok" for o in first.values()
               if o not in ("error",)), first
    if unanswered:
        # the restart reported the replay it performed
        assert any("re-offering" in open(sup_dir / l).read()
                   for l in logs) or "re-offering" in out.stderr, (
            "restart did not report the journal replay")
    # and the journal itself holds every accept
    accepted = {json.loads(l)["id"] for l in open(jpath)
                if l.strip() and json.loads(l).get("op") == "accept"}
    assert accepted == set(ids)
    assert not os.path.exists(sup_dir / CRASH_REPORT)
