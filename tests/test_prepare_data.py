"""Real-dataset converters (demo/*/prepare_data.py) + the providers' real
corpus paths, exercised on tiny raw-format fixtures built in-test (no
network: the converters' role analog is the reference's get_data.sh +
preprocess.py pipelines, whose fetch step this environment can't run —
see doc/divergences.md).

Each test builds a fixture in the dataset's RAW public format, runs the
converter, and feeds the converted output through the demo's actual
provider to prove quality parity is runnable wherever the data exists.
"""

import gzip
import importlib
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _demo_module(demo, name):
    demo_dir = os.path.join(REPO, "demo", demo)
    compat = os.path.join(REPO, "compat")
    for p in (compat, demo_dir):
        if p not in sys.path:
            sys.path.insert(0, p)
    try:
        # demos share module names (common, dataprovider): evict collisions
        for mod in ("common", "dataprovider", "prepare_data"):
            existing = sys.modules.get(mod)
            if existing is not None and demo_dir not in (
                getattr(existing, "__file__", "") or ""
            ):
                del sys.modules[mod]
        m = importlib.import_module(name)
        if demo_dir not in (getattr(m, "__file__", "") or ""):
            m = importlib.reload(m)
        return m
    finally:
        sys.path.remove(demo_dir)


def test_quick_start_amazon_converter(tmp_path):
    reviews = tmp_path / "reviews_Electronics_5.json.gz"
    rows = (
        [{"reviewText": f"great product works great {i}", "overall": 5.0} for i in range(6)]
        + [{"reviewText": f"terrible broke on day {i}", "overall": 1.0} for i in range(6)]
        + [{"reviewText": "it is ok", "overall": 3.0}]  # 3-4 stars discarded
    )
    with gzip.open(reviews, "wt") as f:
        f.write("\n".join(json.dumps(r) for r in rows))

    pd = _demo_module("quick_start", "prepare_data")
    out = tmp_path / "amazon-out"
    n_train, n_test, dict_size = pd.convert(str(reviews), str(out), test_ratio=0.2)
    assert n_train + n_test == 12  # the neutral review was dropped
    assert dict_size > 0

    from paddle_tpu.data import datasets

    word_dict = datasets.load_dict(str(out / "dict.txt"))
    assert "great" in word_dict and "terrible" in word_dict

    # the real corpus flows through the demo provider
    common = _demo_module("quick_start", "common")
    dp = _demo_module("quick_start", "dataprovider_emb")
    settings = dp.process.init(dictionary=word_dict)
    train_file = (out / "train.list").read_text().strip()
    samples = list(dp.process.generator_fn(settings, train_file))
    assert len(samples) == n_train
    ids, label = samples[0]
    assert label in (0, 1) and all(0 <= i < len(word_dict) for i in ids)
    # resolve_dict prefers the converter dict when given
    assert common.resolve_dict(str(out / "dict.txt")) == word_dict
    assert common.resolve_dict("") == {w: i for i, w in enumerate(common.VOCAB)}


def test_sentiment_imdb_converter(tmp_path):
    imdb = tmp_path / "aclImdb"
    texts = {
        "pos": ["a brilliant moving film", "superb acting and story"],
        "neg": ["a dull tedious mess", "boring waste of time"],
    }
    for split in ("train", "test"):
        for sub, lines in texts.items():
            d = imdb / split / sub
            d.mkdir(parents=True)
            for i, t in enumerate(lines):
                (d / f"{i}_7.txt").write_text(t)

    pd = _demo_module("sentiment", "prepare_data")
    out = tmp_path / "imdb-out"
    n_train, n_test, dict_size = pd.convert(str(imdb), str(out), cutoff=0)
    assert n_train == 4 and n_test == 4

    from paddle_tpu.data import datasets

    word_dict = datasets.load_dict(str(out / "dict.txt"))
    dp = _demo_module("sentiment", "dataprovider")
    settings = dp.process.init(dictionary=word_dict)
    samples = list(dp.process.generator_fn(settings, (out / "test.list").read_text().strip()))
    assert len(samples) == 4
    labels = {s[1] for s in samples}
    assert labels == {0, 1}


def test_recommendation_movielens_converter(tmp_path):
    ml = tmp_path / "ml-1m"
    ml.mkdir()
    (ml / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Jumanji (1995)::Adventure|Children's|Fantasy\n",
        encoding="latin-1",
    )
    (ml / "users.dat").write_text(
        "1::F::1::10::48067\n2::M::56::16::70072\n", encoding="latin-1"
    )
    (ml / "ratings.dat").write_text(
        "1::1::5::100\n1::2::3::200\n2::1::4::150\n2::2::1::250\n",
        encoding="latin-1",
    )

    pd = _demo_module("recommendation", "prepare_data")
    out = tmp_path / "ml-out"
    n_train, n_test, dims = pd.convert(str(ml), str(out), test_per_user=1)
    # 2 ratings/user: 1 train + 1 test each
    assert (n_train, n_test) == (2, 2)
    assert dims["movie_ids"] == 3 and dims["user_ids"] == 3
    assert dims["ages"] == 7

    dp = _demo_module("recommendation", "dataprovider")
    settings = dp.process.init(meta=str(out / "meta.pkl"))
    train_file = (out / "train.list").read_text().strip()
    samples = list(dp.process.generator_fn(settings, train_file))
    assert len(samples) == 2
    for s in samples:
        assert -1.0 <= s["rating"][0] <= 1.0
        assert s["movie_title"], "real titles must tokenize to word ids"
    # user 2 is M age 56 job 16
    u2 = [s for s in samples if s["user_id"] == 2][0]
    assert u2["user_gender"] == 0 and u2["user_age"] == 6 and u2["user_job"] == 16


def test_seqtoseq_wmt_converter(tmp_path):
    src = tmp_path / "train.src"
    trg = tmp_path / "train.trg"
    src.write_text("le chat noir\nun chien\nle chien rouge\n")
    trg.write_text("the black cat\na dog\nthe red dog\n")

    pd = _demo_module("seqToseq", "prepare_data")
    out = tmp_path / "wmt-out"
    nt, ns, ds, dt = pd.convert(str(src), str(trg), str(out),
                                test_src=str(src), test_trg=str(trg),
                                lines_per_shard=2)
    assert nt == 2 and ns == 2  # 3 lines at 2/shard

    from paddle_tpu.data import datasets

    src_dict = datasets.load_dict(str(out / "src.dict"))
    trg_dict = datasets.load_dict(str(out / "trg.dict"))
    # reserved ids head both dicts (reference sbeos convention)
    assert src_dict["<s>"] == 0 and src_dict["<e>"] == 1 and src_dict["<unk>"] == 2
    assert trg_dict["the"] >= 3

    dp = _demo_module("seqToseq", "dataprovider")
    settings = dp.process.init(src_dict=str(out / "src.dict"),
                               trg_dict=str(out / "trg.dict"))
    shard0 = (out / "train.list").read_text().splitlines()[0]
    samples = list(dp.process.generator_fn(settings, shard0))
    assert len(samples) == 2
    s = samples[0]
    # teacher forcing: decoder input starts with <s>, label ends with <e>
    assert s["target_language_word"][0] == 0
    assert s["target_language_next_word"][-1] == 1
    assert s["target_language_word"][1:] == s["target_language_next_word"][:-1]
    assert all(i >= 3 for i in s["source_language_word"])  # all in-vocab here
    # unknown words map to <unk>=2
    settings2 = dp.gen_process.init(src_dict=str(out / "src.dict"),
                                    trg_dict=str(out / "trg.dict"))
    unk_file = tmp_path / "unk"
    unk_file.write_text("zebra chat\tzebra cat\n")
    gen = list(dp.gen_process.generator_fn(settings2, str(unk_file)))
    assert gen[0]["source_language_word"][0] == 2


def test_converted_corpus_trains_quick_start(tmp_path):
    """End-to-end: converted real-format corpus -> provider -> a few
    batches of actual training through the quick_start emb config."""
    reviews = tmp_path / "reviews.json"
    rng = np.random.RandomState(0)
    rows = []
    for i in range(120):
        pos = bool(i % 2)
        words = (["great", "love", "excellent"] if pos else ["bad", "hate", "awful"])
        filler = [f"w{int(x)}" for x in rng.randint(0, 30, 5)]
        rows.append({"reviewText": " ".join(words + filler),
                     "overall": 5.0 if pos else 1.0})
    reviews.write_text("\n".join(json.dumps(r) for r in rows))

    pd = _demo_module("quick_start", "prepare_data")
    out = tmp_path / "corpus"
    pd.convert(str(reviews), str(out), test_ratio=0.2)

    import shutil

    ws = tmp_path / "ws"
    shutil.copytree(os.path.join(REPO, "demo", "quick_start"), ws)
    (ws / "train.list").write_text((out / "train.list").read_text())
    (ws / "test.list").write_text((out / "test.list").read_text())

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(ws)
    try:
        cfg = parse_config(str(ws / "trainer_config.emb.py"),
                           f"dict={out / 'dict.txt'}")
        flags = _Flags(config="trainer_config.emb.py", save_dir=str(ws / "model"),
                       num_passes=15, log_period=0, use_tpu=False,
                       config_args=f"dict={out / 'dict.txt'}")
        trainer = Trainer(cfg, flags)
        trainer.train()
        metrics = trainer.test()
    finally:
        os.chdir(cwd)
    assert metrics["cost"] < 0.65, metrics  # learns above chance (ln2=0.693)


def test_srl_conll_converter(tmp_path):
    """prepare_data.py for SRL: raw CoNLL-05-style words+props files ->
    feature lines + dicts (extract_pairs + extract_dict_feature roles),
    consumed end-to-end by the demo provider."""
    words = tmp_path / "train.words"
    props = tmp_path / "train.props"
    # two sentences; first has TWO predicates (two feature lines)
    words.write_text(
        "The\ncat\nsat\ndown\n\nDogs\nbark\n\n"
    )
    props.write_text(
        "-    (A0*  *\n"
        "-    *)    (A0*)\n"
        "sit  (V*)  *\n"
        "down *     (V*)\n"
        "\n"
        "-    (A0*)\n"
        "bark (V*)\n"
        "\n"
    )
    pd = _demo_module("semantic_role_labeling", "prepare_data")
    out = tmp_path / "srl-out"
    n_train, n_test, ds, dt = pd.convert(str(words), str(props), str(out))
    assert n_train == 3 and n_test == 0  # 2 + 1 predicates

    from paddle_tpu.data import datasets

    src = datasets.load_dict(str(out / "src.dict"))
    tgt = datasets.load_dict(str(out / "tgt.dict"))
    assert src["<unk>"] == 0 and "cat" in src
    assert {"B-V", "B-A0", "O"} <= set(tgt)

    lines = (out / "train.txt").read_text().strip().splitlines()
    first = lines[0].split("\t")
    assert first[0] == "the cat sat down"
    assert first[1] == "sat"                      # B-V position
    # reference extract_dict_feature quirk: a second-to-last predicate
    # gets no +1 mark and ctx_p1='eos'
    assert first[5].split() == ["0", "1", "1", "0"]
    assert first[4] == "eos"
    assert first[6].split() == ["B-A0", "I-A0", "B-V", "O"]

    dp = _demo_module("semantic_role_labeling", "dataprovider")
    settings = dp.process.init(src_dict=str(out / "src.dict"),
                               tgt_dict=str(out / "tgt.dict"))
    samples = list(dp.process.generator_fn(settings, str(out / "train.txt")))
    assert len(samples) == 3
    ws, verb, n1, c0, p1, mark, labels = samples[0]
    assert len(ws) == len(labels) == 4
    assert verb == [src["sat"]] * 4
    assert mark == [0, 1, 1, 0]  # reference boundary quirk (see converter)
    assert labels[2] == tgt["B-V"]
