"""Binary shard format (ProtoDataProvider role): round-trip + training."""

import numpy as np

from paddle_tpu.data.binary import read_shard, shard_input_types, write_shard
from paddle_tpu.data.provider import (
    dense_vector,
    integer_value,
    integer_value_sequence,
    sparse_binary_vector,
    sparse_value_slot,
)


def test_shard_round_trip(tmp_path):
    types = [
        integer_value_sequence(50),
        dense_vector(4),
        sparse_binary_vector(30),
        sparse_value_slot(20),
        integer_value(3),
    ]
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(23):
        samples.append([
            [int(x) for x in rng.randint(0, 50, rng.randint(1, 9))],
            rng.rand(4).tolist(),
            sorted(int(i) for i in rng.choice(30, 5, replace=False)),
            [(int(i), float(rng.rand())) for i in sorted(rng.choice(20, 3, replace=False))],
            int(rng.randint(0, 3)),
        ])
    path = str(tmp_path / "shard.npz")
    write_shard(path, samples, types)

    got_types = shard_input_types(path)
    assert [(t.dim, t.seq_type, t.type) for t in got_types] == [
        (t.dim, t.seq_type, t.type) for t in types
    ]
    got = list(read_shard(path))
    assert len(got) == len(samples)
    for orig, back in zip(samples, got):
        assert list(back[0]) == orig[0]
        np.testing.assert_allclose(back[1], orig[1], rtol=1e-6)
        assert list(back[2]) == orig[2]
        assert [i for i, _ in back[3]] == [i for i, _ in orig[3]]
        np.testing.assert_allclose([v for _, v in back[3]], [v for _, v in orig[3]], rtol=1e-6)
        assert back[4] == orig[4]


def test_train_from_binary_shards(tmp_path):
    """A config using define_bin_data_sources trains end-to-end."""
    import os

    types = [dense_vector(8), integer_value(2)]
    rng = np.random.RandomState(1)
    for shard_id in range(2):
        samples = []
        for _ in range(200):
            x = rng.rand(8).astype(np.float32)
            samples.append([x.tolist(), int(x[0] > 0.5)])
        write_shard(str(tmp_path / f"shard{shard_id}.npz"), samples, types)
    (tmp_path / "train.list").write_text(
        "\n".join(str(tmp_path / f"shard{i}.npz") for i in range(2)) + "\n"
    )
    (tmp_path / "conf.py").write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_bin_data_sources('train.list')\n"
        "settings(batch_size=32, learning_rate=0.5)\n"
        "d = data_layer('x', size=8)\n"
        "out = fc_layer(input=d, size=2, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=out, label=data_layer('label', size=2)))\n"
    )

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("conf.py")
        assert cfg.data_config.type == "bin"
        flags = _Flags(config="conf.py", num_passes=8, log_period=100, use_tpu=False)
        trainer = Trainer(cfg, flags)
        trainer.train()
        # the planted rule (label = x[0] > 0.5) is linearly separable
        provider = trainer._provider(for_test=False)
        errs, total = 0.0, 0
        for batch in provider.batches():
            out = trainer.test_fwd(trainer.params, batch)
            cost = float(trainer.gm.total_cost(out))
            errs += cost * batch["label"].ids.shape[0]
            total += batch["label"].ids.shape[0]
        assert errs / total < 0.4, errs / total
    finally:
        os.chdir(cwd)


def test_shard_round_trip_nested_sequences(tmp_path):
    """Sub-sequence slots (reference ProtoDataProvider subseq handling,
    ProtoDataProvider.h:49): two offset levels round-trip exactly,
    including empty subsequences and feeding through the feeder."""
    from paddle_tpu.data.provider import integer_value_sub_sequence

    types = [integer_value_sub_sequence(40), integer_value(2)]
    rng = np.random.RandomState(4)
    samples = []
    for j in range(11):
        n_sub = rng.randint(1, 5)
        subseqs = [
            [int(x) for x in rng.randint(0, 40, rng.randint(1, 6))]
            for _ in range(n_sub)
        ]
        if j % 3 == 0:  # genuinely empty inner sequences round-trip too
            subseqs.append([])
        samples.append([subseqs, int(rng.randint(0, 2))])
    path = str(tmp_path / "nested.pdz")
    write_shard(path, samples, types)
    got = list(read_shard(path))
    assert len(got) == len(samples)
    for orig, back in zip(samples, got):
        assert [list(s) for s in back[0]] == orig[0]
        assert back[1] == orig[1]

    # the shard drives the feeder into a padded nested Argument
    from paddle_tpu.data.feeder import BatchAssembler

    args = BatchAssembler(types, ["words", "label"]).assemble([got[0], got[1]])
    a = args["words"]
    assert a.is_nested_seq
    assert int(a.seq_lengths[0]) == len(samples[0][0])
