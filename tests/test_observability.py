"""Unified run telemetry (doc/observability.md): metrics.jsonl schema +
writer semantics, trace-event spans, hot-path instrumentation through a
real smoke train run, the `paddle metrics` analyzer, plotcurve's
metrics-first path, the supervisor's metrics-tail crash report, and
bench.py's shared-schema record."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import spans as obs_spans
from paddle_tpu.observability.analyze import analyze, load_run
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")
SUBPROC_ENV = {
    **os.environ,
    "PYTHONPATH": f"{REPO}:{REPO}/compat:{PROVIDER_DIR}",
    "JAX_PLATFORMS": "cpu",
}


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    yield
    sys.path.remove(PROVIDER_DIR)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Telemetry state is process-global: isolate each test."""
    obs.registry().reset()
    yield
    obs.configure("")
    obs_spans.configure("")
    FLAGS.metrics_path = ""
    FLAGS.trace_events_path = ""


def _lr_config(tmp_path):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r},
                            test_list={str(test_list)!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02, learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "lr_config.py"
    cfg_path.write_text(src)
    return str(cfg_path)


def _fresh_flags(tmp_path, name="out"):
    FLAGS.save_dir = str(tmp_path / name)
    FLAGS.num_passes = 2
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.seed = 7
    FLAGS.metrics_path = ""
    FLAGS.trace_events_path = ""
    return FLAGS.save_dir


# ----------------------------------------------------- writer + registry


def test_registry_counter_gauge_histogram_snapshot():
    r = obs.MetricsRegistry()
    r.counter("c").inc()
    r.counter("c").inc(2.5)
    r.gauge("g").set(4.0)
    h = r.histogram("h")
    h.observe(1.0)
    snap = r.snapshot()
    assert snap["c"] == pytest.approx(3.5)
    assert snap["g"] == 4.0
    assert snap["h"]["count"] == 1
    with pytest.raises(AssertionError):
        r.gauge("c")  # name reuse across kinds is a bug, not a silent cast


def test_writer_schema_buffering_and_torn_tail(tmp_path):
    w = obs.MetricsWriter(str(tmp_path), host=0, buffer_limit=100)
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    # run_start is a flush kind: already on disk
    assert os.path.exists(path)
    n0 = len(open(path).read().splitlines())
    w.emit("train_window", pass_id=0, step=10, AvgCost=0.5)
    # buffered: nothing new on disk until a boundary kind or the limit
    assert len(open(path).read().splitlines()) == n0
    w.emit("pass_end", pass_id=0, step=20, samples=128, AvgCost=0.4,
           loss=float("nan"))
    records = [json.loads(l) for l in open(path).read().splitlines()]
    assert [r["kind"] for r in records] == ["run_start", "train_window", "pass_end"]
    for rec in records:
        assert obs.validate_record(rec) == [], rec
    # non-finite floats serialize as strings, keeping strict JSON
    assert records[-1]["loss"] == "nan"
    # t is a wall-time OFFSET: monotone nondecreasing
    ts = [r["t"] for r in records]
    assert ts == sorted(ts)
    # torn tail (crash mid-write) must not break readers
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "pass_end", "hos')
    got = list(obs.read_records(path))
    assert len(got) == 3
    # validate_record flags garbage
    assert obs.validate_record({"kind": 3}) != []


def test_writer_host_naming_and_reconfigure(tmp_path):
    w0 = obs.configure(str(tmp_path), host=0)
    assert os.path.basename(w0.path) == "metrics.jsonl"
    # same path reconfigure reuses the writer (no duplicate run_start)
    assert obs.configure(str(tmp_path), host=0) is w0
    w1 = obs.MetricsWriter(str(tmp_path), host=2)
    assert os.path.basename(w1.path) == "metrics.host2.jsonl"
    w1.flush()
    assert sorted(os.path.basename(p) for p in obs.metrics_files(str(tmp_path))) == [
        "metrics.host2.jsonl", "metrics.jsonl",
    ]


# ------------------------------------------------------- smoke train run


def _train_smoke(tmp_path, **flag_overrides):
    cfg = parse_config(_lr_config(tmp_path))
    run_dir = _fresh_flags(tmp_path)
    for k, v in flag_overrides.items():
        setattr(FLAGS, k, v)
    trainer = Trainer(cfg)
    trainer.train(num_passes=2)
    return trainer, run_dir


def test_smoke_train_emits_valid_metrics_stream(tmp_path):
    trainer, run_dir = _train_smoke(tmp_path)
    path = os.path.join(run_dir, "metrics.jsonl")
    assert os.path.exists(path), os.listdir(run_dir)
    records = list(obs.read_records(path))
    for rec in records:
        assert obs.validate_record(rec) == [], rec
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "run_start"
    assert kinds[-1] == "run_end" and records[-1]["status"] == "completed"
    pass_ends = [r for r in records if r["kind"] == "pass_end"]
    assert [r["pass"] for r in pass_ends] == [0, 1]
    for pe in pass_ends:
        # the shared summary dict + step-time quantiles + counters
        for key in ("samples", "AvgCost", "CurrentCost", "samples_per_sec",
                    "pass_time_s", "step_time_p50_s", "step_time_p99_s",
                    "launches_single", "counters", "step"):
            assert key in pe, (key, sorted(pe))
        assert pe["step_time_p99_s"] >= pe["step_time_p50_s"] > 0
        assert pe["samples"] > 0
    # checkpoint telemetry: one save per pass, with duration and bytes
    saves = [r for r in records if r["kind"] == "checkpoint" and r["op"] == "save"]
    assert [s["pass"] for s in saves] == [0, 1]
    assert all(s["bytes"] > 0 and s["duration_s"] > 0 for s in saves)
    # test records ride along (test at pass end, with a test list set)
    assert any(r["kind"] == "test" and "cost" in r for r in records)
    # the quality curve in telemetry matches the in-process history
    hist = {p: res["cost"] for p, res in trainer.test_history}
    tests = {r["pass"]: r["cost"] for r in records if r["kind"] == "test"
             if "pass" in r}
    for p, c in hist.items():
        assert tests[p] == pytest.approx(c)


def test_pass_end_record_matches_logged_line(tmp_path, caplog):
    """Satellite: the 'Pass N done' log text and the pass_end record
    render from ONE shared dict — same keys, same values."""
    import logging
    import re

    # the paddle_tpu logger doesn't propagate (own stderr handler) —
    # attach caplog's handler directly
    from paddle_tpu.utils.logging import logger as ptu_logger

    ptu_logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.INFO, logger="paddle_tpu"):
            _, run_dir = _train_smoke(tmp_path)
    finally:
        ptu_logger.removeHandler(caplog.handler)
    logged = {}
    for m in re.finditer(r"Pass (\d+) done: (.*)", caplog.text):
        kv = dict(re.findall(r"([A-Za-z_][\w.]*)=([-+0-9.eE]+)", m.group(2)))
        logged[int(m.group(1))] = kv
    records = list(obs.read_records(os.path.join(run_dir, "metrics.jsonl")))
    for rec in records:
        if rec["kind"] != "pass_end":
            continue
        kv = logged[rec["pass"]]
        assert int(kv["samples"]) == rec["samples"]
        assert float(kv["AvgCost"]) == pytest.approx(rec["AvgCost"], rel=1e-5)
        assert float(kv["CurrentCost"]) == pytest.approx(
            rec["CurrentCost"], rel=1e-5
        )


def test_trace_events_export_loads_and_nests(tmp_path):
    _, run_dir = _train_smoke(
        tmp_path, trace_events_path=str(tmp_path / "trace.json")
    )
    doc = json.load(open(tmp_path / "trace.json"))  # valid JSON by parse
    events = doc["traceEvents"]
    by_name = {}
    for ev in events:
        assert ev["ph"] in ("X", "i")
        by_name.setdefault(ev["name"], []).append(ev)
    # trainer / data / checkpoint spans all present
    assert "trainer/pass" in by_name
    assert "train_step" in by_name
    assert "checkpoint/save" in by_name
    # nesting: every train_step lies inside some trainer/pass span
    passes = [(e["ts"], e["ts"] + e["dur"]) for e in by_name["trainer/pass"]]
    for step in by_name["train_step"]:
        s0, s1 = step["ts"], step["ts"] + step["dur"]
        assert any(p0 <= s0 and s1 <= p1 + 1 for p0, p1 in passes), (
            (s0, s1), passes
        )


def test_nonfinite_events_recorded(tmp_path):
    from paddle_tpu.resilience import faultinject

    cfg = parse_config(_lr_config(tmp_path))
    run_dir = _fresh_flags(tmp_path)
    FLAGS.nonfinite_policy = "skip"
    faultinject.configure("trainer.nonfinite=raise@2")
    try:
        Trainer(cfg).train(num_passes=1)
    finally:
        faultinject.configure("")
        FLAGS.nonfinite_policy = "abort"
    records = list(obs.read_records(os.path.join(run_dir, "metrics.jsonl")))
    nf = [r for r in records if r["kind"] == "nonfinite"]
    assert len(nf) == 1 and nf[0]["policy"] == "skip"
    assert nf[0]["value"] == "nan"
    faults = [r for r in records if r["kind"] == "fault"]
    assert faults and faults[0]["site"] == "trainer.nonfinite"
    pe = [r for r in records if r["kind"] == "pass_end"][-1]
    assert pe["counters"]["nonfinite.events"] == 1
    assert pe["counters"]["faults.fired"] >= 1


# --------------------------------------------------------------- analyzer


def test_analyzer_aggregates_run(tmp_path):
    _, run_dir = _train_smoke(tmp_path)
    doc = analyze(load_run(run_dir))
    assert doc["hosts"] == [0]
    assert [p["pass"] for p in doc["passes"]] == [0, 1]
    row = doc["passes"][0]
    assert row["samples"] > 0 and "AvgCost" in row
    assert "data_wait_share" in row and 0.0 <= row["data_wait_share"] <= 1.0
    assert {c["op"] for c in doc["checkpoints"]} == {"save"}
    assert doc["run_ended"] is True
    assert doc["invalid_records"] == 0


def test_analyzer_flags_missing_run_end_and_straggler(tmp_path):
    # hand-written two-host streams: host 1 is the straggler, no run_end
    w0 = obs.MetricsWriter(str(tmp_path), host=0)
    w1 = obs.MetricsWriter(str(tmp_path), host=1)
    for host, w, mean in ((0, w0, 0.01), (1, w1, 0.05)):
        w.emit("pass_end", pass_id=0, step=10, samples=64, AvgCost=0.5,
               pass_time_s=1.0, step_time_mean_s=mean,
               step_time_p50_s=mean, step_time_p99_s=mean * 2)
        w.flush()
    doc = analyze(load_run(str(tmp_path)))
    assert doc["hosts"] == [0, 1]
    assert doc["passes"][0]["hosts"] == 2
    assert doc["straggler"] and "slowest=host1" in doc["straggler"]["line"]
    assert any("run_end" in w for w in doc["warnings"])


def test_analyzer_dedupes_rerun_passes_latest_wins(tmp_path):
    """A supervised restart (or rollback) re-runs a pass and appends a
    SECOND pass_end for the same (host, pass) to the same stream — the
    analyzer must keep the latest, not double-count samples or inflate
    the hosts divisor."""
    w = obs.MetricsWriter(str(tmp_path), host=0)
    w.emit("pass_end", pass_id=0, step=10, samples=64, AvgCost=0.9,
           pass_time_s=1.0)
    # crash + restart: the re-run pass lands with different numbers
    w.emit("pass_end", pass_id=0, step=10, samples=64, AvgCost=0.7,
           pass_time_s=2.0)
    w.emit("pass_end", pass_id=1, step=20, samples=64, AvgCost=0.5,
           pass_time_s=1.0)
    w.flush()
    doc = analyze(load_run(str(tmp_path)))
    assert [p["pass"] for p in doc["passes"]] == [0, 1]
    row = doc["passes"][0]
    assert row["hosts"] == 1           # one host, despite two records
    assert row["samples"] == 64        # not doubled
    assert row["AvgCost"] == 0.7       # latest wins
    assert row["pass_time_s"] == 2.0


def test_paddle_metrics_cli_table_and_json(tmp_path):
    _, run_dir = _train_smoke(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "metrics", run_dir],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "AvgCost" in r.stdout and "p99 ms" in r.stdout
    assert "checkpoint" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "metrics", run_dir, "--json"],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    assert r2.returncode == 0, r2.stderr
    doc = json.loads(r2.stdout)
    assert [p["pass"] for p in doc["passes"]] == [0, 1]
    # an empty dir is a clean, jax-free error
    r3 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "metrics", str(tmp_path)],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    assert r3.returncode == 1
    assert "no metrics" in r3.stderr


# -------------------------------------------------------------- plotcurve


def test_plotcurve_prefers_metrics_for_run_dirs(tmp_path, capsys):
    from paddle_tpu.utils import plotcurve

    _, run_dir = _train_smoke(tmp_path)
    series = plotcurve.parse_metrics(run_dir)
    assert len(series["AvgCost"]) == 2
    assert series["AvgCost"][1] < series["AvgCost"][0]  # it learned
    # main() routes a run dir through the metrics path
    assert plotcurve.main([ "-i", run_dir, "AvgCost"]) == 0
    out = capsys.readouterr().out
    assert "AvgCost" in out and "*" in out
    # legacy path intact: log text still parses (old runs keep plotting)
    log = tmp_path / "train.log"
    log.write_text("Pass 0 done: samples=10 AvgCost=0.9 CurrentCost=0.9\n")
    assert plotcurve.main(["-i", str(log), "AvgCost"]) == 0


def test_plotcurve_metrics_series_stay_pass_aligned(tmp_path):
    """A field present in only SOME pass_end records (mfu when FLOP
    accounting failed) must leave a NaN gap at its pass, not shift later
    points left onto the wrong pass."""
    from paddle_tpu.utils import plotcurve

    w = obs.MetricsWriter(str(tmp_path), host=0)
    w.emit("pass_end", pass_id=0, step=5, samples=64, AvgCost=0.9)
    w.emit("pass_end", pass_id=1, step=10, samples=64, AvgCost=0.5, mfu=0.3)
    w.flush()
    series = plotcurve.parse_metrics(str(tmp_path))
    assert series["AvgCost"] == [0.9, 0.5]
    assert len(series["mfu"]) == 2
    assert series["mfu"][0] != series["mfu"][0]  # NaN gap at pass 0
    assert series["mfu"][1] == 0.3
    # the ascii plot skips the gap instead of crashing on NaN min/max
    art = plotcurve.ascii_plot(series["mfu"])
    assert "*" in art


# ------------------------------------------------------------- supervisor


def test_crash_report_carries_metrics_tail(tmp_path):
    from paddle_tpu.resilience.supervisor import Supervisor

    run_dir = str(tmp_path / "out")
    w = obs.MetricsWriter(run_dir, host=0)
    w.emit("pass_end", pass_id=0, step=10, samples=64, AvgCost=0.5)
    w.emit("barrier_skew", pass_id=0, mean_s=[0.01, 0.05], skew_s=0.04,
           slowest_host=1, line="BarrierStat: ... slowest=host1")
    w.flush()

    class Flags:
        save_dir = run_dir
        supervise_dir = str(tmp_path / "sup")
        restart_budget = 1
        crash_loop_threshold = 2
        restart_base_delay = 0.0
        metrics_path = ""
        dry_run = False

    sup = Supervisor(["--config=c.py"], Flags(), child_cmd=["true"])
    os.makedirs(sup.dir, exist_ok=True)
    log = tmp_path / "sup" / "attempt-000.log"
    log.write_text("some child output\n")
    sup._crash_report("crash_loop", str(log), "test detail")
    report = json.load(open(tmp_path / "sup" / "crash_report.json"))
    tail = report["metrics_tail"]["0"]
    assert [r["kind"] for r in tail] == ["run_start", "pass_end", "barrier_skew"]
    # straggler attribution now comes from the STRUCTURED record
    assert report["step_time_skew"]["kind"] == "barrier_skew"
    assert report["step_time_skew"]["slowest_host"] == 1


def test_crash_report_falls_back_to_log_grep_without_metrics(tmp_path):
    from paddle_tpu.resilience.supervisor import Supervisor

    class Flags:
        save_dir = ""
        supervise_dir = str(tmp_path / "sup")
        restart_budget = 1
        crash_loop_threshold = 2
        restart_base_delay = 0.0
        metrics_path = ""
        dry_run = False

    sup = Supervisor([], Flags(), child_cmd=["true"])
    os.makedirs(sup.dir, exist_ok=True)
    log = tmp_path / "sup" / "attempt-000.log"
    log.write_text("noise\nBarrierStat: step mean/host=[...] slowest=host0\n")
    sup._crash_report("crash_loop", str(log), "d")
    report = json.load(open(tmp_path / "sup" / "crash_report.json"))
    assert report["metrics_tail"] == {}
    assert "BarrierStat" in report["step_time_skew"]


# ------------------------------------------------------------------ bench


def test_bench_emit_mirrors_metrics_schema(tmp_path, monkeypatch, capsys):
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    monkeypatch.setenv("PADDLE_TPU_BENCH_METRICS_DIR", str(tmp_path / "bm"))
    bench._emit("resnet50_train_imgs_per_sec_per_chip", 123.4, "imgs/s", 1.0,
                backend="cpu")
    capsys.readouterr()  # swallow the stdout JSON line
    recs = list(obs.read_records(str(tmp_path / "bm" / "metrics.jsonl")))
    bench_recs = [r for r in recs if r["kind"] == "bench"]
    assert len(bench_recs) == 1
    rec = bench_recs[0]
    assert obs.validate_record(rec) == []
    assert rec["metric"] == "resnet50_train_imgs_per_sec_per_chip"
    assert rec["value"] == 123.4 and rec["unit"] == "imgs/s"
