"""Space-to-depth stem rewrite (conv_s2d): the 7x7/stride-2/pad-3
few-channel conv re-expressed as a 4x4/stride-1 VALID conv over a 2x2
space-to-depth view must be ARITHMETICALLY identical (summation order
aside) to the direct convolution — values and gradients.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu.graph  # noqa: F401  (break the layers<->graph import cycle)
from paddle_tpu.layers.vision import _conv2d, _stem_s2d_conv


def _pair(key, B=2, H=16, C=3, O=8):
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (B, H, H, C))
    w = jax.random.normal(kw, (7, 7, C, O)) * 0.1
    return x, w


def _direct(x, w):
    return _conv2d(x, w, (2, 2), [(3, 3), (3, 3)], 1)


def test_value_parity():
    for seed, H in ((0, 16), (1, 32), (2, 224)):
        x, w = _pair(jax.random.PRNGKey(seed), B=1 if H == 224 else 2, H=H)
        ref = _direct(x, w)
        got = _stem_s2d_conv(x, w)
        assert got.shape == ref.shape == (x.shape[0], H // 2, H // 2, 8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_value_parity_other_channel_counts():
    # the gate allows C <= 4 (e.g. grayscale or RGBA stems)
    for C in (1, 2, 4):
        kx, kw = jax.random.split(jax.random.PRNGKey(10 + C))
        x = jax.random.normal(kx, (2, 16, 16, C))
        w = jax.random.normal(kw, (7, 7, C, 8)) * 0.1
        np.testing.assert_allclose(
            np.asarray(_stem_s2d_conv(x, w)), np.asarray(_direct(x, w)),
            rtol=1e-5, atol=1e-5, err_msg=f"C={C}",
        )


def test_gradient_parity():
    x, w = _pair(jax.random.PRNGKey(3))
    cot = jax.random.normal(jax.random.PRNGKey(4), (2, 8, 8, 8))
    gr = jax.grad(lambda x, w: jnp.sum(_direct(x, w) * cot), (0, 1))(x, w)
    gs = jax.grad(lambda x, w: jnp.sum(_stem_s2d_conv(x, w) * cot), (0, 1))(x, w)
    for r, s, name in zip(gr, gs, ("dx", "dw")):
        np.testing.assert_allclose(np.asarray(s), np.asarray(r),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_machine_level_parity(tmp_path):
    # a DSL conv layer with the stem shape: conv_s2d on vs off — same
    # forward output through the whole layer (bias + activation included)
    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine, make_dense

    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=4, learning_rate=1e-3)
    img = data_layer(name="input", size=16 * 16 * 3)
    conv = img_conv_layer(name="stem", input=img, filter_size=7,
                          num_filters=8, num_channels=3, stride=2,
                          padding=3, act=ReluActivation())
    outputs(conv)
    """)
    p = tmp_path / "stem.py"
    p.write_text(src)
    tc = parse_config(str(p))
    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, conv_s2d=True)
    params = gm_off.init_params(seed=5)
    rng = np.random.RandomState(0)
    batch = {"input": make_dense(rng.randn(4, 3 * 16 * 16).astype(np.float32))}
    out_off, _ = gm_off.forward(params, batch, "test")
    out_on, _ = gm_on.forward(params, batch, "test")
    np.testing.assert_allclose(
        np.asarray(out_on["stem"].value), np.asarray(out_off["stem"].value),
        rtol=1e-5, atol=1e-5,
    )


def test_non_stem_shapes_unchanged(tmp_path):
    # a 3x3/s1 conv must NOT take the rewrite even with the knob on
    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine, make_dense

    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=2, learning_rate=1e-3)
    img = data_layer(name="input", size=8 * 8 * 3)
    conv = img_conv_layer(name="c3", input=img, filter_size=3,
                          num_filters=4, num_channels=3, stride=1,
                          padding=1, act=LinearActivation())
    outputs(conv)
    """)
    p = tmp_path / "c3.py"
    p.write_text(src)
    tc = parse_config(str(p))
    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, conv_s2d=True)
    params = gm_off.init_params(seed=6)
    rng = np.random.RandomState(1)
    batch = {"input": make_dense(rng.randn(2, 3 * 8 * 8).astype(np.float32))}
    out_off, _ = gm_off.forward(params, batch, "test")
    out_on, _ = gm_on.forward(params, batch, "test")
    np.testing.assert_array_equal(
        np.asarray(out_on["c3"].value), np.asarray(out_off["c3"].value)
    )
