"""Row-sparse parameter update semantics.

Mirrors the reference's sparse-row training contracts
(SparseRowCpuMatrix::sgdUpdate, OptimizerWithRegularizerSparse lazy
catch-up): untouched embedding rows must not move or advance optimizer
state; missed regularization is applied when a row is next touched.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.optimizer import Updater
from paddle_tpu.proto import ModelConfig, OptimizationConfig, ParameterConfig


def _mk(method="momentum", decay=0.0, momentum=0.0, V=6, D=3, sparse=True):
    m = ModelConfig()
    m.parameters.append(
        ParameterConfig(name="emb", size=V * D, dims=[V, D], momentum=momentum,
                        decay_rate=decay, sparse_update=sparse)
    )
    opt = OptimizationConfig(learning_rate=0.1, learning_method=method,
                             learning_rate_schedule="constant", batch_size=2)
    return Updater(opt, m)


def _grad_rows(V, D, rows, val=1.0):
    g = np.zeros((V, D), np.float32)
    for r in rows:
        g[r] = val
    return jnp.asarray(g)


def test_untouched_rows_frozen():
    V, D = 6, 3
    upd = _mk(method="adagrad", V=V, D=D)
    w0 = jnp.asarray(np.random.RandomState(0).randn(V, D).astype(np.float32))
    params = {"emb": w0}
    state = upd.init_state(params)
    params, state = upd(params, {"emb": _grad_rows(V, D, [1, 3])}, state, 2.0)
    w1 = np.asarray(params["emb"])
    np.testing.assert_array_equal(w1[[0, 2, 4, 5]], np.asarray(w0)[[0, 2, 4, 5]])
    assert not np.allclose(w1[1], np.asarray(w0)[1])
    accum = np.asarray(state.slots["emb"]["accum"])
    assert (accum[[0, 2, 4, 5]] == 0).all() and (accum[[1, 3]] > 0).all()
    t_last = np.asarray(state.slots["emb"]["t_last"])
    np.testing.assert_array_equal(t_last, [0, 1, 0, 1, 0, 0])


def test_lazy_l2_catchup():
    """A row idle for k steps gets its missed decay compounded on touch."""
    V, D, lr, decay = 4, 2, 0.1, 0.5
    upd = _mk(method="sgd", decay=decay, V=V, D=D)
    w0 = np.full((V, D), 2.0, np.float32)
    params = {"emb": jnp.asarray(w0)}
    state = upd.init_state(params)
    # steps 1,2: touch row 0 only; step 3: touch row 1 (idle 2 steps)
    for _ in range(2):
        params, state = upd(params, {"emb": _grad_rows(V, D, [0], 0.1)}, state, 2.0)
    params, state = upd(params, {"emb": _grad_rows(V, D, [1], 0.1)}, state, 2.0)
    w = np.asarray(params["emb"])
    # row 1: catch-up decay (1-lr*decay)^2, then one normal decayed-sgd step
    base = 2.0 * (1 - lr * decay) ** 2
    want = base - lr * (0.1 + decay * base)
    np.testing.assert_allclose(w[1], want, rtol=1e-5)
    # rows 2,3 never touched: bitwise frozen
    np.testing.assert_array_equal(w[2:], w0[2:])


def test_dense_param_unaffected():
    """sparse_update=False params follow the dense path every step."""
    V, D = 4, 2
    upd = _mk(method="sgd", decay=0.5, V=V, D=D, sparse=False)
    w0 = np.full((V, D), 2.0, np.float32)
    params = {"emb": jnp.asarray(w0)}
    state = upd.init_state(params)
    params, _ = upd(params, {"emb": _grad_rows(V, D, [0], 0.0)}, state, 2.0)
    w = np.asarray(params["emb"])
    # zero grad but L2 decay still applies to every row on the dense path
    np.testing.assert_allclose(w, 2.0 - 0.1 * 0.5 * 2.0, rtol=1e-6)


def test_sharded_sparse_update_runs():
    """Sparse-update embedding sharded over the mesh: one SPMD step."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.spmd import _opt_state_sharding, _param_shardings

    V, D = 8, 4
    m = ModelConfig()
    m.parameters.append(
        ParameterConfig(name="emb", size=V * D, dims=[V, D],
                        sparse_update=True, sharding=["model", None])
    )
    opt = OptimizationConfig(learning_rate=0.1, learning_method="adagrad",
                             learning_rate_schedule="constant", batch_size=2)
    upd = Updater(opt, m)
    mesh = make_mesh("data=4,model=2")

    class GM:  # minimal shim for _param_shardings
        param_configs = {p.name: p for p in m.parameters}

    params = {"emb": jnp.ones((V, D), jnp.float32)}
    state = upd.init_state(params)
    shards = _param_shardings(mesh, GM)
    o_spec = _opt_state_sharding(mesh, shards, state)
    # placing the state must succeed (t_last is rank-1 on a rank-2 spec)
    state = jax.device_put(state, o_spec)
    params = jax.device_put(params, {"emb": shards["emb"]})
    g = _grad_rows(V, D, [1, 5])
    params, state = jax.jit(upd)(params, {"emb": g}, state, 2.0)
    w = np.asarray(params["emb"])
    assert not np.allclose(w[1], 1.0) and np.allclose(w[0], 1.0)


def test_remat_full_with_sparse_prefetch_matches_plain():
    """remat='full' on the SPARSE-prefetch grad path (jax.checkpoint
    around loss2): RowSparseGrad reassembly and dense grads must match
    the stored-activation path exactly."""
    import jax

    from paddle_tpu.flagship import example_batch
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.optimizer.sparse import RowSparseGrad

    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        ParamAttr,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        outputs,
        pooling_layer,
        settings,
    )

    with fresh_context() as ctx:
        settings(batch_size=4, learning_rate=0.1)
        words = data_layer(name="words", size=100)
        emb = embedding_layer(
            input=words, size=8,
            param_attr=ParamAttr(name="emb", sparse_update=True),
        )
        pool = pooling_layer(input=emb)
        out = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="out")
        label = data_layer(name="label", size=2)
        outputs(classification_cost(input=out, label=label))
        tc = ctx.finalize()
    gm = GradientMachine(tc.model_config)
    assert gm.sparse_prefetch_plan(), "fixture must exercise the sparse path"
    params = gm.init_params(seed=2)
    batch = example_batch(dict_dim=100, B=4, T=8)
    rng = jax.random.PRNGKey(1)
    la, ga, _, _ = jax.jit(gm.grad_fn(remat="none"))(params, batch, rng)
    lb, gb, _, _ = jax.jit(gm.grad_fn(remat="full"))(params, batch, rng)
    np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
    for k in ga:
        a, b = ga[k], gb[k]
        if isinstance(a, RowSparseGrad):
            np.testing.assert_array_equal(np.asarray(a.ids), np.asarray(b.ids))
            np.testing.assert_allclose(
                np.asarray(a.rows), np.asarray(b.rows), rtol=1e-6, atol=1e-7
            )
        else:
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7, err_msg=k
            )
