"""Length-sorted bucketing (@provider(sort_by_length=True)).

The training feeder length-sorts each shuffle pool before slicing
batches so a batch's padded length is set by similar-length neighbors —
SURVEY hard-part #4's static-shape answer to the reference's no-padding
SequenceToBatch packing. Batch ORDER stays shuffled; every sample is
still delivered exactly once; test/generation order never changes.
"""

import numpy as np

from paddle_tpu.data.feeder import DataProvider, bucket_length
from paddle_tpu.data.provider import integer_value, provider


def _mk_provider(sort):
    @provider(
        input_types={"w": integer_value(1000, seq_type=1), "y": integer_value(2)},
        sort_by_length=sort,
    )
    def proc(settings, file_name):
        import random

        rng = random.Random(file_name)
        for i in range(600):
            t = rng.randint(2, 64)
            yield {"w": [rng.randrange(1000) for _ in range(t)], "y": i % 2}

    return proc


def _padded_tokens(dp):
    """(total padded tokens, per-batch padded T, all delivered lengths)."""
    padded = 0
    padded_ts = []
    lengths = []
    for batch in dp.batches():
        arg = batch["w"]
        B, T = arg.ids.shape
        padded += B * T
        padded_ts.append(T)
        lengths.extend(int(x) for x in np.asarray(arg.seq_lengths))
    return padded, padded_ts, lengths


def _dp(sort, **kw):
    return DataProvider(_mk_provider(sort), ["f1"], batch_size=32,
                        slot_names=["w", "y"], async_prefetch=False,
                        seed=3, **kw)


def test_sorted_batches_waste_less_padding():
    p_unsorted, _, len_a = _padded_tokens(_dp(False))
    p_sorted, ts, len_b = _padded_tokens(_dp(True))
    # identical sample multiset either way (delivery is exactly-once)
    assert sorted(len_a) == sorted(len_b)
    # sorting must cut padded tokens substantially (uniform 2..64 lengths:
    # unsorted batches pad nearly everything to the bucketed max)
    assert p_sorted < 0.75 * p_unsorted, (p_sorted, p_unsorted)
    # and batches must not all share one padded length (bucketed shapes)
    assert len(set(ts)) > 1, ts


def test_sorted_batch_order_is_shuffled():
    _, ts, _ = _padded_tokens(_dp(True))
    # a sorted-but-unshuffled pass would yield non-decreasing padded Ts;
    # the batch-order shuffle must break that
    assert any(a > b for a, b in zip(ts, ts[1:])), ts


def test_test_path_order_unchanged():
    """for_test providers never sort (generation output order contract)."""
    dp = _dp(True, for_test=True)
    assert dp.sort_by_length is False
    got = []
    for batch in dp.batches():
        got.extend(int(x) for x in np.asarray(batch["w"].seq_lengths))
    # order equals generator order: re-run the raw generator to compare
    import random

    rng = random.Random("f1")
    want = []
    for i in range(600):
        t = rng.randint(2, 64)
        [rng.randrange(1000) for _ in range(t)]
        want.append(t)
    assert got == want


def test_subsequence_key_uses_padded_area():
    """SUB_SEQUENCE slots sort by S*max(sub len) (their padded area), not
    by subsequence count — 3 subs of length 60 must sort AFTER 5 subs of
    length 4."""
    from paddle_tpu.data.provider import integer_value, SequenceType

    tp = integer_value(10, seq_type=SequenceType.SUB_SEQUENCE)

    class FakeAssembler:
        slot_names = ["x"]
        input_types = [tp]

    dp = DataProvider.__new__(DataProvider)
    dp.assembler = FakeAssembler()
    small_many = {"x": [[1, 2, 3, 4]] * 5}      # area 5*4 = 20
    big_few = {"x": [[1] * 60] * 3}             # area 3*60 = 180
    assert dp._sample_len(small_many) < dp._sample_len(big_few)
