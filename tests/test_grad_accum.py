"""Gradient accumulation (num_batches_per_send_parameter = N): N batches
of size b accumulated must produce EXACTLY the updates of batch size N*b
(reference TrainerInternal: N forwardBackwards per parameter send — the
sample-weighted mean gradient is identical).
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS


PROVIDER = """
import numpy as np
from paddle_tpu.data import provider, dense_vector, integer_value

@provider(input_types=[dense_vector(20), integer_value(3)],
          should_shuffle=False)
def process(settings, filename):
    rng = np.random.RandomState(7)
    for _ in range(192):
        y = rng.randint(0, 3)
        x = (rng.randn(20) * 0.4 + y).astype(np.float32)
        yield x.tolist(), int(y)
"""


def _config(tmp_path, batch_size, accum):
    train_list = tmp_path / "train.list"
    train_list.write_text("a\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list=None,
                            module="accprov", obj="process")
    settings(batch_size={batch_size}, learning_rate=0.05,
             learning_method=AdamOptimizer(),
             num_batches_per_send_parameter={accum})
    data = data_layer(name="x", size=20)
    h = fc_layer(input=data, size=8, act=TanhActivation(), name="h")
    output = fc_layer(input=h, size=3, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=3)
    outputs(classification_cost(input=output, label=label))
    """)
    p = tmp_path / f"cfg_{batch_size}_{accum}.py"
    p.write_text(src)
    return str(p)


@pytest.fixture()
def ws(tmp_path):
    (tmp_path / "accprov.py").write_text(PROVIDER)
    sys.path.insert(0, str(tmp_path))
    yield tmp_path
    sys.path.remove(str(tmp_path))


def _train(tmp_path, batch_size, accum, mesh_shape=""):
    FLAGS.save_dir = ""
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.mesh_shape = mesh_shape
    try:
        cfg = parse_config(_config(tmp_path, batch_size, accum))
        tr = Trainer(cfg)
        tr.train(num_passes=2)
        return {k: np.asarray(v) for k, v in tr.params.items()}
    finally:
        FLAGS.mesh_shape = ""


def test_accum_matches_large_batch(ws):
    """4 batches of 16 with accum=4 == 1 batch of 64 (unshuffled data):
    identical update sequence, near-identical parameters."""
    p_accum = _train(ws, 16, 4)
    p_big = _train(ws, 64, 1)
    assert set(p_accum) == set(p_big)
    for k in p_big:
        np.testing.assert_allclose(p_accum[k], p_big[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)
    # and accumulation actually changed something vs. no training
    assert any(np.abs(p_big[k]).sum() > 0 for k in p_big)


def test_accum_under_mesh(ws):
    """Accumulation composes with a data-parallel mesh (sharded astep and
    ustep) and matches the unmeshed result."""
    p_mesh = _train(ws, 16, 4, mesh_shape="data=8")
    p_flat = _train(ws, 16, 4)
    for k in p_flat:
        np.testing.assert_allclose(p_mesh[k], p_flat[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_accum_with_sparse_table_falls_back_dense(ws):
    """A sparse_update embedding under accumulation uses dense gradients
    (RowSparseGrad shapes vary per batch and cannot be accumulated);
    training still converges."""
    train_list = ws / "train.list"
    train_list.write_text("a\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list=None,
                            module="seqprov", obj="process")
    settings(batch_size=16, learning_rate=0.1,
             learning_method=AdamOptimizer(),
             num_batches_per_send_parameter=3)
    words = data_layer(name="words", size=50)
    emb = embedding_layer(input=words, size=8,
                          param_attr=ParamAttr(name="emb", sparse_update=True))
    pool = pooling_layer(input=emb, pooling_type=AvgPooling())
    output = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    p = ws / "cfg_sparse_accum.py"
    p.write_text(src)
    (ws / "seqprov.py").write_text(textwrap.dedent("""
    import numpy as np
    from paddle_tpu.data import provider, integer_value_sequence, integer_value

    @provider(input_types=[integer_value_sequence(50), integer_value(2)],
              should_shuffle=False)
    def process(settings, filename):
        rng = np.random.RandomState(3)
        for _ in range(96):
            y = rng.randint(0, 2)
            toks = rng.randint(25 * y, 25 * y + 25, rng.randint(3, 8))
            yield [int(t) for t in toks], int(y)
    """))
    FLAGS.save_dir = ""
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    cfg = parse_config(str(p))
    tr = Trainer(cfg)
    assert tr._accum_n == 3
    batch = next(tr._provider(for_test=False).batches())
    loss0 = float(tr.gm.loss_fn(tr.params, batch, None)[0])
    tr.train(num_passes=4)
    loss1 = float(tr.gm.loss_fn(tr.params, batch, None)[0])
    assert np.isfinite(np.asarray(tr.params["emb"])).all()
    # the separable classes must be learned through the accumulated path
    assert loss1 < 0.5 * loss0, (loss0, loss1)
