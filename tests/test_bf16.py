"""Mixed-precision (bf16) tests.

OptimizationConfig.dtype="bfloat16" runs activations and matmuls in bf16
with f32 master weights, optimizer state, and loss math (the TPU
mixed-precision recipe; no reference counterpart — the reference is
float-or-double only, /root/reference/proto/CMakeLists.txt:15-16
WITH_DOUBLE). Parity tests compare bf16 training against f32 with loose
tolerance, per-layer dtype checks pin the f32 islands (softmax, loss,
batch-norm statistics), and checkgrad proves mixed precision does not
leak into the finite-difference path.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.flagship import example_batch, flagship_config
from paddle_tpu.graph import GradientMachine, make_dense, make_ids
from paddle_tpu.graph.machine import compute_dtype_of
from paddle_tpu.optimizer import Updater


def _train(tc, batch, steps=5, seed=1):
    gm = GradientMachine(tc.model_config, compute_dtype=compute_dtype_of(tc.opt_config))
    up = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=seed)
    st = up.init_state(params)
    grad_fn = gm.grad_fn()

    @jax.jit
    def step(params, st, batch, rng):
        loss, grads, outputs, su = grad_fn(params, batch, rng)
        new_params, new_st = up(params, grads, st, jnp.asarray(float(_bs(batch))))
        for k, v in su.items():
            new_params[k] = v
        return new_params, new_st, loss, grads

    losses = []
    rng = jax.random.PRNGKey(7)
    grads = None
    for _ in range(steps):
        rng, sub = jax.random.split(rng)
        params, st, loss, grads = step(params, st, batch, sub)
        losses.append(float(loss))
    return losses, params, grads, gm


def _bs(batch):
    for a in batch.values():
        return a.batch_size


def test_compute_dtype_of():
    tc = flagship_config()
    assert compute_dtype_of(tc.opt_config) is None
    tc.opt_config.dtype = "bfloat16"
    assert compute_dtype_of(tc.opt_config) == jnp.bfloat16
    tc.opt_config.dtype = "int8"
    with pytest.raises(ValueError):
        compute_dtype_of(tc.opt_config)


def test_lstm_classifier_bf16_parity():
    batch = example_batch(B=8, T=16)
    tc = flagship_config()
    l32, p32, g32, _ = _train(tc, batch)
    tc.opt_config.dtype = "bfloat16"
    l16, p16, g16, _ = _train(tc, batch)
    # losses track within bf16 tolerance and training makes progress
    np.testing.assert_allclose(l16, l32, rtol=0.05, atol=0.02)
    assert l32[-1] < l32[0] and l16[-1] < l16[0]
    # master params and their gradients stay f32
    assert all(v.dtype == jnp.float32 for v in p16.values())
    assert all(getattr(v, "dtype", jnp.float32) == jnp.float32 for v in jax.tree_util.tree_leaves(g16))


def test_bf16_activation_islands():
    """Activations bf16; softmax output bf16 but normalized; cost f32."""
    tc = flagship_config()
    tc.opt_config.dtype = "bfloat16"
    gm = GradientMachine(tc.model_config, compute_dtype=jnp.bfloat16)
    batch = example_batch(B=4, T=8)
    outputs, _ = gm.forward(gm.init_params(seed=1), batch, "train", jax.random.PRNGKey(0))
    assert outputs["__embedding_0__"].value.dtype == jnp.bfloat16
    assert outputs["output"].value.dtype == jnp.bfloat16
    assert outputs["__cost_0__"].value.dtype == jnp.float32
    # softmax computed in f32 internally: rows sum to 1 within bf16 eps
    s = np.asarray(outputs["output"].value.astype(jnp.float32)).sum(-1)
    np.testing.assert_allclose(s, 1.0, atol=2e-2)


def _vgg_cifar_config(dtype):
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        MomentumOptimizer,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        fc_layer,
        img_conv_layer,
        img_pool_layer,
        batch_norm_layer,
        outputs,
        settings,
    )

    with fresh_context() as ctx:
        settings(batch_size=8, learning_rate=0.01,
                 learning_method=MomentumOptimizer(0.9), dtype=dtype)
        img = data_layer(name="image", size=3 * 8 * 8)
        conv = img_conv_layer(input=img, filter_size=3, num_filters=8,
                              num_channels=3, stride=1, padding=1, name="conv")
        bn = batch_norm_layer(input=conv, name="bn")
        pool = img_pool_layer(input=bn, pool_size=2, stride=2, num_channels=8)
        out = fc_layer(input=pool, size=4, act=SoftmaxActivation(), name="out")
        label = data_layer(name="label", size=4)
        outputs(classification_cost(input=out, label=label))
        return ctx.finalize()


def _image_batch(B=8):
    rng = np.random.RandomState(3)
    return {
        "image": make_dense(rng.randn(B, 3 * 8 * 8).astype(np.float32)),
        "label": make_ids(rng.randint(0, 4, (B,)).astype(np.int32)),
    }


def test_conv_bn_bf16_parity_and_f32_stats():
    batch = _image_batch()
    l32, p32, _, _ = _train(_vgg_cifar_config("float32"), batch, steps=4)
    l16, p16, _, gm16 = _train(_vgg_cifar_config("bfloat16"), batch, steps=4)
    np.testing.assert_allclose(l16, l32, rtol=0.08, atol=0.05)
    # batch-norm running stats are master-dtype f32 and track the f32 run
    stats = [n for n in p16 if "moving" in n or "mean" in n or "var" in n]
    assert gm16.compute_dtype == jnp.bfloat16
    for n in p16:
        assert p16[n].dtype == jnp.float32, n
    for n in stats:
        np.testing.assert_allclose(
            np.asarray(p16[n]), np.asarray(p32[n]), rtol=0.05, atol=0.05
        )


def test_checkgrad_ignores_compute_dtype():
    tc = flagship_config()
    tc.opt_config.dtype = "bfloat16"
    gm = GradientMachine(tc.model_config, compute_dtype=jnp.bfloat16)
    params = gm.init_params(seed=1)
    report = gm.check_gradient(params, example_batch(B=4, T=8), max_entries=3)
    assert gm.compute_dtype == jnp.bfloat16  # restored after the check
    assert report and all(d < 5e-2 for d in report.values()), report


def test_cost_only_data_layers_not_narrowed():
    """Regression targets / weights feed only cost layers — their dense
    values must reach the f32 loss island un-rounded."""
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        LinearActivation,
        data_layer,
        fc_layer,
        outputs,
        regression_cost,
        settings,
    )

    with fresh_context() as ctx:
        settings(batch_size=4, learning_rate=0.1, dtype="bfloat16")
        x = data_layer(name="x", size=8)
        y = data_layer(name="y", size=1)
        pred = fc_layer(input=x, size=1, act=LinearActivation(), name="pred")
        outputs(regression_cost(input=pred, label=y))
        tc = ctx.finalize()

    gm = GradientMachine(tc.model_config, compute_dtype=jnp.bfloat16)
    assert gm.no_cast_inputs == frozenset({"y"})
    rng = np.random.RandomState(5)
    batch = {
        "x": make_dense(rng.randn(4, 8).astype(np.float32)),
        "y": make_dense(np.full((4, 1), 0.123456, np.float32)),
    }
    outs, _ = gm.forward(gm.init_params(seed=1), batch, "train", None)
    assert outs["x"].value.dtype == jnp.bfloat16   # feature narrowed
    assert outs["y"].value.dtype == jnp.float32    # target untouched
    np.testing.assert_array_equal(np.asarray(outs["y"].value), batch["y"].value)


def test_sparse_table_grads_stay_f32_under_bf16():
    """sparse_update embedding: prefetched rows cast to bf16 in compute,
    RowSparseGrad rows come back f32 for the master update."""
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        ParamAttr,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        outputs,
        pooling_layer,
        settings,
    )
    from paddle_tpu.optimizer.sparse import RowSparseGrad

    with fresh_context() as ctx:
        settings(batch_size=4, learning_rate=0.1, dtype="bfloat16")
        words = data_layer(name="words", size=100)
        emb = embedding_layer(
            input=words, size=8,
            param_attr=ParamAttr(name="emb", sparse_update=True),
        )
        pool = pooling_layer(input=emb)
        out = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="out")
        label = data_layer(name="label", size=2)
        outputs(classification_cost(input=out, label=label))
        tc = ctx.finalize()

    gm = GradientMachine(tc.model_config, compute_dtype=compute_dtype_of(tc.opt_config))
    params = gm.init_params(seed=1)
    batch = example_batch(dict_dim=100, B=4, T=8)
    loss, grads, _, _ = gm.grad_fn()(params, batch, jax.random.PRNGKey(0))
    g = grads["emb"]
    assert isinstance(g, RowSparseGrad)
    assert g.rows.dtype == jnp.float32
    assert np.isfinite(float(loss))


def test_resnet_bf16_reaches_every_convolution():
    """The perf contract behind the headline bench: under
    dtype='bfloat16', EVERY convolution (forward and backward) in the
    lowered ResNet train step takes/produces bf16 — what the TPU backend
    maps onto the MXU's bf16 path. Checked on the pre-backend StableHLO
    (XLA:CPU would legalize bf16 convs to f32, hiding a regression)."""
    import os
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _sys.path.insert(0, repo)
    try:
        import bench
    finally:
        _sys.path.remove(repo)
    from paddle_tpu.flagship import make_image_batch, resnet_config

    tc = resnet_config(50, 32, 16)
    tc.opt_config.batch_size = 4
    tc.opt_config.dtype = "bfloat16"
    step, params, opt_state, _one = bench._jit_train_step(tc)
    batch = make_image_batch(4, 32, 16)
    txt = step.lower(params, opt_state, batch, jnp.asarray(4.0)).as_text()
    convs = [l for l in txt.splitlines() if "stablehlo.convolution" in l]
    assert len(convs) > 100, f"expected ResNet-50 fwd+bwd convs, got {len(convs)}"
    f32_convs = [l for l in convs if "xbf16>" not in l.split("->")[-1]]
    assert not f32_convs, f"{len(f32_convs)} convolutions fell back to f32:\n" + \
        "\n".join(c.strip()[:160] for c in f32_convs[:5])
