"""Sequence parallelism: ring / all-to-all attention vs the local reference.

The distributed-without-a-cluster pattern (SURVEY.md §4): an 8-device CPU
mesh stands in for a TPU slice; sharded results must match single-device
attention to float tolerance, forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.sequence_parallel import (
    alltoall_attention,
    full_attention,
    ring_attention,
)

B, T, H, D = 2, 32, 4, 8


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    return mk(), mk(), mk()


def _lengths():
    return jnp.asarray([T, T - 9], jnp.int32)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(causal):
    mesh = make_mesh("seq=4")
    q, k, v = _qkv()
    lengths = _lengths()
    ref = full_attention(q, k, v, lengths=lengths, causal=causal)
    out = ring_attention(q, k, v, mesh, lengths=lengths, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_alltoall_matches_full(causal):
    mesh = make_mesh("seq=4")
    q, k, v = _qkv(1)
    lengths = _lengths()
    ref = full_attention(q, k, v, lengths=lengths, causal=causal)
    out = alltoall_attention(q, k, v, mesh, lengths=lengths, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match():
    mesh = make_mesh("seq=4")
    q, k, v = _qkv(2)
    lengths = _lengths()

    def loss_ref(q, k, v):
        out = full_attention(q, k, v, lengths=lengths, causal=True)
        return jnp.sum(out**2)

    def loss_ring(q, k, v):
        out = ring_attention(q, k, v, mesh, lengths=lengths, causal=True)
        return jnp.sum(out**2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_combined_data_seq_mesh():
    # seq parallelism composes with data parallelism on one mesh
    mesh = make_mesh("data=2,seq=4")
    q, k, v = _qkv(3)
    ref = full_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_degenerate_mesh_falls_back():
    mesh = make_mesh("data=8")  # no seq axis: plain attention
    q, k, v = _qkv(4)
    ref = full_attention(q, k, v)
    out = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_scan_path_matches_unrolled(causal, monkeypatch):
    """The large-ring lax.scan branch (RING_UNROLL_MAX exceeded — the
    64-chip configuration) must match both the unrolled ring and the
    single-device reference on the same mesh, forward and backward."""
    import paddle_tpu.parallel.sequence_parallel as sp

    mesh = make_mesh("seq=8")
    q, k, v = _qkv(4)
    lengths = _lengths()
    ref = full_attention(q, k, v, lengths=lengths, causal=causal)
    unrolled = ring_attention(q, k, v, mesh, lengths=lengths, causal=causal)
    monkeypatch.setattr(sp, "RING_UNROLL_MAX", 1)  # force the scan ring
    scanned = ring_attention(q, k, v, mesh, lengths=lengths, causal=causal)
    np.testing.assert_allclose(np.asarray(scanned), np.asarray(ref), atol=2e-5)
    np.testing.assert_allclose(np.asarray(scanned), np.asarray(unrolled), atol=2e-5)

    def loss(fn):
        def f(q, k, v):
            out = fn(q, k, v, mesh, lengths=lengths, causal=causal)
            return jnp.sum(out**2)

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_scan = loss(ring_attention)
    monkeypatch.setattr(sp, "RING_UNROLL_MAX", 8)
    g_unroll = loss(ring_attention)
    for a, b in zip(g_scan, g_unroll):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)
