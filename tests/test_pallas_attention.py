"""Flash-attention pallas kernel vs the XLA reference (interpret mode).

The CPU-stub pattern (SURVEY.md §4): kernels run in pallas interpret mode
on CPU, asserting numerical equality with the XLA full_attention path —
forward and gradients, causal and masked variants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_attention import flash_attention, supported
from paddle_tpu.parallel.sequence_parallel import full_attention

B, T, H, D = 2, 256, 2, 32


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    return mk(), mk(), mk()


def test_supported_predicate():
    assert supported(256, 64)
    assert not supported(100, 64)      # T not divisible by blocks
    assert not supported(256, 512)     # head dim too large


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_xla(causal):
    q, k, v = _qkv()
    lengths = jnp.asarray([T, T - 77], jnp.int32)
    ref = full_attention(q, k, v, lengths=lengths, causal=causal)
    out = flash_attention(q, k, v, lengths=lengths, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_xla(causal):
    q, k, v = _qkv(1)
    lengths = jnp.asarray([T, T - 130], jnp.int32)

    def loss_ref(q, k, v):
        o = full_attention(q, k, v, lengths=lengths, causal=causal)
        # mask padded rows out of the loss: their flash output is 0 but the
        # XLA path produces garbage values there (both are masked by
        # downstream layers in real models)
        m = (jnp.arange(T)[None, :] < lengths[:, None]).astype(o.dtype)
        return jnp.sum((o * m[..., None, None]) ** 2)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, lengths=lengths, causal=causal, interpret=True)
        m = (jnp.arange(T)[None, :] < lengths[:, None]).astype(o.dtype)
        return jnp.sum((o * m[..., None, None]) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_full_lengths_default():
    q, k, v = _qkv(2)
    ref = full_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
