"""Serving fleet (doc/serving.md "Serving fleet"): the multi-replica
router — health-scored least-loaded balancing, journal-replay failover
under at-least-once dedupe-by-id, fleet-wide graceful drain — plus hot
weight reload (checkpoint lands mid-stream, swap at an iteration
boundary, zero dropped/duplicated/stranded requests), the aggregate
`paddle serve-status <dir>` fleet view, the fleet window merge behind
`bench.py serve --replicas=N`, and the fleet.* chaos sites."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability.analyze import load_run
from paddle_tpu.observability.compare import _serve_key
from paddle_tpu.resilience import EXIT_PREEMPTED, faultinject
from paddle_tpu.serving import Engine, FakeBackend
from paddle_tpu.serving.fleet import (
    FleetRouter,
    merge_windows,
    replica_score,
)
from paddle_tpu.serving.resilience import (
    WeightReloader,
    read_status,
    status_main,
)
from paddle_tpu.utils import concurrency as cc

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the race spec's FakeReplica is the reference in-process implementation
# of the duck-typed replica handle protocol — reuse it rather than fork
# a second one that could drift
_spec = importlib.util.spec_from_file_location(
    "spec_serve_fleet",
    os.path.join(REPO, "tests", "race_specs", "spec_serve_fleet.py"))
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)
FakeReplica = _mod.FakeReplica


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")
    faultinject.configure("")


class _Ctx:
    """Stand-in for the race explorer context: static_watch is a no-op
    outside `paddle race`."""

    def static_watch(self, obj):
        pass


def _fleet(n, **kw):
    emitted = []
    elock = cc.Lock()

    def emit(doc):
        with elock:
            emitted.append(doc)

    reps = [FakeReplica(f"replica-{i}") for i in range(n)]
    kw.setdefault("poll_s", 0.005)
    kw.setdefault("health_period_s", 0.0)
    kw.setdefault("restart_base_delay", 0.01)
    router = FleetRouter(reps, emit=emit, **kw)
    for r in reps:
        r.deliver = router.deliver
    return router, reps, emitted


def _run_to_eof(router, timeout=60.0):
    box = {}

    def target():
        box["rc"] = router.run()

    t = cc.Thread(target=target, daemon=True)
    t.start()
    router.note_eof()
    t.join(timeout=timeout)
    assert not t.is_alive(), "router run() did not terminate"
    return box["rc"]


# ------------------------------------------------------------- scoring


def test_replica_score_health_weighted():
    assert replica_score(3, None) == 3.0
    assert replica_score(0, {"queue_depth": 4, "occupancy": 2}) == 6.0
    # a stale doc contributes nothing: outstanding is the only honest
    # signal left
    assert replica_score(2, {"stale": True, "queue_depth": 99}) == 2.0
    assert replica_score(1, {"queue_depth": "bogus"}) == 1.0


# ------------------------------------------------------------- routing


def test_routes_all_answered_in_submission_order():
    router, reps, emitted = _fleet(2)
    router.start()
    ids = [f"r{i}" for i in range(6)]
    for rid in ids:
        assert router.submit({"id": rid, "prompt": [2],
                              "max_new_tokens": 1})
    assert _run_to_eof(router) == 0
    router.shutdown(timeout=10.0)
    assert [d["id"] for d in emitted] == ids
    assert all(d["outcome"] == "ok" for d in emitted), emitted
    # least-loaded balancing actually spread the work: with equal-cost
    # requests neither replica took everything
    assert reps[0].accepted_count() > 0 and reps[1].accepted_count() > 0
    assert router.status()["routed"] == len(ids)


def test_duplicate_submit_refused_and_duplicate_answer_absorbed():
    router, reps, emitted = _fleet(1)
    router.start()
    assert router.submit({"id": "a", "prompt": [2], "max_new_tokens": 1})
    assert router.submit({"id": "a", "prompt": [2]}) is False
    assert _run_to_eof(router) == 0
    # a replayed answer for an already-answered id (at-least-once
    # journal semantics) is counted, never re-emitted
    router.deliver("replica-0", {"id": "a", "outcome": "ok", "tokens": [1]})
    router.shutdown(timeout=10.0)
    assert [d["id"] for d in emitted] == ["a"]
    assert router.status()["duplicate_answers"] == 1


def test_open_breaker_routes_around():
    router, reps, emitted = _fleet(2)
    # replica-0 reports an open breaker: every request must land on
    # replica-1
    orig = reps[0].health
    reps[0].health = lambda now: dict(orig(now), breaker="open")
    router.start()
    for i in range(3):
        assert router.submit({"id": f"b{i}", "prompt": [2],
                              "max_new_tokens": 1})
    assert _run_to_eof(router) == 0
    router.shutdown(timeout=10.0)
    assert [d["outcome"] for d in emitted] == ["ok"] * 3
    assert reps[0].accepted_count() == 0
    assert reps[1].accepted_count() == 3


# ------------------------------------------------------------ failover


def test_failover_reoffers_journal_exactly_once():
    """THE failover drill: replica-0 dies (budgeted exit class) holding
    journaled work; the router re-offers it to replica-1 while
    replica-0's restart replays the same journal — every id answered
    exactly once, the death and restart observable."""
    router, reps, emitted = _fleet(2, restart_budget=3)
    # slow replica-0 down so it dies with work still pending
    reps[0].delay_s = 0.2
    router.start()
    box = {}

    def target():
        box["rc"] = router.run()

    t = cc.Thread(target=target, daemon=True)
    t.start()
    ids = [f"f{i}" for i in range(6)]
    for rid in ids:
        assert router.submit({"id": rid, "prompt": [3],
                              "max_new_tokens": 1})
    deadline = cc.monotonic() + 30.0
    while reps[0].accepted_count() == 0 and cc.monotonic() < deadline:
        cc.sleep(0.002)
    assert reps[0].accepted_count() > 0, "replica-0 never took work"
    reps[0].die(17)  # EXIT_CRASH_LOOP: consumes the restart budget
    router.note_eof()
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate"
    assert box["rc"] == 0
    router.shutdown(timeout=10.0)
    assert [d["id"] for d in emitted] == ids
    assert all(d["outcome"] == "ok" for d in emitted), emitted
    st = router.status()
    assert st["deaths"] >= 1 and st["reoffers"] >= 1, st
    assert st["replicas"]["replica-0"]["restarts"] >= 1, st
    assert reps[0].incarnations >= 2  # it rejoined the rotation


def test_preemption_restart_is_budget_free():
    router, reps, emitted = _fleet(1, restart_budget=0)
    reps[0].delay_s = 0.1
    router.start()
    box = {}

    def target():
        box["rc"] = router.run()

    t = cc.Thread(target=target, daemon=True)
    t.start()
    assert router.submit({"id": "p0", "prompt": [2], "max_new_tokens": 1})
    deadline = cc.monotonic() + 30.0
    while reps[0].accepted_count() == 0 and cc.monotonic() < deadline:
        cc.sleep(0.002)
    reps[0].die(EXIT_PREEMPTED)
    router.note_eof()
    # budget is ZERO — only the free preemption class lets this fleet
    # finish its batch
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate"
    assert box["rc"] == 0
    router.shutdown(timeout=10.0)
    assert [d["id"] for d in emitted] == ["p0"]
    assert emitted[0]["outcome"] == "ok"
    st = router.status()["replicas"]["replica-0"]
    assert st["restarts"] == 0, st  # the free class consumed no budget


def test_all_replicas_down_errors_out_instead_of_hanging():
    router, reps, emitted = _fleet(1, restart_budget=0)
    reps[0].delay_s = 60.0  # never answers within the test
    router.start()
    for i in range(2):
        assert router.submit({"id": f"z{i}", "prompt": [2],
                              "max_new_tokens": 1})
    cc.sleep(0.05)
    reps[0].die(20)  # EXIT_OOM, budget 0: permanently down
    assert _run_to_eof(router, timeout=120.0) == 1
    router.shutdown(timeout=10.0)
    assert [d["id"] for d in emitted] == ["z0", "z1"]
    assert all(d["outcome"] == "error" for d in emitted), emitted


def test_stale_status_routes_around_then_kills_past_bound():
    """An injected fleet.status_stale verdict: the replica is routed
    around immediately; persisting past the staleness bound (no startup
    grace here) it is killed and treated as a death — the fleet still
    answers everything."""
    router, reps, emitted = _fleet(
        2, restart_budget=0, stale_after_s=0.05, startup_grace_s=0.0)
    # replica-0's probe reads permanently stale (the wedged-child
    # verdict the fleet.status_stale chaos site also produces)
    reps[0].health = lambda now: {"stale": True, "detail": "wedged"}
    router.start()
    box = {}

    def target():
        box["rc"] = router.run()

    t = cc.Thread(target=target, daemon=True)
    t.start()
    for i in range(3):
        assert router.submit({"id": f"s{i}", "prompt": [2],
                              "max_new_tokens": 1})
    # keep the loop alive past the staleness bound: the wedged replica
    # must be culled as a death even though the batch already answered
    deadline = cc.monotonic() + 30.0
    while router.status()["deaths"] == 0 and cc.monotonic() < deadline:
        cc.sleep(0.005)
    router.note_eof()
    t.join(timeout=120.0)
    assert not t.is_alive(), "router run() did not terminate"
    assert box["rc"] == 0
    router.shutdown(timeout=10.0)
    assert [d["id"] for d in emitted] == ["s0", "s1", "s2"]
    assert all(d["outcome"] == "ok" for d in emitted), emitted
    # replica-1 carried the fleet; replica-0 was never routed to and
    # was eventually culled as a death
    assert reps[0].accepted_count() == 0
    assert reps[1].accepted_count() == 3
    assert router.status()["deaths"] >= 1


# --------------------------------------------------------------- drain


def test_drain_completes_inflight_rejects_queued():
    router, reps, emitted = _fleet(1)
    reps[0].delay_s = 0.05
    router.start()
    box = {}

    def target():
        box["rc"] = router.run()

    t = cc.Thread(target=target, daemon=True)
    t.start()
    assert router.submit({"id": "in0", "prompt": [2], "max_new_tokens": 1})
    # wait until in0 is actually routed (in-flight), then drain
    deadline = cc.monotonic() + 30.0
    while reps[0].accepted_count() == 0 and cc.monotonic() < deadline:
        cc.sleep(0.002)
    router.request_drain()
    t.join(timeout=60.0)
    assert not t.is_alive() and box["rc"] == 0
    # a post-drain submit is rejected — and still ANSWERED (the late-
    # arrival path emits inline once the loop has exited)
    assert router.submit({"id": "late", "prompt": [2]})
    router.shutdown(timeout=10.0)
    by_id = {d["id"]: d["outcome"] for d in emitted}
    assert by_id["in0"] in ("ok", "error"), by_id  # in-flight completed
    assert by_id["late"] == "rejected", by_id
    st = router.status()
    assert st["draining"] is True
    assert all(not r["up"] for r in st["replicas"].values()), st


# ----------------------------------------------------------- hot reload


def test_engine_reload_swaps_at_boundary_no_dropped_requests(tmp_path):
    """The reload contract end-to-end on the real engine: a swap staged
    mid-stream lands at an iteration boundary — requests admitted
    before it finish, requests after it run on the new weights, the
    swap is visible in status(), counters and the telemetry stream."""
    obs.configure(str(tmp_path))
    be = FakeBackend(slots=2, max_length=8, step_delay_s=0.01)
    eng = Engine(be, request_timeout_s=30.0, idle_poll_s=0.01,
                 replica="replica-0").start()
    try:
        old = be.token_fn
        pre = [eng.submit([2, 3], max_new_tokens=3, rid=f"pre{i}")
               for i in range(3)]
        eng.request_reload(lambda slot, step: 7, tag="ckpt-00042")
        post = [eng.submit([2, 3], max_new_tokens=3, rid=f"post{i}")
                for i in range(3)]
        results = [f.result(timeout=60.0) for f in pre + post]
        assert all(r.outcome == "ok" for r in results), results
        assert all(len(r.tokens) == 3 for r in results), results
        st = eng.status()
        assert st["reloads"] == 1 and st["reload_tag"] == "ckpt-00042", st
        assert be.reloads == 1 and be.token_fn is not old
        # post-swap work really ran on the new weights
        tail = eng.submit([2], max_new_tokens=2, rid="tail").result(60.0)
        assert tail.tokens == [7, 7], tail
    finally:
        assert eng.drain(timeout=60.0)
    obs.flush()
    recs = [r for rs in load_run(str(tmp_path)).values() for r in rs]
    reloads = [r for r in recs if r.get("kind") == "reload"]
    assert len(reloads) == 1, reloads
    assert reloads[0]["path"] == "ckpt-00042"
    assert reloads[0]["replica"] == "replica-0"
    assert not obs.validate_record(reloads[0]), reloads[0]


def test_weight_reloader_probe_swap_and_poison(tmp_path):
    """The watcher half: only a CHANGED durable checkpoint triggers a
    staged reload; a poison checkpoint is skipped permanently; the
    fleet.reload_torn chaos site aborts the attempt and retries."""
    be = FakeBackend(slots=1, max_length=4)
    eng = Engine(be, request_timeout_s=30.0, idle_poll_s=0.01).start()
    try:
        probed = {"path": "ckpt-1"}
        loads = []

        def loader(path):
            loads.append(path)
            return lambda slot, step: 9

        wr = WeightReloader(str(tmp_path), eng, loader,
                            probe=lambda d: probed["path"])
        # baseline: the checkpoint present at start never reloads
        assert wr.check_once() is False and loads == []
        probed["path"] = "ckpt-2"
        assert wr.check_once() is True and loads == ["ckpt-2"]
        assert wr.check_once() is False  # same path: no news
        # torn-commit chaos: abort, keep old weights, RETRY next poll
        probed["path"] = "ckpt-3"
        faultinject.configure("fleet.reload_torn=raise@1")
        assert wr.check_once() is False and loads == ["ckpt-2"]
        assert wr.check_once() is True and loads[-1] == "ckpt-3"
        # poison: the loader blows up — skipped permanently, serving on
        probed["path"] = "ckpt-4"

        def boom(path):
            raise RuntimeError("corrupt")

        wr._loader = boom
        assert wr.check_once() is False
        assert wr.check_once() is False  # not retried in a hot loop
        assert wr.reloads == 2
    finally:
        faultinject.configure("")
        assert eng.drain(timeout=60.0)


# ------------------------------------------------------ fleet status view


def test_serve_status_fleet_view_tolerates_torn(tmp_path, capsys):
    good = {"started": True, "queue_depth": 2, "occupancy": 1,
            "slots": 2, "breaker": "closed", "last_collect_age_s": 0.1,
            "totals": {"ok": 5, "error": 1}}
    (tmp_path / "replica-0.json").write_text(json.dumps(good))
    (tmp_path / "replica-1.json").write_text(json.dumps(
        dict(good, queue_depth=0, occupancy=2,
             totals={"ok": 7, "error": 0})))
    (tmp_path / "replica-2.json").write_text('{"started": tru')  # torn
    assert status_main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "replica-0" in out and "replica-2" in out
    assert "STALE" in out  # the torn doc is a row, not a crash
    assert "2/3 up" in out
    assert "12" in out  # fleet ok total
    # --json: machine-readable, torn docs as {"stale": true}
    assert status_main([str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["replica-2"] == {"stale": True}
    assert doc["replica-0"]["totals"]["ok"] == 5


def test_read_status_tolerant():
    assert read_status("/nonexistent/path.json") is None


# ------------------------------------------------------- sites + merge


def test_fleet_sites_registered():
    for site in ("fleet.replica_crash", "fleet.status_stale",
                 "fleet.reload_torn"):
        assert site in faultinject.SITE_DOCS, site


def _win(completed, gen_tokens, p99, replica):
    return {
        "engine": "continuous", "replica": replica,
        "arrived": completed, "admitted": completed,
        "completed": completed, "rejected": 0, "timeouts": 0,
        "cancelled": 0, "errors": 0, "shed": 0, "breaker_open": 0,
        "launches": completed, "gen_tokens": gen_tokens, "exec_s": 0.5,
        "latency": {"count": completed, "mean": 0.1, "p50": 0.1,
                    "p99": p99, "max": p99},
        "ttft": {"count": completed, "mean": 0.05, "p50": 0.05,
                 "p99": 0.05, "max": 0.05},
        "queue_wait": {"count": completed, "mean": 0.01, "p50": 0.01,
                       "p99": 0.01, "max": 0.01},
        "queue_depth": {"count": 4, "mean": 1.0, "p50": 1.0, "p99": 2.0,
                        "max": 2.0},
        "occupancy": {"count": 4, "mean": 1.5, "p50": 1.5, "p99": 2.0,
                      "max": 2.0},
        "queue_wait_share": 0.1,
    }


def test_merge_windows_sums_counts_keeps_worst_tail(tmp_path):
    obs.configure(str(tmp_path))
    rec = merge_windows(
        [_win(4, 40, 0.2, "replica-0"), _win(8, 80, 0.5, "replica-1")],
        rate_rps=2.0, rung=3, window_s=10.0, router_s=0.25)
    assert rec["replicas"] == 2
    assert rec["completed"] == 12 and rec["gen_tokens"] == 120
    assert rec["goodput_tok_s"] == 12.0
    assert rec["latency"]["p99"] == 0.5  # the WORST replica's tail
    assert rec["latency"]["count"] == 12
    assert rec["router_share"] == 0.025
    assert "replica" not in rec  # the merged record is the fleet's
    obs.flush()
    recs = [r for rs in load_run(str(tmp_path)).values() for r in rs]
    wins = [r for r in recs if r.get("kind") == "serve_window"]
    assert len(wins) == 1 and wins[0]["replicas"] == 2
    assert not obs.validate_record(wins[0]), wins[0]


def test_merge_windows_gauge_means_keep_zero_completion_replicas(tmp_path):
    """The silent-drop bug (PR 18): completion-weighted means gave a
    zero-completion replica weight 0 in the GAUGE snaps too, so an idle
    (or just-restarted) replica vanished from the merged occupancy/
    queue-depth view and the fleet looked busier than it was. Gauges
    are sampled per snapshot, not per completion — they now weight by
    the snap's own sample count."""
    obs.configure(str(tmp_path))
    wa = _win(0, 0, 0.0, "replica-0")     # answered nothing this window
    wa["occupancy"] = {"count": 4, "mean": 0.0, "p50": 0.0, "p99": 0.0,
                       "max": 0.0}
    wa["queue_depth"] = {"count": 4, "mean": 0.0, "p50": 0.0, "p99": 0.0,
                         "max": 0.0}
    wb = _win(8, 80, 0.5, "replica-1")
    wb["occupancy"] = {"count": 4, "mean": 2.0, "p50": 2.0, "p99": 2.0,
                       "max": 2.0}
    wb["queue_depth"] = {"count": 4, "mean": 2.0, "p50": 2.0, "p99": 2.0,
                         "max": 2.0}
    rec = merge_windows([wa, wb], rate_rps=2.0, rung=0, window_s=10.0,
                        router_s=0.0)
    # the idle replica is half the fleet: the merged gauge mean must be
    # 1.0, not replica-1's 2.0 (the pre-fix silent drop)
    assert rec["occupancy"]["mean"] == pytest.approx(1.0)
    assert rec["queue_depth"]["mean"] == pytest.approx(1.0)
    # completion-weighted stats are untouched: all latency mass is B's
    assert rec["latency"]["p99"] == 0.5
    assert rec["completed"] == 8


def test_compare_serve_key_joins_on_replicas():
    seen = set()
    assert _serve_key(2.0, 0, seen) == "serve.2rps."
    assert _serve_key(2.0, 1, set(), replicas=2) == "serve.x2.2rps."
    assert _serve_key(2.0, 2, set(), replicas=1) == "serve.2rps."
    # an x2 rung never collides with the x4 one in the same artifact
    seen2 = set()
    k2 = _serve_key(2.0, 0, seen2, engine="continuous", replicas=2)
    k4 = _serve_key(2.0, 1, seen2, engine="continuous", replicas=4)
    assert k2 != k4


# --------------------------------------------------------- chaos e2e


SERVE_CONFIG = """
import sys
sys.path.insert(0, {demo!r})
from paddle.trainer_config_helpers import *
from seqToseq_net import gru_encoder_decoder

settings(batch_size=2, learning_rate=1e-3, learning_method=AdamOptimizer())
gru_encoder_decoder(source_dict_dim=50, target_dict_dim=50,
                    is_generating=True, word_vector_dim=16,
                    encoder_size=16, decoder_size=16, beam_size=1,
                    max_length=6)
"""

SUBPROC_ENV = dict(
    os.environ, JAX_PLATFORMS="cpu",
    PYTHONPATH=f"{REPO}:{os.path.join(REPO, 'compat')}",
)


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_fleet_kills_one_replica_every_request_answered(tmp_path):
    """THE acceptance scenario: `paddle serve-fleet` with 2 replicas;
    replica 0 takes an injected serve.crash (hard os._exit at its 2nd
    collect boundary, via the per-child fault env). The router marks it
    dead, re-offers its journaled unanswered requests to replica 1,
    restarts it on budget — and every request id is answered EXACTLY
    once on the router's stdout, in submission order, rc 0."""
    cfg = tmp_path / "serve_conf.py"
    cfg.write_text(SERVE_CONFIG.format(
        demo=os.path.join(REPO, "demo", "seqToseq")))
    status_dir = tmp_path / "fleet"
    run_dir = tmp_path / "run"
    ids = [f"c{i}" for i in range(8)]
    reqs = "\n".join(json.dumps(
        {"id": rid, "prompt": [4 + i, 7], "max_new_tokens": 2}
    ) for i, rid in enumerate(ids))
    env = dict(
        SUBPROC_ENV,
        PADDLE_TPU_FLEET_CHILD_FAULTS_0="serve.crash=exit:3@2",
    )
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "serve-fleet",
         f"--config={cfg}", "--use_tpu=0", "--fleet_replicas=2",
         f"--fleet_status_dir={status_dir}",
         "--serve_slots=2", "--serve_prompt_tokens=4",
         "--serve_decode_block=1", "--restart_base_delay=0.01",
         "--restart_budget=1",
         f"--compile_cache_dir={tmp_path / 'ccache'}",
         f"--metrics_path={run_dir}"],
        input=reqs + "\n", capture_output=True, text=True, timeout=600,
        env=env, cwd=str(tmp_path),
    )
    assert out.returncode == 0, (out.returncode, out.stderr[-4000:])
    answers = []
    for line in out.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            doc = json.loads(line)
            if "outcome" in doc:
                answers.append(doc)
    got = [d["id"] for d in answers]
    assert got == ids, (got, out.stderr[-3000:])  # exactly once, in order
    assert all(d["outcome"] == "ok" for d in answers), answers
    # the drill actually fired: the router observed >= 1 death and
    # routed every request — its run_end record carries the counters
    recs = [r for rs in load_run(str(run_dir)).values() for r in rs]
    end = [r for r in recs if r.get("kind") == "run_end"]
    assert end and recs[-1]["kind"] == "run_end", recs[-1]  # stream's last
    counters = end[0].get("counters") or {}
    assert counters.get("fleet.deaths", 0) >= 1, counters
    assert counters.get("fleet.routed", 0) >= len(ids), counters
    # the per-replica journals recorded the failover's raw material
    assert (status_dir / "replica-0.journal.jsonl").exists()


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.trace
def test_chaos_fleet_trace_reconstructs_every_answered_request(tmp_path):
    """PR 18 acceptance: kill 1 of 2 replicas mid-load, then `paddle
    trace` over the fleet run dir must reconstruct a timeline for 100%
    of answered requests EXACTLY once, span sets covering e2e within
    tolerance (gap/overlap reported otherwise), re-offered requests
    carrying a distinct `router.reoffer` span, and the p99 attribution
    naming failover re-offer as its own share."""
    from paddle_tpu.observability.tracing import analyze_trace

    cfg = tmp_path / "serve_conf.py"
    cfg.write_text(SERVE_CONFIG.format(
        demo=os.path.join(REPO, "demo", "seqToseq")))
    run_dir = tmp_path / "run"
    # the status dir INSIDE the run dir: replica streams land at
    # run/fleet_status/replica-*/ where fleet_stream_dirs discovers
    # them next to the router's own stream
    status_dir = run_dir / "fleet_status"
    ids = [f"c{i}" for i in range(8)]
    reqs = "\n".join(json.dumps(
        {"id": rid, "prompt": [4 + i, 7], "max_new_tokens": 2}
    ) for i, rid in enumerate(ids))
    env = dict(
        SUBPROC_ENV,
        PADDLE_TPU_FLEET_CHILD_FAULTS_0="serve.crash=exit:3@2",
    )
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "serve-fleet",
         f"--config={cfg}", "--use_tpu=0", "--fleet_replicas=2",
         f"--fleet_status_dir={status_dir}",
         "--serve_slots=2", "--serve_prompt_tokens=4",
         "--serve_decode_block=1", "--restart_base_delay=0.01",
         "--restart_budget=1",
         f"--compile_cache_dir={tmp_path / 'ccache'}",
         f"--metrics_path={run_dir}"],
        input=reqs + "\n", capture_output=True, text=True, timeout=600,
        env=env, cwd=str(tmp_path),
    )
    assert out.returncode == 0, (out.returncode, out.stderr[-4000:])
    answers = [json.loads(l) for l in out.stdout.splitlines()
               if l.strip().startswith("{") and "outcome" in l]
    answered = [d["id"] for d in answers if d["outcome"] == "ok"]
    assert sorted(answered) == ids, (answered, out.stderr[-3000:])

    doc = analyze_trace([str(run_dir)])
    # the router's stream plus both replicas' were discovered
    assert len(doc["streams"]) >= 3, doc["streams"]
    # exactly-once reconstruction: one timeline per answered id, each
    # with a full e2e interval (requests dict is keyed by trace, so
    # double-counting would have to surface as a missing id)
    recon = {t["rid"]: t for t in doc["requests"].values()
             if t["answered"]}
    assert sorted(recon) == ids, sorted(recon)
    assert doc["n_reconstructed"] == len(ids), doc
    for rid, tl in recon.items():
        assert "e2e_s" in tl, (rid, tl)
        # coverage within tolerance; the gap/overlap numbers ARE the
        # report when this fails
        assert tl["covered_ok"], (rid, tl["coverage"], tl["gap_s"],
                                  tl["overlap_s"])
        # each instant counted once: the bucket sweep partitions e2e
        total = sum(tl["buckets"].values())
        assert total == pytest.approx(tl["e2e_s"], rel=1e-3, abs=1e-4)
    # the drill fired: at least one request was re-offered after the
    # death, and its timeline shows the distinct reoffer span
    reoffered = [t for t in recon.values() if t["reoffered"]]
    assert reoffered, "no request was re-offered — the kill never bit"
    for tl in reoffered:
        names = [sp["name"] for sp in tl["spans"]]
        assert "router.reoffer" in names, names
        assert tl["buckets"].get("reoffer", 0.0) > 0.0, tl["buckets"]
    # failover re-offer is a named share of the attribution table
    assert doc["rungs"], doc
    assert all("reoffer" in r["shares"] for r in doc["rungs"])
    # the skew bound was computed and reported for every replica stream
    assert {s["stream"] for s in doc["skew"]} >= {"replica-0",
                                                  "replica-1"}
