"""Sequence-valued memories in GENERATION — the seqFlag branch of
createMemoryFrameInfo running under generateSequence (reference
RecurrentGradientMachine.cpp:740-744): a hierarchical decoder whose step s
reads step s-1's FULL output sequence. Verified against a numpy rollout
(same methodology as tests/test_nested_recurrent.py).

The step accumulates the generated token's embedding into a carried
SEQUENCE (acc_s = acc_{s-1} + expand(e_s)), scores the next token from the
pooled accumulator, and the memory links to the sequence layer — so each
step consumes the whole sequence produced by the previous step.
"""

import os
import tempfile
import textwrap

import jax.numpy as jnp
import numpy as np

from paddle_tpu.graph import GradientMachine, make_seq


def parse_str(src: str):
    from paddle_tpu.config import parse_config

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(src))
        path = f.name
    try:
        return parse_config(path)
    finally:
        os.unlink(path)


E, V = 6, 9
BOS, EOS = 0, 8

GEN_SEQ_MEM = f"""
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
boot = data_layer(name="boot", size={E})
def gen_step(prev_word):
    mem = memory(name="accseq", size={E}, is_seq=True, boot_layer=boot)
    exp = expand_layer(input=prev_word, expand_as=mem)
    acc = addto_layer(input=[exp, mem], name="accseq", act=LinearActivation(),
                      bias_attr=False)
    pooled = pooling_layer(input=acc, pooling_type=AvgPooling())
    return fc_layer(input=pooled, size={V}, act=SoftmaxActivation(), name="scorer")
out = beam_search(step=gen_step,
                  input=[GeneratedInput(size={V}, embedding_name="Tgen",
                                        embedding_size={E})],
                  bos_id={BOS}, eos_id={EOS}, beam_size=1, max_length=7,
                  name="gen")
"""


def _softmax(x):
    e = np.exp(x - x.max())
    return e / e.sum()


def test_generation_sequence_memory_matches_numpy_rollout():
    B, T = 3, 4
    rng = np.random.RandomState(3)
    boot = rng.randn(B, T, E).astype(np.float32)
    lens = np.array([4, 2, 3], np.int32)

    tc = parse_str(GEN_SEQ_MEM)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=9)
    batch = {"boot": make_seq(jnp.asarray(boot), jnp.asarray(lens))}
    out, _ = gm.forward(params, batch, "gen")
    got_ids = np.asarray(out["gen"].ids)
    got_lens = np.asarray(out["gen"].seq_lengths)

    Tgen = np.asarray(params["Tgen"])
    W = np.asarray(params["_scorer.w0"])
    bias = np.asarray(params["_scorer.wbias"]).reshape(-1)
    for b in range(B):
        l = int(lens[b])
        acc = boot[b, :l].copy()          # step s-1's full output sequence
        prev = BOS
        toks = []
        for _ in range(7):
            acc = acc + Tgen[prev][None, :]   # expand + addto over the seq
            pooled = acc.mean(axis=0)          # avg pool over valid steps
            tok = int(np.argmax(_softmax(pooled @ W + bias)))
            toks.append(tok)
            if tok == EOS:
                break
            prev = tok
        # framework convention: the emitted eos is part of the sequence
        # (length counts it), matching the reference's generated results
        assert int(got_lens[b]) == len(toks), (b, got_lens[b], toks)
        np.testing.assert_array_equal(got_ids[b, : len(toks)], toks, err_msg=str(b))


GEN_JOB_CFG = """
from paddle_tpu.trainer_config_helpers import *
define_py_data_sources2(train_list=None, test_list="test.list",
                        module="genprov", obj="gen_process")
settings(batch_size=8, learning_rate=0.0)
src = data_layer(name="src", size=11)
def gen_step(x_t, prev):
    e = embedding_layer(input=x_t, size=7, name="src_emb",
                        param_attr=ParamAttr(name="Tsrc"))
    h = concat_layer(input=[e, prev], name="h")
    return fc_layer(input=h, size=9, act=SoftmaxActivation(), name="scorer")
out = beam_search(step=gen_step,
                  input=[src, GeneratedInput(size=9, embedding_name="Tgen",
                                             embedding_size=7)],
                  bos_id=0, eos_id=8, beam_size=2, max_length=6, name="gen")
"""

GEN_PROV = """
import random
from paddle_tpu.data import integer_value_sequence, provider

@provider(input_types={"src": integer_value_sequence(11)})
def gen_process(settings, file_name):
    rng = random.Random(int(file_name))
    for _ in range(16):
        n = rng.randint(3, 5)
        yield {"src": [rng.randint(2, 10) for _ in range(n)]}
"""


def test_generate_job_under_mesh_matches_unmeshed(tmp_path):
    """Trainer.generate() with --mesh_shape shards the generation forward
    (VERDICT weak item: generate() previously jitted without shardings)."""
    import sys

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    ws = str(tmp_path)
    (tmp_path / "cfg.py").write_text(GEN_JOB_CFG)
    (tmp_path / "genprov.py").write_text(GEN_PROV)
    (tmp_path / "test.list").write_text("7\n")
    cwd = os.getcwd()
    sys.path.insert(0, ws)
    os.chdir(ws)
    try:
        cfg = parse_config(os.path.join(ws, "cfg.py"))
        flags = _Flags(seed=3, gen_result=os.path.join(ws, "plain.txt"))
        plain = Trainer(cfg, flags).generate()
        flags_m = _Flags(seed=3, mesh_shape="data=4",
                         gen_result=os.path.join(ws, "meshed.txt"))
        meshed = Trainer(parse_config(os.path.join(ws, "cfg.py")), flags_m).generate()
    finally:
        os.chdir(cwd)
        sys.path.remove(ws)

    assert len(plain) == len(meshed) > 0
    for (ids_a, beams_a, scores_a, _), (ids_b, beams_b, scores_b, _) in zip(plain, meshed):
        np.testing.assert_array_equal(ids_a, ids_b)
        np.testing.assert_array_equal(beams_a, beams_b)
        np.testing.assert_allclose(scores_a, scores_b, rtol=1e-5, atol=1e-6)
    assert open(os.path.join(ws, "plain.txt")).read() == open(
        os.path.join(ws, "meshed.txt")
    ).read()


def test_generation_sequence_memory_beam_search_runs():
    """Beam width > 1: beams carry independent sequence memories; shapes
    and finiteness only (numpy beam rollout is covered by greedy above +
    the static beam tests elsewhere)."""
    B, T, K = 2, 3, 3
    rng = np.random.RandomState(5)
    boot = rng.randn(B, T, E).astype(np.float32)
    lens = np.array([3, 2], np.int32)
    src = GEN_SEQ_MEM.replace("beam_size=1", f"beam_size={K}")
    tc = parse_str(src)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=2)
    out, _ = gm.forward(
        params, {"boot": make_seq(jnp.asarray(boot), jnp.asarray(lens))}, "gen"
    )
    beams = out["gen@beams"]
    assert beams.ids.shape[:2] == (B, K)
    scores = np.asarray(beams.value)
    assert np.all(np.isfinite(scores[:, 0]))  # best beam always finite
    # beams are distinct hypotheses: per-sample top beam outscores the rest
    assert np.all(scores[:, 0] >= scores[:, 1:].max(axis=1) - 1e-6)


GEN_MP_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "").replace("--xla_force_host_platform_device_count=8", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
sys.path.insert(0, {repo!r})
sys.path.insert(0, {ws!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as _xb
for _n in list(_xb._backend_factories):
    if _n not in ("cpu", "tpu"):
        del _xb._backend_factories[_n]

pid = int(sys.argv[1])
jax.distributed.initialize(coordinator_address="localhost:" + sys.argv[2],
                           num_processes=2, process_id=pid)
assert len(jax.devices()) == 8

os.chdir({ws!r})
from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import _Flags

flags = _Flags(seed=3, mesh_shape="data=8",
               gen_result=os.path.join({ws!r}, "mp_gen.txt"))
Trainer(parse_config(os.path.join({ws!r}, "cfg.py")), flags).generate()
print("WORKER_OK", pid, flush=True)
"""


def test_generate_job_two_process_matches_single(tmp_path):
    """generate() in a REAL two-process run: collectives + gather + single
    writer; the result file must match a single-process run bit-for-bit
    (params are deterministic from the seed — no training involved).
    Skips (capability probe) where the backend cannot compile
    cross-process device computations — the generation forward spans
    both processes' devices."""
    import socket
    import subprocess
    import sys

    import mp_harness

    mp_harness.skip_unless_cross_process_computations()

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    ws = str(tmp_path)
    (tmp_path / "cfg.py").write_text(GEN_JOB_CFG)
    (tmp_path / "genprov.py").write_text(GEN_PROV)
    (tmp_path / "test.list").write_text("7\n")

    cwd = os.getcwd()
    sys.path.insert(0, ws)
    os.chdir(ws)
    try:
        cfg = parse_config(os.path.join(ws, "cfg.py"))
        flags = _Flags(seed=3, gen_result=os.path.join(ws, "single_gen.txt"))
        Trainer(cfg, flags).generate()
    finally:
        os.chdir(cwd)
        sys.path.remove(ws)

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker_py = os.path.join(ws, "gen_worker.py")
    with open(worker_py, "w") as f:
        f.write(GEN_MP_WORKER.format(repo=REPO, ws=ws))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker_py, str(i), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err[-3000:]
        assert "WORKER_OK" in out, (out, err[-2000:])

    single = open(os.path.join(ws, "single_gen.txt")).read()
    multi = open(os.path.join(ws, "mp_gen.txt")).read()
    assert single and single == multi
