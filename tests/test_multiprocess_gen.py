"""Multi-process GENERATION — the remaining inference surface under a
cross-process mesh: two processes form one 8-device mesh, run the gen job
(globalize + pad the batch, shard the forward, gather results on the
writer process), and the result file must equal the single-process run's.
"""

import os
import sys
import textwrap

import mp_harness

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = """
from paddle_tpu.trainer_config_helpers import *
define_py_data_sources2(train_list=None, test_list={test_list!r},
                        module="genprov", obj="gen_process")
settings(batch_size=8, learning_rate=0.0)
src = data_layer(name="src", size=11)
def gen_step(x_t, prev):
    e = embedding_layer(input=x_t, size=6, name="src_emb",
                        param_attr=ParamAttr(name="Tsrc"))
    h = concat_layer(input=[e, prev], name="h")
    return fc_layer(input=h, size=9, act=SoftmaxActivation(), name="scorer")
out = beam_search(step=gen_step,
                  input=[src, GeneratedInput(size=9, embedding_name="Tgen",
                                             embedding_size=6)],
                  bos_id=0, eos_id=8, beam_size=2, max_length=6, name="gen")
"""

GEN_PROV = """
import random
from paddle_tpu.data import integer_value_sequence, provider

@provider(input_types={"src": integer_value_sequence(11)})
def gen_process(settings, file_name):
    rng = random.Random(int(file_name))
    for _ in range(16):
        n = rng.randint(3, 5)
        yield {"src": [rng.randint(2, 10) for _ in range(n)]}
"""

# providers dir = the workspace itself (the gen provider is written there)
WORKER = mp_harness.WORKER_PREAMBLE + """
from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

os.chdir(ws)
FLAGS.save_dir = ""
FLAGS.mesh_shape = "data=8"
FLAGS.log_period = 0
FLAGS.seed = 5
FLAGS.gen_result = os.path.join(ws, "mp.txt")
Trainer(parse_config(os.path.join(ws, "cfg.py"))).generate()
print("WORKER_OK", pid, flush=True)
"""


def test_two_process_generation_matches_single(tmp_path):
    mp_harness.skip_unless_cross_process_computations()
    ws = str(tmp_path)
    test_list = os.path.join(ws, "test.list")
    with open(test_list, "w") as f:
        f.write("7\n")
    with open(os.path.join(ws, "cfg.py"), "w") as f:
        f.write(textwrap.dedent(CONFIG.format(test_list=test_list)))
    with open(os.path.join(ws, "genprov.py"), "w") as f:
        f.write(textwrap.dedent(GEN_PROV))

    # single-process reference (same 8-device mesh)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    sys.path.insert(0, ws)
    cwd = os.getcwd()
    os.chdir(ws)
    try:
        flags = _Flags(seed=5, mesh_shape="data=8",
                       gen_result=os.path.join(ws, "plain.txt"))
        Trainer(parse_config(os.path.join(ws, "cfg.py")), flags).generate()
    finally:
        os.chdir(cwd)
        sys.path.remove(ws)

    mp_harness.run_two_workers(WORKER.format(repo=REPO, providers=ws), ws)

    plain = open(os.path.join(ws, "plain.txt")).read()
    mp = open(os.path.join(ws, "mp.txt")).read()
    assert plain and plain == mp
