"""Multi-process GENERATION — the remaining inference surface under a
cross-process mesh: two processes form one 8-device mesh, run the gen job
(globalize + pad the batch, shard the forward, gather results on the
writer process), and the result file must equal the single-process run's.
"""

import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = """
from paddle_tpu.trainer_config_helpers import *
define_py_data_sources2(train_list=None, test_list={test_list!r},
                        module="genprov", obj="gen_process")
settings(batch_size=8, learning_rate=0.0)
src = data_layer(name="src", size=11)
def gen_step(x_t, prev):
    e = embedding_layer(input=x_t, size=6, name="src_emb",
                        param_attr=ParamAttr(name="Tsrc"))
    h = concat_layer(input=[e, prev], name="h")
    return fc_layer(input=h, size=9, act=SoftmaxActivation(), name="scorer")
out = beam_search(step=gen_step,
                  input=[src, GeneratedInput(size=9, embedding_name="Tgen",
                                             embedding_size=6)],
                  bos_id=0, eos_id=8, beam_size=2, max_length=6, name="gen")
"""

GEN_PROV = """
import random
from paddle_tpu.data import integer_value_sequence, provider

@provider(input_types={"src": integer_value_sequence(11)})
def gen_process(settings, file_name):
    rng = random.Random(int(file_name))
    for _ in range(16):
        n = rng.randint(3, 5)
        yield {"src": [rng.randint(2, 10) for _ in range(n)]}
"""

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "").replace("--xla_force_host_platform_device_count=8", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
sys.path.insert(0, {repo!r})
ws = sys.argv[3]
sys.path.insert(0, ws)
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as _xb
for _n in list(_xb._backend_factories):
    if _n not in ("cpu", "tpu"):
        del _xb._backend_factories[_n]

pid = int(sys.argv[1])
jax.distributed.initialize(coordinator_address="localhost:" + sys.argv[2],
                           num_processes=2, process_id=pid)
assert len(jax.devices()) == 8

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

os.chdir(ws)
FLAGS.save_dir = ""
FLAGS.mesh_shape = "data=8"
FLAGS.log_period = 0
FLAGS.seed = 5
FLAGS.gen_result = os.path.join(ws, "mp.txt")
Trainer(parse_config(os.path.join(ws, "cfg.py"))).generate()
print("WORKER_OK", pid, flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_generation_matches_single(tmp_path):
    ws = str(tmp_path)
    test_list = os.path.join(ws, "test.list")
    with open(test_list, "w") as f:
        f.write("7\n")
    with open(os.path.join(ws, "cfg.py"), "w") as f:
        f.write(textwrap.dedent(CONFIG.format(test_list=test_list)))
    with open(os.path.join(ws, "genprov.py"), "w") as f:
        f.write(textwrap.dedent(GEN_PROV))

    # single-process reference (same 8-device mesh)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    sys.path.insert(0, ws)
    cwd = os.getcwd()
    os.chdir(ws)
    try:
        flags = _Flags(seed=5, mesh_shape="data=8",
                       gen_result=os.path.join(ws, "plain.txt"))
        Trainer(parse_config(os.path.join(ws, "cfg.py")), flags).generate()
    finally:
        os.chdir(cwd)
        sys.path.remove(ws)

    port = _free_port()
    worker_py = os.path.join(ws, "worker.py")
    with open(worker_py, "w") as f:
        f.write(WORKER.format(repo=REPO))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker_py, str(i), str(port), ws],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, err[-3000:]
        assert "WORKER_OK" in out, (out, err[-2000:])

    plain = open(os.path.join(ws, "plain.txt")).read()
    mp = open(os.path.join(ws, "mp.txt")).read()
    assert plain and plain == mp
