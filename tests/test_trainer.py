"""End-to-end Trainer tests — the Milestone A slice (SURVEY.md §7 stage 3):
a quick_start-style config trains through provider → Trainer → checkpoint,
and quality reaches the expected range.
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer, checkpoint
from paddle_tpu.utils.flags import FLAGS

PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    yield
    sys.path.remove(PROVIDER_DIR)


def write_lists(tmp_path):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n3\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    return str(train_list), str(test_list)


def lr_config(tmp_path):
    train_list, test_list = write_lists(tmp_path)
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={train_list!r}, test_list={test_list!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02, learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    cls = classification_cost(input=output, label=label)
    outputs(cls)
    """)
    cfg_path = tmp_path / "lr_config.py"
    cfg_path.write_text(src)
    return str(cfg_path)


def test_lr_trains_end_to_end(tmp_path):
    cfg = parse_config(lr_config(tmp_path))
    FLAGS.save_dir = str(tmp_path / "out")
    FLAGS.num_passes = 3
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    trainer = Trainer(cfg)
    trainer.train(num_passes=3)
    results = trainer.test()
    err = [v for k, v in results.items() if "classification_error" in k][0]
    assert err < 0.1, f"LR failed to learn: error={err}"
    # checkpoints exist and load back
    last = checkpoint.latest_pass(str(tmp_path / "out"))
    assert last == 2
    params, opt_state, meta = checkpoint.load_checkpoint(
        os.path.join(str(tmp_path / "out"), checkpoint.PASS_FMT % last),
        trainer.opt_state,
    )
    assert set(params) == set(trainer.params)
    assert opt_state is not None and int(opt_state.step) > 0


def test_resume_from_checkpoint(tmp_path):
    cfg = parse_config(lr_config(tmp_path))
    FLAGS.save_dir = str(tmp_path / "out")
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    t1 = Trainer(cfg)
    t1.train(num_passes=1)
    FLAGS.start_pass = 1
    t2 = Trainer(cfg)
    np.testing.assert_allclose(
        np.asarray(t1.params["_output.w0"]), np.asarray(t2.params["_output.w0"])
    )
    assert int(t2.opt_state.step) == int(t1.opt_state.step)
    t2.train(num_passes=2)
    FLAGS.start_pass = 0


def test_checkgrad_job(tmp_path):
    cfg = parse_config(lr_config(tmp_path))
    FLAGS.save_dir = ""
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    trainer = Trainer(cfg)
    assert trainer.check_gradient(max_entries=5)


def test_lstm_sequence_trains(tmp_path):
    train_list, test_list = write_lists(tmp_path)
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={train_list!r}, test_list={test_list!r},
                            module="synthetic_bow", obj="process_seq")
    settings(batch_size=32, learning_rate=0.01, learning_method=AdamOptimizer())
    words = data_layer(name="words", size=100)
    emb = embedding_layer(input=words, size=16)
    lstm = simple_lstm(input=emb, size=16)
    pool = pooling_layer(input=lstm, pooling_type=MaxPooling())
    output = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "lstm_config.py"
    cfg_path.write_text(src)
    cfg = parse_config(str(cfg_path))
    FLAGS.save_dir = ""
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    trainer = Trainer(cfg)
    trainer.train(num_passes=3)
    results = trainer.test()
    err = [v for k, v in results.items() if "classification_error" in k][0]
    assert err < 0.15, f"LSTM failed to learn: error={err}"


def test_remat_full_matches_plain_gradients():
    """settings(remat="full") wraps the loss in jax.checkpoint — backward
    recomputes the forward; gradients must match the stored-activation
    path exactly (same math, different schedule)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.flagship import example_batch, flagship_config
    from paddle_tpu.graph import GradientMachine

    tc = flagship_config()
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=1)
    batch = example_batch(B=4, T=8)
    rng = jax.random.PRNGKey(0)
    loss_a, grads_a, _, _ = jax.jit(gm.grad_fn(remat="none"))(params, batch, rng)
    loss_b, grads_b, _, _ = jax.jit(gm.grad_fn(remat="full"))(params, batch, rng)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    for k in grads_a:
        np.testing.assert_allclose(
            np.asarray(grads_a[k]), np.asarray(grads_b[k]), rtol=1e-6, atol=1e-7,
            err_msg=k,
        )
    import pytest

    with pytest.raises(ValueError):
        gm.grad_fn(remat="bogus")


def test_multi_pass_test_job(tmp_path, caplog):
    """--job=test --test_pass=0 evaluates every saved checkpoint in
    sequence (the reference Tester's pass-by-pass mode)."""
    import logging

    from paddle_tpu import cli

    cfg_path = lr_config(tmp_path)
    FLAGS.save_dir = str(tmp_path / "out")
    FLAGS.num_passes = 3
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    Trainer(parse_config(cfg_path)).train(num_passes=3)

    # the paddle_tpu logger doesn't propagate (own stderr handler) —
    # attach caplog's handler directly to count per-pass evaluations
    from paddle_tpu.utils.logging import logger as ptu_logger

    ptu_logger.addHandler(caplog.handler)
    FLAGS.test_pass = 0
    try:
        with caplog.at_level(logging.INFO, logger="paddle_tpu"):
            rc = cli.main(["test", f"--config={cfg_path}",
                           f"--save_dir={tmp_path / 'out'}",
                           "--num_passes=3", "--test_pass=0"])
    finally:
        FLAGS.test_pass = -1
        ptu_logger.removeHandler(caplog.handler)
    assert rc == 0
    # all three saved passes actually evaluated
    evaluated = [r for r in caplog.records if "Test (pass" in r.getMessage()]
    assert len(evaluated) == 3, [r.getMessage() for r in caplog.records][-10:]
