"""Smoke + gradient tests for the core layer/machine stack.

Mirrors the reference's test_LayerGrad methodology
(/root/reference/paddle/gserver/tests/test_LayerGrad.cpp): build a small
graph, compare analytic gradients to finite differences.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.graph import Argument, GradientMachine, make_dense, make_ids, make_seq
from paddle_tpu.proto import (
    LayerConfig,
    LayerInputConfig,
    ModelConfig,
    ParameterConfig,
)


def tiny_mlp_config(in_dim=6, hidden=8, classes=3) -> ModelConfig:
    m = ModelConfig()
    m.layers.append(LayerConfig(name="input", type="data", size=in_dim))
    m.layers.append(
        LayerConfig(
            name="hidden",
            type="fc",
            size=hidden,
            active_type="tanh",
            inputs=[LayerInputConfig(input_layer_name="input", input_parameter_name="w0")],
            bias_parameter_name="b0",
        )
    )
    m.layers.append(
        LayerConfig(
            name="output",
            type="fc",
            size=classes,
            active_type="softmax",
            inputs=[LayerInputConfig(input_layer_name="hidden", input_parameter_name="w1")],
            bias_parameter_name="b1",
        )
    )
    m.layers.append(LayerConfig(name="label", type="data", size=classes))
    m.layers.append(
        LayerConfig(
            name="cost",
            type="multi-class-cross-entropy",
            size=1,
            inputs=[
                LayerInputConfig(input_layer_name="output"),
                LayerInputConfig(input_layer_name="label"),
            ],
        )
    )
    m.parameters += [
        ParameterConfig(name="w0", size=in_dim * hidden, dims=[in_dim, hidden], initial_std=0.5),
        ParameterConfig(name="b0", size=hidden, dims=[hidden], initial_std=0.0),
        ParameterConfig(name="w1", size=hidden * classes, dims=[hidden, classes], initial_std=0.5),
        ParameterConfig(name="b1", size=classes, dims=[classes], initial_std=0.0),
    ]
    m.input_layer_names += ["input", "label"]
    m.output_layer_names += ["cost"]
    return m


def make_batch(b=4, in_dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "input": make_dense(jnp.asarray(rng.randn(b, in_dim), jnp.float32)),
        "label": make_ids(jnp.asarray(rng.randint(0, classes, (b,)))),
    }


def test_mlp_forward_loss():
    model = tiny_mlp_config()
    gm = GradientMachine(model)
    params = gm.init_params(seed=1)
    outputs, _ = gm.forward(params, make_batch(), pass_type="test")
    probs = outputs["output"].value
    assert probs.shape == (4, 3)
    np.testing.assert_allclose(np.sum(np.asarray(probs), axis=-1), 1.0, rtol=1e-5)
    loss = gm.total_cost(outputs)
    assert float(loss) > 0.0


def test_mlp_gradient_check():
    model = tiny_mlp_config()
    gm = GradientMachine(model)
    params = gm.init_params(seed=1)
    report = gm.check_gradient(params, make_batch(), epsilon=1e-3, max_entries=8)
    for name, diff in report.items():
        assert diff < 5e-2, f"gradient mismatch for {name}: {diff}"


def test_training_reduces_loss():
    model = tiny_mlp_config()
    gm = GradientMachine(model)
    params = gm.init_params(seed=1)
    batch = make_batch(b=16)
    f = jax.jit(lambda p: gm.loss_fn(p, batch, None)[0])
    g = jax.jit(jax.grad(lambda p: gm.loss_fn(p, batch, None)[0]))
    l0 = float(f(params))
    for _ in range(30):
        grads = g(params)
        params = {k: v - 0.5 * grads[k] for k, v in params.items()}
    l1 = float(f(params))
    assert l1 < l0 * 0.7, (l0, l1)


def test_lstm_forward_and_grad():
    hidden = 4
    m = ModelConfig()
    m.layers.append(LayerConfig(name="input", type="data", size=4 * hidden))
    m.layers.append(
        LayerConfig(
            name="lstm",
            type="lstmemory",
            size=hidden,
            active_type="tanh",
            active_gate_type="sigmoid",
            active_state_type="sigmoid",
            inputs=[LayerInputConfig(input_layer_name="input", input_parameter_name="w_r")],
            bias_parameter_name="b_r",
        )
    )
    m.layers.append(
        LayerConfig(
            name="pool",
            type="seqlastins",
            size=hidden,
            inputs=[LayerInputConfig(input_layer_name="lstm")],
        )
    )
    m.layers.append(LayerConfig(name="label", type="data", size=hidden))
    m.layers.append(
        LayerConfig(
            name="cost",
            type="square_error",
            size=1,
            inputs=[
                LayerInputConfig(input_layer_name="pool"),
                LayerInputConfig(input_layer_name="label"),
            ],
        )
    )
    m.parameters += [
        ParameterConfig(name="w_r", size=hidden * hidden * 4, dims=[hidden, 4 * hidden], initial_std=0.3),
        ParameterConfig(name="b_r", size=7 * hidden, dims=[7 * hidden], initial_std=0.0),
    ]
    m.input_layer_names += ["input", "label"]
    m.output_layer_names += ["cost"]
    gm = GradientMachine(m)
    params = gm.init_params(seed=3)
    rng = np.random.RandomState(0)
    B, T = 3, 5
    lengths = np.array([5, 3, 1], np.int32)
    x = rng.randn(B, T, 4 * hidden).astype(np.float32)
    batch = {
        "input": make_seq(jnp.asarray(x), jnp.asarray(lengths)),
        "label": make_dense(jnp.asarray(rng.randn(B, hidden), jnp.float32)),
    }
    outputs, _ = gm.forward(params, batch, pass_type="test")
    y = np.asarray(outputs["lstm"].value)
    # padded timesteps must be zeroed
    assert np.all(y[1, 3:] == 0.0) and np.all(y[2, 1:] == 0.0)
    report = gm.check_gradient(params, batch, epsilon=1e-3, max_entries=6)
    for name, diff in report.items():
        assert diff < 5e-2, f"gradient mismatch for {name}: {diff}"


def test_lstm_padding_invariance():
    """Same sequences with different padding amounts give the same states."""
    hidden = 4
    from paddle_tpu.proto import LayerConfig as LC, LayerInputConfig as LIC

    m = ModelConfig()
    m.layers.append(LC(name="input", type="data", size=4 * hidden))
    m.layers.append(
        LC(
            name="lstm",
            type="lstmemory",
            size=hidden,
            active_type="tanh",
            inputs=[LIC(input_layer_name="input", input_parameter_name="w_r")],
            bias_parameter_name="b_r",
        )
    )
    m.parameters += [
        ParameterConfig(name="w_r", size=hidden * hidden * 4, dims=[hidden, 4 * hidden], initial_std=0.3),
        ParameterConfig(name="b_r", size=7 * hidden, dims=[7 * hidden], initial_std=0.1),
    ]
    m.input_layer_names += ["input"]
    m.output_layer_names += ["lstm"]
    gm = GradientMachine(m)
    params = gm.init_params(seed=3)
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 4 * hidden).astype(np.float32)
    lengths = np.array([4, 2], np.int32)
    out1, _ = gm.forward(params, {"input": make_seq(jnp.asarray(x), jnp.asarray(lengths))}, "test")
    x_padded = np.concatenate([x, np.zeros((2, 3, 4 * hidden), np.float32)], axis=1)
    out2, _ = gm.forward(params, {"input": make_seq(jnp.asarray(x_padded), jnp.asarray(lengths))}, "test")
    np.testing.assert_allclose(
        np.asarray(out1["lstm"].value), np.asarray(out2["lstm"].value)[:, :4], rtol=1e-5, atol=1e-6
    )


def test_concat2_projects_then_concatenates():
    """ConcatenateLayer2 (ref ConcatenateLayer.cpp:95): concat of per-input
    projection outputs (mixed sums them; concat2 concatenates)."""
    import jax.numpy as jnp

    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.graph import GradientMachine, make_dense
    from paddle_tpu.trainer_config_helpers import (
        LinearActivation,
        concat_layer,
        data_layer,
        full_matrix_projection,
        identity_projection,
        outputs,
        settings,
    )

    with fresh_context() as ctx:
        settings(batch_size=4, learning_rate=0.1)
        a = data_layer(name="a", size=5)
        b = data_layer(name="b", size=3)
        out = concat_layer(
            input=[full_matrix_projection(a, size=6), identity_projection(b)],
            act=LinearActivation(), name="cc2",
        )
        outputs(out)
        tc = ctx.finalize()

    lm = {l.name: l for l in tc.model_config.layers}
    assert lm["cc2"].type == "concat2"
    assert lm["cc2"].size == 9
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=2)
    rng = np.random.RandomState(0)
    xa = rng.randn(4, 5).astype(np.float32)
    xb = rng.randn(4, 3).astype(np.float32)
    outs, _ = gm.forward(params, {"a": make_dense(xa), "b": make_dense(xb)}, "test")
    got = np.asarray(outs["cc2"].value)
    w = np.asarray(params["_cc2.w0"])
    np.testing.assert_allclose(got[:, :6], xa @ w, rtol=1e-5)
    np.testing.assert_allclose(got[:, 6:], xb, rtol=1e-6)



def test_concat2_context_and_offset_sizes():
    """concat2 size inference covers context and offset-identity
    projections (review finding: p.size fallback mis-sized them)."""
    import jax.numpy as jnp

    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.graph import GradientMachine, make_seq
    from paddle_tpu.trainer_config_helpers import (
        LinearActivation,
        concat_layer,
        context_projection,
        data_layer,
        identity_projection,
        outputs,
        settings,
    )

    with fresh_context() as ctx:
        settings(batch_size=2, learning_rate=0.1)
        a = data_layer(name="a", size=5)
        out = concat_layer(
            input=[
                context_projection(a, context_len=3),
                identity_projection(a, offset=2),
            ],
            act=LinearActivation(), name="cc",
        )
        outputs(out)
        tc = ctx.finalize()

    lm = {l.name: l for l in tc.model_config.layers}
    assert lm["cc"].size == 5 * 3 + (5 - 2), lm["cc"].size
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=1)
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 5).astype(np.float32)
    lens = np.array([4, 3], np.int32)
    outs, _ = gm.forward(
        params, {"a": make_seq(jnp.asarray(x), jnp.asarray(lens))}, "test"
    )
    got = np.asarray(outs["cc"].value)
    assert got.shape == (2, 4, 18), got.shape
    # offset-identity slice: columns 2..5 of the input
    np.testing.assert_allclose(got[:, :, 15:], x[:, :, 2:], rtol=1e-6)


def test_error_clipping_threshold_clips_backward_only():
    """ExtraAttr(error_clipping_threshold): identity forward, cotangent
    clipped at the layer output on backward (ref Layer.cpp errorClip)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.graph import GradientMachine, make_dense
    from paddle_tpu.trainer_config_helpers import (
        ExtraAttr,
        LinearActivation,
        data_layer,
        fc_layer,
        outputs,
        regression_cost,
        settings,
    )

    def build(clip):
        with fresh_context() as ctx:
            settings(batch_size=2, learning_rate=0.1)
            x = data_layer(name="x", size=3)
            h = fc_layer(input=x, size=4, act=LinearActivation(), name="h",
                         layer_attr=ExtraAttr(error_clipping_threshold=clip) if clip else None)
            y = fc_layer(input=h, size=1, act=LinearActivation(), name="y")
            t = data_layer(name="t", size=1)
            outputs(regression_cost(input=y, label=t))
            return ctx.finalize()

    rng = np.random.RandomState(0)
    batch = {
        "x": make_dense(rng.randn(2, 3).astype(np.float32)),
        # huge targets -> large backward error through h
        "t": make_dense(np.full((2, 1), 1000.0, np.float32)),
    }

    grads = {}
    fwd = {}
    for clip in (0.0, 1e-4):
        tc = build(clip)
        gm = GradientMachine(tc.model_config)
        params = gm.init_params(seed=3)
        loss, g, outs, _ = jax.jit(gm.grad_fn())(params, batch, None)
        grads[clip] = g
        fwd[clip] = float(loss)
    # forward identical; upstream (h-side) gradients shrink under the clip
    np.testing.assert_allclose(fwd[0.0], fwd[1e-4], rtol=1e-6)
    g_plain = np.abs(np.asarray(grads[0.0]["_h.w0"])).max()
    g_clip = np.abs(np.asarray(grads[1e-4]["_h.w0"])).max()
    assert g_clip < g_plain * 1e-2, (g_plain, g_clip)
    # downstream (y-side) gradients are NOT affected by h's clip
    np.testing.assert_allclose(
        np.asarray(grads[0.0]["_y.w0"]), np.asarray(grads[1e-4]["_y.w0"]), rtol=1e-5
    )


def test_pooling_trans_type_levels_on_nested():
    """AggregateLevel semantics on nested input (ref SequencePoolLayer,
    SequenceLastInstanceLayer.cpp:76): 'non-seq' aggregates the whole
    outer sequence (one row per sample); 'seq' aggregates per
    subsequence (plain sequence out)."""
    import jax.numpy as jnp

    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.argument import Argument
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        AggregateLevel,
        AvgPooling,
        data_layer,
        last_seq,
        outputs,
        pooling_layer,
        settings,
    )

    B, S, T, D = 2, 3, 4, 5
    rng = np.random.RandomState(7)
    x = rng.randn(B, S, T, D).astype(np.float32)
    n_subs = np.array([3, 2], np.int32)
    sub_lens = np.array([[4, 2, 3], [1, 4, 0]], np.int32)

    def build(agg):
        with fresh_context() as ctx:
            settings(batch_size=2, learning_rate=0.1)
            a = data_layer(name="a", size=D)
            p = pooling_layer(input=a, pooling_type=AvgPooling(),
                              agg_level=agg, name="pool")
            l = last_seq(input=a, agg_level=agg, name="last")
            outputs(p)
            outputs(l)
            return ctx.finalize()

    batch = {
        "a": Argument(value=jnp.asarray(x), seq_lengths=jnp.asarray(n_subs),
                      sub_seq_lengths=jnp.asarray(sub_lens)),
    }

    # 'seq': per-subsequence
    tc = build(AggregateLevel.EACH_SEQUENCE)
    gm = GradientMachine(tc.model_config)
    outs, _ = gm.forward(gm.init_params(seed=1), batch, "test")
    got = np.asarray(outs["pool"].value)  # [B, S, D]
    for b in range(B):
        for s_i in range(n_subs[b]):
            l = sub_lens[b, s_i]
            if l:
                np.testing.assert_allclose(got[b, s_i], x[b, s_i, :l].mean(0),
                                           rtol=1e-5)
    last = np.asarray(outs["last"].value)
    np.testing.assert_allclose(last[0, 0], x[0, 0, 3], rtol=1e-6)  # len 4

    # 'non-seq': whole outer sequence
    tc = build(AggregateLevel.EACH_TIMESTEP)
    gm = GradientMachine(tc.model_config)
    outs, _ = gm.forward(gm.init_params(seed=1), batch, "test")
    got = np.asarray(outs["pool"].value)  # [B, D]
    for b in range(B):
        toks = np.concatenate(
            [x[b, s_i, : sub_lens[b, s_i]] for s_i in range(n_subs[b])], axis=0
        )
        np.testing.assert_allclose(got[b], toks.mean(0), rtol=1e-5, err_msg=str(b))
    last = np.asarray(outs["last"].value)  # [B, D]
    np.testing.assert_allclose(last[0], x[0, 2, 2], rtol=1e-6)  # last sub len 3
    np.testing.assert_allclose(last[1], x[1, 1, 3], rtol=1e-6)  # last sub len 4


def test_last_instance_skips_empty_subsequences():
    """seqlastins with 'non-seq' on a nested input returns the last token
    of the last NON-EMPTY subsequence, not padding (review finding)."""
    import jax.numpy as jnp

    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.argument import Argument
    from paddle_tpu.trainer_config_helpers import (
        data_layer,
        first_seq,
        last_seq,
        outputs,
        settings,
    )

    B, S, T, D = 2, 3, 3, 4
    rng = np.random.RandomState(9)
    x = rng.randn(B, S, T, D).astype(np.float32)
    n_subs = np.array([3, 2], np.int32)
    sub_lens = np.array([[2, 0, 0], [0, 3, 0]], np.int32)  # trailing/leading empties

    with fresh_context() as ctx:
        settings(batch_size=2, learning_rate=0.1)
        a = data_layer(name="a", size=D)
        outputs(last_seq(input=a, name="last"))
        outputs(first_seq(input=a, name="first"))
        tc = ctx.finalize()

    gm = GradientMachine(tc.model_config)
    outs, _ = gm.forward(
        gm.init_params(seed=1),
        {"a": Argument(value=jnp.asarray(x), seq_lengths=jnp.asarray(n_subs),
                       sub_seq_lengths=jnp.asarray(sub_lens))},
        "test",
    )
    last = np.asarray(outs["last"].value)
    np.testing.assert_allclose(last[0], x[0, 0, 1], rtol=1e-6)  # subs 1,2 empty
    np.testing.assert_allclose(last[1], x[1, 1, 2], rtol=1e-6)
    fst = np.asarray(outs["first"].value)
    np.testing.assert_allclose(fst[0], x[0, 0, 0], rtol=1e-6)
    np.testing.assert_allclose(fst[1], x[1, 1, 0], rtol=1e-6)  # sub 0 empty


def test_scan_unroll_parity():
    """scan_unroll is a pure scheduling knob: loss and gradients are
    unchanged (same ops, unrolled k steps per scan iteration)."""
    from paddle_tpu.flagship import example_batch, flagship_config

    tc = flagship_config(dict_dim=50, emb_dim=8, hidden=8)
    batch = example_batch(dict_dim=50, B=4, T=11)
    results = []
    for unroll in (1, 4):
        gm = GradientMachine(tc.model_config, scan_unroll=unroll)
        params = gm.init_params(seed=5)
        loss, grads = jax.value_and_grad(lambda p: gm.loss_fn(p, batch, None)[0])(params)
        results.append((float(loss), grads))
    (l1, g1), (l4, g4) = results
    assert np.isclose(l1, l4, rtol=1e-6), (l1, l4)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g4[k]), rtol=1e-5, atol=1e-7)
