"""Fused Pallas GRU kernel parity vs the XLA scan path (interpret mode) —
the gated_recurrent analog of test_pallas_lstm.py: forward + hand-derived
gradients against jax.grad of the production scan, masked/reversed/bias
cases, plus a machine-level check through a DSL-built GRU model.
"""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.graph  # noqa: F401  (break the layers<->graph import cycle)
from paddle_tpu.layers.recurrent import _scan_time, gru_cell_step
from paddle_tpu.ops import pallas_gru as pg


def _cfg(reversed_=False, act="tanh", gate="sigmoid", size=128):
    return types.SimpleNamespace(
        size=size, reversed=reversed_, active_type=act, active_gate_type=gate
    )


def _ref(cfg, x, mask, w, bias):
    def cell(h, x_t):
        h2 = gru_cell_step(cfg, x_t, h, w, bias)
        return h2, h2

    B = x.shape[1]
    h0 = jnp.zeros((B, cfg.size), x.dtype)
    _, ys = _scan_time(cell, x, mask, h0, cfg.reversed)
    return ys


def _rand(key, T=5, B=8, H=128, dtype=jnp.float32, with_bias=True):
    ks = jax.random.split(key, 4)
    x = jax.random.normal(ks[0], (T, B, 3 * H), dtype) * 0.5
    w = (jax.random.normal(ks[1], (H, 3 * H), dtype) * float(1.0 / np.sqrt(H))).astype(dtype)
    bias = (jax.random.normal(ks[2], (3 * H,), dtype) * 0.1) if with_bias else None
    lengths = jax.random.randint(ks[3], (B,), 1, T + 1)
    mask = (jnp.arange(T)[:, None] < lengths[None, :]).astype(dtype)
    return x, w, bias, mask


@pytest.mark.parametrize("reversed_", [False, True])
@pytest.mark.parametrize("with_bias", [True, False])
def test_forward_parity(reversed_, with_bias):
    cfg = _cfg(reversed_=reversed_)
    x, w, bias, mask = _rand(jax.random.PRNGKey(0), with_bias=with_bias)
    ref = _ref(cfg, x, mask, w, bias)
    got = pg.gru_layer_forward(cfg, x, mask, w, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("reversed_", [False, True])
def test_gradient_parity(reversed_):
    cfg = _cfg(reversed_=reversed_)
    x, w, bias, mask = _rand(jax.random.PRNGKey(1))
    cot = jax.random.normal(jax.random.PRNGKey(2), (5, 8, 128))

    gr = jax.grad(
        lambda x, w, b: jnp.sum(_ref(cfg, x, mask, w, b) * cot), (0, 1, 2)
    )(x, w, bias)
    gp = jax.grad(
        lambda x, w, b: jnp.sum(
            pg.gru_layer_forward(cfg, x, mask, w, b, interpret=True) * cot
        ),
        (0, 1, 2),
    )(x, w, bias)
    for r, p, name in zip(gr, gp, ("dx", "dw", "dbias")):
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(r), rtol=2e-4, atol=2e-4, err_msg=name
        )


def test_bf16_forward_parity():
    cfg = _cfg()
    x, w, bias, mask = _rand(jax.random.PRNGKey(5), dtype=jnp.bfloat16)
    ref = _ref(cfg, x, mask, w, bias)
    got = pg.gru_layer_forward(cfg, x, mask, w, bias, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), rtol=0.1, atol=0.05
    )


def test_machine_level_parity(monkeypatch):
    # DSL-built GRU classifier: same params/batch, pallas on vs off
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.flagship import example_batch
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.trainer_config_helpers import (
        AdamOptimizer,
        MaxPooling,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        outputs,
        pooling_layer,
        settings,
        simple_gru,
    )

    with fresh_context() as ctx:
        settings(batch_size=16, learning_rate=1e-3, learning_method=AdamOptimizer())
        words = data_layer(name="words", size=200)
        emb = embedding_layer(input=words, size=32)
        gru = simple_gru(input=emb, size=128)
        pool = pooling_layer(input=gru, pooling_type=MaxPooling())
        out = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=2)
        outputs(classification_cost(input=out, label=label))
        tc = ctx.finalize()

    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, pallas_rnn=True)
    params = gm_off.init_params(seed=3)
    batch = example_batch(dict_dim=200, B=16, T=12)

    calls = []
    orig = pg.gru_layer_forward
    monkeypatch.setattr(
        pg, "gru_layer_forward",
        lambda *a, **k: (calls.append(1), orig(*a, **k))[1],
    )
    l_off, g_off, _, _ = gm_off.grad_fn()(params, batch, None)
    assert not calls  # pallas off → scan path
    l_on, g_on, _, _ = gm_on.grad_fn()(params, batch, None)
    assert calls  # the kernel path actually engaged
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-5)
    for k in g_off:
        np.testing.assert_allclose(
            np.asarray(g_on[k]), np.asarray(g_off[k]), rtol=5e-4, atol=5e-5,
            err_msg=k,
        )


def test_edge_lengths():
    cfg = _cfg()
    T, B, H = 3, 8, 128
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    x = jax.random.normal(ks[0], (T, B, 3 * H)) * 0.5
    w = jax.random.normal(ks[1], (H, 3 * H)) * 0.05
    bias = jax.random.normal(ks[2], (3 * H,)) * 0.1
    lengths = jnp.asarray([0, 1, 3, 2, 0, 3, 1, 2], jnp.int32)
    mask = (jnp.arange(T)[:, None] < lengths[None, :]).astype(x.dtype)
    ref = _ref(cfg, x, mask, w, bias)
    got = pg.gru_layer_forward(cfg, x, mask, w, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(got)[:, 0], 0.0)

    ref1 = _ref(cfg, x[:1], mask[:1], w, bias)
    got1 = pg.gru_layer_forward(cfg, x[:1], mask[:1], w, bias, interpret=True)
    np.testing.assert_allclose(np.asarray(got1), np.asarray(ref1), rtol=2e-5, atol=2e-5)


def test_unsupported_shapes_fall_back():
    assert not pg.usable(_cfg(size=96), jnp.zeros((4, 8, 288)))
    assert not pg.usable(_cfg(size=128), jnp.zeros((4, 6, 384)))  # B % 8
    assert pg.usable(_cfg(size=128), jnp.zeros((4, 8, 384)))
