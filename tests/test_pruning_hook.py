"""StaticPruningHook (ref: paddle/parameter/ParameterUpdaterHook.cpp:37):
a bitmask file disables weights; init masks values, update masks
gradients — sparsity is preserved across optimizer updates (momentum, L2
decay, L1 included).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.graph import GradientMachine, make_dense, make_ids
from paddle_tpu.optimizer import Updater
from paddle_tpu.optimizer.hooks import load_mask_file, write_mask_file


def test_mask_file_round_trip(tmp_path):
    rng = np.random.RandomState(0)
    for n in (5, 8, 29, 64):
        mask = rng.rand(n) < 0.5
        path = str(tmp_path / f"m{n}.mask")
        write_mask_file(path, mask)
        np.testing.assert_array_equal(load_mask_file(path), mask)


def _config(mask_path):
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        HookAttr,
        MomentumOptimizer,
        ParamAttr,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        fc_layer,
        outputs,
        settings,
    )

    with fresh_context() as ctx:
        settings(batch_size=8, learning_rate=0.1,
                 learning_method=MomentumOptimizer(0.9))
        x = data_layer(name="x", size=10)
        out = fc_layer(
            input=x, size=4, act=SoftmaxActivation(), name="out",
            param_attr=ParamAttr(
                name="w_pruned", l2_rate=1e-3,
                update_hooks=HookAttr(type="pruning", mask_filename=mask_path),
            ),
        )
        label = data_layer(name="label", size=4)
        outputs(classification_cost(input=out, label=label))
        return ctx.finalize()


def test_pruning_preserves_sparsity_through_training(tmp_path):
    rng = np.random.RandomState(1)
    mask = (rng.rand(10, 4) < 0.6).astype(np.float32)
    mask_path = str(tmp_path / "w.mask")
    write_mask_file(mask_path, mask)

    tc = _config(mask_path)
    gm = GradientMachine(tc.model_config)
    up = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=3)
    st = up.init_state(params)
    params = up.apply_init_hooks(params)
    # init hook: disabled weights are zero immediately
    w = np.asarray(params["w_pruned"])
    np.testing.assert_array_equal(w[mask == 0], 0.0)
    assert np.any(w[mask == 1] != 0.0)

    grad_fn = gm.grad_fn()

    @jax.jit
    def step(params, st, batch):
        loss, grads, _, _ = grad_fn(params, batch, None)
        return *up(params, grads, st, jnp.asarray(8.0)), loss

    batch = {
        "x": make_dense(rng.randn(8, 10).astype(np.float32)),
        "label": make_ids(rng.randint(0, 4, (8,)).astype(np.int32)),
    }
    before = np.asarray(params["w_pruned"]).copy()
    for _ in range(5):
        params, st, loss = step(params, st, batch)
    after = np.asarray(params["w_pruned"])
    # pruned entries exactly zero after momentum + L2 updates; live moved
    np.testing.assert_array_equal(after[mask == 0], 0.0)
    assert np.all(after[mask == 1] != before[mask == 1])
    assert np.isfinite(float(loss))


def test_pruning_mask_searched_in_init_model_path(tmp_path):
    """Reference ctor fallback: a bare filename resolves relative to
    --init_model_path when not found in cwd."""
    from paddle_tpu.optimizer.hooks import resolve_mask

    mask = np.ones((4, 2), np.float32)
    mask[0] = 0
    write_mask_file(str(tmp_path / "rel.mask"), mask)
    got = resolve_mask("rel.mask", (4, 2), init_model_path=str(tmp_path))
    np.testing.assert_array_equal(got, mask != 0)
