"""MultiDataProvider: ratio-mixed sub-providers through one batch path."""

import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


PROVIDER_SRC = '''
from paddle.trainer.PyDataProvider2 import *

@provider(input_types=[dense_vector(4), integer_value(2)])
def pos(settings, file_name):
    for i in range(int(file_name.split("-")[-1])):
        yield [1.0, 0.0, 0.0, float(i % 3)], 1

@provider(input_types=[dense_vector(4), integer_value(2)])
def neg(settings, file_name):
    for i in range(int(file_name.split("-")[-1])):
        yield [0.0, 1.0, 0.0, float(i % 3)], 0
'''


def test_multi_ratio_mixing(tmp_path):
    (tmp_path / "providers_multi.py").write_text(PROVIDER_SRC)
    (tmp_path / "pos.list").write_text("n-300\n")
    (tmp_path / "neg.list").write_text("n-300\n")
    (tmp_path / "conf.py").write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_multi_py_data_sources2(\n"
        "    train_lists=['pos.list', 'neg.list'],\n"
        "    module='providers_multi', obj=['pos', 'neg'], ratios=[3, 1])\n"
        "settings(batch_size=40, learning_rate=0.1)\n"
        "d = data_layer('x', size=4)\n"
        "out = fc_layer(input=d, size=2, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=out, label=data_layer('label', size=2)))\n"
    )
    from paddle_tpu.config import parse_config
    from paddle_tpu.data.feeder import create_data_provider

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("conf.py")
        assert cfg.data_config.type == "multi"
        assert [s.data_ratio for s in cfg.data_config.sub_data_configs] == [3, 1]
        # ordered (test-mode) stream keeps arrival order: while both
        # streams are live each mixing round is 3 pos + 1 neg
        provider = create_data_provider(
            cfg.data_config, cfg.opt_config.batch_size,
            cfg.model_config.input_layer_names, for_test=True,
        )
        labels = []
        for batch in provider.batches():
            labels.extend(np.asarray(batch["label"].ids).tolist())
        assert len(labels) == 600
        early = labels[:400]        # pos (300) exhausts at round 100
        frac_pos = sum(early) / len(early)
        assert frac_pos == 0.75, frac_pos
        assert set(labels[400:]) == {0}
    finally:
        os.chdir(cwd)


def test_multi_trains(tmp_path):
    (tmp_path / "providers_multi.py").write_text(PROVIDER_SRC)
    (tmp_path / "pos.list").write_text("n-200\n")
    (tmp_path / "neg.list").write_text("n-200\n")
    (tmp_path / "conf.py").write_text(
        "from paddle.trainer_config_helpers import *\n"
        "define_multi_py_data_sources2(\n"
        "    train_lists=['pos.list', 'neg.list'],\n"
        "    module='providers_multi', obj=['pos', 'neg'])\n"
        "settings(batch_size=32, learning_rate=0.5)\n"
        "d = data_layer('x', size=4)\n"
        "out = fc_layer(input=d, size=2, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=out, label=data_layer('label', size=2)))\n"
    )
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("conf.py")
        flags = _Flags(config="conf.py", num_passes=4, log_period=100, use_tpu=False)
        trainer = Trainer(cfg, flags)
        trainer.train()
        provider = trainer._provider(for_test=False)
        errs, total = 0.0, 0
        for batch in provider.batches():
            out = trainer.test_fwd(trainer.params, batch)
            errs += float(trainer.gm.total_cost(out)) * batch["label"].ids.shape[0]
            total += batch["label"].ids.shape[0]
        assert errs / total < 0.1, errs / total  # trivially separable
    finally:
        os.chdir(cwd)
