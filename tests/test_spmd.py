"""SPMD data parallelism on a virtual 8-device CPU mesh.

The analog of the reference's loopback-pserver distributed tests
(/root/reference/paddle/trainer/tests/test_TrainerOnePass.cpp:120-296
checkRemoteUpdater*): a sharded trainer must produce the same parameters as
the single-device trainer on the same data.
"""

import os
import sys
import textwrap

import jax
import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.parallel import make_mesh
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    FLAGS.save_dir = ""
    FLAGS.mesh_shape = ""
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.log_period = 0
    yield
    sys.path.remove(PROVIDER_DIR)
    FLAGS.mesh_shape = ""


def _lr_config(tmp_path, batch_size=64):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list={str(test_list)!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size={batch_size}, learning_rate=0.05)
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "lr_config.py"
    cfg_path.write_text(src)
    return parse_config(str(cfg_path))


def test_mesh_construction():
    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    mesh = make_mesh("data=8")
    assert mesh.shape == {"data": 8}
    mesh2 = make_mesh("data=4,model=2")
    assert mesh2.shape == {"data": 4, "model": 2}


def test_sharded_matches_single_device(tmp_path):
    cfg = _lr_config(tmp_path)
    t_single = Trainer(cfg)
    t_single.train(num_passes=1)

    FLAGS.mesh_shape = "data=8"
    t_sharded = Trainer(cfg)
    assert t_sharded._mesh is not None
    t_sharded.train(num_passes=1)
    FLAGS.mesh_shape = ""

    w1 = np.asarray(t_single.params["_output.w0"])
    w2 = np.asarray(t_sharded.params["_output.w0"])
    np.testing.assert_allclose(w1, w2, rtol=2e-4, atol=1e-5)

    r1 = t_single.test()
    r2 = t_sharded.test()
    err1 = [v for k, v in r1.items() if "classification_error" in k][0]
    err2 = [v for k, v in r2.items() if "classification_error" in k][0]
    assert abs(err1 - err2) < 0.02


def test_tensor_parallel_param_sharding(tmp_path):
    """Model-parallel parameter sharding via ParamAttr(sharding=...)."""
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=32, learning_rate=0.05, mesh_shape="data=4,model=2")
    data = data_layer(name="word", size=100)
    hidden = fc_layer(input=data, size=64, name="hidden",
                      param_attr=ParamAttr(sharding=[None, "model"]))
    output = fc_layer(input=hidden, size=2, act=SoftmaxActivation(), name="output",
                      param_attr=ParamAttr(sharding=["model", None]))
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "tp_config.py"
    cfg_path.write_text(src)
    cfg = parse_config(str(cfg_path))
    trainer = Trainer(cfg)
    assert trainer._mesh is not None
    trainer.train(num_passes=1)
    # the hidden weight should actually be sharded over the model axis
    w = trainer.params["_hidden.w0"]
    sh = w.sharding
    spec = getattr(sh, "spec", None)
    assert spec is not None and tuple(spec) == (None, "model"), spec


def test_three_axis_mesh_composed_sharding():
    """data=2 × model=2 × seq=2 in ONE train step: batch sharded over
    data, embedding + softmax weight over model, attention context over
    seq (ring) — the composed 64-chip layout at virtual scale, with bf16
    and remat on (the production stack)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.flagship import example_batch
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.machine import compute_dtype_of
    from paddle_tpu.optimizer import Updater
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.spmd import shard_train_step
    from paddle_tpu.trainer_config_helpers import (
        MaxPooling,
        ParamAttr,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        embedding_layer,
        fc_layer,
        multi_head_attention_layer,
        outputs,
        pooling_layer,
        settings,
    )

    def build(dtype="bfloat16", remat="full", mesh_shape="data=2,model=2,seq=2"):
        with fresh_context() as ctx:
            settings(batch_size=8, learning_rate=1e-3, dtype=dtype,
                     remat=remat, mesh_shape=mesh_shape)
            words = data_layer(name="words", size=300)
            emb = embedding_layer(
                input=words, size=32,
                param_attr=ParamAttr(name="emb", sharding=(None, "model")),
            )
            att = multi_head_attention_layer(
                input=emb, num_heads=4, causal=True, seq_parallel="ring", name="att"
            )
            pool = pooling_layer(input=att, pooling_type=MaxPooling())
            out = fc_layer(
                input=pool, size=4, act=SoftmaxActivation(), name="output",
                param_attr=ParamAttr(name="w_out", sharding=("model", None)),
            )
            label = data_layer(name="label", size=4)
            outputs(classification_cost(input=out, label=label))
            return ctx.finalize()

    losses = {}
    for key, (dtype, remat, mesh_shape) in {
        "plain": ("float32", "none", None),
        "3axis": ("bfloat16", "full", "data=2,model=2,seq=2"),
    }.items():
        tc = build(dtype, remat, mesh_shape or "")
        gm = GradientMachine(tc.model_config,
                             compute_dtype=compute_dtype_of(tc.opt_config))
        up = Updater(tc.opt_config, tc.model_config)
        params = gm.init_params(seed=6)
        opt_state = up.init_state(params)
        grad_fn = gm.grad_fn(remat=tc.opt_config.remat)

        def step(params, opt_state, batch, rng, bs):
            loss, grads, outs, su = grad_fn(params, batch, rng)
            new_params, new_opt = up(params, grads, opt_state, bs)
            for k, v in su.items():
                new_params[k] = v
            return new_params, new_opt, loss, outs["output"].value

        batch = example_batch(dict_dim=300, B=8, T=16, classes=4, seed=2)
        rng = jax.random.PRNGKey(3)
        if mesh_shape:
            mesh = make_mesh(mesh_shape)
            gm.mesh = mesh
            sharded = shard_train_step(step, mesh, gm)
            new_p, _, loss, out = sharded(params, opt_state, batch, rng, jnp.asarray(8.0))
            # parameters keep their declared layouts through the update
            assert "model" in str(new_p["emb"].sharding.spec)
            assert "model" in str(new_p["w_out"].sharding.spec)
        else:
            _, _, loss, out = jax.jit(step)(params, opt_state, batch, rng, jnp.asarray(8.0))
        losses[key] = float(loss)
    assert np.isfinite(losses["3axis"])
    np.testing.assert_allclose(losses["plain"], losses["3axis"], rtol=0.03, atol=0.02)
