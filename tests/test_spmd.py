"""SPMD data parallelism on a virtual 8-device CPU mesh.

The analog of the reference's loopback-pserver distributed tests
(/root/reference/paddle/trainer/tests/test_TrainerOnePass.cpp:120-296
checkRemoteUpdater*): a sharded trainer must produce the same parameters as
the single-device trainer on the same data.
"""

import os
import sys
import textwrap

import jax
import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.parallel import make_mesh
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    FLAGS.save_dir = ""
    FLAGS.mesh_shape = ""
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.log_period = 0
    yield
    sys.path.remove(PROVIDER_DIR)
    FLAGS.mesh_shape = ""


def _lr_config(tmp_path, batch_size=64):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list={str(test_list)!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size={batch_size}, learning_rate=0.05)
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "lr_config.py"
    cfg_path.write_text(src)
    return parse_config(str(cfg_path))


def test_mesh_construction():
    assert len(jax.devices()) == 8, "conftest should provide 8 virtual devices"
    mesh = make_mesh("data=8")
    assert mesh.shape == {"data": 8}
    mesh2 = make_mesh("data=4,model=2")
    assert mesh2.shape == {"data": 4, "model": 2}


def test_sharded_matches_single_device(tmp_path):
    cfg = _lr_config(tmp_path)
    t_single = Trainer(cfg)
    t_single.train(num_passes=1)

    FLAGS.mesh_shape = "data=8"
    t_sharded = Trainer(cfg)
    assert t_sharded._mesh is not None
    t_sharded.train(num_passes=1)
    FLAGS.mesh_shape = ""

    w1 = np.asarray(t_single.params["_output.w0"])
    w2 = np.asarray(t_sharded.params["_output.w0"])
    np.testing.assert_allclose(w1, w2, rtol=2e-4, atol=1e-5)

    r1 = t_single.test()
    r2 = t_sharded.test()
    err1 = [v for k, v in r1.items() if "classification_error" in k][0]
    err2 = [v for k, v in r2.items() if "classification_error" in k][0]
    assert abs(err1 - err2) < 0.02


def test_tensor_parallel_param_sharding(tmp_path):
    """Model-parallel parameter sharding via ParamAttr(sharding=...)."""
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=32, learning_rate=0.05, mesh_shape="data=4,model=2")
    data = data_layer(name="word", size=100)
    hidden = fc_layer(input=data, size=64, name="hidden",
                      param_attr=ParamAttr(sharding=[None, "model"]))
    output = fc_layer(input=hidden, size=2, act=SoftmaxActivation(), name="output",
                      param_attr=ParamAttr(sharding=["model", None]))
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "tp_config.py"
    cfg_path.write_text(src)
    cfg = parse_config(str(cfg_path))
    trainer = Trainer(cfg)
    assert trainer._mesh is not None
    trainer.train(num_passes=1)
    # the hidden weight should actually be sharded over the model axis
    w = trainer.params["_hidden.w0"]
    sh = w.sharding
    spec = getattr(sh, "spec", None)
    assert spec is not None and tuple(spec) == (None, "model"), spec
