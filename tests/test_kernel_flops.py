"""Analytic FLOP accounting for the fused Pallas kernels (ops/kernel_flops).

The analytic formulas must agree with XLA's own count of the equivalent
scan-path computation (fully unrolled so every step is visible to
HloCostAnalysis — a rolled while body is counted once regardless of trip
count), and the trace-time capture must collect exactly one fwd + one bwd
record when a train-shaped jit containing a fused kernel is lowered —
that sum is what bench.py adds to cost_analysis()['flops'] so pallas and
XLA legs report comparable-basis MFU.
"""

import types

import jax
import jax.numpy as jnp

import paddle_tpu.graph  # noqa: F401  (break the layers<->graph import cycle)
from paddle_tpu.layers.recurrent import (
    _scan_time,
    gru_cell_step,
    lstm_cell_step,
)
from paddle_tpu.ops import kernel_flops as kf


def _flops_of(fn, *args):
    cost = jax.jit(fn).lower(*args).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost["flops"])


def _lstm_cfg(H):
    return types.SimpleNamespace(
        size=H, reversed=False, active_type="tanh",
        active_gate_type="sigmoid", active_state_type="sigmoid",
    )


def _gru_cfg(H):
    return types.SimpleNamespace(
        size=H, reversed=False, active_type="tanh", active_gate_type="sigmoid",
    )


def test_lstm_analytic_matches_unrolled_scan_cost_analysis():
    T, B, H = 4, 16, 128
    cfg = _lstm_cfg(H)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(ks[0], (T, B, 4 * H))
    w = jax.random.normal(ks[1], (H, 4 * H)) * 0.05
    bias = jax.random.normal(ks[2], (7 * H,)) * 0.1
    mask = jnp.ones((T, B))

    def loss(x, w, bias):
        def cell(carry, x_t):
            h, c = carry
            h2, c2 = lstm_cell_step(cfg, x_t, h, c, w, bias)
            return (h2, c2), h2

        init = (jnp.zeros((B, H)), jnp.zeros((B, H)))
        _, ys = _scan_time(cell, x, mask, init, False, unroll=T)
        return jnp.sum(ys)

    measured = _flops_of(jax.value_and_grad(loss, argnums=(0, 1, 2)), x, w, bias)
    analytic = kf.lstm_fwd_flops(T, B, H) + kf.lstm_bwd_flops(T, B, H)
    # the scan path's HLO carries extra bookkeeping the kernel doesn't
    # (mask tree_map merges in the grad, bias adds, sum-reduction), and
    # the kernel's elementwise coefficients are approximate — but the
    # matmul terms dominate and must pin the two counts together
    assert 0.75 < analytic / measured < 1.25, (analytic, measured)


def test_gru_analytic_matches_unrolled_scan_cost_analysis():
    T, B, H = 4, 16, 128
    cfg = _gru_cfg(H)
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    x = jax.random.normal(ks[0], (T, B, 3 * H))
    w = jax.random.normal(ks[1], (H, 3 * H)) * 0.05
    bias = jax.random.normal(ks[2], (3 * H,)) * 0.1
    mask = jnp.ones((T, B))

    def loss(x, w, bias):
        def cell(h, x_t):
            h2 = gru_cell_step(cfg, x_t, h, w, bias)
            return h2, h2

        _, ys = _scan_time(cell, x, mask, jnp.zeros((B, H)), False, unroll=T)
        return jnp.sum(ys)

    measured = _flops_of(jax.value_and_grad(loss, argnums=(0, 1, 2)), x, w, bias)
    analytic = kf.gru_fwd_flops(T, B, H) + kf.gru_bwd_flops(T, B, H)
    assert 0.75 < analytic / measured < 1.25, (analytic, measured)


def test_capture_collects_fwd_and_bwd_records_at_lower_time():
    """Lowering a value_and_grad jit over the fused LSTM must record
    exactly one fwd + one bwd analytic count (what bench's AOT lower
    collects); outside capture() recording is a no-op."""
    from paddle_tpu.ops import pallas_lstm as pk

    T, B, H = 3, 8, 128
    cfg = _lstm_cfg(H)
    x = jax.random.normal(jax.random.PRNGKey(2), (T, B, 4 * H))
    w = jax.random.normal(jax.random.PRNGKey(3), (H, 4 * H)) * 0.05
    mask = jnp.ones((T, B))

    def loss(x, w):
        ys = pk.lstm_layer_forward(cfg, x, mask, w, None, interpret=True)
        return jnp.sum(ys)

    with kf.capture() as log:
        jax.jit(jax.value_and_grad(loss, argnums=(0, 1))).lower(x, w)
    assert sorted(log) == sorted(
        [kf.lstm_fwd_flops(T, B, H), kf.lstm_bwd_flops(T, B, H)]
    ), log
    # forward-only trace records only the primal's fwd count
    with kf.capture() as log2:
        jax.jit(loss).lower(x, w)
    assert log2 == [kf.lstm_fwd_flops(T, B, H)], log2
    # no capture active: record() must be a no-op (no stale global list)
    kf.record(123.0)
    with kf.capture() as log3:
        pass
    assert log3 == []


def test_capture_gru_records():
    from paddle_tpu.ops import pallas_gru as pg

    T, B, H = 3, 8, 128
    cfg = _gru_cfg(H)
    x = jax.random.normal(jax.random.PRNGKey(4), (T, B, 3 * H))
    w = jax.random.normal(jax.random.PRNGKey(5), (H, 3 * H)) * 0.05
    mask = jnp.ones((T, B))

    def loss(x, w):
        ys = pg.gru_layer_forward(cfg, x, mask, w, None, interpret=True)
        return jnp.sum(ys)

    with kf.capture() as log:
        jax.jit(jax.value_and_grad(loss, argnums=(0, 1))).lower(x, w)
    assert sorted(log) == sorted(
        [kf.gru_fwd_flops(T, B, H), kf.gru_bwd_flops(T, B, H)]
    ), log


def test_capture_is_reentrant():
    with kf.capture() as outer:
        kf.record(1.0)
        with kf.capture() as inner:
            kf.record(2.0)
        kf.record(3.0)
    assert outer == [1.0, 3.0] and inner == [2.0]


# ---------------------------------------------------- jaxpr matmul counter


def test_jaxpr_flops_matches_cost_analysis_on_scan_free_graph():
    """On a scan-free matmul graph the jaxpr counter and XLA's cost
    analysis must agree (both count 2·M·N·K per dot; the counter skips
    elementwise, which is negligible here)."""
    a = jnp.zeros((64, 128))
    b = jnp.zeros((128, 256))
    c = jnp.zeros((256, 32))

    def f(a, b, c):
        return jnp.sum((a @ b) @ c)

    measured = _flops_of(jax.value_and_grad(f, argnums=(0, 1, 2)), a, b, c)
    analytic = kf.train_step_flops(jax.value_and_grad(f, argnums=(0, 1, 2)), a, b, c)
    assert 0.9 < analytic / measured < 1.1, (analytic, measured)


def test_jaxpr_flops_counts_conv():
    x = jnp.zeros((4, 16, 16, 8))   # NHWC
    k = jnp.zeros((3, 3, 8, 32))    # HWIO

    def f(x, k):
        return jnp.sum(jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")))

    analytic = kf.train_step_flops(f, x, k)
    # out [4,16,16,32]; 2 * out_elems * (3*3*8)
    expected = 2.0 * 4 * 16 * 16 * 32 * (3 * 3 * 8)
    assert analytic == expected, (analytic, expected)


def test_jaxpr_flops_scales_with_scan_length_where_cost_analysis_does_not():
    """The whole point: HloCostAnalysis counts a scan body once regardless
    of trip count; the jaxpr counter multiplies by `length`."""
    w = jnp.zeros((128, 128))

    def f(x, w):
        def body(h, xt):
            h2 = jnp.tanh(xt + h @ w)
            return h2, h2

        _, ys = jax.lax.scan(body, jnp.zeros((16, 128)), x)
        return jnp.sum(ys)

    f1 = kf.train_step_flops(f, jnp.zeros((1, 16, 128)), w)
    f8 = kf.train_step_flops(f, jnp.zeros((8, 16, 128)), w)
    assert abs(f8 / f1 - 8.0) < 1e-6, (f1, f8)
    c1 = _flops_of(f, jnp.zeros((1, 16, 128)), w)
    c8 = _flops_of(f, jnp.zeros((8, 16, 128)), w)
    assert c8 / c1 < 2.0  # cost analysis: body counted once (the bug)


def test_jaxpr_flops_counts_pallas_grid():
    """pallas_call bodies are counted per grid step, so the counter's
    total for the fused LSTM matches the analytic formulas' matmul term."""
    from paddle_tpu.ops import pallas_lstm as pk

    T, B, H = 3, 8, 128
    cfg = _lstm_cfg(H)
    x = jnp.zeros((T, B, 4 * H))
    w = jnp.zeros((H, 4 * H))
    mask = jnp.ones((T, B))

    def loss(x, w):
        return jnp.sum(pk.lstm_layer_forward(cfg, x, mask, w, None, interpret=True))

    analytic = kf.train_step_flops(jax.value_and_grad(loss, argnums=(0, 1)), x, w)
    matmul_terms = T * (8.0 * B * H * H + 16.0 * B * H * H)
    # counter sees only dots (inside the kernel + none outside here)
    assert abs(analytic - matmul_terms) / matmul_terms < 1e-6, (
        analytic, matmul_terms)
