"""`paddle race` — the deterministic schedule explorer (dynamic half of
the analysis stack; doc/static_analysis.md "Dynamic analysis").

Coverage:

- detector fixtures: the three PR-9 PTL005 bugs (unlocked async-writer
  `completed`, heartbeat `_seq`, hangwatch `_fired`), reintroduced as
  subclass twins of the REAL classes, are each detected as torn reads
  within the default schedule budget; lock-order inversion and lost
  wakeup fixtures for the other detectors;
- the drain progress-signal regression this PR fixed (a concurrent
  save's queue motion credited as writer progress): the legacy logic
  fails its invariant under exploration, the shipped code is clean;
- replay: the whole run is a pure function of (seed, schedules) —
  identical findings, fingerprints, and traces across runs;
- the repo-wide gate: every spec under tests/race_specs passes with
  the checked-in ZERO-entry baseline, jax-free, in well under 60 s;
- --json records validate against the schema; `paddle compare` judges
  race artifacts direction-aware (growth ⇒ REGRESSION exit 1).

Everything here is jax-free and fast, like test_lint.py.
"""

import json
import os
import re
import sys
import time

import pytest

from paddle_tpu.analysis.dynamic.cli import (
    DEFAULT_SCHEDULES,
    RACE_BASELINE_NAME,
    main as race_main,
)
from paddle_tpu.analysis.dynamic.explore import Explorer, load_specs
from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience.hangwatch import HangWatch
from paddle_tpu.resilience.heartbeat import HeartbeatWriter, write_beat
from paddle_tpu.trainer.async_ckpt import AsyncCheckpointer
from paddle_tpu.utils import concurrency as cc

pytestmark = pytest.mark.race

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS_DIR = os.path.join(REPO, "tests", "race_specs")


def explore(spec, schedules=DEFAULT_SCHEDULES, seed=0):
    return Explorer(seed=seed, schedules=schedules).run_spec(spec)


def rules_of(result):
    return sorted({f.rule for f in result.findings})


# ----------------------------------------- PR-9 PTL005 bugs, reintroduced


class _BuggyWriter(AsyncCheckpointer):
    """PR-9 bug #1 reintroduced: the background writer's `completed`
    increment without the cv — drain's progress signal can tear."""

    def _write(self, job):
        (self._write_fn or self._default_write_fn())(
            self.save_dir, job.pass_id, job.params, job.opt_state,
            extra_meta=job.extra_meta, keep=job.keep,
            protect_pass=job.protect_pass,
        )
        self.completed += 1  # the pre-PR-9 unlocked write


class _SpecBuggyCompleted:
    NAME = "twin_completed"

    @staticmethod
    def run(ctx):
        ac = _BuggyWriter("", inflight_limit=2,
                          write_fn=lambda *a, **k: "p",
                          snapshot_fn=lambda tree: tree)
        ctx.watch(ac, "completed")
        ac.save(0, {"w": 0})
        ac.save(1, {"w": 1})
        ac.drain()


class _BuggyBeat(HeartbeatWriter):
    """PR-9 bug #2 reintroduced: `_seq += 1` outside `_seq_lock` —
    stop()'s final beat overlaps a daemon renewal wedged in slow
    shared-fs I/O past the bounded join, and the counter tears. The
    virtual sleep IS that slow write (3 s > stop's 1 s join timeout)."""

    def beat(self, **extra):
        seq = self._seq + 1
        cc.sleep(3.0)  # the slow-fs window the real class's lock covers
        self._seq = seq
        write_beat(self.dir, self.host, seq=seq, clock=self.clock,
                   extra=extra)


class _SpecBuggySeq:
    NAME = "twin_seq"

    @staticmethod
    def run(ctx):
        hb = _BuggyBeat(ctx.tmpdir, host=0, interval_s=1.0,
                        clock=lambda: 1e9)
        ctx.watch(hb, "_seq")
        hb.start()
        cc.sleep(2.5)
        hb.stop()


class _BuggyHangWatch(HangWatch):
    """PR-9 bug #3 reintroduced: the `_fired` test-and-set claimed
    WITHOUT the lock — two concurrent check() calls double-report."""

    def check(self):
        age = self.clock() - self._last
        if age > self.timeout_s and not self._fired:
            self._fired = True
            self.exit_fn(19)
        return age


class _SpecBuggyFired:
    NAME = "twin_fired"

    @staticmethod
    def run(ctx):
        exits = []
        hw = _BuggyHangWatch(timeout_s=2.0, report_dir=ctx.tmpdir,
                             exit_fn=exits.append, poll_s=1.0)
        ctx.watch(hw, "_fired")
        hw.start()                 # monitor thread drives check()
        cc.sleep(3.0)              # past the timeout, no pings
        hw.check()                 # caller-side check races the monitor
        hw.stop()


@pytest.mark.parametrize("spec", [
    _SpecBuggyCompleted, _SpecBuggySeq, _SpecBuggyFired,
], ids=lambda s: s.NAME)
def test_ptl005_bugs_detected_as_torn_reads(spec):
    """Acceptance: each PR-9 statically-found bug, reintroduced against
    the real class, is DYNAMICALLY proven racy within the default
    budget — static finds the fields, dynamic proves the race."""
    result = explore(spec)
    torn = [f for f in result.findings if f.rule == "torn_read"]
    assert torn, (
        f"{spec.NAME}: no torn_read within {DEFAULT_SCHEDULES} schedules:\n"
        + "\n".join(f.render() for f in result.findings)
    )
    attr = {"twin_completed": "completed", "twin_seq": "_seq",
            "twin_fired": "_fired"}[spec.NAME]
    assert any(f".{attr}`" in f.message for f in torn), torn[0].message


def test_fixed_classes_are_clean():
    """The same scenarios against the SHIPPED classes: no findings —
    the locks PR 9 added satisfy the happens-before detector."""
    specs = load_specs(SPECS_DIR)
    ex = Explorer(seed=0, schedules=DEFAULT_SCHEDULES)
    for spec in specs:
        result = ex.run_spec(spec)
        assert result.findings == [], (
            f"{spec.NAME}:\n" + "\n".join(
                f.render() for f in result.findings
            )
        )


# ------------------------------------ the drain progress-signal regression


class _LegacyDrainCheckpointer(AsyncCheckpointer):
    """The pre-PR `_wait_idle` progress signal: (completed,
    len(pending), id(active)) — trainer-side queue motion (a concurrent
    save / drop-oldest) and id() reuse both read as writer progress."""

    def _wait_idle(self, timeout=None):
        from paddle_tpu.resilience import CheckpointError

        deadline = None if timeout is None else cc.monotonic() + timeout
        self._ensure_thread()
        with self._cv:
            last_state = None
            while self._pending or self._active is not None:
                state = (self.completed, len(self._pending),
                         id(self._active))
                if (self.hangwatch is not None
                        and self._active is not None
                        and state != last_state):
                    self.hangwatch.ping(self._active.pass_id)
                last_state = state
                self._cv.wait(timeout=0.2)
                if deadline is not None and cc.monotonic() > deadline:
                    raise CheckpointError("drain timeout")


def _drain_signal_spec(cls):
    class _Spec:
        NAME = f"drain_signal_{cls.__name__}"

        @staticmethod
        def run(ctx):
            gate = cc.Event()
            pings = []

            class _Hw:
                def ping(self, pass_id=None, step=None):
                    import threading

                    if "writer" in threading.current_thread().name:
                        return
                    active = ac._active
                    pings.append((ac.completed,
                                  active.seq if active else None))

            def write_fn(save_dir, pass_id, params, opt_state=None, **kw):
                if pass_id == 0:
                    gate.wait()
                return "p"

            ac = cls("", inflight_limit=2, hangwatch=_Hw(),
                     write_fn=write_fn, snapshot_fn=lambda tree: tree)

            def late_saver():
                # wait until the main thread is demonstrably inside
                # drain (its first ping landed), then enqueue while the
                # writer is still wedged — queue motion, NOT progress
                while not pings:
                    cc.sleep(0.05)
                ac.save(1, {"w": 1})
                gate.set()

            ac.save(0, {"w": 0})
            while ac._active is None:  # ensure claimed, not droppable
                cc.sleep(0.01)
            t = cc.Thread(target=late_saver, name="saver2", daemon=False)
            t.start()
            ac.drain()
            t.join()
            # at most one ping per distinct WRITER state — a duplicate
            # means queue motion was credited as progress (the masked-
            # wedged-writer bug)
            assert len(pings) == len(set(pings)), (
                f"drain credited non-writer motion as progress: {pings}"
            )

    return _Spec


def test_legacy_drain_signal_bug_is_surfaced():
    """The explorer surfaces the concrete interleaving bug this PR
    fixed: under the legacy signal, a concurrent save during drain
    produces a duplicate-state ping (⇒ a wedged writer could never trip
    the hangwatch); the shipped signal is clean on the same spec."""
    legacy = explore(_drain_signal_spec(_LegacyDrainCheckpointer))
    assert any(
        f.rule == "spec_error" and "non-writer motion" in f.message
        for f in legacy.findings
    ), "\n".join(f.render() for f in legacy.findings) or "no findings"
    fixed = explore(_drain_signal_spec(AsyncCheckpointer))
    assert fixed.findings == [], "\n".join(
        f.render() for f in fixed.findings
    )


# -------------------------------------------- other detector fixture pairs


class _SpecLockOrder:
    NAME = "lock_order_pair"

    @staticmethod
    def run(ctx):
        a, b = cc.Lock(), cc.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t = cc.Thread(target=ba, daemon=False)
        t.start()
        ab()
        t.join()


def test_lock_order_cycle_detected_without_deadlocking():
    """The union graph catches the inversion even in schedules where
    the deadlock never actually fires."""
    result = explore(_SpecLockOrder)
    assert any(f.rule == "lock_order" for f in result.findings), (
        "\n".join(f.render() for f in result.findings) or "no findings"
    )
    lo = [f for f in result.findings if f.rule == "lock_order"][0]
    assert "cycle" in lo.message


class _SpecLostWakeup:
    NAME = "lost_wakeup_pair"

    @staticmethod
    def run(ctx):
        ev = cc.Event()

        def waiter():
            ev.wait()  # no timeout, and nothing will ever set it

        t = cc.Thread(target=waiter, daemon=False)
        t.start()
        t.join()


def test_lost_wakeup_detected():
    result = explore(_SpecLostWakeup)
    dets = rules_of(result)
    assert "lost_wakeup" in dets, dets
    assert any("no possible future wake" in f.message
               for f in result.findings)


# ----------------------------------------------------------------- replay


def test_run_is_a_pure_function_of_seed_and_budget():
    a = explore(_SpecBuggyCompleted, schedules=16, seed=7)
    b = explore(_SpecBuggyCompleted, schedules=16, seed=7)
    assert [(f.rule, f.fingerprint, f.schedule, f.trace)
            for f in a.findings] == \
           [(f.rule, f.fingerprint, f.schedule, f.trace)
            for f in b.findings]
    assert a.schedules_run == b.schedules_run and a.steps == b.steps


def test_finding_fingerprints_are_line_shift_stable():
    """Fingerprints key on (file, function, attr), not line numbers —
    the same rule lint's baseline follows."""
    a = explore(_SpecBuggyCompleted)
    fps = {f.fingerprint for f in a.findings}
    assert fps and all(re.fullmatch(r"[0-9a-f]{16}", fp) for fp in fps)


# ------------------------------------------------------------- CLI / gate


def test_repo_wide_race_gate_zero_findings_fast_and_jax_free():
    """THE gate (mirrors test_lint's): every shipped spec passes with
    the checked-in ZERO-entry baseline, well under the 60 s budget."""
    bl_path = os.path.join(REPO, RACE_BASELINE_NAME)
    assert os.path.isfile(bl_path), "checked-in race baseline missing"
    with open(bl_path) as f:
        doc = json.load(f)
    assert doc["findings"] == [], (
        "the race baseline must stay EMPTY — fix races, don't "
        "grandfather them"
    )
    jax_loaded_before = "jax" in sys.modules  # other suites may have
    t0 = time.monotonic()
    rc = race_main(["--specs", SPECS_DIR, "--baseline", bl_path])
    dt = time.monotonic() - t0
    assert rc == 0
    assert dt < 60, f"race gate took {dt:.1f}s (budget 60s)"
    assert ("jax" in sys.modules) == jax_loaded_before, (
        "the race gate must stay jax-free (a spec imported the "
        "accelerator runtime)"
    )


def test_cli_json_records_validate(tmp_path, capsys):
    rc = race_main(["--specs", SPECS_DIR, "--spec", "heartbeat",
                    "--no-baseline", "--json", "--schedules", "6"])
    out = capsys.readouterr().out
    recs = [json.loads(line) for line in out.splitlines() if line.strip()]
    assert rc == 0
    assert recs[-1]["kind"] == "race_summary"
    for rec in recs:
        assert obs.validate_record(rec) == [], rec
    assert recs[-1]["findings"] == 0
    assert set(recs[-1]["counts"]) <= set(recs[-1]["detectors"])
    assert recs[-1]["specs"] == ["heartbeat"]


def test_cli_list_and_unknown_spec(capsys):
    assert race_main(["--specs", SPECS_DIR, "--list"]) == 0
    out = capsys.readouterr().out
    for name in ("async_ckpt", "sharded_commit", "hangwatch",
                 "heartbeat", "feeder_pool"):
        assert name in out
    assert race_main(["--specs", SPECS_DIR, "--spec", "nope"]) == 2


def _buggy_spec_dir(tmp_path):
    d = tmp_path / "specs"
    d.mkdir()
    (d / "spec_bug.py").write_text(
        "from paddle_tpu.utils import concurrency as cc\n"
        "NAME = 'bugfix'\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "    def work(self):\n"
        "        self.n += 1\n"
        "def run(ctx):\n"
        "    c = C()\n"
        "    ctx.watch(c, 'n')\n"
        "    t = cc.Thread(target=c.work, daemon=True)\n"
        "    t.start()\n"
        "    c.n += 1\n"
        "    t.join()\n"
    )
    return str(d)


def test_cli_exit_1_on_new_findings_and_baseline_grandfathers(tmp_path,
                                                              capsys):
    d = _buggy_spec_dir(tmp_path)
    bl = str(tmp_path / RACE_BASELINE_NAME)
    assert race_main(["--specs", d, "--no-baseline"]) == 1
    capsys.readouterr()
    # grandfather, then the same run is clean — and the findings stay
    # visible as [baselined]
    assert race_main(["--specs", d, "--write-baseline",
                      "--baseline", bl]) == 0
    capsys.readouterr()
    assert race_main(["--specs", d, "--baseline", bl]) == 0
    out = capsys.readouterr().out
    assert "[baselined]" in out


def test_compare_diffs_race_runs(tmp_path, capsys):
    """`paddle compare` on two race artifacts: detector-count growth is
    a REGRESSION (exit 1), shrinkage an improvement."""
    from paddle_tpu.observability.compare import main as compare_main

    clean_dir = SPECS_DIR
    race_main(["--specs", clean_dir, "--spec", "heartbeat",
               "--no-baseline", "--json", "--schedules", "4"])
    a = tmp_path / "a.jsonl"
    a.write_text(capsys.readouterr().out)
    race_main(["--specs", _buggy_spec_dir(tmp_path), "--no-baseline",
               "--json", "--schedules", "4"])
    b = tmp_path / "b.jsonl"
    b.write_text(capsys.readouterr().out)

    assert compare_main([str(a), str(b)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "race.torn_read" in out
    assert compare_main([str(a), str(a)]) == 0
    assert "NO CHANGE" in capsys.readouterr().out
    assert compare_main([str(b), str(a)]) == 0
    assert "IMPROVED" in capsys.readouterr().out


def test_check_analysis_script_is_the_combined_gate():
    """bin/check_analysis.sh runs lint + race against both checked-in
    baselines — a PR introducing a lock-order inversion (or any new
    finding) fails it before review. Run here end-to-end, jax-free."""
    import subprocess

    script = os.path.join(REPO, "bin", "check_analysis.sh")
    assert os.path.isfile(script) and os.access(script, os.X_OK), (
        "bin/check_analysis.sh missing or not executable"
    )
    r = subprocess.run(
        ["bash", script, "--schedules", "8"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "PYTHON": sys.executable, "JAX_PLATFORMS": "cpu"},
        cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "analysis gate clean" in r.stdout


def test_race_marker_registered():
    with open(os.path.join(REPO, "pyproject.toml"), encoding="utf-8") as f:
        assert re.search(r'^\s*"race:', f.read(), re.MULTILINE), (
            "race pytest marker missing from pyproject.toml"
        )
