"""`paddle lint` — rule fixtures, suppressions, baseline, repo gate.

One golden fixture pair per rule (a violating snippet the rule must
flag, a clean twin it must stay silent on), the mandatory-reason
suppression contract, the baseline round trip, the doc/catalog
reverse-consistency check, `--json` schema validation, the
`paddle compare` lint diff, and the repo-wide run that IS the CI gate:
zero non-baselined findings over paddle_tpu/.

Everything here is jax-free and fast (<10 s) so the gate executes even
when the tier-1 window truncates the suite.
"""

import json
import os
import re
import textwrap

import pytest

from paddle_tpu.analysis import ALL_RULES, load_baseline, run_lint, write_baseline
from paddle_tpu.analysis.baseline import BASELINE_NAME
from paddle_tpu.analysis.cli import main as lint_main
from paddle_tpu.observability import metrics as obs

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_tree(tmp_path, files):
    """Write {relpath: source} under tmp_path and lint the tree."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return run_lint([str(tmp_path)])


def rules_of(result):
    return sorted({f.rule for f in result.findings})


# --------------------------------------------------------- fixture pairs


def test_ptl001_wall_clock_pair(tmp_path):
    viol = lint_tree(tmp_path / "v", {"observability/win.py": """\
        import time

        def window_start():
            return time.time()
        """})
    assert [f.rule for f in viol.findings] == ["PTL001"]
    assert "time.time" in viol.findings[0].message
    clean = lint_tree(tmp_path / "c", {"observability/win.py": """\
        import time

        def window_start():
            return time.monotonic()
        """})
    assert clean.findings == []


def test_ptl001_scoped_to_hot_path_modules(tmp_path):
    # the same wall-clock read OUTSIDE the hot-path module list (e.g. a
    # supervisor-side module) is not this rule's business
    res = lint_tree(tmp_path, {"resilience/supervisor.py": """\
        import time

        def stamp():
            return time.time()
        """})
    assert res.findings == []


def test_ptl002_host_sync_pair(tmp_path):
    viol = lint_tree(tmp_path / "v", {"trainer/trainer.py": """\
        def train_one_pass(provider, train_step, params, log):
            for batch in provider:
                params, loss = train_step(params, batch)
                log(float(loss))
        """})
    assert [f.rule for f in viol.findings] == ["PTL002"]
    assert "float" in viol.findings[0].message
    # clean twin: the loss stays on device inside the loop; the read
    # happens at the pass boundary (outside the loop body)
    clean = lint_tree(tmp_path / "c", {"trainer/trainer.py": """\
        def train_one_pass(provider, train_step, params, log):
            loss = None
            for batch in provider:
                params, loss = train_step(params, batch)
            log(float(loss))
        """})
    assert clean.findings == []


def test_ptl002_while_test_is_per_iteration(tmp_path):
    # a while's condition re-evaluates every iteration — a sync there
    # is a per-step stall exactly like one in the body
    res = lint_tree(tmp_path, {"trainer/trainer.py": """\
        def train_one_pass(provider, train_step, params, done):
            loss = None
            while loss is None or not done(float(loss)):
                params, loss = train_step(params, next(provider))
        """})
    assert [f.rule for f in res.findings] == ["PTL002"]


def test_ptl003_use_after_donate_pair(tmp_path):
    viol = lint_tree(tmp_path / "v", {"engine.py": """\
        import jax

        def run(update, params, batch):
            step = jax.jit(update, donate_argnums=(0,))
            new_params = step(params, batch)
            return params, new_params
        """})
    assert [f.rule for f in viol.findings] == ["PTL003"]
    assert "`params`" in viol.findings[0].message
    clean = lint_tree(tmp_path / "c", {"engine.py": """\
        import jax

        def run(update, params, batch):
            step = jax.jit(update, donate_argnums=(0,))
            params = step(params, batch)
            return params
        """})
    assert clean.findings == []


def test_ptl004_recompile_hazard_pair(tmp_path):
    viol = lint_tree(tmp_path / "v", {"sig.py": """\
        import jax

        scale_table = [1.0, 2.0]

        @jax.jit
        def scaled(x):
            return x * scale_table[0]

        def sig_of(shapes):
            return tuple(shapes.items())
        """})
    assert [f.rule for f in viol.findings] == ["PTL004", "PTL004"]
    msgs = " / ".join(f.message for f in viol.findings)
    assert "scale_table" in msgs and "iteration order" in msgs
    clean = lint_tree(tmp_path / "c", {"sig.py": """\
        import jax

        SCALE_TABLE = (1.0, 2.0)

        @jax.jit
        def scaled(x):
            return x * SCALE_TABLE[0]

        def sig_of(shapes):
            return tuple(sorted(shapes.items()))
        """})
    assert clean.findings == []


def test_ptl005_unlocked_thread_write_pair(tmp_path):
    viol = lint_tree(tmp_path / "v", {"writer.py": """\
        import threading

        class Writer:
            def __init__(self):
                self._lock = threading.Lock()
                self.completed = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self._write()

            def _write(self):
                self.completed += 1
        """})
    assert [f.rule for f in viol.findings] == ["PTL005"]
    assert "completed" in viol.findings[0].message
    clean = lint_tree(tmp_path / "c", {"writer.py": """\
        import threading

        class Writer:
            def __init__(self):
                self._lock = threading.Lock()
                self.completed = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self._write()

            def _write(self):
                with self._lock:
                    self.completed += 1
        """})
    assert clean.findings == []


def test_ptl006_exit_without_flush_pair(tmp_path):
    viol = lint_tree(tmp_path / "v", {"faults.py": """\
        import os

        def fire(obs):
            obs.emit("fault", site="x", action="exit")
            os._exit(3)
        """})
    assert [f.rule for f in viol.findings] == ["PTL006"]
    clean = lint_tree(tmp_path / "c", {"faults.py": """\
        import os

        def fire(obs):
            obs.emit("fault", site="x", action="exit")
            obs.flush()
            os._exit(3)
        """})
    assert clean.findings == []


_PTL007_REGISTRIES = {
    "observability/metrics.py": """\
        KIND_REQUIRED = {
            "pass_end": (),
        }
        FLUSH_KINDS = frozenset({"pass_end", "ghost"})
        """,
    "resilience/faultinject.py": """\
        SITE_DOCS = {
            "checkpoint.write": "before each checkpoint file write",
            "phantom.site": "documented but never planted",
        }
        """,
    "doc_stub": """\
        ### Record kinds

        | kind | emitted by | fields |
        |---|---|---|
        | `pass_end` | pass boundary | samples |
        """,
}


def test_ptl007_registry_drift_pair(tmp_path):
    files = dict(_PTL007_REGISTRIES)
    doc = files.pop("doc_stub")
    (tmp_path / "v" / "doc").mkdir(parents=True)
    (tmp_path / "v" / "doc" / "observability.md").write_text(
        textwrap.dedent(doc)
    )
    viol = lint_tree(tmp_path / "v", dict(files, **{
        "trainer/trainer.py": """\
            def run(emit, fault_point):
                fault_point("checkpoint.write")
                fault_point("trainer.unknown")
                emit("pass_end", samples=1)
                emit("mystery", foo=2)
            """,
    }))
    msgs = [f.message for f in viol.findings]
    assert all(f.rule == "PTL007" for f in viol.findings)
    assert any("`mystery`" in m and "KIND_REQUIRED" in m for m in msgs)
    assert any("`mystery`" in m and "undocumented" in m for m in msgs)
    assert any("`ghost`" in m for m in msgs)
    assert any("`trainer.unknown`" in m for m in msgs)
    assert any("`phantom.site`" in m for m in msgs)

    (tmp_path / "c" / "doc").mkdir(parents=True)
    (tmp_path / "c" / "doc" / "observability.md").write_text(
        textwrap.dedent(doc)
    )
    clean = lint_tree(tmp_path / "c", {
        "observability/metrics.py": """\
            KIND_REQUIRED = {
                "pass_end": (),
            }
            FLUSH_KINDS = frozenset({"pass_end"})
            """,
        "resilience/faultinject.py": """\
            SITE_DOCS = {
                "checkpoint.write": "before each checkpoint file write",
            }
            """,
        "trainer/trainer.py": """\
            def run(emit, fault_point):
                fault_point("checkpoint.write")
                emit("pass_end", samples=1)
            """,
    })
    assert clean.findings == []


def test_ptl008_unbounded_daemon_blocking_pair(tmp_path):
    viol = lint_tree(tmp_path / "v", {"writer.py": """\
        import threading

        class Writer:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    self._cv.wait()
                    self.q.get()
                    self.lock.acquire()
                    self.lock.acquire(True)
                    self.q.get(True)
        """})
    assert [f.rule for f in viol.findings] == ["PTL008"] * 5
    msgs = " / ".join(f.message for f in viol.findings)
    assert "wait" in msgs and "get" in msgs and "acquire" in msgs
    # bounded waits, non-blocking forms, dict.get, and NON-daemon
    # threads all pass
    clean = lint_tree(tmp_path / "c", {"writer.py": """\
        import threading

        class Writer:
            def start(self):
                threading.Thread(target=self._run, daemon=True).start()
                threading.Thread(target=self._join_side).start()

            def _run(self):
                while True:
                    self._cv.wait(timeout=60.0)
                    self.q.get(timeout=1.0)
                    self.lock.acquire(timeout=1.0)
                    self.lock.acquire(blocking=False)
                    self.lock.acquire(False)
                    self.q.get(False)
                    self.q.get(block=False)
                    self.opts.get("key")
                    self.opts.get(self.key)
                    self.q.get_nowait()

            def _join_side(self):
                self._cv.wait()
        """})
    assert clean.findings == []


# ----------------------------------------------------------- suppressions


def test_suppression_with_reason_silences(tmp_path):
    res = lint_tree(tmp_path, {"observability/win.py": """\
        import time

        def window_start():
            return time.time()  # lint: disable=PTL001 -- civil-time anchor for this fixture
        """})
    assert res.findings == []


def test_suppression_on_comment_line_above(tmp_path):
    res = lint_tree(tmp_path, {"observability/win.py": """\
        import time

        def window_start():
            # lint: disable=PTL001 -- civil-time anchor for this fixture
            return time.time()
        """})
    assert res.findings == []


def test_suppression_trailing_a_wrapped_call(tmp_path):
    # black-style wrapped call: the suppression lands on the closing-
    # paren line; it must still govern the finding anchored to line 1
    # of the call's span
    res = lint_tree(tmp_path, {"observability/win.py": """\
        import time

        def window_start(fmt):
            return fmt(
                time.time(),
                precision=6,
            )  # lint: disable=PTL001 -- civil-time anchor for this fixture
        """})
    assert res.findings == []


def test_suppression_requires_reason(tmp_path):
    # a reason-less suppression suppresses NOTHING and is itself a
    # finding (PTL000) — both must surface
    res = lint_tree(tmp_path, {"observability/win.py": """\
        import time

        def window_start():
            return time.time()  # lint: disable=PTL001
        """})
    assert rules_of(res) == ["PTL000", "PTL001"]
    ptl000 = [f for f in res.findings if f.rule == "PTL000"][0]
    assert "reason" in ptl000.message


# --------------------------------------------------------------- baseline


def test_baseline_round_trip(tmp_path):
    tree = tmp_path / "t"
    files = {"observability/a.py": """\
        import time

        def one():
            return time.time()
        """}
    res = lint_tree(tree, files)
    assert [f.rule for f in res.findings] == ["PTL001"]

    # grandfather everything; the re-run reports zero NEW findings
    bl_path = str(tmp_path / BASELINE_NAME)
    write_baseline(bl_path, res.findings)
    doc = load_baseline(bl_path)
    assert len(doc["findings"]) == 1
    again = run_lint([str(tree)], baseline=doc)
    assert again.new == [] and len(again.findings) == 1
    assert again.findings[0].baselined

    # a NEW violation in another file is not covered by the baseline
    (tree / "observability" / "b.py").write_text(
        "import time\n\ndef two():\n    return time.time()\n"
    )
    drift = run_lint([str(tree)], baseline=doc)
    assert len(drift.new) == 1 and drift.new[0].path.endswith("b.py")
    # fingerprints are line-independent: shifting a.py's finding down
    # must not invalidate its baseline entry
    (tree / "observability" / "a.py").write_text(
        "import time\n\n\n\ndef one():\n    return time.time()\n"
    )
    shifted = run_lint([str(tree)], baseline=doc)
    assert [f.path for f in shifted.new] == [drift.new[0].path]
    assert not shifted.stale_baseline


# ------------------------------------------------------------ CLI / JSON


def test_cli_json_records_validate(tmp_path, capsys):
    (tmp_path / "observability").mkdir(parents=True)
    (tmp_path / "observability" / "w.py").write_text(
        "import time\n\ndef f():\n    return time.time()\n"
    )
    rc = lint_main([str(tmp_path), "--json", "--no-baseline"])
    out = capsys.readouterr().out
    recs = [json.loads(line) for line in out.splitlines() if line.strip()]
    assert rc == 1
    assert [r["kind"] for r in recs] == ["lint_finding", "lint_summary"]
    for rec in recs:
        assert obs.validate_record(rec) == [], rec
    assert recs[-1]["counts"] == {"PTL001": 1}
    assert set(recs[-1]["rules"]) == set(ALL_RULES)
    assert recs[-1]["skipped"] == 0 and recs[-1]["stale_baseline"] == 0


def test_json_summary_reports_skipped_files(tmp_path, capsys):
    # coverage honesty: a syntax-error file scans nothing — the --json
    # summary must say so instead of letting a gate read shrunken
    # coverage as "clean"
    (tmp_path / "broken.py").write_text("def oops(:\n")
    rc = lint_main([str(tmp_path), "--json", "--no-baseline"])
    cap = capsys.readouterr()
    summary = json.loads(cap.out.splitlines()[-1])
    assert rc == 0 and summary["skipped"] == 1
    assert "broken.py" in cap.err


def test_cli_clean_tree_exits_zero(tmp_path, capsys):
    (tmp_path / "m.py").write_text("x = 1\n")
    assert lint_main([str(tmp_path)]) == 0


def test_compare_diffs_lint_runs(tmp_path, capsys):
    """`paddle compare a.jsonl b.jsonl` on two lint artifacts: growing
    per-rule counts are a REGRESSION (exit 1); identical runs are not."""
    from paddle_tpu.observability.compare import main as compare_main

    def artifact(name, n_viol):
        d = tmp_path / name
        (d / "observability").mkdir(parents=True)
        for i in range(n_viol):
            (d / "observability" / f"v{i}.py").write_text(
                f"import time\n\ndef f{i}():\n    return time.time()\n"
            )
        lint_main([str(d), "--json", "--no-baseline"])
        path = tmp_path / f"{name}.jsonl"
        path.write_text(capsys.readouterr().out)
        return str(path)

    a, b = artifact("a", 1), artifact("b", 2)
    assert compare_main([a, b]) == 1  # new finding => regression
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "lint.PTL001" in out
    assert compare_main([a, a]) == 0
    assert "NO CHANGE" in capsys.readouterr().out
    # direction-aware: fewer findings is an improvement, not a regression
    assert compare_main([b, a]) == 0
    assert "IMPROVED" in capsys.readouterr().out


# ----------------------------------------------------- repo-wide CI gate


def test_repo_wide_lint_zero_new_findings():
    """THE gate: `paddle lint paddle_tpu/` is clean modulo the checked-in
    baseline, which stays within its grandfathering budget."""
    pkg = os.path.join(REPO, "paddle_tpu")
    bl_path = os.path.join(REPO, BASELINE_NAME)
    assert os.path.isfile(bl_path), "checked-in lint baseline missing"
    doc = load_baseline(bl_path)
    assert len(doc["findings"]) <= 10, (
        "grandfathering budget exceeded — fix or suppress (with reasons) "
        "instead of growing the baseline"
    )
    res = run_lint([pkg], baseline=doc)
    assert res.files_scanned > 50
    assert res.new == [], "new lint findings:\n" + "\n".join(
        f.render() for f in res.new
    )
    assert not res.stale_baseline, (
        "baseline entries no longer match — regenerate with "
        "`paddle lint paddle_tpu/ --write-baseline`: "
        + ", ".join(res.stale_baseline)
    )
    # every suppression in the tree carried a reason, or PTL000 would
    # have surfaced above. (Deliberately NO assertion that the baseline
    # is non-empty: fixing the grandfathered findings and shrinking the
    # baseline to [] is the encouraged end state.)


def test_subset_write_baseline_keeps_out_of_scope_entries(tmp_path, capsys):
    """`--write-baseline` over a subset must carry forward grandfathered
    entries for files the scan never saw."""
    tree = tmp_path / "t"
    for sub in ("observability", "trainer"):
        (tree / sub).mkdir(parents=True)
        (tree / sub / "m.py").write_text(
            "import time\n\ndef f():\n    return time.time()\n"
        )
    bl_path = str(tmp_path / BASELINE_NAME)
    # full-tree baseline: only observability/m.py is PTL001-scoped
    # (trainer/m.py is not a hot-path file), so exactly 1 entry
    lint_main([str(tree), "--write-baseline", "--baseline", bl_path])
    capsys.readouterr()
    full = load_baseline(bl_path)
    assert len(full["findings"]) == 1  # only observability/m.py matches PTL001
    # subset regeneration over trainer/ must not drop the entry
    lint_main([str(tree / "trainer"), "--write-baseline",
               "--baseline", bl_path])
    capsys.readouterr()
    merged = load_baseline(bl_path)
    assert merged["findings"] == full["findings"]


def test_subset_scan_keeps_out_of_scope_baseline_quiet():
    """A subset run must not call the full tree's grandfathered entries
    stale (the advice to --write-baseline would drop them)."""
    doc = load_baseline(os.path.join(REPO, BASELINE_NAME))
    res = run_lint(
        [os.path.join(REPO, "paddle_tpu", "observability")], baseline=doc
    )
    assert res.stale_baseline == []
    assert res.new == [], "\n".join(f.render() for f in res.new)


def test_subset_scan_of_registry_module_has_no_spurious_drift():
    """Scanning resilience/ alone sees SITE_DOCS but none of the
    trainer/feeder/checkpoint planting sites — that must not read as
    'every documented site is unplanted'."""
    res = run_lint([os.path.join(REPO, "paddle_tpu", "resilience")])
    drift = [f for f in res.findings if f.rule == "PTL007"]
    assert drift == [], "\n".join(f.render() for f in drift)


def test_baseline_entry_for_deleted_file_goes_stale(tmp_path):
    """Entries whose file vanished must be reported stale (and dropped
    by --write-baseline), never carried forward forever."""
    tree = tmp_path / "t"
    (tree / "observability").mkdir(parents=True)
    # a marked root: deletion detection needs stable entry paths
    (tree / "pyproject.toml").write_text("")
    target = tree / "observability" / "gone.py"
    target.write_text("import time\n\ndef f():\n    return time.time()\n")
    res = run_lint([str(tree)])
    bl_path = str(tmp_path / BASELINE_NAME)
    write_baseline(bl_path, res.findings)
    target.unlink()
    stale = run_lint([str(tree)], baseline=load_baseline(bl_path))
    assert stale.stale_baseline == [res.findings[0].fingerprint]


def test_doc_catalog_reverse_consistency():
    """Every implemented rule ID is documented in doc/static_analysis.md
    and every documented ID is implemented (PTL007's discipline applied
    to the linter itself)."""
    path = os.path.join(REPO, "doc", "static_analysis.md")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    documented = set(re.findall(r"PTL\d{3}", text))
    implemented = set(ALL_RULES)
    assert documented == implemented, (
        f"doc/static_analysis.md vs ALL_RULES drift: "
        f"undocumented={sorted(implemented - documented)} "
        f"unimplemented={sorted(documented - implemented)}"
    )


def test_lint_marker_registered():
    with open(os.path.join(REPO, "pyproject.toml"), encoding="utf-8") as f:
        assert re.search(r'^\s*"lint:', f.read(), re.MULTILINE), (
            "lint pytest marker missing from pyproject.toml"
        )
