"""model_zoo/embedding demo: skip-gram + hsigmoid learns cluster structure,
extract_para subsets the table by user dictionary."""

import os
import shutil
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "demo", "model_zoo", "embedding")


def test_embedding_trains_and_extracts(tmp_path):
    for f in os.listdir(DEMO):
        if f.endswith(".py"):
            shutil.copy(os.path.join(DEMO, f), tmp_path)
    (tmp_path / "train.list").write_text("corpus-seed-1\n")
    (tmp_path / "test.list").write_text("corpus-seed-2\n")

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("trainer_config.py", "dim=16")
        flags = _Flags(config="trainer_config.py", num_passes=3,
                       log_period=1000, use_tpu=False,
                       save_dir=str(tmp_path / "output"))
        trainer = Trainer(cfg, flags)
        trainer.train()

        import common
        emb = np.asarray(trainer.params["_emb"])
        # planted cluster structure: mean within-cluster cosine similarity
        # must exceed across-cluster similarity
        norm = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-8)
        k = common.WORDS_PER_CLUSTER
        within, across = [], []
        rng = np.random.RandomState(0)
        for _ in range(400):
            a, b = rng.randint(0, emb.shape[0], 2)
            sim = float(norm[a] @ norm[b])
            (within if common.cluster_of(a) == common.cluster_of(b) else across).append(sim)
        assert np.mean(within) > np.mean(across) + 0.05, (
            f"within={np.mean(within):.3f} across={np.mean(across):.3f}"
        )

        # extract_para subsets rows correctly
        words = common.word_list()
        (tmp_path / "pre.dict").write_text("\n".join(words) + "\n")
        usr = [words[3], words[40], words[77]]
        (tmp_path / "usr.dict").write_text("\n".join(usr) + "\n")
        out = subprocess.run(
            [sys.executable, "extract_para.py",
             "--model_dir=output/pass-00002",
             "--pre_dict=pre.dict", "--usr_dict=usr.dict", "--out=usr.npz"],
            capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": f"{REPO}:{REPO}/compat"},
        )
        assert out.returncode == 0, out.stderr
        with np.load("usr.npz") as z:
            assert list(z["words"]) == usr
            np.testing.assert_allclose(z["vectors"][0], emb[3], rtol=1e-6)
            np.testing.assert_allclose(z["vectors"][1], emb[40], rtol=1e-6)
    finally:
        os.chdir(cwd)
