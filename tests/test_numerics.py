"""Numerics observability (doc/observability.md "Memory & numerics
telemetry"): per-layer health aux inside the jitted step
(--numerics_log_period), zero recompiles after warmup with the aux
enabled, the nonfinite blame re-run naming the poisoned layer
(`trainer.nonfinite_layer` fault site), no false blame when only the
loss was faked, and `paddle compare`'s direction-awareness for the new
metrics."""

import json
import math
import os
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import numerics as obs_num
from paddle_tpu.resilience import NonFiniteLossError, faultinject

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    yield
    sys.path.remove(PROVIDER_DIR)


@pytest.fixture(autouse=True)
def _fresh_state():
    obs.registry().reset()
    yield
    obs.configure("")
    faultinject.configure("")


def _write_config(tmp_path):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r},
                            test_list={str(test_list)!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02, learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    hid = fc_layer(input=data, size=8, act=TanhActivation(), name="hid")
    output = fc_layer(input=hid, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "cfg.py"
    cfg_path.write_text(src)
    return str(cfg_path)


def _trainer(cfg, save_dir, **flag_overrides):
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    FLAGS.config = cfg
    FLAGS.save_dir = save_dir
    FLAGS.num_passes = 2
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.seed = 7
    FLAGS.metrics_path = ""
    FLAGS.mesh_shape = ""
    FLAGS.nonfinite_policy = "abort"
    FLAGS.max_nonfinite_steps = 3
    FLAGS.fault_spec = ""
    FLAGS.numerics_log_period = 0
    for k, v in flag_overrides.items():
        setattr(FLAGS, k, v)
    return Trainer(parse_config(cfg, ""), FLAGS)


def _records(run_dir):
    out = []
    for path in obs.metrics_files(str(run_dir)):
        out.extend(obs.read_records(path))
    return out


# ------------------------------------------------------------------ units


def test_layer_groups_maps_params_to_layers(tmp_path):
    from paddle_tpu.config import parse_config

    cfg = _write_config(tmp_path)
    config = parse_config(cfg, "")
    pnames = [p.name for p in config.model_config.parameters]
    groups = obs_num.layer_groups(config.model_config, pnames)
    assert set(groups["output"]) == {"_output.w0", "_output.wbias"}
    assert set(groups["hid"]) == {"_hid.w0", "_hid.wbias"}
    # every param lands in exactly one group
    assert sorted(p for ps in groups.values() for p in ps) == sorted(pnames)


def test_step_health_and_derive_roundtrip():
    import jax.numpy as jnp

    params = {"w": jnp.asarray([3.0, 4.0]), "b": jnp.asarray([1.0])}
    new_params = {"w": jnp.asarray([3.0, 4.2]), "b": jnp.asarray([1.0])}
    grads = {"w": jnp.asarray([0.6, 0.8]), "b": jnp.asarray([float("nan")])}
    groups = {"fc": ["w", "b"]}
    health = obs_num.step_health(params, new_params, grads, groups)
    layers, nf_layers, gnorm = obs_num.derive(
        {k: np.asarray(v) for k, v in health.items()}
    )
    fc = layers["fc"]
    # param norm sqrt(9+16+1); update norm 0.2 over it
    assert fc["param_norm"] == pytest.approx(math.sqrt(26.0), rel=1e-5)
    assert fc["update_ratio"] == pytest.approx(0.2 / math.sqrt(26.0), rel=1e-4)
    assert fc["nonfinite"] == 1
    assert nf_layers == ["fc"]
    # the NaN grad poisons the norm sums — reported as nonfinite, and
    # the global norm skips the poisoned (non-finite) contribution
    assert not math.isfinite(fc["grad_norm"]) or fc["grad_norm"] >= 1.0
    assert math.isfinite(gnorm)


def test_derive_takes_last_batch_of_fused_stack():
    stacked = {"fc": np.asarray([[1.0, 1.0, 0.0, 0.0],
                                 [4.0, 9.0, 1.0, 2.0]])}
    layers, nf_layers, _ = obs_num.derive(stacked)
    assert layers["fc"]["grad_norm"] == pytest.approx(2.0)
    assert layers["fc"]["param_norm"] == pytest.approx(3.0)
    assert layers["fc"]["nonfinite"] == 2
    assert nf_layers == ["fc"]


def test_record_kinds_registered():
    """Satellite: memory/numerics/oom are first-class schema citizens —
    registered (validate_record enforces their fields); memory/oom are
    flush kinds (an oom must reach disk before the death), numerics is
    BUFFERED like its analog train_window (a per-record flush at
    --numerics_log_period=1 would put file I/O on the hot step loop)."""
    for kind in ("memory", "numerics", "oom"):
        assert kind in obs.KIND_REQUIRED
    assert "memory" in obs.FLUSH_KINDS and "oom" in obs.FLUSH_KINDS
    assert "numerics" not in obs.FLUSH_KINDS
    assert obs.validate_record(
        {"v": 1, "kind": "numerics", "host": 0, "t": 0.0}
    ) == ["numerics record missing required key 'layers'"]


# --------------------------------------------------------- smoke + blame


@pytest.fixture(scope="module")
def numerics_run(tmp_path_factory):
    """One 2-pass smoke train with --numerics_log_period=2 — shared by
    the record/recompile tests below."""
    tmp_path = tmp_path_factory.mktemp("numerics_smoke")
    cfg = _write_config(tmp_path)
    sys.path.insert(0, PROVIDER_DIR)
    obs.registry().reset()
    save_dir = str(tmp_path / "out")
    try:
        trainer = _trainer(cfg, save_dir, numerics_log_period=2)
        trainer.train()
    finally:
        obs.configure("")
        sys.path.remove(PROVIDER_DIR)
    return save_dir, _records(save_dir)


def test_numerics_records_validate_and_carry_layers(numerics_run):
    _save_dir, recs = numerics_run
    nums = [r for r in recs if r["kind"] == "numerics"]
    assert nums, "no numerics records from the smoke run"
    for r in nums:
        assert obs.validate_record(r) == []
        for layer in ("hid", "output"):
            row = r["layers"][layer]
            assert row["grad_norm"] >= 0
            assert row["param_norm"] > 0
            assert row["update_ratio"] > 0  # Adam moves every step
            assert row["nonfinite"] == 0
        assert r["nonfinite_layers"] == []
        assert r["global_grad_norm"] > 0
    # pass-end emission: every pass has at least one numerics record
    assert {r["pass"] for r in nums} == {0, 1}


def test_numerics_zero_recompiles_after_warmup(numerics_run):
    """Acceptance: enabling --numerics_log_period causes zero
    recompiles after warmup — every compile record lands in pass 0 (or
    unscoped), and no launch group compiles the same signature twice."""
    _save_dir, recs = numerics_run
    compiles = [r for r in recs if r["kind"] == "compile"]
    assert compiles
    assert all(c.get("pass", 0) <= 0 for c in compiles), (
        "a compile happened after warmup with numerics enabled: "
        + json.dumps([{k: c.get(k) for k in ("group", "sig", "pass")}
                      for c in compiles])
    )
    sigs = [(c["group"], c["sig"]) for c in compiles]
    assert len(sigs) == len(set(sigs)), "a (group, sig) compiled twice"


def test_numerics_table_column_and_analyzer_doc(numerics_run):
    save_dir, _recs = numerics_run
    from paddle_tpu.observability.analyze import (
        _fmt_table,
        analyze,
        load_run,
    )

    doc = analyze(load_run(save_dir))
    assert doc["numerics"] == {"records": doc["numerics"]["records"],
                               "nonfinite_layers": []}
    assert doc["numerics"]["records"] >= 2
    for row in doc["passes"]:
        assert row["nf_layers"] == 0
    table = _fmt_table(doc)
    assert "nf lyr" in table
    assert "numerics telemetry:" in table


def test_blame_names_poisoned_layer_e2e(tmp_path):
    """trainer.nonfinite_layer=raise:hid plants a real NaN in layer
    `hid`'s parameters; the loss goes NaN, the policy trips, and the
    blame re-run must name `hid` (phase `params`) — on the nonfinite
    record AND in the raised error. No shortcut: blame never consults
    the injector."""
    cfg = _write_config(tmp_path)
    save_dir = str(tmp_path / "out")
    trainer = _trainer(
        cfg, save_dir, numerics_log_period=2, nonfinite_policy="skip",
        max_nonfinite_steps=1, fault_spec="trainer.nonfinite_layer=raise:hid@3",
    )
    faultinject.configure("trainer.nonfinite_layer=raise:hid@3")
    with pytest.raises(NonFiniteLossError) as ei:
        trainer.train()
    assert "layer 'hid'" in str(ei.value)
    obs.flush()
    nf_recs = [r for r in _records(save_dir) if r["kind"] == "nonfinite"]
    assert nf_recs
    for r in nf_recs:
        assert obs.validate_record(r) == []
        assert r["blame_layer"] == "hid"
        assert r["blame_phase"] == "params"
    # the numerics aux saw the nonfinite gradients too (the NaN weight
    # poisons hid's grads through the chain rule)
    nums = [r for r in _records(save_dir) if r["kind"] == "numerics"]
    assert any(r["nonfinite_layers"] for r in nums)


def test_no_false_blame_on_faked_loss(tmp_path):
    """trainer.nonfinite only FAKES the loss value host-side — the
    model itself is healthy, so the blame re-run must find nothing and
    the record must carry no blame fields (a wrong blame is worse than
    none)."""
    cfg = _write_config(tmp_path)
    save_dir = str(tmp_path / "out")
    trainer = _trainer(
        cfg, save_dir, nonfinite_policy="skip", max_nonfinite_steps=3,
    )
    faultinject.configure("trainer.nonfinite=raise@3")
    trainer.train()
    nf_recs = [r for r in _records(save_dir) if r["kind"] == "nonfinite"]
    assert len(nf_recs) == 1
    assert "blame_layer" not in nf_recs[0]


def test_numerics_under_mesh(tmp_path):
    """The sharded train step carries the aux through its explicit
    out_shardings (spmd.shard_train_step extra_outs) — a data=1 mesh on
    the CPU backend exercises exactly that wrapper."""
    cfg = _write_config(tmp_path)
    save_dir = str(tmp_path / "out")
    trainer = _trainer(
        cfg, save_dir, numerics_log_period=2, mesh_shape="data=1",
        num_passes=1,
    )
    trainer.train()
    nums = [r for r in _records(save_dir) if r["kind"] == "numerics"]
    assert nums and all(obs.validate_record(r) == [] for r in nums)
    assert all(r["layers"]["output"]["param_norm"] > 0 for r in nums)


def test_numerics_disabled_under_accumulation(tmp_path, caplog):
    """Honest degradation: gradient accumulation applies updates
    outside the one-batch step, so the aux would misattribute — the
    flag is refused with a warning, not silently mis-measured."""
    import logging

    from paddle_tpu.utils.logging import logger as ptu_logger

    cfg = _write_config(tmp_path)
    src = open(cfg).read().replace(
        "settings(batch_size=64, learning_rate=0.02, "
        "learning_method=AdamOptimizer())",
        "settings(batch_size=64, learning_rate=0.02, "
        "learning_method=AdamOptimizer(), "
        "num_batches_per_send_parameter=2)",
    )
    cfg2 = tmp_path / "cfg_accum.py"
    cfg2.write_text(src)
    ptu_logger.addHandler(caplog.handler)  # propagate=False on this logger
    try:
        with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
            trainer = _trainer(str(cfg2), str(tmp_path / "out"),
                               numerics_log_period=2)
    finally:
        ptu_logger.removeHandler(caplog.handler)
    assert trainer._numerics_groups is None
    assert trainer._numerics_period == 0
    assert any("--numerics_log_period is not supported" in m
               for m in caplog.messages)


# ------------------------------------------------------------- compare


def test_compare_direction_awareness(tmp_path):
    """Peak-bytes growth and a layer newly producing nonfinite
    gradients are REGRESSIONs (exit 1); shrinkage/cleanup improves."""
    from paddle_tpu.observability.compare import compare, load_side

    def run_dir(name, peak, nf_layers):
        d = tmp_path / name
        w = obs.MetricsWriter(str(d), host=0)
        w.emit("numerics", pass_id=0, step=2,
               layers={"output": {"grad_norm": 1.0, "param_norm": 1.0,
                                  "update_ratio": 0.1,
                                  "nonfinite": 1 if nf_layers else 0}},
               nonfinite_layers=nf_layers, global_grad_norm=1.0)
        w.emit("memory", pass_id=0, step=9, host_rss_bytes=10 ** 9,
               hbm_in_use_bytes=peak // 2, hbm_peak_bytes=peak, devices=1)
        w.emit("run_end", status="completed")
        w.close()
        return str(d)

    a = run_dir("a", peak=4 * 10 ** 9, nf_layers=[])
    b = run_dir("b", peak=6 * 10 ** 9, nf_layers=["output"])
    doc = compare(load_side(a), load_side(b))
    assert doc["verdict"] == "REGRESSION"
    assert "hbm_peak_bytes" in doc["regressions"]
    assert "nonfinite_layers" in doc["regressions"]
    # reverse direction improves (footprint shrank, layer went clean)
    doc = compare(load_side(b), load_side(a))
    assert doc["verdict"] == "IMPROVED"
    assert "hbm_peak_bytes" in doc["improvements"]
    # identical sides: no change
    doc = compare(load_side(a), load_side(a))
    assert doc["verdict"] == "NO CHANGE"
