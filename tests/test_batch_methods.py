"""Whole-data batch algorithms (L-BFGS / OWL-QN) — the reference's
trainOnePassBatch mode (Trainer.cpp:492) realized as host-side
quasi-Newton between jitted full-data sweeps.
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.optimizer.batch_methods import BatchMethod
from paddle_tpu.trainer import Trainer, checkpoint
from paddle_tpu.utils.flags import FLAGS

PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    yield
    sys.path.remove(PROVIDER_DIR)


# ------------------------------------------------------------- unit level


def _drive(bm, x, grad_fn, cost_fn, iters):
    """One trainer-shaped pass loop: sweep → record → direction → search."""
    costs = []
    for _ in range(iters):
        g = grad_fn(x)
        bm.record_grad(g)
        d = bm.direction(x, g)
        accepted, x, f = bm.line_search(x, cost_fn(x), g, d, cost_fn)
        costs.append(f)
    return x, costs


def test_lbfgs_quadratic_converges():
    """Strongly convex quadratic: L-BFGS reaches the optimum to high
    precision in far fewer iterations than its dimension."""
    rng = np.random.RandomState(0)
    A = rng.randn(12, 12).astype(np.float64)
    A = A @ A.T + 0.5 * np.eye(12)
    b = rng.randn(12)
    x_star = np.linalg.solve(A, b)

    cost = lambda p: float(0.5 * p["x"] @ A @ p["x"] - b @ p["x"])
    grad = lambda p: {"x": A @ p["x"] - b}

    bm = BatchMethod(method="lbfgs", history=10, learning_rate=1.0)
    # Armijo-only backtracking (the reference's c1/backoff search — no
    # Wolfe curvature condition) converges linearly, not superlinearly
    x, costs = _drive(bm, {"x": np.zeros(12)}, grad, cost, iters=40)
    assert costs[-1] < costs[0]
    np.testing.assert_allclose(x["x"], x_star, atol=1e-3)


def test_owlqn_produces_sparse_solution():
    """Lasso-style objective: coordinates with weak data support end at
    EXACT zero (the orthant projection, not just small values)."""
    rng = np.random.RandomState(1)
    A = np.diag(np.linspace(1.0, 3.0, 10))
    x_true = np.zeros(10)
    x_true[:3] = [2.0, -1.5, 1.0]  # only 3 informative coordinates
    b = A @ x_true + 0.01 * rng.randn(10)

    cost = lambda p: float(0.5 * np.sum((A @ p["x"] - b) ** 2))
    grad = lambda p: {"x": A.T @ (A @ p["x"] - b)}

    bm = BatchMethod(method="owlqn", history=10, l1weight=0.5, learning_rate=1.0)
    x, costs = _drive(bm, {"x": np.zeros(10)}, grad, cost, iters=40)
    assert costs[-1] < costs[0]
    # weak coordinates are exactly zero; strong ones survive
    assert np.all(x["x"][5:] == 0.0), x["x"]
    assert np.all(np.abs(x["x"][:3]) > 0.1), x["x"]


def test_line_search_rejects_ascent():
    """A cost function that cannot improve: line search rejects and the
    params are returned unchanged."""
    cost = lambda p: 1.0  # flat everywhere the search looks
    bm = BatchMethod(method="lbfgs", max_backoff=3, c1=0.5)
    x0 = {"x": np.ones(4)}
    g = {"x": np.ones(4)}
    accepted, x, f = bm.line_search(x0, 1.0, g, {"x": -np.ones(4)}, cost)
    assert not accepted
    np.testing.assert_array_equal(x["x"], x0["x"])


# ------------------------------------------------------- config surface


def test_settings_owlqn_mapping(tmp_path):
    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=32, learning_rate=1.0,
             learning_method=OWLQNOptimizer(history=7, max_backoff=4),
             regularization=L1Regularization(0.25))
    data = data_layer(name="x", size=4)
    out = fc_layer(input=data, size=1, act=LinearActivation(), name="out")
    label = data_layer(name="y", size=1)
    outputs(regression_cost(input=out, label=label))
    """)
    p = tmp_path / "cfg.py"
    p.write_text(src)
    tc = parse_config(str(p))
    oc = tc.opt_config
    assert oc.algorithm == "owlqn"
    assert oc.learning_method == "owlqn"
    assert oc.owlqn_steps == 7
    assert oc.max_backoff == 4
    assert oc.l1weight == 0.25


# --------------------------------------------------------- end to end


def _bow_lbfgs_config(tmp_path):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r}, test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=1.0,
             learning_method=LBFGSOptimizer())
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "lbfgs_config.py"
    cfg_path.write_text(src)
    return str(cfg_path)


def test_lbfgs_trains_end_to_end(tmp_path):
    cfg = parse_config(_bow_lbfgs_config(tmp_path))
    FLAGS.save_dir = str(tmp_path / "out")
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    trainer = Trainer(cfg)
    c0, _, n = trainer._full_data_sweep(trainer.params, trainer._provider(False), False)
    trainer.train(num_passes=8)
    c1, _, _ = trainer._full_data_sweep(trainer.params, trainer._provider(False), False)
    assert n > 0
    assert c1 < 0.25 * c0, (c0, c1)
    assert trainer._batch_method.n_accepted >= 4
    # accepted passes checkpoint through the normal pass-%05d surface
    assert checkpoint.latest_pass(str(tmp_path / "out")) == 7


def test_on_reject_semantics():
    """First rejection with curvature → restart (True); rejection with no
    curvature to drop → stop (False)."""
    bm = BatchMethod(method="lbfgs")
    assert bm.on_reject() is False  # nothing to retry with
    # manufacture curvature history via an accepted quadratic step
    cost = lambda p: float(0.5 * p["x"] @ p["x"])
    grad = lambda p: {"x": p["x"]}
    x = {"x": np.ones(3)}
    g = grad(x)
    _, x, _ = bm.line_search(x, cost(x), g, bm.direction(x, g), cost)
    bm.record_grad(grad(x))
    assert len(bm._hist) == 1
    assert bm.on_reject() is True
    assert len(bm._hist) == 0


def test_lbfgs_resume_from_checkpoint(tmp_path):
    """Batch-mode runs resume through the pass-%05d surface: params load,
    curvature history rebuilds (reference pserver kept it in memory too),
    and the objective keeps improving."""
    cfg = parse_config(_bow_lbfgs_config(tmp_path))
    FLAGS.save_dir = str(tmp_path / "out")
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    tr1 = Trainer(cfg)
    tr1.train(num_passes=3)
    c_mid, _, _ = tr1._full_data_sweep(tr1.params, tr1._provider(False), False)

    FLAGS.start_pass = 3
    cfg2 = parse_config(_bow_lbfgs_config(tmp_path))
    tr2 = Trainer(cfg2)
    # restored exactly where the first run stopped
    c_loaded, _, _ = tr2._full_data_sweep(tr2.params, tr2._provider(False), False)
    np.testing.assert_allclose(c_loaded, c_mid, rtol=1e-6)
    tr2.train(num_passes=6)
    c_end, _, _ = tr2._full_data_sweep(tr2.params, tr2._provider(False), False)
    assert c_end < c_mid, (c_mid, c_end)
    FLAGS.start_pass = 0
