"""Tracked convergence curves at scale (quality-regression tripwires).

BASELINE.md's published quality rows were measured on real datasets this
environment cannot download (see doc/performance.md "Quality-parity rows
and real data" for the exact dependency list). What CI *can* pin is the
convergence CURVE on deterministic synthetic data at meaningful row
counts: every per-pass held-out cost must stay inside a band recorded
from a known-good run, so a regression in the optimizer, feeder order,
rng plumbing, bf16 policy or layer math fails the suite even when the
final cost would still clear a loose "learned something" threshold.

Pinned values were measured on 2026-07-31 (round 5) on the CPU backend
with the default seed; the data and batch order are fully deterministic,
so the bands are tight (±3%) — they allow float/scheduling drift, not
behavior drift. At ±3% the pinned curves' shapes (LR strictly
decreasing; recommendation's pass-3 overfit jump) are implied by the
band itself, so no extra shape assertion can fail on drift the band
allows.

- quick_start LR: 25k train / 5k test synthetic rows (the reference's
  test cap is 12.5k/class; its Amazon train set is larger than CI can
  afford, see doc note). Final test error lands at ~7.6%, the same
  ballpark as the reference's published 8.652% on real data
  (doc/demo/quick_start/index_en.md:199-220).
- recommendation: 20k train / 4k test synthetic ratings. The held-out
  curve bottoms at pass 2 and then OVERFITS (train keeps dropping) —
  the same best-pass-selection shape the reference's tutorial reports
  (best pass 9 on ML-1M, ml_regression.rst:333-343); the band pins both
  the descent and the turn.
- sentiment: 1.6k train / 800 test synthetic reviews with the
  provider's sort_by_length bucketing ON — this curve doubles as the
  bucketing feature's training-interaction tripwire (reference
  real-data row: 0.115645 bi-LSTM error, needs IMDB).
- image_classification: the small=1 VGG/CIFAR configuration (conv/BN/
  pool family tripwire; reference real-data rows need CIFAR-10).
"""

from demo_utils import setup_demo, train_demo


def _curve(tmp_path, demo, cfg_name, train_entries, test_entries, passes):
    setup_demo(
        tmp_path, demo,
        train_lines=[f"seed-train-{i}" for i in range(1, train_entries + 1)],
        test_lines=[f"seed-test-{i}" for i in range(1, test_entries + 1)],
    )
    trainer, _ = train_demo(tmp_path, cfg_name, num_passes=passes)
    return trainer.test_history


def _assert_curve(history, pinned, rtol, key="cost"):
    assert len(history) == len(pinned), (history, pinned)
    got = [res[key] for _, res in history]
    for i, (g, want) in enumerate(zip(got, pinned)):
        assert abs(g - want) <= rtol * want, (
            f"pass {i}: {key}={g:.4f} outside ±{rtol:.0%} of the pinned "
            f"{want:.4f} — convergence behavior changed (full curve {got} "
            f"vs pinned {pinned}); if the change is an intended improvement, "
            f"re-pin the band with the new measured curve"
        )
    return got


# the pinned curves themselves encode the required shape (decreasing for
# LR, descent-then-overfit-turn for recommendation); the band is the only
# assertion, so drift the band explicitly allows can never fail shape-wise
PINNED_LR_COST = [0.29132, 0.22416, 0.19969, 0.18781]


def test_quick_start_lr_curve(tmp_path):
    history = _curve(tmp_path, "quick_start", "trainer_config.lr.py",
                     train_entries=25, test_entries=5, passes=4)
    _assert_curve(history, PINNED_LR_COST, rtol=0.03)
    # final test error in the reference's published ballpark (8.652% on
    # real Amazon data; synthetic lands ~7.6%)
    err = history[-1][1]["__cost_0__.classification_error.classification_error"]
    assert 0.05 < err < 0.10, err


PINNED_REC_COST = [0.44199, 0.44118, 0.43898, 0.47360]


def test_recommendation_curve(tmp_path):
    history = _curve(tmp_path, "recommendation", "trainer_config.py",
                     train_entries=10, test_entries=2, passes=4)
    costs = _assert_curve(history, PINNED_REC_COST, rtol=0.03)
    # the overfit turn (implied by the band at ±3%: 0.4736*0.97 >
    # 0.43898*1.03): held-out cost must rise after the best pass while
    # training cost keeps falling — the early-stopping shape the
    # reference's tutorial reports
    assert costs[3] > costs[2], costs


# measured 2026-07-31 (round 5) WITH sort_by_length=True in the provider
# (the bucketing changes batch composition, so this curve is the
# feature's regression tripwire too); 1600 train / 800 test reviews
PINNED_SENTIMENT_COST = [0.29417, 0.14709, 0.10738]


def test_sentiment_curve(tmp_path):
    history = _curve(tmp_path, "sentiment", "trainer_config.py",
                     train_entries=2, test_entries=1, passes=3)
    _assert_curve(history, PINNED_SENTIMENT_COST, rtol=0.03)
    # the reference's published bi-LSTM row is 0.115645 error on real
    # IMDB (doc/demo/sentiment_analysis.md:272-275, needs real data);
    # the synthetic corpus is easier — err must stay well under 0.08
    err = history[-1][1][
        "__cost_0__.classification_error.classification_error"]
    assert err < 0.08, (err, history)


# measured 2026-07-31 (round 5); pass 3 uptick (0.00719) is part of the
# pinned shape on this tiny set, so only 3 passes are tracked
PINNED_VGG_COST = [0.04350, 0.00809, 0.00707]


def test_vgg_cifar_curve(tmp_path):
    setup_demo(tmp_path, "image_classification")  # demo ships its lists
    trainer, _ = train_demo(tmp_path, "vgg_16_cifar.py", num_passes=3,
                            config_arg_str="small=1")
    _assert_curve(trainer.test_history, PINNED_VGG_COST, rtol=0.03)
