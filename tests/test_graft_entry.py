"""The driver-facing entry points, tested the way the driver runs them.

Round 1 failed both gates (bench crash, dryrun hang) while 90 tests
passed — because nothing tested __graft_entry__ or bench.py themselves.
These tests run them in SUBPROCESSES with the same hostile environment
the driver has (accelerator plugin pre-registered, no JAX_PLATFORMS
pre-set) and enforce a hard wall-clock budget.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, timeout, extra_env=None):
    env = dict(os.environ)
    # emulate the driver: no pre-forced platform; the entry point must
    # defend itself against the pre-registered accelerator plugin
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_dryrun_multichip_under_budget():
    out = _run(
        "import __graft_entry__ as g; g.dryrun_multichip(8)",
        timeout=240,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    assert "OK" in out.stdout


def test_entry_compiles_single_device():
    code = (
        "from paddle_tpu.utils.backend_guard import ensure_cpu_mesh;"
        "ensure_cpu_mesh(1);"
        "import __graft_entry__ as g, jax;"
        "fn, args = g.entry();"
        "out = jax.jit(fn)(*args);"
        "print('shape', out.shape)"
    )
    out = _run(code, timeout=240)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "shape" in out.stdout


def test_bench_emits_json_even_without_accelerator():
    # 5s probe timeout: the accelerator probe must fail fast and the bench
    # must still print exactly one parseable JSON line on the CPU fallback
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO,
        env={**os.environ, "PADDLE_TPU_BENCH_PROBE_TIMEOUT": "5",
             "PYTHONPATH": REPO},
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out.stdout
    parsed = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in parsed, parsed
    assert parsed["metric"] != "bench_failed", parsed
