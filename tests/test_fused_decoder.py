"""Fused attention-GRU decoder (OptimizationConfig.pallas_decoder):
kernel-level parity against a pure-jax scan of the same math, and
machine-level train-step parity on the real seqToseq decoder group —
loss and every parameter gradient must match the unfused recurrent-group
scan, with the fused path PROVEN to have engaged.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.graph  # noqa: F401
from paddle_tpu.graph import fused_decoder as fd
from paddle_tpu.ops.pallas_attention_gru import fused_attention_gru, supported


def _ref_decoder(ep, ev, em, xw, dmask, h0, wa, ba, v, wctx, wg):
    """The decoder loop in plain jax — the scan semantics the kernel
    replaces (raw h_new stream, masked carry)."""
    f32 = jnp.float32
    D = xw.shape[2] // 3

    def step(h, inp):
        xw_t, dm_t = inp
        m = (h.astype(wa.dtype) @ wa).astype(f32) + ba.astype(f32)
        comb = jnp.tanh(ep.astype(f32) + m[None])
        s = jnp.sum(comb * v.astype(f32)[None], -1)
        s = jnp.where(em[:, :, 0] > 0, s, -1e30)
        a = jax.nn.softmax(s, axis=0)
        a = jnp.where(em[:, :, 0] > 0, a, 0.0)
        ctx = jnp.sum(a[:, :, None] * ev.astype(f32), 0)
        din = (ctx.astype(wctx.dtype) @ wctx).astype(f32) + xw_t.astype(f32)
        xg, xc = din[:, : 2 * D], din[:, 2 * D :]
        g = jax.nn.sigmoid(xg + (h.astype(wg.dtype) @ wg[:, : 2 * D]).astype(f32))
        u, r = g[:, :D], g[:, D:]
        c = jnp.tanh(xc + ((r * h).astype(wg.dtype) @ wg[:, 2 * D :]).astype(f32))
        h_new = u * h + (1 - u) * c
        return dm_t * h_new + (1 - dm_t) * h, h_new

    _, ys = jax.lax.scan(step, h0.astype(f32), (xw, dmask))
    return ys


def _operands(key, Te=5, Td=7, B=8, D=16, E=32, dtype=jnp.float32):
    ks = jax.random.split(key, 12)
    f32 = jnp.float32
    em = (jax.random.uniform(ks[2], (Te, B, 1)) > 0.2).astype(f32)
    em = em.at[0].set(1.0)
    dmask = (jax.random.uniform(ks[4], (Td, B, 1)) > 0.3).astype(f32)
    return dict(
        ep=jax.random.normal(ks[0], (Te, B, D), f32).astype(dtype),
        ev=jax.random.normal(ks[1], (Te, B, E), f32).astype(dtype),
        em=em.astype(dtype),
        xw=(jax.random.normal(ks[3], (Td, B, 3 * D), f32) * 0.5).astype(dtype),
        dmask=dmask.astype(dtype),
        h0=(jax.random.normal(ks[5], (B, D), f32) * 0.5).astype(dtype),
        wa=(jax.random.normal(ks[6], (D, D), f32) * 0.2).astype(dtype),
        ba=(jax.random.normal(ks[7], (1, D), f32) * 0.1).astype(dtype),
        v=(jax.random.normal(ks[8], (1, D), f32) * 0.3).astype(dtype),
        wctx=(jax.random.normal(ks[9], (E, 3 * D), f32) * 0.15).astype(dtype),
        wg=(jax.random.normal(ks[10], (D, 3 * D), f32) * 0.2).astype(dtype),
    )


def test_kernel_forward_and_grad_parity():
    ops = _operands(jax.random.PRNGKey(0))
    order = ("ep", "ev", "em", "xw", "dmask", "h0",
             "wa", "ba", "v", "wctx", "wg")
    args = [ops[k] for k in order]
    acts = ("tanh", "sigmoid")
    ys_k = fused_attention_gru(*args, acts, True)
    ys_r = _ref_decoder(*args)
    np.testing.assert_allclose(
        np.asarray(ys_k, np.float32), np.asarray(ys_r), rtol=1e-5, atol=1e-5
    )
    cot = jax.random.normal(jax.random.PRNGKey(9), ys_r.shape)
    diff = (0, 1, 3, 5, 6, 7, 8, 9, 10)  # skip the masks
    gk = jax.grad(
        lambda *a: jnp.sum(fused_attention_gru(*a, acts, True).astype(jnp.float32) * cot),
        diff,
    )(*args)
    gr = jax.grad(lambda *a: jnp.sum(_ref_decoder(*a) * cot), diff)(*args)
    for name, a, b in zip(("dep", "dev", "dxw", "dh0", "dwa", "dba", "dv",
                           "dwctx", "dwg"), gk, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-4, atol=1e-4, err_msg=name,
        )


def test_supported_gate():
    assert supported(448, 32, 512, 1024, 2)       # flagship shapes
    assert not supported(448, 32, 500, 1024, 2)   # D not lane-aligned
    assert not supported(12, 32, 512, 1024, 2)    # B has no block size
    assert supported(7, 32, 512, 1024, 2)         # tiny-B full-block fallback


# ---------------------------------------------------------- machine level


def _nmt_tc(dim=16, vocab=50, B=4):
    from paddle_tpu.flagship import nmt_config

    return nmt_config(vocab=vocab, dim=dim, batch_size=B)


def _nmt_batch(vocab=50, B=4, T=5):
    from paddle_tpu.flagship import nmt_batch

    return nmt_batch(vocab=vocab, B=B, T=T)


@pytest.mark.parametrize("dim", [16])
def test_machine_parity_seqtoseq(monkeypatch, dim):
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.graph import GradientMachine

    tc = _nmt_tc(dim=dim)
    batch = _nmt_batch()
    rng = jax.random.PRNGKey(0)
    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, pallas_decoder=True)
    params = gm_off.init_params(seed=11)

    # prove engagement: the fused runner must be called and return a
    # non-None stream
    calls = {}
    orig = fd.run_fused_decoder

    def spy(*a, **kw):
        out = orig(*a, **kw)
        calls["ys"] = out
        return out

    monkeypatch.setattr(fd, "run_fused_decoder", spy)
    loss_on, grads_on, _, _ = gm_on.grad_fn()(params, batch, rng)
    assert calls.get("ys") is not None, "fused decoder path did not engage"

    loss_off, grads_off, _, _ = gm_off.grad_fn()(params, batch, rng)
    np.testing.assert_allclose(
        float(loss_on), float(loss_off), rtol=1e-5, atol=1e-6
    )
    for name in sorted(grads_off):
        np.testing.assert_allclose(
            np.asarray(grads_on[name], np.float32),
            np.asarray(grads_off[name], np.float32),
            rtol=2e-4, atol=2e-5, err_msg=name,
        )


def test_non_matching_group_falls_back(monkeypatch):
    """A plain (non-attention) recurrent group must not engage the fused
    path even with the knob on."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    import textwrap

    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine, make_dense, make_seq

    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=4, learning_rate=1e-3)
    x = data_layer(name="x", size=8)

    def step(inp):
        mem = memory(name="m", size=8)
        out = fc_layer(input=[inp, mem], size=8, act=TanhActivation(),
                       name="m", bias_attr=False)
        return out

    r = recurrent_group(name="rg", step=step, input=[x])
    last = last_seq(input=r)
    lbl = data_layer(name="y", size=2)
    fc = fc_layer(input=last, size=2, act=SoftmaxActivation())
    outputs(classification_cost(name="cost", input=fc, label=lbl))
    """)
    import tempfile, os as _os

    with tempfile.TemporaryDirectory() as td:
        p = _os.path.join(td, "cfg.py")
        with open(p, "w") as f:
            f.write(src)
        tc = parse_config(p)
    calls = {"n": 0}
    orig = fd.run_fused_decoder

    def spy(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(fd, "run_fused_decoder", spy)
    gm = GradientMachine(tc.model_config, pallas_decoder=True)
    params = gm.init_params(seed=1)
    rng = np.random.RandomState(0)
    onehot = np.zeros((4, 2), np.float32)
    onehot[np.arange(4), rng.randint(0, 2, 4)] = 1.0
    batch = {
        "x": make_seq(rng.randn(4, 6, 8).astype(np.float32),
                      np.array([6, 5, 3, 6], np.int32)),
        "y": make_dense(onehot),
    }
    loss, grads, _, _ = gm.grad_fn()(params, batch, jax.random.PRNGKey(0))
    assert calls["n"] == 0
    assert np.isfinite(float(loss))


def test_machine_parity_seqtoseq_bf16(monkeypatch):
    """The bench configuration (bf16 compute) — looser tolerance, but
    the kernel must track the scan within bf16 noise."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    import jax.numpy as jnp

    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.machine import compute_dtype_of

    tc = _nmt_tc(dim=16)
    tc.opt_config.dtype = "bfloat16"
    cd = compute_dtype_of(tc.opt_config)
    batch = _nmt_batch()
    rng = jax.random.PRNGKey(0)
    gm_off = GradientMachine(tc.model_config, compute_dtype=cd)
    gm_on = GradientMachine(tc.model_config, compute_dtype=cd,
                            pallas_decoder=True)
    params = gm_off.init_params(seed=11)
    loss_off, grads_off, _, _ = gm_off.grad_fn()(params, batch, rng)
    loss_on, grads_on, _, _ = gm_on.grad_fn()(params, batch, rng)
    np.testing.assert_allclose(float(loss_on), float(loss_off),
                               rtol=5e-3, atol=1e-3)
    for name in sorted(grads_off):
        a = np.asarray(grads_on[name], np.float32)
        b = np.asarray(grads_off[name], np.float32)
        scale = max(1e-3, float(np.max(np.abs(b))))
        np.testing.assert_allclose(a / scale, b / scale, rtol=0.0,
                                   atol=0.05, err_msg=name)


def test_machine_parity_biased_template(monkeypatch):
    """A hand-built decoder step with biases on the attention transform,
    combine, and din mixed layers (the template allows them; the runner
    folds them into b_att / xw) — parity proves the folds are right."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    import textwrap

    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine, make_seq

    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=4, learning_rate=1e-3)
    src_w = data_layer(name="src_word", size=40)
    src_emb = embedding_layer(input=src_w, size=12,
                              param_attr=ParamAttr(name="_semb"))
    enc = simple_gru(input=src_emb, size=12)
    enc_rev = simple_gru(input=src_emb, size=12, reverse=True)
    enc_vec = concat_layer(input=[enc, enc_rev])
    with mixed_layer(size=12) as enc_proj:
        enc_proj += full_matrix_projection(enc_vec)
    boot_first = first_seq(input=enc_rev)
    with mixed_layer(size=12, act=TanhActivation()) as boot:
        boot += full_matrix_projection(boot_first)

    def step(enc_v, enc_p, cur):
        mem = memory(name="dec", size=12, boot_layer=boot)
        with mixed_layer(size=12, bias_attr=True,
                         name="att_transform") as m:
            m += full_matrix_projection(mem)
        ex = expand_layer(input=m, expand_as=enc_v, name="att_expand")
        with mixed_layer(size=12, act=TanhActivation(), bias_attr=True,
                         name="att_combine") as comb:
            comb += identity_projection(ex)
            comb += identity_projection(enc_p)
        att = fc_layer(input=comb, size=1, act=SequenceSoftmaxActivation(),
                       bias_attr=False, name="att_softmax")
        sc = scaling_layer(weight=att, input=enc_v, name="att_scaling")
        ctxt = pooling_layer(input=sc, pooling_type=SumPooling(),
                             name="att_pool")
        with mixed_layer(size=12 * 3, bias_attr=True, name="din") as din:
            din += full_matrix_projection(ctxt)
            din += full_matrix_projection(cur)
        g = gru_step_layer(name="dec", input=din, output_mem=mem, size=12)
        with mixed_layer(size=40, bias_attr=True,
                         act=SoftmaxActivation()) as out:
            out += full_matrix_projection(input=g)
        return out

    trg = embedding_layer(input=data_layer(name="trg_word", size=40),
                          size=12, param_attr=ParamAttr(name="_temb"))
    dec = recurrent_group(name="dgrp", step=step,
                          input=[StaticInput(input=enc_vec, is_seq=True),
                                 StaticInput(input=enc_proj, is_seq=True),
                                 trg])
    lbl = data_layer(name="trg_next", size=40)
    outputs(classification_cost(name="cost", input=dec, label=lbl))
    """)
    import tempfile, os as _os

    with tempfile.TemporaryDirectory() as td:
        p = _os.path.join(td, "cfg.py")
        with open(p, "w") as f:
            f.write(src)
        tc = parse_config(p)

    rng_np = np.random.RandomState(4)
    B, Ts, Tt = 4, 6, 5
    src_ids = rng_np.randint(0, 40, (B, Ts)).astype(np.int32)
    trg_ids = rng_np.randint(0, 40, (B, Tt)).astype(np.int32)
    nxt_ids = rng_np.randint(0, 40, (B, Tt)).astype(np.int32)
    sl = np.array([6, 5, 4, 6], np.int32)
    tl = np.array([5, 5, 3, 4], np.int32)
    batch = {
        "src_word": make_seq(None, sl, ids=src_ids),
        "trg_word": make_seq(None, tl, ids=trg_ids),
        "trg_next": make_seq(None, tl, ids=nxt_ids),
    }
    rng = jax.random.PRNGKey(0)
    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, pallas_decoder=True)
    params = gm_off.init_params(seed=21)

    calls = {}
    orig = fd.run_fused_decoder

    def spy(*a, **kw):
        out = orig(*a, **kw)
        calls["ys"] = out
        return out

    monkeypatch.setattr(fd, "run_fused_decoder", spy)
    loss_on, grads_on, _, _ = gm_on.grad_fn()(params, batch, rng)
    assert calls.get("ys") is not None, "biased template did not engage"
    loss_off, grads_off, _, _ = gm_off.grad_fn()(params, batch, rng)
    np.testing.assert_allclose(float(loss_on), float(loss_off),
                               rtol=1e-5, atol=1e-6)
    for name in sorted(grads_off):
        np.testing.assert_allclose(
            np.asarray(grads_on[name], np.float32),
            np.asarray(grads_off[name], np.float32),
            rtol=2e-4, atol=2e-5, err_msg=name,
        )


def test_fused_decoder_under_data_mesh(monkeypatch):
    """A purely data-parallel mesh runs the decoder kernel per-shard via
    shard_map: sharded fused train step == unsharded scan step, with
    engagement asserted."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    import jax.numpy as jnp

    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.optimizer import Updater
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.spmd import shard_train_step

    B = 8
    tc = _nmt_tc(dim=16, B=B)
    batch = _nmt_batch(B=B)
    rng = jax.random.PRNGKey(0)

    def step_fns(tc, pallas_decoder):
        gm = GradientMachine(tc.model_config, pallas_decoder=pallas_decoder)
        updater = Updater(tc.opt_config, tc.model_config)
        params = gm.init_params(seed=13)
        opt_state = updater.init_state(params)
        grad_fn = gm.grad_fn()

        def step(params, opt_state, batch, rng, bs):
            loss, grads, outputs, state_updates = grad_fn(params, batch, rng)
            new_params, new_opt = updater(params, grads, opt_state, bs)
            for k, v in state_updates.items():
                new_params[k] = v
            return new_params, new_opt, loss, loss

        return gm, step, params, opt_state

    gm0, step0, params0, opt0 = step_fns(tc, False)
    p_ref, _, loss_ref, _ = jax.jit(step0)(
        params0, opt0, batch, rng, jnp.asarray(float(B))
    )

    calls = {}
    orig = fd.run_fused_decoder

    def spy(*a, **kw):
        out = orig(*a, **kw)
        calls["ys"] = out
        return out

    monkeypatch.setattr(fd, "run_fused_decoder", spy)
    tc2 = _nmt_tc(dim=16, B=B)
    tc2.opt_config.mesh_shape = "data=4"
    gm2, step2, params2, opt2 = step_fns(tc2, True)
    gm2.mesh = make_mesh("data=4")
    sharded = shard_train_step(step2, gm2.mesh, gm2)
    p_sh, _, loss_sh, _ = sharded(params2, opt2, batch, rng,
                                  jnp.asarray(float(B)))
    assert calls.get("ys") is not None, "fused decoder did not engage on mesh"
    np.testing.assert_allclose(float(loss_sh), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    for k in p_ref:
        np.testing.assert_allclose(
            np.asarray(p_sh[k], np.float32), np.asarray(p_ref[k], np.float32),
            rtol=2e-4, atol=2e-5, err_msg=k,
        )


def test_all_pallas_knobs_composed(monkeypatch):
    """pallas_rnn (encoder GRUs) + pallas_decoder + the flat interface
    all on at once — the composed-defaults candidate the session
    measures if the individual A/Bs win — must match the plain scan.
    Shapes pass the GRU kernel gate (H%128, B%8) and BOTH kernel paths
    assert engagement, so neither knob can vacuously scan-fall-back."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("PADDLE_TPU_PALLAS_FLAT", "1")
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.ops import pallas_gru as pg

    tc = _nmt_tc(dim=128, B=8)
    batch = _nmt_batch(B=8)
    rng = jax.random.PRNGKey(0)
    gm0 = GradientMachine(tc.model_config)
    params = gm0.init_params(seed=11)
    loss0, grads0, _, _ = gm0.grad_fn()(params, batch, rng)

    calls = {"dec": 0, "gru_flat": 0}
    orig_dec = fd.run_fused_decoder
    orig_gru = pg.gru_layer_forward

    def spy_dec(*a, **kw):
        out = orig_dec(*a, **kw)
        calls["dec"] += int(out is not None)
        return out

    def spy_gru(cfg, x, mask, w, bias, interpret, x_bt=None):
        calls["gru_flat"] += int(x_bt is not None)
        return orig_gru(cfg, x, mask, w, bias, interpret, x_bt=x_bt)

    monkeypatch.setattr(fd, "run_fused_decoder", spy_dec)
    monkeypatch.setattr(pg, "gru_layer_forward", spy_gru)
    gm1 = GradientMachine(tc.model_config, pallas_rnn=True,
                          pallas_decoder=True)
    loss1, grads1, _, _ = gm1.grad_fn()(params, batch, rng)
    assert calls["dec"] > 0, "decoder kernel did not engage"
    assert calls["gru_flat"] > 0, "flat GRU kernel did not engage"
    np.testing.assert_allclose(float(loss1), float(loss0),
                               rtol=1e-5, atol=1e-6)
    for k in sorted(grads0):
        np.testing.assert_allclose(
            np.asarray(grads1[k], np.float32),
            np.asarray(grads0[k], np.float32),
            rtol=2e-4, atol=2e-5, err_msg=k,
        )
