"""batches_per_launch (fused device launches): k consecutive same-shape
batches train in ONE dispatch via lax.scan, each with its own optimizer
update — numerics match the unfused loop (the TPU-native answer to
per-step dispatch latency; no reference counterpart, see
doc/performance.md).
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    yield
    sys.path.remove(PROVIDER_DIR)


DEFAULT_BODY = """
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
"""

DROPOUT_BODY = """
    data = data_layer(name="word", size=100)
    hid = fc_layer(input=data, size=32, act=ReluActivation())
    drop = dropout_layer(input=hid, dropout_rate=0.5)
    output = fc_layer(input=drop, size=2, act=SoftmaxActivation(), name="output")
"""


def _config(tmp_path, extra_settings="", body=DEFAULT_BODY, with_test=True):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n3\n")
    if with_test:
        test_list = tmp_path / "test.list"
        test_list.write_text("99\n")
        test_ref = str(test_list)
    else:
        test_ref = None
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r},
                            test_list={test_ref!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02,
             learning_method=AdamOptimizer(){extra_settings})
{body}
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / f"cfg{abs(hash(extra_settings + body)) % 997}.py"
    cfg_path.write_text(src)
    return parse_config(str(cfg_path))


def _fresh_flags(tmp_path, name):
    FLAGS.save_dir = str(tmp_path / name)
    FLAGS.num_passes = 2
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.seed = 7


def test_fused_matches_unfused(tmp_path):
    _fresh_flags(tmp_path, "out1")
    t1 = Trainer(_config(tmp_path))
    t1.train(num_passes=2)
    r1 = t1.test()

    _fresh_flags(tmp_path, "out3")
    cfg3 = _config(tmp_path, extra_settings=", batches_per_launch=3")
    assert cfg3.opt_config.batches_per_launch == 3  # settings() plumbing
    t3 = Trainer(cfg3)
    assert t3._fuse_k == 3
    t3.train(num_passes=2)
    r3 = t3.test()

    # same batches in the same order, one optimizer update per batch either
    # way — parameters agree to float tolerance (fusion only changes how
    # XLA schedules the same math) and the optimizer stepped once per batch
    assert int(t1.opt_state.step) == int(t3.opt_state.step)
    for k in t1.params:
        np.testing.assert_allclose(
            np.asarray(t1.params[k]), np.asarray(t3.params[k]),
            rtol=2e-5, atol=2e-6, err_msg=k,
        )
    for k, v in r1.items():
        assert abs(v - r3[k]) < 1e-4, (k, v, r3[k])


def test_fused_remainder_runs_single(tmp_path):
    # 1200 samples / batch 64 = 18 full batches + one 48-sample remainder:
    # with k=4 the remainder (and the flushed tail of full batches) must
    # run through the single-step path, never dropping a batch
    _fresh_flags(tmp_path, "out4")
    cfg = _config(tmp_path, extra_settings=", batches_per_launch=4")
    t = Trainer(cfg)
    t.train(num_passes=1)
    assert int(t.opt_state.step) == 19  # every batch updated exactly once


def test_launch_groups_grouping(tmp_path):
    _fresh_flags(tmp_path, "out5")
    cfg = _config(tmp_path, extra_settings=", batches_per_launch=2")
    t = Trainer(cfg)

    def item(n, shape):
        return (n, None, {"x": np.zeros(shape, np.float32)})

    stream = [
        item(4, (4, 3)),  # a
        item(4, (4, 3)),  # b -> fused(a,b)
        item(4, (4, 3)),  # c
        item(4, (4, 5)),  # shape change: c flushes single
        item(4, (4, 5)),  # -> fused(d,e)
        item(2, (2, 5)),  # tail -> single
    ]
    got = [(kind, g) for kind, g in t._launch_groups(iter(stream))]
    kinds = [k for k, _ in got]
    assert kinds == ["fused", "single", "fused", "single"]
    assert [len(g) for k, g in got if k == "fused"] == [2, 2]
    # order preserved overall
    flat = []
    for k, g in got:
        flat.extend(g if k == "fused" else [g])
    assert [f[0] for f in flat] == [4, 4, 4, 4, 4, 2]
    assert [f[2]["x"].shape for f in flat] == [
        (4, 3), (4, 3), (4, 3), (4, 5), (4, 5), (2, 5)
    ]


def test_fused_sequence_model_trains(tmp_path):
    # sequence batches (Argument with ids + seq_lengths) stack through the
    # fused scan when the padded T agrees; differing-T batches fall back
    # to single dispatches via the shape signature — either way every
    # batch gets exactly one optimizer update
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r},
                            test_list={str(test_list)!r},
                            module="synthetic_bow", obj="process_seq")
    settings(batch_size=25, learning_rate=0.01,
             learning_method=AdamOptimizer(), batches_per_launch=2)
    words = data_layer(name="words", size=100)
    emb = embedding_layer(input=words, size=16)
    lstm = simple_lstm(input=emb, size=16)
    pool = pooling_layer(input=lstm, pooling_type=MaxPooling())
    output = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "lstm_fused.py"
    cfg_path.write_text(src)
    _fresh_flags(tmp_path, "out_seq")
    t = Trainer(parse_config(str(cfg_path)))
    t.train(num_passes=1)
    # 2 files x 200 samples / 25 = 16 batches
    assert int(t.opt_state.step) == 16
    err = [v for k, v in t.test().items() if "classification_error" in k][0]
    assert err < 0.2


def test_fused_launch_composes_with_pallas_rnn(tmp_path, monkeypatch):
    # both knobs at once: the pallas sequence kernel runs inside the
    # fused-launch lax.scan body (a custom call in the scan is fine) and
    # the trained parameters match the plain (unfused, scan-path) loop.
    # B must satisfy the kernel's B % 8 gate or the pallas path silently
    # declines (tests/test_pallas_lstm.py pins that rejection).
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")

    def cfg_src(extra):
        return textwrap.dedent(f"""
        from paddle_tpu.trainer_config_helpers import *

        define_py_data_sources2(train_list={str(train_list)!r},
                                test_list={str(test_list)!r},
                                module="synthetic_bow", obj="process_seq")
        settings(batch_size=40, learning_rate=0.01,
                 learning_method=AdamOptimizer(){extra})
        words = data_layer(name="words", size=100)
        emb = embedding_layer(input=words, size=16)
        lstm = simple_lstm(input=emb, size=128)
        pool = pooling_layer(input=lstm, pooling_type=MaxPooling())
        output = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=2)
        outputs(classification_cost(input=output, label=label))
        """)

    from paddle_tpu.ops import pallas_lstm as pk

    calls = []
    orig = pk.lstm_layer_forward
    monkeypatch.setattr(
        pk, "lstm_layer_forward",
        lambda *a, **k: (calls.append(1), orig(*a, **k))[1],
    )

    p_base = tmp_path / "base.py"
    p_base.write_text(cfg_src(""))
    _fresh_flags(tmp_path, "out_base")
    t_base = Trainer(parse_config(str(p_base)))
    t_base.train(num_passes=1)
    assert not calls  # baseline: plain loop, scan path

    p_both = tmp_path / "both.py"
    p_both.write_text(cfg_src(", batches_per_launch=2, pallas_rnn=True"))
    _fresh_flags(tmp_path, "out_both")
    t_both = Trainer(parse_config(str(p_both)))
    t_both.train(num_passes=1)
    assert calls  # the kernel ran inside the fused-launch scan

    assert int(t_both.opt_state.step) == int(t_base.opt_state.step) == 5
    for k in t_base.params:
        np.testing.assert_allclose(
            np.asarray(t_both.params[k]), np.asarray(t_base.params[k]),
            rtol=5e-4, atol=5e-5, err_msg=k,
        )


def test_fused_nan_gate_fires_before_housekeeping(tmp_path):
    # a non-finite loss inside a fused launch must abort with the launch
    # batch index BEFORE any periodic housekeeping can observe (and e.g.
    # checkpoint) the poisoned params
    _fresh_flags(tmp_path, "out_nan")
    cfg = _config(tmp_path, extra_settings=", batches_per_launch=4")
    # Adam normalizes updates, so a large-but-finite lr keeps the loss
    # finite; an inf lr poisons the params after the first update and the
    # SECOND batch of the first launch sees a non-finite loss
    cfg.opt_config.learning_rate = float("inf")
    FLAGS.saving_period_by_batches = 1  # housekeeping WOULD save each batch
    try:
        t = Trainer(cfg)
        with pytest.raises(FloatingPointError, match="launch of"):
            t.train(num_passes=1)
    finally:
        FLAGS.saving_period_by_batches = 0
    # the gate fired before per-batch housekeeping: despite a save period
    # of one batch, no checkpoint of the poisoned params was written
    # (telemetry artifacts — metrics.jsonl — are fine; pass dirs are not)
    save_dir = str(tmp_path / "out_nan")
    assert not os.path.exists(save_dir) or not [
        d for d in os.listdir(save_dir) if d.startswith("pass-")
    ]


def test_fused_rejects_accumulation(tmp_path):
    _fresh_flags(tmp_path, "out6")
    cfg = _config(
        tmp_path,
        extra_settings=(
            ", batches_per_launch=2, num_batches_per_send_parameter=2"
        ),
    )
    with pytest.raises(ValueError, match="batches_per_launch"):
        Trainer(cfg)


def test_fused_matches_unfused_with_dropout(tmp_path):
    """rng-using models too: the fused path consumes one split of the
    pass rng chain PER BATCH exactly like the unfused loop, so dropout
    masks are identical and k>1 reproduces k=1 numerics bitwise (up to
    float scheduling tolerance)."""

    _fresh_flags(tmp_path, "outd1")
    t1 = Trainer(_config(tmp_path, body=DROPOUT_BODY, with_test=False))
    t1.train(num_passes=1)

    _fresh_flags(tmp_path, "outd3")
    t3 = Trainer(_config(tmp_path, ", batches_per_launch=3",
                         body=DROPOUT_BODY, with_test=False))
    t3.train(num_passes=1)

    assert int(t1.opt_state.step) == int(t3.opt_state.step)
    for k in t1.params:
        np.testing.assert_allclose(
            np.asarray(t1.params[k], dtype=np.float32),
            np.asarray(t3.params[k], dtype=np.float32),
            rtol=2e-5, atol=2e-6, err_msg=k,
        )
