"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the analog of the reference's
CPU-only stub build, /root/reference/paddle/cuda/include/stub/, which lets
the whole suite run without accelerators): sharding/collective tests get 8
devices; numerics match the TPU path because both are XLA.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
