"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the analog of the reference's
CPU-only stub build, /root/reference/paddle/cuda/include/stub/, which lets
the whole suite run without accelerators): sharding/collective tests get 8
devices; numerics match the TPU path because both are XLA.

The environment may pre-register an accelerator PJRT plugin (e.g. the
axon TPU tunnel) via sitecustomize and set JAX_PLATFORMS to it; tests must
never claim the real chip, so we force the CPU platform and drop any
non-CPU backend factories before any backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# jax may already be imported (sitecustomize registers the accelerator
# plugin at interpreter start), so the env var was read too early —
# override the config directly as well.
jax.config.update("jax_platforms", "cpu")

for _name in list(_xb._backend_factories):
    # keep "tpu" registered (never initialized under JAX_PLATFORMS=cpu;
    # there is no local libtpu — the real chip is behind the axon plugin)
    # so pallas/checkify can still register their tpu lowering rules
    if _name not in ("cpu", "tpu"):
        del _xb._backend_factories[_name]

jax.config.update("jax_threefry_partitionable", True)

assert len(jax.devices()) == 8, (
    "test suite expects 8 virtual CPU devices; got "
    f"{jax.devices()} — check conftest ordering"
)
