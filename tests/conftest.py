"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the analog of the reference's
CPU-only stub build, /root/reference/paddle/cuda/include/stub/, which lets
the whole suite run without accelerators): sharding/collective tests get 8
devices; numerics match the TPU path because both are XLA.

The backend hardening (force CPU platform, drop the pre-registered
accelerator plugin before any backend initializes) lives in
paddle_tpu.utils.backend_guard so the driver entry points share it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.utils.backend_guard import ensure_cpu_mesh  # noqa: E402

ensure_cpu_mesh(8)

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)

assert len(jax.devices()) == 8, (
    "test suite expects 8 virtual CPU devices; got "
    f"{jax.devices()} — check conftest ordering"
)
