"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the analog of the reference's
CPU-only stub build, /root/reference/paddle/cuda/include/stub/, which lets
the whole suite run without accelerators): sharding/collective tests get 8
devices; numerics match the TPU path because both are XLA.

The backend hardening (force CPU platform, drop the pre-registered
accelerator plugin before any backend initializes) lives in
paddle_tpu.utils.backend_guard so the driver entry points share it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.utils.backend_guard import ensure_cpu_mesh  # noqa: E402

ensure_cpu_mesh(8)

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
# persistent compilation cache: repeat suite runs skip recompiling the
# big jitted steps (~30% wall-clock on warm cache); JAX_COMPILATION_CACHE_DIR
# overrides, and a cold cache is merely the old speed
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

assert len(jax.devices()) == 8, (
    "test suite expects 8 virtual CPU devices; got "
    f"{jax.devices()} — check conftest ordering"
)
