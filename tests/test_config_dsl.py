"""Config DSL → ModelConfig → GradientMachine integration tests.

Mirrors the reference's config_parser_test.py role: configs built through
trainer_config_helpers must produce executable models.
"""

import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.graph import GradientMachine, make_dense, make_ids, make_seq


def parse_str(src: str, config_args: str = ""):
    import tempfile, os

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(src))
        path = f.name
    try:
        return parse_config(path, config_args)
    finally:
        os.unlink(path)


LR_CONFIG = """
from paddle_tpu.trainer_config_helpers import *

settings(batch_size=32, learning_rate=2e-3, learning_method=AdamOptimizer(),
         regularization=L2Regularization(8e-4), gradient_clipping_threshold=25)

data = data_layer(name="word", size=100)
output = fc_layer(input=data, size=2, act=SoftmaxActivation())
label = data_layer(name="label", size=2)
cls = classification_cost(input=output, label=label)
outputs(cls)
"""


def test_parse_lr_config():
    tc = parse_str(LR_CONFIG)
    m = tc.model_config
    assert [l.type for l in m.layers] == ["data", "fc", "data", "multi-class-cross-entropy"]
    assert m.input_layer_names == ["word", "label"]
    assert len(m.output_layer_names) == 1
    assert tc.opt_config.batch_size == 32
    assert tc.opt_config.learning_method == "adam"
    assert tc.opt_config.gradient_clipping_threshold == 25
    # L2 regularization became per-parameter decay
    w = [p for p in m.parameters if p.dims and p.dims[0] == 100][0]
    assert w.decay_rate == pytest.approx(8e-4)
    assert len(m.evaluators) == 1 and m.evaluators[0].type == "classification_error"
    # round-trip through json
    from paddle_tpu.proto import TrainerConfig

    tc2 = TrainerConfig.from_json(tc.to_json())
    assert tc2.to_json() == tc.to_json()


def test_lr_config_trains():
    tc = parse_str(LR_CONFIG)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=1)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 100).astype(np.float32)
    w_true = rng.randn(100)
    y = (x @ w_true > 0).astype(np.int32)
    batch = {"word": make_dense(jnp.asarray(x)), "label": make_ids(jnp.asarray(y))}
    import jax

    lossf = jax.jit(lambda p: gm.loss_fn(p, batch, None)[0])
    gradf = jax.jit(jax.grad(lambda p: gm.loss_fn(p, batch, None)[0]))
    l0 = float(lossf(params))
    for _ in range(50):
        g = gradf(params)
        params = {k: v - 0.5 * g[k] for k, v in params.items()}
    assert float(lossf(params)) < l0 * 0.5


MIXED_EMB_CONFIG = """
from paddle_tpu.trainer_config_helpers import *

settings(batch_size=4, learning_rate=1e-3)
words = data_layer(name="words", size=50)
emb = embedding_layer(input=words, size=16)
pool = pooling_layer(input=emb, pooling_type=AvgPooling())
output = fc_layer(input=pool, size=3, act=SoftmaxActivation(), name="output")
label = data_layer(name="label", size=3)
outputs(classification_cost(input=output, label=label))
"""


def test_embedding_sequence_model():
    tc = parse_str(MIXED_EMB_CONFIG)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=1)
    ids = np.array([[3, 5, 7, 0], [1, 2, 0, 0]], dtype=np.int32)
    lengths = np.array([3, 2], dtype=np.int32)
    batch = {
        "words": make_seq(None, lengths, ids=ids),
        "label": make_ids(np.array([0, 2], dtype=np.int32)),
    }
    outputs_, _ = gm.forward(params, batch, "test")
    assert outputs_["output"].value.shape == (2, 3)
    # padding invariance: growing the pad must not change the output
    ids2 = np.concatenate([ids, np.zeros((2, 4), np.int32)], axis=1)
    batch2 = {
        "words": make_seq(None, lengths, ids=ids2),
        "label": make_ids(np.array([0, 2], dtype=np.int32)),
    }
    out2, _ = gm.forward(params, batch2, "test")
    np.testing.assert_allclose(
        np.asarray(outputs_["output"].value), np.asarray(out2["output"].value), rtol=1e-5
    )


SIMPLE_LSTM_CONFIG = """
from paddle_tpu.trainer_config_helpers import *

settings(batch_size=4, learning_rate=1e-3)
words = data_layer(name="words", size=30)
emb = embedding_layer(input=words, size=8)
lstm = simple_lstm(input=emb, size=6)
pool = pooling_layer(input=lstm, pooling_type=MaxPooling())
output = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
label = data_layer(name="label", size=2)
outputs(classification_cost(input=output, label=label))
"""


def test_simple_lstm_model():
    tc = parse_str(SIMPLE_LSTM_CONFIG)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=2)
    ids = np.array([[3, 5, 7, 2, 9, 4, 0, 0], [1, 2, 8, 0, 0, 0, 0, 0]], dtype=np.int32)
    lengths = np.array([6, 3], dtype=np.int32)
    batch = {
        "words": make_seq(None, lengths, ids=ids),
        "label": make_ids(np.array([0, 1], dtype=np.int32)),
    }
    out, _ = gm.forward(params, batch, "test")
    assert out["output"].value.shape == (2, 2)
    report = gm.check_gradient(params, batch, max_entries=4)
    for name, diff in report.items():
        assert diff < 5e-2, f"{name}: {diff}"


def test_get_config_arg():
    src = """
from paddle_tpu.trainer_config_helpers import *
hidden = get_config_arg('hidden', int, 7)
settings(batch_size=2, learning_rate=1e-3)
d = data_layer(name="x", size=4)
out = fc_layer(input=d, size=hidden)
outputs(out)
"""
    tc = parse_str(src, "hidden=11")
    fc = [l for l in tc.model_config.layers if l.type == "fc"][0]
    assert fc.size == 11
    tc2 = parse_str(src)
    fc2 = [l for l in tc2.model_config.layers if l.type == "fc"][0]
    assert fc2.size == 7


def test_bidirectional_lstm_and_shared_params():
    src = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=2, learning_rate=1e-3)
x = data_layer(name="x", size=20)
emb = embedding_layer(input=x, size=10, param_attr=ParamAttr(name="emb"))
emb2 = embedding_layer(input=x, size=10, param_attr=ParamAttr(name="emb"))
bi = bidirectional_lstm(input=emb, size=5)
out = fc_layer(input=bi, size=2, act=SoftmaxActivation(), name="output")
label = data_layer(name="label", size=2)
outputs(classification_cost(input=out, label=label))
"""
    tc = parse_str(src)
    m = tc.model_config
    embs = [p for p in m.parameters if p.name == "emb"]
    assert len(embs) == 1 and embs[0].is_shared
    gm = GradientMachine(m)
    params = gm.init_params(seed=0)
    ids = np.array([[1, 2, 3, 0], [4, 5, 0, 0]], dtype=np.int32)
    batch = {
        "x": make_seq(None, np.array([3, 2], np.int32), ids=ids),
        "label": make_ids(np.array([0, 1], np.int32)),
    }
    out, _ = gm.forward(params, batch, "test")
    assert out["output"].value.shape == (2, 2)
