"""Compile & cost attribution (doc/observability.md "Compile telemetry",
doc/performance.md "Roofline methodology"): kind=compile/roofline record
schema through a real smoke train run, the persistent compilation cache
e2e (two `paddle train` runs sharing --compile_cache_dir: the second
run's compile records show cache hits and a measured drop in
time_to_first_step_s), the cost_analysis-unavailable fallback, `paddle
roofline`, `paddle compare` (incl. the regression verdict), `paddle
metrics --follow`, and the warm-resume verification skip."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.config import parse_config
from paddle_tpu.observability import compile_log
from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import spans as obs_spans
from paddle_tpu.observability.analyze import analyze, follow, load_run
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")
SUBPROC_ENV = {
    **os.environ,
    "PYTHONPATH": f"{REPO}:{REPO}/compat:{PROVIDER_DIR}",
    "JAX_PLATFORMS": "cpu",
}


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    yield
    sys.path.remove(PROVIDER_DIR)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")
    obs_spans.configure("")
    FLAGS.metrics_path = ""
    FLAGS.trace_events_path = ""
    FLAGS.compile_cache_dir = ""


def _lr_config(tmp_path, hidden=0):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    mid = (
        f'h = fc_layer(input=data, size={hidden}, act=ReluActivation())'
        if hidden else "h = data"
    )
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r},
                            test_list={str(test_list)!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02, learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    {mid}
    output = fc_layer(input=h, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "lr_config.py"
    cfg_path.write_text(src)
    return str(cfg_path)


def _train_smoke(tmp_path, **flag_overrides):
    cfg = parse_config(_lr_config(tmp_path))
    FLAGS.save_dir = str(tmp_path / "out")
    FLAGS.num_passes = 2
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.seed = 7
    for k, v in flag_overrides.items():
        setattr(FLAGS, k, v)
    trainer = Trainer(cfg)
    trainer.train(num_passes=2)
    return trainer, FLAGS.save_dir


# ------------------------------------------------- records through a run


def test_smoke_train_emits_compile_and_roofline_records(tmp_path):
    _, run_dir = _train_smoke(tmp_path)
    records = list(obs.read_records(os.path.join(run_dir, "metrics.jsonl")))
    compiles = [r for r in records if r["kind"] == "compile"]
    rooflines = [r for r in records if r["kind"] == "roofline"]
    assert compiles and rooflines
    for rec in compiles + rooflines:
        assert obs.validate_record(rec) == [], rec
    # one compile per (group, batch-shape signature): the full batch and
    # the end-of-pass remainder each compile the train step once, and
    # NOT again on pass 2
    groups = {c["group"] for c in compiles}
    assert "train_step" in groups and "test_fwd" in groups
    by_group_sig = {(c["group"], c["sig"]) for c in compiles}
    assert len(by_group_sig) == len(compiles), "recompiled a cached signature"
    for c in compiles:
        assert c["trace_s"] >= 0 and c["compile_s"] > 0
        assert isinstance(c["recompiles"], int)
        # CPU backend provides cost analysis: FLOPs/bytes captured
        assert c.get("flops", 0) > 0 and c.get("bytes_accessed", 0) > 0
    # train_step compiles carry the analytic cross-check fields
    ts = [c for c in compiles if c["group"] == "train_step"]
    assert all("flops_analytic" in c and "flops_disagreement" in c for c in ts)
    # roofline records: cumulative exec totals per group+sig — the
    # test forward is timed too (standalone `paddle test`/`paddle gen`
    # get the same roofline discipline as training)
    roof_groups = {r["group"] for r in rooflines}
    assert "train_step" in roof_groups and "test_fwd" in roof_groups
    for r in rooflines:
        assert r["launches"] > 0 and r["exec_s"] >= 0
        assert r.get("flops_per_launch", 0) > 0
        assert r["device_kind"]
    # counters snapshot carries the compile tallies
    pe = [r for r in records if r["kind"] == "pass_end"][-1]
    assert pe["counters"]["compile.count"] == len(compiles)


def test_paddle_metrics_shows_compile_table(tmp_path):
    _, run_dir = _train_smoke(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "metrics", run_dir],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert "compile totals:" in r.stdout
    assert "train_step" in r.stdout and "trace s" in r.stdout
    doc = analyze(load_run(run_dir))
    t = doc["compile_totals"]
    assert t["count"] == len(doc["compiles"]) > 0
    assert t["compile_s"] > 0


def test_roofline_cli_prints_group_table(tmp_path):
    _, run_dir = _train_smoke(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "roofline", run_dir],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    # per-launch-group table with the documented columns
    for col in ("group", "launches", "GFLOP/launch", "MB/launch",
                "GFLOP/s", "FLOP/B", "bucket", "train_step"):
        assert col in r.stdout, (col, r.stdout)
    r2 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "roofline", run_dir, "--json"],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    doc = json.loads(r2.stdout)
    assert doc["groups"] and doc["compile_totals"]["count"] > 0
    row = doc["groups"][0]
    assert row["bucket"] in ("compute-bound", "memory-bound", "host-bound",
                             "unknown")
    assert row.get("achieved_flops_per_s", 0) > 0
    assert row.get("intensity", 0) > 0
    # an empty dir is a clean, jax-free error
    r3 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "roofline", str(tmp_path)],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    assert r3.returncode == 1


def test_roofline_bucket_classification():
    from paddle_tpu.observability.costs import classify

    # v4: 275 TFLOP/s / 1228 GB/s → ridge ~224 FLOP/B
    assert classify(500.0, "TPU v4") == "compute-bound"
    assert classify(10.0, "TPU v4") == "memory-bound"
    # data-wait dominance trumps the ridge position
    assert classify(500.0, "TPU v4", data_wait_share=0.8) == "host-bound"
    # unknown chips / missing analysis are never guessed
    assert classify(10.0, "cpu") == "unknown"
    assert classify(None, "TPU v4") == "unknown"


# ------------------------------------------------ persistent cache e2e


def test_compile_cache_two_runs_hit_and_faster_ttfs(tmp_path):
    """Acceptance: two `paddle train` runs sharing --compile_cache_dir —
    the second run's compile records show cache hits and a measured
    drop in time_to_first_step_s."""
    cfg = _lr_config(tmp_path, hidden=256)  # big enough that compile dominates
    cache = str(tmp_path / "cache")

    def run(name):
        out = str(tmp_path / name)
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.cli", "train",
             f"--config={cfg}", f"--save_dir={out}", "--num_passes=1",
             "--log_period=0", "--use_tpu=0",
             f"--compile_cache_dir={cache}"],
            capture_output=True, text=True, env=SUBPROC_ENV, timeout=300,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        recs = list(obs.read_records(os.path.join(out, "metrics.jsonl")))
        compiles = [x for x in recs if x["kind"] == "compile"]
        restart = [x for x in recs if x["kind"] == "restart"]
        assert compiles and len(restart) == 1
        return compiles, restart[0]

    c_cold, r_cold = run("runA")
    c_warm, r_warm = run("runB")
    # cold run: all misses (cache dir was empty); warm run: all hits
    assert all(c.get("cache_hit") is False for c in c_cold), c_cold
    assert all(c.get("cache_hit") is True for c in c_warm), c_warm
    # the warm run's XLA compile time collapses...
    cold_s = sum(c["compile_s"] for c in c_cold)
    warm_s = sum(c["compile_s"] for c in c_warm)
    assert warm_s < cold_s
    # ...and time_to_first_step_s drops measurably (restore + trace
    # still run; the XLA half is what the cache absorbs)
    assert r_warm["time_to_first_step_s"] < r_cold["time_to_first_step_s"]


# ------------------------------------------------------ fallback paths


def test_cost_analysis_of_graceful_on_unavailable_backends():
    from paddle_tpu.observability.costs import cost_analysis_of

    class Raises:
        def cost_analysis(self):
            raise NotImplementedError("backend says no")

    class Listy:
        def cost_analysis(self):
            return [{"flops": 8.0, "bytes accessed": 4.0}]

    class Empty:
        def cost_analysis(self):
            return {"transcendentals": 3.0}

    class Scalarless:
        def cost_analysis(self):
            return "not a dict"

    assert cost_analysis_of(Raises()) is None
    assert cost_analysis_of(Empty()) is None
    assert cost_analysis_of(Scalarless()) is None
    assert cost_analysis_of(Listy()) == {"flops": 8.0, "bytes_accessed": 4.0}


def test_registry_inline_fallback_without_lower(tmp_path):
    """Callables without .lower (mesh-sharded closures, plain python)
    still get a compile record — mode=inline, combined timing, no cost
    analysis — and the launch result is returned unchanged."""
    obs.configure(str(tmp_path), host=0)
    reg = compile_log.CompileRegistry(device_kind="cpu")
    calls = []

    def step(x):
        calls.append(x)
        return x * 2

    assert reg.call("train_step", ("sig", 1), step, 21) == 42
    assert reg.call("train_step", ("sig", 1), step, 4) == 8
    obs.flush()
    recs = [r for r in obs.read_records(os.path.join(str(tmp_path), "metrics.jsonl"))
            if r["kind"] == "compile"]
    assert len(recs) == 1  # second call hit the registry cache
    rec = recs[0]
    assert obs.validate_record(rec) == []
    assert rec["mode"] == "inline"
    assert rec["compile_s"] > 0 and "trace_s" not in rec
    assert "flops" not in rec  # no executable to cost-analyze
    assert calls == [21, 4]


def test_registry_cost_analysis_raise_keeps_compile_record(tmp_path, monkeypatch):
    """A backend whose compiled.cost_analysis() raises still yields the
    timed compile record — just without FLOPs/bytes."""
    import jax

    from paddle_tpu.observability import costs

    obs.configure(str(tmp_path), host=0)
    monkeypatch.setattr(
        costs, "cost_analysis_of",
        lambda compiled: (_ for _ in ()).throw(RuntimeError("unreachable")),
    )
    # the registry must swallow even a raising helper (graceful contract)
    reg = compile_log.CompileRegistry()
    fn = jax.jit(lambda x: x + 1)
    try:
        out = reg.call("train_step", ("s",), fn, 1.0)
    except RuntimeError:
        pytest.fail("cost-analysis failure leaked out of the registry")
    assert float(out) == 2.0
    obs.flush()
    recs = [r for r in obs.read_records(os.path.join(str(tmp_path), "metrics.jsonl"))
            if r["kind"] == "compile"]
    assert len(recs) == 1 and recs[0]["compile_s"] > 0


def test_flops_cross_check_warns_once_per_signature(tmp_path, caplog):
    import logging

    import jax

    from paddle_tpu.utils.logging import logger as ptu_logger

    obs.configure(str(tmp_path), host=0)
    reg = compile_log.CompileRegistry()
    fn = jax.jit(lambda x: x @ x)
    x = np.eye(8, dtype=np.float32)
    ptu_logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING, logger="paddle_tpu"):
            reg.call("train_step", ("s",), fn, x, analytic_flops=1e12)
    finally:
        ptu_logger.removeHandler(caplog.handler)
    assert "FLOPs accounting disagreement" in caplog.text
    assert "scan/while bodies once" in caplog.text
    obs.flush()
    rec = [r for r in obs.read_records(os.path.join(str(tmp_path), "metrics.jsonl"))
           if r["kind"] == "compile"][0]
    assert rec["flops_analytic"] == 1e12
    assert rec["flops_disagreement"] > 0.10


# --------------------------------------------------------------- compare


def _fake_run(tmp_path, name, sps, p99, compile_s):
    d = str(tmp_path / name)
    w = obs.MetricsWriter(d, host=0)
    w.emit("compile", group="train_step", sig="aaaa", recompiles=0,
           trace_s=0.01, compile_s=compile_s, cache_hit=False)
    w.emit("pass_end", pass_id=0, step=10, samples=640, AvgCost=0.5,
           pass_time_s=1.0, samples_per_sec=sps, mfu=0.30,
           step_time_mean_s=p99 / 2, step_time_p50_s=p99 / 2,
           step_time_p99_s=p99)
    w.emit("run_end", status="completed")
    w.close()
    return d


def test_compare_regression_verdict_and_exit_code(tmp_path):
    a = _fake_run(tmp_path, "a", sps=1000.0, p99=0.010, compile_s=1.0)
    b = _fake_run(tmp_path, "b", sps=800.0, p99=0.020, compile_s=1.0)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "compare", a, b],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    # golden shape: per-metric rows with direction-aware verdicts, then
    # the overall verdict naming the regressed metrics; exit code 1
    assert r.returncode == 1, r.stdout + r.stderr
    assert "samples_per_sec" in r.stdout and "step_p99_ms" in r.stdout
    assert "verdict: REGRESSION" in r.stdout
    assert "samples_per_sec" in r.stdout.splitlines()[-1]
    # within-noise comparison: NO CHANGE, exit 0
    c = _fake_run(tmp_path, "c", sps=1010.0, p99=0.0101, compile_s=1.0)
    r2 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "compare", a, c],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "verdict: NO CHANGE" in r2.stdout
    # --json carries the full document
    r3 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "compare", a, b, "--json"],
        capture_output=True, text=True, env=SUBPROC_ENV, timeout=120,
    )
    doc = json.loads(r3.stdout)
    assert doc["verdict"] == "REGRESSION"
    assert "samples_per_sec" in doc["regressions"]
    assert "mfu" not in doc["regressions"]  # unchanged metric


def test_compare_bench_artifacts(tmp_path):
    from paddle_tpu.observability.compare import compare, load_side

    a = tmp_path / "BENCH_a.json"
    a.write_text(json.dumps({
        "n": 1, "cmd": "bench", "rc": 0,
        "tail": 'noise\n' + json.dumps({
            "metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2000.0,
            "unit": "imgs/s", "vs_baseline": 1.0, "mfu": 0.30,
            "compile_s": 10.0,
            "legs": {"nmt_train_tokens_per_sec": {"value": 500000.0,
                                                  "unit": "tokens/s"}},
        }) + "\n",
    }))
    b = tmp_path / "BENCH_b.json"  # raw result-line file also accepted
    b.write_text(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2400.0,
        "unit": "imgs/s", "vs_baseline": 1.2, "mfu": 0.36, "compile_s": 2.0,
        "legs": {"nmt_train_tokens_per_sec": {"value": 430000.0,
                                              "unit": "tokens/s"}},
    }))
    doc = compare(load_side(str(a)), load_side(str(b)))
    by = {m["metric"]: m["verdict"] for m in doc["metrics"]}
    assert by["resnet50_train_imgs_per_sec_per_chip"] == "IMPROVED"
    assert by["compile_total_s"] == "IMPROVED"          # lower is better
    assert by["nmt_train_tokens_per_sec"] == "REGRESSION"  # -14% throughput
    assert doc["verdict"] == "REGRESSION"  # any regression wins overall


# ---------------------------------------------------------------- follow


def test_metrics_follow_tails_live_stream(tmp_path):
    run_dir = str(tmp_path)
    w = obs.MetricsWriter(run_dir, host=0)
    w.emit("pass_end", pass_id=0, step=10, samples=64, AvgCost=0.5)
    w.flush()
    path = os.path.join(run_dir, "metrics.jsonl")
    g = follow(run_dir, poll_s=0.01, max_polls=200)
    assert next(g)["kind"] == "run_start"
    assert next(g)["kind"] == "pass_end"
    # live append while following: a complete record plus a TORN tail —
    # the record is yielded, the torn half stays buffered
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "checkpoint", "host": 0, "t": 1.0}\n'
                '{"v": 1, "kind": "run_')
    assert next(g)["kind"] == "checkpoint"
    with open(path, "a") as f:
        f.write('end", "host": 0, "t": 2.0, "status": "completed"}\n')
    rec = next(g)
    assert rec["kind"] == "run_end" and rec["status"] == "completed"
    # max_polls bounds the wait when nothing more arrives
    assert list(follow(run_dir, poll_s=0, max_polls=2))[-1]["kind"] == "run_end"


# ----------------------------------------------------------- warm resume


def _small_params():
    import jax.numpy as jnp

    return {"w": jnp.arange(8, dtype=jnp.float32)}


def test_warm_resume_skips_reverify_of_self_written_checkpoints(
        tmp_path, monkeypatch):
    from paddle_tpu.trainer import checkpoint as ckpt

    d = str(tmp_path)
    verified = []
    real = ckpt.verify_checkpoint
    monkeypatch.setattr(
        ckpt, "verify_checkpoint", lambda p: (verified.append(p), real(p))[1]
    )
    ckpt.save_checkpoint(d, 0, _small_params())
    path = os.path.join(d, "pass-00000")
    assert ckpt.written_this_process(path)

    # rollback-path lookups trust this process's own commits: no CRC walk
    verified.clear()
    assert ckpt.find_restorable_checkpoint(d, trust_own_writes=True) == path
    assert verified == []
    ckpt.load_checkpoint(path, trust_own_writes=True)
    assert verified == []

    # the default (cold-restore contract) still verifies in full
    verified.clear()
    assert ckpt.find_restorable_checkpoint(d) == path
    assert verified == [path]
    verified.clear()
    ckpt.load_checkpoint(path)
    assert verified == [path]

    # fresh process ⇒ empty write log ⇒ trust is inert (full verify)
    monkeypatch.setattr(ckpt, "_written_this_process", set())
    verified.clear()
    assert ckpt.find_restorable_checkpoint(d, trust_own_writes=True) == path
    assert verified == [path]


def test_quarantine_revokes_self_written_trust(tmp_path):
    from paddle_tpu.trainer import checkpoint as ckpt

    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _small_params())
    path = os.path.join(d, "pass-00000")
    assert ckpt.written_this_process(path)
    assert ckpt._quarantine(path) is not None
    assert not ckpt.written_this_process(path)


def test_corrupt_trusted_checkpoint_falls_back_not_config_error(tmp_path):
    """A TRUSTED (verify-skipped) checkpoint whose bytes are torn on
    disk must enter the fallback chain, not re-raise as a config error
    — nothing CRC-verified it on the trusted path."""
    from paddle_tpu.trainer import checkpoint as ckpt

    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _small_params())
    ckpt.save_checkpoint(d, 1, _small_params())
    newest = os.path.join(d, "pass-00001")
    assert ckpt.written_this_process(newest)
    # torn npz AFTER the manifest was recorded (fsync'd then damaged):
    # trust skips the CRC, so only deserialization can catch it
    npz = [os.path.join(newest, f) for f in os.listdir(newest)
           if f.endswith(".npz")][0]
    with open(npz, "r+b") as f:
        f.truncate(10)
    params, _, meta = ckpt.load_checkpoint(
        newest, trust_own_writes=True, fallback=True
    )
    # fell back to pass 0 instead of dying on BadZipFile
    assert meta["pass_id"] == 0
    assert os.path.isdir(newest + ".corrupt")
