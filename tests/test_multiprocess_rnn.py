"""Multi-process training of a recurrent-group model — the loopback
cluster analog (reference test_TrainerOnePass.cpp checkRemoteUpdater) for
the RGM path: two processes form one 8-device mesh, train an embedding →
recurrent_group (lax.scan) → pool → softmax classifier, and must match
the single-process 8-device run. Round-2 coverage gap: multi-process runs
only ever trained a bag-of-words fc model.
"""

import os
import sys
import textwrap

import mp_harness

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

WORKER = mp_harness.WORKER_PREAMBLE + """

from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

FLAGS.save_dir = ""
FLAGS.mesh_shape = "data=8"
FLAGS.log_period = 0
FLAGS.seed = 13
trainer = Trainer(parse_config(os.path.join(ws, "cfg.py")))
trainer.train(num_passes=1)
if jax.process_index() == 0:
    import numpy as np
    np.savez(os.path.join(ws, "mp_params.npz"),
             **{{k: np.asarray(v) for k, v in trainer.params.items()}})
print("WORKER_OK", pid, flush=True)
"""

CONFIG = """
from paddle_tpu.trainer_config_helpers import *
define_py_data_sources2(train_list={train_list!r}, test_list=None,
                        module="synthetic_bow", obj="process_seq")
settings(batch_size=64, learning_rate=0.05)
word = data_layer(name="word", size=100)
emb = embedding_layer(input=word, size=12)
def step(x_t):
    mem = memory(name="rnn", size=12)
    return fc_layer(input=[x_t, mem], size=12, act=TanhActivation(), name="rnn")
rnn = recurrent_group(step=step, input=emb, name="rg")
pool = pooling_layer(input=rnn, pooling_type=MaxPooling())
output = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
label = data_layer(name="label", size=2)
outputs(classification_cost(input=output, label=label))
"""


def test_two_process_recurrent_group_matches_single(tmp_path):
    mp_harness.skip_unless_cross_process_computations()
    ws = str(tmp_path)
    train_list = os.path.join(ws, "train.list")
    with open(train_list, "w") as f:
        f.write("1\n2\n")
    with open(os.path.join(ws, "cfg.py"), "w") as f:
        f.write(textwrap.dedent(CONFIG.format(train_list=train_list)))

    sys.path.insert(0, PROVIDERS)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    FLAGS.save_dir = ""
    FLAGS.mesh_shape = "data=8"
    FLAGS.log_period = 0
    FLAGS.seed = 13
    try:
        ref = Trainer(parse_config(os.path.join(ws, "cfg.py")))
        ref.train(num_passes=1)
    finally:
        FLAGS.mesh_shape = ""
        sys.path.remove(PROVIDERS)

    outs = mp_harness.run_two_workers(
        WORKER.format(repo=REPO, providers=PROVIDERS), ws)

    with np.load(os.path.join(ws, "mp_params.npz")) as z:
        mp_params = {k: z[k] for k in z.files}
    assert any("rnn" in k for k in mp_params), mp_params.keys()
    for name, ref_v in ref.params.items():
        np.testing.assert_allclose(
            np.asarray(ref_v), mp_params[name], rtol=3e-4, atol=2e-5,
            err_msg=name,
        )
