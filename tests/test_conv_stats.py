"""Fused 1x1-conv + BN-statistics (OptimizationConfig.conv_stats_mode):
the "pallas" matmul-epilogue kernel (ops/pallas_conv1x1_bn) and the
"gram" input-side algebra (layers/vision.py _publish_gram_stats).
Interpret-mode value/gradient parity against the unfused XLA path, the
bf16 accuracy bound of the gram reformulation, plus the layer-level
gates — the fused paths must only engage for 1x1/s1/p0 linear convs in
training, and a downstream batch_norm must reproduce the unfused
statistics, moving averages, and parameter gradients.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.graph  # noqa: F401  (break the layers<->graph import cycle)
from paddle_tpu.ops import pallas_conv1x1_bn as pcb


# ------------------------------------------------------------ kernel unit


@pytest.mark.parametrize(
    "M,K,N",
    [
        (512, 64, 256),     # resnet stage-2 expand shape class
        (1024, 256, 128),   # bn == N == 128 smallest lane block
        (2048, 1024, 512),  # multi-k-block accumulation (nk=2)
        (896, 128, 128),    # bm=896 (128*7 divisor path)
    ],
)
def test_kernel_value_parity(M, K, N):
    kx, kw, kb = jax.random.split(jax.random.PRNGKey(M + N), 3)
    x = jax.random.normal(kx, (M, K)).astype(jnp.bfloat16)
    w = (jax.random.normal(kw, (K, N)) * 0.1).astype(jnp.bfloat16)
    b = (jax.random.normal(kb, (N,)) * 0.1).astype(jnp.bfloat16)
    assert pcb.supported(M, K, N, 2)
    y, s, q = pcb.conv1x1_stats(x, w, b, True)
    yref = (
        x.astype(jnp.float32) @ w.astype(jnp.float32)
        + b.astype(jnp.float32)[None]
    ).astype(jnp.bfloat16)
    yf = yref.astype(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yf), rtol=0.02, atol=0.1
    )
    # statistics reduce the ROUNDED output; tolerance is reduction-order
    # rounding scaled by row count
    np.testing.assert_allclose(
        np.asarray(s), np.asarray(yf.sum(0)), rtol=1e-3, atol=0.02 * M ** 0.5
    )
    np.testing.assert_allclose(
        np.asarray(q), np.asarray((yf * yf).sum(0)), rtol=2e-3, atol=0.02 * M
    )


def test_kernel_gradient_parity():
    M, K, N = 512, 64, 256
    kx, kw, kb, kc = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(kx, (M, K)).astype(jnp.bfloat16)
    w = (jax.random.normal(kw, (K, N)) * 0.1).astype(jnp.bfloat16)
    b = (jax.random.normal(kb, (N,)) * 0.1).astype(jnp.bfloat16)
    cs, cq = jax.random.split(kc)
    gs = jax.random.normal(cs, (N,))
    gq = jax.random.normal(cq, (N,)) * 0.1

    def fused(x, w, b):
        y, s, q = pcb.conv1x1_stats(x, w, b, True)
        return (
            jnp.sum(y.astype(jnp.float32) * 1.5)
            + jnp.sum(s * gs)
            + jnp.sum(q * gq)
        )

    def ref(x, w, b):
        y = (
            x.astype(jnp.float32) @ w.astype(jnp.float32)
            + b.astype(jnp.float32)[None]
        ).astype(x.dtype)
        yf = y.astype(jnp.float32)
        return (
            jnp.sum(yf * 1.5)
            + jnp.sum(yf.sum(0) * gs)
            + jnp.sum((yf * yf).sum(0) * gq)
        )

    g1 = jax.grad(fused, (0, 1, 2))(x, w, b)
    g2 = jax.grad(ref, (0, 1, 2))(x, w, b)
    for got, want, name in zip(g1, g2, ("dx", "dw", "db")):
        np.testing.assert_allclose(
            np.asarray(got, np.float32),
            np.asarray(want, np.float32),
            rtol=0.05,
            atol=0.05 * max(1.0, float(jnp.max(jnp.abs(want)))),
            err_msg=name,
        )


def test_shape_gate():
    assert not pcb.supported(7, 64, 256, 2)       # M has no block divisor
    assert not pcb.supported(512, 520, 256, 2)    # K not tileable
    assert not pcb.supported(512, 64, 200, 2)     # N not tileable
    assert not pcb.supported(512, 64, 64, 2)      # N < 128: measured Mosaic rejection
    assert pcb.supported(12544, 2048, 512, 2)     # resnet stage-5 reduce


def test_gram_stats_bf16_bound():
    """The gram mode reduces the UNROUNDED x@w while the direct path
    reduces the bf16-rounded y; pin that the bf16-regime discrepancy
    stays inside BN's eps scale on realistic magnitudes (the docstring's
    ~1e-3-relative claim)."""
    M, K, N = 4096, 64, 256
    f32 = jnp.float32
    kx, kw = jax.random.split(jax.random.PRNGKey(42))
    x = jax.random.normal(kx, (M, K)).astype(jnp.bfloat16)
    w = (jax.random.normal(kw, (K, N)) * 0.1).astype(jnp.bfloat16)
    # direct path: stats of the rounded bf16 output, f32 accumulation
    y = (x @ w).astype(jnp.bfloat16)
    mean_d = jnp.mean(y, axis=0, dtype=f32)
    msq_d = jnp.mean(jnp.square(y.astype(f32)), axis=0, dtype=f32)
    # gram path (the _publish_gram_stats algebra, no bias)
    cs = jnp.sum(x, axis=0, dtype=f32)
    gram = jnp.einsum("mk,ml->kl", x, x, preferred_element_type=f32)
    w32 = w.astype(f32)
    mean_g = (cs @ w32) / M
    msq_g = jnp.einsum("kn,kl,ln->n", w32, gram, w32) / M
    var_d = msq_d - jnp.square(mean_d)
    var_g = msq_g - jnp.square(mean_g)
    # discrepancy must be small relative to the per-channel STD (what BN
    # divides by), i.e. well inside the rsqrt(var+eps) regime
    std = jnp.sqrt(jnp.maximum(var_d, 1e-6))
    assert float(jnp.max(jnp.abs(mean_g - mean_d) / std)) < 5e-3
    assert float(jnp.max(jnp.abs(var_g - var_d) / jnp.maximum(var_d, 1e-6))) < 2e-2


# ------------------------------------------------------- layer-level path


_NET = """
from paddle_tpu.trainer_config_helpers import *

settings(batch_size=8, learning_rate=1e-3)
img = data_layer(name="input", size=4 * 4 * 8)
conv = img_conv_layer(name="c1", input=img, filter_size=1,
                      num_filters=128, num_channels=8, stride=1,
                      padding=0, act=LinearActivation(), bias_attr=False)
bn = batch_norm_layer(name="bn", input=conv, act=ReluActivation())
fc = fc_layer(name="fc", input=bn, size=4, act=SoftmaxActivation())
lbl = data_layer(name="label", size=4)
cost = classification_cost(name="cost", input=fc, label=lbl)
outputs(cost)
"""


def _setup(tmp_path, mode):
    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine

    p = tmp_path / "net.py"
    p.write_text(textwrap.dedent(_NET))
    tc = parse_config(str(p))
    return GradientMachine(tc.model_config, conv_stats_mode=mode)


def _batch():
    from paddle_tpu.graph import make_dense

    rng = np.random.RandomState(0)
    x = rng.randn(8, 8 * 4 * 4).astype(np.float32)
    labels = rng.randint(0, 4, size=(8,))
    onehot = np.zeros((8, 4), np.float32)
    onehot[np.arange(8), labels] = 1.0
    return {"input": make_dense(x), "label": make_dense(onehot)}


@pytest.mark.parametrize("mode", ["pallas", "gram"])
def test_machine_parity_train(tmp_path, monkeypatch, mode):
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    gm_off = _setup(tmp_path, "")
    gm_on = _setup(tmp_path, mode)
    params = gm_off.init_params(seed=3)
    batch = _batch()
    rng = jax.random.PRNGKey(0)
    loss_off, grads_off, _, su_off = gm_off.grad_fn()(params, batch, rng)
    loss_on, grads_on, _, su_on = gm_on.grad_fn()(params, batch, rng)
    np.testing.assert_allclose(
        float(loss_on), float(loss_off), rtol=1e-5, atol=1e-6
    )
    for name in grads_off:
        np.testing.assert_allclose(
            np.asarray(grads_on[name], np.float32),
            np.asarray(grads_off[name], np.float32),
            rtol=1e-4, atol=1e-5, err_msg=name,
        )
    # moving mean/var updates must match (same statistics)
    assert set(su_on) == set(su_off)
    for name in su_off:
        np.testing.assert_allclose(
            np.asarray(su_on[name]), np.asarray(su_off[name]),
            rtol=1e-5, atol=1e-6, err_msg=name,
        )


@pytest.mark.parametrize("mode", ["pallas", "gram"])
def test_stats_actually_published(tmp_path, monkeypatch, mode):
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    gm = _setup(tmp_path, mode)
    params = gm.init_params(seed=3)
    # run forward with a train pass and capture the ctx via the network
    ctx_box = {}
    orig_forward = gm.network.forward

    def spy_forward(ctx, in_args):
        ctx_box["ctx"] = ctx
        return orig_forward(ctx, in_args)

    monkeypatch.setattr(gm.network, "forward", spy_forward)
    gm.forward(params, _batch(), "train", rng=jax.random.PRNGKey(0))
    assert "c1" in ctx_box["ctx"].conv_stats, (
        "fused conv did not publish statistics"
    )
    # test pass must NOT publish (BN uses global stats there)
    gm.forward(params, _batch(), "test")
    assert "c1" not in ctx_box["ctx"].conv_stats or ctx_box[
        "ctx"
    ].pass_type == "train"


def test_gates_fall_through(tmp_path, monkeypatch):
    """3x3 and strided 1x1 convs must not take the fused path even with
    the knob on — outputs bit-identical to the knob-off machine."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine, make_dense

    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=4, learning_rate=1e-3)
    img = data_layer(name="input", size=8 * 8 * 8)
    c3 = img_conv_layer(name="c3", input=img, filter_size=3,
                        num_filters=8, num_channels=8, stride=1,
                        padding=1, act=LinearActivation(), bias_attr=False)
    c1s2 = img_conv_layer(name="c1s2", input=img, filter_size=1,
                          num_filters=8, num_channels=8, stride=2,
                          padding=0, act=LinearActivation(), bias_attr=False)
    outputs(c3, c1s2)
    """)
    p = tmp_path / "gates.py"
    p.write_text(src)
    tc = parse_config(str(p))
    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, conv_stats_mode="pallas")
    gm_gram = GradientMachine(tc.model_config, conv_stats_mode="gram")
    params = gm_off.init_params(seed=7)
    rng = np.random.RandomState(2)
    batch = {"input": make_dense(rng.randn(4, 8 * 8 * 8).astype(np.float32))}
    out_off, _ = gm_off.forward(params, batch, "train", rng=jax.random.PRNGKey(1))
    out_on, _ = gm_on.forward(params, batch, "train", rng=jax.random.PRNGKey(1))
    out_gram, _ = gm_gram.forward(params, batch, "train", rng=jax.random.PRNGKey(1))
    for name in ("c3", "c1s2"):
        np.testing.assert_array_equal(
            np.asarray(out_on[name].value), np.asarray(out_off[name].value),
            err_msg=name,
        )
        np.testing.assert_array_equal(
            np.asarray(out_gram[name].value), np.asarray(out_off[name].value),
            err_msg=f"gram {name}",
        )


def test_gram_strided_projection_parity(tmp_path, monkeypatch):
    """Stride-2 1x1 downsample projections (resnet shortcut convs) take
    the gram path: train-step loss/grads/moving stats must match the
    unfused machine."""
    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine

    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=8, learning_rate=1e-3)
    img = data_layer(name="input", size=8 * 8 * 8)
    proj = img_conv_layer(name="proj", input=img, filter_size=1,
                          num_filters=32, num_channels=8, stride=2,
                          padding=0, act=LinearActivation(), bias_attr=False)
    bn = batch_norm_layer(name="bn", input=proj, act=ReluActivation())
    fc = fc_layer(name="fc", input=bn, size=4, act=SoftmaxActivation())
    lbl = data_layer(name="label", size=4)
    cost = classification_cost(name="cost", input=fc, label=lbl)
    outputs(cost)
    """)
    p = tmp_path / "proj.py"
    p.write_text(src)
    tc = parse_config(str(p))
    from paddle_tpu.graph import make_dense

    gm_off = GradientMachine(tc.model_config)
    gm_on = GradientMachine(tc.model_config, conv_stats_mode="gram")
    params = gm_off.init_params(seed=9)
    nprng = np.random.RandomState(5)
    onehot = np.zeros((8, 4), np.float32)
    onehot[np.arange(8), nprng.randint(0, 4, size=(8,))] = 1.0
    batch = {"input": make_dense(nprng.randn(8, 8 * 8 * 8).astype(np.float32)),
             "label": make_dense(onehot)}
    rng = jax.random.PRNGKey(0)
    # prove the path actually engaged before comparing numerics
    ctx_box = {}
    orig_forward = gm_on.network.forward

    def spy_forward(ctx, in_args):
        ctx_box["ctx"] = ctx
        return orig_forward(ctx, in_args)

    monkeypatch.setattr(gm_on.network, "forward", spy_forward)
    gm_on.forward(params, batch, "train", rng=rng)
    assert "proj" in ctx_box["ctx"].conv_stats, (
        "strided 1x1 projection did not publish gram statistics"
    )
    loss_off, grads_off, _, su_off = gm_off.grad_fn()(params, batch, rng)
    loss_on, grads_on, _, su_on = gm_on.grad_fn()(params, batch, rng)
    np.testing.assert_allclose(float(loss_on), float(loss_off),
                               rtol=1e-5, atol=1e-6)
    for name in grads_off:
        np.testing.assert_allclose(
            np.asarray(grads_on[name], np.float32),
            np.asarray(grads_off[name], np.float32),
            rtol=1e-4, atol=1e-5, err_msg=name,
        )
    for name in su_off:
        np.testing.assert_allclose(
            np.asarray(su_on[name]), np.asarray(su_off[name]),
            rtol=1e-5, atol=1e-6, err_msg=name,
        )
