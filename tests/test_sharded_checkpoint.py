"""Sharded multi-host checkpointing.

The reference saves/loads sharded parameter state where it lives
(pserver-side loadValueVector/saveValueVector,
/root/reference/paddle/pserver/ParameterServer2.cpp:1150-1213); SURVEY §5
calls the orbax-style sharded checkpoint a required upgrade. These tests
run a REAL two-process mesh (data=4,model=2) with a fully-sharded
embedding table — the configuration whose save crashed before (np.asarray on a
cross-host shard) — and assert:

- every process writes only the shards it owns; process 0 merges the
  index (no full-array npz, no cross-host materialization)
- reload with the current-mesh shardings round-trips bit-exactly and the
  restored state drives another training step
- the sharded checkpoint re-shards onto a DIFFERENT layout: this
  single-process test assembles it to host numpy and matches a
  single-process reference run
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "").replace("--xla_force_host_platform_device_count=8", "")
    + " --xla_force_host_platform_device_count=4"
).strip()
sys.path.insert(0, {repo!r})
sys.path.insert(0, {providers!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as _xb
for _n in list(_xb._backend_factories):
    if _n not in ("cpu", "tpu"):
        del _xb._backend_factories[_n]

pid = int(sys.argv[1])
jax.distributed.initialize(coordinator_address="localhost:" + sys.argv[2],
                           num_processes=2, process_id=pid)
assert len(jax.devices()) == 8

import numpy as np
from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer, checkpoint as ckpt
from paddle_tpu.parallel.spmd import checkpoint_sharding_fn
from paddle_tpu.utils.flags import FLAGS

ws = sys.argv[3]
FLAGS.save_dir = os.path.join(ws, "model")
FLAGS.mesh_shape = "data=4,model=2"
FLAGS.log_period = 0
FLAGS.seed = 11
trainer = Trainer(parse_config(os.path.join(ws, "cfg.py")))
trainer.train(num_passes=1)

# --- reload the saved pass with current-mesh shardings; must round-trip
# bit-exactly against the live state on every process
path = os.path.join(FLAGS.save_dir, ckpt.PASS_FMT % 0)
fn = checkpoint_sharding_fn(trainer._mesh, trainer.gm)
params2, opt2, meta = ckpt.load_checkpoint(
    path, trainer.opt_state, expected_params=trainer.params, sharding_for=fn)
for name in trainer.params:
    live = trainer.params[name]
    back = params2[name]
    assert back.sharding.is_equivalent_to(live.sharding, live.ndim), name
    for s1, s2 in zip(live.addressable_shards, back.addressable_shards):
        np.testing.assert_array_equal(np.asarray(s1.data), np.asarray(s2.data),
                                      err_msg=name)
for name, d in trainer.opt_state.slots.items():
    for slot, arr in d.items():
        for s1, s2 in zip(arr.addressable_shards, opt2.slots[name][slot].addressable_shards):
            np.testing.assert_array_equal(np.asarray(s1.data), np.asarray(s2.data),
                                          err_msg=name + "/" + slot)
assert int(opt2.step) == int(trainer.opt_state.step)

# --- the restored state must drive the sharded train step
trainer.params, trainer.opt_state = params2, opt2
provider = trainer._provider(for_test=False)
from paddle_tpu.parallel.spmd import globalize_batch
import jax.numpy as jnp
batch = globalize_batch(next(iter(provider.batches())), trainer._mesh)
trainer.params, trainer.opt_state, loss, _ = trainer.train_step(
    trainer.params, trainer.opt_state, batch, jax.random.PRNGKey(0),
    jnp.asarray(64.0))
assert np.isfinite(float(loss))
print("WORKER_OK", pid, flush=True)
"""


def _write_config(ws):
    train_list = os.path.join(ws, "train.list")
    with open(train_list, "w") as f:
        f.write("1\n2\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={train_list!r}, test_list=None,
                            module="synthetic_bow", obj="process_seq")
    settings(batch_size=64, learning_rate=0.05)
    word = data_layer(name="word", size=100)
    # fully sharded table (rows over 'model', cols over 'data' — the
    # FSDP-style layout): its replica-0 shards live on BOTH processes
    emb = embedding_layer(input=word, size=16,
                          param_attr=ParamAttr(name="emb", sharding=("model", "data")))
    pool = pooling_layer(input=emb)
    output = fc_layer(input=pool, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    path = os.path.join(ws, "cfg.py")
    with open(path, "w") as f:
        f.write(src)
    return path


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def two_proc_ckpt(tmp_path_factory):
    """Run the two-process training+save+reload worker once; return ws.

    Skips (capability probe, not a failure) where the backend cannot run
    cross-process device computations — the worker TRAINS across the
    process pair, which the CPU backend refuses to compile. The sharded
    checkpoint protocol itself is host-side and stays covered everywhere
    by tests/test_elastic_ckpt.py's two-process round-trips."""
    import mp_harness

    mp_harness.skip_unless_cross_process_computations()
    ws = str(tmp_path_factory.mktemp("shardckpt"))
    _write_config(ws)
    port = _free_port()
    worker_py = os.path.join(ws, "worker.py")
    with open(worker_py, "w") as f:
        f.write(WORKER.format(repo=REPO, providers=PROVIDERS))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker_py, str(i), str(port), ws],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "WORKER_OK" in out, (out, err[-2000:])
    return ws


def test_sharded_layout_on_disk(two_proc_ckpt):
    """Both processes wrote shard files; index merged; no monolithic npz."""
    path = os.path.join(two_proc_ckpt, "model", "pass-00000")
    files = sorted(os.listdir(path))
    assert "params.index.json" in files, files
    assert "params.shard00000.npz" in files and "params.shard00001.npz" in files, files
    assert "params.npz" not in files  # nothing materialized whole
    assert not any(f.startswith("params.index.0") for f in files)  # partials merged
    with open(os.path.join(path, "params.index.json")) as f:
        index = json.load(f)
    # the model-sharded embedding has shards in BOTH processes' files
    emb_files = {rec["file"] for rec in index["emb"]["shards"]}
    assert emb_files == {"params.shard00000.npz", "params.shard00001.npz"}, emb_files
    assert index["emb"]["shape"] == [100, 16]
    # replicated fc weight is stored exactly once
    w = index["_output.w0"]
    starts = [tuple(r["start"]) for r in w["shards"]]
    assert starts == [(0, 0)], starts


def test_sharded_ckpt_reshards_to_single_process(two_proc_ckpt):
    """Assemble the 2-process checkpoint on this (single-process, 8-device)
    host and match a single-process reference run of the same config."""
    sys.path.insert(0, PROVIDERS)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer, checkpoint as ckpt
    from paddle_tpu.utils.flags import FLAGS

    FLAGS.save_dir = ""
    FLAGS.mesh_shape = "data=4,model=2"
    FLAGS.log_period = 0
    FLAGS.seed = 11
    try:
        ref = Trainer(parse_config(os.path.join(two_proc_ckpt, "cfg.py")))
        ref.train(num_passes=1)
    finally:
        FLAGS.mesh_shape = ""
        sys.path.remove(PROVIDERS)

    path = os.path.join(two_proc_ckpt, "model", "pass-00000")
    params, opt_state, meta = ckpt.load_checkpoint(path, ref.opt_state,
                                                   expected_params=ref.params)
    assert meta["format_version"] == 2
    for name, ref_v in ref.params.items():
        np.testing.assert_allclose(
            np.asarray(ref_v), np.asarray(params[name]), rtol=2e-4, atol=1e-5,
            err_msg=name,
        )
    assert int(opt_state.step) == int(ref.opt_state.step)


def test_merge_model_reads_sharded_checkpoint(two_proc_ckpt, tmp_path):
    """merge_model bundles a sharded (format-2) checkpoint into one
    deployable npz — assembled values equal the shard contents."""
    from paddle_tpu.trainer import checkpoint as ckpt

    out = str(tmp_path / "merged.npz")
    ckpt.merge_model(os.path.join(two_proc_ckpt, "model"), 0, '{"m":1}', out)
    with np.load(out) as z:
        assert "__config_json__" in z.files
        merged = {k: z[k] for k in z.files if k != "__config_json__"}
    raw = ckpt._load_tree_numpy(
        os.path.join(two_proc_ckpt, "model", "pass-00000"), "params"
    )
    assert set(merged) == set(raw)
    for k in raw:
        np.testing.assert_array_equal(merged[k], raw[k], err_msg=k)


def test_streaming_restore_reads_only_overlapping_shards(tmp_path):
    """The streaming restore claim (reference block-wise semantics,
    ParameterServer2.cpp:1150-1213): assembling one device slice of a
    model-sharded 1M-row table reads ONLY the shard records overlapping
    it — O(shard bytes), never O(table bytes) — and a full restore reads
    each record exactly once (no per-device decompression amplification,
    including under full replication)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.trainer import checkpoint as ckpt

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("model",))
    rows, cols = 1_000_000, 8
    sh = NamedSharding(mesh, P("model", None))
    table = jax.device_put(
        (jnp.arange(rows, dtype=jnp.float32)[:, None] % 997.0)
        * jnp.ones((1, cols), jnp.float32),
        sh,
    )
    path = str(tmp_path)
    ckpt._save_tree_sharded(path, "params", {"table": table})
    ckpt._merge_tree_indexes(path, "params")

    table_bytes = rows * cols * 4
    shard_rows = rows // 8
    shard_bytes = shard_rows * cols * 4

    # one device slice costs one record, not the table
    reader = ckpt._ShardedTreeReader(path, ckpt._tree_index(path, "params"))
    got = reader.read_slice(
        "table", (slice(shard_rows, 2 * shard_rows), slice(None)),
        (rows, cols), np.float32,
    )
    np.testing.assert_array_equal(
        got, np.asarray(table[shard_rows : 2 * shard_rows]))
    assert reader.bytes_read == shard_bytes, (reader.bytes_read, shard_bytes)
    reader.close()

    # full sharded restore: every record read exactly once, bit-exact
    stats = {}
    params, _, _ = ckpt.load_checkpoint(
        path, sharding_for=lambda base, key, shape: sh, io_stats=stats)
    assert stats["params"] == table_bytes, stats
    np.testing.assert_array_equal(np.asarray(params["table"]), np.asarray(table))

    # fully-replicated restore must not amplify reads across the 8 devices
    rep = NamedSharding(mesh, P(None, None))
    stats2 = {}
    params2, _, _ = ckpt.load_checkpoint(
        path, sharding_for=lambda base, key, shape: rep, io_stats=stats2)
    assert stats2["params"] == table_bytes, stats2
    np.testing.assert_array_equal(np.asarray(params2["table"]), np.asarray(table))


def test_streaming_restore_cross_alignment(tmp_path):
    """A requested slice that is NOT aligned to the written shard records
    (cross-layout restore: different mesh on load) assembles from partial
    overlaps of exactly the records it intersects."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.trainer import checkpoint as ckpt

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("model",))
    rows, cols = 4096, 4
    table = jax.device_put(
        jnp.arange(rows * cols, dtype=jnp.float32).reshape(rows, cols),
        NamedSharding(mesh, P("model", None)),  # 8 records of 512 rows
    )
    path = str(tmp_path)
    ckpt._save_tree_sharded(path, "params", {"t": table})
    ckpt._merge_tree_indexes(path, "params")

    reader = ckpt._ShardedTreeReader(path, ckpt._tree_index(path, "params"))
    # rows [700, 1900) span records 1..3 with partial overlap on both ends
    got = reader.read_slice("t", (slice(700, 1900), slice(None)),
                            (rows, cols), np.float32)
    np.testing.assert_array_equal(got, np.asarray(table[700:1900]))
    # exactly records 1,2,3 were read (512 rows * 4 cols * 4 bytes each)
    assert reader.bytes_read == 3 * 512 * cols * 4, reader.bytes_read
    reader.close()
