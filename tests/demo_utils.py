"""Shared demo-driving harness for tests: copy a demo's .py files into a
scratch dir, write the synthetic list files, train via the Trainer API
from inside that dir, and restore cwd — the one workflow previously
re-implemented per test module (test_quick_start, test_recommendation,
test_quality_curves)."""

import os
import shutil

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_demo(tmp_path, demo, train_lines=None, test_lines=None):
    """Copy demo/<demo>/*.py to tmp_path and write train/test lists.
    train_lines/test_lines: iterable of list-file entries (each entry
    seeds the demo's deterministic synthetic generator); None keeps the
    demo's own committed list file (demos that ship one)."""
    demo_dir = os.path.join(REPO, "demo", demo)
    for f in os.listdir(demo_dir):
        if f.endswith((".py", ".list")):
            shutil.copy(os.path.join(demo_dir, f), tmp_path)
    if train_lines is not None:
        (tmp_path / "train.list").write_text("".join(f"{s}\n" for s in train_lines))
    if test_lines is not None:
        (tmp_path / "test.list").write_text("".join(f"{s}\n" for s in test_lines))


def train_demo(tmp_path, cfg_name, num_passes, dtype=None, log_period=0,
               run_final_test=False, config_arg_str="", **flag_overrides):
    """parse_config + Trainer.train() from inside tmp_path (the demos use
    relative module imports and list paths). Returns (trainer, final test
    results or None)."""
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config(cfg_name, config_arg_str=config_arg_str)
        if dtype:
            cfg.opt_config.dtype = dtype
        flags = _Flags(config=cfg_name, num_passes=num_passes,
                       log_period=log_period, use_tpu=False, **flag_overrides)
        trainer = Trainer(cfg, flags)
        trainer.train()
        results = trainer.test() if run_final_test else None
        return trainer, results
    finally:
        os.chdir(cwd)
