"""Whole-net equivalence: the same math expressed through two different
config forms must produce identical outputs and gradients — the analog of
the reference's gserver/tests/test_NetworkCompare.cpp pairs
(concat_dotmul_a/b.conf, concat_fullmatrix_a/b.conf, concat_table_a/b.conf:
projections fed straight to concat_layer vs wrapped in mixed_layer).
"""

import os
import tempfile
import textwrap

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.graph import GradientMachine, make_dense, make_ids, make_seq


def parse_str(src: str):
    from paddle_tpu.config import parse_config

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(src))
        path = f.name
    try:
        return parse_config(path)
    finally:
        os.unlink(path)


def _compare(src_a: str, src_b: str, batch, out_name="concat"):
    """Both configs share parameter names, so the same seed gives the
    same init — outputs and input-cost gradients must agree exactly."""
    results = []
    for src in (src_a, src_b):
        tc = parse_str(src)
        gm = GradientMachine(tc.model_config)
        params = gm.init_params(seed=11)

        def loss(p):
            outs, _ = gm.forward(p, batch, "test")
            return jnp.sum(outs[out_name].value ** 2), outs[out_name].value

        (l, out), grads = jax.value_and_grad(loss, has_aux=True)(params)
        results.append((np.asarray(out), float(l),
                        {k: np.asarray(v) for k, v in grads.items()}))
    (out_a, l_a, g_a), (out_b, l_b, g_b) = results
    np.testing.assert_allclose(out_a, out_b, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(l_a, l_b, rtol=1e-6)
    assert set(g_a) == set(g_b)
    for k in g_a:
        np.testing.assert_allclose(g_a[k], g_b[k], rtol=1e-5, atol=1e-7,
                                   err_msg=k)


DOTMUL_A = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=16, learning_rate=0.1)
data = data_layer(name="input", size=20)
with mixed_layer(size=20, name="m1", bias_attr=False) as layer1:
    layer1 += dotmul_projection(input=data, param_attr=ParamAttr(name="dm1"))
with mixed_layer(size=20, name="m2", bias_attr=False) as layer2:
    layer2 += dotmul_projection(input=data, param_attr=ParamAttr(name="dm2"))
concat = concat_layer(input=[layer1, layer2], name="concat")
outputs(concat)
"""

DOTMUL_B = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=16, learning_rate=0.1)
data = data_layer(name="input", size=20)
proj1 = dotmul_projection(input=data, param_attr=ParamAttr(name="dm1"))
proj2 = dotmul_projection(input=data, param_attr=ParamAttr(name="dm2"))
concat = concat_layer(input=[proj1, proj2], name="concat")
outputs(concat)
"""


def test_concat_dotmul_forms_match():
    rng = np.random.RandomState(0)
    batch = {"input": make_dense(rng.randn(6, 20).astype(np.float32))}
    _compare(DOTMUL_A, DOTMUL_B, batch)


FULLMATRIX_A = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=16, learning_rate=0.1)
data = data_layer(name="input", size=10)
with mixed_layer(size=14, name="m1", bias_attr=False) as layer1:
    layer1 += full_matrix_projection(input=data, param_attr=ParamAttr(name="fm1"))
with mixed_layer(size=14, name="m2", bias_attr=False) as layer2:
    layer2 += full_matrix_projection(input=data, param_attr=ParamAttr(name="fm2"))
concat = concat_layer(input=[layer1, layer2], name="concat")
outputs(concat)
"""

FULLMATRIX_B = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=16, learning_rate=0.1)
data = data_layer(name="input", size=10)
proj1 = full_matrix_projection(input=data, size=14, param_attr=ParamAttr(name="fm1"))
proj2 = full_matrix_projection(input=data, size=14, param_attr=ParamAttr(name="fm2"))
concat = concat_layer(input=[proj1, proj2], name="concat")
outputs(concat)
"""


def test_concat_fullmatrix_forms_match():
    rng = np.random.RandomState(1)
    batch = {"input": make_dense(rng.randn(5, 10).astype(np.float32))}
    _compare(FULLMATRIX_A, FULLMATRIX_B, batch)


TABLE_A = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=16, learning_rate=0.1)
data = data_layer(name="input", size=30)
with mixed_layer(size=12, name="m1", bias_attr=False) as layer1:
    layer1 += table_projection(input=data, param_attr=ParamAttr(name="tb1"))
with mixed_layer(size=12, name="m2", bias_attr=False) as layer2:
    layer2 += table_projection(input=data, param_attr=ParamAttr(name="tb2"))
concat = concat_layer(input=[layer1, layer2], name="concat")
outputs(concat)
"""

TABLE_B = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=16, learning_rate=0.1)
data = data_layer(name="input", size=30)
proj1 = table_projection(input=data, size=12, param_attr=ParamAttr(name="tb1"))
proj2 = table_projection(input=data, size=12, param_attr=ParamAttr(name="tb2"))
concat = concat_layer(input=[proj1, proj2], name="concat")
outputs(concat)
"""


def test_concat_table_forms_match():
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 30, (4,)).astype(np.int32)
    batch = {"input": make_ids(ids)}
    _compare(TABLE_A, TABLE_B, batch)


def test_conv_operator_matches_torch_per_sample():
    """ConvOperator in a mixed layer: conv(image, filter_input) with
    PER-SAMPLE dynamic filters (reference ConvOperator.cpp, used for
    spatial attention). Verified against torch F.conv2d sample by
    sample."""
    import torch
    import torch.nn.functional as TF

    C, H, F, fs = 2, 6, 3, 3
    src = f"""
    from paddle_tpu.trainer_config_helpers import *
    settings(batch_size=4, learning_rate=0.1)
    img = data_layer(name="img", size={C * H * H})
    filt = data_layer(name="filt", size={F * C * fs * fs})
    with mixed_layer(size={F * 4 * 4}, name="convop", bias_attr=False) as m:
        m += conv_operator(input=[img, filt], filter_size={fs},
                           num_filters={F}, num_channel={C}, stride=1,
                           padding=0)
    outputs(m)
    """
    tc = parse_str(src)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=1)
    rng = np.random.RandomState(4)
    B = 2
    img = rng.randn(B, C * H * H).astype(np.float32)
    filt = rng.randn(B, F * C * fs * fs).astype(np.float32)
    outs, _ = gm.forward(
        params,
        {"img": make_dense(img), "filt": make_dense(filt)},
        "test",
    )
    got = np.asarray(outs["convop"].value)  # [B, F*out*out]
    for b in range(B):
        x = torch.from_numpy(img[b].reshape(1, C, H, H))
        w = torch.from_numpy(filt[b].reshape(F, C, fs, fs))
        ref = TF.conv2d(x, w, stride=1, padding=0).numpy().reshape(-1)
        np.testing.assert_allclose(got[b], ref, rtol=1e-4, atol=1e-5,
                                   err_msg=str(b))
