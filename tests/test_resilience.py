"""Fault-tolerance layer (doc/resilience.md): atomic checksummed
checkpoints with fallback restore, the data-pipeline watchdog, the
bad-sample budget, the shared RetryPolicy, and the deterministic
fault-injection harness that drives the chaos tests.

The chaos tests are fast and deterministic (seeded injection at named
sites), so they ride along with tier-1 under the ``chaos`` marker.
"""

import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.data.feeder import DataProvider
from paddle_tpu.data.provider import dense_vector, integer_value, provider
from paddle_tpu.resilience import (
    BadSampleError,
    CheckpointCorruptError,
    DataStallError,
    faultinject,
)
from paddle_tpu.resilience import manifest as mf
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.utils.retry import RetryPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clear_faults():
    """Fault plans are process-global; never leak one across tests."""
    yield
    faultinject.configure("")


def _params(offset=0.0):
    return {
        "w": jnp.arange(12.0).reshape(3, 4) + offset,
        "b": jnp.ones((4,)) + offset,
    }


def _truncate(path, keep_ratio=0.5):
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: int(len(data) * keep_ratio)])


# --------------------------------------------------------------- manifest


def test_manifest_roundtrip_and_detection(tmp_path):
    d = str(tmp_path)
    (tmp_path / "a.bin").write_bytes(b"hello world" * 100)
    (tmp_path / "b.json").write_text('{"k": 1}')
    mf.write_manifest(d)
    assert mf.verify_dir(d) == []
    # size mismatch (truncation)
    _truncate(os.path.join(d, "a.bin"))
    problems = mf.verify_dir(d)
    assert len(problems) == 1 and "size" in problems[0], problems
    # crc mismatch (same-size corruption)
    mf.write_manifest(d)
    data = bytearray((tmp_path / "a.bin").read_bytes())
    data[10] ^= 0xFF
    (tmp_path / "a.bin").write_bytes(bytes(data))
    problems = mf.verify_dir(d)
    assert len(problems) == 1 and "crc32" in problems[0], problems
    # missing file
    os.remove(os.path.join(d, "b.json"))
    assert any("missing" in p for p in mf.verify_dir(d))
    # a dir with no manifest verifies clean (pre-resilience checkpoints)
    assert mf.verify_dir(str(tmp_path / "nodir_yet")) == [] or True
    other = tmp_path / "legacy"
    other.mkdir()
    (other / "params.npz").write_bytes(b"x")
    assert mf.verify_dir(str(other)) == []


def test_partial_manifest_merge(tmp_path):
    d = str(tmp_path)
    (tmp_path / "t.shard00000.npz").write_bytes(b"p0" * 50)
    (tmp_path / "t.shard00001.npz").write_bytes(b"p1" * 70)
    (tmp_path / "meta.json").write_text("{}")
    mf.write_partial_manifest(d, 0, ["t.shard00000.npz"])
    mf.write_partial_manifest(d, 1, ["t.shard00001.npz"])
    merged = mf.merge_partial_manifests(d)
    # partials merged + process-0-local leftovers (meta.json) digested
    assert set(merged["files"]) == {
        "t.shard00000.npz", "t.shard00001.npz", "meta.json",
    }
    assert not [n for n in os.listdir(d) if n.startswith("MANIFEST.partial")]
    assert mf.verify_dir(d) == []


# ------------------------------------------------------------ RetryPolicy


def test_retry_policy_retries_then_succeeds():
    sleeps = []
    policy = RetryPolicy(
        max_attempts=4, base_delay=0.1, jitter=0.0, sleep=sleeps.append
    )
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert policy.call(flaky) == "ok"
    assert len(calls) == 3
    assert sleeps == [0.1, 0.2]  # exponential, no jitter


def test_retry_policy_exhausts_attempts():
    policy = RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0, sleep=lambda s: None)
    calls = []

    def always_fails():
        calls.append(1)
        raise OSError("still broken")

    with pytest.raises(OSError, match="still broken"):
        policy.call(always_fails)
    assert len(calls) == 3


def test_retry_policy_nonretryable_passes_through():
    policy = RetryPolicy(max_attempts=5, sleep=lambda s: None)
    with pytest.raises(ValueError):
        policy.call(lambda: (_ for _ in ()).throw(ValueError("logic bug")))


def test_retry_policy_jitter_and_cap():
    import random

    policy = RetryPolicy(base_delay=1.0, max_delay=4.0, multiplier=2.0, jitter=0.25)
    rng = random.Random(0)
    for attempt, cap in [(1, 1.0), (2, 2.0), (3, 4.0), (10, 4.0)]:
        for _ in range(50):
            d = policy.delay_for(attempt, rng)
            assert cap * 0.75 <= d <= cap * 1.25, (attempt, d)


def test_retry_policy_deadline():
    policy = RetryPolicy(max_attempts=1000, base_delay=0.02, jitter=0.0, deadline=0.08)
    calls = []

    def always_fails():
        calls.append(1)
        raise OSError("nope")

    with pytest.raises(OSError):
        policy.call(always_fails)
    assert 1 < len(calls) < 100  # deadline stopped it long before max_attempts


# ------------------------------------------------------------ faultinject


def test_fault_spec_parsing_and_triggers():
    inj = faultinject.FaultInjector("a.b=raise@2;c.d=raise@3+")
    # nth: fires on exactly the 2nd hit
    inj.fire("a.b")
    with pytest.raises(faultinject.FaultInjected):
        inj.fire("a.b")
    inj.fire("a.b")  # 3rd hit: silent again
    # from: every hit >= 3
    inj.fire("c.d")
    inj.fire("c.d")
    for _ in range(3):
        with pytest.raises(faultinject.FaultInjected):
            inj.fire("c.d")
    # unknown sites are free
    inj.fire("nobody.home")
    assert inj.hits("a.b") == 3


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError):
        faultinject.FaultInjector("not a spec")
    with pytest.raises(ValueError):
        faultinject.FaultInjector("site=raise@p1.5")


def test_fault_probability_is_seed_deterministic():
    def pattern(seed):
        inj = faultinject.FaultInjector("x=raise@p0.5", seed)
        out = []
        for _ in range(40):
            try:
                inj.fire("x")
                out.append(0)
            except faultinject.FaultInjected:
                out.append(1)
        return out

    p7 = pattern(7)
    assert p7 == pattern(7)  # pure function of (seed, site)
    assert 0 < sum(p7) < 40  # actually probabilistic
    assert p7 != pattern(8)


def test_fault_oserror_is_retryable():
    faultinject.configure("x.y=oserror@1")
    policy = RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0, sleep=lambda s: None)
    calls = []

    def op():
        calls.append(1)
        faultinject.fault_point("x.y")
        return "ok"

    assert policy.call(op) == "ok"
    assert len(calls) == 2  # one injected EIO, one clean retry


# ---------------------------------------------- atomic checkpoint + chaos


@pytest.mark.chaos
def test_midwrite_fault_preserves_previous_checkpoint(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    faultinject.configure("checkpoint.write=raise@1")
    with pytest.raises(faultinject.FaultInjected):
        ckpt.save_checkpoint(d, 1, _params(offset=100.0))
    # the aborted save never touched the published namespace
    assert not os.path.exists(os.path.join(d, "pass-00001"))
    assert ckpt.verify_checkpoint(os.path.join(d, "pass-00000")) == []
    params, _, meta = ckpt.load_checkpoint(os.path.join(d, "pass-00000"))
    np.testing.assert_array_equal(np.asarray(params["w"]), np.asarray(_params()["w"]))
    # a later clean save of the same pass succeeds and sweeps the stale tmp
    faultinject.configure("")
    ckpt.save_checkpoint(d, 1, _params(offset=100.0))
    names = sorted(os.listdir(d))
    assert names == ["pass-00000", "pass-00001"], names


@pytest.mark.chaos
def test_torn_rename_leaves_both_old_checkpoint_and_tmp(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    faultinject.configure("checkpoint.rename=raise@1")
    with pytest.raises(faultinject.FaultInjected):
        ckpt.save_checkpoint(d, 1, _params(offset=1.0))
    # torn exactly between write and rename: tmp fully written, final absent
    assert os.path.exists(os.path.join(d, "pass-00001.tmp", "MANIFEST.json"))
    assert not os.path.exists(os.path.join(d, "pass-00001"))
    assert ckpt.find_restorable_checkpoint(d) == os.path.join(d, "pass-00000")


CRASH_CHILD = """
import sys
sys.path.insert(0, {repo!r})
from paddle_tpu.utils.backend_guard import ensure_cpu_mesh
ensure_cpu_mesh(1)
import jax.numpy as jnp
from paddle_tpu.resilience import faultinject
from paddle_tpu.trainer import checkpoint as ckpt

d = sys.argv[1]
params = {{"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.ones((4,))}}
ckpt.save_checkpoint(d, 0, params)
faultinject.configure("checkpoint.rename=exit@1")  # os._exit: a real kill
ckpt.save_checkpoint(d, 1, {{"w": params["w"] + 100.0, "b": params["b"]}})
print("UNREACHABLE")
"""


@pytest.mark.chaos
def test_hard_kill_between_write_and_rename_subprocess(tmp_path):
    """The acceptance scenario end-to-end, with a REAL process death
    (os._exit — no finally blocks, no atexit): the previous pass dir
    stays intact and restorable, and the next save heals the litter."""
    d = str(tmp_path / "out")
    r = subprocess.run(
        [sys.executable, "-c", CRASH_CHILD.format(repo=REPO), d],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS=""),
    )
    assert r.returncode == 3, (r.returncode, r.stderr[-2000:])
    assert "UNREACHABLE" not in r.stdout
    # killed between write and rename: tmp complete, final never appeared
    assert os.path.isdir(os.path.join(d, "pass-00001.tmp"))
    assert not os.path.exists(os.path.join(d, "pass-00001"))
    # the previous checkpoint is intact, verified, and restorable
    prev = os.path.join(d, "pass-00000")
    assert ckpt.verify_checkpoint(prev) == []
    assert ckpt.find_restorable_checkpoint(d) == prev
    params, _, meta = ckpt.load_checkpoint(prev)
    np.testing.assert_array_equal(
        np.asarray(params["w"]), np.arange(12.0).reshape(3, 4)
    )
    assert meta["pass_id"] == 0
    # recovery save sweeps the stale tmp
    ckpt.save_checkpoint(d, 1, _params(offset=100.0))
    assert sorted(os.listdir(d)) == ["pass-00000", "pass-00001"]


@pytest.mark.chaos
def test_corrupt_latest_quarantined_and_falls_back(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    ckpt.save_checkpoint(d, 1, _params(offset=50.0))
    _truncate(os.path.join(d, "pass-00001", "params.npz"))
    params, _, meta = ckpt.load_checkpoint(os.path.join(d, "pass-00001"))
    # fell back to the prior pass and quarantined the bad dir
    assert meta["pass_id"] == 0
    np.testing.assert_array_equal(np.asarray(params["w"]), np.asarray(_params()["w"]))
    names = sorted(os.listdir(d))
    assert names == ["pass-00000", "pass-00001.corrupt"], names


@pytest.mark.chaos
def test_legacy_checkpoint_without_manifest_still_falls_back(tmp_path):
    """Pre-manifest checkpoints can't be caught by verification — a
    truncated legacy npz surfaces as BadZipFile at deserialization time
    and must still enter the quarantine+fallback chain."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    ckpt.save_checkpoint(d, 1, _params(offset=5.0))
    os.remove(os.path.join(d, "pass-00001", "MANIFEST.json"))  # legacy dir
    _truncate(os.path.join(d, "pass-00001", "params.npz"))
    params, _, meta = ckpt.load_checkpoint(os.path.join(d, "pass-00001"))
    assert meta["pass_id"] == 0
    assert os.path.isdir(os.path.join(d, "pass-00001.corrupt"))


@pytest.mark.chaos
def test_protected_old_dir_survives_rotation_sweep(tmp_path):
    """Torn-commit recovery: the pass-N.old a run restored from is the
    only known-good state — rotation must not sweep it until protection
    is lifted."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    os.rename(os.path.join(d, "pass-00000"), os.path.join(d, "pass-00000.old"))
    ckpt.save_checkpoint(d, 1, _params(offset=1.0), protect_pass=0)
    assert sorted(os.listdir(d)) == ["pass-00000.old", "pass-00001"]
    # protection lifted (a newer save proved durable): litter is swept
    ckpt.save_checkpoint(d, 2, _params(offset=2.0))
    assert sorted(os.listdir(d)) == ["pass-00001", "pass-00002"]


def test_nonexistent_path_raises_filenotfound(tmp_path):
    """A never-existed path (wrong --start_pass, typo'd init_model_path)
    is a caller error: fail fast, never silently substitute an older
    checkpoint, never mutate the save_dir."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 3, _params())
    with pytest.raises(FileNotFoundError):
        ckpt.load_checkpoint(os.path.join(d, "pass-00009"))
    assert sorted(os.listdir(d)) == ["pass-00003"]


def test_fallback_candidates_verified_even_when_first_preverified(tmp_path):
    """verify=False covers only the first (caller-verified) candidate —
    anything the fallback chain reaches is unvetted and must pass
    verification before being deserialized."""
    d = str(tmp_path)
    for p in range(3):
        ckpt.save_checkpoint(d, p, _params(offset=float(p)))
    # pass-2: params tree gone (load fails after the skipped verify);
    # pass-1: truncated (only verification catches it); pass-0: clean
    os.remove(os.path.join(d, "pass-00002", "params.npz"))
    _truncate(os.path.join(d, "pass-00001", "params.npz"))
    params, _, meta = ckpt.load_checkpoint(
        os.path.join(d, "pass-00002"), verify=False
    )
    assert meta["pass_id"] == 0
    np.testing.assert_array_equal(np.asarray(params["w"]), np.asarray(_params()["w"]))


def test_no_fallback_candidate_raises(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    _truncate(os.path.join(d, "pass-00000", "params.npz"))
    with pytest.raises(CheckpointCorruptError) as ei:
        ckpt.load_checkpoint(os.path.join(d, "pass-00000"))
    assert "pass-00000" in str(ei.value)
    assert os.path.isdir(os.path.join(d, "pass-00000.corrupt"))


@pytest.mark.chaos
def test_torn_commit_old_dir_is_last_resort_restorable(tmp_path):
    """Crash exactly between _commit's two renames (re-save of the same
    pass): pass-N.old holds the previous durable checkpoint and the
    restore scan recovers it when nothing else exists."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    os.rename(os.path.join(d, "pass-00000"), os.path.join(d, "pass-00000.old"))
    got = ckpt.find_restorable_checkpoint(d)
    assert got == os.path.join(d, "pass-00000.old")
    params, _, _ = ckpt.load_checkpoint(got)
    np.testing.assert_array_equal(np.asarray(params["w"]), np.asarray(_params()["w"]))
    # once a newer save completes, the leftover is swept
    ckpt.save_checkpoint(d, 1, _params(offset=1.0))
    assert sorted(os.listdir(d)) == ["pass-00001"]


def test_resave_same_pass_is_atomic(tmp_path):
    """Periodic save then pass-end save hit the same pass id: the second
    replaces the first without a window where neither exists."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    ckpt.save_checkpoint(d, 0, _params(offset=9.0))
    assert sorted(os.listdir(d)) == ["pass-00000"]
    params, _, _ = ckpt.load_checkpoint(os.path.join(d, "pass-00000"))
    np.testing.assert_array_equal(
        np.asarray(params["w"]), np.asarray(_params(offset=9.0)["w"])
    )


def test_write_fault_retried_by_io_policy(tmp_path, monkeypatch):
    from paddle_tpu.utils.flags import FLAGS

    monkeypatch.setattr(FLAGS, "io_retry_base_delay", 0.01)
    faultinject.configure("checkpoint.write=oserror@1")
    path = ckpt.save_checkpoint(str(tmp_path), 0, _params())
    assert ckpt.verify_checkpoint(path) == []
    assert faultinject.current().hits("checkpoint.write") >= 2  # retried


def test_rotation_budget_and_protection(tmp_path):
    d = str(tmp_path)
    for p in range(3):
        ckpt.save_checkpoint(d, p, _params(), keep=2)
    assert sorted(os.listdir(d)) == ["pass-00001", "pass-00002"]
    # tmp/corrupt dirs never count toward the keep budget; stale tmp is
    # swept, quarantine is kept
    os.makedirs(os.path.join(d, "pass-00007.tmp"))
    os.makedirs(os.path.join(d, "pass-00006.corrupt"))
    ckpt.save_checkpoint(d, 3, _params(), keep=2)
    names = sorted(os.listdir(d))
    assert names == ["pass-00002", "pass-00003", "pass-00006.corrupt"], names
    # the restored-from pass is never rolled away
    for p in range(4, 7):
        ckpt.save_checkpoint(d, p, _params(), keep=2, protect_pass=2)
    names = sorted(n for n in os.listdir(d) if ckpt._is_pass_dir_name(n))
    assert names == ["pass-00002", "pass-00005", "pass-00006"], names


def test_latest_pass_ignores_tmp_and_corrupt(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 1, _params())
    os.makedirs(os.path.join(d, "pass-00009.tmp"))
    os.makedirs(os.path.join(d, "pass-00008.corrupt"))
    assert ckpt.latest_pass(d) == 1


# ------------------------------------------------------- data pipeline


def _dense_provider(n=64, bad_every=0):
    @provider(input_types=[dense_vector(4), integer_value(2)])
    def process(settings, file_name):
        for i in range(n):
            if bad_every and i % bad_every == 3:
                yield ["not", "a", "float", "!"], 0  # malformed dense row
            else:
                yield [float(i)] * 4, i % 2

    return process


def _mk_dp(p, **kw):
    kw.setdefault("stall_timeout", 0)
    kw.setdefault("max_bad_samples", 0)
    kw.setdefault(
        "retry",
        RetryPolicy(max_attempts=3, base_delay=0.01, max_delay=0.02, jitter=0.0),
    )
    return DataProvider(p, ["f1"], 8, ["x", "y"], **kw)


@pytest.mark.chaos
def test_stalled_provider_raises_datastallerror_within_timeout():
    import time

    faultinject.configure("provider.stall=sleep:20@2")
    dp = _mk_dp(_dense_provider(), stall_timeout=1.0)
    t0 = time.monotonic()
    with pytest.raises(DataStallError) as ei:
        list(dp.batches())
    elapsed = time.monotonic() - t0
    assert elapsed < 10, elapsed  # raised within the timeout, not after 20s
    # the error is diagnosable: liveness + stall age + the knob to turn
    msg = str(ei.value)
    assert "data_stall_timeout" in msg and "alive" in msg, msg


@pytest.mark.chaos
def test_flaky_provider_succeeds_under_retry_exactly_once():
    faultinject.configure("provider.yield=oserror@5")
    dp = _mk_dp(_dense_provider(n=40), async_prefetch=False)
    batches = list(dp.batches())
    xs = sorted(
        float(v)
        for b in batches
        for v in np.asarray(b["x"].value)[:, 0]
    )
    # every sample delivered exactly once despite the mid-file EIO
    assert xs == [float(i) for i in range(40)], xs[:10]
    assert faultinject.current().hits("provider.yield") > 40  # retried


@pytest.mark.chaos
def test_retry_budget_resets_after_progress():
    """Two isolated transient errors far apart in one file must not add
    up to 'retries exhausted' — successful progress earns a fresh
    budget."""
    faultinject.configure("provider.yield=oserror@3;provider.yield=oserror@30")
    dp = _mk_dp(
        _dense_provider(n=40), async_prefetch=False,
        retry=RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0,
                          sleep=lambda s: None),
    )
    xs = sorted(
        float(v) for b in dp.batches() for v in np.asarray(b["x"].value)[:, 0]
    )
    assert xs == [float(i) for i in range(40)]  # both hiccups survived


@pytest.mark.chaos
def test_flaky_provider_fails_when_retries_exhausted():
    faultinject.configure("provider.yield=oserror@5+")  # every hit >= 5
    dp = _mk_dp(_dense_provider(n=40), async_prefetch=False)
    with pytest.raises(OSError):
        list(dp.batches())


def test_bad_sample_budget_skips_then_fails():
    # 40 samples, malformed at i = 3, 13, 23, 33 → 4 bad
    dp = _mk_dp(_dense_provider(n=40, bad_every=10), max_bad_samples=5,
                async_prefetch=False)
    total = sum(len(np.asarray(b["y"].ids)) for b in dp.batches())
    assert total == 36  # the 4 bad samples were skipped, all others kept
    # budget exceeded → loud typed failure
    dp2 = _mk_dp(_dense_provider(n=40, bad_every=10), max_bad_samples=3,
                 async_prefetch=False)
    with pytest.raises(BadSampleError, match="max_bad_samples"):
        list(dp2.batches())


def test_bad_sample_budget_disabled_is_failfast():
    dp = _mk_dp(_dense_provider(n=20, bad_every=10), max_bad_samples=0,
                async_prefetch=False)
    with pytest.raises(Exception):
        list(dp.batches())


# ----------------------------------------------------- trainer wiring


def test_trainer_auto_restore_skips_corrupt_and_resumes(tmp_path):
    import textwrap

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    providers = os.path.join(REPO, "tests", "providers")
    sys.path.insert(0, providers)
    try:
        (tmp_path / "train.list").write_text("1\n")
        cfg_src = textwrap.dedent(f"""
        from paddle_tpu.trainer_config_helpers import *
        define_py_data_sources2(train_list={str(tmp_path / 'train.list')!r},
                                test_list=None,
                                module="synthetic_bow", obj="process")
        settings(batch_size=64, learning_rate=0.02,
                 learning_method=AdamOptimizer())
        data = data_layer(name="word", size=100)
        output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
        label = data_layer(name="label", size=2)
        outputs(classification_cost(input=output, label=label))
        """)
        (tmp_path / "cfg.py").write_text(cfg_src)
        cfg = parse_config(str(tmp_path / "cfg.py"))
        save_dir = str(tmp_path / "out")
        t1 = Trainer(cfg, _Flags(save_dir=save_dir, log_period=0))
        t1.train(num_passes=2)
        assert ckpt.latest_pass(save_dir) == 1
        step_after = int(t1.opt_state.step)

        # corrupt the newest checkpoint; auto-restore must skip it,
        # resume from pass 0, and protect pass 0 from rotation
        _truncate(os.path.join(save_dir, "pass-00001", "params.npz"))
        t2 = Trainer(
            cfg, _Flags(save_dir=save_dir, init_model_path="auto", log_period=0)
        )
        assert t2._restored_pass == 0
        assert t2.start_pass == 1  # resumes after the restored pass
        assert 0 < int(t2.opt_state.step) < step_after

        # nothing restorable → fresh start, not a crash
        t3 = Trainer(
            cfg,
            _Flags(save_dir=str(tmp_path / "empty"), init_model_path="auto",
                   log_period=0),
        )
        assert t3._restored_pass is None and t3.start_pass == 0
    finally:
        sys.path.remove(providers)


# ------------------------------------------------------------- tooling


def test_check_checkpoint_cli(tmp_path, capsys):
    from paddle_tpu import cli

    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, _params())
    ckpt.save_checkpoint(d, 1, _params())
    assert cli.main(["check-checkpoint", d]) == 0
    out = capsys.readouterr().out
    assert out.count("OK") == 2 and "CORRUPT" not in out
    # single pass dir form
    assert cli.main(["check-checkpoint", os.path.join(d, "pass-00001")]) == 0
    # corruption detected offline
    _truncate(os.path.join(d, "pass-00001", "params.npz"))
    assert cli.main(["check-checkpoint", d]) == 1
    out = capsys.readouterr().out
    assert "CORRUPT" in out and "truncated" in out
    # usage errors
    assert cli.main(["check-checkpoint"]) == 2
    assert cli.main(["check-checkpoint", str(tmp_path / "nope")]) == 2
