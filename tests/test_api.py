"""Embedding API (swig_paddle roles): build, load, forward, generate.

Mirrors the reference's api tests (paddle/api/test/testGradientMachine.py,
testTrain.py:48-60): construct a machine from a parsed config, run
forwardTest from numpy via the converter, mutate parameters, run a custom
training step from Python, and beam-generate from a seqToseq model.
"""

import os
import shutil

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lr_config(tmp_path, dict_dim=30, with_cost=True):
    tail = (
        "label = data_layer('label', size=2)\n"
        "outputs(classification_cost(input=out, label=label))\n"
        if with_cost
        else "outputs(out)\n"
    )
    name = f"api_conf_{int(with_cost)}.py"
    (tmp_path / name).write_text(
        "from paddle.trainer_config_helpers import *\n"
        f"settings(batch_size=8, learning_rate=1e-2)\n"
        f"data = data_layer('word', size={dict_dim})\n"
        "out = fc_layer(input=data, size=2, act=SoftmaxActivation(), name='out')\n"
        + tail
    )
    return str(tmp_path / name)


def test_forward_and_parameter_access(tmp_path):
    from paddle_tpu.api import DataProviderConverter, GradientMachine
    from paddle_tpu.config import parse_config
    from paddle_tpu.data.provider import dense_vector, integer_value

    conf = parse_config(_lr_config(tmp_path, with_cost=False))
    machine = GradientMachine.createFromConfigProto(conf.model_config)

    names = machine.getParameterNames()
    assert any("out" in n for n in names), names

    conv = DataProviderConverter(
        [dense_vector(30)], machine.input_layer_names()
    )
    samples = [[np.random.RandomState(i).rand(30).tolist()] for i in range(4)]
    out = machine.forwardTest(conv(samples))
    # output layers: cost + 'out'; find the softmax output entry
    probs = [e for e in out if "value" in e and e["value"].shape[-1] == 2]
    assert probs and np.allclose(probs[0]["value"].sum(axis=-1), 1.0, atol=1e-5)

    # setParameter round-trip changes the forward result
    w_name = next(n for n in names if "w" in n.lower() or "out" in n)
    w = machine.getParameter(w_name)
    machine.setParameter(w_name, np.zeros_like(w))
    out2 = machine.forwardTest(conv(samples))
    probs2 = [e for e in out2 if "value" in e and e["value"].shape[-1] == 2]
    assert not np.allclose(probs[0]["value"], probs2[0]["value"])


def test_custom_train_loop_and_save_load(tmp_path):
    from paddle_tpu.api import DataProviderConverter, GradientMachine
    from paddle_tpu.config import parse_config
    from paddle_tpu.data.provider import dense_vector, integer_value

    conf = parse_config(_lr_config(tmp_path))
    machine = GradientMachine.createFromConfigProto(conf.model_config)
    conv = DataProviderConverter(
        [dense_vector(30), integer_value(2)], machine.input_layer_names()
    )
    rng = np.random.RandomState(0)
    # planted rule: label = (x[0] > 0.5)
    xs = rng.rand(64, 30).astype(np.float32)
    ys = (xs[:, 0] > 0.5).astype(np.int32)
    batch = conv([[x.tolist(), int(y)] for x, y in zip(xs, ys)])

    losses = []
    for _ in range(60):
        loss, grads = machine.forwardBackward(batch)
        losses.append(loss)
        for name, g in grads.items():
            machine.setParameter(name, machine.getParameter(name) - 0.5 * g)
    assert losses[-1] < losses[0] * 0.7, losses[::20]

    # save / reload round-trip preserves behavior
    machine.saveParameters(str(tmp_path / "ckpt"), pass_id=3)
    fresh = GradientMachine.createFromConfigProto(conf.model_config, seed=99)
    fresh.loadParameters(str(tmp_path / "ckpt"))
    a = machine.forwardTest(batch)
    b = fresh.forwardTest(batch)
    np.testing.assert_allclose(
        np.asarray(a[0].get("value", 0)), np.asarray(b[0].get("value", 0)), rtol=1e-6
    )


def test_sequence_generator(tmp_path):
    from paddle_tpu.api import GradientMachine
    from paddle_tpu.config import parse_config
    from paddle_tpu.data.feeder import BatchAssembler
    from paddle_tpu.data.provider import integer_value_sequence

    demo = os.path.join(REPO, "demo", "seqToseq")
    for f in os.listdir(demo):
        if f.endswith((".py", ".conf")):
            shutil.copy(os.path.join(demo, f), tmp_path)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        (tmp_path / "test.list").write_text("gen-seed-1\n")
        conf = parse_config("gen.conf")
        machine = GradientMachine.createFromConfigProto(conf.model_config)
        gen = machine.asSequenceGenerator(max_length=10)
        import dataprovider as dp

        names = machine.input_layer_names()
        assembler = BatchAssembler(
            [integer_value_sequence(dp.VOCAB)] * len(names), names
        )
        src = [[3, 4, 5, 6], [7, 8, 9]]
        batch = assembler.assemble([[s] * len(names) for s in src])
        results = gen.generate(batch)
        assert len(results) == 2
        for beams in results:
            assert beams and all("ids" in b and "score" in b for b in beams)
            # best-first ordering
            scores = [b["score"] for b in beams]
            assert scores == sorted(scores, reverse=True)
    finally:
        os.chdir(cwd)
