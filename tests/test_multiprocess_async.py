"""Multi-process async SGD (local SGD) — the loopback-pserver analog for
the async path (reference async tests ran loopback pservers too,
test_TrainerOnePass.cpp:120-296).

Two OS processes form one 8-device CPU mesh (4 virtual devices each) and
train an is_async=True config; the replica-stacked step, the drift-gated
merge, and the collapse are all cross-process collectives here. The
final parameters must match the single-process 8-device async run — the
mode is SPMD-deterministic, so process count cannot change numerics
beyond float reassociation.
"""

import os
import sys
import textwrap

import numpy as np

import mp_harness

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

WORKER = mp_harness.WORKER_PREAMBLE + """
from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS

FLAGS.save_dir = ""
FLAGS.mesh_shape = "data=8"
FLAGS.log_period = 0
FLAGS.seed = 7
trainer = Trainer(parse_config(os.path.join(ws, "cfg.py")))
assert trainer._async, "async mode must be active on the 8-way data mesh"
trainer.train(num_passes=1)

if jax.process_index() == 0:
    import numpy as np
    np.savez(os.path.join(ws, "mp_async_params.npz"),
             **{{k: np.asarray(v) for k, v in trainer.params.items()}})
print("WORKER_OK", pid, flush=True)
"""


def _write_config(ws):
    train_list = os.path.join(ws, "train.list")
    with open(train_list, "w") as f:
        f.write("1\n2\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={train_list!r}, test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.05,
             learning_method=MomentumOptimizer(momentum=0.9),
             is_async=True, num_batches_per_send_parameter=3)
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    path = os.path.join(ws, "cfg.py")
    with open(path, "w") as f:
        f.write(src)
    return path


def test_two_process_async_matches_single(tmp_path):
    mp_harness.skip_unless_cross_process_computations()
    ws = str(tmp_path)
    cfg_path = _write_config(ws)
    sys.path.insert(0, PROVIDERS)

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    FLAGS.save_dir = ""
    FLAGS.mesh_shape = "data=8"
    FLAGS.log_period = 0
    FLAGS.seed = 7
    try:
        ref = Trainer(parse_config(cfg_path))
        assert ref._async
        ref.train(num_passes=1)
    finally:
        FLAGS.mesh_shape = ""
        sys.path.remove(PROVIDERS)

    mp_harness.run_two_workers(WORKER.format(repo=REPO, providers=PROVIDERS), ws)

    with np.load(os.path.join(ws, "mp_async_params.npz")) as z:
        mp_params = {k: z[k] for k in z.files}
    for name, ref_v in ref.params.items():
        np.testing.assert_allclose(
            np.asarray(ref_v), mp_params[name], rtol=2e-4, atol=1e-5,
            err_msg=name,
        )
