"""Preemption-aware checkpointing: SIGTERM during train() saves a
consistent checkpoint at the next launch boundary and exits cleanly —
the TPU-pod recovery story SURVEY §5 flags as the reference's gap (its
design is fail-fast restart-from-last-pass only)."""

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = """
import sys
sys.path.insert(0, {repo!r})
import os
os.chdir({ws!r})
from paddle_tpu.utils.backend_guard import ensure_cpu_mesh
ensure_cpu_mesh(1)
from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import _Flags

open("cfg.py", "w").write('''
from paddle_tpu.trainer_config_helpers import *
define_py_data_sources2(train_list="train.list", test_list=None,
                        module="slow_provider", obj="process")
settings(batch_size=16, learning_rate=0.1, learning_method=MomentumOptimizer())
data = data_layer(name="x", size=8)
out = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
label = data_layer(name="y", size=2)
outputs(classification_cost(input=out, label=label))
''')
open("train.list", "w").write("s1\\n")
open("slow_provider.py", "w").write('''
from paddle_tpu.data.provider import *
import os, time

@provider(input_types=[dense_vector(8), integer_value(2)])
def process(settings, file_name):
    for i in range(100000):
        time.sleep(0.002)  # slow stream: many launch boundaries
        if i == 200:       # the loop is demonstrably live
            open("started.flag", "w").write("x")
        yield [0.1] * 8, i % 2
''')
cfg = parse_config("cfg.py")
flags = _Flags(config="cfg.py", num_passes=1, log_period=0, save_dir="out")
t = Trainer(cfg, flags)
t.train()
print("TRAIN_RETURNED_CLEANLY", flush=True)
"""


def test_sigterm_saves_checkpoint_and_exits(tmp_path):
    proc = subprocess.Popen(
        [sys.executable, "-c", CHILD.format(repo=REPO, ws=str(tmp_path))],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=tmp_path,
        env=dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu"),
    )
    try:
        flag = tmp_path / "started.flag"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not flag.exists():
            if proc.poll() is not None:
                out, _ = proc.communicate()
                raise AssertionError(f"child exited early:\n{out[-2500:]}")
            time.sleep(0.25)
        assert flag.exists(), "training loop never became live"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:  # never leak the slow-provider child
            proc.kill()
            proc.wait()
    assert proc.returncode == 0, out[-2500:]
    assert "TRAIN_RETURNED_CLEANLY" in out, out[-2500:]
    assert "preemption: checkpoint saved" in out, out[-2500:]
    assert (tmp_path / "out" / "pass-00000").exists(), out[-1500:]


RESUME_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import os
os.chdir({ws!r})
from paddle_tpu.utils.backend_guard import ensure_cpu_mesh
ensure_cpu_mesh(1)
from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import _Flags

cfg = parse_config("cfg.py")
flags = _Flags(config="cfg.py", num_passes=1, log_period=0,
               init_model_path=os.path.join("out", "pass-00000"))
t = Trainer(cfg, flags)
# the preemption checkpoint carries the optimizer state: the step
# counter must resume from where the SIGTERM landed, not zero
step = int(t.opt_state.step)
print(f"RESUMED_STEP={{step}}", flush=True)
assert step > 0, step
"""


def test_resume_from_preemption_checkpoint(tmp_path):
    """The documented resume path: --init_model_path on the preemption
    checkpoint restores parameters AND optimizer state."""
    # first leg: train, preempt, save (same flow as the test above)
    proc = subprocess.Popen(
        [sys.executable, "-c", CHILD.format(repo=REPO, ws=str(tmp_path))],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=tmp_path,
        env=dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu"),
    )
    try:
        flag = tmp_path / "started.flag"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not flag.exists():
            assert proc.poll() is None, proc.communicate()[0][-2000:]
            time.sleep(0.25)
        assert flag.exists()
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert proc.returncode == 0, out[-2000:]
    # second leg: resume and verify the optimizer step counter carried over
    r = subprocess.run(
        [sys.executable, "-c", RESUME_CHILD.format(repo=REPO, ws=str(tmp_path))],
        capture_output=True, text=True, timeout=180, cwd=tmp_path,
        env=dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "RESUMED_STEP=" in r.stdout
