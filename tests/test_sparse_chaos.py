"""Sparse-parameter chaos drills (doc/sparse.md): host loss between the
row-shard write and the commit, elastic reshard-and-resume with
bit-exact surviving rows, the launcher's row-budget refusal, the CTR
demo's train/checkpoint/crash/recover loop, and the two-process REAL
snapshot path stamping ``row_range`` over the jax distributed runtime.

The fast structural/unit half lives in tests/test_sparse_rowshard.py.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mp_harness
from paddle_tpu.sparse import ckpt as sparse_ckpt
from paddle_tpu.sparse import rowshard
from paddle_tpu.sparse import runtime as sparse_rt
from paddle_tpu.trainer import checkpoint as ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

pytestmark = [pytest.mark.chaos, pytest.mark.sparse]


@pytest.fixture(autouse=True)
def _fresh_state():
    from paddle_tpu.observability import metrics as obs
    from paddle_tpu.resilience import faultinject

    sparse_rt.clear_tables()
    obs.registry().reset()
    yield
    sparse_rt.clear_tables()
    faultinject.configure("", 0)
    obs.configure("")


def _write_fake_ssh(bin_dir, body):
    ssh = bin_dir / "ssh"
    ssh.write_text("#!/bin/sh\nhost=$3\nremote=$4\n" + body)
    ssh.chmod(0o755)
    return {**os.environ, "PATH": f"{bin_dir}:{os.environ['PATH']}",
            "PYTHONPATH": f"{REPO}:{REPO}/compat"}


# ------------------------------------------- launcher chaos drill (e2e)

_STUB_SPARSE_TRAINER = '''#!/usr/bin/env python3
"""Fake `paddle train` for the sparse chaos drill: drives the REAL
row-shard write/commit/verify/reshard functions over a 10-row table,
then loses one host AT the row-shard write boundary via the REAL
sparse.shard_lost fault site."""
import os, sys, time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from paddle_tpu.resilience import faultinject
from paddle_tpu.sparse import ckpt as sparse_ckpt
from paddle_tpu.sparse import rowshard
from paddle_tpu.trainer import checkpoint as ckpt

args = sys.argv[2:]


def flagval(name, default=""):
    for a in args:
        if a.startswith("--" + name + "="):
            return a.split("=", 1)[1]
    return default


pid = int(flagval("process_id", "0"))
n = int(flagval("num_processes", "1"))
save_dir = flagval("save_dir")
resume = flagval("init_model_path") == "auto"

ROWS, COLS = 10, 4


def table(pass_id):
    return (np.arange(ROWS * COLS, dtype=np.float32).reshape(ROWS, COLS)
            + 100.0 * pass_id)


def snapshot(pass_id, lo, hi):
    return {{"params": (
        {{"emb::%d" % pid: table(pass_id)[lo:hi]}},
        {{"emb": {{"shape": [ROWS, COLS], "dtype": "float32",
                   "shards": [{{"file": "params.shard%05d.npz" % pid,
                                "key": "emb::%d" % pid, "start": [lo, 0],
                                "shape": [hi - lo, COLS],
                                "row_range": [lo, hi]}}]}}}},
    )}}


def wait_for(path, timeout=60):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if os.path.exists(path):
            return True
        time.sleep(0.05)
    return False


def save_pass(p):
    lo, hi = rowshard.partition_rows(ROWS, n)[pid]
    ckpt.write_sharded_host_trees(save_dir, p, snapshot(p, lo, hi), pid)
    tmp = os.path.join(save_dir, ckpt.PASS_FMT % p) + ckpt.TMP_SUFFIX
    final = os.path.join(save_dir, ckpt.PASS_FMT % p)
    if pid == 0:
        for q in range(n):
            assert wait_for(os.path.join(
                tmp, "MANIFEST.partial.%05d.json" % q)), "peer never wrote"
        ckpt.finalize_sharded_pass(
            save_dir, p, ["params"], {{"pass_id": p, "format_version": 2,
                                       "sparse_tables": {{"emb": ROWS}},
                                       "sparse_hosts": n}},
            expected_pids=range(n))
    else:
        assert wait_for(final), "commit never landed"


if not resume:
    save_pass(0)  # pass 0 fully commits on every host
    # pass 1: host 1 dies AT its row-shard write boundary — the REAL
    # sparse.shard_lost site, so its shards/partial index never land
    if pid == 1:
        faultinject.configure("sparse.shard_lost=exit:3", 0)
    lo, hi = rowshard.partition_rows(ROWS, n)[pid]
    ckpt.write_sharded_host_trees(save_dir, 1, snapshot(1, lo, hi), pid)
    time.sleep(120)  # host 0 blocks "in the agreement" until torn down
else:
    best = ckpt.find_restorable_checkpoint(save_dir)
    assert best and best.endswith(ckpt.PASS_FMT % 0), best
    if pid == 1:
        os._exit(3)  # the lost host stays lost -> the launcher drops it
    if n == 2:
        time.sleep(120)  # full-set resume round: peer dies, we get torn down
    # SOLO survivor: reshard 2 -> 1 from the last committed pass, every
    # surviving row bit-exact, then train + commit the next pass alone
    lo, hi = rowshard.partition_rows(ROWS, 1)[0]
    rows = sparse_ckpt.load_table_rows(best, "emb", lo, hi)
    assert np.array_equal(rows, table(0)[lo:hi]), "resharded rows differ"
    # committing pass 2 rotates the torn pass-1 tmp away (it is garbage
    # once a newer pass lands) — copy the torn state aside first so the
    # test can run check-checkpoint against the mid-recovery evidence
    import shutil
    shutil.copytree(save_dir,
                    os.path.join(os.path.dirname(save_dir), "torn_evidence"))
    save_pass(2)
    sys.exit(0)
'''


def test_host_lost_at_row_shard_write_reshards_and_resumes(tmp_path, capsys):
    """Acceptance chaos e2e: 2 hosts commit pass 0; host 1 dies at the
    pass-1 row-shard write (sparse.shard_lost), stays dead, gets
    dropped; the solo survivor reshards the table from the last
    committed pass (rows bit-exact), resumes, and commits pass 2 —
    while check-checkpoint names the torn pass's exact row hole."""
    from paddle_tpu import cli

    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h0', 'u@h1']\n")
    save_dir = tmp_path / "model"
    stub = tmp_path / "paddle_stub"
    stub.write_text(_STUB_SPARSE_TRAINER.format(repo=REPO))
    stub.chmod(0o755)
    calls = tmp_path / "calls.log"
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$host $remote\" >> {calls}\n"
        "[ \"$remote\" = true ] && exit 1\n"  # dead host never rejoins
        "exec sh -c \"$remote\"\n"
    ))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", str(tmp_path),
         "--paddle", str(stub),
         "--poll_interval", "0.1", "--grace", "2",
         "--max_restarts", "2", "--restart_delay", "0.1",
         "--elastic_min_hosts", "1",
         "--", "--config=train.conf", "--mesh_shape=data=2",
         f"--save_dir={save_dir}"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300,
    )
    assert out.returncode == 0, (out.returncode, out.stderr[-3000:])
    assert "relaunching" in out.stderr
    assert "dropping host u@h1" in out.stderr, out.stderr[-3000:]
    # the solo round resumed with a resharded mesh
    solo = [l for l in calls.read_text().splitlines()
            if "--num_processes=1" in l]
    assert solo and all("--init_model_path=auto" in l
                        and "--mesh_shape=data=1" in l for l in solo), (
        calls.read_text())
    # pass 0 survived whole; pass 2 was committed by the solo survivor
    # with full row coverage from ONE host
    p0 = os.path.join(str(save_dir), ckpt.PASS_FMT % 0)
    assert ckpt.verify_checkpoint(p0) == []
    assert ckpt.verify_sharded_shards(p0) == []
    p2 = os.path.join(str(save_dir), ckpt.PASS_FMT % 2)
    assert ckpt.verify_sharded_shards(p2) == []
    files = sorted(os.listdir(p2))
    assert "params.shard00000.npz" in files
    assert "params.shard00001.npz" not in files
    exp2 = (np.arange(40, dtype=np.float32).reshape(10, 4) + 200.0)
    np.testing.assert_array_equal(
        sparse_ckpt.load_table_rows(p2, "emb", 0, 10), exp2)
    # the torn pass 1 as the survivor saw it mid-recovery (the pass-2
    # commit rotates the tmp away afterwards — also asserted above by
    # pass-00001 being absent): host 1's rows never landed — named,
    # PARTIAL, exit 1
    evidence = tmp_path / "torn_evidence"
    assert not os.path.exists(
        os.path.join(str(save_dir), ckpt.PASS_FMT % 1) + ckpt.TMP_SUFFIX)
    tmp = os.path.join(str(evidence), ckpt.PASS_FMT % 1) + ckpt.TMP_SUFFIX
    holes = sparse_ckpt.partial_row_holes(tmp)
    assert holes and "rows [5, 10)" in holes[0], holes
    assert "host(s) 0" in holes[0], holes
    assert cli.main(["check-checkpoint", str(evidence)]) == 1
    out_text = capsys.readouterr().out
    assert "PARTIAL" in out_text and "rows [5, 10)" in out_text, out_text


def test_cluster_launch_refuses_shrink_over_row_budget(tmp_path):
    """A drop that would concentrate more rows per host than
    --sparse_row_budget allows is refused BEFORE burning a relaunch
    round on n identical trainer crashes."""
    conf = tmp_path / "conf.py"
    conf.write_text("HOSTS = ['u@h_bad', 'u@h_ok']\n")
    calls = tmp_path / "calls.log"
    env = _write_fake_ssh(tmp_path, (
        f"echo \"$host $remote\" >> {calls}\n"
        "case \"$host\" in\n"
        "  *bad*) sleep 0.2; exit 2;;\n"
        "  *) sleep 120;;\n"
        "esac\n"
    ))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.utils.cluster_launch",
         "--conf", str(conf), "--workdir", "/job",
         "--poll_interval", "0.1", "--grace", "2",
         "--restart_delay", "0.1", "--max_restarts", "2",
         "--elastic_min_hosts", "1", "--rejoin_probe_timeout", "0",
         "--", "--config=train.conf", "--mesh_shape=data=2",
         "--sparse_row_budget=5", "--sparse_total_rows=8"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    # 2 hosts hold 8 rows at 4/host; 1 host would need 8 > 5: refused
    assert out.returncode == 2, (out.returncode, out.stderr)
    assert "cannot drop host u@h_bad" in out.stderr, out.stderr
    assert "--sparse_row_budget=5" in out.stderr and "needs 8" in out.stderr
    # no round ever launched the over-budget single-host job
    assert "--num_processes=1" not in calls.read_text()


# ----------------------------------------------- CTR demo crash/recover


def test_ctr_demo_trains_crashes_and_recovers_bit_exact(tmp_path):
    """The demo job end to end: the CTR model trains 2 passes with
    per-pass checkpoints and kind=sparse telemetry, crashes mid-pass-2
    (trainer.crash), and the relaunch restores the embedding tables
    from the last committed pass BIT-EXACT before training on to
    completion."""
    from demo_utils import setup_demo, train_demo
    from paddle_tpu.config import parse_config
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    setup_demo(tmp_path, "ctr", ["impressions-seed-1"])
    save_dir = str(tmp_path / "output")
    mdir = str(tmp_path / "run")
    trainer, _ = train_demo(
        tmp_path, "trainer_config.py", num_passes=2, log_period=1000,
        save_dir=save_dir, metrics_path=mdir)
    emb1 = {k: np.asarray(trainer.params[k]).copy()
            for k in ("_user_emb", "_ad_emb")}
    _, _, meta = ckpt.load_checkpoint(
        os.path.join(save_dir, ckpt.PASS_FMT % 1))
    assert meta["sparse_tables"] == {"_user_emb": 120, "_ad_emb": 48}
    assert meta["sparse_hosts"] == 1
    recs = [json.loads(l)
            for l in open(os.path.join(mdir, "metrics.jsonl"))]
    sparse_recs = [r for r in recs if r.get("kind") == "sparse"]
    assert len(sparse_recs) == 2  # one per pass
    assert all(r["rows_touched"] == 2048 for r in sparse_recs)  # 2 tables
    assert all(0 < r["unique_rows"] <= 120 + 48 for r in sparse_recs)

    # crash mid-pass-2: the resumed run must restore pass 1's tables
    faultinject.configure("trainer.crash=raise@5", 0)
    try:
        with pytest.raises(faultinject.FaultInjected):
            train_demo(tmp_path, "trainer_config.py", num_passes=4,
                       log_period=1000, save_dir=save_dir,
                       init_model_path="auto")
    finally:
        faultinject.configure("", 0)

    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("trainer_config.py", "")
        flags = _Flags(config="trainer_config.py", num_passes=4,
                       log_period=1000, use_tpu=False, save_dir=save_dir,
                       init_model_path="auto")
        recovered = Trainer(cfg, flags)
        # restored tables are BIT-EXACT copies of the committed pass
        for k, want in emb1.items():
            np.testing.assert_array_equal(
                np.asarray(recovered.params[k]), want, err_msg=k)
        recovered.train()  # passes 2..3 complete
    finally:
        os.chdir(cwd)
    assert os.path.isdir(os.path.join(save_dir, ckpt.PASS_FMT % 3))
    for k in emb1:  # training actually moved the tables afterwards
        assert not np.array_equal(np.asarray(recovered.params[k]), emb1[k])


def test_numerics_covers_embedding_and_blame_names_it(tmp_path):
    """--numerics_log_period health rows cover the sparse embedding
    layers (row-sparse grads and all), and the nonfinite per-layer
    blame re-run names the poisoned EMBEDDING — a NaN row in a sparse
    table is exactly the failure a dense-only blame sweep would miss."""
    from demo_utils import setup_demo, train_demo
    from paddle_tpu.resilience import NonFiniteLossError, faultinject

    setup_demo(tmp_path, "ctr", ["impressions-seed-1"])
    mdir = str(tmp_path / "run")
    faultinject.configure("trainer.nonfinite_layer=raise:user@3", 0)
    try:
        with pytest.raises(NonFiniteLossError) as ei:
            train_demo(tmp_path, "trainer_config.py", num_passes=1,
                       log_period=1000, metrics_path=mdir,
                       numerics_log_period=2, nonfinite_policy="skip",
                       max_nonfinite_steps=1)
    finally:
        faultinject.configure("", 0)
    assert "layer 'user'" in str(ei.value)
    from paddle_tpu.observability import metrics as obs
    obs.flush()
    recs = [json.loads(l)
            for l in open(os.path.join(mdir, "metrics.jsonl"))]
    nf = [r for r in recs if r.get("kind") == "nonfinite"]
    assert nf and all(r["blame_layer"] == "user" for r in nf), nf
    nums = [r for r in recs if r.get("kind") == "numerics"]
    assert nums, recs
    for r in nums:
        assert "user" in r["layers"] and "ad" in r["layers"], r["layers"]


def test_ctr_demo_table_must_be_sharded_under_budget(tmp_path):
    """The demo's headline property: sized past the per-host row budget
    the table does NOT fit one host (the trainer refuses), but fits the
    same budget sharded across two."""
    from demo_utils import setup_demo
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    setup_demo(tmp_path, "ctr", ["impressions-seed-1"])
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        cfg = parse_config("trainer_config.py", "num_users=1000")
        flags = _Flags(config="trainer_config.py", num_passes=1,
                       use_tpu=False, save_dir=str(tmp_path / "out"),
                       sparse_row_budget=600)
        with pytest.raises(ValueError, match="_user_emb"):
            Trainer(cfg, flags)  # 1000 rows > 600/host on one host
    finally:
        os.chdir(cwd)
    # the same budget is satisfiable by the 2-host split the launcher
    # would relaunch with
    assert rowshard.row_budget_error({"_user_emb": 1000}, 2, 600) is None


# ------------------------------------------- two-process real-path test
# Host-side protocol only (snapshot + KV commit agreement, no
# cross-process device computation), so per mp_harness's contract it
# does NOT gate on skip_unless_cross_process_computations() — the CPU
# CI backend runs it; the harness's probe gating is for TRAINING tests.

_SPARSE2_WORKER = mp_harness.WORKER_PREAMBLE + """
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from paddle_tpu.sparse import runtime as sparse_rt
from paddle_tpu.trainer.async_ckpt import ShardedAsyncCheckpointer
from paddle_tpu.trainer import checkpoint as ckpt

mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
rows, cols = 64, 4
exp = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
table = jax.make_array_from_callback(
    (rows, cols), NamedSharding(mesh, P("data", None)),
    lambda idx: exp[idx])

sparse_rt.register_tables({{"emb": rows}})
save_dir = os.path.join(ws, "model")
ac = ShardedAsyncCheckpointer(save_dir, inflight_limit=2, agree_timeout=120)
ac.save(0, {{"emb": table}}, extra_meta={{"batch_id": 1}})
ac.drain()
assert os.path.isdir(os.path.join(save_dir, ckpt.PASS_FMT % 0))
print("WORKER_OK", pid, flush=True)
"""


def test_two_process_snapshot_stamps_row_ranges_and_reshards(tmp_path):
    """The REAL snapshot path over the jax distributed runtime: two
    hosts' live device shards produce row_range-stamped records whose
    union provably tiles the table, the meta records the sparse host
    set, and a single surviving process reshards any row slice
    bit-exactly from them."""
    mp_harness.run_two_workers(
        _SPARSE2_WORKER.format(repo=REPO, providers=PROVIDERS),
        str(tmp_path))
    path = os.path.join(str(tmp_path), "model", ckpt.PASS_FMT % 0)
    assert ckpt.verify_checkpoint(path) == []
    assert ckpt.verify_sharded_shards(path) == []
    with open(os.path.join(path, "params.index.json")) as f:
        index = json.load(f)
    recs = index["emb"]["shards"]
    # one record per owned device shard, every one row_range-stamped,
    # and the union provably tiles the table with no hole or overlap
    assert all("row_range" in r for r in recs), recs
    ranges = sorted(tuple(r["row_range"]) for r in recs)
    assert ranges == [(i * 8, (i + 1) * 8) for i in range(8)]
    assert rowshard.coverage_problems(
        64, [(a, b, i) for i, (a, b) in enumerate(ranges)]) == []
    # each host's shard file holds exactly its half of the rows
    for pid, (lo, hi) in enumerate(rowshard.partition_rows(64, 2)):
        mine = [r for r in recs if r["file"].endswith(f"shard{pid:05d}.npz")]
        assert sorted(tuple(r["row_range"]) for r in mine) == [
            (j, j + 8) for j in range(lo, hi, 8)]
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    assert meta["sparse_tables"] == {"emb": 64}
    assert meta["sparse_hosts"] == 2
    exp = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)
    for lo, hi in rowshard.partition_rows(64, 3):  # 2 -> 3 host reshard
        np.testing.assert_array_equal(
            sparse_ckpt.load_table_rows(path, "emb", lo, hi), exp[lo:hi])
