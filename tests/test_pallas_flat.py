"""Transpose-free ("flat") interface of the fused recurrent kernels
(PADDLE_TPU_PALLAS_FLAT=1): the kernel reads the x-projection's
batch-major value through a free [B, T*width] reshape and writes ys the
same way, so the time-major boundary transposes (a measured 16.9% of
the pallas-leg step) never exist. Parity: kernel-level flat-vs-time-
major on both kernels, and machine-level losses/gradients through the
LSTM flagship and the NMT encoder.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.graph  # noqa: F401
from paddle_tpu.ops.pallas_gru import fused_gru
from paddle_tpu.ops.pallas_lstm import fused_lstm


def test_lstm_flat_parity():
    T, B, H = 6, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    x4_tm = jax.random.normal(ks[0], (T, B, 4 * H)) * 0.5
    mask = (jax.random.uniform(ks[1], (T, B)) > 0.3).astype(jnp.float32)
    w = jax.random.normal(ks[2], (H, 4 * H)) * 0.2
    peep = jnp.zeros((3, H))
    acts = ("tanh", "sigmoid", "tanh")
    x4_flat = jnp.swapaxes(x4_tm, 0, 1).reshape(B, T * 4 * H)
    ys_tm = fused_lstm(x4_tm, mask, w, peep, acts, True, False)
    ys_fl = fused_lstm(x4_flat, mask, w, peep, acts, True, True)
    np.testing.assert_allclose(
        np.asarray(jnp.swapaxes(ys_tm, 0, 1)),
        np.asarray(ys_fl.reshape(B, T, H)),
        rtol=1e-6, atol=1e-6,
    )
    cot = jax.random.normal(ks[3], (T, B, H))
    cot_fl = jnp.swapaxes(cot, 0, 1).reshape(B, T * H)
    g_tm = jax.grad(
        lambda x, w: jnp.sum(fused_lstm(x, mask, w, peep, acts, True, False) * cot),
        (0, 1),
    )(x4_tm, w)
    g_fl = jax.grad(
        lambda x, w: jnp.sum(fused_lstm(x, mask, w, peep, acts, True, True) * cot_fl),
        (0, 1),
    )(x4_flat, w)
    np.testing.assert_allclose(
        np.asarray(jnp.swapaxes(g_tm[0], 0, 1)),
        np.asarray(g_fl[0].reshape(B, T, 4 * H)),
        rtol=1e-5, atol=1e-6, err_msg="dx4",
    )
    np.testing.assert_allclose(
        np.asarray(g_tm[1]), np.asarray(g_fl[1]),
        rtol=1e-5, atol=1e-6, err_msg="dw",
    )


def test_gru_flat_parity():
    T, B, H = 5, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(7), 4)
    x3_tm = jax.random.normal(ks[0], (T, B, 3 * H)) * 0.5
    mask = (jax.random.uniform(ks[1], (T, B)) > 0.2).astype(jnp.float32)
    w = jax.random.normal(ks[2], (H, 3 * H)) * 0.2
    acts = ("tanh", "sigmoid")
    x3_flat = jnp.swapaxes(x3_tm, 0, 1).reshape(B, T * 3 * H)
    ys_tm = fused_gru(x3_tm, mask, w, acts, True, False)
    ys_fl = fused_gru(x3_flat, mask, w, acts, True, True)
    np.testing.assert_allclose(
        np.asarray(jnp.swapaxes(ys_tm, 0, 1)),
        np.asarray(ys_fl.reshape(B, T, H)),
        rtol=1e-6, atol=1e-6,
    )
    cot = jax.random.normal(ks[3], (T, B, H))
    cot_fl = jnp.swapaxes(cot, 0, 1).reshape(B, T * H)
    g_tm = jax.grad(
        lambda x, w: jnp.sum(fused_gru(x, mask, w, acts, True, False) * cot),
        (0, 1),
    )(x3_tm, w)
    g_fl = jax.grad(
        lambda x, w: jnp.sum(fused_gru(x, mask, w, acts, True, True) * cot_fl),
        (0, 1),
    )(x3_flat, w)
    np.testing.assert_allclose(
        np.asarray(jnp.swapaxes(g_tm[0], 0, 1)),
        np.asarray(g_fl[0].reshape(B, T, 3 * H)),
        rtol=1e-5, atol=1e-6, err_msg="dx3",
    )
    np.testing.assert_allclose(
        np.asarray(g_tm[1]), np.asarray(g_fl[1]),
        rtol=1e-5, atol=1e-6, err_msg="dw",
    )


def test_machine_flat_parity(monkeypatch):
    """The env knob end-to-end: flagship LSTM train grads identical flat
    vs time-major (incl. the reversed-GRU NMT encoder in the sibling
    session A/B)."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    from paddle_tpu.flagship import example_batch, flagship_config
    from paddle_tpu.graph import GradientMachine

    tc = flagship_config(dict_dim=128, emb_dim=32, hidden=128)
    gm = GradientMachine(tc.model_config, pallas_rnn=True)
    params = gm.init_params(seed=5)
    batch = example_batch(dict_dim=128, B=8, T=8, seed=3)
    rng = jax.random.PRNGKey(0)
    monkeypatch.delenv("PADDLE_TPU_PALLAS_FLAT", raising=False)
    loss_tm, grads_tm, _, _ = gm.grad_fn()(params, batch, rng)
    monkeypatch.setenv("PADDLE_TPU_PALLAS_FLAT", "1")
    loss_fl, grads_fl, _, _ = gm.grad_fn()(params, batch, rng)
    np.testing.assert_allclose(float(loss_fl), float(loss_tm),
                               rtol=1e-6, atol=1e-7)
    for k in grads_tm:
        np.testing.assert_allclose(
            np.asarray(grads_fl[k], np.float32),
            np.asarray(grads_tm[k], np.float32),
            rtol=1e-5, atol=1e-6, err_msg=k,
        )


def test_reversed_gru_flat_parity(monkeypatch):
    """cfg.reversed flips axis 1 in flat mode — pin against time-major."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    import textwrap

    from paddle_tpu.config import parse_config
    from paddle_tpu.graph import GradientMachine, make_seq

    # shapes must PASS the kernel gate (H % 128 == 0, B % 8 == 0) or
    # both runs silently take the scan fallback and the test is vacuous
    src = textwrap.dedent("""
    from paddle_tpu.trainer_config_helpers import *

    settings(batch_size=8, learning_rate=1e-3, pallas_rnn=True)
    x = data_layer(name="x", size=384)
    g = simple_gru(input=x, size=128, reverse=True)
    last = first_seq(input=g)
    lbl = data_layer(name="y", size=2)
    fc = fc_layer(input=last, size=2, act=SoftmaxActivation())
    outputs(classification_cost(name="cost", input=fc, label=lbl))
    """)
    import tempfile, os as _os

    with tempfile.TemporaryDirectory() as td:
        pth = _os.path.join(td, "cfg.py")
        with open(pth, "w") as f:
            f.write(src)
        tc = parse_config(pth)
    gm = GradientMachine(tc.model_config, pallas_rnn=True)
    params = gm.init_params(seed=3)
    rng_np = np.random.RandomState(1)
    B = 8
    onehot = np.zeros((B, 2), np.float32)
    onehot[np.arange(B), rng_np.randint(0, 2, B)] = 1.0
    lengths = np.array([6, 4, 5, 6, 6, 3, 6, 2], np.int32)
    from paddle_tpu.graph import make_dense

    batch = {
        "x": make_seq(rng_np.randn(B, 6, 384).astype(np.float32), lengths),
        "y": make_dense(onehot),
    }
    rng = jax.random.PRNGKey(0)
    # engagement: the fused path must actually run (monkeypatch-spy the
    # layer wrapper, same pattern as tests/test_pallas_gru.py)
    from paddle_tpu.ops import pallas_gru as pg

    calls = {"n": 0, "flat": 0}
    orig = pg.gru_layer_forward

    def spy(cfg, x, mask, w, bias, interpret, x_bt=None):
        calls["n"] += 1
        calls["flat"] += int(x_bt is not None)
        return orig(cfg, x, mask, w, bias, interpret, x_bt=x_bt)

    monkeypatch.setattr(pg, "gru_layer_forward", spy)
    monkeypatch.delenv("PADDLE_TPU_PALLAS_FLAT", raising=False)
    loss_tm, grads_tm, _, _ = gm.grad_fn()(params, batch, rng)
    assert calls["n"] > 0, "fused GRU path did not engage"
    monkeypatch.setenv("PADDLE_TPU_PALLAS_FLAT", "1")
    loss_fl, grads_fl, _, _ = gm.grad_fn()(params, batch, rng)
    assert calls["flat"] > 0, "flat interface did not engage"
    np.testing.assert_allclose(float(loss_fl), float(loss_tm),
                               rtol=1e-6, atol=1e-7)
    for k in grads_tm:
        np.testing.assert_allclose(
            np.asarray(grads_fl[k], np.float32),
            np.asarray(grads_tm[k], np.float32),
            rtol=1e-5, atol=1e-6, err_msg=k,
        )


@pytest.mark.parametrize("flat", [False, True])
def test_pallas_kernels_under_data_mesh(monkeypatch, flat):
    """Data-only meshes run the fused kernels per-shard via shard_map
    (layers/recurrent.py _pallas_rnn_path), in BOTH interface modes:
    sharded pallas train step == unsharded scan step. Engagement
    asserted via the layer-wrapper spy (a silent scan fallback must
    fail, not vacuously pass)."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    if flat:
        monkeypatch.setenv("PADDLE_TPU_PALLAS_FLAT", "1")
    else:
        monkeypatch.delenv("PADDLE_TPU_PALLAS_FLAT", raising=False)
    from paddle_tpu.flagship import example_batch, flagship_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.optimizer import Updater
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.spmd import shard_train_step
    from paddle_tpu.ops import pallas_lstm as pk

    # per-shard batch must pass the kernel gate (B_local % 8 == 0)
    B, T = 64, 8
    rng = jax.random.PRNGKey(0)
    batch = example_batch(dict_dim=128, B=B, T=T)

    def step_fns(tc, pallas):
        gm = GradientMachine(tc.model_config, pallas_rnn=pallas)
        updater = Updater(tc.opt_config, tc.model_config)
        params = gm.init_params(seed=2)
        opt_state = updater.init_state(params)
        grad_fn = gm.grad_fn()

        def step(params, opt_state, batch, rng, bs):
            loss, grads, outputs, state_updates = grad_fn(params, batch, rng)
            new_params, new_opt = updater(params, grads, opt_state, bs)
            for k, v in state_updates.items():
                new_params[k] = v
            return new_params, new_opt, loss, outputs["output"].value

        return gm, step, params, opt_state

    tc = flagship_config(dict_dim=128, hidden=128)
    gm0, step0, params0, opt0 = step_fns(tc, pallas=False)
    p_ref, _, loss_ref, _ = jax.jit(step0)(
        params0, opt0, batch, rng, jnp.asarray(float(B))
    )

    calls = {"n": 0, "flat": 0}
    orig = pk.lstm_layer_forward

    def spy(cfg, x, mask, w, bias, interpret, x_bt=None):
        calls["n"] += 1
        calls["flat"] += int(x_bt is not None)
        return orig(cfg, x, mask, w, bias, interpret, x_bt=x_bt)

    monkeypatch.setattr(pk, "lstm_layer_forward", spy)
    tc2 = flagship_config(dict_dim=128, hidden=128, mesh_shape="data=8")
    gm2, step2, params2, opt2 = step_fns(tc2, pallas=True)
    gm2.mesh = make_mesh("data=8")
    sharded = shard_train_step(step2, gm2.mesh, gm2)
    p_sh, _, loss_sh, _ = sharded(
        params2, opt2, batch, rng, jnp.asarray(float(B))
    )
    assert calls["n"] > 0, "pallas path did not engage under the data mesh"
    assert calls["flat"] == (calls["n"] if flat else 0), "wrong interface mode"
    np.testing.assert_allclose(float(loss_sh), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    for k in p_ref:
        np.testing.assert_allclose(
            np.asarray(p_sh[k], np.float32), np.asarray(p_ref[k], np.float32),
            rtol=1e-4, atol=1e-5, err_msg=k,
        )
